"""The mesh-shardable embedding bank: registration, build, blocked MIPS query.

The reference's candidate generation is a fan-out: ALS block cross-join,
an external Elasticsearch More-Like-This query, curated/popular SQL views —
each a host thread with its own deadline (``serving/pipeline.py``). Every
embedding-backed source among them is the same computation wearing a
different costume: score a query vector against a row table, keep the
top-k. At albedo scale (~1M repos x rank <= 256) that is ONE bandwidth-bound
GEMM per batch — well within the measured 285 GB/s roofline — so the bank
collapses them into one device-resident table set served by a single fused
gather -> blocked GEMM -> top-k executable per batch shape.

**Sources.** A :class:`BankSourceSpec` registers one source:

- ``kind="user_rows"``: the query vector is a row of a user table aligned
  with the serving matrix's dense user indices (ALS user factors; or the
  user table itself scored against the user table — user-to-user
  similarity).
- ``kind="item_mean"``: the query vector is the L2-normalized mean of
  example rows of the source's OWN table (content/tfidf More-Like-This:
  query by the user's recently starred repos; the query rows themselves
  are excluded from the results, matching ES MLT semantics).

**Build.** ``build()`` is the versioned step: capacity admission
(``utils.capacity.plan_retrieval`` — resident generations are priced before
any byte moves), device upload (single device) or row padding for the mesh
layout (the ALX row-sharded serving layout from PR 8), per-source row-norm /
score **calibration** (a deterministic probe records the scale that maps
each source's raw top-1 scores onto ~1.0, so heterogeneous sources can fuse
on one scale; queries return RAW scores — calibration is metadata applied
only where a caller asks, which is what keeps bank-vs-host parity exact),
and a content-hash ``version``. ``save()`` seals the build like every other
artifact: pickle + ``.meta.json`` stamp (sources, calibration, lineage) +
the ``.sha256`` manifest written LAST.

**Query.** Single device: one fused executable per (batch bucket, k,
source-mask, query-width, exclusion-mode) shape, acquired through
``utils.aot.persistent_aot_executable`` and held — the hot path is
``compiled(tables, user_idx, q_idx, excl)`` with no tracing. Seen-item
exclusion gathers rows from the SAME device-resident ``-1``-padded
exclusion table the serving micro-batcher uploads (sources whose row space
differs from the matrix item space carry a device remap table). Mesh: each
source's table is row-sharded over the ``item`` axis and served by the
``parallel/topk.py`` per-shard top-k + k-per-device all-gather merge, now
routed through the persistent AOT layer.

**Overlay.** ``publish_user_rows`` lands freshly folded-in user rows
(``streaming/foldin.py``) into a ``user_rows`` source's table — the bank is
the natural overlay target for the minutes-stale loop: the next query batch
reads the new rows because tables are call-time arguments, not baked-in
constants.

Fault sites: ``retrieval.build`` (head of the build step) and
``retrieval.query`` (head of every query batch) — catalogued in
ARCHITECTURE.md; queries are counted per source in
``albedo_retrieval_queries_total{source=}``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import threading
import time
from typing import Callable

import numpy as np

from albedo_tpu.analysis.locksmith import named_lock
from albedo_tpu.utils import events, faults
from albedo_tpu.utils import pow2_at_least as _pow2

log = logging.getLogger(__name__)

BUILD_FAULT = faults.site("retrieval.build")
QUERY_FAULT = faults.site("retrieval.query")

KINDS = ("user_rows", "item_mean")


def bank_artifact_name(tag: str) -> str:
    """The bank artifact naming convention (one definition: build job,
    serve wiring, and the reload watcher glob all agree)."""
    return f"{tag}-retrievalBank-v1.pkl"


@dataclasses.dataclass
class BankSourceSpec:
    """One embedding source's registration.

    ``vectors`` is the scored table — (N, d) float32 host rows whose raw ids
    are ``item_ids``. ``user_vectors`` (``user_rows`` kind) is the query
    table, row-aligned with the serving matrix's dense user indices.
    ``query_items`` (``item_mean`` kind) maps a raw user id to the raw item
    ids whose rows form the query (e.g. the user's most recent stars); a
    spec without one uses the stage's shared provider. ``exclude_seen``
    opts the source into the shared seen-item exclusion table (meaningful
    for ``user_rows`` sources whose candidates are catalog items).
    ``owner`` keys shared device residency (``utils.devcache``) so a bank
    build and the host fallback path hold ONE device copy of the table.
    """

    name: str
    kind: str
    vectors: np.ndarray
    item_ids: np.ndarray
    user_vectors: np.ndarray | None = None
    query_items: Callable[[int], np.ndarray] | None = None
    exclude_seen: bool = False
    owner: object | None = None

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown bank source kind {self.kind!r} (not in {KINDS})")
        self.vectors = np.asarray(self.vectors, dtype=np.float32)
        self.item_ids = np.asarray(self.item_ids, dtype=np.int64)
        if self.vectors.ndim != 2 or self.vectors.shape[0] != self.item_ids.shape[0]:
            raise ValueError(
                f"source {self.name!r}: vectors {self.vectors.shape} do not "
                f"row-align with item_ids {self.item_ids.shape}"
            )
        if self.kind == "user_rows":
            if self.user_vectors is None:
                raise ValueError(f"user_rows source {self.name!r} needs user_vectors")
            self.user_vectors = np.asarray(self.user_vectors, dtype=np.float32)
            if self.user_vectors.shape[1] != self.vectors.shape[1]:
                raise ValueError(
                    f"source {self.name!r}: user rank {self.user_vectors.shape[1]} "
                    f"!= item rank {self.vectors.shape[1]}"
                )


def _calibration(spec: BankSourceSpec, probe_rows: int = 32) -> dict:
    """Deterministic per-source score calibration, recorded at build time.

    Probes the first ``probe_rows`` query vectors (user rows, or the
    source's own normalized rows for item_mean) against the full table and
    records ``scale`` = 1 / median top-1 score — multiplying a source's raw
    scores by its scale puts every source's best-match at ~1.0, one shared
    scale for cross-source fusion. Row-norm stats ride along so an operator
    inspecting a stamp can see WHY a scale is what it is. Pure f32 host
    arithmetic on a bounded probe: build-time cost, not query-time.
    """
    vf = spec.vectors
    norms = np.linalg.norm(vf, axis=1)
    if spec.kind == "user_rows":
        q = spec.user_vectors[: min(probe_rows, spec.user_vectors.shape[0])]
    else:
        q = vf[: min(probe_rows, vf.shape[0])]
        qn = np.linalg.norm(q, axis=1, keepdims=True)
        q = np.where(qn > 0, q / np.maximum(qn, 1e-9), 0.0)
    if q.shape[0] == 0 or vf.shape[0] == 0:
        scale = 1.0
    else:
        top1 = np.abs((q @ vf.T).max(axis=1))
        med = float(np.median(top1))
        scale = 1.0 / med if med > 1e-9 else 1.0
    return {
        "scale": round(float(scale), 8),
        "probe_rows": int(q.shape[0]),
        "row_norm_mean": round(float(norms.mean()) if norms.size else 0.0, 8),
        "row_norm_max": round(float(norms.max()) if norms.size else 0.0, 8),
    }


def mean_query_vectors(
    vectors: np.ndarray, q_mat: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Host-side item_mean query assembly: masked mean of the query rows,
    L2-normalized; returns ``(queries (B, d) f32, has_query (B,) bool)``.

    ONE definition for every host-assembled path (the mesh query, similar-
    by-example on a mesh) — it must stay in lockstep with the device
    program's inlined copy in :func:`_make_query_program` AND with the host
    recommenders (``tfidf.similar_to_repos``/``content.more_like_this``):
    the candidate-parity contract is pinned against all of them."""
    valid = q_mat >= 0
    rows = vectors[np.clip(q_mat, 0, None)]
    w = valid.astype(np.float32)
    qv = (rows * w[..., None]).sum(axis=1)
    qv /= np.maximum(w.sum(axis=1, keepdims=True), 1.0)
    qv /= np.maximum(np.linalg.norm(qv, axis=1, keepdims=True), 1e-9)
    return qv.astype(np.float32), valid.any(axis=1)


def _make_query_program(
    kinds: tuple[str, ...],
    k_each: tuple[int, ...],
    use_excl: tuple[bool, ...],
    remap: tuple[bool, ...],
    k: int,
    item_block: int,
):
    """Build the fused all-sources query program for one static layout.

    One jitted function = one device dispatch per batch, whatever the
    source mask: per source, gather the query vectors (user-table rows, or
    the masked mean of example rows), run the blocked MIPS top-k
    (``ops.topk.topk_scores`` — the same streaming-merge kernel the
    micro-batcher serves ALS with), and pad every source's output to a
    uniform (B, k). The jitted callable is acquired exclusively through
    ``utils.aot.persistent_aot_executable`` (see ``RetrievalBank._executable``).
    """
    import jax
    import jax.numpy as jnp

    from albedo_tpu.ops.topk import topk_scores

    neg_inf = float("-inf")

    def run(tables, user_idx, q_idxs, excl_all):
        outs = []
        for i, kind in enumerate(kinds):
            tab = tables[i]
            if kind == "user_rows":
                uf, vf = tab[0], tab[1]
                qv = jnp.take(uf, user_idx, axis=0)
                e = None
                if use_excl[i]:
                    e = jnp.take(excl_all, user_idx, axis=0)
                    if remap[i]:
                        excl_map = tab[2]
                        e = jnp.where(
                            e < 0, -1, jnp.take(excl_map, jnp.clip(e, 0))
                        )
                vals, idx = topk_scores(
                    qv, vf, k=k_each[i], exclude_idx=e, item_block=item_block
                )
            else:
                vf = tab[0]
                q_idx = q_idxs[i]
                valid = q_idx >= 0
                rows = jnp.take(vf, jnp.clip(q_idx, 0), axis=0)   # (B, Q, d)
                w = valid.astype(vf.dtype)
                qv = (rows * w[..., None]).sum(axis=1)
                qv = qv / jnp.maximum(w.sum(axis=1, keepdims=True), 1.0)
                qv = qv / jnp.maximum(
                    jnp.linalg.norm(qv, axis=1, keepdims=True), 1e-9
                )
                # The query rows themselves are excluded (ES MLT semantics:
                # "more like this", never "this").
                vals, idx = topk_scores(
                    qv, vf, k=k_each[i], exclude_idx=q_idx, item_block=item_block
                )
                has_q = valid.any(axis=1)
                vals = jnp.where(has_q[:, None], vals, neg_inf)
                idx = jnp.where(has_q[:, None], idx, -1)
            if k_each[i] < k:
                pad = k - k_each[i]
                vals = jnp.pad(vals, ((0, 0), (0, pad)), constant_values=neg_inf)
                idx = jnp.pad(idx, ((0, 0), (0, pad)), constant_values=-1)
            outs.append((vals, idx))
        return tuple(outs)

    return jax.jit(run)


class RetrievalBank:
    """Registered embedding sources, one device-resident bank, one query path.

    Lifecycle: ``register_source()`` (host arrays) -> ``build()`` (capacity
    admission, device upload / mesh layout, calibration, version stamp) ->
    ``query()`` / ``query_similar()`` / ``publish_user_rows()``. ``save()``
    persists the build; ``RetrievalBank.load()`` restores it (un-built —
    the loading process runs its own admission and upload). ``reshard()``
    re-lays a built bank onto a different mesh — the degraded-ladder rung a
    device loss leaves serving — with version/calibration/overlay intact
    (ARCHITECTURE.md "Elastic operation").
    """

    def __init__(self, item_block: int = 4096, max_batch: int = 64):
        self.item_block = int(item_block)
        self.max_batch = max(1, _pow2(int(max_batch)))
        self.specs: dict[str, BankSourceSpec] = {}
        self.calibration: dict[str, dict] = {}
        self.version: str | None = None
        self.built_at: float = 0.0
        self.overlay_generation = 0
        self.mesh = None
        self._built = False
        # Device state (single-device build): per-source tables + remaps.
        self._vf: dict[str, object] = {}
        self._uf: dict[str, object] = {}
        self._excl_map: dict[str, object] = {}
        self._rowmap: dict[str, dict[int, int]] = {}
        self._excl_map_np: dict[str, np.ndarray] = {}
        self._excl_np: np.ndarray | None = None
        self._excl_dev = None
        self._executables: dict[tuple, object] = {}
        self._exec_lock = named_lock("retrieval.bank.exec")
        self._overlay_owned: set[str] = set()
        self.admission = None

    # ------------------------------------------------------------ registration

    @property
    def source_names(self) -> tuple[str, ...]:
        return tuple(self.specs)

    def register(self, spec: BankSourceSpec) -> None:
        if self._built:
            raise RuntimeError(
                "bank already built — register sources first, then build(); "
                "a new source set is a new bank generation"
            )
        if spec.name in self.specs:
            raise ValueError(f"source {spec.name!r} already registered")
        self.specs[spec.name] = spec

    def register_source(self, name: str, **kwargs) -> None:
        self.register(BankSourceSpec(name=name, **kwargs))

    # ------------------------------------------------------------------- build

    def build(
        self,
        matrix=None,
        exclude_table: np.ndarray | None = None,
        mesh=None,
        budget: int | None = None,
        generations: int = 1,
    ) -> "RetrievalBank":
        """The versioned build step: admission -> upload -> calibration.

        ``matrix`` (the serving :class:`StarMatrix`) enables seen-item
        exclusion remaps for sources whose row space is not the matrix item
        space; ``exclude_table`` is the micro-batcher's device-resident
        ``-1``-padded seen-item table, reused verbatim. ``mesh`` selects the
        row-sharded layout served by ``parallel/topk.py``. A build that
        cannot fit ``generations`` resident copies raises
        :class:`~albedo_tpu.utils.capacity.CapacityExceeded` (the refusal is
        recorded; the host fan-out keeps serving).
        """
        from albedo_tpu.utils import capacity

        if not self.specs:
            raise ValueError("no sources registered")
        BUILD_FAULT.hit()
        t0 = time.perf_counter()
        verdict = capacity.admit(
            self._retrieval_plan(
                mesh,
                excl_entries=int(exclude_table.size) if exclude_table is not None else 0,
                generations=generations,
            ),
            degradable=False, budget=budget,
        )
        self.admission = verdict
        if verdict.verdict == "refuse":
            raise capacity.CapacityExceeded(verdict)

        matrix_item_ids = None if matrix is None else np.asarray(matrix.item_ids)
        for name in sorted(self.specs):
            spec = self.specs[name]
            self._rowmap[name] = {int(i): r for r, i in enumerate(spec.item_ids)}
            self.calibration[name] = _calibration(spec)
            # Seen-item exclusion remap: matrix dense item index -> source
            # row, -1 where the source does not carry the item. Identity
            # (the ALS case: source rows ARE the matrix item space) skips
            # the gather entirely.
            excl_map = None
            if (
                spec.kind == "user_rows"
                and spec.exclude_seen
                and matrix_item_ids is not None
                and not np.array_equal(spec.item_ids, matrix_item_ids)
            ):
                pos = np.searchsorted(spec.item_ids, matrix_item_ids)
                pos_c = np.clip(pos, 0, max(0, len(spec.item_ids) - 1))
                hit = (
                    (pos < len(spec.item_ids))
                    & (spec.item_ids[pos_c] == matrix_item_ids)
                )
                excl_map = np.where(hit, pos_c, -1).astype(np.int32)
                if not np.all(np.diff(spec.item_ids) > 0):
                    # searchsorted needs sorted ids; fall back to a dict map.
                    excl_map = np.array(
                        [self._rowmap[name].get(int(i), -1) for i in matrix_item_ids],
                        dtype=np.int32,
                    )
            if excl_map is not None:
                self._excl_map_np[name] = excl_map
        if exclude_table is not None:
            self._excl_np = np.asarray(exclude_table, dtype=np.int32)
        self._upload(mesh)
        self.version = self._content_hash()
        self.built_at = time.time()
        self._built = True
        log.info(
            "retrieval bank built: %d source(s), version %s, %.2fs%s",
            len(self.specs), self.version, time.perf_counter() - t0,
            f", mesh {dict(mesh.shape)}" if mesh is not None else "",
        )
        return self

    def _retrieval_plan(self, mesh, excl_entries: int, generations: int):
        """The bank's capacity plan for a given layout — PER DEVICE when a
        mesh is given (tables row-shard over the item axis)."""
        from albedo_tpu.parallel.mesh import ITEM_AXIS
        from albedo_tpu.utils import capacity

        return capacity.plan_retrieval(
            [
                shape
                for s in self.specs.values()
                for shape in (
                    [s.vectors.shape]
                    + ([s.user_vectors.shape] if s.user_vectors is not None else [])
                )
            ],
            excl_entries=excl_entries,
            generations=generations,
            max_batch=self.max_batch,
            item_block=self.item_block,
            n_devices=1 if mesh is None else int(mesh.shape[ITEM_AXIS]),
        )

    def _upload(self, mesh) -> None:
        """Device placement for the registered tables on ``mesh`` (or the
        single default device when None) — the mesh-dependent tail of
        ``build()``, shared with :meth:`reshard` so a built bank can re-lay
        itself onto whatever mesh the degraded ladder gives. Clears any
        previous layout's device state and shape-keyed executables (new
        padded shapes = new programs); host-side products (row maps,
        calibration, exclusion remaps) are layout-independent and kept."""
        import jax.numpy as jnp

        from albedo_tpu.utils.devcache import device_put_cached

        self.mesh = mesh
        self._vf.clear()
        self._uf.clear()
        self._excl_map.clear()
        self._excl_dev = None
        self._executables.clear()
        for name in sorted(self.specs):
            spec = self.specs[name]
            excl_map = self._excl_map_np.get(name)
            owner = spec.owner if spec.owner is not None else spec
            if mesh is None:
                self._vf[name] = device_put_cached(owner, spec.vectors)
                if spec.user_vectors is not None:
                    self._uf[name] = jnp.asarray(spec.user_vectors)
                if excl_map is not None:
                    self._excl_map[name] = jnp.asarray(excl_map)
            else:
                # Mesh layout: pre-pad to the item-axis multiple ONCE and
                # pin the device array — per-query calls pass the resident
                # table (the aligned fast path in ``sharded_topk_scores``)
                # instead of re-uploading the whole table per batch.
                from albedo_tpu.parallel.mesh import ITEM_AXIS, pad_rows_to

                padded = pad_rows_to(spec.vectors, int(mesh.shape[ITEM_AXIS]))
                self._vf[name] = (
                    device_put_cached(owner, spec.vectors)
                    if padded is spec.vectors else jnp.asarray(padded)
                )
                if excl_map is not None:
                    self._excl_map[name] = excl_map  # host: remapped on host
        if self._excl_np is not None:
            self._excl_dev = (
                self._excl_np if mesh is not None else jnp.asarray(self._excl_np)
            )

    def reshard(self, mesh, budget: int | None = None,
                generations: int = 1) -> "RetrievalBank":
        """Re-lay a BUILT bank onto a different mesh — the degraded-mesh
        serving move: after the ladder hands serving a smaller rung (or a
        single device), the SAME bank re-prices and re-shards onto it with
        its version, calibration, and overlay state intact. Admission runs
        against the NEW layout's per-device price first (shards double when
        the mesh halves); a refusal raises
        :class:`~albedo_tpu.utils.capacity.CapacityExceeded` and leaves the
        current layout serving — a recorded rejection, never a torn swap.
        """
        from albedo_tpu.utils import capacity

        self._require_built()
        verdict = capacity.admit(
            self._retrieval_plan(
                mesh,
                excl_entries=0 if self._excl_np is None else int(self._excl_np.size),
                generations=generations,
            ),
            degradable=False, budget=budget,
        )
        if verdict.verdict == "refuse":
            raise capacity.CapacityExceeded(verdict)
        self.admission = verdict
        old = None if self.mesh is None else dict(self.mesh.shape)
        self._upload(mesh)
        log.warning(
            "retrieval bank resharded: %s -> %s (version %s unchanged)",
            old or "single-device",
            dict(mesh.shape) if mesh is not None else "single-device",
            self.version,
        )
        return self

    def _content_hash(self) -> str:
        """Deterministic digest of every registered table — the bank's
        ``version``. Recomputed at build AND at save, so overlay publishes
        between the two stamp the content actually sealed."""
        h = hashlib.sha256()
        for name in sorted(self.specs):
            spec = self.specs[name]
            h.update(name.encode())
            h.update(spec.kind.encode())
            h.update(spec.vectors.tobytes())
            h.update(spec.item_ids.tobytes())
            if spec.user_vectors is not None:
                h.update(spec.user_vectors.tobytes())
        return h.hexdigest()[:16]

    def manifest(self) -> dict:
        """The build's inspectable record (also what ``save()`` stamps)."""
        return {
            "version": self.version,
            "built_at": self.built_at,
            "overlay_generation": self.overlay_generation,
            "sharded": self.mesh is not None,
            "sources": {
                name: {
                    "kind": s.kind,
                    "rows": int(s.vectors.shape[0]),
                    "dim": int(s.vectors.shape[1]),
                    "user_rows": (
                        int(s.user_vectors.shape[0])
                        if s.user_vectors is not None else 0
                    ),
                    "exclude_seen": bool(s.exclude_seen),
                    "calibration": self.calibration.get(name, {}),
                }
                for name, s in self.specs.items()
            },
        }

    # ----------------------------------------------------------------- queries

    def _require_built(self) -> None:
        if not self._built:
            raise RuntimeError("bank not built — call build() first")

    def _executable(self, names: tuple[str, ...], bucket: int, k_exec: int,
                    q_widths: tuple[int, ...], with_excl: bool):
        """(source-mask, batch bucket, k, query widths, exclusion) ->
        compiled fused program via the persistent AOT caches."""
        key = (names, bucket, k_exec, q_widths, with_excl)
        compiled = self._executables.get(key)
        if compiled is not None:
            return compiled
        with self._exec_lock:
            compiled = self._executables.get(key)
            if compiled is not None:
                return compiled
            return self._build_executable(key)

    def _build_executable(self, key):
        import jax

        from albedo_tpu.utils.aot import persistent_aot_executable

        names, bucket, k_exec, q_widths, with_excl = key
        kinds = tuple(self.specs[n].kind for n in names)
        k_each = tuple(
            min(k_exec, int(self.specs[n].vectors.shape[0])) for n in names
        )
        use_excl = tuple(
            with_excl and self.specs[n].exclude_seen and kinds[i] == "user_rows"
            for i, n in enumerate(names)
        )
        remap = tuple(n in self._excl_map for n in names)
        tables, user_idx, q_idxs, excl = self._program_args(
            names, np.zeros(bucket, dtype=np.int32),
            tuple(
                np.full((bucket, w), -1, dtype=np.int32) if w else None
                for w in q_widths
            ),
            with_excl,
        )
        fn = _make_query_program(
            kinds, k_each, use_excl, remap, k_exec, self.item_block
        )
        key_parts = (
            "retrieval_query", names, kinds, bucket, k_exec, q_widths,
            with_excl, use_excl, remap, self.item_block,
            tuple(tuple(self.specs[n].vectors.shape) for n in names),
            tuple(
                tuple(self.specs[n].user_vectors.shape)
                if self.specs[n].user_vectors is not None else ()
                for n in names
            ),
            () if self._excl_dev is None else tuple(np.asarray(self._excl_dev).shape),
            jax.default_backend(),
        )
        compiled, compile_s, source = persistent_aot_executable(
            fn, (tables, user_idx, q_idxs, excl), None, None,
            key_parts, name="retrieval_query",
        )
        if source != "memory":
            log.info(
                "retrieval shape (sources=%s, bucket=%d, k=%d, excl=%s) "
                "ready (%s, %.2fs)", ",".join(names), bucket, k_exec,
                with_excl, source, compile_s,
            )
        self._executables[key] = compiled
        return compiled

    def _program_args(self, names, user_idx, q_idxs, with_excl):
        """Assemble the call-time argument pytree: CURRENT device tables
        (overlay publishes swap the array, the executable is shape-keyed),
        the user-index gather rows, per-source query rows, exclusion table."""
        tables = []
        for n in names:
            spec = self.specs[n]
            if spec.kind == "user_rows":
                tab = [self._uf[n], self._vf[n]]
                if n in self._excl_map:
                    tab.append(self._excl_map[n])
                tables.append(tuple(tab))
            else:
                tables.append((self._vf[n],))
        excl = self._excl_dev if with_excl else None
        return tuple(tables), user_idx, q_idxs, excl

    def _q_rows(self, name: str, queries: list[np.ndarray]) -> tuple[np.ndarray, int]:
        """Raw query item ids -> padded (B, Q) source-row index matrix."""
        rowmap = self._rowmap[name]
        rows = [
            np.array(
                [rowmap[int(i)] for i in q if int(i) in rowmap], dtype=np.int32
            )
            for q in queries
        ]
        width = _pow2(max(1, max((r.size for r in rows), default=1)))
        out = np.full((len(queries), width), -1, dtype=np.int32)
        for b, r in enumerate(rows):
            out[b, : r.size] = r
        return out, width

    def query(
        self,
        user_dense: np.ndarray,
        k: int,
        raw_user_ids: np.ndarray | None = None,
        sources: tuple[str, ...] | None = None,
        exclude_seen: bool = False,
    ) -> dict[str, tuple[np.ndarray, np.ndarray]]:
        """One fused candidate pass for a batch of users.

        ``user_dense``: dense matrix user indices (``-1`` = unknown: user-row
        sources return no rows, item_mean sources still answer from
        ``query_items``). Returns per source ``(scores (B, k) f32, rows
        (B, k) int32)`` — rows index the source's ``item_ids``; ``-1`` marks
        an empty slot. Scores are RAW (host-path parity); apply
        ``calibration[name]["scale"]`` for cross-source fusion.
        """
        self._require_built()
        QUERY_FAULT.hit()
        names = tuple(sources) if sources is not None else self.source_names
        unknown = set(names) - set(self.specs)
        if unknown:
            raise KeyError(f"unregistered bank source(s): {sorted(unknown)}")
        user_dense = np.asarray(user_dense, dtype=np.int64)
        b = user_dense.shape[0]
        if raw_user_ids is not None and len(raw_user_ids) != b:
            # A short id list would silently serve empty candidates for the
            # tail users (and a long one a shape mismatch deep in dispatch).
            raise ValueError(
                f"raw_user_ids ({len(raw_user_ids)}) must align with "
                f"user_dense ({b})"
            )
        if b == 0:
            empty = (
                np.zeros((0, k), dtype=np.float32),
                np.full((0, k), -1, dtype=np.int32),
            )
            return {n: empty for n in names}
        # Per-source example-query rows (host dict lookups; tiny per batch).
        q_raw: dict[str, list[np.ndarray]] = {}
        for n in names:
            spec = self.specs[n]
            if spec.kind != "item_mean":
                continue
            fn = spec.query_items
            if fn is not None and raw_user_ids is None:
                # query_items providers are keyed by RAW user id; silently
                # feeding them dense indices would answer with some OTHER
                # user's candidates — refuse instead.
                raise ValueError(
                    f"source {n!r} needs raw_user_ids (its query_items "
                    f"provider is keyed by raw user id, not dense index)"
                )
            q_raw[n] = [
                (
                    np.asarray(fn(int(u)), dtype=np.int64)
                    if fn is not None
                    else np.zeros(0, dtype=np.int64)
                )
                for u in (raw_user_ids if fn is not None else user_dense)
            ]
        wants_excl = bool(exclude_seen) and any(
            self.specs[n].exclude_seen for n in names
        )
        if wants_excl and self._excl_dev is None:
            # Refuse rather than silently return seen items: the caller
            # asked for the exclusion contract and this build cannot honor
            # it (build() was not given the exclusion table).
            raise ValueError(
                "exclude_seen=True but the bank was built without an "
                "exclude_table; pass the batcher's exclusion table to build()"
            )
        with_excl = wants_excl
        known = user_dense >= 0
        if self.mesh is not None:
            out = self._query_sharded(names, user_dense, q_raw, k, with_excl)
        else:
            out = self._query_fused(names, user_dense, q_raw, k, with_excl, b)
        # Unknown users never answer from user-row sources (the host paths'
        # inner-join-on-userFactors semantics).
        for n in names:
            if self.specs[n].kind == "user_rows" and not known.all():
                vals, idx = out[n]
                vals = np.where(known[:, None], vals, np.float32(-np.inf))
                idx = np.where(known[:, None], idx, np.int32(-1))
                out[n] = (vals.astype(np.float32), idx.astype(np.int32))
            events.retrieval_queries.inc(b, source=n)
        return out

    def _query_fused(self, names, user_dense, q_raw, k, with_excl, b):
        bucket = _pow2(min(self.max_batch, max(1, b)))
        if b > bucket:  # batches beyond the ladder split (batcher discipline)
            out: dict[str, tuple[np.ndarray, np.ndarray]] = {}
            for start in range(0, b, bucket):
                part = self._query_fused(
                    names, user_dense[start:start + bucket],
                    {n: q[start:start + bucket] for n, q in q_raw.items()},
                    k, with_excl, min(bucket, b - start),
                )
                for n, (v, i) in part.items():
                    pv, pi = out.get(n, (np.zeros((0, k), np.float32),
                                         np.full((0, k), -1, np.int32)))
                    out[n] = (np.concatenate([pv, v]), np.concatenate([pi, i]))
            return out
        k_exec = _pow2(int(k))
        user_idx = np.zeros(bucket, dtype=np.int32)
        user_idx[:b] = np.clip(user_dense, 0, None).astype(np.int32)
        q_idxs, widths = [], []
        for n in names:
            if self.specs[n].kind == "item_mean":
                q_mat, w = self._q_rows(n, q_raw[n])
                if q_mat.shape[0] < bucket:
                    q_mat = np.pad(
                        q_mat, ((0, bucket - q_mat.shape[0]), (0, 0)),
                        constant_values=-1,
                    )
                q_idxs.append(q_mat)
                widths.append(w)
            else:
                q_idxs.append(None)
                widths.append(0)
        compiled = self._executable(
            names, bucket, k_exec, tuple(widths), with_excl
        )
        tables, user_idx, q_idxs, excl = self._program_args(
            names, user_idx, tuple(q_idxs), with_excl
        )
        results = compiled(tables, user_idx, q_idxs, excl)
        out = {}
        for n, (vals, idx) in zip(names, results):
            out[n] = (
                np.asarray(vals)[:b, :k],
                np.asarray(idx)[:b, :k],
            )
        return out

    def _query_sharded(self, names, user_dense, q_raw, k, with_excl):
        """Mesh path: per-source sharded MIPS through ``parallel/topk.py``
        (per-shard top-k -> cross-shard k-per-device merge) against the
        tables PINNED at build (pre-padded device residents — only the
        small query/exclusion rows move per batch). One dispatch per source
        rather than one fused pass — the tables are the big thing on a
        mesh, not the dispatch."""
        from albedo_tpu.parallel.topk import sharded_topk_scores

        b = user_dense.shape[0]
        out = {}
        for n in names:
            spec = self.specs[n]
            n_rows = int(spec.vectors.shape[0])
            if spec.kind == "user_rows":
                q = spec.user_vectors[np.clip(user_dense, 0, None)]
                excl = None
                if with_excl and spec.exclude_seen:
                    excl = np.asarray(self._excl_dev)[
                        np.clip(user_dense, 0, None)
                    ].astype(np.int32)
                    emap = self._excl_map.get(n)
                    if emap is not None:
                        emap = np.asarray(emap)
                        excl = np.where(
                            excl < 0, -1, emap[np.clip(excl, 0, None)]
                        ).astype(np.int32)
                vals, idx = sharded_topk_scores(
                    q, self._vf[n], k=k, mesh=self.mesh, exclude_idx=excl,
                    n_items=n_rows,
                )
            else:
                q_mat, _ = self._q_rows(n, q_raw[n])
                qv, has_q = mean_query_vectors(spec.vectors, q_mat)
                vals, idx = sharded_topk_scores(
                    qv, self._vf[n], k=k, mesh=self.mesh,
                    exclude_idx=q_mat, n_items=n_rows,
                )
                vals, idx = np.asarray(vals), np.asarray(idx)
                vals = np.where(has_q[:, None], vals, -np.inf)
                idx = np.where(has_q[:, None], idx, -1)
            out[n] = (
                np.asarray(vals, dtype=np.float32)[:b],
                np.asarray(idx, dtype=np.int32)[:b],
            )
        return out

    def query_similar(
        self, name: str, example_ids: list[np.ndarray] | np.ndarray, k: int
    ) -> list[tuple[np.ndarray, np.ndarray]]:
        """Similar-by-example over any source ("similar repos": example =
        one repo id against ``als``/``content``/``tfidf``; user-to-user:
        register the user table as its own source). Returns per query
        ``(raw_item_ids, scores)`` with the example rows excluded."""
        self._require_built()
        QUERY_FAULT.hit()
        if isinstance(example_ids, np.ndarray) and example_ids.ndim == 1:
            example_ids = [np.asarray([i]) for i in example_ids]
        queries = [np.asarray(q, dtype=np.int64) for q in example_ids]
        spec = self.specs[name]
        events.retrieval_queries.inc(len(queries), source=name)
        if self.mesh is not None:
            out = self._query_sharded(
                (name,),
                np.full(len(queries), -1, dtype=np.int64),
                {name: queries}, k, False,
            )[name] if spec.kind == "item_mean" else None
            if out is None:
                # user_rows source queried by example: run it as item_mean
                # over its own table (host-assembled queries).
                from albedo_tpu.parallel.topk import sharded_topk_scores

                q_mat, _ = self._q_rows(name, queries)
                qv, has_q = mean_query_vectors(spec.vectors, q_mat)
                vals, idx = sharded_topk_scores(
                    qv, self._vf[name], k=k, mesh=self.mesh,
                    exclude_idx=q_mat, n_items=int(spec.vectors.shape[0]),
                )
                vals = np.where(has_q[:, None], np.asarray(vals), -np.inf)
                idx = np.where(has_q[:, None], np.asarray(idx), -1)
                out = (vals.astype(np.float32), idx.astype(np.int32))
            vals, idx = out
        else:
            vals, idx = self._similar_fused(name, queries, k)
        results = []
        for b in range(len(queries)):
            ok = (idx[b] >= 0) & np.isfinite(vals[b])
            results.append((spec.item_ids[idx[b][ok]], vals[b][ok].astype(np.float64)))
        return results

    def _similar_fused(self, name: str, queries: list[np.ndarray], k: int):
        """Single-device similar-by-example: the item_mean program over one
        source (user_rows sources included — their table is queried by its
        own rows), through the same AOT executable ladder."""
        import jax

        from albedo_tpu.utils.aot import persistent_aot_executable

        b = len(queries)
        bucket = _pow2(min(self.max_batch, max(1, b)))
        if b > bucket:
            parts = [
                self._similar_fused(name, queries[s:s + bucket], k)
                for s in range(0, b, bucket)
            ]
            return (
                np.concatenate([p[0] for p in parts]),
                np.concatenate([p[1] for p in parts]),
            )
        k_exec = _pow2(int(k))
        q_mat, width = self._q_rows(name, queries)
        if q_mat.shape[0] < bucket:
            q_mat = np.pad(
                q_mat, ((0, bucket - q_mat.shape[0]), (0, 0)), constant_values=-1
            )
        spec = self.specs[name]
        key = ("similar", name, bucket, k_exec, width)
        compiled = self._executables.get(key)
        if compiled is None:
            # Same cache discipline as _executable(): double-checked under
            # the lock so concurrent cold callers compile once.
            with self._exec_lock:
                compiled = self._executables.get(key)
                if compiled is None:
                    fn = _make_query_program(
                        ("item_mean",),
                        (min(k_exec, int(spec.vectors.shape[0])),),
                        (False,), (False,), k_exec, self.item_block,
                    )
                    key_parts = (
                        "retrieval_similar", name, bucket, k_exec, width,
                        tuple(spec.vectors.shape), self.item_block,
                        jax.default_backend(),
                    )
                    compiled, _, _ = persistent_aot_executable(
                        fn,
                        (
                            ((self._vf[name],),),
                            np.zeros(bucket, dtype=np.int32),
                            (q_mat,),
                            None,
                        ),
                        None, None, key_parts, name="retrieval_similar",
                    )
                    self._executables[key] = compiled
        ((vals, idx),) = compiled(
            ((self._vf[name],),), np.zeros(bucket, dtype=np.int32), (q_mat,), None
        )
        return np.asarray(vals)[:b, :k], np.asarray(idx)[:b, :k]

    # ----------------------------------------------------------------- overlay

    def publish_user_rows(
        self, name: str, dense_rows: np.ndarray, rows: np.ndarray
    ) -> int:
        """Land freshly solved user rows (the fold-in engine's output) into a
        ``user_rows`` source's query table — the streaming overlay target.
        Tables are call-time arguments of the query executables, so the next
        batch reads the new rows with no recompile. Returns the bank's new
        overlay generation."""
        import jax.numpy as jnp

        self._require_built()
        spec = self.specs[name]
        if spec.kind != "user_rows":
            raise ValueError(f"source {name!r} has no user-row table to overlay")
        dense_rows = np.asarray(dense_rows, dtype=np.int64)
        rows = np.asarray(rows, dtype=np.float32)
        if rows.shape != (dense_rows.shape[0], spec.user_vectors.shape[1]):
            raise ValueError(
                f"overlay rows {rows.shape} do not match "
                f"({dense_rows.shape[0]}, {spec.user_vectors.shape[1]})"
            )
        # Host copy first (the sharded path and a future save() read it),
        # then the device table (functional update; old array stays valid
        # for in-flight batches — the generation-snapshot discipline).
        if name not in self._overlay_owned:
            # The registered array may BE the model's own cached factors
            # (the adapters register no-copy views); mutating it in place
            # would rewrite the trained model under every other holder —
            # the overlay owns its copy from the first publish on.
            spec.user_vectors = spec.user_vectors.copy()
            self._overlay_owned.add(name)
        spec.user_vectors[dense_rows] = rows
        if self.mesh is None:
            self._uf[name] = self._uf[name].at[jnp.asarray(dense_rows)].set(
                jnp.asarray(rows)
            )
        self.overlay_generation += 1
        return self.overlay_generation

    # ----------------------------------------------------------- persistence

    def save(self, artifact_name: str, lineage: dict | None = None):
        """Persist the built bank: pickle + ``.meta.json`` stamp (the
        manifest() record + lineage) + the ``.sha256`` manifest written
        LAST — the same seal every publishable artifact carries, so a death
        mid-write leaves nothing a watcher would promote."""
        from albedo_tpu.datasets import artifacts as store

        self._require_built()
        path = store.artifact_path(artifact_name)
        # Overlay publishes since build() changed the sealed content; the
        # stamp must vouch for the bytes actually written.
        self.version = self._content_hash()
        payload = {
            "format": "retrieval-bank-v1",
            "version": self.version,
            "built_at": self.built_at,
            "item_block": self.item_block,
            "max_batch": self.max_batch,
            "calibration": self.calibration,
            "sources": [
                {
                    "name": s.name,
                    "kind": s.kind,
                    "exclude_seen": bool(s.exclude_seen),
                    "vectors": s.vectors,
                    "item_ids": s.item_ids,
                    "user_vectors": s.user_vectors,
                }
                for s in self.specs.values()
            ],
        }
        store.save_pickle(path, payload)
        store.write_meta(path, {
            "bank": self.manifest(),
            "lineage": dict(lineage or {}),
        })
        store.write_manifest(path)
        return path

    @classmethod
    def load(cls, artifact_name: str, verify: bool = True) -> "RetrievalBank":
        """Restore a saved bank (un-built: the loading process runs its own
        admission + upload via ``build()``). ``verify`` enforces the
        ``.sha256`` manifest — a mismatch raises rather than serving
        corrupted embeddings; reload-style quarantine is the stage's job.
        Query-item providers are live callables and do not persist — rebind
        them (``bind_query_items``) before serving item_mean sources."""
        from albedo_tpu.datasets import artifacts as store

        path = store.artifact_path(artifact_name)
        if verify and store.verify_manifest(path) is False:
            raise ValueError(f"bank artifact {path.name} fails its manifest")
        payload = store.load_pickle(path)
        if payload.get("format") != "retrieval-bank-v1":
            raise ValueError(f"not a retrieval bank artifact: {path.name}")
        bank = cls(
            item_block=int(payload.get("item_block", 4096)),
            max_batch=int(payload.get("max_batch", 64)),
        )
        for s in payload["sources"]:
            bank.register(BankSourceSpec(
                name=s["name"], kind=s["kind"], vectors=s["vectors"],
                item_ids=s["item_ids"], user_vectors=s["user_vectors"],
                exclude_seen=bool(s["exclude_seen"]),
            ))
        return bank

    def bind_query_items(self, name: str, fn: Callable[[int], np.ndarray]) -> None:
        """Re-attach a query-item provider after ``load()`` (providers are
        live callables over the serving tables; they never persist)."""
        self.specs[name].query_items = fn
