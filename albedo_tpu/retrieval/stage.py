"""BankStage: the serving pipeline's bank-backed candidate stage.

Inside :class:`~albedo_tpu.serving.pipeline.TwoStagePipeline`, sources the
bank carries stop being threads: stage 1 submits ONE bank task that answers
every bank-resident source in a single fused device pass, while truly
external sources (and any source the bank does not carry) keep the
thread + breaker fan-out. The degradation contract gains one new edge —
a bank query that times out or raises falls back to the **host-side
per-source path** for exactly the sources it was covering (tagged
``bank_timeout`` / ``bank_error``, counted in
``albedo_retrieval_fallbacks_total{reason=}``), never a 500.

The stage also owns bank **generations**: ``reload()`` promotes a freshly
saved bank artifact through the same gate shape the model hot-swap uses
(manifest -> stamp -> load -> invariants -> capacity -> probe), atomically
swapping the served bank only after every gate passes. Outcomes land in
``albedo_retrieval_promotions_total{outcome=}``; a capacity refusal is a
recorded rejection, not a quarantine (the bytes are fine, the process is
full — the reload capacity-gate convention).
"""

from __future__ import annotations

import logging
import threading

import numpy as np
import pandas as pd

from albedo_tpu.analysis.locksmith import named_lock
from albedo_tpu.retrieval.bank import RetrievalBank
from albedo_tpu.utils import events

log = logging.getLogger(__name__)


class BankStage:
    """One served bank + the host fallbacks behind it.

    ``fallbacks`` maps source name -> host-side :class:`Recommender`; on a
    bank failure the pipeline fans those out exactly as it would have
    without a bank. ``calibrate=True`` multiplies each source's scores by
    its build-time calibration scale (cross-source fusion on one scale);
    the default serves RAW scores — bit-comparable with the host paths.
    """

    def __init__(
        self,
        bank: RetrievalBank,
        matrix,
        sources: tuple[str, ...] | None = None,
        fallbacks: dict | None = None,
        top_k: int = 30,
        calibrate: bool = False,
        timeout_s: float = 1.0,
    ):
        self._bank = bank
        self.matrix = matrix
        self._sources = tuple(sources) if sources is not None else bank.source_names
        self.fallbacks = dict(fallbacks or {})
        self.top_k = int(top_k)
        self.calibrate = bool(calibrate)
        # The bank's OWN wait budget inside stage 1 — strictly less than the
        # stage deadline by construction (the pipeline caps it at half the
        # remaining stage budget), so a timed-out bank always leaves the
        # host fallback real time to answer instead of a zero-budget collect.
        self.timeout_s = float(timeout_s)
        self._swap_lock = named_lock("retrieval.stage.swap")
        self.generation = 1

    @property
    def bank(self) -> RetrievalBank:
        return self._bank

    @property
    def source_names(self) -> tuple[str, ...]:
        return tuple(n for n in self._sources if n in self._bank.specs)

    def publish_user_rows(self, source: str, dense_rows, rows) -> int:
        """Forward a streaming overlay publish to the CURRENTLY SERVED bank.

        Fold-in subscribers attach the STAGE, not a bank object — a bank
        held directly would go stale at the first generation promotion and
        every later publish would land in the retired tables."""
        return self._bank.publish_user_rows(source, dense_rows, rows)

    def snapshot(self) -> dict:
        """The readiness probe's view of the stage."""
        return {
            "generation": self.generation,
            "version": self._bank.version,
            "overlay_generation": self._bank.overlay_generation,
            "sources": list(self.source_names),
            "sharded": self._bank.mesh is not None,
        }

    # ------------------------------------------------------------------ query

    def query_frames(
        self,
        user_id: int,
        k: int | None = None,
        exclude_seen: bool = True,
        sources: tuple[str, ...] | None = None,
    ) -> dict[str, pd.DataFrame]:
        """One user's candidates from the requested bank sources, as
        recommender frames (user_id, repo_id, score, source) — the
        fusion-ready shape ``recommenders.base`` produces, from one device
        pass. ``sources`` restricts the pass (the pipeline excludes names
        its generation snapshot already answers — a bank frame must never
        clobber the snapshot's). ``k`` overrides the stage's ``top_k`` —
        the brownout ladder's reduced-k tier passes its halved budget here;
        it is clamped to >= 1 so an aggressively-degraded request can never
        drive the device pass with an empty shape."""
        bank = self._bank  # snapshot: a concurrent reload must not tear us
        k = max(1, self.top_k if k is None else int(k))
        dense = self.matrix.users_of(np.asarray([int(user_id)], dtype=np.int64))
        # Filter against the SNAPSHOTTED bank — source_names reads the live
        # one, and a mid-request promote that adds a source would otherwise
        # ask the old bank for a name it never registered.
        wanted = self._sources if sources is None else tuple(sources)
        names = tuple(n for n in wanted if n in bank.specs)
        out = bank.query(
            dense, k,
            raw_user_ids=np.asarray([int(user_id)], dtype=np.int64),
            sources=names, exclude_seen=exclude_seen,
        )
        frames: dict[str, pd.DataFrame] = {}
        for name, (vals, idx) in out.items():
            spec = bank.specs[name]
            ok = (idx[0] >= 0) & np.isfinite(vals[0])
            scores = vals[0][ok].astype(np.float64)
            if self.calibrate:
                scores = scores * float(
                    bank.calibration.get(name, {}).get("scale", 1.0)
                )
            frames[name] = pd.DataFrame({
                "user_id": np.full(int(ok.sum()), int(user_id), dtype=np.int64),
                "repo_id": spec.item_ids[idx[0][ok]],
                "score": scores,
                "source": name,
            })
        return frames

    # ----------------------------------------------------------- generations

    _INCUMBENT_MESH = object()  # sentinel: "build on the incumbent's mesh"

    def reload(
        self,
        artifact_name: str,
        require_stamp: bool = False,
        probe_users: int = 4,
        probe_k: int = 10,
        mesh=_INCUMBENT_MESH,
    ) -> dict:
        """Promote a bank artifact through the validation gates.

        Gates, in order (any failure = recorded rejection, incumbent keeps
        serving): **manifest** (``.sha256`` verifies), **stamp**
        (``.meta.json`` present when required), **load** (unpickle +
        format), **invariants** (finite tables; source names/dims cover the
        incumbent's — a shrunken bank is a restart, not a swap),
        **capacity** (candidate priced ALONGSIDE the incumbent,
        ``generations=2``), **probe** (probe users answer with finite
        scores and in-range rows through the candidate's real query path).

        ``mesh`` overrides the layout the candidate builds onto; the
        default is the incumbent's own mesh. This is the degraded-serving
        seam: the shard count is a per-process LAYOUT choice, not part of
        the artifact — a bank saved by an 8-shard builder promotes onto
        whatever rung the ladder gave THIS process (4, 2, 1, or a plain
        single device), and a candidate too big for the smaller rung is a
        recorded capacity rejection, never a quarantine.
        """
        from albedo_tpu.datasets import artifacts as store
        from albedo_tpu.utils.capacity import CapacityExceeded

        def reject(gate: str, why: str) -> dict:
            events.retrieval_promotions.inc(outcome="rejected")
            log.warning("bank reload rejected at gate %s: %s", gate, why)
            return {"outcome": "rejected", "gate": gate, "why": why}

        path = store.artifact_path(artifact_name)
        if store.verify_manifest(path) is not True:
            return reject("manifest", f"{path.name}: missing or failing manifest")
        meta = store.read_meta(path)
        if require_stamp and meta is None:
            return reject("stamp", f"{path.name}: unstamped bank artifact")
        try:
            candidate = RetrievalBank.load(artifact_name)
        except Exception as e:  # noqa: BLE001 — any unreadable candidate rejects
            return reject("load", f"{type(e).__name__}: {e}")

        incumbent = self._bank
        for name in incumbent.specs:
            if name not in candidate.specs:
                return reject(
                    "invariants",
                    f"candidate drops source {name!r} — a changed source set "
                    f"is a restart, not a swap",
                )
            if candidate.specs[name].vectors.shape[1] != incumbent.specs[name].vectors.shape[1]:
                return reject(
                    "invariants",
                    f"source {name!r} rank changed "
                    f"{incumbent.specs[name].vectors.shape[1]} -> "
                    f"{candidate.specs[name].vectors.shape[1]}",
                )
        for name, spec in candidate.specs.items():
            if not np.all(np.isfinite(spec.vectors)) or (
                spec.user_vectors is not None
                and not np.all(np.isfinite(spec.user_vectors))
            ):
                return reject("invariants", f"source {name!r} carries non-finite rows")
            # Live query-item providers never persist; inherit the
            # incumbent's bindings so item_mean sources keep answering (a
            # GROWN source set is legal — an added source the incumbent
            # never carried simply has no binding to inherit).
            if spec.kind == "item_mean" and spec.query_items is None:
                inc_spec = incumbent.specs.get(name)
                if inc_spec is not None:
                    spec.query_items = inc_spec.query_items

        try:
            candidate.build(
                matrix=self.matrix,
                exclude_table=(
                    np.asarray(incumbent._excl_dev)
                    if incumbent._excl_dev is not None else None
                ),
                mesh=incumbent.mesh if mesh is self._INCUMBENT_MESH else mesh,
                generations=2,  # incumbent + candidate resident through the swap
            )
        except CapacityExceeded as e:
            # Recorded rejection, NOT a quarantine: the artifact is fine,
            # this process is full (the reload capacity-gate convention).
            return reject("capacity", str(e))
        except Exception as e:  # noqa: BLE001
            return reject("load", f"build failed: {type(e).__name__}: {e}")

        try:
            n = min(int(probe_users), max(1, self.matrix.n_users))
            probe = candidate.query(
                np.arange(n, dtype=np.int64), int(probe_k),
                raw_user_ids=self.matrix.user_ids[:n],
                sources=tuple(candidate.source_names),
                exclude_seen=False,
            )
            for name, (vals, idx) in probe.items():
                live = idx >= 0  # filled slots; -1 = legitimately empty
                if np.any(idx[live] >= candidate.specs[name].item_ids.shape[0]):
                    return reject("probe", f"source {name!r} returned out-of-range rows")
                if np.any(~np.isfinite(vals[live])):
                    return reject("probe", f"source {name!r} returned non-finite scores")
        except Exception as e:  # noqa: BLE001
            return reject("probe", f"{type(e).__name__}: {e}")

        with self._swap_lock:
            self._bank = candidate
            self.generation += 1
        events.retrieval_promotions.inc(outcome="promoted")
        log.info(
            "bank generation %d promoted (version %s, %d source(s))",
            self.generation, candidate.version, len(candidate.specs),
        )
        return {
            "outcome": "promoted",
            "generation": self.generation,
            "version": candidate.version,
        }

    def reshard(self, mesh) -> dict:
        """Re-lay the LIVE bank onto a different mesh — the in-place
        degraded-serving move after a device loss halves the serving slice
        mid-flight (promotion-shaped swaps go through :meth:`reload`).
        Re-admission runs first (per-device shards double when the mesh
        halves); a refusal leaves the current layout serving and is a
        recorded rejection, not a quarantine. Returns the stage snapshot.
        """
        from albedo_tpu.utils.capacity import CapacityExceeded

        with self._swap_lock:
            try:
                self._bank.reshard(mesh)
            except CapacityExceeded as e:
                events.retrieval_promotions.inc(outcome="rejected")
                log.warning("bank reshard refused: %s", e)
                return {"outcome": "rejected", "gate": "capacity", "why": str(e)}
        log.info(
            "bank resharded onto %s",
            dict(mesh.shape) if mesh is not None else "single-device",
        )
        return dict(self.snapshot(), outcome="resharded")
