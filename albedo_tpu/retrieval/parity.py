"""Candidate-set parity between the bank and the host-side score paths.

The acceptance contract for the bank (tests AND the bench's parity gate):
for each registered source, the bank-served top-k over the probe users must
match the existing host-side recommender's top-k — scores within ``atol``,
item sets equal **modulo tie handling** (two items whose scores differ by
less than ``atol`` are interchangeable at the cut; both paths sort
value-desc with index-asc tie-break, but their index SPACES differ, so the
tie ORDER can legitimately differ while the score profile cannot).
"""

from __future__ import annotations

import numpy as np


def candidate_parity(
    host: "tuple[np.ndarray, np.ndarray]",
    bank: "tuple[np.ndarray, np.ndarray]",
    atol: float = 1e-5,
) -> dict:
    """Compare one user's host vs bank top-k: ``(item_ids, scores)`` pairs,
    score-descending. Returns a report dict with ``ok`` plus what broke."""
    h_ids, h_scores = (np.asarray(a) for a in host)
    b_ids, b_scores = (np.asarray(a) for a in bank)
    report: dict = {"ok": True, "n_host": int(h_ids.size), "n_bank": int(b_ids.size)}
    if h_ids.size != b_ids.size:
        report.update(ok=False, why="candidate count differs")
        return report
    if h_ids.size == 0:
        return report
    order_h = np.argsort(-h_scores, kind="stable")
    order_b = np.argsort(-b_scores, kind="stable")
    hs, bs = h_scores[order_h], b_scores[order_b]
    score_err = float(np.max(np.abs(hs - bs)))
    report["max_score_err"] = score_err
    if score_err > atol:
        report.update(ok=False, why=f"rank-wise scores differ by {score_err:.2e}")
        return report
    # Set equality modulo ties: any item in exactly one set must be tied
    # (within atol) with an item of the other set at the same score level.
    only_h = np.setdiff1d(h_ids, b_ids)
    only_b = np.setdiff1d(b_ids, h_ids)
    report["symmetric_difference"] = int(only_h.size + only_b.size)
    for ids, own_ids, own_scores, other_scores in (
        (only_h, h_ids, h_scores, b_scores),
        (only_b, b_ids, b_scores, h_scores),
    ):
        for item in ids:
            s = float(own_scores[np.nonzero(own_ids == item)[0][0]])
            if not np.any(np.abs(other_scores - s) <= atol):
                report.update(
                    ok=False,
                    why=(
                        f"item {int(item)} (score {s:.6g}) has no tied "
                        f"counterpart in the other path's set"
                    ),
                )
                return report
    return report


def frame_to_pairs(frame, user_id: int) -> tuple[np.ndarray, np.ndarray]:
    """A recommender frame's rows for one user as ``(item_ids, scores)``."""
    rows = frame[frame["user_id"] == int(user_id)]
    return (
        rows["repo_id"].to_numpy(np.int64),
        rows["score"].to_numpy(np.float64),
    )
