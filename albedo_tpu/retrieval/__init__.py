"""Unified on-device candidate retrieval (ROADMAP item 5).

One device-resident, mesh-shardable **embedding bank** replaces the
host-side candidate fan-out for every source that is really just a dot
product against an embedding table: ALS item factors, Word2Vec repo
embeddings, TF-IDF projections, and user rows (user-to-user / similar-repo
scenarios). Serving queries become a single fused gather -> blocked GEMM ->
top-k device pass per batch instead of N host threads with per-source
deadlines and breakers — the breaker machinery remains only for sources
that are truly external.
"""

from albedo_tpu.retrieval.bank import (
    BankSourceSpec,
    RetrievalBank,
    bank_artifact_name,
)
from albedo_tpu.retrieval.parity import candidate_parity
from albedo_tpu.retrieval.stage import BankStage

__all__ = [
    "BankSourceSpec",
    "BankStage",
    "RetrievalBank",
    "bank_artifact_name",
    "candidate_parity",
]
