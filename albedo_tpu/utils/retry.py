"""Shared retry policy: exponential backoff with full jitter + deadline budget.

One policy object serves every layer that talks to something flaky — the
GitHub crawler's transport, artifact IO, and the ``run_pipeline`` stage
driver — replacing the per-site fixed sleeps (``sleep(1800)``/``sleep(1.0)``)
the seed hard-coded. Full jitter (delay ~ U(0, min(cap, base * mult^n)))
follows the AWS architecture-blog result ALX-style preemptible fleets rely
on: synchronized retry storms after a shared outage are worse than the
failure itself.

Servers that SAY when to come back are honored exactly: raise
:class:`RetryAfter` from the attempt (the crawler does, from the GitHub
``Retry-After`` / ``X-RateLimit-Reset`` headers) and the wait is the server's
number, not the backoff curve's. Every performed retry is counted in the
process-global ``albedo_retry_attempts_total{site=...}`` counter
(``utils.events``) so `/metrics` shows which dependency is flapping.

The serving circuit breakers (``serving.breaker``) ride the SAME
:class:`RetryPolicy` schedule for their open -> half-open reopen timing
(base/multiplier/cap walked up per consecutive trip), with equal jitter
instead of full jitter — a breaker drawing a ~0 s delay would probe a dead
dependency exactly when it should back off.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Any, Callable

from albedo_tpu.utils import events


# Substrings a device OOM carries, across backends and jax versions. An
# XlaRuntimeError's class lives deep in jaxlib and moves between releases, so
# classification is by name + message — which also covers the fault harness's
# InjectedResourceExhausted (a MemoryError) without importing jax here.
_RESOURCE_EXHAUSTED_MARKERS = (
    "RESOURCE_EXHAUSTED", "RESOURCE EXHAUSTED", "Out of memory",
    "out of memory", "OOM",
)


def is_resource_exhausted(exc: BaseException) -> bool:
    """True for device/host OOM-shaped failures: ``MemoryError``, an
    ``XlaRuntimeError`` (by class name — jaxlib moves it between modules)
    whose message says RESOURCE_EXHAUSTED/out-of-memory, or the fault
    harness's injected OOM. These are PERMANENT for retry purposes: the
    same allocation re-OOMs the same device, so backoff burns the whole
    budget re-crashing — the caller must fail fast to the degrade path
    (``utils.capacity``) instead."""
    if isinstance(exc, MemoryError):
        return True
    msg = str(exc)
    name = type(exc).__name__
    if name == "XlaRuntimeError" or "XlaRuntimeError" in name:
        return any(m in msg for m in _RESOURCE_EXHAUSTED_MARKERS)
    return any(m in msg for m in _RESOURCE_EXHAUSTED_MARKERS[:2])


# Substrings a dead/hung collective carries, across backends and jax
# versions: jaxlib surfaces a shard that stopped answering as an
# XlaRuntimeError DEADLINE_EXCEEDED from the stuck all-gather/psum, and the
# distributed runtime (coordination service) reports the lost worker as a
# missed-heartbeat failure. Classification is by name + message, like the
# OOM predicate above — which also covers the fault harness's
# InjectedDeviceLoss without importing jax here.
_COLLECTIVE_LOST_MARKERS = (
    "DEADLINE_EXCEEDED", "DEADLINE EXCEEDED", "heartbeat",
    "coordination service", "task disconnected", "device lost",
)


def is_collective_lost(exc: BaseException) -> bool:
    """True for device-loss-shaped collective failures: a jaxlib
    ``XlaRuntimeError`` whose message says DEADLINE_EXCEEDED (the stuck
    collective's timeout), a distributed-runtime heartbeat/coordination
    failure, the elastic watchdog's own :class:`CollectiveTimeout`-shaped
    deadline trip, or the fault harness's injected ``loss`` kind. These are
    PERMANENT for retry purposes: the shard is dead or wedged, so every
    retry re-hangs the same collective until the backoff budget burns —
    the caller must fail FAST to the elastic remesh-resume path
    (``parallel/elastic.py``) instead."""
    msg = str(exc)
    name = type(exc).__name__
    if name in ("InjectedDeviceLoss", "CollectiveTimeout"):
        return True
    if "XlaRuntimeError" in name or "RuntimeError" in name:
        return any(m.lower() in msg.lower() for m in _COLLECTIVE_LOST_MARKERS)
    return any(m.lower() in msg.lower() for m in _COLLECTIVE_LOST_MARKERS[:2])


def default_retry_predicate(exc: BaseException) -> bool:
    """The shared baseline predicate: any Exception retries EXCEPT
    resource exhaustion (see :func:`is_resource_exhausted`) and collective
    device loss (see :func:`is_collective_lost`) — both re-fail identically
    on retry and must fail fast to their degrade/elastic paths. Callers
    with their own predicate should compose it:
    ``lambda e: my_check(e) and default_retry_predicate(e)``."""
    return not (is_resource_exhausted(exc) or is_collective_lost(exc))


class RetryAfter(Exception):
    """An attempt failed but the server supplied the wait: honor it.

    ``delay_s`` overrides the backoff curve for this one retry (still clipped
    to the policy's remaining deadline). Raised by callers' attempt
    functions; never raised by this module.
    """

    def __init__(self, delay_s: float, message: str = ""):
        super().__init__(message or f"retry after {delay_s:g}s")
        self.delay_s = max(0.0, float(delay_s))


class RetriesExhausted(Exception):
    """All attempts failed (or the deadline expired). ``__cause__`` is the
    last attempt's exception; ``attempts`` is how many were made."""

    def __init__(self, site: str, attempts: int, last: BaseException):
        super().__init__(f"{site}: giving up after {attempts} attempts: {last!r}")
        self.site = site
        self.attempts = attempts
        self.last = last


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Backoff schedule + stop conditions (immutable, shareable).

    ``max_attempts`` counts TOTAL attempts (first try included);
    ``deadline_s`` caps wall-clock across attempts AND sleeps — a retry whose
    jittered delay would overshoot the deadline sleeps only the remainder,
    gets one last attempt, and then gives up.
    """

    max_attempts: int = 5
    base_s: float = 0.5
    multiplier: float = 2.0
    max_delay_s: float = 60.0
    deadline_s: float | None = None
    jitter: bool = True  # full jitter; False = deterministic caps (tests)

    def cap(self, attempt: int) -> float:
        """The (un-jittered) backoff ceiling after the ``attempt``-th failure
        (0-based) — the one place the curve is defined. The exponent guard
        keeps a counter that keeps climbing (e.g. a breaker open for hours)
        from overflowing float range long after ``max_delay_s`` took over."""
        return min(
            self.max_delay_s, self.base_s * (self.multiplier ** min(attempt, 64))
        )

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Backoff delay after the ``attempt``-th failure (0-based)."""
        cap = self.cap(attempt)
        return rng.uniform(0.0, cap) if self.jitter else cap


def retry_call(
    fn: Callable[[], Any],
    *,
    policy: RetryPolicy | None = None,
    retry_on: Callable[[BaseException], bool] | None = None,
    site: str = "call",
    sleeper: Callable[[float], None] = time.sleep,
    rng: random.Random | None = None,
    clock: Callable[[], float] = time.monotonic,
    on_retry: Callable[[int, BaseException, float], None] | None = None,
) -> Any:
    """Call ``fn()`` until it returns, the predicate rejects, or budget ends.

    - ``retry_on(exc)`` decides retryability (default:
      :func:`default_retry_predicate` — any Exception EXCEPT resource
      exhaustion, which re-OOMs identically and must fail fast to the
      capacity degrade path); non-retryable exceptions propagate unchanged.
      :class:`RetryAfter` is always retryable and carries its own delay.
    - ``on_retry(attempt, exc, delay_s)`` observes each scheduled retry.
    - Exhaustion raises :class:`RetriesExhausted` from the last exception.

    ``sleeper``/``rng``/``clock`` are injectable for deterministic tests.
    """
    policy = policy or RetryPolicy()
    rng = rng or random.Random()
    start = clock()
    last: BaseException | None = None
    for attempt in range(max(1, policy.max_attempts)):
        try:
            return fn()
        except RetryAfter as e:
            last = e
            delay = e.delay_s
        except Exception as e:  # noqa: BLE001 — predicate decides
            predicate = retry_on if retry_on is not None else default_retry_predicate
            if not predicate(e):
                raise
            last = e
            delay = policy.delay(attempt, rng)
        if attempt + 1 >= policy.max_attempts:
            break
        if policy.deadline_s is not None:
            remaining = policy.deadline_s - (clock() - start)
            if remaining <= 0:
                break
            delay = min(delay, remaining)
        events.retry_attempts.inc(site=site)
        if on_retry is not None:
            on_retry(attempt, last, delay)
        if delay > 0:
            sleeper(delay)
    raise RetriesExhausted(site, min(policy.max_attempts, attempt + 1), last) from last
