"""Timing and profiling harness.

Reference parity: the reference measures wall-clock by prefixing ``time`` on
every spark-submit (``Makefile:64,78,131``) and decorating crawler methods
with ``timing_decorator`` (``app/utils_timing.py:7-15``); deeper inspection
goes through the Spark UI. Here timing is a first-class module (SURVEY.md §5):
``timed``/``Timer`` synchronize device work (``block_until_ready``) so numbers
mean what they say, and ``profiler_trace`` wraps the JAX profiler (the
TensorBoard-viewable trace is the Spark-UI analogue).
"""

from __future__ import annotations

import contextlib
import functools
import threading

from albedo_tpu.analysis.locksmith import named_lock
import time
from typing import Any, Callable, Iterator

import jax


def _sync(value: Any) -> None:
    """Block until every jax array in a pytree is computed."""
    for leaf in jax.tree.leaves(value):
        if hasattr(leaf, "block_until_ready"):
            leaf.block_until_ready()


class Timer:
    """Accumulating named wall-clock sections.

    >>> t = Timer()
    >>> with t.section("sweep"):
    ...     out = step()          # any jax outputs are synced on exit
    >>> t.report()
    """

    def __init__(self) -> None:
        self.totals: dict[str, float] = {}
        self.counts: dict[str, int] = {}
        # The serving pipeline accumulates sections from concurrent HTTP
        # threads; the read-modify-write below would lose increments
        # unlocked. Uncontended acquisition is ~100 ns — noise against the
        # device work the sections time.
        self._lock = named_lock("utils.profiling.timer")

    @contextlib.contextmanager
    def section(self, name: str, sync: Any = None) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            _sync(sync)
            dt = time.perf_counter() - t0
            with self._lock:
                self.totals[name] = self.totals.get(name, 0.0) + dt
                self.counts[name] = self.counts.get(name, 0) + 1

    def snapshot(self) -> dict[str, dict]:
        """Point-in-time copy of the accumulated sections:
        ``{"totals": {name: seconds}, "counts": {name: calls}}``.

        This is the one exchange format between the offline fit reports and
        the online `/metrics` plane (``serving.metrics.MetricsRegistry
        .observe_timer``) — both render the same dicts, so a stage timed here
        can never read differently in the two places."""
        with self._lock:
            return {"totals": dict(self.totals), "counts": dict(self.counts)}

    def report(self, printer: Callable[[str], None] = print) -> dict[str, float]:
        for name in sorted(self.totals, key=self.totals.get, reverse=True):  # type: ignore[arg-type]
            printer(
                f"{name}: {self.totals[name]:.3f}s over {self.counts[name]} call(s)"
            )
        return dict(self.totals)


@contextlib.contextmanager
def timed(label: str, sync: Any = None, printer: Callable[[str], None] = print):
    """One-shot timed block; syncs ``sync`` (a pytree of jax arrays) on exit."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _sync(sync)
        printer(f"[{label}] {time.perf_counter() - t0:.3f}s")


def timing(fn: Callable) -> Callable:
    """Decorator parity with the crawler's ``timing_decorator``
    (``app/utils_timing.py:7-15``): prints the wall-clock of each call,
    synchronizing any jax outputs first."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        _sync(out)
        print(f"[{fn.__name__}] {time.perf_counter() - t0:.3f}s")
        return out

    return wrapper


@contextlib.contextmanager
def profiler_trace(log_dir: str, enabled: bool = True):
    """JAX profiler trace (view in TensorBoard/XProf) — the Spark-UI analogue."""
    if not enabled:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield
