"""Dependency-free metric primitives + process-global offline event counters.

The Prometheus-compatible :class:`Counter`/:class:`Gauge`/:class:`Histogram`
primitives used by the serving metrics plane live here (``serving.metrics``
re-exports them) so the OFFLINE layers — artifact store, checkpointing,
retry, fault injection — can count events without importing the serving
package (which pulls jax through ``serving.service``).

Offline events are process-global by design: an artifact quarantined while a
``train_als`` job warms a serving process must show up on that process's
``/metrics`` page, whichever :class:`~albedo_tpu.serving.metrics.MetricsRegistry`
renders it. ``global_counter`` is get-or-create by metric name, and
``MetricsRegistry.render`` appends ``global_metrics()`` to every exposition.

Exposition follows the Prometheus text format 0.0.4 (`# HELP` / `# TYPE`
lines, cumulative `_bucket{le=...}` histogram rows, `_sum`/`_count` totals).
"""

from __future__ import annotations

import threading
from typing import Iterable, Mapping

# Latency-oriented default buckets (seconds): sub-ms dispatches up to
# multi-second degraded responses.
DEFAULT_TIME_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.5, 5.0,
)
# Batch-size buckets: the power-of-two shape ladder the micro-batcher pads to.
DEFAULT_SIZE_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)


def _fmt_value(v: float) -> str:
    """Prometheus renders integers bare and floats as-is; +Inf specially."""
    if v == float("inf"):
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels: Mapping[str, str] | None) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    """Monotonic counter, optionally labelled (one child per label set)."""

    kind = "counter"

    def __init__(self, name: str, help_: str, label_names: tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._values: dict[tuple[str, ...], float] = {}
        # Leaf metric-primitive lock, one per counter instance, never held
        # across another acquisition — tracking hundreds of these would
        # bloat the sanitizer graph for zero ordering signal.
        self._lock = threading.Lock()  # albedo: noqa[lock-discipline]

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(str(labels.get(n, "")) for n in self.label_names)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        key = tuple(str(labels.get(n, "")) for n in self.label_names)
        with self._lock:
            return self._values.get(key, 0.0)

    def total(self) -> float:
        """Sum across every label set (convenience for tests/reports)."""
        with self._lock:
            return sum(self._values.values())

    def samples(self) -> list[tuple[dict, float]]:
        """Every (labels, value) child — consumers that need per-label
        arithmetic (the reload error-rate watchdog) read this instead of
        poking the internals."""
        with self._lock:
            items = list(self._values.items())
        return [(dict(zip(self.label_names, key)), v) for key, v in items]

    def clear(self) -> None:
        """Drop all samples — test isolation for process-global counters."""
        with self._lock:
            self._values.clear()

    def render(self) -> Iterable[str]:
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.label_names:
            items = [((), 0.0)]  # unlabelled counters always expose a sample
        for key, value in items:
            labels = dict(zip(self.label_names, key))
            yield f"{self.name}{_fmt_labels(labels)} {_fmt_value(value)}"


class Gauge(Counter):
    """Settable value; shares the labelled-children plumbing of Counter."""

    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        key = tuple(str(labels.get(n, "")) for n in self.label_names)
        with self._lock:
            self._values[key] = float(value)


class Histogram:
    """Cumulative-bucket histogram (unlabelled — one series per metric)."""

    kind = "histogram"

    def __init__(self, name: str, help_: str, buckets: tuple[float, ...] = DEFAULT_TIME_BUCKETS):
        self.name = name
        self.help = help_
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +1 for +Inf
        self._sum = 0.0
        self._count = 0
        # Leaf primitive lock — see Counter.__init__ for why it stays bare.
        self._lock = threading.Lock()  # albedo: noqa[lock-discipline]

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._sum += value
            self._count += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def snapshot(self) -> dict:
        """(count, sum, per-bucket cumulative counts) under one lock."""
        with self._lock:
            cum, total = [], 0
            for c in self._counts:
                total += c
                cum.append(total)
            return {"count": self._count, "sum": self._sum, "cumulative": cum}

    def percentile(self, q: float) -> float:
        """Bucket-resolution percentile estimate (upper bound of the bucket
        holding the q-quantile observation) — for bench summaries, not SLOs."""
        snap = self.snapshot()
        if snap["count"] == 0:
            return 0.0
        target = q * snap["count"]
        for i, c in enumerate(snap["cumulative"][:-1]):
            if c >= target:
                return self.buckets[i]
        return float("inf")

    def render(self) -> Iterable[str]:
        snap = self.snapshot()
        edges = [*self.buckets, float("inf")]
        for edge, c in zip(edges, snap["cumulative"]):
            yield f'{self.name}_bucket{{le="{_fmt_value(edge)}"}} {c}'
        yield f"{self.name}_sum {_fmt_value(snap['sum'])}"
        yield f"{self.name}_count {snap['count']}"


# --- the metric-name registry -------------------------------------------------
#
# Every ``albedo_*`` metric name in the codebase is defined HERE, once, as a
# constant — the serving registry (serving/metrics.py) and the offline
# counters below both build from these. graftlint's contract-drift rule
# (albedo_tpu/analysis) enforces the discipline both ways: an inline
# ``"albedo_..."`` literal anywhere else in the package is a finding, and so
# is a registered name missing from the ARCHITECTURE.md metrics catalog.

# Serving plane (serving/metrics.py MetricsRegistry).
REQUESTS_TOTAL = "albedo_requests_total"
REQUEST_LATENCY_SECONDS = "albedo_request_latency_seconds"
SERVING_BATCH_SIZE = "albedo_serving_batch_size"
SERVING_BATCH_SECONDS = "albedo_serving_batch_seconds"
CACHE_HITS_TOTAL = "albedo_cache_hits_total"
CACHE_MISSES_TOTAL = "albedo_cache_misses_total"
DEGRADED_TOTAL = "albedo_degraded_total"
SHED_TOTAL = "albedo_shed_total"
DEADLINE_SHED_TOTAL = "albedo_deadline_shed_total"
MODEL_GENERATION = "albedo_model_generation"
RELOAD_TOTAL = "albedo_reload_total"
RELOAD_REJECTED_TOTAL = "albedo_reload_rejected_total"
GENERATION_REQUESTS_TOTAL = "albedo_generation_requests_total"
BREAKER_STATE = "albedo_breaker_state"
BREAKER_TRANSITIONS_TOTAL = "albedo_breaker_transitions_total"
STAGE_SECONDS = "albedo_stage_seconds"
STAGE_CALLS = "albedo_stage_calls"

# Offline fault-tolerance plane (the process-global counters below).
ARTIFACT_CORRUPTIONS_TOTAL = "albedo_artifact_corruptions_total"
CHECKPOINT_FALLBACKS_TOTAL = "albedo_checkpoint_fallbacks_total"
RETRY_ATTEMPTS_TOTAL = "albedo_retry_attempts_total"
FAULTS_FIRED_TOTAL = "albedo_faults_fired_total"
AOT_FINGERPRINT_MISMATCHES_TOTAL = "albedo_aot_fingerprint_mismatches_total"

# Data-quality firewall (PR 5).
DATA_VIOLATIONS_TOTAL = "albedo_data_violations_total"
WATCHDOG_TRIPS_TOTAL = "albedo_watchdog_trips_total"
PUBLISH_REJECTED_TOTAL = "albedo_publish_rejected_total"

# Streaming plane (PR 6).
STREAM_DELTAS_TOTAL = "albedo_stream_deltas_total"
FOLDIN_USERS_TOTAL = "albedo_foldin_users_total"
DRIFT_REFITS_TOTAL = "albedo_drift_refits_total"
STREAM_PUBLISHES_TOTAL = "albedo_stream_publishes_total"

# Capacity guardrails (PR 7).
CAPACITY_VERDICTS_TOTAL = "albedo_capacity_verdicts_total"
MESH_DEGRADED_TOTAL = "albedo_mesh_degraded_total"

# Elastic sharded operation (PR 12).
MESH_LOSSES_TOTAL = "albedo_mesh_losses_total"
ELASTIC_RESUMES_TOTAL = "albedo_elastic_resumes_total"

# Retrieval bank (ROADMAP item 5).
RETRIEVAL_QUERIES_TOTAL = "albedo_retrieval_queries_total"
RETRIEVAL_FALLBACKS_TOTAL = "albedo_retrieval_fallbacks_total"
RETRIEVAL_PROMOTIONS_TOTAL = "albedo_retrieval_promotions_total"

# Concurrency sanitizer (analysis/locksmith.py, ALBEDO_LOCKCHECK=1).
LOCKCHECK_VIOLATIONS_TOTAL = "albedo_lockcheck_violations_total"

# Full-catalog batch scoring (ROADMAP item 4, the score_all job).
SCORE_USERS_TOTAL = "albedo_score_users_total"
SCORE_SHARDS_TOTAL = "albedo_score_shards_total"
SCORE_PUBLISH_REJECTED_TOTAL = "albedo_score_publish_rejected_total"

# Overload-resilience plane (serving/overload.py, PR 20).
BROWNOUT_LEVEL = "albedo_brownout_level"
OVERLOAD_SHED_TOTAL = "albedo_overload_shed_total"
ADMISSION_LIMIT = "albedo_admission_limit"

METRIC_NAMES: frozenset = frozenset(
    v for k, v in list(globals().items())
    if k.isupper() and isinstance(v, str) and v.startswith("albedo_")
)


# --- process-global offline counters -----------------------------------------

# Held only around registry-dict access; counter construction under it
# acquires nothing — a leaf like the per-counter locks above.
_global_lock = threading.Lock()  # albedo: noqa[lock-discipline]
_global_metrics: dict[str, Counter] = {}


def global_counter(name: str, help_: str, label_names: tuple[str, ...] = ()) -> Counter:
    """Get-or-create a process-global counter by metric name.

    The label schema is fixed by the first caller; a mismatched re-request is
    a programming error and raises rather than silently forking the series.
    """
    with _global_lock:
        existing = _global_metrics.get(name)
        if existing is not None:
            if existing.label_names != tuple(label_names):
                raise ValueError(
                    f"global counter {name!r} exists with labels "
                    f"{existing.label_names}, requested {tuple(label_names)}"
                )
            return existing
        m = Counter(name, help_, label_names)
        _global_metrics[name] = m
        return m


def global_metrics() -> list[Counter]:
    """Every process-global metric, render-order stable (registration order)."""
    with _global_lock:
        return list(_global_metrics.values())


def reset_global_metrics() -> None:
    """Zero every global counter (keeps registrations) — test isolation."""
    for m in global_metrics():
        m.clear()


# The offline fault-tolerance plane, pre-registered so /metrics exposes the
# whole catalog from the first scrape.
artifact_corruptions = global_counter(
    ARTIFACT_CORRUPTIONS_TOTAL,
    "Artifacts quarantined after failed checksum verification or load, by artifact name.",
    ("artifact",),
)
checkpoint_fallbacks = global_counter(
    CHECKPOINT_FALLBACKS_TOTAL,
    "Unreadable checkpoint steps skipped while restoring the latest step.",
)
retry_attempts = global_counter(
    RETRY_ATTEMPTS_TOTAL,
    "Retries performed by utils.retry after a failed attempt, by call site.",
    ("site",),
)
faults_fired = global_counter(
    FAULTS_FIRED_TOTAL,
    "Injected faults fired by the utils.faults harness, by site.",
    ("site",),
)
aot_fingerprint_mismatches = global_counter(
    AOT_FINGERPRINT_MISMATCHES_TOTAL,
    "Serialized AOT executables discarded because their probe-output "
    "fingerprint did not match the exporting process's record.",
    ("name",),
)
# The data-quality firewall (PR 5): ingest violations, training divergence
# trips, and refused publishes all surface on the same /metrics page.
data_violations = global_counter(
    DATA_VIOLATIONS_TOTAL,
    "Raw star rows flagged by the ingest validator, by rule "
    "(datasets.validate; dropped under --data-policy repair, fatal under "
    "strict).",
    ("rule",),
)
watchdog_trips = global_counter(
    WATCHDOG_TRIPS_TOTAL,
    "Training divergence watchdog tripwires fired, by kind "
    "(nonfinite/norm/trajectory/lr).",
    ("kind",),
)
publish_rejected = global_counter(
    PUBLISH_REJECTED_TOTAL,
    "Artifacts refused publication or promotion, by gate "
    "(canary = pipeline quality gate, stamp = serving reload stamp gate).",
    ("gate",),
)
# The streaming plane (ROADMAP item 4): delta ingest routing, fold-in
# throughput, and the drift monitor's refit trigger.
stream_deltas = global_counter(
    STREAM_DELTAS_TOTAL,
    "Star deltas processed by the streaming ingest, by disposition "
    "(applied/tombstoned/folded_out = deferred to the next refit/"
    "dangling_tombstone/superseded = cross-op keep-last resolution/"
    "dropped = validation).",
    ("kind",),
)
foldin_users = global_counter(
    FOLDIN_USERS_TOTAL,
    "User rows re-solved on device by the streaming fold-in engine.",
)
drift_refits = global_counter(
    DRIFT_REFITS_TOTAL,
    "Full checkpointed refits triggered by the streaming drift monitor "
    "(quality decay past tolerance, or fold-out queue overflow), by "
    "outcome: completed, completed_degraded (the elastic driver survived "
    "a mid-refit device loss by remeshing), mesh_lost (out of rungs/"
    "budget), failed (any other stage failure).",
    ("outcome",),
)
stream_publishes = global_counter(
    STREAM_PUBLISHES_TOTAL,
    "Incremental stream generations published to the artifact store, by "
    "outcome.",
    ("outcome",),
)
# The capacity guardrail plane (PR 7): admission verdicts at every dispatch
# seam and degraded-mesh boots.
capacity_verdicts = global_counter(
    CAPACITY_VERDICTS_TOTAL,
    "Memory-budget admission verdicts (utils.capacity), by verdict "
    "(fit/degrade/refuse) and workload (als_fit/serve/foldin/...).",
    ("verdict", "workload"),
)
mesh_degraded = global_counter(
    MESH_DEGRADED_TOTAL,
    "Mesh constructions that remeshed to fewer devices than requested "
    "(device loss or an injected mesh.devices fault).",
)
# The elastic sharded plane (PR 12): mid-fit shard losses detected by the
# collective watchdog, and what the remesh-resume machinery did about them.
mesh_losses = global_counter(
    MESH_LOSSES_TOTAL,
    "Mid-fit mesh shard losses detected by the collective watchdog "
    "(DEADLINE_EXCEEDED / heartbeat failure / injected loss fault) during "
    "a sharded fit.",
)
elastic_resumes = global_counter(
    ELASTIC_RESUMES_TOTAL,
    "Elastic remesh-resume attempts after a mid-fit shard loss, by outcome "
    "(resumed = the fit continued on a smaller mesh rung; failed = no rung "
    "left or the resumed chunk failed -> MeshLost).",
    ("outcome",),
)
# The retrieval bank (ROADMAP item 5): fused candidate queries per source,
# bank-failure fallbacks to the host fan-out, and bank generation swaps.
retrieval_queries = global_counter(
    RETRIEVAL_QUERIES_TOTAL,
    "User rows answered by the device-resident retrieval bank, by source.",
    ("source",),
)
retrieval_fallbacks = global_counter(
    RETRIEVAL_FALLBACKS_TOTAL,
    "Bank-backed candidate stages that fell back to the host-side "
    "per-source fan-out, by reason (bank_timeout/bank_error).",
    ("reason",),
)
retrieval_promotions = global_counter(
    RETRIEVAL_PROMOTIONS_TOTAL,
    "Retrieval-bank generation swaps, by outcome (promoted/rejected).",
    ("outcome",),
)
# The lock-order sanitizer (graftlint's runtime complement): inversions,
# self-deadlocks, and unguarded shared-state accesses observed under
# ALBEDO_LOCKCHECK=1. Stays at zero in every green sanitize/soak run.
lockcheck_violations = global_counter(
    LOCKCHECK_VIOLATIONS_TOTAL,
    "Lock-order / unguarded-shared-state violations observed by the "
    "ALBEDO_LOCKCHECK sanitizer, by kind (order/self-deadlock/unguarded).",
    ("kind",),
)
# The batch-scoring plane (ROADMAP item 4): the score_all sweep's progress
# and its canary-gated publish refusals.
score_users = global_counter(
    SCORE_USERS_TOTAL,
    "User rows scored and spilled by the score_all batch sweep.",
)
score_shards = global_counter(
    SCORE_SHARDS_TOTAL,
    "User shards processed by the score_all sweep cursor, by outcome "
    "(scored = freshly scored + sealed; skipped = completed in a prior "
    "run and verified on resume; rescored = a prior spill failed its "
    "checksum and was scored again).",
    ("outcome",),
)
score_publish_rejected = global_counter(
    SCORE_PUBLISH_REJECTED_TOTAL,
    "score_all output manifests refused sealing, by gate (canary = the "
    "probe-slice NDCG@30 floor/regression gate).",
    ("gate",),
)
