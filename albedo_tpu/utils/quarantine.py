"""One quarantine convention for every layer: ``<name>.corrupt-<n>``.

The artifact store moves a bad artifact aside before regenerating it, the
hot-swap manager moves a rejected candidate aside before the watcher can
retry it, and the ingest validator moves bad *rows* aside before the star
matrix is built. All three keep the evidence next to the original under a
numbered marker suffix so operators can triage (and tests can assert) what
was refused — this module owns the naming and the rename so the convention
cannot drift between layers.

Markers:

- ``.corrupt-<n>``     whole files/directories that failed integrity or a
                       validation gate (``quarantine_rename``);
- ``.quarantine-<n>``  row-level sidecars the data validator writes — a
                       reviewable CSV of the dropped rows, tagged per rule
                       (``datasets.validate``).

Sidecar files (the ``.sha256`` manifest, the ``.meta.json`` quality stamp)
travel WITH the quarantined artifact: a stale sidecar left behind under the
original name would vouch for whatever regenerates into that slot.
"""

from __future__ import annotations

import itertools
import logging
from pathlib import Path

log = logging.getLogger(__name__)

CORRUPT_MARKER = ".corrupt-"
ROWS_MARKER = ".quarantine-"

# Sidecars that must follow a quarantined artifact to its new name.
SIDECAR_SUFFIXES = (".sha256", ".meta.json")


def next_marked_path(path: Path, marker: str = CORRUPT_MARKER, suffix: str = "") -> Path:
    """First free ``<name><marker><n><suffix>`` next to ``path`` (1-based)."""
    path = Path(path)
    for n in itertools.count(1):
        dest = path.with_name(f"{path.name}{marker}{n}{suffix}")
        if not dest.exists():
            return dest
    raise AssertionError("unreachable")  # pragma: no cover


def quarantine_rename(
    path: Path,
    reason: str = "corrupt",
    sidecar_suffixes: tuple[str, ...] = SIDECAR_SUFFIXES,
) -> Path:
    """Move ``path`` (and its sidecars) aside to ``<name>.corrupt-<n>``.

    The evidence survives for debugging while the slot regenerates; sidecars
    are renamed alongside so no stale manifest/stamp vouches for the next
    occupant of the original name.
    """
    path = Path(path)
    dest = next_marked_path(path, CORRUPT_MARKER)
    path.rename(dest)
    for suf in sidecar_suffixes:
        sidecar = path.with_name(path.name + suf)
        if sidecar.exists():
            sidecar.rename(dest.with_name(dest.name + suf))
    log.warning("quarantined %s -> %s (%s)", path.name, dest.name, reason)
    return dest
