"""Memory-budget admission: price a workload BEFORE dispatching it.

Until now nothing in the system modeled capacity: the first matrix whose
bucket slabs out-size a chip's HBM was a raw ``RESOURCE_EXHAUSTED`` crash
that ``utils.retry`` futilely re-OOMed and ``run_pipeline`` journaled as a
generic stage failure. ALX (arxiv 2112.02194) makes sharded-beyond-one-chip
factors the next scale step and iALS++ (arxiv 2110.14044) pushes ranks to
128/256 — both multiply memory pressure, so exhaustion must become a
*handled* failure mode before those land.

The planner is a **static cost model from shapes and dtypes**: every
dispatch seam knows its slab shapes before any byte moves (bucket plans,
factor-table dims, ladder rungs), so pricing is host arithmetic — no probe
allocation, no device round-trip. Costs are deliberately coarse (they ignore
allocator fragmentation and XLA scratch), which is why admission compares
against a *headroom-scaled* budget and why, where an AOT handle exists, the
static estimate is cross-checked against the compiler's own
``compiled.memory_analysis()`` (:func:`compiled_memory_bytes`).

One admission call returns a verdict:

``fit``      the priced bytes fit the budget: dispatch the resident path.
``degrade``  over budget but the caller declared a degraded mode (chunked
             host-streamed ALS groups, a lower fold-in ladder rung): take it.
``refuse``   over budget with no degraded mode (a hot-swap candidate that
             cannot sit alongside the incumbent): a recorded rejection,
             never a crash.

Verdicts are counted in ``albedo_capacity_verdicts_total{verdict=,workload=}``.
The ``capacity.admit`` fault site fires inside every admission; arming the
new ``oom`` kind forces the over-budget path (the injected
``RESOURCE_EXHAUSTED`` is caught HERE and converted to degrade/refuse), so
chaos drills exercise the real degraded machinery without a 16 GB
allocation.

Budget detection order (per device):

1. ``ALBEDO_DEVICE_MEM_BYTES`` — explicit override, the CPU-CI knob and the
   chaos-drill pressure valve (suffixes k/m/g accepted).
2. ``jax.local_devices()[0].memory_stats()["bytes_limit"]`` — what the TPU
   runtime actually reports.
3. ``/proc/meminfo`` MemTotal (CPU backends: host RAM is device RAM).
4. 16 GiB (the v5e figure) when nothing above answers.

``ALBEDO_MEM_HEADROOM`` (default 0.85) scales the detected total into the
admission budget; ``ALBEDO_CAPACITY=off`` disables admission entirely
(everything verdicts ``fit`` — the escape hatch if the cost model ever
refuses a workload that would in fact fit).
"""

from __future__ import annotations

import dataclasses
import logging
import os

import numpy as np

from albedo_tpu.utils import events, faults
from albedo_tpu.utils.retry import is_resource_exhausted

log = logging.getLogger(__name__)

ADMIT_FAULT = faults.site("capacity.admit")

_ENV_BYTES = "ALBEDO_DEVICE_MEM_BYTES"
_ENV_HEADROOM = "ALBEDO_MEM_HEADROOM"
_ENV_TOGGLE = "ALBEDO_CAPACITY"
_DEFAULT_HEADROOM = 0.85
_FALLBACK_BYTES = 16 << 30  # v5e per-chip HBM; the "no signal at all" anchor


class CapacityExceeded(MemoryError):
    """An admission verdict of ``refuse`` where the caller cannot proceed at
    all — carries the verdict so journals/reports can record the pricing.

    Subclasses :class:`MemoryError` ON PURPOSE: ``utils.retry.
    is_resource_exhausted`` classifies MemoryError as permanent, so a
    deterministic capacity refusal fails FAST through the pipeline's stage
    retries instead of re-pricing the identical refusal through the whole
    backoff budget — the same fail-fast contract a real device OOM gets."""

    def __init__(self, verdict: "AdmissionVerdict"):
        super().__init__(
            f"workload {verdict.workload!r} needs ~{verdict.required_bytes:,} "
            f"bytes against a {verdict.budget_bytes:,}-byte budget "
            f"(refused: capacity)"
        )
        self.verdict = verdict


def _parse_bytes(raw: str) -> int:
    raw = raw.strip().lower()
    mult = 1
    if raw and raw[-1] in "kmg":
        mult = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30}[raw[-1]]
        raw = raw[:-1]
    return int(float(raw) * mult)


def enabled() -> bool:
    return os.environ.get(_ENV_TOGGLE, "on").lower() not in ("off", "0", "false")


def device_memory_bytes() -> int:
    """Detected per-device memory (bytes). See module doc for the order."""
    raw = os.environ.get(_ENV_BYTES)
    if raw:
        return _parse_bytes(raw)
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:  # noqa: BLE001 — detection must never be the crash
        pass
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return _FALLBACK_BYTES


def headroom() -> float:
    try:
        h = float(os.environ.get(_ENV_HEADROOM, _DEFAULT_HEADROOM))
    except ValueError:
        h = _DEFAULT_HEADROOM
    return min(1.0, max(0.05, h))


def budget_bytes() -> int:
    """The admission budget: detected per-device memory x headroom."""
    return int(device_memory_bytes() * headroom())


@dataclasses.dataclass(frozen=True)
class CapacityPlan:
    """A priced workload: named byte items summing to ``required_bytes``.

    ``items`` keeps the per-component split (factor tables, slabs, transient
    gather blocks) so a ``refused: capacity`` journal entry tells the
    operator WHAT is too big, not just that something is.
    """

    workload: str
    items: dict[str, int]

    @property
    def required_bytes(self) -> int:
        return int(sum(self.items.values()))

    def to_dict(self) -> dict:
        return {
            "workload": self.workload,
            "required_bytes": self.required_bytes,
            "items": {k: int(v) for k, v in self.items.items()},
        }


@dataclasses.dataclass(frozen=True)
class AdmissionVerdict:
    """The outcome of one admission: verdict + the numbers behind it.

    ``chosen`` names the workload of the plan the admission actually
    selected — for plain :func:`admit` it equals ``workload``; for
    :func:`admit_ladder` it is the first rung that fit the budget.
    """

    workload: str
    verdict: str  # "fit" | "degrade" | "refuse"
    required_bytes: int
    budget_bytes: int
    detail: str = ""
    plan: CapacityPlan | None = None
    chosen: str = ""

    @property
    def fits(self) -> bool:
        return self.verdict == "fit"

    def to_dict(self) -> dict:
        out = {
            "workload": self.workload,
            "verdict": self.verdict,
            "required_bytes": int(self.required_bytes),
            "budget_bytes": int(self.budget_bytes),
            "detail": self.detail,
        }
        if self.chosen:
            out["chosen"] = self.chosen
        if self.plan is not None:
            out["items"] = {k: int(v) for k, v in self.plan.items.items()}
        return out


def admit(
    plan: CapacityPlan,
    *,
    degradable: bool = False,
    budget: int | None = None,
    fallback_plan: CapacityPlan | None = None,
) -> AdmissionVerdict:
    """Price ``plan`` against the budget and return the verdict.

    ``degradable`` declares that the caller HAS a cheaper mode to fall back
    to — over-budget then verdicts ``degrade`` instead of ``refuse``. When
    the fallback itself has a priceable plan, pass it as ``fallback_plan``:
    a fallback that ALSO busts the budget turns the verdict into ``refuse``
    — one admission, one counted verdict, never a degrade that could not
    actually run. The ``capacity.admit`` fault site fires on every
    admission; an injected ``oom`` is caught here and converted to the
    over-budget verdict (chaos drives the real degrade path), while other
    injected kinds propagate like any fault-site error.
    """
    budget = budget_bytes() if budget is None else int(budget)
    required = plan.required_bytes
    forced = ""
    if enabled():
        try:
            ADMIT_FAULT.hit()
        except Exception as e:  # noqa: BLE001 — only OOM converts; rest propagate
            if not is_resource_exhausted(e):
                raise
            forced = f" (forced over-budget by injected fault: {e})"
            required = max(required, budget + 1)
    over = enabled() and required > budget
    # An injected oom must land on the DEGRADE path (that is the drill);
    # only a genuinely over-budget fallback refuses.
    fallback_fits = fallback_plan is None or forced or (
        fallback_plan.required_bytes <= budget
    )
    if not over:
        verdict = "fit"
        detail = f"{required:,} bytes within {budget:,}-byte budget"
    elif degradable and fallback_fits:
        verdict = "degrade"
        detail = (
            f"{required:,} bytes over the {budget:,}-byte budget; "
            f"taking the degraded path{forced}"
        )
    elif degradable:
        verdict = "refuse"
        detail = (
            f"{required:,} bytes over the {budget:,}-byte budget and the "
            f"degraded plan needs {fallback_plan.required_bytes:,} bytes "
            f"itself{forced}"
        )
    else:
        verdict = "refuse"
        detail = (
            f"{required:,} bytes over the {budget:,}-byte budget and no "
            f"degraded mode{forced}"
        )
    out = AdmissionVerdict(
        workload=plan.workload, verdict=verdict, required_bytes=required,
        budget_bytes=budget, detail=detail, plan=plan,
    )
    events.capacity_verdicts.inc(verdict=verdict, workload=plan.workload)
    if verdict != "fit":
        log.warning("capacity admission [%s]: %s", plan.workload, detail)
    return out


def admit_ladder(
    plans: list[CapacityPlan],
    *,
    budget: int | None = None,
) -> AdmissionVerdict:
    """Admission over an ordered degradation ladder of priced plans.

    ``plans[0]`` is the preferred mode; each later plan is a cheaper
    degraded mode. The verdict is ``fit`` when the first plan fits,
    ``degrade`` when a later rung is the first that fits (``chosen`` names
    it), and ``refuse`` when no rung fits. Like :func:`admit`, one call =
    one counted verdict, and the ``capacity.admit`` fault site fires once —
    an injected ``oom`` forces the preferred rung over budget so the drill
    lands on the first degraded rung (never a crash).
    """
    if not plans:
        raise ValueError("admit_ladder needs at least one plan")
    budget = budget_bytes() if budget is None else int(budget)
    required = [p.required_bytes for p in plans]
    forced = ""
    if enabled():
        try:
            ADMIT_FAULT.hit()
        except Exception as e:  # noqa: BLE001 — only OOM converts; rest propagate
            if not is_resource_exhausted(e):
                raise
            forced = f" (forced over-budget by injected fault: {e})"
            required[0] = max(required[0], budget + 1)
    if not enabled():
        chosen = 0
    else:
        chosen = next(
            (i for i, r in enumerate(required) if r <= budget or (forced and i == 1)),
            len(plans),
        )
    if chosen == 0:
        verdict = "fit"
        detail = (
            f"{required[0]:,} bytes within {budget:,}-byte budget"
        )
    elif chosen < len(plans):
        verdict = "degrade"
        detail = (
            f"{required[0]:,} bytes over the {budget:,}-byte budget; taking "
            f"degraded rung {chosen} ({plans[chosen].workload}: "
            f"{required[chosen]:,} bytes){forced}"
        )
    else:
        verdict = "refuse"
        detail = (
            f"every rung over the {budget:,}-byte budget "
            f"({', '.join(f'{p.workload}={r:,}' for p, r in zip(plans, required))})"
            f"{forced}"
        )
    idx = min(chosen, len(plans) - 1)
    out = AdmissionVerdict(
        workload=plans[0].workload, verdict=verdict,
        required_bytes=required[0], budget_bytes=budget, detail=detail,
        plan=plans[idx], chosen=plans[idx].workload if verdict != "refuse" else "",
    )
    events.capacity_verdicts.inc(verdict=verdict, workload=plans[0].workload)
    if verdict != "fit":
        log.warning("capacity admission [%s]: %s", plans[0].workload, detail)
    return out


# --- static cost models -------------------------------------------------------
# All coarse, all conservative-ish, all pure host arithmetic. f32 = 4 bytes;
# the gather dtype may halve the streamed block. Each model prices what is
# RESIDENT for the workload's lifetime plus the single largest transient the
# program materializes at once.


def _dtype_bytes(gather_dtype: str | None) -> int:
    return 2 if gather_dtype == "bfloat16" else 4


def plan_fit(
    bucket_shapes_user: list[tuple[int, int]],
    bucket_shapes_item: list[tuple[int, int]],
    n_users: int,
    n_items: int,
    rank: int,
    gather_dtype: str | None = None,
    n_devices: int = 1,
) -> CapacityPlan:
    """Price the device-resident fused ALS fit, PER DEVICE.

    Resident: both factor tables, every uploaded bucket slab (row_ids + idx
    + val + mask for BOTH sides — the whole point of the resident path is
    that ratings stay on device across sweeps), and the landing pools
    (``concat(solved_blocks..., target)`` materializes ``n_slots + n_target``
    rank-vectors per half-sweep). Transient: the largest bucket's gathered
    ``(B, L, rank)`` block plus its ``(B, rank, rank)`` Gramian correction.

    ``n_devices > 1`` prices the GSPMD mesh-resident path: factor tables
    (and the landing pool's target segment) stay REPLICATED per device,
    while slabs, solved-slot pools, and transients split over the batch
    axis — the replicated tables are exactly why this path stops scaling
    and the fully sharded plan (:func:`plan_fit_sharded`) takes over.
    """
    gb = _dtype_bytes(gather_dtype)
    n = max(1, int(n_devices))
    tables = (n_users + n_items) * rank * 4
    slabs = 0
    slots_u = slots_i = 0
    transient = 0
    for shapes, side in ((bucket_shapes_user, "u"), (bucket_shapes_item, "i")):
        for b, ln in shapes:
            slabs += b * 4 + b * ln * (4 + 4 + 1)
            if side == "u":
                slots_u += b
            else:
                slots_i += b
            transient = max(transient, b * ln * (rank * gb + gb) + b * rank * rank * 4)
    landing = ((slots_u + slots_i) // n + n_users + n_items) * rank * 4
    return CapacityPlan(
        workload="als_fit",
        items={
            "factor_tables": tables,
            "bucket_slabs": slabs // n,
            "landing_pools": landing,
            "transient_gather": transient // n,
        },
    )


def _shard_pad(n: int, n_devices: int) -> int:
    return -(-n // n_devices) * n_devices


def plan_fit_sharded(
    bucket_shapes_user: list[tuple[int, int]],
    bucket_shapes_item: list[tuple[int, int]],
    n_users: int,
    n_items: int,
    rank: int,
    n_devices: int,
    gather_dtype: str | None = None,
    streamed: bool = False,
    mode: str = "allgather",
    solver: str = "cholesky",
    pipelined: bool = True,
) -> CapacityPlan:
    """Price the fully sharded ALS fit (ALX layout), PER DEVICE.

    Resident: 1/n of BOTH row-sharded factor tables, plus (non-streamed)
    1/n of every bucket slab. Streamed mode keeps only the in-flight bucket
    slab shards on device — the star matrix is never device-resident whole:
    under the default PIPELINED dataflow the double-buffered prefetch holds
    **two** bucket slabs at once (the one being solved plus the one the
    background uploader just landed), priced as the worst same-side pair of
    slab shards — both in-flight buckets always belong to one half-sweep;
    ``pipelined=False`` is the synchronous dataflow's single slab — which is
    why the admission ladder can pick unpipelined-streamed as a cheaper rung
    below pipelined-streamed. Transient, per bucket: the assembled source
    factors — the FULL (padded) table under ``mode="allgather"``, a
    double-buffered 1/n shard ring slot under ``mode="ring"`` — plus the
    local gathered block, its Gramian correction, and the all-gathered
    solved rows of the bucket. The CG solver additionally all-gathers the
    target table for its warm-start rows, so its transient prices BOTH
    tables under all-gather.
    """
    gb = _dtype_bytes(gather_dtype)
    n = max(1, int(n_devices))
    u_pad, i_pad = _shard_pad(n_users, n), _shard_pad(n_items, n)
    tables = (u_pad + i_pad) * rank * 4 // n
    slabs = 0
    worst_slab = 0
    worst_pair = 0
    transient = 0
    for shapes, src_rows, tgt_rows in (
        (bucket_shapes_user, i_pad, u_pad),  # user solves gather item factors
        (bucket_shapes_item, u_pad, i_pad),
    ):
        if mode == "ring":
            # Two ring slots in flight (the held shard + the arriving one).
            assembled = 2 * (src_rows // n) * rank * gb
        else:
            assembled = src_rows * rank * gb
            if solver == "cg":
                assembled += tgt_rows * rank * 4  # warm-start gather
        side_worst = side_second = 0
        for b, ln in shapes:
            slab = b * 4 + b * ln * (4 + 4 + 1)
            slabs += slab // n
            worst_slab = max(worst_slab, slab // n)
            if slab // n >= side_worst:
                side_worst, side_second = slab // n, side_worst
            elif slab // n > side_second:
                side_second = slab // n
            local = (
                (b // n) * ln * (rank * gb + gb)
                + (b // n) * rank * rank * 4
                + b * rank * 4  # all-gathered solved rows land on every device
            )
            transient = max(transient, assembled + local)
        # The double-buffer only ever holds buckets of ONE half-sweep, so
        # the pipelined in-flight peak is the worst SAME-SIDE pair (a
        # one-bucket side never double-buffers itself).
        worst_pair = max(worst_pair, side_worst + side_second)
    items = {
        "factor_table_shards": tables,
        "transient_assembly": transient,
    }
    workload = "als_fit_sharded"
    if streamed and pipelined:
        # Double-buffered prefetch: the bucket being solved + the one the
        # background uploader holds — the two largest slabs of one side.
        items["streamed_slabs_in_flight"] = worst_pair
        workload = "als_fit_sharded_streamed"
    elif streamed:
        items["streamed_slab_in_flight"] = worst_slab
        workload = "als_fit_sharded_streamed_sync"
    else:
        items["bucket_slab_shards"] = slabs
    return CapacityPlan(workload=workload, items=items)


def plan_fit_chunked(
    bucket_shapes_user: list[tuple[int, int]],
    bucket_shapes_item: list[tuple[int, int]],
    n_users: int,
    n_items: int,
    rank: int,
    gather_dtype: str | None = None,
) -> CapacityPlan:
    """Price the chunked host-streamed fallback: only the factor tables stay
    resident; one bucket's slab + gather block is in flight at a time."""
    gb = _dtype_bytes(gather_dtype)
    tables = (n_users + n_items) * rank * 4
    worst = 0
    for shapes in (bucket_shapes_user, bucket_shapes_item):
        for b, ln in shapes:
            worst = max(
                worst,
                b * 4 + b * ln * (4 + 4 + 1)
                + b * ln * (rank * gb + gb) + b * rank * rank * 4
                + b * rank * 4,
            )
    return CapacityPlan(
        workload="als_fit_chunked",
        items={"factor_tables": tables, "worst_bucket_in_flight": worst},
    )


def plan_serve(
    n_users: int,
    n_items: int,
    rank: int,
    excl_entries: int = 0,
    generations: int = 1,
    n_devices: int = 1,
) -> CapacityPlan:
    """Price ``generations`` device-resident serving generations, PER
    DEVICE.

    A generation pins both factor tables (``ALSModel.device_factors``) plus
    the -1-padded exclusion table (int32 per entry). During a hot swap TWO
    generations are resident — the incumbent never stops until the candidate
    passes its post-swap checks — which is exactly the pressure the reload
    capacity gate admits against.

    ``n_devices > 1`` prices the mesh-resident serving layout (factor
    tables and the exclusion table row-sharded over the mesh, the PR 8
    layout): each device holds 1/n. This is what makes degraded-mesh
    serving admission honest — after the ladder halves the mesh, the SAME
    artifact's per-device price doubles, and the reload gate must re-judge
    it against the smaller rung rather than the boot-time one.
    """
    n = max(1, int(n_devices))
    per_gen = (_shard_pad(n_users, n) + _shard_pad(n_items, n)) * rank * 4 // n
    return CapacityPlan(
        workload="serve",
        items={
            "factor_tables": per_gen * max(1, generations),
            "exclusion_table": int(excl_entries) * 4 // n,
        },
    )


def plan_foldin(
    bucket: int,
    length: int,
    rank: int,
    n_items: int,
    n_devices: int = 1,
    mode: str = "allgather",
) -> CapacityPlan:
    """Price one fold-in ladder rung, PER DEVICE: the frozen item side
    (factors + Gramian, resident across every batch) plus the rung's padded
    slab and its gathered block.

    ``n_devices > 1`` prices the mesh-resident fold-in (parallel/foldin.py):
    the frozen item table is row-sharded (each device holds 1/n of the
    padded table plus a replicated Gramian), the user slab is routed so each
    shard solves ``bucket // n`` of its own users, and ``mode`` picks the
    source-assembly transient — ``allgather`` materialises the whole padded
    item table per batch, ``ring`` only ever holds two 1/n shards (the
    resident one plus the ppermute'd one in flight). This is the same
    allgather-vs-ring footprint split ``plan_fit_sharded`` prices for
    training, and it is what lets ``admit_ladder`` honestly degrade a
    fold-in batch from allgather to ring when the gather transient is what
    busts the budget.
    """
    n = max(1, int(n_devices))
    i_pad = _shard_pad(n_items, n)
    item_side = i_pad * rank * 4 // n + rank * rank * 4
    slab = bucket * length * (4 + 4 + 1) // n
    b_per = max(1, bucket // n)
    gathered = b_per * length * rank * 4 + b_per * rank * rank * 4
    items = {
        "frozen_item_side": item_side,
        "rung_slab": slab,
        "rung_gather": gathered,
    }
    if n == 1:
        workload = "foldin"
    elif mode == "ring":
        workload = "foldin_sharded_ring"
        # Two source shards in flight: the resident one and the ppermute'd
        # visitor (double-buffered, same as plan_fit_sharded's ring price).
        items["transient_assembly"] = 2 * (i_pad // n) * rank * 4
    else:
        workload = "foldin_sharded"
        items["transient_assembly"] = i_pad * rank * 4
    return CapacityPlan(workload=workload, items=items)


def plan_retrieval(
    tables: "list[tuple[int, int]]",
    excl_entries: int = 0,
    generations: int = 1,
    max_batch: int = 64,
    item_block: int = 4096,
    k: int = 64,
    n_devices: int = 1,
) -> CapacityPlan:
    """Price ``generations`` resident retrieval-bank generations, PER
    DEVICE.

    ``tables``: every table the bank pins — each source's (rows, dim)
    embedding table plus its user-row query table when it has one. During a
    bank hot-swap TWO generations are resident (the incumbent keeps serving
    until the candidate's gates pass), which is what ``generations=2``
    admits against. Transient: one query batch's gathered rows + the
    blocked-MIPS working set (a (B, item_block) score block and the running
    (B, k) top-k) for the widest table.

    ``n_devices > 1`` prices the mesh layout: source tables row-sharded
    over the mesh (``parallel/topk.py`` serves per-shard top-k), so each
    device holds 1/n of the resident tables while the per-batch transient
    stays whole. A bank that fit at 8 shards can genuinely refuse at 4 —
    the degraded-ladder rung doubles each device's share — and that
    refusal stays a recorded non-quarantine rejection.
    """
    n = max(1, int(n_devices))
    resident = sum(_shard_pad(int(rows), n) * int(d) * 4 // n for rows, d in tables)
    max_dim = max((int(d) for _, d in tables), default=0)
    b = max(1, int(max_batch))
    transient = b * max_dim * 4 + b * (int(item_block) + int(k)) * 4
    return CapacityPlan(
        workload="retrieval",
        items={
            "embedding_tables": resident * max(1, int(generations)),
            "exclusion_table": int(excl_entries) * 4,
            "transient_query": transient,
        },
    )


def plan_score(
    tables: "list[tuple[int, int]]",
    shard_users: int,
    k: int = 30,
    max_batch: int = 64,
    item_block: int = 4096,
    n_devices: int = 1,
    streamed: bool = False,
) -> CapacityPlan:
    """Price one batch-scoring sweep configuration, PER DEVICE.

    The ``score_all`` job streams user shards through the retrieval bank's
    blocked MIPS and the LR re-rank; its admission ladder has two rungs
    built from this model:

    - **resident** (``streamed=False``): the whole user shard is one query
      batch — the bank sees ``B = shard_users`` and the blocked-MIPS
      working set scales with it. Fastest when it fits.
    - **streamed** (``streamed=True``): the bank's internal ``max_batch``
      splitting bounds the in-flight batch at ``B = max_batch``; only the
      per-shard top-k landing buffer still scales with the shard. The
      cheap rung for out-of-core catalogs.

    ``tables`` lists every (rows, dim) table the bank pins (source item
    tables + their user query tables), row-sharded over ``n_devices``
    like :func:`plan_retrieval` — a batch job holds ONE generation (no
    hot-swap pressure). Refusal of BOTH rungs is the "before any byte
    moves" contract: :class:`CapacityExceeded` fires at admission, before
    the bank is built or a single shard is read.
    """
    n = max(1, int(n_devices))
    resident = sum(_shard_pad(int(rows), n) * int(d) * 4 // n for rows, d in tables)
    max_dim = max((int(d) for _, d in tables), default=0)
    b = max(1, int(max_batch) if streamed else int(shard_users))
    transient = b * max_dim * 4 + b * (int(item_block) + int(k)) * 4
    # Per-shard top-k landing buffer (scores f32 + rows i32), resident for
    # the shard's lifetime on whichever rung — it is what the spill writes.
    landing = max(1, int(shard_users)) * int(k) * (4 + 4)
    return CapacityPlan(
        workload="score_streamed" if streamed else "score",
        items={
            "bank_tables": resident,
            "transient_query": transient,
            "topk_landing": landing,
        },
    )


def max_foldin_entries(
    rank: int, n_items: int, budget: int | None = None, length: int = 1
) -> int:
    """The largest ``bucket * length`` product whose fold-in rung fits the
    budget — the cap on the pow2 shape ladder, for rungs of the given
    ``length``. Returns at least 1 (a single short row must always be
    dispatchable; if even that OOMs for real, the solve itself will say so).

    Per-entry bytes must cover everything ``plan_foldin`` prices, or a rung
    shrunk to this cap would still admit over-budget: slab (idx+val+mask)
    + gathered rank-vector + the per-SLOT ``(B, rank, rank)`` Gramian
    correction, which amortizes as ``rank^2*4 / length`` per entry. The
    default ``length=1`` is the conservative floor — a caller that knows
    its rung's padded length passes it and gets a proportionally larger
    cap; one that doesn't never under-prices a batch of 1-star rows."""
    budget = budget_bytes() if budget is None else int(budget)
    item_side = n_items * rank * 4 + rank * rank * 4
    per_entry = (4 + 4 + 1) + rank * 4 + (rank * rank * 4) // max(1, int(length))
    spare = budget - item_side
    if spare <= per_entry:
        return 1
    return max(1, int(spare // per_entry))


# --- compiler cross-check -----------------------------------------------------


def compiled_memory_bytes(compiled) -> dict | None:
    """Best-effort read of an AOT executable's own memory analysis.

    Returns ``{argument, output, temp, generated_code, total}`` bytes or
    ``None`` when the backend doesn't expose ``memory_analysis()`` (older
    jaxlib, some CPU builds). Callers use it to cross-check the static model
    — a static estimate wildly below the compiler's own number means the
    model went stale, and the larger figure should drive admission."""
    try:
        ma = compiled.memory_analysis()
        if ma is None:
            return None
        out = {
            "argument": int(getattr(ma, "argument_size_in_bytes", 0)),
            "output": int(getattr(ma, "output_size_in_bytes", 0)),
            "temp": int(getattr(ma, "temp_size_in_bytes", 0)),
            "generated_code": int(getattr(ma, "generated_code_size_in_bytes", 0)),
        }
        alias = int(getattr(ma, "alias_size_in_bytes", 0))
        out["total"] = max(0, sum(out.values()) - alias)
        return out
    except Exception:  # noqa: BLE001 — advisory only
        return None


def cross_check(plan: CapacityPlan, compiled) -> dict | None:
    """Compare the static plan against the compiler's memory analysis.

    Advisory: returns the comparison record (logged when the static model
    underestimates by >2x) or None when no analysis is available."""
    analysis = compiled_memory_bytes(compiled)
    if analysis is None or not analysis.get("total"):
        return None
    static = plan.required_bytes
    ratio = analysis["total"] / max(1, static)
    record = {
        "static_bytes": static,
        "compiled_bytes": analysis["total"],
        "ratio": round(ratio, 3),
        "analysis": analysis,
    }
    # Warn only on MATERIAL underestimates: tiny programs carry fixed XLA
    # temp overheads that dwarf their slabs (ratio noise at KB scale), and
    # a model off by a few hundred KB cannot mis-admit anything.
    if ratio > 2.0 and analysis["total"] - static > 64 << 20:
        log.warning(
            "capacity model underestimates %s: static %s bytes vs compiler "
            "%s bytes (%.1fx) — admission should trust the larger figure",
            plan.workload, f"{static:,}", f"{analysis['total']:,}", ratio,
        )
    return record


def bucket_plan_shapes(indptr: np.ndarray, **layout_kwargs) -> list[tuple[int, int]]:
    """Shapes ``(B, L)`` the bucket planner would allocate for this CSR/CSC
    side — the pricing input, computed WITHOUT filling any slab."""
    from albedo_tpu.datasets.ragged import plan_buckets

    return [p.shape for p in plan_buckets(indptr, **layout_kwargs)]


def counts_indptr(row_ids: np.ndarray, n_rows: int) -> np.ndarray:
    """An indptr from bare row ids — all the planner needs. Pricing must
    not pay the O(nnz log nnz) argsort a full ``matrix.csr()``/``csc()``
    view costs just to read row lengths (the cold path sorts them again
    for real minutes later)."""
    counts = np.bincount(np.asarray(row_ids), minlength=n_rows)
    indptr = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(counts, out=indptr[1:])
    return indptr
