"""Schema assertions for host tables.

Reference parity: ``utils/SchemaUtils.scala:6-18`` (nullability-insensitive
schema equality + column-type assertion) — the runtime contract checks the
reference uses in place of tests (SURVEY.md §4).
"""

from __future__ import annotations

import pandas as pd


def equals_ignore_nullability(a: pd.DataFrame, b: pd.DataFrame) -> bool:
    """Same column names and kinds (int/float/bool/object), ignoring the
    nullable-vs-plain dtype distinction."""
    if list(a.columns) != list(b.columns):
        return False
    for col in a.columns:
        if a[col].dtype.kind != b[col].dtype.kind:
            return False
    return True


def assert_columns(df: pd.DataFrame, expected: dict[str, str]) -> None:
    """Require columns to exist with the given dtype kind
    (``SchemaUtils.checkColumnType`` analogue)."""
    for col, kind in expected.items():
        if col not in df.columns:
            raise ValueError(f"missing column {col!r}")
        actual = df[col].dtype.kind
        if actual != kind:
            raise ValueError(
                f"column {col!r} must have dtype kind {kind!r} but was {actual!r}"
            )
