"""Utility layer: profiling/timing harness and schema assertions."""


def pow2_at_least(n: int) -> int:
    """Smallest power of two >= n (and >= 1) — the shape-ladder rounding
    shared by the feature assembler's bag pads and the serving batcher's
    user-bucket/k quantization."""
    return 1 << max(0, int(n - 1).bit_length())


from albedo_tpu.utils.checkpoint import (  # noqa: E402
    Preempted,
    PreemptionHandler,
    StepCheckpointer,
    checkpointed_als_fit,
    restore_pytree,
    save_pytree,
)
from albedo_tpu.utils.faults import FaultInjected
from albedo_tpu.utils.profiling import Timer, profiler_trace, timed, timing
from albedo_tpu.utils.retry import (
    RetriesExhausted,
    RetryAfter,
    RetryPolicy,
    retry_call,
)
from albedo_tpu.utils.schema import assert_columns, equals_ignore_nullability

__all__ = [
    "FaultInjected",
    "Preempted",
    "PreemptionHandler",
    "RetriesExhausted",
    "RetryAfter",
    "RetryPolicy",
    "StepCheckpointer",
    "Timer",
    "pow2_at_least",
    "assert_columns",
    "checkpointed_als_fit",
    "equals_ignore_nullability",
    "profiler_trace",
    "restore_pytree",
    "retry_call",
    "save_pytree",
    "timed",
    "timing",
]
