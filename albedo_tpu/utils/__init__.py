"""Utility layer: profiling/timing harness and schema assertions."""

from albedo_tpu.utils.profiling import Timer, profiler_trace, timed, timing
from albedo_tpu.utils.schema import assert_columns, equals_ignore_nullability

__all__ = [
    "Timer",
    "assert_columns",
    "equals_ignore_nullability",
    "profiler_trace",
    "timed",
    "timing",
]
