"""Utility layer: profiling/timing harness and schema assertions."""

from albedo_tpu.utils.checkpoint import (
    StepCheckpointer,
    checkpointed_als_fit,
    restore_pytree,
    save_pytree,
)
from albedo_tpu.utils.profiling import Timer, profiler_trace, timed, timing
from albedo_tpu.utils.schema import assert_columns, equals_ignore_nullability

__all__ = [
    "StepCheckpointer",
    "Timer",
    "assert_columns",
    "checkpointed_als_fit",
    "equals_ignore_nullability",
    "profiler_trace",
    "restore_pytree",
    "save_pytree",
    "timed",
    "timing",
]
