"""Persistent XLA-executable cache across processes.

The reference's JVM warms its code cache within one long-lived Spark session;
a JAX job pays XLA compilation again in every fresh process (~47 s for the
ranker's L-BFGS executable on the tunneled backend, r5 measurement). JAX's
persistent compilation cache serializes compiled executables to disk keyed by
HLO fingerprint, so repeat runs (the ``loadOrCreate`` philosophy,
``utils/ModelUtils.scala:7-21``, applied to executables) skip the compile:
measured working on the axon remote-compile backend (second process ~2x
faster on a toy program; the full LR executable drops from ~47 s to ~0).

Disable with ``ALBEDO_JAX_CACHE=0``; the default directory lives beside the
artifact store (``ALBEDO_DATA_DIR``), so ``drop_data``-style cleanup removes
both.

Preemption hardening: jax 0.4.x's on-disk cache writes entries with a bare
``write_bytes`` — a process killed mid-write (pod preemption, the fault
harness's ``kill`` action) leaves a TRUNCATED serialized executable that a
later process happily deserializes. :func:`harden_jax_cache_writes` patches
the write to the tmp + ``os.replace`` protocol every other artifact in this
repo already uses, closing the torn-write window; stale tmp files from a
killed writer are swept when the cache is enabled. The patch is best-effort
and version-guarded: unrecognized jax internals leave jax untouched.
"""

from __future__ import annotations

import os
from pathlib import Path

_ENABLED = False
_PATCHED = False


def harden_jax_cache_writes() -> bool:
    """Make jax's persistent-compilation-cache writes atomic (idempotent).

    Returns True when the patch is active. Call sites are anywhere jax is
    already imported and about to compile (``utils.aot``, the CLI after
    ``init_distributed``); before jax is imported there is nothing to patch.
    """
    global _PATCHED
    if _PATCHED:
        return True
    try:
        from jax._src import lru_cache as _lc

        cls = _lc.LRUCache
        orig_put = cls.put
        cache_suffix = _lc._CACHE_SUFFIX
        atime_suffix = _lc._ATIME_SUFFIX
    except Exception:  # noqa: BLE001 — unknown jax internals: leave stock
        return False
    import time as _time

    def _atomic_put(self, key: str, val: bytes) -> None:
        if self.eviction_enabled and len(val) > self.max_size:
            orig_put(self, key, val)  # keep jax's too-large warning path
            return
        cache_path = self.path / f"{key}{cache_suffix}"
        atime_path = self.path / f"{key}{atime_suffix}"
        if self.eviction_enabled:
            self.lock.acquire(timeout=self.lock_timeout_secs)
        try:
            if cache_path.exists():
                return
            self._evict_if_needed(additional_size=len(val))
            tmp = self.path / f"{key}.albedo-tmp-{os.getpid()}"
            tmp.write_bytes(val)
            os.replace(tmp, cache_path)  # a kill leaves tmp, never a torn entry
            atime_path.write_bytes(_time.time_ns().to_bytes(8, "little"))
        finally:
            if self.eviction_enabled:
                self.lock.release()

    def put(self, key: str, val: bytes) -> None:
        if not key:
            raise ValueError("key cannot be empty")
        try:
            _atomic_put(self, key, val)
        except (AttributeError, TypeError, FileNotFoundError):
            # Internals drifted, or a concurrent sweep raced our tmp file:
            # fall back to jax's stock write rather than failing the compile.
            orig_put(self, key, val)

    cls.put = put
    _PATCHED = True
    return True


def _sweep_stale_tmp(cache_dir: Path, max_age_s: float = 3600.0) -> None:
    """Remove tmp files a killed writer left behind (best-effort).

    Age-gated: a tmp file younger than ``max_age_s`` may belong to a LIVE
    writer in another process (compose `serve` warming while a trainer
    runs) — deleting it mid-write would break that writer's os.replace.
    """
    import time as _time

    now = _time.time()
    try:
        for p in Path(cache_dir).glob("*.albedo-tmp-*"):
            try:
                if now - p.stat().st_mtime >= max_age_s:
                    p.unlink(missing_ok=True)
            except OSError:
                continue
    except OSError:
        pass


def enable_persistent_compilation_cache(cache_dir: str | Path | None = None) -> bool:
    """Idempotently point JAX's persistent compilation cache at a directory.

    Returns True if the cache is active after the call. Respects an existing
    user-set ``jax_compilation_cache_dir`` and the ``ALBEDO_JAX_CACHE=0``
    kill switch.
    """
    global _ENABLED
    if os.environ.get("ALBEDO_JAX_CACHE", "1") == "0":
        return False
    import sys as _sys

    if "jax" in _sys.modules:
        # Re-invocations after jax lands still apply the atomic-write patch
        # (the first call usually runs pre-import, where there is nothing
        # to patch).
        harden_jax_cache_writes()
    if _ENABLED:
        return True
    if cache_dir is None:
        from albedo_tpu.settings import get_settings

        cache_dir = get_settings().data_dir / "jax-cache"
    import sys

    if "jax" not in sys.modules:
        # jax not imported yet (e.g. a host-only CLI job that may never touch
        # it): configure via env vars, which jax reads at import — the call
        # stays free of the multi-second jax import.
        Path(cache_dir).mkdir(parents=True, exist_ok=True)
        _sweep_stale_tmp(Path(cache_dir))
        os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", str(cache_dir))
        os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")
        _ENABLED = True
        return True
    import jax

    if jax.config.jax_compilation_cache_dir:
        _ENABLED = True
        return True
    Path(cache_dir).mkdir(parents=True, exist_ok=True)
    _sweep_stale_tmp(Path(cache_dir))
    jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    # Executables this small recompile faster than they deserialize; only
    # persist genuinely expensive compiles.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    _ENABLED = True
    return True
