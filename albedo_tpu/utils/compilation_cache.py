"""Persistent XLA-executable cache across processes.

The reference's JVM warms its code cache within one long-lived Spark session;
a JAX job pays XLA compilation again in every fresh process (~47 s for the
ranker's L-BFGS executable on the tunneled backend, r5 measurement). JAX's
persistent compilation cache serializes compiled executables to disk keyed by
HLO fingerprint, so repeat runs (the ``loadOrCreate`` philosophy,
``utils/ModelUtils.scala:7-21``, applied to executables) skip the compile:
measured working on the axon remote-compile backend (second process ~2x
faster on a toy program; the full LR executable drops from ~47 s to ~0).

Disable with ``ALBEDO_JAX_CACHE=0``; the default directory lives beside the
artifact store (``ALBEDO_DATA_DIR``), so ``drop_data``-style cleanup removes
both.
"""

from __future__ import annotations

import os
from pathlib import Path

_ENABLED = False


def enable_persistent_compilation_cache(cache_dir: str | Path | None = None) -> bool:
    """Idempotently point JAX's persistent compilation cache at a directory.

    Returns True if the cache is active after the call. Respects an existing
    user-set ``jax_compilation_cache_dir`` and the ``ALBEDO_JAX_CACHE=0``
    kill switch.
    """
    global _ENABLED
    if os.environ.get("ALBEDO_JAX_CACHE", "1") == "0":
        return False
    if _ENABLED:
        return True
    if cache_dir is None:
        from albedo_tpu.settings import get_settings

        cache_dir = get_settings().data_dir / "jax-cache"
    import sys

    if "jax" not in sys.modules:
        # jax not imported yet (e.g. a host-only CLI job that may never touch
        # it): configure via env vars, which jax reads at import — the call
        # stays free of the multi-second jax import.
        Path(cache_dir).mkdir(parents=True, exist_ok=True)
        os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", str(cache_dir))
        os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")
        _ENABLED = True
        return True
    import jax

    if jax.config.jax_compilation_cache_dir:
        _ENABLED = True
        return True
    Path(cache_dir).mkdir(parents=True, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    # Executables this small recompile faster than they deserialize; only
    # persist genuinely expensive compiles.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    _ENABLED = True
    return True
