"""Ahead-of-time executable caching: bounded in-memory LRU + on-disk export.

The r5 `cold_prep` record put 13.4 s of every fresh ALS process into XLA
compilation (VERDICT r5 weak #1). Two layers kill it:

1. **In-memory LRU** of AOT-compiled executables (``lower().compile()``),
   bounded so long-lived processes fitting many distinct shapes don't
   accumulate device memory (ADVICE r5 #1 — the unbounded ``_AOT_CACHE``).
2. **On-disk ``jax.export`` round-trip** keyed by an explicit signature
   (bucket shapes + mesh + solver + backend): a second process deserializes
   the StableHLO instead of re-tracing/lowering, and the persistent XLA
   compilation cache (``utils.compilation_cache``) turns the remaining
   compile into a disk read. Serialization happens from the SAME exported
   module both paths compile, so a disk hit provably reproduces the fresh
   compile's program — pinned by the round-trip parity test.

Kill switch: ``ALBEDO_ALS_AOT=0`` disables the disk layer (the LRU stays).
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
import time
from collections import OrderedDict
from pathlib import Path
from typing import Any

log = logging.getLogger(__name__)


class LRUCache:
    """Small thread-safe LRU for compiled executables (and similar handles)."""

    def __init__(self, maxsize: int = 8):
        self.maxsize = max(1, int(maxsize))
        self._data: OrderedDict = OrderedDict()
        self._lock = threading.Lock()

    def get(self, key, default=None):
        with self._lock:
            if key in self._data:
                self._data.move_to_end(key)
                return self._data[key]
        return default

    def put(self, key, value) -> None:
        with self._lock:
            self._data[key] = value
            self._data.move_to_end(key)
            while len(self._data) > self.maxsize:
                self._data.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._data

    def clear(self) -> None:
        with self._lock:
            self._data.clear()


_EXECUTABLES = LRUCache(maxsize=int(os.environ.get("ALBEDO_AOT_MEMORY_SLOTS", "8")))


def reset_memory_cache() -> None:
    """Drop all in-memory executables (tests simulate a fresh process)."""
    _EXECUTABLES.clear()


def disk_cache_enabled() -> bool:
    return os.environ.get("ALBEDO_ALS_AOT", "1") != "0"


def export_dir() -> Path:
    """Serialized-export directory — beside the artifact store, like the
    persistent XLA cache, so ``drop_data``-style cleanup removes both."""
    from albedo_tpu.settings import get_settings

    return get_settings().data_dir / "aot-export"


def signature_digest(key_parts: tuple) -> str:
    return hashlib.sha256(repr(key_parts).encode("utf-8")).hexdigest()[:24]


def _has_custom_calls(exported) -> bool:
    """True if the exported module embeds any ``stablehlo.custom_call``.

    Custom calls are the unstable part of ``jax.export``: their backend
    configs are not guaranteed to survive a cross-process round trip (the
    CPU LAPACK ``lapack_spotrf`` of the Cholesky solver segfaults when a
    deserialized module executes in a fresh process on jaxlib 0.4.x), so
    any module containing one stays memory-cached only. TPU lowers the same
    solves to pure HLO — no custom calls — and the CG fast path has none on
    any backend, so the disk layer still covers the paths that matter.
    """
    import re

    return bool(re.search(r"stablehlo\.custom_call", exported.mlir_module()))


def persistent_aot_call(
    jitted: Any,
    args: tuple,
    dyn_kwargs: dict | None,
    static_kwargs: dict | None,
    key_parts: tuple,
    name: str = "fn",
) -> tuple[Any, float, str]:
    """Call a jitted function through an explicit AOT compile with caching.

    Returns ``(outputs, compile_s, source)`` where ``source`` is ``"memory"``
    (LRU hit, ``compile_s == 0``), ``"disk"`` (deserialized export —
    ``compile_s`` is the residual StableHLO->executable step, itself served
    from the persistent XLA cache when warm), or ``"compile"`` (fresh
    trace + lower + compile; the export is serialized for the next process).

    ``args``/``dyn_kwargs`` are the dynamic arguments (what the compiled
    executable is called with); ``static_kwargs`` only participate in
    lowering. ``key_parts`` must pin everything the executable depends on
    (shapes, dtypes, statics, mesh, backend): a stale key would replay the
    wrong program.
    """
    compiled, compile_s, source = persistent_aot_executable(
        jitted, args, dyn_kwargs, static_kwargs, key_parts, name=name
    )
    return compiled(*args, **(dyn_kwargs or {})), compile_s, source


def persistent_aot_executable(
    jitted: Any,
    args: tuple,
    dyn_kwargs: dict | None,
    static_kwargs: dict | None,
    key_parts: tuple,
    name: str = "fn",
) -> tuple[Any, float, str]:
    """Resolve the cached executable WITHOUT calling it.

    Same contract and cache layers as :func:`persistent_aot_call`, but the
    returned ``compiled`` handle is the product: long-lived callers (the
    serving micro-batcher pre-warming one executable per batch bucket) hold
    it and invoke ``compiled(*args, **dyn_kwargs)`` directly per request,
    skipping the digest + LRU lookup on the hot path entirely.
    """
    import jax

    from albedo_tpu.utils.compilation_cache import harden_jax_cache_writes

    # About to compile (and possibly persist the executable): make sure the
    # persistent cache's writes are torn-write-safe first (idempotent).
    harden_jax_cache_writes()

    dyn_kwargs = dict(dyn_kwargs or {})
    static_kwargs = dict(static_kwargs or {})
    digest = signature_digest(key_parts)
    mem_key = (name, digest)

    compiled = _EXECUTABLES.get(mem_key)
    if compiled is not None:
        return compiled, 0.0, "memory"

    source = "compile"
    compiled = None
    path = export_dir() / f"{name}-{digest}.jaxexport" if disk_cache_enabled() else None
    t0 = time.perf_counter()

    if path is not None and path.exists():
        try:
            from jax import export as jax_export

            restored = jax_export.deserialize(bytearray(path.read_bytes()))
            # Belt and braces: refuse to execute a blob with custom calls
            # even if one was written by hand/an older build (see
            # _has_custom_calls — executing one can crash the process).
            if _has_custom_calls(restored):
                raise ValueError("serialized module contains custom calls")
            compiled = jax.jit(restored.call).lower(*args, **dyn_kwargs).compile()
            source = "disk"
        except Exception as e:  # noqa: BLE001
            # Stale/incompatible blob: fall through to a fresh compile, but
            # say so — a silently dead disk layer reads exactly like a cold
            # cache and the 13s cold compile returns unnoticed.
            log.warning("AOT export %s unusable (%r); recompiling", path.name, e)
            compiled = None

    if compiled is None:
        source = "compile"
        exported = None
        if path is not None:
            try:
                from jax import export as jax_export

                exported = jax_export.export(jitted)(*args, **dyn_kwargs, **static_kwargs)
                if _has_custom_calls(exported):
                    log.debug("%s embeds custom calls; memory cache only", name)
                    exported = None  # not round-trip-safe: memory cache only
            except Exception as e:  # noqa: BLE001
                log.warning("jax.export of %s failed (%r); disk AOT layer off "
                            "for this program", name, e)
                exported = None
        if exported is not None:
            # Compile the SAME StableHLO a later disk hit will deserialize:
            # fresh-compile and round-trip runs execute the identical program.
            compiled = jax.jit(exported.call).lower(*args, **dyn_kwargs).compile()
            try:
                tmp = path.with_name(path.name + f".tmp{os.getpid()}")
                path.parent.mkdir(parents=True, exist_ok=True)
                tmp.write_bytes(exported.serialize())
                os.replace(tmp, path)
            except OSError:
                pass  # cache write is best-effort, never fatal
        else:
            compiled = jitted.lower(*args, **dyn_kwargs, **static_kwargs).compile()
    compile_s = time.perf_counter() - t0

    _EXECUTABLES.put(mem_key, compiled)
    return compiled, compile_s, source
