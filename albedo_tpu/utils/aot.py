"""Ahead-of-time executable caching: bounded in-memory LRU + on-disk export.

The r5 `cold_prep` record put 13.4 s of every fresh ALS process into XLA
compilation (VERDICT r5 weak #1). Two layers kill it:

1. **In-memory LRU** of AOT-compiled executables (``lower().compile()``),
   bounded so long-lived processes fitting many distinct shapes don't
   accumulate device memory (ADVICE r5 #1 — the unbounded ``_AOT_CACHE``).
2. **On-disk ``jax.export`` round-trip** keyed by an explicit signature
   (bucket shapes + mesh + solver + backend): a second process deserializes
   the StableHLO instead of re-tracing/lowering, and the persistent XLA
   compilation cache (``utils.compilation_cache``) turns the remaining
   compile into a disk read. Serialization happens from the SAME exported
   module both paths compile, so a disk hit provably reproduces the fresh
   compile's program — pinned by the round-trip parity test.

Kill switch: ``ALBEDO_ALS_AOT=0`` disables the disk layer (the LRU stays).

**Verified cross-process reuse** (PR 4). Serialized-executable reuse on
some CPU/jaxlib combinations reproduced DIFFERENT numerics than a fresh
compile of the same program — the PR 3 kill-resume drills had to pin
``--no-compilation-cache``. Root cause (PR 4 drills): the persistent XLA
cache's deserialized executables for CUSTOM-CALL programs (the CPU LAPACK
Cholesky) corrupt numerics **nondeterministically** (sub-1e-3 drift up to
all-NaN factors on real inputs, while reproducing probe outputs — so no
verification can make that reuse safe). Three scoped defenses:

1. **Custom-call programs never reuse serialized executables at ANY
   layer**: already excluded from the ``jax.export`` disk cache, they now
   also compile with the persistent XLA cache bypassed. TPU lowers the
   same solves to pure HLO and keeps the full cache stack; CPU Cholesky
   pays a per-process compile — correctness over warmth.
2. **Output-fingerprint self-check on export round-trips**: at export time
   the fresh-compiled executable runs once on a deterministic probe input
   (derived from argument shapes/dtypes; varied index patterns — an
   all-equal batch is invariant to exactly the stride/layout bugs corrupt
   executables exhibit) and a SHA-256 of its output bytes lands in a
   ``.fp`` sidecar; a deserializing process replays the probe and, on
   mismatch, deletes the export and recompiles
   (``albedo_aot_fingerprint_mismatches_total{name=}``).
3. **Export-failed programs** (custom-call status unknown) get the same
   probe fingerprint across the XLA-cache boundary: mismatch recompiles
   with the cache bypassed.

``ALBEDO_AOT_FINGERPRINT=0`` disables all three (the pre-PR-4 behavior).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading

from albedo_tpu.analysis.locksmith import named_lock
import time
from collections import OrderedDict
from pathlib import Path
from typing import Any

log = logging.getLogger(__name__)


class LRUCache:
    """Small thread-safe LRU for compiled executables (and similar handles)."""

    def __init__(self, maxsize: int = 8):
        self.maxsize = max(1, int(maxsize))
        self._data: OrderedDict = OrderedDict()
        self._lock = named_lock("utils.aot.memcache")

    def get(self, key, default=None):
        with self._lock:
            if key in self._data:
                self._data.move_to_end(key)
                return self._data[key]
        return default

    def put(self, key, value) -> None:
        with self._lock:
            self._data[key] = value
            self._data.move_to_end(key)
            while len(self._data) > self.maxsize:
                self._data.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._data

    def clear(self) -> None:
        with self._lock:
            self._data.clear()


_EXECUTABLES = LRUCache(maxsize=int(os.environ.get("ALBEDO_AOT_MEMORY_SLOTS", "8")))
# Serializes the XLA-cache bypass toggle (see _compile_bypassing_xla_cache).
_BYPASS_LOCK = named_lock("utils.aot.bypass")


def reset_memory_cache() -> None:
    """Drop all in-memory executables (tests simulate a fresh process)."""
    _EXECUTABLES.clear()


def disk_cache_enabled() -> bool:
    return os.environ.get("ALBEDO_ALS_AOT", "1") != "0"


def export_dir() -> Path:
    """Serialized-export directory — beside the artifact store, like the
    persistent XLA cache, so ``drop_data``-style cleanup removes both."""
    from albedo_tpu.settings import get_settings

    return get_settings().data_dir / "aot-export"


def signature_digest(key_parts: tuple) -> str:
    return hashlib.sha256(repr(key_parts).encode("utf-8")).hexdigest()[:24]


def fingerprint_enabled() -> bool:
    return os.environ.get("ALBEDO_AOT_FINGERPRINT", "1") != "0"


def _fingerprint_path(path: Path) -> Path:
    return path.with_name(path.name + ".fp")


def _probe_leaf(leaf):
    """A deterministic stand-in with ``leaf``'s shape/dtype. Integer leaves
    get a small VARIED pattern (``arange % 7`` — XLA gathers clamp and
    scatters drop out-of-range indices, so small values are always safe;
    varied values matter because an all-equal batch is invariant to exactly
    the batched-solve stride/layout bugs a corrupt executable exhibits, and
    a zeros probe provably missed the CPU kill-resume drift). Booleans stay
    zeros (masks: the empty-bucket path is shape-safe everywhere). Floats
    get a fixed repeating POSITIVE ramp in [0.25, 0.75) — any value drift
    shows in the output bytes, and scalar hyperparameters (regularization,
    confidence) stay in well-posed territory so solver probes exercise the
    real numeric path rather than a NaN fill. Only shape/dtype are read (no
    device download)."""
    import numpy as np

    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return leaf  # python scalar static-alike: already deterministic
    dtype = np.dtype(dtype)
    size = int(np.prod(shape)) if shape else 1
    if dtype.kind == "b":
        return np.zeros(shape, dtype)
    if dtype.kind in "iu":
        if not shape:
            # 0-d int leaves are traced COUNTS (n_iter, steps): probe with 2
            # so the loop body the fingerprint exists to verify actually
            # executes (a zero count would fingerprint only the prologue).
            return np.asarray(2, dtype)
        return (np.arange(max(size, 1))[:size] % 7).reshape(shape).astype(dtype)
    ramp = (np.arange(max(size, 1)) % 61).astype(np.float64) / 122.0 + 0.25
    return ramp[:size].reshape(shape).astype(dtype)


def _xla_persistent_cache_engaged() -> bool:
    """True when compiles can be served from the on-disk XLA compilation
    cache — the only way a CUSTOM-CALL program's executable crosses process
    boundaries (such programs never enter the jax.export disk layer)."""
    import jax

    try:
        return bool(jax.config.jax_enable_compilation_cache) and bool(
            jax.config.jax_compilation_cache_dir
        )
    except AttributeError:  # pragma: no cover — much older jax
        return False


def _compile_bypassing_xla_cache(jitted, args, dyn_kwargs, static_kwargs):
    """A provably-fresh compile: the persistent XLA cache is switched off
    for just this lower+compile, then restored.

    jax 0.4.x latches the is-cache-used decision process-globally on first
    compile, so flipping the config alone is a silent no-op — the latch must
    be reset around the toggle (and again after, so every other program
    keeps its cache). The toggle is serialized under a module lock:
    overlapping bypassers would otherwise save each other's mid-toggle
    state and could leave the cache disabled process-wide. A concurrent
    NON-bypass compile during the window at worst misses the cache once
    (slower, never wrong)."""
    import jax

    try:
        from jax._src.compilation_cache import reset_cache as _reset_latch
    except (ImportError, AttributeError):  # pragma: no cover — future jax
        _reset_latch = lambda: None  # noqa: E731

    with _BYPASS_LOCK:
        prev = bool(jax.config.jax_enable_compilation_cache)
        try:
            jax.config.update("jax_enable_compilation_cache", False)
            _reset_latch()
            return jitted.lower(*args, **dyn_kwargs, **static_kwargs).compile()
        finally:
            jax.config.update("jax_enable_compilation_cache", prev)
            _reset_latch()


def _output_fingerprint(compiled, args: tuple, dyn_kwargs: dict) -> str:
    """Run ``compiled`` on the deterministic probe and hash the raw output
    bytes (shape + dtype + buffer; NaNs compare by representation)."""
    import jax
    import numpy as np

    probe_args, probe_kwargs = jax.tree_util.tree_map(
        _probe_leaf, (tuple(args), dict(dyn_kwargs))
    )
    out = compiled(*probe_args, **probe_kwargs)
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(out):
        arr = np.asarray(leaf)
        h.update(str(arr.shape).encode())
        h.update(str(arr.dtype).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def _has_custom_calls(exported) -> bool:
    """True if the exported module embeds any ``stablehlo.custom_call``.

    Custom calls are the unstable part of ``jax.export``: their backend
    configs are not guaranteed to survive a cross-process round trip (the
    CPU LAPACK ``lapack_spotrf`` of the Cholesky solver segfaults when a
    deserialized module executes in a fresh process on jaxlib 0.4.x), so
    any module containing one stays memory-cached only. TPU lowers the same
    solves to pure HLO — no custom calls — and the CG fast path has none on
    any backend, so the disk layer still covers the paths that matter.
    """
    import re

    return bool(re.search(r"stablehlo\.custom_call", exported.mlir_module()))


def persistent_aot_call(
    jitted: Any,
    args: tuple,
    dyn_kwargs: dict | None,
    static_kwargs: dict | None,
    key_parts: tuple,
    name: str = "fn",
) -> tuple[Any, float, str]:
    """Call a jitted function through an explicit AOT compile with caching.

    Returns ``(outputs, compile_s, source)`` where ``source`` is ``"memory"``
    (LRU hit, ``compile_s == 0``), ``"disk"`` (deserialized export —
    ``compile_s`` is the residual StableHLO->executable step, itself served
    from the persistent XLA cache when warm), or ``"compile"`` (fresh
    trace + lower + compile; the export is serialized for the next process).

    ``args``/``dyn_kwargs`` are the dynamic arguments (what the compiled
    executable is called with); ``static_kwargs`` only participate in
    lowering. ``key_parts`` must pin everything the executable depends on
    (shapes, dtypes, statics, mesh, backend): a stale key would replay the
    wrong program.
    """
    compiled, compile_s, source = persistent_aot_executable(
        jitted, args, dyn_kwargs, static_kwargs, key_parts, name=name
    )
    return compiled(*args, **(dyn_kwargs or {})), compile_s, source


def persistent_aot_executable(
    jitted: Any,
    args: tuple,
    dyn_kwargs: dict | None,
    static_kwargs: dict | None,
    key_parts: tuple,
    name: str = "fn",
) -> tuple[Any, float, str]:
    """Resolve the cached executable WITHOUT calling it.

    Same contract and cache layers as :func:`persistent_aot_call`, but the
    returned ``compiled`` handle is the product: long-lived callers (the
    serving micro-batcher pre-warming one executable per batch bucket) hold
    it and invoke ``compiled(*args, **dyn_kwargs)`` directly per request,
    skipping the digest + LRU lookup on the hot path entirely.
    """
    import jax

    from albedo_tpu.utils.compilation_cache import harden_jax_cache_writes

    # About to compile (and possibly persist the executable): make sure the
    # persistent cache's writes are torn-write-safe first (idempotent).
    harden_jax_cache_writes()

    dyn_kwargs = dict(dyn_kwargs or {})
    static_kwargs = dict(static_kwargs or {})
    digest = signature_digest(key_parts)
    mem_key = (name, digest)

    compiled = _EXECUTABLES.get(mem_key)
    if compiled is not None:
        return compiled, 0.0, "memory"

    source = "compile"
    compiled = None
    path = export_dir() / f"{name}-{digest}.jaxexport" if disk_cache_enabled() else None
    t0 = time.perf_counter()

    if path is not None and path.exists():
        try:
            from jax import export as jax_export

            restored = jax_export.deserialize(bytearray(path.read_bytes()))
            # Belt and braces: refuse to execute a blob with custom calls
            # even if one was written by hand/an older build (see
            # _has_custom_calls — executing one can crash the process).
            if _has_custom_calls(restored):
                raise ValueError("serialized module contains custom calls")
            compiled = jax.jit(restored.call).lower(*args, **dyn_kwargs).compile()
            # Self-check: the deserialized executable must reproduce the
            # exporting process's probe output bit-for-bit. A mismatch means
            # some cache layer handed back a divergent program — discard the
            # export and recompile rather than serve drifted numerics.
            fp_path = _fingerprint_path(path)
            if fingerprint_enabled() and fp_path.exists():
                expected = json.loads(fp_path.read_text()).get("sha256")
                got = _output_fingerprint(compiled, args, dyn_kwargs)
                if got != expected:
                    from albedo_tpu.utils import events

                    events.aot_fingerprint_mismatches.inc(name=name)
                    log.warning(
                        "AOT export %s output fingerprint mismatch "
                        "(%s != %s); discarding and recompiling",
                        path.name, got[:12], str(expected)[:12],
                    )
                    for stale in (path, fp_path):
                        try:
                            stale.unlink()
                        except OSError:
                            pass
                    compiled = None
                else:
                    source = "disk"
            else:
                source = "disk"
        except Exception as e:  # noqa: BLE001
            # Stale/incompatible blob: fall through to a fresh compile, but
            # say so — a silently dead disk layer reads exactly like a cold
            # cache and the 13s cold compile returns unnoticed.
            log.warning("AOT export %s unusable (%r); recompiling", path.name, e)
            compiled = None

    if compiled is None:
        source = "compile"
        exported = None
        custom_calls: bool | None = None  # None = export failed, can't tell
        if path is not None:
            try:
                from jax import export as jax_export

                exported = jax_export.export(jitted)(*args, **dyn_kwargs, **static_kwargs)
                custom_calls = _has_custom_calls(exported)
                if custom_calls:
                    log.debug("%s embeds custom calls; memory cache only", name)
                    exported = None  # not round-trip-safe: memory cache only
            except Exception as e:  # noqa: BLE001
                log.warning("jax.export of %s failed (%r); disk AOT layer off "
                            "for this program", name, e)
                exported = None
        if exported is not None:
            # Compile the SAME StableHLO a later disk hit will deserialize:
            # fresh-compile and round-trip runs execute the identical program.
            compiled = jax.jit(exported.call).lower(*args, **dyn_kwargs).compile()
            wrote_export = False
            try:
                # serialize() can fail beyond IO: a pytree node type with no
                # registered export serialization (e.g. optax optimizer
                # states) raises ValueError. The program still compiled fine
                # — it just cannot cross processes via the export layer, so
                # the write is best-effort for ANY failure, never fatal.
                blob = exported.serialize()
                tmp = path.with_name(path.name + f".tmp{os.getpid()}")
                path.parent.mkdir(parents=True, exist_ok=True)
                tmp.write_bytes(blob)
                os.replace(tmp, path)
                wrote_export = True
            except Exception as e:  # noqa: BLE001
                if not isinstance(e, OSError):
                    log.warning(
                        "serializing AOT export of %s failed (%r); disk "
                        "layer off for this program", name, e,
                    )
            if wrote_export and fingerprint_enabled():
                # Record what THIS (fresh-compiled) executable computes on
                # the deterministic probe; deserializing processes must
                # reproduce it or recompile. A probe that cannot run (any
                # error, not just IO — e.g. a mesh-committed program
                # rejecting synthetic host inputs) must not crash the job,
                # but it also must not leave a sidecar-less export behind
                # for later processes to trust unverified.
                try:
                    fp = _output_fingerprint(compiled, args, dyn_kwargs)
                    fp_path = _fingerprint_path(path)
                    fp_tmp = fp_path.with_name(fp_path.name + f".tmp{os.getpid()}")
                    fp_tmp.write_text(json.dumps({"sha256": fp}))
                    os.replace(fp_tmp, fp_path)
                except Exception as e:  # noqa: BLE001
                    log.warning(
                        "probe fingerprint of %s failed (%r); removing the "
                        "unverifiable export", name, e,
                    )
                    try:
                        path.unlink()
                    except OSError:
                        pass
        elif custom_calls and fingerprint_enabled() and _xla_persistent_cache_engaged():
            # Known custom-call program (the CPU Cholesky fit). Custom calls
            # are the unstable part of EVERY serialization layer, not just
            # jax.export: the persistent XLA cache's deserialized executables
            # for this program class corrupted numerics NONDETERMINISTICALLY
            # on CPU/jaxlib 0.4.x (sub-1e-3 drift up to all-NaN factors —
            # root-caused by the PR 4 kill-resume drills; a probe fingerprint
            # passes and the same executable then NaNs on real data, so
            # verification cannot make this reuse safe). Do what we already
            # do at the export layer — refuse serialized reuse — and compile
            # fresh with the XLA cache bypassed. TPU lowers these solves to
            # pure HLO and keeps the full cache stack.
            log.debug(
                "%s embeds custom calls; compiling fresh (persistent XLA "
                "cache bypassed for this program)", name
            )
            compiled = _compile_bypassing_xla_cache(
                jitted, args, dyn_kwargs, static_kwargs
            )
        else:
            compiled = jitted.lower(*args, **dyn_kwargs, **static_kwargs).compile()
            # Export-failed programs (custom-call status unknown) still ride
            # the persistent XLA cache across processes — guard that reuse
            # with the probe fingerprint: the first process (cold cache)
            # records the fresh compile's probe output; a later process
            # whose cache-fed executable cannot reproduce it recompiles
            # with the XLA cache bypassed.
            if (
                fingerprint_enabled()
                and disk_cache_enabled()
                and _xla_persistent_cache_engaged()
            ):
                fp_path = export_dir() / f"{name}-{digest}.fp"
                got = None
                try:
                    got = _output_fingerprint(compiled, args, dyn_kwargs)
                except Exception as e:  # noqa: BLE001 — probe must not kill the job
                    log.warning(
                        "probe fingerprint of %s failed (%r); skipping "
                        "cross-process verification for this program", name, e,
                    )
                try:
                    if got is None:
                        pass
                    elif fp_path.exists():
                        expected = json.loads(fp_path.read_text()).get("sha256")
                        if got != expected:
                            from albedo_tpu.utils import events

                            events.aot_fingerprint_mismatches.inc(name=name)
                            log.warning(
                                "XLA-cached compile of %s diverges from the "
                                "recorded fresh-compile fingerprint (%s != "
                                "%s); recompiling with the compilation "
                                "cache bypassed",
                                name, got[:12], str(expected)[:12],
                            )
                            compiled = _compile_bypassing_xla_cache(
                                jitted, args, dyn_kwargs, static_kwargs
                            )
                    else:
                        # Baseline creation must be provably fresh: THIS
                        # process's compile may itself have been fed by a
                        # warm persistent cache (a pre-fingerprint process
                        # can have left a corrupt deserialized executable),
                        # and recording its probe output would make every
                        # later verification vacuous — the corruption would
                        # BE the baseline. Pay one bypassed compile to
                        # anchor it, and hold ourselves to the same check.
                        try:
                            fresh = _compile_bypassing_xla_cache(
                                jitted, args, dyn_kwargs, static_kwargs
                            )
                            baseline = _output_fingerprint(fresh, args, dyn_kwargs)
                        except Exception as e:  # noqa: BLE001
                            log.warning(
                                "fresh baseline compile of %s failed (%r); "
                                "skipping cross-process verification", name, e,
                            )
                        else:
                            fp_path.parent.mkdir(parents=True, exist_ok=True)
                            fp_tmp = fp_path.with_name(
                                fp_path.name + f".tmp{os.getpid()}"
                            )
                            fp_tmp.write_text(json.dumps({"sha256": baseline}))
                            os.replace(fp_tmp, fp_path)
                            if got != baseline:
                                from albedo_tpu.utils import events

                                events.aot_fingerprint_mismatches.inc(name=name)
                                log.warning(
                                    "XLA-cached compile of %s diverges from "
                                    "the fresh-compile baseline (%s != %s); "
                                    "serving the bypassed compile",
                                    name, got[:12], baseline[:12],
                                )
                                compiled = fresh
                except (OSError, ValueError):
                    pass  # fingerprint bookkeeping is best-effort
    compile_s = time.perf_counter() - t0

    _EXECUTABLES.put(mem_key, compiled)
    return compiled, compile_s, source
