"""Training divergence watchdog: on-device health stats, tripwires, remediation.

A diverging implicit-ALS fit rarely crashes — bf16 gathers under an
aggressive alpha, a near-singular normal-equation block, or corrupt input
rows produce factors that are NaN, inf, or merely enormous, and the fit
"succeeds" into an artifact whose NDCG falls off a cliff. The ALX solve-
sanity posture (arxiv 2112.02194) is to check the solve itself, not just
its inputs; this module is that check for both device fits (ALS) and the
LR ranker.

Design constraints:

- **No host syncs on the happy path.** ``factor_health`` is one fused
  jitted reduction over the factor tables whose 3-float result depends on
  EVERY factor element — so its device->host read doubles as the fit's
  completion barrier (``models.als.ImplicitALS.fit`` previously read two
  probe elements for exactly that ordering guarantee; the health read
  replaces it, adding zero round-trips). Chunk-boundary checks in
  ``checkpointed_als_fit`` run on the host copies the checkpoint write
  materializes anyway.
- **Remediate before giving up.** A tripped chunk is re-run ONCE from the
  previous checkpointed factors with f32 gather accumulation and damped
  (increased) regularization (:func:`damped`); only a trip that survives
  remediation raises :class:`TrainingDiverged`. Every trip and every
  remediation outcome lands in the fit journal and in
  ``albedo_watchdog_trips_total{kind=}``.
- **Fault-injectable.** The ``train.watchdog`` site fires inside every
  check; an armed ``error`` kind scribbles NaN into the checked factors so
  chaos drills exercise the real detect -> remediate -> journal path with
  no hand-stubbing.

Tripwire kinds: ``nonfinite`` (any NaN/inf factor), ``norm`` (factor RMS
above an absolute ceiling), ``trajectory`` (RMS grew by more than
``max_growth`` x since the last healthy check — explosion caught before it
reaches inf), ``lr`` (non-finite LR training loss).
"""

from __future__ import annotations

import dataclasses
import logging

import numpy as np

from albedo_tpu.utils import events, faults
from albedo_tpu.utils.faults import FaultInjected

log = logging.getLogger(__name__)

WATCHDOG_FAULT = faults.site("train.watchdog")

_factor_health_jit = None


def factor_health(user_f, item_f):
    """Device-side health vector ``[nonfinite_count, max_abs, rms]`` over
    both factor tables (float32, shape (3,)). Dispatched async — reading it
    to host is the caller's synchronization point."""
    global _factor_health_jit
    if _factor_health_jit is None:
        import jax
        import jax.numpy as jnp

        def _health(uf, vf):
            def stats(x):
                finite = jnp.isfinite(x)
                safe = jnp.where(finite, x, 0.0)
                return (
                    (x.size - finite.sum()).astype(jnp.float32),
                    jnp.max(jnp.abs(safe)),
                    jnp.sqrt(jnp.mean(safe * safe)),
                )

            un, ua, ur = stats(uf)
            vn, va, vr = stats(vf)
            return jnp.stack([un + vn, jnp.maximum(ua, va), jnp.maximum(ur, vr)])

        _factor_health_jit = jax.jit(_health)
    return _factor_health_jit(user_f, item_f)


def health_dict(health) -> dict:
    """Host-readable form of a :func:`factor_health` vector (this read is
    the d2h completion barrier when called on a device array)."""
    h = np.asarray(health, dtype=np.float64)
    return {
        "nonfinite": int(h[0]),
        "max_abs": float(h[1]),
        "rms": float(h[2]),
    }


class TrainingDiverged(RuntimeError):
    """A divergence tripwire survived remediation; the fit's factors are
    garbage and must not be published."""

    def __init__(self, step: int, kinds: list[str]):
        super().__init__(
            f"training diverged at step {step} ({'/'.join(kinds)}) and the "
            f"damped re-run did not recover; refusing to produce factors"
        )
        self.step = step
        self.kinds = kinds


def damped(als):
    """A one-chunk remediation estimator: f32 gather accumulation (drop the
    bf16 fast path) and regularization damped UP by ``10x`` — the standard
    stabilizers for an exploding implicit-ALS normal equation."""
    return dataclasses.replace(
        als, gather_dtype=None, reg_param=float(als.reg_param) * 10.0
    )


@dataclasses.dataclass
class DivergenceWatchdog:
    """Tripwire state across one fit's checks (chunk boundaries or final).

    ``check`` returns the tripped kinds (empty = healthy) and records every
    trip in ``trips`` (journal-ready dicts) and the process-global counter.
    The RMS baseline for the trajectory tripwire only advances on healthy
    checks, so a slow-motion explosion can't ratchet its own baseline up.
    """

    max_rms: float = 1e4
    max_growth: float = 50.0
    trips: list[dict] = dataclasses.field(default_factory=list)
    _prev_rms: float | None = dataclasses.field(default=None, init=False)

    def check(self, step: int, user_f: np.ndarray, item_f: np.ndarray) -> list[str]:
        user_f = np.asarray(user_f)
        item_f = np.asarray(item_f)
        try:
            WATCHDOG_FAULT.hit()
        except FaultInjected:
            # Chaos hook: a mid-fit NaN appears exactly as a real divergence
            # would — the genuine detection + remediation path runs from here.
            user_f = user_f.copy()
            user_f.flat[0] = np.nan
        kinds: list[str] = []
        finite_u = np.isfinite(user_f)
        finite_v = np.isfinite(item_f)
        nonfinite = int(user_f.size - finite_u.sum()) + int(item_f.size - finite_v.sum())
        if nonfinite:
            kinds.append("nonfinite")
        # Same statistic the device-side factor_health reports: the larger
        # of the two tables' RMS over their finite entries-as-zero view.
        rms_u = float(np.sqrt(np.mean(np.square(np.where(finite_u, user_f, 0.0)))))
        rms_v = float(np.sqrt(np.mean(np.square(np.where(finite_v, item_f, 0.0)))))
        rms = max(rms_u, rms_v)
        if rms > self.max_rms:
            kinds.append("norm")
        if (
            self._prev_rms is not None
            and rms > self.max_growth * max(self._prev_rms, 1e-12)
        ):
            kinds.append("trajectory")
        if kinds:
            for kind in kinds:
                events.watchdog_trips.inc(kind=kind)
            self.trips.append({
                "step": int(step), "kinds": kinds,
                "nonfinite": nonfinite, "rms": rms, "remediated": False,
            })
            log.warning(
                "divergence watchdog tripped at step %d: %s (nonfinite=%d rms=%.3g)",
                step, kinds, nonfinite, rms,
            )
        else:
            self._prev_rms = rms
        return kinds

    def mark_remediated(self) -> None:
        """The damped re-run of the last tripped chunk checked healthy."""
        if self.trips:
            self.trips[-1]["remediated"] = True


def guarded_fit(als, matrix, watchdog: DivergenceWatchdog | None = None):
    """Fit with the watchdog on the FINAL factors (the non-checkpointed
    path): check once, remediate once via a damped full re-fit, raise
    :class:`TrainingDiverged` if the re-fit is still sick. Returns
    ``(model, trips)``."""
    wd = watchdog or DivergenceWatchdog()
    model = als.fit(matrix)
    if wd.check(als.max_iter, model.user_factors, model.item_factors):
        log.warning("re-running diverged fit once with f32/damped regularization")
        model = damped(als).fit(matrix)
        if wd.check(als.max_iter, model.user_factors, model.item_factors):
            raise TrainingDiverged(als.max_iter, wd.trips[-1]["kinds"])
        wd.mark_remediated()
    return model, wd.trips


def check_lr_loss(loss: float) -> bool:
    """True when an LR training loss is healthy; a non-finite loss counts a
    ``kind="lr"`` trip (the caller re-runs damped, then raises)."""
    if np.isfinite(loss):
        return True
    events.watchdog_trips.inc(kind="lr")
    return False
