"""The pipelined-dataflow switch.

The sharded ALS dataflow is pipelined by default (double-buffered bucket
prefetch, overlapped ring collectives, fused landing scatter — see
ARCHITECTURE.md "Pipelined sharded dataflow"). ``ALBEDO_PIPELINE=off``
reverts every stage to the synchronous PR 8 dataflow in one flip — the A/B
and triage path: if a pipelined fit ever misbehaves, the first move is to
re-run with the pipeline off and diff.

Kept in a dependency-free module (no jax import) so host-only layers — the
out-of-core dataset reader, the capacity planner's callers — can consult
the same switch the device driver uses.
"""

from __future__ import annotations

import os

PIPELINE_ENV = "ALBEDO_PIPELINE"


def pipeline_enabled() -> bool:
    """Whether the pipelined sharded dataflow is on (default: yes)."""
    return os.environ.get(PIPELINE_ENV, "on").lower() not in ("off", "0", "false")
