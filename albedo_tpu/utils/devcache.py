"""Weakref-keyed device residency for host-owned matrices.

The content/tfidf similarity searches keep their projected matrices as host
arrays (picklable, artifact-friendly) and used to re-upload them on EVERY
query call (``jnp.asarray(self.vectors)`` per ``more_like_this``) — a full
host->device copy of the whole table per request. The ALS layer already
solved this shape of problem with an id-keyed weakref cache
(``models/als.py _matrix_cache``); this is the same pattern, generalized:
one device copy per (owner object, host array), dropped automatically when
the owner is garbage-collected.

Keyed by ``id(owner)`` with a liveness check (a ``WeakKeyDictionary`` would
need hashable owners; dataclasses holding ndarrays aren't), and
``weakref.finalize`` evicts the owner's slots when it dies so long-lived
processes rotating many models don't accumulate device memory. Within an
owner, slots key on the HOST ARRAY's identity and hold a weakref to it —
an id() reused by a different array after garbage collection can never
serve a stale device copy. Sharing is therefore by object, not by value:
the host fallback path and a retrieval-bank build that read the same array
object hold ONE device copy between them.
"""

from __future__ import annotations

import threading

from albedo_tpu.analysis.locksmith import named_lock
import weakref
from typing import Any

_CACHES: dict[int, tuple[weakref.ref, dict]] = {}
_LOCK = named_lock("utils.devcache.entries")


def owner_cache(owner: Any) -> dict:
    """The per-owner slot dict (created on first use, evicted with the owner)."""
    key = id(owner)
    with _LOCK:
        hit = _CACHES.get(key)
        if hit is not None:
            ref, cache = hit
            if ref() is owner:
                return cache
        cache: dict = {}
        _CACHES[key] = (weakref.ref(owner), cache)
        weakref.finalize(owner, _CACHES.pop, key, None)
        return cache


def device_put_cached(owner: Any, host_array):
    """Get-or-create the device copy of ``host_array`` under ``owner``.

    The upload runs at most once per (owner, array object) lifetime.
    Identity-keyed on purpose — the stores treat artifacts as immutable, so
    a mutated-in-place table must be replaced, not edited, to be re-uploaded
    (the overlay paths that DO edit in place manage their own device state).
    """
    cache = owner_cache(owner)
    # Prune slots whose host array died: an owner that replaces its table
    # (a refit on a live object) must not keep the OLD device copy pinned
    # until the owner itself dies.
    for slot in [s for s, (ref, _) in cache.items() if ref() is None]:
        del cache[slot]
    slot = id(host_array)
    hit = cache.get(slot)
    if hit is not None:
        ref, dev = hit
        if ref() is host_array:
            return dev
    import jax.numpy as jnp

    dev = jnp.asarray(host_array)
    cache[slot] = (weakref.ref(host_array), dev)
    return dev
