"""Deterministic fault injection: named sites, armed by tests or environment.

Chaos engineering for the offline pipeline and the serving plane. Production
code declares **fault sites** — named points where reality can go wrong —
and calls ``site.hit()`` (optionally with the file path being touched).
Unarmed, a hit is one dict lookup under a lock: cheap enough to leave in the
hot-ish paths permanently. Armed, the Nth hit performs the configured fault:

==========  ================================================================
kind        effect at the Nth hit
==========  ================================================================
``error``   raise :class:`FaultInjected` (RuntimeError)
``ioerror`` raise ``OSError`` (what a dying disk/NFS mount raises)
``corrupt`` flip one byte of the file at ``path`` (bit-level corruption;
            directories corrupt their first regular file)
``delay``   sleep ``param`` seconds (default 0.05), then continue
``kill``    ``os._exit(137)`` — a hard SIGKILL-style preemption, no cleanup
``term``    ``os.kill(os.getpid(), SIGTERM)`` — a polite preemption notice,
            exercising the SIGTERM checkpoint-and-exit path
``oom``     raise :class:`InjectedResourceExhausted` — a stand-in for the
            ``XlaRuntimeError: RESOURCE_EXHAUSTED`` a real over-HBM
            allocation throws (``utils.retry.is_resource_exhausted``
            classifies both as permanent; ``utils.capacity.admit`` converts
            one fired at ``capacity.admit`` into an over-budget verdict)
``loss``    raise :class:`InjectedDeviceLoss` — a stand-in for the
            ``DEADLINE_EXCEEDED`` / distributed-runtime heartbeat failure a
            dead or hung mesh shard surfaces as mid-collective
            (``utils.retry.is_collective_lost`` classifies both as
            permanent; the elastic sharded fit (``parallel/elastic.py``)
            catches one fired at ``als.shard.collective`` and runs the real
            checkpoint -> remesh -> resume machinery)
==========  ================================================================

Arming is programmatic (``faults.site("artifact.load").arm(kind="corrupt")``)
or environment-driven for subprocess chaos tests::

    ALBEDO_FAULTS="artifact.load:corrupt@1,checkpoint.save:kill@2"

``site:kind@N`` fires at the Nth hit (1-based, default 1); ``site:kind@N*M``
fires for M consecutive hits (``*0`` = every hit from N on). Every firing is
counted in the process-global ``albedo_faults_fired_total{site=...}``
(``utils.events``) so chaos runs can assert — from `/metrics` — that the
fault actually happened.

Site catalog (kept in ARCHITECTURE.md "Fault tolerance", linted against the
code by ``tests/test_fault_sites.py``): ``artifact.load``,
``artifact.save``, ``checkpoint.save``, ``checkpoint.restore``,
``crawler.transport``, ``pipeline.stage``, ``pipeline.stage.<name>``,
``serving.source.<name>``, ``serving.rank``, ``serving.breaker.<name>``,
``reload.load``, ``reload.validate``, ``capacity.admit``, ``mesh.devices``,
``als.chunked``, ``als.shard.collective``, ``serving.admit``,
``loadgen.tick``.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time
from pathlib import Path

from albedo_tpu.analysis.locksmith import named_lock
from albedo_tpu.utils import events

_ENV_VAR = "ALBEDO_FAULTS"
KINDS = ("error", "ioerror", "corrupt", "delay", "kill", "term", "oom", "loss")


class FaultInjected(RuntimeError):
    """The generic injected failure (kind=error)."""


class InjectedResourceExhausted(MemoryError):
    """The injected OOM (kind=oom): message and classification match what a
    real ``XlaRuntimeError: RESOURCE_EXHAUSTED`` looks like to the retry
    predicates, without this module importing jax."""


class InjectedDeviceLoss(RuntimeError):
    """The injected mid-collective device loss (kind=loss): message and
    classification match what a dead/hung mesh shard surfaces as on a real
    slice — jaxlib's ``DEADLINE_EXCEEDED`` collective timeout or a
    distributed-runtime heartbeat failure — so
    ``utils.retry.is_collective_lost`` treats both identically, without
    this module importing jax."""


@dataclasses.dataclass
class FaultSpec:
    """One armed fault: fire at the ``at``-th hit AFTER arming (1-based),
    for ``times`` hits (0 = every hit from ``at`` on). ``base`` is the
    site's hit count at arm time (set by the registry)."""

    site: str
    kind: str = "error"
    at: int = 1
    times: int = 1
    param: float = 0.05  # delay seconds (kind=delay)
    base: int = dataclasses.field(default=0, compare=False)

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} (one of {KINDS})")
        if self.at < 1:
            raise ValueError(f"fault 'at' is 1-based, got {self.at}")

    def active_for(self, hit_number: int) -> bool:
        n = hit_number - self.base
        if n < self.at:
            return False
        return self.times == 0 or n < self.at + self.times


def _flip_byte(path: Path, offset_seed: int = 0) -> None:
    """Deterministically flip one byte of ``path`` (dirs: first regular file,
    sorted). Empty files grow one garbage byte so the change is observable."""
    path = Path(path)
    if path.is_dir():
        files = sorted(p for p in path.rglob("*") if p.is_file())
        if not files:
            return
        path = files[0]
    data = bytearray(path.read_bytes())
    if not data:
        path.write_bytes(b"\xff")
        return
    i = (len(data) // 2 + offset_seed) % len(data)
    data[i] ^= 0xFF
    path.write_bytes(bytes(data))


class FaultRegistry:
    """Hit counters + armed specs for every named site (thread-safe)."""

    def __init__(self, env: str | None = None):
        self._lock = named_lock("utils.faults.registry")
        self._specs: dict[str, list[FaultSpec]] = {}
        self._hits: dict[str, int] = {}
        self._fired: dict[str, int] = {}
        self.load_env(env if env is not None else os.environ.get(_ENV_VAR, ""))

    # --- arming -------------------------------------------------------------

    def arm(self, site: str, kind: str = "error", at: int = 1, times: int = 1,
            param: float = 0.05) -> FaultSpec:
        with self._lock:
            spec = FaultSpec(
                site=site, kind=kind, at=at, times=times, param=param,
                base=self._hits.get(site, 0),  # 'at' counts from arming
            )
            self._specs.setdefault(site, []).append(spec)
        return spec

    def disarm(self, site: str | None = None) -> None:
        with self._lock:
            if site is None:
                self._specs.clear()
            else:
                self._specs.pop(site, None)

    def reset(self) -> None:
        """Disarm everything and zero hit/fired counters (test isolation)."""
        with self._lock:
            self._specs.clear()
            self._hits.clear()
            self._fired.clear()

    def load_env(self, value: str) -> None:
        """Parse ``site:kind@N[*M]`` comma-separated specs (see module doc)."""
        for chunk in (value or "").split(","):
            chunk = chunk.strip()
            if not chunk:
                continue
            try:
                site, _, rest = chunk.partition(":")
                kind, _, when = rest.partition("@")
                at, times = 1, 1
                if when:
                    n, _, m = when.partition("*")
                    at = int(n)
                    if m != "":
                        times = int(m)
                self.arm(site, kind=kind or "error", at=at, times=times)
            except ValueError as e:
                # This parse runs at import in EVERY albedo process; a typo'd
                # spec leaking into an unrelated job must name its source.
                raise ValueError(
                    f"invalid {_ENV_VAR} spec {chunk!r} "
                    f"(expected site:kind@N[*M]): {e}"
                ) from e

    # --- observation --------------------------------------------------------

    def hits(self, site: str) -> int:
        with self._lock:
            return self._hits.get(site, 0)

    def fired(self, site: str) -> int:
        with self._lock:
            return self._fired.get(site, 0)

    def armed(self, site: str) -> list[FaultSpec]:
        with self._lock:
            return list(self._specs.get(site, ()))

    # --- the injection point ------------------------------------------------

    def hit(self, site: str, path: str | os.PathLike | None = None) -> None:
        """Record a hit at ``site``; perform any armed fault that matches.

        ``path`` is the file/directory the caller is about to touch — required
        for ``corrupt`` faults to have something to flip (a corrupt fault at a
        path-less hit is a no-op rather than an error, so one env spec can arm
        heterogeneous sites).
        """
        with self._lock:
            n = self._hits.get(site, 0) + 1
            self._hits[site] = n
            spec = next(
                (s for s in self._specs.get(site, ()) if s.active_for(n)), None
            )
            if spec is None:
                return
            self._fired[site] = self._fired.get(site, 0) + 1
        events.faults_fired.inc(site=site)
        self._perform(spec, site, path)

    def _perform(self, spec: FaultSpec, site: str, path) -> None:
        if spec.kind == "delay":
            time.sleep(spec.param)
            return
        if spec.kind == "corrupt":
            if path is not None:
                _flip_byte(Path(path))
            return
        if spec.kind == "kill":
            # The SIGKILL exit code a preempted pod reports (lazy import:
            # the fault harness stays dependency-free for the offline layers).
            from albedo_tpu.cli import EXIT_KILLED

            os._exit(EXIT_KILLED)
        if spec.kind == "term":
            os.kill(os.getpid(), signal.SIGTERM)
            return
        if spec.kind == "ioerror":
            raise OSError(f"injected IOError at fault site {site!r}")
        if spec.kind == "oom":
            raise InjectedResourceExhausted(
                f"RESOURCE_EXHAUSTED: injected out-of-memory at fault site "
                f"{site!r} (simulated over-HBM allocation)"
            )
        if spec.kind == "loss":
            raise InjectedDeviceLoss(
                f"DEADLINE_EXCEEDED: injected device loss at fault site "
                f"{site!r} (simulated collective timeout / heartbeat failure "
                f"of a mesh shard)"
            )
        raise FaultInjected(f"injected fault at site {site!r}")


@dataclasses.dataclass(frozen=True)
class FaultSite:
    """A named injection point, bound to the global registry.

    Modules create one at import (``_LOAD_FAULT = faults.site("artifact.load")``)
    and call ``.hit()`` where the fault belongs; tests arm through the same
    handle.
    """

    name: str

    def hit(self, path: str | os.PathLike | None = None) -> None:
        FAULTS.hit(self.name, path=path)

    def arm(self, kind: str = "error", at: int = 1, times: int = 1,
            param: float = 0.05) -> FaultSpec:
        return FAULTS.arm(self.name, kind=kind, at=at, times=times, param=param)

    def disarm(self) -> None:
        FAULTS.disarm(self.name)

    def hits(self) -> int:
        return FAULTS.hits(self.name)

    def fired(self) -> int:
        return FAULTS.fired(self.name)


# The process-wide registry: arms from $ALBEDO_FAULTS at import, so chaos
# subprocesses are configured before any albedo code runs.
FAULTS = FaultRegistry()


def site(name: str) -> FaultSite:
    return FaultSite(name)


def hit(name: str, path: str | os.PathLike | None = None) -> None:
    FAULTS.hit(name, path=path)


def arm(name: str, kind: str = "error", at: int = 1, times: int = 1,
        param: float = 0.05) -> FaultSpec:
    return FAULTS.arm(name, kind=kind, at=at, times=times, param=param)


def disarm(name: str | None = None) -> None:
    FAULTS.disarm(name)


def reset() -> None:
    FAULTS.reset()
