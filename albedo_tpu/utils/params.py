"""Estimator parameter dumps.

Reference parity: Spark's ``explainParams`` printed before expensive fits
(``Word2VecCorpusBuilder.scala:85``) so the exact hyperparameters of a run are
in its log. Estimators here are dataclasses, so the dump is their fields.
"""

from __future__ import annotations

import dataclasses
from typing import Any


# Infrastructure fields elided from dumps: runtime wiring, not
# hyperparameters. Meaningful None HYPERparameters (e.g. ImplicitALS
# max_len=None, gather_dtype=None) print like Spark's explainParams prints
# defaults — two configs differing only in a None-vs-set field must not dump
# identically (ADVICE r4 #4).
_INFRA_FIELDS = frozenset({"mesh", "init_factors", "callback"})


def explain_params(estimator: Any) -> str:
    """``name: field=value, ...`` over dataclass fields (non-dataclasses fall
    back to their public ``__dict__``), eliding only the explicit
    infrastructure fields (``_INFRA_FIELDS``)."""
    name = type(estimator).__name__
    if dataclasses.is_dataclass(estimator):
        pairs = [
            (f.name, getattr(estimator, f.name))
            for f in dataclasses.fields(estimator)
        ]
    else:
        pairs = [
            (k, v) for k, v in vars(estimator).items() if not k.startswith("_")
        ]
    body = ", ".join(f"{k}={v!r}" for k, v in pairs if k not in _INFRA_FIELDS)
    return f"{name}({body})"
