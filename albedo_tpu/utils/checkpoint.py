"""Orbax-backed checkpointing for model state (factor matrices, LR params).

Reference parity: the reference's checkpoint/resume story is artifact-level —
every trained model is memoized to a date-keyed parquet path and reloaded on
rerun (``utils/ModelUtils.scala:7-21``; RDD checkpointing at
``ALSRecommenderBuilder.scala:36`` only truncates lineage). The pickle-based
artifact store (``datasets.artifacts``) covers that. This module adds the
TPU-native layer SURVEY.md §5 prescribes on top: Orbax checkpoints for
device-array pytrees — atomic, async-capable, sharding-aware storage that
restores directly to device (and, on a mesh, to the SAME sharding layout)
without a host pickle round-trip.

Steps are integer-versioned under one directory, mirroring training loops that
checkpoint every N sweeps; ``latest_step``/``restore`` give resume-from-latest.

Fault tolerance (the ALX preemption-tolerance posture, arxiv 2112.02194):

- ``steps()`` only reports directories that *look like* checkpoints
  (``step_<8 digits>`` exactly) — leftover Orbax temp dirs and other garbage
  are invisible rather than fatal.
- every ``save`` leaves a ``step_XXXXXXXX.sha256`` content manifest;
  ``restore_latest`` verifies it and walks BACKWARD to the newest *readable*
  step when the newest is truncated/corrupt (counted in the process-global
  ``albedo_checkpoint_fallbacks_total``).
- ``keep_last=N`` prunes old steps after each save so long preemptible runs
  don't fill the disk.
- :class:`PreemptionHandler` converts SIGTERM/SIGINT into a
  checkpoint-at-next-boundary + :class:`Preempted` exit, and
  ``checkpointed_als_fit`` journals its progress (``journal.json``) so a
  rerun knows whether it is resuming a preempted, crashed, or complete fit.
"""

from __future__ import annotations

import os
import re
import shutil
import signal
import threading
import time
from pathlib import Path
from typing import Any

import numpy as np

from albedo_tpu.utils import events, faults
from albedo_tpu.utils.jsonio import atomic_write_json, read_json_or_none

_STEP_RE = re.compile(r"^step_(\d{8})$")
_SAVE_FAULT = faults.site("checkpoint.save")
_RESTORE_FAULT = faults.site("checkpoint.restore")


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_pytree(path: str | Path, tree: Any, *, force: bool = True) -> Path:
    """Atomically write a pytree of arrays (Orbax handles tmp+rename)."""
    path = Path(path).absolute()
    _checkpointer().save(path, tree, force=force)
    return path


def restore_pytree(path: str | Path) -> Any:
    """Restore a pytree saved by ``save_pytree`` (numpy arrays on host)."""
    return _checkpointer().restore(Path(path).absolute())


class Preempted(RuntimeError):
    """Training was interrupted by SIGTERM/SIGINT and checkpointed cleanly;
    rerun with ``--resume`` to continue. ``step`` is the checkpointed step."""

    def __init__(self, step: int, directory: Path | None = None):
        super().__init__(
            f"preempted at step {step}"
            + (f" (checkpoints in {directory})" if directory else "")
        )
        self.step = step
        self.directory = directory


class PreemptionHandler:
    """Convert SIGTERM/SIGINT into a cooperative stop flag.

    Training loops poll :meth:`should_stop` at chunk boundaries and
    checkpoint-then-exit instead of dying mid-sweep — the TPU-pod preemption
    contract (the scheduler sends SIGTERM, the job has seconds to leave a
    resumable trail). A second signal falls through to the previous handler
    (typically KeyboardInterrupt), so a stuck run can still be killed.

    Signal handlers only install from the main thread (Python restriction);
    elsewhere the handler degrades to a manually settable flag.
    """

    def __init__(self, signals: tuple[int, ...] = (signal.SIGTERM, signal.SIGINT)):
        self.signals = signals
        self._stop = threading.Event()
        self._previous: dict[int, Any] = {}

    def __enter__(self) -> "PreemptionHandler":
        if threading.current_thread() is threading.main_thread():
            for sig in self.signals:
                self._previous[sig] = signal.signal(sig, self._on_signal)
        return self

    def __exit__(self, *exc) -> None:
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous.clear()

    def _on_signal(self, signum, frame) -> None:
        if self._stop.is_set():  # second signal: restore + re-deliver
            import os

            prev = self._previous.get(signum, signal.SIG_DFL)
            signal.signal(signum, prev)
            if callable(prev):
                prev(signum, frame)
            else:
                # SIG_DFL isn't callable — re-deliver so the restored default
                # disposition actually fires (the escape hatch must work on
                # the SECOND signal, not silently consume it).
                os.kill(os.getpid(), signum)
            return
        self._stop.set()

    def request_stop(self) -> None:
        """Programmatic preemption (tests, embedding loops)."""
        self._stop.set()

    def should_stop(self) -> bool:
        return self._stop.is_set()


class StepCheckpointer:
    """Integer-step checkpoints under one directory with resume-from-latest.

    >>> ckpt = StepCheckpointer(dir, keep_last=3)
    >>> ckpt.save(10, model.to_arrays())
    >>> step, arrays = ckpt.restore_latest()

    ``keep_last=N`` prunes to the newest N steps after each save (None keeps
    everything). ``restore_latest`` skips unreadable/corrupt steps.
    """

    def __init__(self, directory: str | Path, keep_last: int | None = None):
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        self.keep_last = keep_last

    def _step_dir(self, step: int) -> Path:
        return self.directory / f"step_{step:08d}"

    def _manifest_path(self, step: int) -> Path:
        return self.directory / f"step_{step:08d}.sha256"

    def save(self, step: int, tree: Any) -> Path:
        path = save_pytree(self._step_dir(step), tree)
        # Chaos hook: 'corrupt' flips a byte inside the step dir; 'kill'
        # preempts between the write and the manifest — both must be
        # survivable by restore_latest's backward walk.
        _SAVE_FAULT.hit(path=path)
        from albedo_tpu.datasets.artifacts import file_sha256

        atomic_write_json(
            self._manifest_path(step), {"sha256": file_sha256(path), "step": step}
        )
        if self.keep_last is not None:
            self.prune(self.keep_last)
        return path

    def steps(self) -> list[int]:
        """Steps with a plausibly complete checkpoint directory: the name
        matches ``step_<8 digits>`` exactly (Orbax temp dirs — e.g.
        ``step_00000010.orbax-checkpoint-tmp-...`` — and stray files don't)
        and the directory is non-empty."""
        out = []
        for p in self.directory.iterdir():
            m = _STEP_RE.match(p.name)
            if not m or not p.is_dir():
                continue
            if not any(p.iterdir()):  # half-created: mkdir happened, write didn't
                continue
            out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> int | None:
        steps = self.steps()
        return steps[-1] if steps else None

    def verify(self, step: int) -> bool:
        """True unless the step's manifest exists AND mismatches (a missing
        manifest — pre-manifest checkpoint or a kill between write and
        manifest — leaves the restore attempt to decide). Shares the artifact
        store's sidecar layout and verifier."""
        from albedo_tpu.datasets.artifacts import verify_manifest

        return verify_manifest(self._step_dir(step)) is not False

    def restore(self, step: int) -> Any:
        _RESTORE_FAULT.hit(path=self._step_dir(step))
        return restore_pytree(self._step_dir(step))

    def restore_latest(self) -> tuple[int, Any] | None:
        """(step, tree) of the newest **readable** checkpoint, or None.

        Walks newest -> oldest; a step that fails checksum verification or
        raises on restore is skipped (and counted in
        ``albedo_checkpoint_fallbacks_total``) instead of crashing the
        resume — the newest readable step wins.
        """
        for step in reversed(self.steps()):
            if not self.verify(step):
                events.checkpoint_fallbacks.inc()
                continue
            try:
                return step, self.restore(step)
            except Exception:  # noqa: BLE001 — unreadable step: fall back
                events.checkpoint_fallbacks.inc()
        return None

    def prune(self, keep_last: int) -> list[int]:
        """Delete all but the newest ``keep_last`` steps (and their
        manifests); returns the pruned step numbers."""
        doomed = self.steps()[:-keep_last] if keep_last > 0 else []
        for step in doomed:
            shutil.rmtree(self._step_dir(step), ignore_errors=True)
            mpath = self._manifest_path(step)
            if mpath.exists():
                mpath.unlink()
        return doomed

    # --- the fit journal -----------------------------------------------------

    def journal_path(self) -> Path:
        return self.directory / "journal.json"

    def write_journal(
        self, status: str, step: int, max_iter: int, extra: dict | None = None
    ) -> None:
        """Atomic progress record: {status: running|preempted|complete|
        diverged}. ``extra`` merges additional keys (e.g. the divergence
        watchdog's trip records)."""
        payload = {
            "status": status,
            "step": int(step),
            "max_iter": int(max_iter),
            "updated_at": time.time(),
        }
        if extra:
            payload.update(extra)
        atomic_write_json(self.journal_path(), payload)

    def read_journal(self) -> dict | None:
        return read_json_or_none(self.journal_path())


class ShardedStepCheckpointer(StepCheckpointer):
    """Mesh-portable sweep-boundary checkpoints for the sharded ALS fit.

    A sharded fit's factor tables live row-sharded across the mesh; on a
    real multi-host slice no single host can materialize the whole table,
    and the mesh that RESTORES may be smaller than the mesh that SAVED
    (the degraded ladder after a device loss). So a step is written as a
    **mesh-size-independent logical table**: one file per shard plus a
    layout manifest that records how the shards reassemble::

        step_00000002/
          layout.json              # logical shapes, rank, n_shards,
                                   # per-shard row ranges + sha256
          user_000.npy ... user_NNN.npy   # row shards, zero-padded tail
          item_000.npy ...
        step_00000002.sha256       # step-level content manifest (dir hash)

    ``restore`` concatenates the shards in row order and trims the zero
    padding back to the logical row counts — the result is bit-identical
    whatever shard count wrote it, so a fit checkpointed on 8 devices
    resumes on 4, 2, or 1 (the resuming engine re-shards the logical table
    onto ITS mesh). Every shard file is written tmp + ``os.replace`` and
    ``layout.json`` lands LAST, so a kill mid-checkpoint leaves a step the
    restore walk skips, never a half-written shard a manifest-less restore
    would trust; stale tmp files are swept age-gated on resume
    (:meth:`sweep_stale_tmps`, the jax-cache hardening pattern).

    Everything else — ``steps()`` filtering, the backward restore walk,
    ``keep_last`` retention, the journal — is inherited from
    :class:`StepCheckpointer`.
    """

    LAYOUT_NAME = "layout.json"
    _TMP_MARKER = ".albedo-tmp-"

    @staticmethod
    def _pad_split(table: np.ndarray, n_shards: int) -> list[np.ndarray]:
        n = table.shape[0]
        target = -(-n // n_shards) * n_shards
        if target != n:
            pad = np.zeros((target - n, *table.shape[1:]), dtype=table.dtype)
            table = np.concatenate([table, pad], axis=0)
        return np.split(table, n_shards, axis=0)

    def _write_shard(self, step_dir: Path, name: str, shard: np.ndarray) -> dict:
        from albedo_tpu.datasets.artifacts import file_sha256

        path = step_dir / name
        tmp = step_dir / f"{name}{self._TMP_MARKER}{os.getpid()}"
        with open(tmp, "wb") as f:
            np.save(f, np.ascontiguousarray(shard))
        os.replace(tmp, path)  # a kill leaves tmp, never a torn shard
        return {"file": name, "rows": int(shard.shape[0]),
                "sha256": file_sha256(path)}

    def save(self, step: int, tree: Any, n_shards: int = 1) -> Path:  # type: ignore[override]
        """Write ``tree`` (``user_factors``/``item_factors``/``rank``) as
        ``n_shards`` row shards per table. ``n_shards`` is a LAYOUT choice
        (normally the saving mesh's shard count); restore is agnostic to it.
        """
        step_dir = self._step_dir(step)
        step_dir.mkdir(parents=True, exist_ok=True)
        n_shards = max(1, int(n_shards))
        layout: dict = {"format": "sharded-factors-v1", "step": int(step),
                        "n_shards": n_shards, "rank": int(tree["rank"]),
                        "tables": {}}
        for table in ("user_factors", "item_factors"):
            arr = np.asarray(tree[table], dtype=np.float32)
            shards = [
                self._write_shard(step_dir, f"{table[:4]}_{i:03d}.npy", s)
                for i, s in enumerate(self._pad_split(arr, n_shards))
            ]
            layout["tables"][table] = {
                "logical_rows": int(arr.shape[0]),
                "cols": int(arr.shape[1]),
                "shards": shards,
            }
        # The layout seals the step: shards a kill orphaned before this
        # write are invisible (restore only trusts what layout lists).
        atomic_write_json(step_dir / self.LAYOUT_NAME, layout)
        _SAVE_FAULT.hit(path=step_dir)
        from albedo_tpu.datasets.artifacts import file_sha256

        # The step manifest hashes ONLY layout.json: it already records
        # every shard's sha256, so the layout digest covers the shard bytes
        # transitively — re-hashing the full tables here would double the
        # checkpoint I/O the elastic driver pays every `every` sweeps.
        atomic_write_json(
            self._manifest_path(step),
            {"sha256": file_sha256(step_dir / self.LAYOUT_NAME), "step": step},
        )
        if self.keep_last is not None:
            self.prune(self.keep_last)
        return step_dir

    def verify(self, step: int) -> bool:
        """Manifest check against the layout digest (see ``save``); per-shard
        content is verified at restore against the layout's recorded
        sha256s. A missing manifest leaves the restore attempt to decide,
        matching the parent's semantics."""
        manifest = read_json_or_none(self._manifest_path(step))
        if manifest is None:
            return True
        from albedo_tpu.datasets.artifacts import file_sha256

        layout_path = self._step_dir(step) / self.LAYOUT_NAME
        try:
            return manifest.get("sha256") == file_sha256(layout_path)
        except OSError:
            return False

    def restore(self, step: int) -> Any:
        from albedo_tpu.datasets.artifacts import file_sha256

        step_dir = self._step_dir(step)
        _RESTORE_FAULT.hit(path=step_dir)
        layout = read_json_or_none(step_dir / self.LAYOUT_NAME)
        if not layout or layout.get("format") != "sharded-factors-v1":
            raise ValueError(f"{step_dir.name}: no sealed shard layout")
        out: dict[str, Any] = {"rank": np.int64(layout["rank"])}
        for table, rec in layout["tables"].items():
            parts = []
            for shard in rec["shards"]:
                p = step_dir / shard["file"]
                if file_sha256(p) != shard["sha256"]:
                    raise ValueError(
                        f"{step_dir.name}/{shard['file']}: shard checksum "
                        f"mismatch (half-written or corrupted)"
                    )
                parts.append(np.load(p, allow_pickle=False))
            full = np.concatenate(parts, axis=0)[: rec["logical_rows"]]
            if full.shape != (rec["logical_rows"], rec["cols"]):
                raise ValueError(
                    f"{step_dir.name}/{table}: reassembled shape "
                    f"{full.shape} != logical {(rec['logical_rows'], rec['cols'])}"
                )
            out[table] = full
        return out

    def sweep_stale_tmps(self, max_age_s: float = 3600.0) -> int:
        """Remove shard tmp files a killed writer left behind (best-effort,
        age-gated like the jax-cache hardening: a young tmp may belong to a
        LIVE concurrent writer whose ``os.replace`` must not be broken).
        Called on resume; returns the number of files removed."""
        removed = 0
        now = time.time()
        try:
            for p in self.directory.rglob(f"*{self._TMP_MARKER}*"):
                try:
                    if now - p.stat().st_mtime >= max_age_s:
                        p.unlink()
                        removed += 1
                except OSError:
                    continue
        except OSError:
            pass
        return removed

    def restore_latest(self) -> tuple[int, Any] | None:
        # Resume entry point: clear any stale half-written shard tmps FIRST
        # so nothing in the directory predating this process can ever be
        # mistaken for live checkpoint state.
        self.sweep_stale_tmps()
        return super().restore_latest()


class JsonStepCheckpointer(StepCheckpointer):
    """Step checkpoints whose payload is a plain JSON document.

    The batch-scoring sweep cursor (``albedo_tpu/scoring``) checkpoints a
    small host-side record — which user shards have sealed spill files —
    not device arrays, so an Orbax pytree step would be pure overhead.
    This variant keeps every piece of the :class:`StepCheckpointer`
    discipline (``step_<8 digits>`` dirs, ``.sha256`` sidecar manifests,
    the backward restore walk over readable steps, ``keep_last``
    retention, the journal) and swaps the payload format: one
    ``state.json`` per step, written atomically, manifest-hashed like the
    sharded layout (the digest covers the whole step because the step IS
    the one document). The cursor is therefore mesh-size independent by
    construction — a sweep checkpointed at 8 devices resumes on any rung.
    """

    DOC_NAME = "state.json"

    def save(self, step: int, tree: Any) -> Path:  # type: ignore[override]
        step_dir = self._step_dir(step)
        step_dir.mkdir(parents=True, exist_ok=True)
        doc_path = atomic_write_json(step_dir / self.DOC_NAME, tree)
        # Chaos hook parity with the Orbax path: 'corrupt' flips a byte of
        # the sealed document; 'kill' preempts between the write and its
        # manifest — both must be survivable by restore_latest's walk.
        _SAVE_FAULT.hit(path=doc_path)
        from albedo_tpu.datasets.artifacts import file_sha256

        atomic_write_json(
            self._manifest_path(step),
            {"sha256": file_sha256(doc_path), "step": step},
        )
        if self.keep_last is not None:
            self.prune(self.keep_last)
        return step_dir

    def verify(self, step: int) -> bool:
        manifest = read_json_or_none(self._manifest_path(step))
        if manifest is None:
            return True
        from albedo_tpu.datasets.artifacts import file_sha256

        try:
            return manifest.get("sha256") == file_sha256(
                self._step_dir(step) / self.DOC_NAME
            )
        except OSError:
            return False

    def restore(self, step: int) -> Any:
        step_dir = self._step_dir(step)
        _RESTORE_FAULT.hit(path=step_dir)
        doc = read_json_or_none(step_dir / self.DOC_NAME)
        if doc is None:
            raise ValueError(f"{step_dir.name}: no readable {self.DOC_NAME}")
        return doc


def checkpointed_als_fit(
    als,
    matrix,
    directory: str | Path,
    every: int = 5,
    keep_last: int | None = None,
    preemption: PreemptionHandler | None = None,
    watchdog=None,
):
    """Resumable ALS training: checkpoint factors every ``every`` iterations
    and resume from the latest checkpoint after a kill — the framework-level
    analogue of the reference's artifact-level restartability, but mid-train.

    Training runs in chunks of ``every`` FUSED iterations (one device dispatch
    per chunk, warm-started via ``init_factors``), so factors only cross to
    the host at checkpoint boundaries — not every sweep. Resumed runs continue
    from saved factors rather than replaying the exact iteration stream, so a
    resumed fit is numerically equivalent, not bitwise identical, to an
    uninterrupted one.

    With a :class:`PreemptionHandler`, a SIGTERM/SIGINT arriving mid-fit is
    honored at the next chunk boundary: the current factors are already
    checkpointed, the journal flips to ``preempted``, and :class:`Preempted`
    propagates for the CLI to turn into a clean resumable exit.

    With a :class:`~albedo_tpu.utils.watchdog.DivergenceWatchdog`, every
    chunk boundary runs the tripwires over the host factor copies the
    checkpoint write materializes anyway (no added device syncs). A tripped
    chunk is re-run ONCE from the previous checkpointed factors with f32
    accumulation and damped regularization before the fit gives up with
    ``TrainingDiverged`` (journal status ``diverged``); trips and
    remediation outcomes are journaled under ``"watchdog"`` and counted in
    ``albedo_watchdog_trips_total{kind=}``.
    """
    import dataclasses

    from albedo_tpu.models.als import ALSModel
    from albedo_tpu.utils.watchdog import TrainingDiverged, damped

    if every < 1:
        # min(every, remaining) would pin the chunk size at 0 and loop
        # forever re-saving step 0; callers gate on every > 0, but a direct
        # caller deserves an error, not an infinite loop.
        raise ValueError(f"checkpoint interval must be >= 1, got {every}")
    ckpt = StepCheckpointer(directory, keep_last=keep_last)
    latest = ckpt.restore_latest()
    start = 0
    factors = None

    def _journal_extra() -> dict | None:
        if watchdog is not None and watchdog.trips:
            return {"watchdog": watchdog.trips}
        return None

    if latest is not None:
        start, arrays = latest
        if int(arrays["rank"]) != als.rank:
            raise ValueError(
                f"checkpoint rank {int(arrays['rank'])} != configured rank "
                f"{als.rank}; refusing to resume into a wrong-rank model"
            )
        expect_u = (matrix.n_users, als.rank)
        expect_i = (matrix.n_items, als.rank)
        got_u = tuple(arrays["user_factors"].shape)
        got_i = tuple(arrays["item_factors"].shape)
        if got_u != expect_u or got_i != expect_i:
            raise ValueError(
                f"checkpoint factor shapes {got_u}/{got_i} do not match the "
                f"matrix/config {expect_u}/{expect_i}"
            )
        factors = (arrays["user_factors"], arrays["item_factors"])
        if start >= als.max_iter:
            ckpt.write_journal("complete", start, als.max_iter)
            return ALSModel.from_arrays(arrays)

    ckpt.write_journal("running", start, als.max_iter)
    while start < als.max_iter:
        n = min(every, als.max_iter - start)
        prev = factors
        model = dataclasses.replace(als, max_iter=n, init_factors=prev).fit(matrix)
        factors = (model.user_factors, model.item_factors)
        if watchdog is not None and watchdog.check(start + n, *factors):
            # Remediation: ONE damped re-run of this chunk from the factors
            # the previous checkpoint already holds (prev is None only on
            # the first chunk, where the damped estimator re-seeds).
            model = dataclasses.replace(
                damped(als), max_iter=n, init_factors=prev
            ).fit(matrix)
            factors = (model.user_factors, model.item_factors)
            if watchdog.check(start + n, *factors):
                ckpt.write_journal(
                    "diverged", start, als.max_iter, extra=_journal_extra()
                )
                raise TrainingDiverged(start + n, watchdog.trips[-1]["kinds"])
            watchdog.mark_remediated()
        start += n
        ckpt.save(start, {
            "user_factors": factors[0], "item_factors": factors[1],
            "rank": np.int64(als.rank),
        })
        if preemption is not None and preemption.should_stop() and start < als.max_iter:
            ckpt.write_journal("preempted", start, als.max_iter, extra=_journal_extra())
            raise Preempted(start, ckpt.directory)
        ckpt.write_journal("running", start, als.max_iter, extra=_journal_extra())
    ckpt.write_journal("complete", start, als.max_iter, extra=_journal_extra())
    return ALSModel(user_factors=factors[0], item_factors=factors[1], rank=als.rank)
