"""Orbax-backed checkpointing for model state (factor matrices, LR params).

Reference parity: the reference's checkpoint/resume story is artifact-level —
every trained model is memoized to a date-keyed parquet path and reloaded on
rerun (``utils/ModelUtils.scala:7-21``; RDD checkpointing at
``ALSRecommenderBuilder.scala:36`` only truncates lineage). The pickle-based
artifact store (``datasets.artifacts``) covers that. This module adds the
TPU-native layer SURVEY.md §5 prescribes on top: Orbax checkpoints for
device-array pytrees — atomic, async-capable, sharding-aware storage that
restores directly to device (and, on a mesh, to the SAME sharding layout)
without a host pickle round-trip.

Steps are integer-versioned under one directory, mirroring training loops that
checkpoint every N sweeps; ``latest_step``/``restore`` give resume-from-latest.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

import numpy as np


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_pytree(path: str | Path, tree: Any, *, force: bool = True) -> Path:
    """Atomically write a pytree of arrays (Orbax handles tmp+rename)."""
    path = Path(path).absolute()
    _checkpointer().save(path, tree, force=force)
    return path


def restore_pytree(path: str | Path) -> Any:
    """Restore a pytree saved by ``save_pytree`` (numpy arrays on host)."""
    return _checkpointer().restore(Path(path).absolute())


class StepCheckpointer:
    """Integer-step checkpoints under one directory with resume-from-latest.

    >>> ckpt = StepCheckpointer(dir)
    >>> ckpt.save(10, model.to_arrays())
    >>> step, arrays = ckpt.restore_latest()
    """

    def __init__(self, directory: str | Path):
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)

    def _step_dir(self, step: int) -> Path:
        return self.directory / f"step_{step:08d}"

    def save(self, step: int, tree: Any) -> Path:
        return save_pytree(self._step_dir(step), tree)

    def steps(self) -> list[int]:
        out = []
        for p in self.directory.glob("step_*"):
            try:
                out.append(int(p.name.split("_")[1]))
            except (IndexError, ValueError):
                continue
        return sorted(out)

    def latest_step(self) -> int | None:
        steps = self.steps()
        return steps[-1] if steps else None

    def restore(self, step: int) -> Any:
        return restore_pytree(self._step_dir(step))

    def restore_latest(self) -> tuple[int, Any] | None:
        """(step, tree) of the newest checkpoint, or None if none exist."""
        step = self.latest_step()
        if step is None:
            return None
        return step, self.restore(step)


def checkpointed_als_fit(als, matrix, directory: str | Path, every: int = 5):
    """Resumable ALS training: checkpoint factors every ``every`` iterations
    and resume from the latest checkpoint after a kill — the framework-level
    analogue of the reference's artifact-level restartability, but mid-train.

    Training runs in chunks of ``every`` FUSED iterations (one device dispatch
    per chunk, warm-started via ``init_factors``), so factors only cross to
    the host at checkpoint boundaries — not every sweep. Resumed runs continue
    from saved factors rather than replaying the exact iteration stream, so a
    resumed fit is numerically equivalent, not bitwise identical, to an
    uninterrupted one.
    """
    import dataclasses

    from albedo_tpu.models.als import ALSModel

    ckpt = StepCheckpointer(directory)
    latest = ckpt.restore_latest()
    start = 0
    factors = None
    if latest is not None:
        start, arrays = latest
        if int(arrays["rank"]) != als.rank:
            raise ValueError(
                f"checkpoint rank {int(arrays['rank'])} != configured rank "
                f"{als.rank}; refusing to resume into a wrong-rank model"
            )
        expect_u = (matrix.n_users, als.rank)
        expect_i = (matrix.n_items, als.rank)
        got_u = tuple(arrays["user_factors"].shape)
        got_i = tuple(arrays["item_factors"].shape)
        if got_u != expect_u or got_i != expect_i:
            raise ValueError(
                f"checkpoint factor shapes {got_u}/{got_i} do not match the "
                f"matrix/config {expect_u}/{expect_i}"
            )
        factors = (arrays["user_factors"], arrays["item_factors"])
        if start >= als.max_iter:
            return ALSModel.from_arrays(arrays)

    while start < als.max_iter:
        n = min(every, als.max_iter - start)
        model = dataclasses.replace(als, max_iter=n, init_factors=factors).fit(matrix)
        start += n
        factors = (model.user_factors, model.item_factors)
        ckpt.save(start, {
            "user_factors": factors[0], "item_factors": factors[1],
            "rank": np.int64(als.rank),
        })
    return ALSModel(user_factors=factors[0], item_factors=factors[1], rank=als.rank)
