"""Atomic JSON persistence: the tmp-write + rename idiom, once.

Every journal/manifest in the fault-tolerance layer (artifact manifests,
checkpoint fit journals, the run_pipeline stage journal) persists small JSON
through the same two primitives, so a kill can leave a stale ``*.tmp`` but
never a torn document, and hardening (e.g. fsync-before-rename) has exactly
one place to land.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any


def atomic_write_json(path: str | Path, obj: Any, *, indent: int | None = None) -> Path:
    """Serialize ``obj`` to ``path`` via tmp + rename (same-directory, so the
    rename is atomic on POSIX)."""
    path = Path(path)
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(json.dumps(obj, indent=indent, sort_keys=True))
    tmp.rename(path)
    return path


def read_json_or_none(path: str | Path) -> Any | None:
    """Parse ``path`` as JSON; a missing or undecodable file is None, never a
    crash (resume paths treat both as 'no journal')."""
    try:
        return json.loads(Path(path).read_text())
    except (OSError, ValueError):
        return None
