"""Logging configuration.

Reference parity: ``log4j.properties`` — WARN-level root so Spark internals
stay quiet, with the app package at INFO (``log4j.properties:1-27``). The JAX
analogue quiets the backend/compiler loggers and keeps ``albedo_tpu`` at INFO;
``ALBEDO_LOG_LEVEL`` overrides the app level (the env tier of the reference's
three-tier config system, SURVEY.md §5).
"""

from __future__ import annotations

import logging
import os
import sys

_CONFIGURED = False


def configure_logging(app_level: str | None = None) -> logging.Logger:
    """Idempotent: root WARN, noisy backend loggers WARN, app at INFO (or
    ``ALBEDO_LOG_LEVEL``). Returns the app logger."""
    global _CONFIGURED
    level_name = (app_level or os.environ.get("ALBEDO_LOG_LEVEL", "INFO")).upper()
    # Literal map, not logging.getLevelNamesMapping() (3.11+ only; pyproject
    # supports 3.10).
    levels = {
        "CRITICAL": logging.CRITICAL, "FATAL": logging.CRITICAL,
        "ERROR": logging.ERROR,
        "WARNING": logging.WARNING, "WARN": logging.WARNING,
        "INFO": logging.INFO, "DEBUG": logging.DEBUG, "NOTSET": logging.NOTSET,
    }
    if level_name not in levels:
        print(
            f"warning: unknown ALBEDO_LOG_LEVEL {level_name!r}, using INFO",
            file=sys.stderr,
        )
        level_name = "INFO"
    app = logging.getLogger("albedo_tpu")
    if not _CONFIGURED:
        logging.basicConfig(
            level=logging.WARNING,
            format="%(levelname)s:%(asctime)s:%(name)s: %(message)s",
        )
        for noisy in ("jax", "jax._src", "absl", "urllib3"):
            logging.getLogger(noisy).setLevel(logging.WARNING)
        _CONFIGURED = True
    app.setLevel(levels[level_name])
    return app
