"""Block-sparse linear model kernels over a ``FeatureMatrix``.

The reference's ranker trains Spark MLlib ``LogisticRegression`` on a giant
sparse vector assembled from one-hots over every categorical (including
``user_id``/``repo_id``) plus count-vectors and word2vec blocks
(``LogisticRegressionRanker.scala:176-235``). The TPU-native layout keeps the
blocks separate (``features/assembler.py``): the linear form

``logit = b + dense @ w_dense + sum_f W_cat[f][idx_f] + sum_f <bag_val, W_bag[f][bag_idx]>``

is mathematically the one-hot dot product, computed as weight-row gathers and
masked reductions — fixed shapes, no million-wide vectors.

Standardization (Spark ``setStandardization(true)``): features are implicitly
scaled by ``1/std`` (no centering, preserving sparsity, as MLlib). Training
optimizes the coefficients of the SCALED features with the L2 penalty applied
to them (MLlib's convention), which is what makes regParam=0.7 reproduce the
reference's AUC; ``fold_scales`` converts back to raw-space coefficients.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from albedo_tpu.features.assembler import FeatureMatrix

Params = dict[str, Any]


def feature_batch(fm: FeatureMatrix) -> dict[str, jnp.ndarray]:
    """Upload a FeatureMatrix's arrays as a flat dict of device arrays.

    Bag fields are laid out as DUAL-SORTED flat arrays rather than the padded
    ``(N, L)`` arrays the host keeps: the padded-gather formulation costs a
    random-order 49M-element gather forward and a random scatter-add backward
    on TPU — measured ~95% of the LR fit (1.62 s vs 0.20 s per value_and_grad
    at bench scale). The flat layout carries a row-sorted copy (+ row indptr)
    for the forward and a vocab-sorted copy (+ vocab indptr) for the weight
    gradient, so BOTH directions reduce by the cumsum-difference trick over
    only the real entries (``_bag_term``) — no scatter at all. The mesh path
    (``parallel.lr.shard_feature_batch``) keeps the padded layout — a
    row-shardable rectangle — and ``block_logits`` consumes either.
    """
    batch: dict[str, jnp.ndarray] = {"dense": jnp.asarray(fm.dense)}
    for f, v in fm.cat.items():
        batch[f"cat:{f}"] = jnp.asarray(v)
    for f in fm.bag_idx:
        idx, val = fm.bag_idx[f], fm.bag_val[f]
        n = idx.shape[0]
        ok = idx >= 0
        rows = np.broadcast_to(np.arange(n, dtype=np.int64)[:, None], idx.shape)[ok]
        vocab = idx[ok].astype(np.int32)
        vals = val[ok].astype(np.float32)
        order = np.argsort(vocab, kind="stable")
        # Vocab indptr spans the FULL weight table, so the backward
        # cumsum-difference yields a gradient shaped exactly like the table.
        v_size = fm.bag_sizes[f]
        r_indptr = np.zeros(n + 1, np.int32)
        np.cumsum(np.bincount(rows, minlength=n), out=r_indptr[1:])
        v_indptr = np.zeros(v_size + 1, np.int32)
        np.cumsum(np.bincount(vocab, minlength=v_size), out=v_indptr[1:])
        batch[f"bagflat:{f}:r_vocab"] = jnp.asarray(vocab)              # row-sorted
        batch[f"bagflat:{f}:r_val"] = jnp.asarray(vals)
        batch[f"bagflat:{f}:r_indptr"] = jnp.asarray(r_indptr)
        batch[f"bagflat:{f}:v_rows"] = jnp.asarray(rows[order].astype(np.int32))
        batch[f"bagflat:{f}:v_val"] = jnp.asarray(vals[order])          # vocab-sorted
        batch[f"bagflat:{f}:v_indptr"] = jnp.asarray(v_indptr)
    return batch


def init_params(fm: FeatureMatrix) -> Params:
    p: Params = {
        "bias": jnp.zeros((), jnp.float32),
        "dense": jnp.zeros((fm.dense.shape[1],), jnp.float32),
    }
    for f, size in fm.cat_sizes.items():
        p[f"cat:{f}"] = jnp.zeros((size,), jnp.float32)
    for f, size in fm.bag_sizes.items():
        p[f"bag:{f}"] = jnp.zeros((size,), jnp.float32)
    return p


def inverse_std_scales(fm: FeatureMatrix) -> Params:
    """Per-feature ``1/std`` in the same structure as the params (host side).

    One-hot/bag columns get the std of their expanded 0/1(or count) column;
    constant features get scale 0 so their (useless) coefficient is frozen at
    zero effect, mirroring MLlib's handling of zero-variance features.
    """
    n = max(1, fm.n_rows)
    # MLlib's MultivariateOnlineSummarizer standardizes by the UNBIASED sample
    # std (n-1 denominator); population→sample correction factor n/(n-1).
    bessel = n / (n - 1) if n > 1 else 1.0

    def inv(std: np.ndarray) -> np.ndarray:
        return np.where(std > 0, 1.0 / np.maximum(std, 1e-12), 0.0).astype(np.float32)

    scales: Params = {"bias": np.float32(1.0)}
    d = fm.dense.astype(np.float64)
    std = d.std(axis=0, ddof=1) if n > 1 else d.std(axis=0)
    scales["dense"] = inv(std)
    for f, size in fm.cat_sizes.items():
        p = np.bincount(fm.cat[f], minlength=size) / n
        scales[f"cat:{f}"] = inv(np.sqrt(p * (1 - p) * bessel))
    for f, size in fm.bag_sizes.items():
        idx, val = fm.bag_idx[f], fm.bag_val[f]
        ok = idx >= 0
        rows = np.broadcast_to(np.arange(fm.n_rows)[:, None], idx.shape)[ok]
        cols = idx[ok].astype(np.int64)
        vals = val[ok].astype(np.float64)
        # Aggregate duplicate indices within a row first: the expanded column
        # value is the SUM of a row's entries for that index, so moments must
        # be taken over per-(row, col) sums.
        key = rows.astype(np.int64) * size + cols
        order = np.argsort(key, kind="stable")
        key_s, vals_s = key[order], vals[order]
        uniq, start = np.unique(key_s, return_index=True)
        agg = np.add.reduceat(vals_s, start) if start.size else np.zeros(0)
        col_of = uniq % size
        s1 = np.bincount(col_of, weights=agg, minlength=size)
        s2 = np.bincount(col_of, weights=agg**2, minlength=size)
        mean = s1 / n
        var = (s2 / n - mean**2) * bessel
        scales[f"bag:{f}"] = inv(np.sqrt(np.maximum(var, 0)))
    return scales


def dense_center(fm: FeatureMatrix) -> np.ndarray:
    """Per-column means of the dense block (host side).

    MLlib standardizes WITHOUT centering to preserve sparsity; that is fine in
    its float64 aggregator, but in float32 a near-constant large-magnitude
    column (e.g. document-embedding dims on homogeneous text) standardizes to
    a huge constant offset that destroys the optimizer's conditioning. The
    dense block is already dense, so centering it is free; the objective is
    unchanged (the bias absorbs the shift) and the L2 penalty still applies to
    the same standardized coefficients.
    """
    return fm.dense.astype(np.float64).mean(axis=0).astype(np.float32)


def _segment_sums(data: jnp.ndarray, indptr: jnp.ndarray) -> jnp.ndarray:
    """Sorted-segment sums via the cumsum-difference trick: an exclusive
    cumsum gathered at segment boundaries. No scatter — TPU scatters and
    large random gathers both measured ~100x slower than this streaming
    formulation for the bag blocks. float32 cumsum over ~10^7 mixed-sign
    entries costs ~eps * |running total| per segment (~1e-4 absolute on
    bench-scale logits) — well inside LR tolerance; gradient parity vs the
    padded path is test-pinned."""
    c = jnp.concatenate([jnp.zeros(1, data.dtype), jnp.cumsum(data)])
    return c[indptr[1:]] - c[indptr[:-1]]


def _bag_term(
    w: jnp.ndarray,           # (V,) effective bag weights (params * scales)
    r_vocab: jnp.ndarray, r_val: jnp.ndarray, r_indptr: jnp.ndarray,
    v_rows: jnp.ndarray, v_val: jnp.ndarray, v_indptr: jnp.ndarray,
) -> jnp.ndarray:
    """Per-row bag logit contribution with a cumsum-difference VJP.

    Forward: per-row sums of ``w[r_vocab] * r_val`` over the row-sorted flat
    entries. Backward wrt ``w``: the SAME reduction over the vocab-sorted
    copy. Plain autodiff of the padded form emits a random scatter-add (and
    its forward a 49M-element random gather) — measured 8x slower end-to-end
    at bench scale on TPU."""

    @jax.custom_vjp
    def term(w):
        return _segment_sums(w[r_vocab] * r_val, r_indptr)

    def fwd(w):
        return term(w), None

    def bwd(_, g):
        # v_indptr spans the full weight table, so this is (V,) exactly.
        return (_segment_sums(g[v_rows] * v_val, v_indptr),)

    term.defvjp(fwd, bwd)
    return term(w)


def block_logits(
    params: Params,
    scales: Params,
    batch: dict[str, jnp.ndarray],
    center: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """(N,) logits; ``params`` are standardized-space coefficients and
    ``scales`` the per-feature 1/std factors (use all-ones for raw space).
    ``center`` (optional) is subtracted from the dense block before scaling.

    Bag fields arrive either flat-dual-sorted (``feature_batch``; fast VJP)
    or padded (``parallel.lr.shard_feature_batch``; row-shardable)."""
    dense = batch["dense"] if center is None else batch["dense"] - center
    logits = params["bias"] + (dense * scales["dense"]) @ params["dense"]
    for key, arr in batch.items():
        if key.startswith("cat:"):
            f = key[len("cat:"):]
            w = params[f"cat:{f}"] * scales[f"cat:{f}"]
            logits = logits + w[arr]
        elif key.startswith("bagflat:") and key.endswith(":r_vocab"):
            f = key[len("bagflat:"):-len(":r_vocab")]
            w = params[f"bag:{f}"] * scales[f"bag:{f}"]
            p = f"bagflat:{f}:"
            logits = logits + _bag_term(
                w,
                batch[p + "r_vocab"], batch[p + "r_val"], batch[p + "r_indptr"],
                batch[p + "v_rows"], batch[p + "v_val"], batch[p + "v_indptr"],
            )
        elif key.startswith("bag_idx:"):
            f = key[len("bag_idx:"):]
            w = params[f"bag:{f}"] * scales[f"bag:{f}"]
            idx = arr
            val = batch[f"bag_val:{f}"]
            safe = jnp.where(idx < 0, 0, idx)
            contrib = jnp.where(idx < 0, 0.0, w[safe] * val)
            logits = logits + contrib.sum(axis=1)
    return logits


def weighted_logloss(
    params: Params,
    scales: Params,
    batch: dict[str, jnp.ndarray],
    labels: jnp.ndarray,
    weights: jnp.ndarray,
    reg: float,
    center: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """MLlib objective: (sum_i w_i * ce_i) / sum_i w_i + 0.5 * reg * ||beta_std||^2
    (bias unpenalized)."""
    logits = block_logits(params, scales, batch, center=center)
    # Pre-clip to a finite range: if a line-search trial overshoots params so
    # far the logits overflow to inf, the straight-through correction below
    # would be inf - inf = nan. 1e6 is exactly representable in float32, so
    # clipped + (35 - clipped) still evaluates to exactly 35.
    logits = jnp.clip(logits, -1e6, 1e6)
    # Straight-through clip: cap the CE value so an L-BFGS line-search
    # overshoot can't produce inf - inf = nan, while keeping the gradient of
    # out-of-range (badly misclassified) samples alive.
    logits = logits + jax.lax.stop_gradient(jnp.clip(logits, -35.0, 35.0) - logits)
    ce = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    data = jnp.sum(weights * ce) / jnp.sum(weights)
    pen = sum(
        jnp.sum(v**2) for k, v in params.items() if k != "bias"
    )
    return data + 0.5 * reg * pen


def fold_scales(params: Params, scales: Params) -> Params:
    """Convert standardized-space coefficients to raw-space (beta = beta_std / std)."""
    return jax.tree.map(lambda p, s: p * s, params, scales)
