"""Block-sparse linear model kernels over a ``FeatureMatrix``.

The reference's ranker trains Spark MLlib ``LogisticRegression`` on a giant
sparse vector assembled from one-hots over every categorical (including
``user_id``/``repo_id``) plus count-vectors and word2vec blocks
(``LogisticRegressionRanker.scala:176-235``). The TPU-native layout keeps the
blocks separate (``features/assembler.py``): the linear form

``logit = b + dense @ w_dense + sum_f W_cat[f][idx_f] + sum_f <bag_val, W_bag[f][bag_idx]>``

is mathematically the one-hot dot product, computed as weight-row gathers and
masked reductions — fixed shapes, no million-wide vectors.

Standardization (Spark ``setStandardization(true)``): features are implicitly
scaled by ``1/std`` (no centering, preserving sparsity, as MLlib). Training
optimizes the coefficients of the SCALED features with the L2 penalty applied
to them (MLlib's convention), which is what makes regParam=0.7 reproduce the
reference's AUC; ``fold_scales`` converts back to raw-space coefficients.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from albedo_tpu.features.assembler import FeatureMatrix

Params = dict[str, Any]


def feature_batch(fm: FeatureMatrix) -> dict[str, jnp.ndarray]:
    """Upload a FeatureMatrix's arrays as a flat dict of device arrays.

    Bag fields are laid out as DUAL-SORTED flat arrays rather than the padded
    ``(N, L)`` arrays the host keeps: the padded-gather formulation costs a
    random-order 49M-element gather forward and a random scatter-add backward
    on TPU — measured ~95% of the LR fit (1.62 s vs 0.20 s per value_and_grad
    at bench scale). The flat layout carries a row-sorted copy (+ row indptr)
    for the forward and a vocab-sorted copy (+ vocab indptr) for the weight
    gradient, so BOTH directions reduce by the cumsum-difference trick over
    only the real entries (``_bag_term``) — no scatter at all.

    Vector (embedding) fields upload FACTORED: the (U, D) distinct vectors,
    the (N,) rep gather, and a rep-sorted order + indptr so the backward of
    the per-row gather is a cumsum-difference segment sum (``_rep_term``),
    not a TPU scatter-add. The mesh path
    (``parallel.lr.shard_feature_batch``) keeps the padded/expanded layout —
    a row-shardable rectangle — and ``block_logits`` consumes either.
    """
    batch: dict[str, jnp.ndarray] = {"dense": jnp.asarray(fm.dense)}
    for f in fm.vec_fields():  # canonical sorted order (see vec_fields)
        rep, order, indptr = _rep_layout(fm.vec_rep[f], fm.vec[f].shape[0])
        batch[f"vecflat:{f}:vec"] = jnp.asarray(fm.vec[f])
        batch[f"vecflat:{f}:rep"] = jnp.asarray(rep)
        batch[f"vecflat:{f}:order"] = jnp.asarray(order)
        batch[f"vecflat:{f}:indptr"] = jnp.asarray(indptr)
    for f, v in fm.cat.items():
        batch[f"cat:{f}"] = jnp.asarray(v)
    flat = fm.flat_bags()
    for f in fm.bag_idx:
        rows, vocab, vals = flat[f]
        # Flats are over the STORED rows — the ~50-80x smaller distinct-
        # document set for factored fields (fm.bag_rep), whose per-distinct
        # sums expand to data rows through the same _rep_term machinery as
        # the vec fields (the two custom VJPs compose under autodiff).
        n = fm.bag_idx[f].shape[0]
        order = np.argsort(vocab, kind="stable")
        # Vocab indptr spans the FULL weight table, so the backward
        # cumsum-difference yields a gradient shaped exactly like the table.
        v_size = fm.bag_sizes[f]
        r_indptr = np.zeros(n + 1, np.int32)
        np.cumsum(np.bincount(rows, minlength=n), out=r_indptr[1:])
        v_indptr = np.zeros(v_size + 1, np.int32)
        np.cumsum(np.bincount(vocab, minlength=v_size), out=v_indptr[1:])
        batch[f"bagflat:{f}:r_vocab"] = jnp.asarray(vocab)              # row-sorted
        batch[f"bagflat:{f}:r_val"] = jnp.asarray(vals)
        batch[f"bagflat:{f}:r_indptr"] = jnp.asarray(r_indptr)
        batch[f"bagflat:{f}:v_rows"] = jnp.asarray(rows[order].astype(np.int32))
        batch[f"bagflat:{f}:v_val"] = jnp.asarray(vals[order])          # vocab-sorted
        batch[f"bagflat:{f}:v_indptr"] = jnp.asarray(v_indptr)
        bag_rep = fm.bag_rep.get(f)
        if bag_rep is not None:
            rep, rorder, rindptr = _rep_layout(bag_rep, n)
            batch[f"bagrep:{f}:rep"] = jnp.asarray(rep)
            batch[f"bagrep:{f}:order"] = jnp.asarray(rorder)
            batch[f"bagrep:{f}:indptr"] = jnp.asarray(rindptr)
    return batch


def _rep_layout(rep: np.ndarray, n_distinct: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """The ``_rep_term`` input layout for a (N,) rep vector: ``(rep int32,
    rep-sorted row order, (n_distinct+1,) segment indptr)`` — shared by the
    vec and factored-bag feeders so the two gather VJP layouts cannot drift."""
    rep = np.asarray(rep).astype(np.int32)
    order = np.argsort(rep, kind="stable").astype(np.int32)
    indptr = np.zeros(n_distinct + 1, np.int32)
    np.cumsum(np.bincount(rep, minlength=n_distinct), out=indptr[1:])
    return rep, order, indptr


def init_params(fm: FeatureMatrix) -> Params:
    # Host-side zeros: they ride to the device as jit-call arguments. Eager
    # jnp.zeros would cost one tunneled dispatch per field (~70 ms each,
    # ~3 s at ranker scale).
    p: Params = {
        "bias": np.float32(0.0),
        # One flat coefficient vector for the LOGICAL dense block
        # [scalars | vec fields] — the factored storage changes the batch
        # layout only, never the parameter/scales/coefficients structure.
        "dense": np.zeros((fm.dense_width,), np.float32),
    }
    for f, size in fm.cat_sizes.items():
        p[f"cat:{f}"] = np.zeros((size,), np.float32)
    for f, size in fm.bag_sizes.items():
        p[f"bag:{f}"] = np.zeros((size,), np.float32)
    return p


def inverse_std_scales(fm: FeatureMatrix) -> Params:
    """Per-feature ``1/std`` in the same structure as the params (host side).

    One-hot/bag columns get the std of their expanded 0/1(or count) column;
    constant features get scale 0 so their (useless) coefficient is frozen at
    zero effect, mirroring MLlib's handling of zero-variance features.
    """
    n = max(1, fm.n_rows)
    # MLlib's MultivariateOnlineSummarizer standardizes by the UNBIASED sample
    # std (n-1 denominator); population→sample correction factor n/(n-1).
    bessel = n / (n - 1) if n > 1 else 1.0

    def inv(std: np.ndarray) -> np.ndarray:
        return np.where(std > 0, 1.0 / np.maximum(std, 1e-12), 0.0).astype(np.float32)

    scales: Params = {"bias": np.float32(1.0)}
    ddof = 1 if n > 1 else 0
    # Scalar block: f64 ACCUMULATION without materializing an f64 copy (the
    # astype copied 1.3 GB at r5 ranker bench scale).
    std_parts = [fm.dense.std(axis=0, dtype=np.float64, ddof=ddof)]
    for f in fm.vec_fields():  # canonical order must match block_logits offsets
        # Factored vec field: moments of the EXPANDED column are count-
        # weighted moments over the distinct vectors — O(U*D), not O(N*D).
        v = fm.vec[f].astype(np.float64)
        counts = np.bincount(fm.vec_rep[f], minlength=v.shape[0]).astype(np.float64)
        mean = counts @ v / n
        var = counts @ (v**2) / n - mean**2
        if ddof:
            var = var * (n / (n - 1))
        std_parts.append(np.sqrt(np.maximum(var, 0)))
    scales["dense"] = inv(np.concatenate(std_parts) if len(std_parts) > 1 else std_parts[0])
    for f, size in fm.cat_sizes.items():
        p = np.bincount(fm.cat[f], minlength=size) / n
        scales[f"cat:{f}"] = inv(np.sqrt(p * (1 - p) * bessel))
    flat = fm.flat_bags()
    for f, size in fm.bag_sizes.items():
        rows, cols, vals64 = flat[f]
        cols = cols.astype(np.int64)
        vals = vals64.astype(np.float64)
        # Factored fields store one row per DISTINCT document; the expanded
        # moments weight each distinct row by its multiplicity.
        rep = fm.bag_rep.get(f)
        if rep is None:
            mult = None
        else:
            mult = np.bincount(rep, minlength=fm.bag_idx[f].shape[0]).astype(np.float64)
        # The expanded column value is the SUM of a row's entries for that
        # index, so moments must be over per-(row, col) sums. Entries are
        # row-major; when indices are sorted-unique within each row (what
        # CountVectorizer emits) the O(n) adjacency check proves there is
        # nothing to aggregate and the key-sort pass is skipped entirely.
        same_row = rows[1:] == rows[:-1]
        within_sorted = not np.any(same_row & (cols[1:] < cols[:-1]))
        has_dup = within_sorted and bool(np.any(same_row & (cols[1:] == cols[:-1])))
        if within_sorted and not has_dup:
            w1 = vals if mult is None else vals * mult[rows]
            w2 = vals**2 if mult is None else vals**2 * mult[rows]
            s1 = np.bincount(cols, weights=w1, minlength=size)
            s2 = np.bincount(cols, weights=w2, minlength=size)
        else:
            key = rows.astype(np.int64) * size + cols
            order = np.argsort(key, kind="stable")
            key_s, vals_s = key[order], vals[order]
            uniq, start = np.unique(key_s, return_index=True)
            agg = np.add.reduceat(vals_s, start) if start.size else np.zeros(0)
            col_of = uniq % size
            m_of = 1.0 if mult is None else mult[uniq // size]
            s1 = np.bincount(col_of, weights=agg * m_of, minlength=size)
            s2 = np.bincount(col_of, weights=agg**2 * m_of, minlength=size)
        mean = s1 / n
        var = (s2 / n - mean**2) * bessel
        scales[f"bag:{f}"] = inv(np.sqrt(np.maximum(var, 0)))
    return scales


def dense_center(fm: FeatureMatrix) -> np.ndarray:
    """Per-column means of the dense block (host side).

    MLlib standardizes WITHOUT centering to preserve sparsity; that is fine in
    its float64 aggregator, but in float32 a near-constant large-magnitude
    column (e.g. document-embedding dims on homogeneous text) standardizes to
    a huge constant offset that destroys the optimizer's conditioning. The
    dense block is already dense, so centering it is free; the objective is
    unchanged (the bias absorbs the shift) and the L2 penalty still applies to
    the same standardized coefficients.
    """
    n = max(1, fm.n_rows)
    parts = [fm.dense.mean(axis=0, dtype=np.float64)]
    for f in fm.vec_fields():  # canonical order must match block_logits offsets
        counts = np.bincount(fm.vec_rep[f], minlength=fm.vec[f].shape[0])
        parts.append(counts.astype(np.float64) @ fm.vec[f].astype(np.float64) / n)
    out = np.concatenate(parts) if len(parts) > 1 else parts[0]
    return out.astype(np.float32)


def _segment_sums(data: jnp.ndarray, indptr: jnp.ndarray) -> jnp.ndarray:
    """Sorted-segment sums via the cumsum-difference trick: an exclusive
    cumsum gathered at segment boundaries. No scatter — TPU scatters and
    large random gathers both measured ~100x slower than this streaming
    formulation for the bag blocks.

    Precision (ADVICE r4 #3): a float32 cumsum costs ~eps * |running prefix|
    per segment. Since r5 the streams are SHORT — factored bags collapse the
    flat entries to the distinct-document set (~270k vs 17M at ranker bench
    scale) and the _rep_term backward runs over one grad value per data row
    (~382k, entries ~1/N each, prefix O(1)) — so the absolute error stays
    ~1e-6..1e-5, far inside LR tolerance. Guarded by a bench-scale f64-parity
    test (tests/test_models.py::test_segment_sums_precision_at_scale) rather
    than an f64 cumsum, which would need global jax_enable_x64."""
    c = jnp.concatenate([jnp.zeros(1, data.dtype), jnp.cumsum(data)])
    return c[indptr[1:]] - c[indptr[:-1]]


def _bag_term(
    w: jnp.ndarray,           # (V,) effective bag weights (params * scales)
    r_vocab: jnp.ndarray, r_val: jnp.ndarray, r_indptr: jnp.ndarray,
    v_rows: jnp.ndarray, v_val: jnp.ndarray, v_indptr: jnp.ndarray,
) -> jnp.ndarray:
    """Per-row bag logit contribution with a cumsum-difference VJP.

    Forward: per-row sums of ``w[r_vocab] * r_val`` over the row-sorted flat
    entries. Backward wrt ``w``: the SAME reduction over the vocab-sorted
    copy. Plain autodiff of the padded form emits a random scatter-add (and
    its forward a 49M-element random gather) — measured 8x slower end-to-end
    at bench scale on TPU."""

    @jax.custom_vjp
    def term(w):
        return _segment_sums(w[r_vocab] * r_val, r_indptr)

    def fwd(w):
        return term(w), None

    def bwd(_, g):
        # v_indptr spans the full weight table, so this is (V,) exactly.
        return (_segment_sums(g[v_rows] * v_val, v_indptr),)

    term.defvjp(fwd, bwd)
    return term(w)


def _rep_term(
    lu: jnp.ndarray,          # (U,) per-distinct-vector logit contributions
    rep: jnp.ndarray,         # (N,) representative index per row
    order: jnp.ndarray,       # (N,) row indices sorted by rep
    indptr: jnp.ndarray,      # (U+1,) rep segment boundaries in `order`
) -> jnp.ndarray:
    """Expand per-distinct values to rows with a segment-sum VJP.

    Forward: the (N,) gather ``lu[rep]``. Backward wrt ``lu``: plain autodiff
    would emit a scatter-add over N rows into U slots (TPU scatters measured
    ~100x slower than streaming); the rep-sorted order + indptr reduce it to
    the same cumsum-difference trick as the bag fields."""

    @jax.custom_vjp
    def term(lu):
        return lu[rep]

    def fwd(lu):
        return term(lu), None

    def bwd(_, g):
        return (_segment_sums(g[order], indptr),)

    term.defvjp(fwd, bwd)
    return term(lu)


def block_logits(
    params: Params,
    scales: Params,
    batch: dict[str, jnp.ndarray],
    center: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """(N,) logits; ``params`` are standardized-space coefficients and
    ``scales`` the per-feature 1/std factors (use all-ones for raw space).
    ``center`` (optional) is subtracted from the dense block before scaling.

    The logical dense block is [scalars | vec fields]; ``params["dense"]``
    and ``scales["dense"]`` span the full width. When the batch carries
    factored ``vecflat:`` fields (``feature_batch``), each field's term is
    computed per DISTINCT vector — O(U*D) instead of O(N*D) — then expanded
    by a gather; the expanded layout (``shard_feature_batch``) computes the
    same affine form directly. Bag fields likewise arrive flat-dual-sorted
    (fast VJP) or padded (row-shardable)."""
    w_dense = params["dense"] * scales["dense"]
    d_scalar = batch["dense"].shape[1]
    dense = batch["dense"] if center is None else batch["dense"] - center[:d_scalar]
    logits = params["bias"] + dense @ w_dense[:d_scalar]
    off = d_scalar
    # EXPLICIT sorted field order: scales/center/dense_names are laid out in
    # sorted(vec) order (FeatureMatrix.vec_fields) and jax reconstructs dict
    # pytrees sorted-by-key inside jit anyway — an insertion-order iteration
    # here would silently pair one field's values with another's moments and
    # coefficient slice whenever vector_cols aren't alphabetical.
    vec_fields = sorted(
        key[len("vecflat:"):-len(":vec")]
        for key in batch
        if key.startswith("vecflat:") and key.endswith(":vec")
    )
    for f in vec_fields:
        arr = batch[f"vecflat:{f}:vec"]
        d = arr.shape[1]
        w_f = w_dense[off:off + d]
        # Center BEFORE the contraction: ``vec @ w - c @ w`` cancels two
        # large near-equal dots per distinct vector (w2v dims are
        # near-constant — the exact conditioning problem dense_center
        # exists for; computing it the cancelling way sent the r5 bench
        # fit from 31 to 163 L-BFGS iterations).
        vals = arr if center is None else arr - center[off:off + d]
        lu = vals @ w_f
        p = f"vecflat:{f}:"
        logits = logits + _rep_term(
            lu, batch[p + "rep"], batch[p + "order"], batch[p + "indptr"]
        )
        off += d
    for key, arr in batch.items():
        if key.startswith("cat:"):
            f = key[len("cat:"):]
            w = params[f"cat:{f}"] * scales[f"cat:{f}"]
            logits = logits + w[arr]
        elif key.startswith("bagflat:") and key.endswith(":r_vocab"):
            f = key[len("bagflat:"):-len(":r_vocab")]
            w = params[f"bag:{f}"] * scales[f"bag:{f}"]
            p = f"bagflat:{f}:"
            term = _bag_term(
                w,
                batch[p + "r_vocab"], batch[p + "r_val"], batch[p + "r_indptr"],
                batch[p + "v_rows"], batch[p + "v_val"], batch[p + "v_indptr"],
            )
            rp = f"bagrep:{f}:"
            if rp + "rep" in batch:
                # Factored field: `term` is per DISTINCT document; expand to
                # data rows (the two custom VJPs compose under autodiff).
                term = _rep_term(
                    term, batch[rp + "rep"], batch[rp + "order"], batch[rp + "indptr"]
                )
            logits = logits + term
        elif key.startswith("bag_idx:"):
            f = key[len("bag_idx:"):]
            w = params[f"bag:{f}"] * scales[f"bag:{f}"]
            idx = arr
            val = batch[f"bag_val:{f}"]
            safe = jnp.where(idx < 0, 0, idx)
            contrib = jnp.where(idx < 0, 0.0, w[safe] * val)
            logits = logits + contrib.sum(axis=1)
    return logits


def weighted_logloss(
    params: Params,
    scales: Params,
    batch: dict[str, jnp.ndarray],
    labels: jnp.ndarray,
    weights: jnp.ndarray,
    reg: float,
    center: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """MLlib objective: (sum_i w_i * ce_i) / sum_i w_i + 0.5 * reg * ||beta_std||^2
    (bias unpenalized)."""
    logits = block_logits(params, scales, batch, center=center)
    # Pre-clip to a finite range: if a line-search trial overshoots params so
    # far the logits overflow to inf, the straight-through correction below
    # would be inf - inf = nan. 1e6 is exactly representable in float32, so
    # clipped + (35 - clipped) still evaluates to exactly 35.
    logits = jnp.clip(logits, -1e6, 1e6)
    # Straight-through clip: cap the CE value so an L-BFGS line-search
    # overshoot can't produce inf - inf = nan, while keeping the gradient of
    # out-of-range (badly misclassified) samples alive.
    logits = logits + jax.lax.stop_gradient(jnp.clip(logits, -35.0, 35.0) - logits)
    ce = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    data = jnp.sum(weights * ce) / jnp.sum(weights)
    pen = sum(
        jnp.sum(v**2) for k, v in params.items() if k != "bias"
    )
    return data + 0.5 * reg * pen


def fold_scales(params: Params, scales: Params) -> Params:
    """Convert standardized-space coefficients to raw-space (beta = beta_std / std)."""
    return jax.tree.map(lambda p, s: p * s, params, scales)
