"""Implicit-feedback ALS normal-equation kernels.

The math (Hu-Koren-Volinsky implicit ALS, with Spark MLlib's conventions so the
reference's NDCG is reproducible — SURVEY.md section 7 hard part (b)):

- confidence ``c_ui = 1 + alpha * r_ui``; preference ``p_ui = 1`` where ``r > 0``
- user solve:  ``x_u = (YtY + Y_u^T diag(alpha r_u) Y_u + lambda n_u I)^-1
  Y_u^T (1 + alpha r_u)``
  where ``n_u`` is the user's nonzero count — MLlib scales ``regParam`` by the
  explicit rating count (ALS-WR scaling), see ``ALSRecommenderBuilder.scala:46-58``
  for the hyperparameters this must match.

The reference executes this inside Spark MLlib as shuffled user/item blocks
with per-block LAPACK Cholesky on executors. Here each half-sweep is a set of
fixed-shape bucket solves: gather ``Y[idx] -> (B, L, k)``, one fused einsum for
the Gramian correction, batched solve, land solved rows by an
inverse-permutation gather — all on the MXU, no
shuffle. Buckets come from ``albedo_tpu.datasets.bucket_rows``. The layout is
the same family as ALX's TPU matrix factorization (arXiv:2112.02194 — padded
dense gather blocks over sharded factor tables), and the warm-started-CG fast
path follows the iALS speedup literature (arXiv:2110.14044; the ``implicit``
package's CG solver).

Why XLA HLO and not a hand-written Pallas kernel: the op mix here is exactly
what XLA fuses well — a row gather feeding a batched contraction with static
shapes. A Pallas version would have to issue one small DMA per gathered row
(arbitrary-index row gathers don't tile; ~k*4 bytes per transfer, latency-
bound), and the k=50 factor width sits far off the 128-lane VMEM tile, so a
custom kernel loses to the compiler's gather+einsum fusion. Pallas pays off
when fusion FAILS (e.g. data-dependent inner structure); everything in this
sweep is fusion-friendly by construction — that is what the tier-packed
fixed-shape bucket layout is for.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from albedo_tpu.datasets.ragged import Bucket


def gramian(factors: jax.Array) -> jax.Array:
    """``F^T F`` in float32 — the shared ``YtY`` term of every implicit solve."""
    return factors.T @ factors


def scatter_solved(
    target: jax.Array, row_ids: jax.Array, solved: jax.Array
) -> jax.Array:
    """Land a solved block into ``target``: padding slots (``row_ids == -1``)
    scatter out of bounds and drop. One definition of the landing contract —
    shared by the per-bucket reference path, the chunked host-streamed path,
    and the scan fallback; the sharded landing (``parallel.als.
    _landing_scatter``) is the owner-shard variant of the same rule."""
    safe_rows = jnp.where(row_ids < 0, target.shape[0], row_ids)
    return target.at[safe_rows].set(solved, mode="drop")


def _gather(source: jax.Array, idx: jax.Array, gather_dtype) -> jax.Array:
    """Row-gather the fixed side's factors, optionally through a reduced-
    precision copy of the table.

    With ``gather_dtype="bfloat16"`` the (tiny) factor table is cast once and
    the (huge) gathered ``(B, L, k)`` blocks live in bf16 in HBM — halving the
    streamed bytes of the bandwidth-bound sweep. All contractions over the
    gathered blocks accumulate in float32 (``preferred_element_type``), the
    MXU's native bf16-in/f32-out mode."""
    if gather_dtype is None:
        return source[idx]
    return source.astype(jnp.dtype(gather_dtype))[idx]


def _gdot(spec: str, gathered: jax.Array, other: jax.Array) -> jax.Array:
    """Einsum against the gathered block with f32 accumulation; the non-block
    operand is cast to the block's (possibly bf16) dtype so the MXU consumes
    both natively instead of upcasting the big block to f32 in HBM."""
    return jnp.einsum(
        spec, gathered, other.astype(gathered.dtype),
        preferred_element_type=jnp.float32,
    )


def bucket_solve_body(
    source: jax.Array,   # (n_source, k) fixed side's factors
    yty: jax.Array,      # (k, k) gramian of `source`
    idx: jax.Array,      # (B, L) int32 indices into `source`
    val: jax.Array,      # (B, L) float32 ratings, 0 on padding
    mask: jax.Array,     # (B, L) bool
    reg: jax.Array,      # () float32 regParam
    alpha: jax.Array,    # () float32 confidence scale
    gather_dtype=None,   # None = f32 gathers; "bfloat16" halves streamed bytes
) -> jax.Array:
    """The normal-equation solve for a padded bucket: gather → fused Gramian
    correction → batched Cholesky. Shared by the single-device and shard_map'd
    paths (``parallel.als``), so a parity fix lands in both."""
    gathered = _gather(source, idx, gather_dtype)  # (B, L, k)
    c1 = alpha * val                            # (B, L); 0 on padding
    w = jnp.where(mask, 1.0 + c1, 0.0)          # b-vector weights

    corr, b_vec = bucket_partial_terms(gathered, c1, w)
    n_b = mask.sum(axis=1).astype(jnp.float32)
    return solve_corrected(yty, corr, b_vec, n_b, reg)


def bucket_partial_terms(
    gathered: jax.Array,  # (B, L, k) gathered source rows (zeros where absent)
    c1: jax.Array,        # (B, L) alpha * val, zeroed where the entry is absent
    w: jax.Array,         # (B, L) b-vector weights, zeroed where absent
) -> tuple[jax.Array, jax.Array]:
    """The Gramian correction and b-vector for one (partial) gathered block.

    The bucket solve's data-dependent terms are SUMS over a row's entries, so
    a ring-passed sharded sweep (``parallel.als`` with ``mode="ring"``) can
    accumulate them phase by phase — each phase zeroing the entries whose
    source rows live on a shard not yet seen — and the total equals the
    full-gather terms. Factored out so the ring path's math IS
    ``bucket_solve_body``'s math, not a reimplementation.
    """
    # A_b correction = sum_l c1 * y y^T
    corr = jnp.einsum(
        "blk,bl,blm->bkm", gathered, c1.astype(gathered.dtype), gathered,
        preferred_element_type=jnp.float32,
    )
    # b-vector weights stay float32 even under bf16 gathers: w = 1 + alpha*r
    # spends ~8 significant bits on the integer part alone, so a bf16 cast
    # adds ~0.4% relative error per entry (ADVICE r5 #3). The MXU consumes
    # mixed bf16/f32 inputs with f32 accumulation natively — only the big
    # gathered block needs the reduced dtype to save bandwidth.
    b_vec = jnp.einsum(
        "blk,bl->bk", gathered, w, preferred_element_type=jnp.float32
    )
    return corr, b_vec


def solve_corrected(
    yty: jax.Array,    # (k, k)
    corr: jax.Array,   # (B, k, k) accumulated Gramian correction
    b_vec: jax.Array,  # (B, k)
    n_b: jax.Array,    # (B,) float32 per-row nonzero counts
    reg: jax.Array,    # () float32
) -> jax.Array:
    """Batched Cholesky solve of ``(YtY + corr + reg n_b I) x = b`` — the
    shared tail of the full-gather and ring-accumulated bucket solves."""
    k = yty.shape[0]
    eye = jnp.eye(k, dtype=jnp.float32)
    a_mat = yty[None] + corr + (reg * n_b)[:, None, None] * eye
    chol = jnp.linalg.cholesky(a_mat)
    return jax.scipy.linalg.cho_solve((chol, True), b_vec[..., None])[..., 0]


def bucket_cg_body(
    source: jax.Array,   # (n_source, k) fixed side's factors
    yty: jax.Array,      # (k, k) gramian of `source`
    idx: jax.Array,      # (B, L) int32 indices into `source`
    val: jax.Array,      # (B, L) float32 ratings, 0 on padding
    mask: jax.Array,     # (B, L) bool
    x0: jax.Array,       # (B, k) warm-start iterates (current factors)
    reg: jax.Array,      # () float32 regParam
    alpha: jax.Array,    # () float32 confidence scale
    cg_steps: int,
    gather_dtype=None,   # None = f32 gathers; "bfloat16" halves streamed bytes
) -> jax.Array:
    """Matrix-free Jacobi-preconditioned conjugate gradient on the implicit
    normal equations — never materializes the (B, k, k) systems.

    The matvec ``A p = YtY p + Y_u^T (alpha r (.) (Y_u p)) + reg n_u p`` is two
    gathered einsums, so each CG step costs ~4 B L k MXU FLOPs versus the
    Cholesky path's k^3-shaped factorization, which XLA executes as ~k
    sequential panel steps at a few GF/s on TPU (measured 6 GF/s; the einsum
    phases of the same sweep hit ~1 TF/s). Warm-starting from the previous
    sweep's factors makes a few CG steps per half-sweep converge to the same
    fixed point — the established fast implicit-ALS practice (e.g. the
    ``implicit`` package's conjugate-gradient solver, default 3 steps), while
    MLlib's exact per-block Cholesky (what ``bucket_solve_body`` mirrors)
    remains the parity reference.
    """
    gathered = _gather(source, idx, gather_dtype)  # (B, L, k)
    c1 = alpha * val                            # (B, L); 0 on padding
    w = jnp.where(mask, 1.0 + c1, 0.0)
    n_b = mask.sum(axis=1).astype(jnp.float32)
    # f32 weights for the b-vector under bf16 gathers — see bucket_solve_body.
    b_vec = jnp.einsum(
        "blk,bl->bk", gathered, w, preferred_element_type=jnp.float32
    )

    # Jacobi preconditioner: diag(A) = diag(YtY) + sum_l c1 y_l^2 + reg n.
    diag = (
        jnp.diagonal(yty)[None]
        + _gdot("blk,bl->bk", gathered * gathered, c1)
        + (reg * n_b)[:, None]
    )
    diag = jnp.maximum(diag, 1e-12)

    def matvec(p):
        t = c1 * _gdot("blk,bk->bl", gathered, p)
        return (
            p @ yty
            + _gdot("blk,bl->bk", gathered, t)
            + (reg * n_b)[:, None] * p
        )

    tiny = jnp.float32(1e-30)
    x = x0
    r = b_vec - matvec(x)
    z = r / diag
    p = z
    rz = jnp.sum(r * z, axis=1)
    for _ in range(cg_steps):  # static unroll: fixed shapes, no host sync
        ap = matvec(p)
        step = rz / (jnp.sum(p * ap, axis=1) + tiny)
        x = x + step[:, None] * p
        r = r - step[:, None] * ap
        z = r / diag
        rz_new = jnp.sum(r * z, axis=1)
        beta = rz_new / (rz + tiny)
        p = z + beta[:, None] * p
        rz = rz_new
    return x


# Per-bucket eager reference path (als_half_sweep): parity tests and small
# interactive runs only — hot fits go through als_fit_fused/als_init_fit_fused,
# which ARE acquired via utils/aot.
# albedo: noqa[bare-jit]
@functools.partial(jax.jit, donate_argnames=("target",))
def solve_bucket(
    source: jax.Array,   # (n_source, k) fixed side's factors
    yty: jax.Array,      # (k, k) gramian of `source`
    target: jax.Array,   # (n_target, k) factors being updated (donated)
    row_ids: jax.Array,  # (B,) int32 target rows, -1 on padding slots
    idx: jax.Array,      # (B, L) int32 indices into `source`
    val: jax.Array,      # (B, L) float32 ratings, 0 on padding
    mask: jax.Array,     # (B, L) bool
    reg: jax.Array,      # () float32 regParam
    alpha: jax.Array,    # () float32 confidence scale
) -> jax.Array:
    """One normal-equation solve for a padded bucket of rows; returns updated
    ``target`` with solved rows scattered in."""
    solved = bucket_solve_body(source, yty, idx, val, mask, reg, alpha)
    return scatter_solved(target, row_ids, solved)


@functools.partial(
    jax.jit,
    donate_argnums=(2,),  # target: the chunked path must not double-buffer it
    static_argnames=("solver", "cg_steps", "gather_dtype"),
)
def chunked_bucket_update(
    source: jax.Array,   # (n_source, k) fixed side's factors
    yty: jax.Array,      # (k, k) gramian of `source`
    target: jax.Array,   # (n_target, k) factors being updated (donated)
    row_ids: jax.Array,  # (B,) int32 target rows, -1 on padding slots
    idx: jax.Array,      # (B, L) int32 indices into `source`
    val: jax.Array,      # (B, L) float32 ratings, 0 on padding
    mask: jax.Array,     # (B, L) bool
    reg: jax.Array,      # () float32 regParam
    alpha: jax.Array,    # () float32 confidence scale
    solver: str = "cholesky",
    cg_steps: int = 3,
    gather_dtype: str | None = None,
) -> jax.Array:
    """One bucket's solve for the **chunked host-streamed** fallback path
    (``models.als`` under a ``degrade`` capacity verdict): the bucket slab
    arrives fresh from the host per call, only the factor tables stay
    device-resident. Same kernels as the fused sweep (``bucket_solve_body``
    / ``bucket_cg_body``) so the fallback is numerics-parity with the
    resident path; each target row appears in exactly one bucket, so the
    sequential scatters land exactly what the fused landing gather lands.
    """
    if solver == "cg":
        x0 = target[jnp.where(row_ids < 0, 0, row_ids)]
        solved = bucket_cg_body(
            source, yty, idx, val, mask, x0, reg, alpha, cg_steps,
            gather_dtype=gather_dtype,
        )
    else:
        solved = bucket_solve_body(
            source, yty, idx, val, mask, reg, alpha, gather_dtype=gather_dtype,
        )
    return scatter_solved(target, row_ids, solved)


def als_half_sweep(
    source: jax.Array,
    target: jax.Array,
    buckets: list[Bucket],
    reg: float,
    alpha: float,
) -> jax.Array:
    """Update every (nonempty) row of ``target`` from fixed ``source`` factors.

    One compiled kernel per distinct bucket shape (O(log max_len) shapes).
    """
    yty = gramian(source)
    reg_arr = jnp.float32(reg)
    alpha_arr = jnp.float32(alpha)
    for b in buckets:
        target = solve_bucket(
            source, yty, target,
            jnp.asarray(b.row_ids), jnp.asarray(b.idx),
            jnp.asarray(b.val), jnp.asarray(b.mask),
            reg_arr, alpha_arr,
        )
    return target


def scan_half_sweep(
    source: jax.Array,
    target: jax.Array,
    groups: list[Bucket],
    reg: jax.Array,
    alpha: jax.Array,
    solver: str = "cholesky",
    cg_steps: int = 3,
    landing: jax.Array | None = None,
    gather_dtype=None,
) -> jax.Array:
    """Traceable half-sweep over stacked same-shape bucket groups
    (``ragged.group_buckets``): one ``lax.scan`` per distinct shape, so the
    whole sweep lives inside a single XLA program with no per-bucket dispatch.

    Each row appears in exactly one bucket, so scan order within a half-sweep
    is irrelevant. ``solver="cholesky"`` is the exact MLlib-parity solve
    (``bucket_solve_body``, shared with the per-bucket and shard_map paths);
    ``solver="cg"`` is the matrix-free warm-started CG (``bucket_cg_body``).

    ``landing`` (``models.als`` precomputes it on host) is the inverse
    permutation that lands solved rows by a GATHER from
    ``concat(solved_blocks..., target)`` instead of a scatter into ``target``
    — TPU scatters serialize (measured ~0.03 s/iter, the largest single
    phase of the r4 CG iteration) while the equivalent gather streams.
    ``landing[r] = flat slot position of row r``, or ``n_slots + r`` to keep
    the old factor for rows in no bucket.
    """
    if solver not in ("cholesky", "cg"):
        raise ValueError(f"unknown solver {solver!r} (expected 'cholesky' or 'cg')")
    yty = gramian(source)

    # Every target row appears in exactly one bucket, so the solves never
    # read rows written this half-sweep: solve all groups against the
    # PRE-SWEEP target (CG warm starts read it), collect the solved blocks,
    # and land them in ONE gather (or scatter, without `landing`) — keeping
    # the (n_target, k) table out of the scan carry.
    def body(_, g):
        row_ids, idx, val, mask = g
        if solver == "cg":
            x0 = target[jnp.where(row_ids < 0, 0, row_ids)]
            solved = bucket_cg_body(
                source, yty, idx, val, mask, x0, reg, alpha, cg_steps,
                gather_dtype=gather_dtype,
            )
        else:
            solved = bucket_solve_body(
                source, yty, idx, val, mask, reg, alpha,
                gather_dtype=gather_dtype,
            )
        return None, solved

    k = target.shape[1]
    all_rows, all_solved = [], []
    for g in groups:
        _, solved = jax.lax.scan(body, None, (g.row_ids, g.idx, g.val, g.mask))
        all_rows.append(g.row_ids.reshape(-1))
        all_solved.append(solved.reshape(-1, k))
    if landing is not None:
        pool = jnp.concatenate(all_solved + [target])
        return pool[landing]
    rows = jnp.concatenate(all_rows)
    solved = jnp.concatenate(all_solved)
    return scatter_solved(target, rows, solved)


def _fit_loop(
    user_f, item_f, user_groups, item_groups, reg, alpha, n_iter,
    solver, cg_steps, user_landing=None, item_landing=None, gather_dtype=None,
):
    ug = [Bucket(*g) for g in user_groups]
    ig = [Bucket(*g) for g in item_groups]

    def iteration(_, carry):
        uf, vf = carry
        # MLlib order: item factors first (from user factors), then users.
        vf = scan_half_sweep(
            uf, vf, ig, reg, alpha, solver, cg_steps, item_landing, gather_dtype
        )
        uf = scan_half_sweep(
            vf, uf, ug, reg, alpha, solver, cg_steps, user_landing, gather_dtype
        )
        return uf, vf

    return jax.lax.fori_loop(0, n_iter, iteration, (user_f, item_f))


@functools.partial(
    jax.jit,
    donate_argnames=("user_f", "item_f"),
    static_argnames=("solver", "cg_steps", "gather_dtype"),
)
def als_fit_fused(
    user_f: jax.Array,
    item_f: jax.Array,
    user_groups: list[tuple],  # (row_ids, idx, val, mask) per stacked shape group
    item_groups: list[tuple],
    reg: jax.Array,
    alpha: jax.Array,
    n_iter: jax.Array,         # traced scalar: one executable for any iter count
    solver: str = "cholesky",
    cg_steps: int = 3,
    user_landing: jax.Array | None = None,
    item_landing: jax.Array | None = None,
    gather_dtype: str | None = None,
) -> tuple[jax.Array, jax.Array]:
    """The entire ALS fit as ONE device dispatch.

    The reference runs 26 alternating sweeps as hundreds of Spark stages with a
    shuffle boundary each (``ALSRecommenderBuilder.scala:46-58``); the previous
    revision here still paid one host->device dispatch per bucket per sweep
    (~1.5k dispatches — the dominant cost on a remote/tunneled TPU). Now the
    ``max_iter`` loop is a ``lax.fori_loop`` whose body is two scanned
    half-sweeps, so dispatch overhead is paid once per *fit*. ``n_iter`` is a
    traced scalar: warmup with ``n_iter=1`` reuses the same executable as the
    real run.
    """
    return _fit_loop(
        user_f, item_f, user_groups, item_groups, reg, alpha, n_iter,
        solver, cg_steps, user_landing, item_landing, gather_dtype,
    )


@functools.partial(
    jax.jit,
    static_argnames=("n_users", "n_items", "rank", "solver", "cg_steps", "gather_dtype"),
)
def als_init_fit_fused(
    key: jax.Array,            # PRNG key for the seeded factor init
    user_groups: list[tuple],
    item_groups: list[tuple],
    reg: jax.Array,
    alpha: jax.Array,
    n_iter: jax.Array,
    n_users: int,
    n_items: int,
    rank: int,
    solver: str = "cholesky",
    cg_steps: int = 3,
    user_landing: jax.Array | None = None,
    item_landing: jax.Array | None = None,
    gather_dtype: str | None = None,
) -> tuple[jax.Array, jax.Array]:
    """``als_fit_fused`` with the seeded factor init INSIDE the program.

    Creating the init factors eagerly costs ~6 separate device dispatches
    (PRNGKey, split, 2x normal, 2x scale) — measured ~1.0 s of the 3.8 s r4
    fit on the tunneled backend at ~70 ms/dispatch. Fusing the init into the
    fit program makes the whole train ONE dispatch and the values identical
    (same traced PRNG ops, same key).
    """
    ukey, ikey = jax.random.split(key)
    scale = 1.0 / jnp.sqrt(jnp.float32(rank))
    user_f = jax.random.normal(ukey, (n_users, rank), jnp.float32) * scale
    item_f = jax.random.normal(ikey, (n_items, rank), jnp.float32) * scale
    return _fit_loop(
        user_f, item_f, user_groups, item_groups, reg, alpha, n_iter,
        solver, cg_steps, user_landing, item_landing, gather_dtype,
    )


def implicit_loss(
    user_factors: jax.Array,
    item_factors: jax.Array,
    rows: jax.Array,
    cols: jax.Array,
    vals: jax.Array,
    reg: float,
    alpha: float,
) -> jax.Array:
    """The exact implicit-ALS objective (for tests/monitoring; O(U*I) — small
    data only).

    ``sum_ui c_ui (p_ui - x_u . y_i)^2 + reg * (sum_u n_u |x_u|^2 + sum_i n_i |y_i|^2)``
    """
    scores = user_factors @ item_factors.T
    conf = jnp.ones_like(scores)
    pref = jnp.zeros_like(scores)
    conf = conf.at[rows, cols].add(alpha * vals)
    pref = pref.at[rows, cols].set(jnp.where(vals > 0, 1.0, 0.0))
    data_term = (conf * (pref - scores) ** 2).sum()

    n_u = jnp.zeros(user_factors.shape[0]).at[rows].add(1.0)
    n_i = jnp.zeros(item_factors.shape[0]).at[cols].add(1.0)
    reg_term = (n_u * (user_factors**2).sum(axis=1)).sum() + (
        n_i * (item_factors**2).sum(axis=1)
    ).sum()
    return data_term + reg * reg_term
