"""Device compute primitives (JAX/XLA; Pallas kernels under ``ops.pallas``).

This is the TPU-native replacement for the reference's netlib-BLAS hot loops
(SURVEY.md section 2: the MLlib ALS normal-equation solves and the
``F2jBLAS.sdot`` scoring loop in ``recommenders/ALSRecommender.scala:51``).
"""

from albedo_tpu.ops.als import als_half_sweep, gramian, solve_bucket
from albedo_tpu.ops.topk import topk_scores

__all__ = ["als_half_sweep", "gramian", "solve_bucket", "topk_scores"]
