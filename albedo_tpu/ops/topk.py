"""Blocked user x item scoring with streaming top-k.

Replaces the reference's retrieval hot loop (``recommenders/ALSRecommender.scala:21-61``):
blockify both factor tables (4096 rows/block), cross-join blocks, score each
pair with ``F2jBLAS.sdot``, and keep a per-user ``BoundedPriorityQueue``. Here
the block cross-product is a ``lax.scan`` over item blocks: each step is one
``(U, k) @ (k, B)`` MXU GEMM followed by a merge of the running ``(U, K)``
top-k with the block's scores via ``lax.top_k`` — no materialized U x I score
matrix (SURVEY.md section 7 hard part (c)).

Optionally masks out each user's already-seen items (the PySpark track's
``recommend_items`` exclusion, ``albedo_toolkit/common.py:47-71``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


# Single-request cold path (ALSModel.recommend) and an inlined building block
# of the batched programs: the serving hot path acquires _gather_topk* through
# utils/aot (serving/batcher.py); this standalone jit serves ad-hoc calls.
# albedo: noqa[bare-jit]
@functools.partial(jax.jit, static_argnames=("k", "item_block"))
def topk_scores(
    user_factors: jax.Array,          # (U, r)
    item_factors: jax.Array,          # (I, r)
    k: int,
    exclude_idx: jax.Array | None = None,  # (U, E) int32 item indices, -1 = none
    item_block: int = 4096,
) -> tuple[jax.Array, jax.Array]:
    """Return ``(scores (U, k), item_indices (U, k))`` of the top-k items/user.

    Items are processed in ``item_block``-sized chunks; the running top-k is
    merged with each chunk, so peak memory is ``O(U * (k + item_block))``.
    ``exclude_idx`` rows list per-user items to mask to -inf (padded with -1).
    """
    n_users, rank = user_factors.shape
    n_items = item_factors.shape[0]

    n_blocks = -(-n_items // item_block)
    padded = n_blocks * item_block
    if padded == n_items:
        # Block-aligned item table: a pure reshape, no zero-fill + scatter.
        # The serving micro-batcher calls this once per coalesced batch, so
        # the aligned case is a per-batch copy saved, not a one-off.
        item_blocks = item_factors.reshape(n_blocks, item_block, rank)
    else:
        items_pad = jnp.zeros((padded, rank), dtype=item_factors.dtype)
        items_pad = items_pad.at[:n_items].set(item_factors)
        item_blocks = items_pad.reshape(n_blocks, item_block, rank)

    neg_inf = jnp.asarray(-jnp.inf, dtype=user_factors.dtype)
    init_vals = jnp.full((n_users, k), neg_inf, dtype=user_factors.dtype)
    init_idx = jnp.full((n_users, k), -1, dtype=jnp.int32)

    u_rows = jnp.arange(n_users)[:, None]

    def step(carry, inp):
        top_vals, top_idx = carry
        block_id, block_factors = inp
        start = block_id * item_block
        scores = user_factors @ block_factors.T            # (U, B) on the MXU
        # Mask item-padding tail.
        global_ids = start + jnp.arange(item_block, dtype=jnp.int32)
        scores = jnp.where(global_ids[None, :] < n_items, scores, neg_inf)
        if exclude_idx is not None:
            local = exclude_idx - start                     # (U, E)
            oob = (local < 0) | (local >= item_block) | (exclude_idx < 0)
            local = jnp.where(oob, item_block, local)       # drop out of bounds
            hit = jnp.zeros((n_users, item_block), dtype=bool)
            hit = hit.at[u_rows, local].set(True, mode="drop")
            scores = jnp.where(hit, neg_inf, scores)

        merged_vals = jnp.concatenate([top_vals, scores], axis=1)
        merged_idx = jnp.concatenate(
            [top_idx, jnp.broadcast_to(global_ids[None, :], scores.shape)], axis=1
        )
        new_vals, pos = jax.lax.top_k(merged_vals, k)
        new_idx = jnp.take_along_axis(merged_idx, pos, axis=1)
        return (new_vals, new_idx), None

    (top_vals, top_idx), _ = jax.lax.scan(
        step,
        (init_vals, init_idx),
        (jnp.arange(n_blocks, dtype=jnp.int32), item_blocks),
    )
    return top_vals, top_idx
