"""The full offline chain from one command, with a journal and resume.

``albedo-tpu run_pipeline`` drives the paper's batch-job DAG — popularity ->
ALS -> user/repo profiles -> word2vec -> LR ranker — the way the reference's
Makefile drives its spark-submit targets one by one, but fault-tolerantly:

- every stage is recorded in a per-run JSON **journal**
  (``<tag>-pipeline-journal.json`` in the artifact dir): status
  (``running``/``done``/``failed``), attempt count, wall-clock, the artifact
  names it materialized, and a compact result (rows, AUC, ...);
- ``--resume`` skips stages the journal already marks ``done`` — combined
  with the artifact store's own memoization this makes a rerun after ANY
  crash cheap: completed stages don't even pay an artifact load;
- each stage retries with the shared jittered backoff
  (``utils.retry``) before the pipeline gives up, because transient IO —
  a flaky NFS mount, a preempted colocated job — should cost a retry, not
  the whole chain;
- the ``pipeline.stage`` / ``pipeline.stage.<name>`` fault sites
  (``utils.faults``) let chaos tests kill, delay, or fail any stage
  deterministically.

MLlib pipeline-persistence parity (arxiv 1505.06807): the journal + the
date-keyed artifact store together are the persistence layer — every stage's
product is reloadable by name, and the journal is the pipeline's saved
execution state.
"""

from __future__ import annotations

import argparse
import time
from pathlib import Path
from typing import Callable

from albedo_tpu.cli import register_job
from albedo_tpu.utils import faults
from albedo_tpu.utils.checkpoint import Preempted
from albedo_tpu.utils.jsonio import atomic_write_json, read_json_or_none
from albedo_tpu.utils.retry import RetryPolicy, retry_call

_STAGE_FAULT = faults.site("pipeline.stage")

JOURNAL_NAME = "pipeline-journal.json"


class PipelineStageFailed(RuntimeError):
    """A stage exhausted its retries; the journal holds the failure record."""

    def __init__(self, stage: str, cause: BaseException):
        super().__init__(f"pipeline stage {stage!r} failed: {cause!r}")
        self.stage = stage
        self.cause = cause


# --- stages -------------------------------------------------------------------
# Each stage: fn(ctx) -> (result_dict, artifact_names). Stages lean on the
# artifact store / JobContext memoization, so a resumed or repeated stage is
# a cheap load, and a regenerated (quarantined) artifact is rebuilt here.


def _stage_popularity(ctx) -> tuple[dict, list[str]]:
    from albedo_tpu.datasets.artifacts import load_or_create_df
    from albedo_tpu.datasets.tables import popular_repos

    lo, hi = ctx.star_range()
    name = ctx.artifact_name("popularRepoDF.parquet")
    df = load_or_create_df(name, lambda: popular_repos(ctx.tables().repo_info, lo, hi))
    return {"rows": int(len(df))}, [name]


def _stage_train_als(ctx) -> tuple[dict, list[str]]:
    model = ctx.als_model()
    return {"rank": int(model.rank)}, []


def _stage_user_profile(ctx) -> tuple[dict, list[str]]:
    from albedo_tpu.datasets.artifacts import load_or_create_df

    name = ctx.artifact_name("userProfileDF.parquet")
    df = load_or_create_df(name, lambda: ctx.profiles()[0])
    return {"rows": int(len(df))}, [name]


def _stage_repo_profile(ctx) -> tuple[dict, list[str]]:
    from albedo_tpu.datasets.artifacts import load_or_create_df

    name = ctx.artifact_name("repoProfileDF.parquet")
    df = load_or_create_df(name, lambda: ctx.profiles()[2])
    return {"rows": int(len(df))}, [name]


def _stage_word2vec(ctx) -> tuple[dict, list[str]]:
    model = ctx.word2vec()
    return {"vocab": int(len(model.vocab))}, [ctx.word2vec_artifact_name()]


def _stage_train_lr(ctx) -> tuple[dict, list[str]]:
    ctx.ranker_model()
    auc = ctx._cache.get("ranker_auc")
    return {"auc": float(auc) if auc is not None else None}, []


STAGES: tuple[tuple[str, Callable], ...] = (
    ("popularity", _stage_popularity),
    ("train_als", _stage_train_als),
    ("user_profile", _stage_user_profile),
    ("repo_profile", _stage_repo_profile),
    ("word2vec", _stage_word2vec),
    ("train_lr", _stage_train_lr),
)


# --- the journal --------------------------------------------------------------


def _empty_journal(tag: str) -> dict:
    return {"tag": tag, "status": "running", "stages": {}, "updated_at": time.time()}


def load_journal(path: Path) -> dict | None:
    return read_json_or_none(path)


def _save_journal(path: Path, journal: dict) -> None:
    journal["updated_at"] = time.time()
    atomic_write_json(path, journal, indent=2)


# --- the driver ---------------------------------------------------------------


def run_pipeline(
    ctx,
    *,
    resume: bool = False,
    stages: list[str] | None = None,
    max_stage_attempts: int = 3,
    policy: RetryPolicy | None = None,
    sleeper: Callable[[float], None] = time.sleep,
    verbose: bool = True,
) -> dict:
    """Run the offline chain; returns the final journal dict.

    ``resume=True`` skips stages already ``done`` in the journal. A stage
    that exhausts its retries marks the journal ``failed`` (persisted) and
    raises :class:`PipelineStageFailed` — the rerun story is
    ``run_pipeline --resume``.
    """
    from albedo_tpu.datasets.artifacts import artifact_path

    journal_path = artifact_path(ctx.artifact_name(JOURNAL_NAME))
    journal = (load_journal(journal_path) if resume else None) or _empty_journal(ctx.tag)
    journal["status"] = "running"

    selected = [(n, fn) for n, fn in STAGES if stages is None or n in stages]
    if stages is not None:
        unknown = set(stages) - {n for n, _ in STAGES}
        if unknown:
            raise ValueError(f"unknown pipeline stages: {sorted(unknown)}")

    policy = policy or RetryPolicy(
        max_attempts=max_stage_attempts, base_s=0.5, max_delay_s=30.0
    )
    for name, fn in selected:
        record = journal["stages"].get(name)
        if resume and record and record.get("status") == "done":
            if verbose:
                print(f"[run_pipeline] {name}: already done, skipping (resume)")
            continue
        record = {
            "status": "running",
            "attempts": 0,
            "started_at": time.time(),
            "finished_at": None,
            "artifacts": [],
            "result": None,
            "error": None,
        }
        journal["stages"][name] = record
        _save_journal(journal_path, journal)

        def attempt(name=name, fn=fn, record=record):
            record["attempts"] += 1
            _STAGE_FAULT.hit()
            faults.hit(f"pipeline.stage.{name}")
            return fn(ctx)

        t0 = time.time()
        try:
            result, artifacts = retry_call(
                attempt, policy=policy, site=f"pipeline.{name}",
                sleeper=sleeper,
                # A preemption notice is NOT a transient failure: retrying
                # would restart training under a scheduler that is about to
                # hard-kill us. Let it propagate for the CLI's exit-75 path.
                retry_on=lambda e: not isinstance(e, Preempted),
            )
        except Preempted:
            record.update(status="preempted", finished_at=time.time())
            journal["status"] = "preempted"
            _save_journal(journal_path, journal)
            raise  # cli.main maps this to exit 75; --resume continues
        except Exception as e:  # noqa: BLE001 — journal the failure, then raise
            record.update(status="failed", error=repr(e), finished_at=time.time())
            journal["status"] = "failed"
            _save_journal(journal_path, journal)
            raise PipelineStageFailed(name, e) from e
        record.update(
            status="done", result=result, artifacts=artifacts,
            finished_at=time.time(), error=None,
        )
        _save_journal(journal_path, journal)
        if verbose:
            print(
                f"[run_pipeline] {name}: done in {time.time() - t0:.1f}s "
                f"(attempts={record['attempts']}, result={result})"
            )

    # "complete" is a statement about the WHOLE chain — a --stages subset run
    # that finished cleanly but skipped stages is "partial", so journal
    # consumers can't mistake a popularity-only run for a trained pipeline.
    journal["status"] = (
        "complete"
        if all(
            journal["stages"].get(n, {}).get("status") == "done" for n, _ in STAGES
        )
        else "partial"
    )
    _save_journal(journal_path, journal)
    return journal


@register_job("run_pipeline")
def run_pipeline_job(args) -> int | None:
    """The one-command offline chain (see module docstring).

    Extra flags: --stages a,b,c (subset, in canonical order),
    --max-stage-attempts N (default 3). Honors the global --resume,
    --checkpoint-every/--keep-last (ALS mid-fit checkpoints), --small,
    --tables.
    """
    from albedo_tpu.builders.jobs import JobContext

    extra = argparse.ArgumentParser()
    extra.add_argument("--stages", default="")
    extra.add_argument("--max-stage-attempts", type=int, default=3)
    ns, _ = extra.parse_known_args(getattr(args, "_rest", []))

    t0 = time.time()
    ctx = JobContext(args)
    stages = [s for s in ns.stages.split(",") if s] or None
    try:
        journal = run_pipeline(
            ctx,
            resume=bool(getattr(args, "resume", False)),
            stages=stages,
            max_stage_attempts=ns.max_stage_attempts,
        )
    except PipelineStageFailed as e:
        print(f"[run_pipeline] FAILED: {e} (journal has the record; rerun "
              f"with --resume to retry from there)")
        return 1
    done = [n for n, r in journal["stages"].items() if r["status"] == "done"]
    print(f"[run_pipeline] stages complete = {len(done)}/{len(journal['stages'])}")
    print(f"[run_pipeline] wall-clock = {time.time() - t0:.1f}s")
    return None
