"""The full offline chain from one command, with a journal and resume.

``albedo-tpu run_pipeline`` drives the paper's batch-job DAG — validated
ingest -> popularity -> ALS -> user/repo profiles -> word2vec -> LR ranker
-> canary publish gate — the way the reference's Makefile drives its
spark-submit targets one by one, but fault-tolerantly:

- every stage is recorded in a per-run JSON **journal**
  (``<tag>-pipeline-journal.json`` in the artifact dir): status
  (``running``/``done``/``failed``), attempt count, wall-clock, the artifact
  names it materialized, and a compact result (rows, AUC, ...);
- ``--resume`` skips stages the journal already marks ``done`` — combined
  with the artifact store's own memoization this makes a rerun after ANY
  crash cheap: completed stages don't even pay an artifact load;
- each stage retries with the shared jittered backoff
  (``utils.retry``) before the pipeline gives up, because transient IO —
  a flaky NFS mount, a preempted colocated job — should cost a retry, not
  the whole chain;
- the ``pipeline.stage`` / ``pipeline.stage.<name>`` fault sites
  (``utils.faults``) let chaos tests kill, delay, or fail any stage
  deterministically.

MLlib pipeline-persistence parity (arxiv 1505.06807): the journal + the
date-keyed artifact store together are the persistence layer — every stage's
product is reloadable by name, and the journal is the pipeline's saved
execution state.
"""

from __future__ import annotations

import argparse
import time
from pathlib import Path
from typing import Callable

from albedo_tpu.cli import EXIT_FAILURE, EXIT_REJECTED, register_job
from albedo_tpu.utils import faults
from albedo_tpu.utils.checkpoint import Preempted
from albedo_tpu.utils.jsonio import atomic_write_json, read_json_or_none
from albedo_tpu.utils.retry import RetryPolicy, default_retry_predicate, retry_call

_STAGE_FAULT = faults.site("pipeline.stage")
# The publish quality gate's own site: fires inside the canary evaluation so
# chaos drills can fail the GATE (not just the stage wrapper) deterministically.
_CANARY_FAULT = faults.site("pipeline.canary")

JOURNAL_NAME = "pipeline-journal.json"

# Canary gate defaults: a candidate must score at least this fraction of the
# last-known-good artifact's recorded canary score to publish.
CANARY_TOLERANCE = 0.10


class PipelineStageFailed(RuntimeError):
    """A stage exhausted its retries; the journal holds the failure record."""

    def __init__(self, stage: str, cause: BaseException):
        super().__init__(f"pipeline stage {stage!r} failed: {cause!r}")
        self.stage = stage
        self.cause = cause


class PublishRejected(RuntimeError):
    """The canary quality gate refused to publish the trained artifact.

    Deliberately NOT a stage *failure*: the chain ran to completion and the
    journal says so — the artifact just isn't good enough to stamp. The CLI
    maps this to exit code 4 (distinct from 1 = crash and 75 = preempted)
    so schedulers can tell "retrain/investigate" from "rerun".
    """

    def __init__(self, detail: str, score: float | None = None,
                 baseline: float | None = None):
        super().__init__(detail)
        self.detail = detail
        self.score = score
        self.baseline = baseline


# --- stages -------------------------------------------------------------------
# Each stage: fn(ctx) -> (result_dict, artifact_names). Stages lean on the
# artifact store / JobContext memoization, so a resumed or repeated stage is
# a cheap load, and a regenerated (quarantined) artifact is rebuilt here.


def _stage_ingest(ctx) -> tuple[dict, list[str]]:
    """The data-quality firewall pass: build the validated star matrix
    (``datasets.validate`` runs inside ``validated_star_matrix`` under the
    job's ``--data-policy``), journal the per-rule violation counts and the
    quarantine sidecar name. Under ``strict`` a dirty dataset fails the
    pipeline HERE, before any accelerator time is spent."""
    ctx.matrix()
    report = ctx.data_report()
    result = report.to_dict()
    artifacts = [report.quarantined_to] if report.quarantined_to else []
    return result, artifacts


def _stage_popularity(ctx) -> tuple[dict, list[str]]:
    from albedo_tpu.datasets.artifacts import load_or_create_df
    from albedo_tpu.datasets.tables import popular_repos

    lo, hi = ctx.star_range()
    name = ctx.artifact_name("popularRepoDF.parquet")
    df = load_or_create_df(name, lambda: popular_repos(ctx.tables().repo_info, lo, hi))
    return {"rows": int(len(df))}, [name]


def _stage_train_als(ctx) -> tuple[dict, list[str]]:
    model = ctx.als_model()
    return {"rank": int(model.rank)}, []


def _stage_user_profile(ctx) -> tuple[dict, list[str]]:
    from albedo_tpu.datasets.artifacts import load_or_create_df

    name = ctx.artifact_name("userProfileDF.parquet")
    df = load_or_create_df(name, lambda: ctx.profiles()[0])
    return {"rows": int(len(df))}, [name]


def _stage_repo_profile(ctx) -> tuple[dict, list[str]]:
    from albedo_tpu.datasets.artifacts import load_or_create_df

    name = ctx.artifact_name("repoProfileDF.parquet")
    df = load_or_create_df(name, lambda: ctx.profiles()[2])
    return {"rows": int(len(df))}, [name]


def _stage_word2vec(ctx) -> tuple[dict, list[str]]:
    model = ctx.word2vec()
    return {"vocab": int(len(model.vocab))}, [ctx.word2vec_artifact_name()]


def _stage_train_lr(ctx) -> tuple[dict, list[str]]:
    ctx.ranker_model()
    auc = ctx._cache.get("ranker_auc")
    return {"auc": float(auc) if auc is not None else None}, []


def _canary_score(ctx) -> float:
    """NDCG@30 of the trained ALS artifact on the held-out probe slice (the
    deterministic test-user sample + canary user every builder evaluates)."""
    from albedo_tpu.recommenders import ALSRecommender

    model = ctx.als_model()
    matrix = ctx.matrix()
    users = matrix.user_ids[ctx.test_user_dense(150)]
    frame = ALSRecommender(model, matrix, top_k=30).recommend_for_users(users)
    return float(ctx.evaluate_topk(frame))


def last_known_good(ctx) -> tuple[str, float] | None:
    """(artifact name, canary score) of the newest stamped flagship artifact
    for this dataset tag AND hyperparameter key, or None when nothing was
    ever published. Keying on ``als_artifact_name`` (rank/reg/alpha/iters/
    solver baked in) keeps the gate honest: a ``--small`` rank-16 run must
    not be judged against a rank-50 stamp's score — different configs have
    different legitimate baselines."""
    from albedo_tpu.datasets import artifacts as store

    art_dir = store.get_settings().artifact_dir
    best: tuple[float, str, float] | None = None
    for mpath in art_dir.glob(f"{ctx.als_artifact_name()}*{store.META_SUFFIX}"):
        if ".corrupt-" in mpath.name:
            continue
        meta = store.read_meta(art_dir / mpath.name[: -len(store.META_SUFFIX)])
        if not meta:
            continue
        score = (meta.get("canary") or {}).get("score")
        if score is None:
            continue
        stamped = float(meta.get("stamped_at", 0.0))
        if best is None or stamped > best[0]:
            best = (stamped, str(meta.get("artifact", mpath.name)), float(score))
    return None if best is None else (best[1], best[2])


def _stage_canary(ctx) -> tuple[dict, list[str]]:
    """The publish quality gate: score the trained artifact on the probe
    slice, compare against the last-known-good stamp (and an optional
    absolute floor), and only then stamp the artifact with its lineage +
    quality record (``.meta.json``) — the serving reload's stamp gate
    refuses anything unstamped or regressed, so a bad model can finish
    training yet never reach the swap path.

    ``--publish-force`` publishes past a failed gate, loudly: the journal
    and the stamp both carry ``forced: true``.
    """
    from albedo_tpu.datasets import artifacts as store
    from albedo_tpu.datasets.validate import matrix_fingerprint
    from albedo_tpu.utils import events

    score = _canary_score(ctx)
    _CANARY_FAULT.hit()
    floor = float(getattr(ctx.args, "canary_floor", 0.0) or 0.0)
    tolerance = getattr(ctx.args, "canary_tolerance", None)
    tolerance = CANARY_TOLERANCE if tolerance is None else float(tolerance)
    force = bool(getattr(ctx.args, "publish_force", False))

    lkg = last_known_good(ctx)
    baseline = None if lkg is None else lkg[1]
    failures = []
    if score < floor:
        failures.append(f"score {score:.5f} below --canary-floor {floor:.5f}")
    if baseline is not None and score < baseline * (1.0 - tolerance):
        failures.append(
            f"score {score:.5f} regressed more than {tolerance:.0%} below "
            f"last-known-good {baseline:.5f} ({lkg[0]})"
        )
    passed = not failures
    result = {
        "metric": "ndcg@30",
        "score": round(score, 6),
        "baseline": None if baseline is None else round(baseline, 6),
        "passed": passed,
        "forced": bool(force and not passed),
    }
    if not passed:
        if not force:
            # Counted only on an actual refusal — a forced publish DID
            # publish (the override stays visible via forced: true in the
            # stamp/journal), and the reload stamp gate counts the same way.
            events.publish_rejected.inc(gate="canary")
            raise PublishRejected("; ".join(failures), score=score, baseline=baseline)
        # Loud by design: a forced publish must be unmissable in the logs
        # and permanently recorded in both the journal and the stamp.
        print(f"[run_pipeline] !!! CANARY GATE OVERRIDDEN (--publish-force): "
              f"{'; '.join(failures)} — publishing anyway")

    report = ctx.data_report()
    path = store.artifact_path(ctx.als_artifact_name())
    store.write_meta(path, {
        "lineage": {
            "data_hash": matrix_fingerprint(ctx.matrix()),
            "rows": {
                "in": report.rows_in, "out": report.rows_out,
                "n_users": int(ctx.matrix().n_users),
                "n_items": int(ctx.matrix().n_items),
                "nnz": int(ctx.matrix().nnz),
            },
            "quarantined": report.violations,
            "policy": report.policy,
        },
        "watchdog": {
            "trips": list(ctx._cache.get("watchdog_trips", [])),
        },
        "canary": result,
    })
    return result, [store.meta_path(path).name]


STAGES: tuple[tuple[str, Callable], ...] = (
    ("ingest", _stage_ingest),
    ("popularity", _stage_popularity),
    ("train_als", _stage_train_als),
    ("user_profile", _stage_user_profile),
    ("repo_profile", _stage_repo_profile),
    ("word2vec", _stage_word2vec),
    ("train_lr", _stage_train_lr),
    ("canary", _stage_canary),
)


# --- the journal --------------------------------------------------------------


def _empty_journal(tag: str) -> dict:
    return {"tag": tag, "status": "running", "stages": {}, "updated_at": time.time()}


def load_journal(path: Path) -> dict | None:
    return read_json_or_none(path)


def _save_journal(path: Path, journal: dict) -> None:
    journal["updated_at"] = time.time()
    atomic_write_json(path, journal, indent=2)


# --- the driver ---------------------------------------------------------------


def run_pipeline(
    ctx,
    *,
    resume: bool = False,
    stages: list[str] | None = None,
    max_stage_attempts: int = 3,
    policy: RetryPolicy | None = None,
    sleeper: Callable[[float], None] = time.sleep,
    verbose: bool = True,
) -> dict:
    """Run the offline chain; returns the final journal dict.

    ``resume=True`` skips stages already ``done`` in the journal. A stage
    that exhausts its retries marks the journal ``failed`` (persisted) and
    raises :class:`PipelineStageFailed` — the rerun story is
    ``run_pipeline --resume``.
    """
    from albedo_tpu.datasets.artifacts import artifact_path

    journal_path = artifact_path(ctx.artifact_name(JOURNAL_NAME))
    journal = (load_journal(journal_path) if resume else None) or _empty_journal(ctx.tag)
    journal["status"] = "running"

    selected = [(n, fn) for n, fn in STAGES if stages is None or n in stages]
    if stages is not None:
        unknown = set(stages) - {n for n, _ in STAGES}
        if unknown:
            raise ValueError(f"unknown pipeline stages: {sorted(unknown)}")

    policy = policy or RetryPolicy(
        max_attempts=max_stage_attempts, base_s=0.5, max_delay_s=30.0
    )
    for name, fn in selected:
        record = journal["stages"].get(name)
        if resume and record and record.get("status") == "done":
            if verbose:
                print(f"[run_pipeline] {name}: already done, skipping (resume)")
            continue
        record = {
            "status": "running",
            "attempts": 0,
            "started_at": time.time(),
            "finished_at": None,
            "artifacts": [],
            "result": None,
            "error": None,
        }
        journal["stages"][name] = record
        _save_journal(journal_path, journal)

        def attempt(name=name, fn=fn, record=record):
            record["attempts"] += 1
            _STAGE_FAULT.hit()
            faults.hit(f"pipeline.stage.{name}")
            return fn(ctx)

        t0 = time.time()
        try:
            result, artifacts = retry_call(
                attempt, policy=policy, site=f"pipeline.{name}",
                sleeper=sleeper,
                # A preemption notice is NOT a transient failure: retrying
                # would restart training under a scheduler that is about to
                # hard-kill us. A canary-gate refusal is a VERDICT — the
                # same artifact would score the same again. And a device OOM
                # re-OOMs identically: burning the backoff budget re-crashing
                # the device delays the capacity degrade path. All propagate.
                retry_on=lambda e: (
                    not isinstance(e, (Preempted, PublishRejected))
                    and default_retry_predicate(e)
                ),
            )
        except Preempted:
            record.update(status="preempted", finished_at=time.time())
            journal["status"] = "preempted"
            _save_journal(journal_path, journal)
            raise  # cli.main maps this to exit 75; --resume continues
        except PublishRejected as e:
            record.update(
                status="rejected", finished_at=time.time(),
                error=str(e),
                result={"score": e.score, "baseline": e.baseline, "passed": False},
            )
            journal["status"] = "rejected"
            _save_journal(journal_path, journal)
            raise  # run_pipeline_job maps this to exit 4
        except Exception as e:  # noqa: BLE001 — journal the failure, then raise
            record.update(status="failed", error=repr(e), finished_at=time.time())
            journal["status"] = "failed"
            _save_journal(journal_path, journal)
            raise PipelineStageFailed(name, e) from e
        record.update(
            status="done", result=result, artifacts=artifacts,
            finished_at=time.time(), error=None,
        )
        _save_journal(journal_path, journal)
        if verbose:
            print(
                f"[run_pipeline] {name}: done in {time.time() - t0:.1f}s "
                f"(attempts={record['attempts']}, result={result})"
            )

    # "complete" is a statement about the WHOLE chain — a --stages subset run
    # that finished cleanly but skipped stages is "partial", so journal
    # consumers can't mistake a popularity-only run for a trained pipeline.
    journal["status"] = (
        "complete"
        if all(
            journal["stages"].get(n, {}).get("status") == "done" for n, _ in STAGES
        )
        else "partial"
    )
    _save_journal(journal_path, journal)
    return journal


@register_job("run_pipeline")
def run_pipeline_job(args) -> int | None:
    """The one-command offline chain (see module docstring).

    Extra flags: --stages a,b,c (subset, in canonical order),
    --max-stage-attempts N (default 3), --canary-floor SCORE (absolute
    NDCG@30 minimum for the publish gate), --canary-tolerance FRAC (max
    allowed regression vs the last-known-good stamp, default 0.10),
    --publish-force (publish past a failed canary gate, loudly journaled).
    Honors the global --resume, --data-policy,
    --checkpoint-every/--keep-last (ALS mid-fit checkpoints), --small,
    --tables. Exit codes: 0 ok, 1 stage failure, 4 canary gate refused the
    publish, 75 preempted.
    """
    from albedo_tpu.builders.jobs import JobContext

    extra = argparse.ArgumentParser()
    extra.add_argument("--stages", default="")
    extra.add_argument("--max-stage-attempts", type=int, default=3)
    extra.add_argument("--canary-floor", type=float, default=0.0)
    extra.add_argument("--canary-tolerance", type=float, default=None)
    extra.add_argument("--publish-force", action="store_true")
    ns, _ = extra.parse_known_args(getattr(args, "_rest", []))
    # The canary stage reads its knobs off the shared args namespace.
    args.canary_floor = ns.canary_floor
    args.canary_tolerance = ns.canary_tolerance
    args.publish_force = ns.publish_force

    t0 = time.time()
    ctx = JobContext(args)
    stages = [s for s in ns.stages.split(",") if s] or None
    try:
        journal = run_pipeline(
            ctx,
            resume=bool(getattr(args, "resume", False)),
            stages=stages,
            max_stage_attempts=ns.max_stage_attempts,
        )
    except PublishRejected as e:
        print(f"[run_pipeline] PUBLISH REFUSED by the canary gate: {e} "
              f"(artifact trained but NOT stamped; --publish-force overrides)")
        return EXIT_REJECTED
    except PipelineStageFailed as e:
        print(f"[run_pipeline] FAILED: {e} (journal has the record; rerun "
              f"with --resume to retry from there)")
        return EXIT_FAILURE
    done = [n for n, r in journal["stages"].items() if r["status"] == "done"]
    print(f"[run_pipeline] stages complete = {len(done)}/{len(journal['stages'])}")
    print(f"[run_pipeline] wall-clock = {time.time() - t0:.1f}s")
    return None
