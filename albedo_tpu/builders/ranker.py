"""The second-stage logistic-regression ranker: feature pipeline, negative
sampling, weighted LR, candidate fusion, re-ranking.

Reference parity: ``LogisticRegressionRanker.scala:21-447`` (call stack traced
in SURVEY.md §3.2):

1. reduced starring (users with <= maxStarredReposCount stars, :137-149)
2. profile joins (:151-154)
3. ~30-stage feature pipeline (:161-235): cross features, ALS score column,
   StringIndexer per categorical INCLUDING user_id/repo_id, CountVectorizer per
   list column, tokenizer+stopwords+Word2Vec per text column, vector assembly
4. NegativeBalancer on popular-minus-positives (:244-267)
5. weight SQL + weighted LR maxIter=300 regParam=0.7 (:316-350)
6. AUC (:354-364); candidate fusion from ALS+curation+popularity (:368-404);
   re-rank by P(star); NDCG@30 (:430-444)

The feature target is the block ``FeatureMatrix`` (gathers + segment sums on
TPU) rather than million-wide one-hot vectors — same math, MXU-friendly.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np
import pandas as pd

from albedo_tpu.builders.profiles import FeatureColumns
from albedo_tpu.datasets.star_matrix import StarMatrix
from albedo_tpu.datasets.tables import RawTables, popular_repos
from albedo_tpu.evaluators import RankingEvaluator, area_under_roc, user_actual_items, user_items_from_pairs
from albedo_tpu.features import (
    CountVectorizer,
    FeatureAssembler,
    InstanceWeigher,
    NegativeBalancer,
    Pipeline,
    StringIndexer,
    StopWordsRemover,
    Tokenizer,
    Transformer,
    UserRepoTransformer,
)
from albedo_tpu.features.assembler import FeatureAssemblerModel, FeatureMatrix
from albedo_tpu.features.pipeline import PipelineModel
from albedo_tpu.models.als import ALSModel
from albedo_tpu.models.logistic_regression import LogisticRegression, LogisticRegressionModel
from albedo_tpu.models.word2vec import Word2VecModel
from albedo_tpu.recommenders.base import Recommender, fuse_candidates


class ALSScorer(Transformer):
    """ALSModel as a feature stage: adds ``als_score`` = user.item factor dot.

    Parity: the loaded ``ALSModel`` with ``setPredictionCol("als_score")`` and
    ``coldStartStrategy="drop"`` inside the feature pipeline
    (``LogisticRegressionRanker.scala:167-174``) — rows whose user or repo the
    factorization never saw are DROPPED (both here and at re-rank time).
    """

    def __init__(
        self,
        model: ALSModel,
        matrix: StarMatrix,
        user_col: str = "user_id",
        item_col: str = "repo_id",
        output_col: str = "als_score",
        cold_start: str = "drop",
    ):
        self.model = model
        self.matrix = matrix
        self.user_col = user_col
        self.item_col = item_col
        self.output_col = output_col
        self.cold_start = cold_start

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        self.require_cols(df, [self.user_col, self.item_col])
        rows = self.matrix.users_of(df[self.user_col].to_numpy(np.int64))
        cols = self.matrix.items_of(df[self.item_col].to_numpy(np.int64))
        known = (rows >= 0) & (cols >= 0)
        score = np.zeros(len(df), dtype=np.float32)
        score[known] = self.model.predict(rows[known], cols[known])
        out = df.copy()
        out[self.output_col] = score
        if self.cold_start == "drop":
            out = out[known].reset_index(drop=True)
        return out


@dataclasses.dataclass
class RankerConfig:
    """Hyperparameters, reference defaults in comments."""

    max_starred_repos_count: int = 4000   # :132 (30 in laptop mode)
    negative_positive_ratio: float = 1.0  # :246
    lr_max_iter: int = 300                # :331
    lr_reg_param: float = 0.7             # :332
    weight_col: str = "positive_starred_weight"  # :336
    test_ratio: float = 0.05              # :297 (0.3 in laptop mode)
    n_test_users: int = 200               # :309
    top_k: int = 30                       # :430
    min_df: int = 10                      # CountVectorizer minDF, :195
    max_bag_pad: int = 256
    popular_min_stars: int = 1000         # loadPopularRepoDF range
    popular_max_stars: int = 290_000
    seed: int = 42

    def small(self) -> "RankerConfig":
        """Laptop-mode shrink (the RUN_WITH_INTELLIJ switch, :24-34,133,297)."""
        return dataclasses.replace(
            self, max_starred_repos_count=30, test_ratio=0.3, lr_max_iter=50
        )


@dataclasses.dataclass
class RankerModel:
    """Everything needed to score (user, repo) candidates."""

    feature_pipeline: PipelineModel
    assembler: FeatureAssemblerModel
    lr_model: LogisticRegressionModel
    user_profile: pd.DataFrame
    repo_profile: pd.DataFrame
    auc: float

    def score(self, candidates: pd.DataFrame) -> pd.DataFrame:
        """Join profiles, run the feature pipeline, return candidates with a
        ``probability`` column (cold pairs dropped, as coldStartStrategy)."""
        df = candidates.merge(self.user_profile, on="user_id").merge(
            self.repo_profile, on="repo_id"
        )
        df = self.feature_pipeline.transform(df)
        fm = self.assembler.assemble(df)
        out = df[[c for c in ("user_id", "repo_id", "score", "source") if c in df.columns]].copy()
        out["probability"] = self.lr_model.predict_proba(fm)
        return out


@dataclasses.dataclass
class RankerResult:
    model: RankerModel
    auc: float
    ndcg: float | None
    n_rows: int = 0  # balanced (positive + sampled-negative) training rows
    # Weight-column CV grid results [(weight_col, auc)], best first, when
    # train_ranker ran with weight_cols (LogisticRegressionRankerCV parity).
    grid: list | None = None


def reduce_starring(starring: pd.DataFrame, max_count: int) -> pd.DataFrame:
    """Drop hyperactive users (> max starred repos), :137-149."""
    counts = starring.groupby("user_id")["repo_id"].transform("size")
    return starring[counts <= max_count].reset_index(drop=True)


def build_feature_pipeline(
    als_scorer: ALSScorer,
    user_cols: FeatureColumns,
    repo_cols: FeatureColumns,
    w2v: Word2VecModel,
    min_df: int,
) -> tuple[Pipeline, dict]:
    """The ~30-stage feature pipeline (:161-235). Returns (pipeline, assembler
    column spec): categorical -> StringIndexer; list -> CountVectorizer;
    text -> Tokenizer -> StopWordsRemover -> Word2Vec vector."""
    stages: list = [UserRepoTransformer(), als_scorer]

    categorical = [*user_cols.categorical, *repo_cols.categorical, "user_id", "repo_id"]
    cat_out = []
    for col in categorical:
        stages.append(StringIndexer(col, f"{col}__idx"))
        cat_out.append(f"{col}__idx")

    bag_out = []
    for col in [*user_cols.list_, *repo_cols.list_]:
        stages.append(CountVectorizer(col, f"{col}__cv", min_df=min_df))
        bag_out.append(f"{col}__cv")

    vec_out = []
    for col in [*user_cols.text, *repo_cols.text]:
        # Tokenizer -> StopWordsRemover staging as the reference (:200-216);
        # stop-word removal happens in the remover stage, not both.
        stages.append(Tokenizer(col, f"{col}__words", remove_stop_words=False))
        stages.append(StopWordsRemover(f"{col}__words", f"{col}__filtered"))
        w2v_stage = dataclasses.replace(
            w2v, input_col=f"{col}__filtered", output_col=f"{col}__w2v"
        )
        stages.append(w2v_stage)
        vec_out.append(f"{col}__w2v")

    dense = [
        *user_cols.boolean, *repo_cols.boolean,
        *user_cols.continuous, *repo_cols.continuous,
        "repo_language_index_in_user_recent_repo_languages",
        "repo_language_count_in_user_recent_repo_languages",
        "als_score",
    ]
    spec = {
        "dense_cols": dense,
        "vector_cols": vec_out,
        "cat_cols": {c: None for c in cat_out},
        "bag_cols": {c: None for c in bag_out},
    }
    return Pipeline(stages), spec


def train_ranker(
    tables: RawTables,
    user_profile: pd.DataFrame,
    user_cols: FeatureColumns,
    repo_profile: pd.DataFrame,
    repo_cols: FeatureColumns,
    als_model: ALSModel,
    matrix: StarMatrix,
    w2v: Word2VecModel,
    now: float,
    config: RankerConfig = RankerConfig(),
    recommenders: Sequence[Recommender] | None = None,
    eval_actual: "UserItems | None" = None,
    timer=None,
    weight_cols: Sequence[str] | None = None,
    grid_mesh=None,
    lr_mesh=None,
) -> RankerResult:
    """End-to-end ranker training + evaluation (SURVEY.md §3.2).

    ``timer`` (``albedo_tpu.utils.profiling.Timer``) if given records per-stage
    wall-clock — the bench's stage breakdown vs the reference's 1h35m job
    (``Makefile:209``).

    ``weight_cols`` switches the LR stage into CV-grid mode
    (``LogisticRegressionRankerCV.scala:326-332``): the SHARED featurized set
    is fit once per weight column in a single vmapped L-BFGS solve
    (optionally grid-sharded over ``grid_mesh``), each scored by AUC; the best
    column's model continues into fusion/NDCG and the full grid is returned.

    ``lr_mesh`` lays the LR training batch out row-sharded over the mesh's
    data axis (``parallel.lr``) — the end-to-end sharded ranker path: XLA
    inserts the ICI psums that replace MLlib LR's gradient treeAggregate.
    """
    rng = np.random.default_rng(config.seed)
    if timer is None:
        from albedo_tpu.utils.profiling import Timer

        timer = Timer()

    # 1-2. Reduce + negative-sample + profile joins. The reference featurizes
    # the positives first to FIT the pipeline (:237-240), then transforms the
    # balanced set; vocab-fitting on positives only is preserved here.
    with timer.section("reduce_join"):
        reduced = reduce_starring(tables.starring, config.max_starred_repos_count)
        profile_starring = reduced.merge(user_profile, on="user_id").merge(
            repo_profile, on="repo_id"
        )

    with timer.section("pipeline_fit"):
        als_scorer = ALSScorer(als_model, matrix)
        pipeline, spec = build_feature_pipeline(
            als_scorer, user_cols, repo_cols, w2v, config.min_df
        )
        feature_model = pipeline.fit(profile_starring)

    # 4. Negative balancing on the reduced starring, then profile join +
    # featurize (:244-291).
    with timer.section("negative_balance"):
        pop = popular_repos(
            tables.repo_info, config.popular_min_stars, config.popular_max_stars
        )
        balancer = NegativeBalancer(
            pop["repo_id"].to_numpy(np.int64),
            negative_positive_ratio=config.negative_positive_ratio,
        )
        balanced = balancer.transform(reduced)
        profile_balanced = balanced.merge(user_profile, on="user_id").merge(
            repo_profile, on="repo_id"
        )
    with timer.section("featurize"):
        featured = feature_model.transform(profile_balanced)

    with timer.section("assembler_fit"):
        assembler = FeatureAssembler(**spec, max_bag_pad=config.max_bag_pad).fit(featured)

    # 5. Split, weigh, train LR (:297-350).
    with timer.section("weigh_assemble"):
        is_test = rng.random(len(featured)) < config.test_ratio
        train_df = featured[~is_test].reset_index(drop=True)
        test_df = featured[is_test].reset_index(drop=True)

        weigher = InstanceWeigher(now=now)
        train_w = weigher.transform(train_df)
        fm_train = assembler.assemble(train_w)
    grid = None
    with timer.section("lr_fit"):
        lr = LogisticRegression(
            max_iter=config.lr_max_iter, reg_param=config.lr_reg_param,
            # CV-grid mode shards the GRID axis (grid_mesh); a row-sharded
            # batch on top of that is unsupported by fit_many.
            mesh=None if weight_cols else lr_mesh,
        )
        labels = train_w["starring"].to_numpy(np.float32)
        if not weight_cols:
            lr_model = lr.fit(
                fm_train, labels,
                sample_weight=train_w[config.weight_col].to_numpy(np.float32),
            )
            first_model = lr_model
        else:
            ws = np.stack(
                [train_w[c].to_numpy(np.float32) for c in weight_cols]
            )
            grid_models = lr.fit_many(fm_train, labels, ws, grid_mesh=grid_mesh)
            first_model = grid_models[0]
    # Re-attribute the lr_fit stage into its real parts (VERDICT r4 #1: the
    # r4 stage conflated them and read as 63% of the ranker wall-clock):
    # lr_prepare = host batch layout + standardization moments + upload
    # dispatch; lr_compile = one-time XLA compile (0 on a warm executable
    # cache); lr_fit = the device L-BFGS solve. In grid mode the split comes
    # from grid_models[0], and prepare/compile are SHARED by the whole
    # vmapped solve — they are not per-model costs.
    for part, name in ((first_model.prep_s, "lr_prepare"),
                       (first_model.compile_s, "lr_compile")):
        if part is not None:
            timer.totals["lr_fit"] -= part
            timer.totals[name] = timer.totals.get(name, 0.0) + part
            timer.counts[name] = timer.counts.get(name, 0) + 1
    # The parts were measured by perf_counter scopes inside fit() while the
    # stage total came from the timer's own clock scope: tiny overlaps can
    # drive the residual slightly negative — clamp at 0 (ADVICE r5 #4).
    timer.totals["lr_fit"] = max(0.0, timer.totals["lr_fit"])

    # 6a. AUC on the held-out split (:354-364).
    with timer.section("auc_eval"):
        fm_test = assembler.assemble(test_df)
        test_labels = test_df["starring"].to_numpy(np.float32)
        if not weight_cols:
            auc = area_under_roc(test_labels, lr_model.predict_proba(fm_test))
        else:
            scored = [
                (col, float(area_under_roc(test_labels, m.predict_proba(fm_test))), m)
                for col, m in zip(weight_cols, grid_models)
            ]
            scored.sort(key=lambda t: -t[1])
            grid = [(col, auc_g) for col, auc_g, _ in scored]
            _, auc, lr_model = scored[0]

    model = RankerModel(
        feature_pipeline=feature_model,
        assembler=assembler,
        lr_model=lr_model,
        user_profile=user_profile,
        repo_profile=repo_profile,
        auc=float(auc),
    )

    # 6b. Candidate fusion + re-rank + NDCG@30 (:368-444).
    ndcg = None
    if recommenders:
        with timer.section("fuse_rerank_ndcg"):
            test_users = test_df["user_id"].unique()
            take = min(config.n_test_users, len(test_users))
            sampled = rng.choice(test_users, size=take, replace=False)
            candidates = fuse_candidates(
                [r.recommend_for_users(sampled) for r in recommenders]
            )
            scored = model.score(candidates)
            dense_users = matrix.users_of(scored["user_id"].to_numpy(np.int64))
            predicted = user_items_from_pairs(
                dense_users,
                matrix.items_of(scored["repo_id"].to_numpy(np.int64)),
                order_key=scored["probability"].to_numpy(np.float64),
                k=config.top_k,
            )
            actual = eval_actual if eval_actual is not None else user_actual_items(matrix, k=config.top_k)
            ndcg = RankingEvaluator(metric_name="ndcg@k", k=config.top_k).evaluate(
                predicted, actual
            )

    return RankerResult(
        model=model, auc=float(auc), ndcg=ndcg, n_rows=len(train_df), grid=grid
    )
