"""User and repo profile ETL.

Reference parity: ``UserProfileBuilder.scala:12-230`` and
``RepoProfileBuilder.scala:10-179`` — impute, clean, keyword flags, ratios,
date diffs, per-user recent top-50 lists, frequency binning. Host-side
pandas/numpy (the reference runs this on Spark executors; it is dataframe ETL,
not device compute — SURVEY.md §7 step 7). Each profile also returns its
feature-bucket column lists (boolean/continuous/categorical/list/text), the
five buckets the builders track (``UserProfileBuilder.scala:45-49``) and the
ranker's feature pipeline consumes.

``now`` is an explicit epoch-seconds argument everywhere the reference calls
``current_date()``, keeping artifacts and tests deterministic.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pandas as pd

from albedo_tpu.datasets.tables import RawTables
from albedo_tpu.features.indexers import FrequencyBinner
from albedo_tpu.text import clean_company, clean_location

_DAY = 86400.0

# Bio keyword groups (UserProfileBuilder.scala:84-98). The reference matches
# with SQL LIKE '%kw%' via Column.like.
_USER_KEYWORD_FLAGS = {
    "user_knows_web": ["web", "fullstack", "full stack"],
    "user_knows_backend": ["backend", "back end", "back-end"],
    "user_knows_frontend": ["frontend", "front end", "front-end"],
    "user_knows_mobile": ["mobile", "ios", "android"],
    "user_knows_devops": ["devops", "sre", "admin", "infrastructure"],
    "user_knows_data": ["machine learning", "deep learning", "data scien", "data analy"],
    "user_knows_recsys": ["data mining", "recommend", "information retrieval"],
    "user_is_lead": ["team lead", "architect", "creator", "director", "cto", "vp of engineering"],
    "user_is_scholar": ["researcher", "scientist", "phd", "professor"],
    "user_is_freelancer": ["freelance"],
    "user_is_junior": ["junior", "beginner", "newbie"],
    "user_is_pm": ["product manager"],
}

# Repo description filters (RepoProfileBuilder.scala:80-98).
_UNMAINTAINED_WORDS = [
    "unmaintained", "no longer maintained", "no longer actively maintained",
    "not maintained", "not actively maintained", "deprecated", "moved to",
]
_ASSIGNMENT_WORDS = ["assignment", "作業", "作业"]
_DEMO_WORDS_EXACT = ["test"]   # LIKE 'test' (no wildcards) = exact match
_DEMO_WORDS = ["demo project"]
_BLOG_WORDS_EXACT = ["my blog"]

VINTA_USER_ID = 652070  # the smoke-canary user (ALSRecommenderBuilder.scala:68)


@dataclasses.dataclass
class FeatureColumns:
    """The five feature buckets a profile contributes."""

    boolean: list[str]
    continuous: list[str]
    categorical: list[str]
    list_: list[str]
    text: list[str]

    def all(self) -> list[str]:
        return self.boolean + self.continuous + self.categorical + self.list_ + self.text


def plain_columns(df: pd.DataFrame) -> pd.DataFrame:
    """Materialize extension-backed columns as plain numpy-dtype columns.

    Arrow-backed columns pay a boxed per-element cost in every downstream
    merge ``take`` and Python iteration; the ranker merges each profile into
    the row set several times (measured 3x faster merges with plain object
    columns at bench scale). Numeric/bool extension columns become their
    numpy equivalents; everything else becomes object.
    """
    out = df.copy()
    for c in out.columns:
        dt = out[c].dtype
        if isinstance(dt, np.dtype):
            continue
        if pd.api.types.is_bool_dtype(dt):
            # NA -> False is intended for the profile flag columns: the
            # reference imputes nulls to "" BEFORE computing its LIKE-based
            # keyword flags (UserProfileBuilder.scala:60-66), so a missing
            # source value is a False flag, not a missing flag.
            out[c] = out[c].to_numpy(dtype=bool, na_value=False)
        elif pd.api.types.is_integer_dtype(dt):
            # Preserve missingness: nullable ints with NAs become float64/NaN
            # (pandas' classic promotion) rather than a fake 0.
            if out[c].isna().any():
                out[c] = out[c].to_numpy(dtype=np.float64, na_value=np.nan)
            else:
                out[c] = out[c].to_numpy(dtype=np.int64)
        elif pd.api.types.is_float_dtype(dt):
            out[c] = out[c].to_numpy(dtype=np.float64, na_value=np.nan)
        else:
            arr = out[c].to_numpy(dtype=object)
            # Arrow LIST columns box each element as an ndarray; keep the
            # list-of-str semantics downstream code (and Spark parity) expects.
            # Full scan, not a first-element sniff: a leading null must not
            # skip conversion for the rest of the column.
            if any(isinstance(v, np.ndarray) for v in arr):
                fixed = np.empty(len(arr), dtype=object)
                fixed[:] = [
                    v.tolist() if isinstance(v, np.ndarray) else v for v in arr
                ]
                arr = fixed
            out[c] = arr
    return out


def _contains_any(series: pd.Series, words: list[str]) -> np.ndarray:
    low = series.str.lower()
    hit = np.zeros(len(series), dtype=bool)
    for w in words:
        hit |= low.str.contains(w, regex=False).to_numpy(dtype=bool)
    return hit


def build_user_profile(
    tables: RawTables,
    now: float,
    recent_k: int = 50,
    company_bin_threshold: int = 5,
    location_bin_threshold: int = 50,
) -> tuple[pd.DataFrame, FeatureColumns]:
    """``UserProfileBuilder`` parity; returns (profile frame, feature buckets).

    Users with no starrings are dropped by the inner joins on the
    starred-count/recent-list aggregations, exactly like the reference's
    ``join(..., Seq("user_id"))`` chain (:146-152).
    """
    u = tables.user_info.copy()
    s = tables.starring
    r = tables.repo_info

    # Impute (the conformed schema already coerces null strings to "", so the
    # has-null flag keys off emptiness of the nullable columns).
    nullable = ["user_name", "user_company", "user_blog", "user_location", "user_bio"]
    u["user_has_null"] = (u[nullable] == "").any(axis=1)

    # Clean.
    u["user_clean_company"] = [clean_company(x) for x in u["user_company"]]
    u["user_clean_location"] = [clean_location(x) for x in u["user_location"]]
    u["user_clean_bio"] = u["user_bio"].str.lower()

    # Keyword flags.
    for col, words in _USER_KEYWORD_FLAGS.items():
        u[col] = _contains_any(u["user_clean_bio"], words)

    # Ratios / datediffs.
    u["user_followers_following_ratio"] = np.round(
        u["user_followers_count"] / (u["user_following_count"] + 1.0), 3
    )
    u["user_days_between_created_at_today"] = np.floor(
        (now - u["user_created_at"]) / _DAY
    )
    u["user_days_between_updated_at_today"] = np.floor(
        (now - u["user_updated_at"]) / _DAY
    )

    # Starred-repos count + per-user recent top-k lists over starred repos
    # (rank() over starred_at desc <= 50; UserProfileBuilder.scala:104-125).
    sr = s.merge(r, on="repo_id", how="inner")
    sr = sr.sort_values(["user_id", "starred_at"], ascending=[True, False], kind="stable")
    counts = s.groupby("user_id").size().rename("user_starred_repos_count")

    recent = sr.groupby("user_id", sort=False).head(recent_k)
    langs = recent.groupby("user_id")["repo_language"].agg(
        lambda col: [x.lower() for x in col]
    ).rename("user_recent_repo_languages")

    with_topics = recent[recent["repo_topics"] != ""]
    topics = with_topics.groupby("user_id")["repo_topics"].agg(
        lambda col: ",".join(x.lower() for x in col).split(",")
    ).rename("user_recent_repo_topics")

    with_desc = recent[recent["repo_description"] != ""]
    descs = with_desc.groupby("user_id")["repo_description"].agg(
        lambda col: " ".join(x.lower() for x in col)
    ).rename("user_recent_repo_descriptions")

    u = (
        u.merge(counts, on="user_id", how="inner")
        .merge(descs, on="user_id", how="inner")
        .merge(topics, on="user_id", how="inner")
        .merge(langs, on="user_id", how="inner")
    )
    u["user_avg_daily_starred_repos_count"] = np.round(
        u["user_starred_repos_count"] / (u["user_days_between_created_at_today"] + 1.0), 3
    )

    # Frequency binning + blog flag (UserProfileBuilder.scala:177-200).
    u = FrequencyBinner(
        "user_clean_company", "user_binned_company", company_bin_threshold
    ).fit(u).transform(u)
    u = FrequencyBinner(
        "user_clean_location", "user_binned_location", location_bin_threshold
    ).fit(u).transform(u)
    u["user_has_blog"] = u["user_blog"] != ""

    cols = FeatureColumns(
        boolean=["user_has_null", *(_USER_KEYWORD_FLAGS.keys()), "user_has_blog"],
        continuous=[
            "user_public_repos_count", "user_public_gists_count",
            "user_followers_count", "user_following_count",
            "user_followers_following_ratio",
            "user_days_between_created_at_today",
            "user_days_between_updated_at_today",
            "user_starred_repos_count", "user_avg_daily_starred_repos_count",
        ],
        categorical=["user_account_type", "user_binned_company", "user_binned_location"],
        list_=["user_recent_repo_languages", "user_recent_repo_topics"],
        text=["user_clean_bio", "user_recent_repo_descriptions"],
    )
    profile = plain_columns(
        u[["user_id", "user_login", *cols.all()]].reset_index(drop=True)
    )
    return profile, cols


def build_repo_profile(
    tables: RawTables,
    now: float,
    min_stars: int = 30,
    max_stars: int = 100_000,
    max_forks: int = 90_000,
    language_bin_threshold: int = 30,
    canary_user_id: int = VINTA_USER_ID,
) -> tuple[pd.DataFrame, FeatureColumns]:
    """``RepoProfileBuilder`` parity; returns (profile frame, feature buckets)."""
    r = tables.repo_info.copy()
    s = tables.starring

    nullable = ["repo_description", "repo_homepage"]
    r["repo_has_null"] = (r[nullable] == "").any(axis=1)

    # Reduce: no forks, bounded stars/forks (RepoProfileBuilder.scala:73-77).
    r = r[
        (~r["repo_is_fork"])
        & (r["repo_forks_count"] <= max_forks)
        & r["repo_stargazers_count"].between(min_stars, max_stars)
    ].copy()

    r["repo_clean_description"] = r["repo_description"].str.lower()
    low_stars = r["repo_stargazers_count"] <= 40
    r["repo_is_unmaintained"] = _contains_any(r["repo_clean_description"], _UNMAINTAINED_WORDS)
    r["repo_is_assignment"] = _contains_any(r["repo_clean_description"], _ASSIGNMENT_WORDS)
    r["repo_is_demo"] = (
        r["repo_clean_description"].isin(_DEMO_WORDS_EXACT)
        | _contains_any(r["repo_clean_description"], _DEMO_WORDS)
    ) & low_stars
    r["repo_is_blog"] = r["repo_clean_description"].isin(_BLOG_WORDS_EXACT) & low_stars
    r = r[
        ~(r["repo_is_unmaintained"] | r["repo_is_assignment"] | r["repo_is_demo"] | r["repo_is_blog"])
    ].copy()

    r["repo_clean_language"] = r["repo_language"].str.lower()

    # Constructed features (RepoProfileBuilder.scala:108-124).
    canary_repos = set(s[s["user_id"] == canary_user_id]["repo_id"].tolist())
    r["repo_has_activities_in_60days"] = (now - r["repo_pushed_at"]) / _DAY <= 60
    r["repo_has_homepage"] = r["repo_homepage"] != ""
    r["repo_is_vinta_starred"] = r["repo_id"].isin(canary_repos)
    r["repo_days_between_created_at_today"] = np.floor((now - r["repo_created_at"]) / _DAY)
    r["repo_days_between_updated_at_today"] = np.floor((now - r["repo_updated_at"]) / _DAY)
    r["repo_days_between_pushed_at_today"] = np.floor((now - r["repo_pushed_at"]) / _DAY)
    r["repo_subscribers_stargazers_ratio"] = np.round(
        r["repo_subscribers_count"] / (r["repo_stargazers_count"] + 1.0), 3
    )
    r["repo_forks_stargazers_ratio"] = np.round(
        r["repo_forks_count"] / (r["repo_stargazers_count"] + 1.0), 3
    )
    r["repo_open_issues_stargazers_ratio"] = np.round(
        r["repo_open_issues_count"] / (r["repo_stargazers_count"] + 1.0), 3
    )
    r["repo_text"] = (
        r["repo_owner_username"].astype(str)
        + " " + r["repo_name"].astype(str)
        + " " + r["repo_language"].astype(str)
        + " " + r["repo_description"].astype(str)
    ).str.lower()

    # Binned language + topics list (RepoProfileBuilder.scala:135-148).
    r = FrequencyBinner(
        "repo_clean_language", "repo_binned_language", language_bin_threshold
    ).fit(r).transform(r)
    r["repo_clean_topics"] = [
        [t for t in str(x).lower().split(",") if t] for x in r["repo_topics"]
    ]

    cols = FeatureColumns(
        boolean=[
            "repo_has_issues", "repo_has_projects", "repo_has_downloads",
            "repo_has_wiki", "repo_has_pages", "repo_has_null",
            "repo_has_activities_in_60days", "repo_has_homepage",
            "repo_is_vinta_starred",
        ],
        continuous=[
            "repo_size", "repo_stargazers_count", "repo_forks_count",
            "repo_subscribers_count", "repo_open_issues_count",
            "repo_days_between_created_at_today",
            "repo_days_between_updated_at_today",
            "repo_days_between_pushed_at_today",
            "repo_subscribers_stargazers_ratio",
            "repo_forks_stargazers_ratio",
            "repo_open_issues_stargazers_ratio",
        ],
        categorical=["repo_owner_type", "repo_language", "repo_binned_language"],
        list_=["repo_clean_topics"],
        text=["repo_text"],
    )
    profile = plain_columns(
        r[
            ["repo_id", "repo_full_name", "repo_owner_id", "repo_created_at",
             "repo_updated_at", "repo_pushed_at", *cols.all()]
        ].reset_index(drop=True)
    )
    return profile, cols
