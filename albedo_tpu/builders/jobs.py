"""CLI jobs: one per reference entry point.

Reference parity: the L4 ``object ... main`` builders and their Makefile
targets (``make train_als``, ``make train_lr``, ..., ``Makefile:131-218``).
Each job loads the raw tables (file/sqlite source via ``--tables``, else the
synthetic generator), runs its workload, and prints params + metrics the way
the reference ``println``s them; expensive products memoize through the
date-keyed artifact store.

Evaluation protocol matches the builders: train on the FULL star matrix,
sample test users (+ the canary user), recommend top-30, and score NDCG@30
against each user's most recent 30 stars (``ALSRecommenderBuilder.scala:60-105``,
``loadUserActualItemsDF``)."""

from __future__ import annotations

import argparse
import itertools
import threading
import time

import numpy as np

from albedo_tpu.cli import EXIT_FAILURE, EXIT_REFUSED, register_job
from albedo_tpu.datasets import (
    load_or_create_raw_tables,
    load_raw_tables,
    sample_test_users,
    synthetic_tables,
)
from albedo_tpu.datasets.artifacts import load_or_create_pickle
from albedo_tpu.datasets.tables import RawTables, popular_repos
from albedo_tpu.evaluators import RankingEvaluator, UserItems, user_actual_items, user_items_from_pairs
from albedo_tpu.builders.profiles import VINTA_USER_ID, build_repo_profile, build_user_profile

TOP_K = 30

# The flagship ALS artifact's hyperparameter defaults — re-exported from
# the estimator itself (ONE definition), shared with the streaming fold-in
# engine, which must solve with the SAME regularization/alpha the base
# artifact was trained with (a mismatch would bias every folded row
# relative to the refit path).
from albedo_tpu.models.als import ImplicitALS as _ImplicitALS  # noqa: E402

ALS_REG = _ImplicitALS.reg_param
ALS_ALPHA = _ImplicitALS.alpha


class JobContext:
    """Shared lazily-built artifacts for one CLI invocation."""

    def __init__(
        self,
        args: argparse.Namespace,
        tables: RawTables | None = None,
        tag: str | None = None,
    ):
        """``tables``/``tag`` inject a pre-built dataset (and its artifact
        identity) without going through ``--tables`` — used by the bench."""
        self.args = args
        self.small = bool(getattr(args, "small", False))
        now = getattr(args, "now", None)
        self.now = float(now) if now is not None else time.time()
        # Dataset identity tag baked into every artifact name, so a run
        # against different --tables (or synthetic vs real) on the same day
        # can never resume another dataset's cached model.
        from albedo_tpu.settings import md5

        source = str(getattr(args, "tables", None) or f"synthetic-{self.small}")
        if (tables is None) != (tag is None):
            # A tag without its dataset (or vice versa) would stamp artifacts
            # with the wrong identity and resume another dataset's models.
            raise ValueError("inject tables and tag together, or neither")
        self.tag = tag if tag is not None else md5(source)[:10]
        self._cache: dict[str, object] = {}
        # Checkpoint dirs this process has already initialized: a retry of a
        # failed stage must RESUME from this run's own steps, not wipe them.
        self._ckpt_initialized: set[str] = set()
        if tables is not None:
            self._cache["tables"] = tables
        # Persistent executable reuse by default, even when a JobContext is
        # built directly (bench, notebooks) rather than through cli.main —
        # idempotent, and a no-op under --no-compilation-cache /
        # ALBEDO_JAX_CACHE=0.
        if not bool(getattr(args, "no_compilation_cache", False)):
            from albedo_tpu.utils.compilation_cache import (
                enable_persistent_compilation_cache,
            )

            enable_persistent_compilation_cache()

    def artifact_name(self, base: str) -> str:
        return f"{self.tag}-{base}"

    def tables(self) -> RawTables:
        if "tables" not in self._cache:
            path = getattr(self.args, "tables", None)
            if path:
                self._cache["tables"] = load_or_create_raw_tables(
                    lambda: load_raw_tables(path), key=self.artifact_name("raw_tables.pkl")
                )
            else:
                n_users, n_items = (400, 300) if self.small else (5000, 3000)
                self._cache["tables"] = synthetic_tables(
                    n_users=n_users, n_items=n_items, mean_stars=20, seed=42
                )
        return self._cache["tables"]  # type: ignore[return-value]

    def curators(self) -> tuple[int, ...] | None:
        """Single curator policy for curation_job AND the ranker's curation
        source: the reference's hard-coded ids on real tables, the five most
        active users on synthetic data (where those ids don't exist)."""
        if getattr(self.args, "tables", None):
            return None  # CurationRecommender's default CURATOR_IDS
        star = self.tables().starring
        return tuple(star["user_id"].value_counts().index[:5].tolist())

    def data_policy(self) -> str:
        """The ingest firewall policy (``--data-policy strict|repair|off``;
        default ``repair``): strict fails the job on any bad star row,
        repair drops/quarantines bad rows, off is the bare seed path."""
        from albedo_tpu.datasets.validate import default_policy

        return getattr(self.args, "data_policy", None) or default_policy()

    def matrix(self):
        if "matrix" not in self._cache:
            policy = self.data_policy()
            matrix, report = self.tables().validated_star_matrix(
                policy=policy,
                quarantine_name=(
                    self.artifact_name("starring") if policy == "repair" else None
                ),
                now=self.now,
            )
            self._cache["matrix"] = matrix
            self._cache["data_report"] = report
        return self._cache["matrix"]

    def data_report(self):
        """The ingest :class:`~albedo_tpu.datasets.validate.ValidationReport`
        (building the matrix on first call)."""
        self.matrix()
        return self._cache["data_report"]

    def als_solver(self) -> tuple[str, int]:
        """(solver, cg_steps) from the CLI ``--solver``/``--cg-steps`` flags."""
        steps = getattr(self.args, "cg_steps", None)
        return (
            getattr(self.args, "solver", "cholesky") or "cholesky",
            3 if steps is None else int(steps),
        )

    def mesh(self):
        """The training mesh from ``--mesh-devices`` (None = single device),
        built once per context. Fewer visible devices than requested remesh
        down the degraded ladder (loudly, counted) — the same call path a
        checkpointed sharded fit resumes through on a smaller slice."""
        n = int(getattr(self.args, "mesh_devices", 0) or 0)
        if n <= 0:
            return None
        if "mesh" not in self._cache:
            from albedo_tpu.parallel.mesh import make_mesh

            self._cache["mesh"] = make_mesh(n)
        return self._cache["mesh"]

    def mesh_opts(self) -> dict:
        """Estimator kwargs for the mesh fit: ``--sharded`` maps auto ->
        None (the admission ladder decides); ``--shard-mode`` passes
        through. Empty when no mesh is configured."""
        mesh = self.mesh()
        if mesh is None:
            return {}
        sharded = getattr(self.args, "sharded", "auto") or "auto"
        return dict(
            mesh=mesh,
            sharded=None if sharded == "auto" else sharded,
            shard_mode=getattr(self.args, "shard_mode", "allgather") or "allgather",
        )

    def checkpoint_opts(self) -> tuple[int, bool, int | None]:
        """(checkpoint_every, resume, keep_last) from the CLI flags;
        ``--keep-last 0`` means keep every step (maps to None)."""
        keep = getattr(self.args, "keep_last", 3)
        keep = 3 if keep is None else int(keep)
        return (
            int(getattr(self.args, "checkpoint_every", 0) or 0),
            bool(getattr(self.args, "resume", False)),
            keep if keep > 0 else None,
        )

    def checkpointed_als(self, est, matrix, key: str):
        """Preemption-safe ALS fit: checkpoints every ``--checkpoint-every``
        iterations under ``checkpoint_dir/<tag>-<key>``, resumes from the
        newest readable step under ``--resume``, and converts SIGTERM/SIGINT
        into a checkpoint + :class:`~albedo_tpu.utils.checkpoint.Preempted`
        clean exit (the CLI maps it to exit code 75).

        A MESH estimator routes to the ELASTIC driver
        (:func:`~albedo_tpu.parallel.elastic.elastic_sharded_fit`): the
        same preemption/journal/retention contract, plus mesh-portable
        sharded checkpoints (a fit checkpointed on 8 devices resumes on a
        4/2/1-device rung) and mid-fit device-loss remesh-resume."""
        import shutil

        from albedo_tpu.settings import get_settings
        from albedo_tpu.utils.checkpoint import (
            PreemptionHandler,
            checkpointed_als_fit,
        )

        from albedo_tpu.utils.watchdog import DivergenceWatchdog

        every, resume, keep_last = self.checkpoint_opts()
        ckdir = get_settings().checkpoint_dir / self.artifact_name(key)
        if not resume and key not in self._ckpt_initialized and ckdir.exists():
            # A fresh (non-resume) run must not silently adopt stale factors —
            # but only on the FIRST fit per key: an in-process retry (e.g.
            # run_pipeline's stage retry after a transient checkpoint-write
            # error) resumes from the steps this very run just saved instead
            # of deleting them and restarting from iteration 0.
            shutil.rmtree(ckdir)
        self._ckpt_initialized.add(key)
        watchdog = DivergenceWatchdog()
        try:
            with PreemptionHandler() as preemption:
                if est.mesh is not None:
                    from albedo_tpu.parallel.elastic import elastic_sharded_fit

                    return elastic_sharded_fit(
                        est, matrix, ckdir, every=every, keep_last=keep_last,
                        preemption=preemption, watchdog=watchdog,
                    )
                return checkpointed_als_fit(
                    est, matrix, ckdir, every=every, keep_last=keep_last,
                    preemption=preemption, watchdog=watchdog,
                )
        finally:
            # Trips (with remediation outcomes) feed the publish stamp's
            # quality record, even when the fit ultimately diverged.
            if watchdog.trips:
                self._cache.setdefault("watchdog_trips", []).extend(watchdog.trips)

    def star_range(self) -> tuple[int, int]:
        # The reference's popular/profile star windows assume GitHub-scale
        # counts; synthetic tables are smaller.
        if getattr(self.args, "tables", None):
            return (1000, 290_000)
        return (1, 10**9)

    def als_key(self, rank=50, reg=ALS_REG, alpha=ALS_ALPHA, iters=26) -> str:
        """The flagship ALS artifact's base key (hyperparams baked into the
        name, solver-tagged when not the parity default) — one definition
        shared by training, the canary publish gate, and the serve watcher."""
        if self.small:
            rank, iters = 16, 8
        solver, cg_steps = self.als_solver()
        key = f"alsModel-{rank}-{reg}-{alpha}-{iters}"
        if solver != "cholesky":
            key += f"-{solver}{cg_steps}"  # solver-tagged artifact, no mixups
        return key

    def als_artifact_name(self, **kw) -> str:
        return self.artifact_name(self.als_key(**kw) + ".pkl")

    def als_model(self, rank=50, reg=ALS_REG, alpha=ALS_ALPHA, iters=26):
        from albedo_tpu.models.als import ImplicitALS

        key = self.als_key(rank=rank, reg=reg, alpha=alpha, iters=iters)
        if self.small:
            rank, iters = 16, 8
        solver, cg_steps = self.als_solver()

        def train():
            est = ImplicitALS(
                rank=rank, reg_param=reg, alpha=alpha, max_iter=iters,
                solver=solver, cg_steps=cg_steps, **self.mesh_opts(),
            )
            every, _, _ = self.checkpoint_opts()
            if every > 0:
                return self.checkpointed_als(est, self.matrix(), key)
            # Non-checkpointed fits still run under the divergence watchdog
            # (check-final + one damped re-fit; utils.watchdog.guarded_fit).
            from albedo_tpu.utils.watchdog import guarded_fit

            model, trips = guarded_fit(est, self.matrix())
            if trips:
                self._cache.setdefault("watchdog_trips", []).extend(trips)
            return model

        if "als" not in self._cache:
            from albedo_tpu.models.als import ALSModel

            arrays = load_or_create_pickle(
                self.artifact_name(key + ".pkl"), lambda: train().to_arrays()
            )
            self._cache["als"] = ALSModel.from_arrays(arrays)
        return self._cache["als"]

    def profiles(self):
        if "profiles" not in self._cache:
            lo, hi = self.star_range()
            up, uc = build_user_profile(self.tables(), now=self.now)
            rp, rc = build_repo_profile(
                self.tables(), now=self.now, min_stars=max(1, lo // 30), max_stars=hi,
                language_bin_threshold=3 if not getattr(self.args, "tables", None) else 30,
            )
            self._cache["profiles"] = (up, uc, rp, rc)
        return self._cache["profiles"]

    def word2vec_corpus(self) -> list[list[str]]:
        """The reference's W2V corpus (``Word2VecCorpusBuilder.scala:47-69``):
        ``concat_ws(", ", login/name/bio/company/location)`` per user union
        ``concat_ws(", ", owner/name/language/description/topics)`` per repo,
        then the SAME Tokenizer -> StopWordsRemover stages the ranker's
        inference pipeline applies, so corpus vocab and inference tokens
        can never diverge (no punctuation-OOV)."""
        import pandas as pd

        from albedo_tpu.features.text import StopWordsRemover, Tokenizer

        tables = self.tables()

        def concat_ws(df, cols: list[str]):
            parts = [df[c].fillna("").astype(str) for c in cols]
            out = parts[0]
            for p in parts[1:]:
                out = out + ", " + p
            return out

        user_text = concat_ws(
            tables.user_info,
            ["user_login", "user_name", "user_bio", "user_company", "user_location"],
        )
        repo_text = concat_ws(
            tables.repo_info,
            ["repo_owner_username", "repo_name", "repo_language", "repo_description", "repo_topics"],
        )
        corpus_df = pd.DataFrame({"text": list(user_text) + list(repo_text)})
        staged = StopWordsRemover("text__words", "text__filtered").transform(
            Tokenizer("text", "text__words", remove_stop_words=False).transform(corpus_df)
        )
        return list(staged["text__filtered"])

    def word2vec_estimator(self):
        """The configured (untrained) Word2Vec — also what the
        ``train_word2vec`` job's explainParams dump prints.

        Reference config (dim=200, maxIter=30, Word2VecCorpusBuilder.scala:74-83)
        on real ``--tables`` runs or when ``args.w2v_full`` is set (the bench
        sets it so its wall-clock compares apples-to-apples against the 38m58s
        baseline); the small config keeps synthetic/laptop runs snappy."""
        from albedo_tpu.models.word2vec import Word2Vec

        full = bool(getattr(self.args, "w2v_full", False)) or (
            bool(getattr(self.args, "tables", None)) and not self.small
        )
        dim, iters = (200, 30) if full else (16, 3)
        return Word2Vec(
            dim=dim, min_count=3 if self.small else 10, max_iter=iters, subsample=0.0
        )

    def word2vec_artifact_name(self) -> str:
        """The trained-w2v artifact name (one definition — the run_pipeline
        journal records the same name this cache writes)."""
        est = self.word2vec_estimator()
        return self.artifact_name(f"word2VecModel-v2-{est.dim}-{est.max_iter}.pkl")

    def word2vec(self):
        from albedo_tpu.models.word2vec import Word2VecModel

        if "w2v" not in self._cache:
            est = self.word2vec_estimator()

            def train():
                # Corpus built lazily inside the closure: a cache hit on the
                # trained model skips the full-table tokenization pass.
                return est.fit_corpus(self.word2vec_corpus())

            arrays = load_or_create_pickle(
                self.word2vec_artifact_name(), lambda: train().to_arrays()
            )
            self._cache["w2v"] = Word2VecModel(
                vocab=list(arrays["vocab"]), vectors=np.asarray(arrays["vectors"], np.float32)
            )
        return self._cache["w2v"]

    def ranker_model(self):
        """Trained LR :class:`~albedo_tpu.builders.ranker.RankerModel` for
        online re-ranking (``serve --two-stage``). Trained in-process and
        cached per context — the model holds live pipeline stages (w2v, LR
        device arrays), so it memoizes here rather than through the pickle
        store; its ingredients (ALS factors, w2v vectors) still come from
        their date-keyed artifacts."""
        if "ranker" not in self._cache:
            from albedo_tpu.builders.ranker import RankerConfig, train_ranker

            up, uc, rp, rc = self.profiles()
            lo, hi = self.star_range()
            config = RankerConfig(
                popular_min_stars=lo, popular_max_stars=hi,
                min_df=3 if self.small else 10,
            )
            if self.small:
                config = config.small()
            result = train_ranker(
                self.tables(), up, uc, rp, rc, self.als_model(), self.matrix(),
                self.word2vec(), now=self.now, config=config,
            )
            print(f"[serve] ranker trained: AUC = {result.auc:.4f}")
            self._cache["ranker"] = result.model
            self._cache["ranker_auc"] = float(result.auc)
        return self._cache["ranker"]

    def test_user_dense(self, n=250) -> np.ndarray:
        matrix = self.matrix()
        canary = matrix.users_of(np.array([VINTA_USER_ID]))
        extra = canary[canary >= 0]
        return sample_test_users(matrix, n=n, always_include=extra if extra.size else None)

    def evaluate_topk(self, frame) -> float:
        """NDCG@30 of a (user_id, repo_id, score) candidate frame."""
        matrix = self.matrix()
        predicted = user_items_from_pairs(
            matrix.users_of(frame["user_id"].to_numpy(np.int64)),
            matrix.items_of(frame["repo_id"].to_numpy(np.int64)),
            order_key=frame["score"].to_numpy(np.float64),
            k=TOP_K,
        )
        actual = user_actual_items(matrix, k=TOP_K)
        return RankingEvaluator(metric_name="ndcg@k", k=TOP_K).evaluate(predicted, actual)


def _report(job: str, metric_name: str, value: float, t0: float) -> None:
    print(f"[{job}] {metric_name} = {value}")
    print(f"[{job}] wall-clock = {time.time() - t0:.1f}s")


@register_job("popularity")
def popularity_job(args) -> None:
    """``PopularityRecommenderBuilder`` (NDCG@30 gate 0.00202)."""
    from albedo_tpu.recommenders import PopularityRecommender

    t0 = time.time()
    ctx = JobContext(args)
    lo, hi = ctx.star_range()
    pop = popular_repos(ctx.tables().repo_info, lo, hi)
    rec = PopularityRecommender(pop, top_k=TOP_K)
    users = ctx.matrix().user_ids[ctx.test_user_dense()]
    ndcg = ctx.evaluate_topk(rec.recommend_for_users(users))
    _report("popularity", "NDCG@30", ndcg, t0)


@register_job("curation")
def curation_job(args) -> None:
    """``CurationRecommenderBuilder`` (NDCG@30 gate 0.00319)."""
    from albedo_tpu.recommenders import CurationRecommender

    t0 = time.time()
    ctx = JobContext(args)
    star = ctx.tables().starring
    curators = ctx.curators()
    rec = (
        CurationRecommender(star, curator_ids=curators, top_k=TOP_K)
        if curators
        else CurationRecommender(star, top_k=TOP_K)
    )
    users = ctx.matrix().user_ids[ctx.test_user_dense()]
    ndcg = ctx.evaluate_topk(rec.recommend_for_users(users))
    _report("curation", "NDCG@30", ndcg, t0)


@register_job("content")
def content_job(args) -> None:
    """``ContentRecommenderBuilder`` — embedding MLT backend."""
    from albedo_tpu.recommenders import ContentRecommender, EmbeddingSearchBackend

    t0 = time.time()
    ctx = JobContext(args)
    backend = EmbeddingSearchBackend(ctx.tables().repo_info, ctx.word2vec())
    rec = ContentRecommender(
        backend, ctx.tables().starring, top_k=TOP_K, enable_evaluation_mode=True
    )
    users = ctx.matrix().user_ids[ctx.test_user_dense(100)]
    ndcg = ctx.evaluate_topk(rec.recommend_for_users(users))
    _report("content", "NDCG@30", ndcg, t0)


@register_job("train_als")
def train_als_job(args) -> None:
    """``ALSRecommenderBuilder`` — the flagship (NDCG@30 gate 0.05209)."""
    from albedo_tpu.recommenders import ALSRecommender

    t0 = time.time()
    ctx = JobContext(args)
    # Sparsity print: the PySpark track's calculate_sparsity parity
    # (albedo_toolkit/common.py).
    print(f"[train_als] star-matrix sparsity = {ctx.matrix().sparsity():.6f}")
    model = ctx.als_model()
    rec = ALSRecommender(model, ctx.matrix(), top_k=TOP_K)
    users = ctx.matrix().user_ids[ctx.test_user_dense()]
    ndcg = ctx.evaluate_topk(rec.recommend_for_users(users))
    _report("train_als", "NDCG@30", ndcg, t0)


@register_job("cv_als")
def cv_als_job(args) -> None:
    """``ALSRecommenderCV`` — 2-fold grid over rank x regParam x alpha."""
    from albedo_tpu.cv import cross_validate, param_grid
    from albedo_tpu.models.als import ImplicitALS
    from albedo_tpu.recommenders import ALSRecommender

    t0 = time.time()
    ctx = JobContext(args)
    grid = (
        param_grid(rank=[8, 16], reg_param=[0.1, 0.5], alpha=[1.0, 40.0])
        if ctx.small or not getattr(args, "tables", None)
        else param_grid(rank=[50, 100], reg_param=[0.01, 0.5], alpha=[0.01, 40.0])
    )
    iters = 6 if ctx.small else 13

    solver, cg_steps = ctx.als_solver()

    fit_no = itertools.count()

    def fit(params, train):
        est = ImplicitALS(max_iter=iters, solver=solver, cg_steps=cg_steps, **params)
        every, _, _ = ctx.checkpoint_opts()
        if every > 0:
            # Per-(params, fold) checkpoint identity. cross_validate iterates
            # params x folds in a deterministic order, so the sequential fit
            # number is stable across reruns and -- unlike shape/nnz alone --
            # can never collide between folds (two folds with equal nnz would
            # otherwise share a dir and --resume would hand fold 2 fold 1's
            # trained factors).
            from albedo_tpu.settings import md5

            key = md5(f"{sorted(params.items())}-fit{next(fit_no)}")[:12]
            return ctx.checkpointed_als(est, train, f"cvALS-{key}")
        return est.fit(train)

    def evaluate(model, train, test):
        users = sample_test_users(test, n=150)
        rec_frame = ALSRecommender(model, train, top_k=TOP_K).recommend_for_users(
            train.user_ids[users]
        )
        predicted = user_items_from_pairs(
            train.users_of(rec_frame["user_id"].to_numpy(np.int64)),
            train.items_of(rec_frame["repo_id"].to_numpy(np.int64)),
            order_key=rec_frame["score"].to_numpy(np.float64),
            k=TOP_K,
        )
        return RankingEvaluator(metric_name="ndcg@k", k=TOP_K).evaluate(
            predicted, user_actual_items(test, k=TOP_K)
        )

    results = cross_validate(fit, evaluate, ctx.matrix(), grid, n_folds=2, verbose=True)
    best = results[0]
    print(f"[cv_als] best params = {best.params}")
    _report("cv_als", "NDCG@30", best.mean_metric, t0)


@register_job("build_user_profile")
def build_user_profile_job(args) -> None:
    from albedo_tpu.datasets.artifacts import load_or_create_df

    t0 = time.time()
    ctx = JobContext(args)
    df = load_or_create_df(
        ctx.artifact_name("userProfileDF.parquet"), lambda: ctx.profiles()[0]
    )
    _report("build_user_profile", "rows", float(len(df)), t0)


@register_job("build_repo_profile")
def build_repo_profile_job(args) -> None:
    from albedo_tpu.datasets.artifacts import load_or_create_df

    t0 = time.time()
    ctx = JobContext(args)
    df = load_or_create_df(
        ctx.artifact_name("repoProfileDF.parquet"), lambda: ctx.profiles()[2]
    )
    _report("build_repo_profile", "rows", float(len(df)), t0)


@register_job("train_word2vec")
def train_word2vec_job(args) -> None:
    """``Word2VecCorpusBuilder`` (explainParams dump parity, :85)."""
    from albedo_tpu.utils.params import explain_params

    t0 = time.time()
    ctx = JobContext(args)
    print(f"[train_word2vec] {explain_params(ctx.word2vec_estimator())}")
    model = ctx.word2vec()
    _report("train_word2vec", "vocab", float(len(model.vocab)), t0)


@register_job("train_lr")
def train_lr_job(args) -> None:
    """``LogisticRegressionRanker`` (AUC gate 0.9425, NDCG@30 gate 0.0211)."""
    from albedo_tpu.builders.ranker import RankerConfig, train_ranker
    from albedo_tpu.recommenders import ALSRecommender, CurationRecommender, PopularityRecommender

    t0 = time.time()
    ctx = JobContext(args)
    up, uc, rp, rc = ctx.profiles()
    als = ctx.als_model()
    lo, hi = ctx.star_range()
    config = RankerConfig(popular_min_stars=lo, popular_max_stars=hi, min_df=3 if ctx.small else 10)
    if ctx.small:
        config = config.small()
    star = ctx.tables().starring
    curators = ctx.curators()
    recs = [
        ALSRecommender(als, ctx.matrix(), top_k=60),
        CurationRecommender(star, curator_ids=curators, top_k=TOP_K)
        if curators
        else CurationRecommender(star, top_k=TOP_K),
        PopularityRecommender(popular_repos(ctx.tables().repo_info, lo, hi), top_k=TOP_K),
    ]
    result = train_ranker(
        ctx.tables(), up, uc, rp, rc, als, ctx.matrix(), ctx.word2vec(),
        now=ctx.now, config=config, recommenders=recs,
    )
    print(f"[train_lr] areaUnderROC = {result.auc}")
    _report("train_lr", "NDCG@30", result.ndcg or 0.0, t0)


def _holdout_cf_ndcg(ctx: JobContext, rec_cls) -> float:
    """NDCG@30 for the memory-based CFs under a held-out split.

    The CF recommenders drop the user's own stars from the ranked list
    (``train_item_cf.py:38`` behavior), so the full-matrix protocol the other
    builders use (actual = recent stars the model trained on) would score an
    exact 0 by construction; they are evaluated on held-out stars instead:
    fit on the train split, recommend with train stars excluded, score
    against each user's held-out items."""
    from albedo_tpu.datasets import random_split_by_user

    matrix = ctx.matrix()
    train, test = random_split_by_user(matrix, test_ratio=0.1, seed=42)
    rec = rec_cls(train, top_k=TOP_K)
    users_dense = sample_test_users(test, n=250, seed=42)
    frame = rec.recommend_for_users(matrix.user_ids[users_dense])
    predicted = user_items_from_pairs(
        matrix.users_of(frame["user_id"].to_numpy(np.int64)),
        matrix.items_of(frame["repo_id"].to_numpy(np.int64)),
        order_key=frame["score"].to_numpy(np.float64),
        k=TOP_K,
    )
    actual = user_actual_items(test, k=TOP_K)
    return RankingEvaluator(metric_name="ndcg@k", k=TOP_K).evaluate(predicted, actual)


@register_job("item_cf")
def item_cf_job(args) -> None:
    """``train_item_cf`` legacy-trainer parity: item-item cosine CF, NDCG@30
    on a held-out split."""
    from albedo_tpu.recommenders.cf import ItemCFRecommender

    t0 = time.time()
    ndcg = _holdout_cf_ndcg(JobContext(args), ItemCFRecommender)
    _report("item_cf", "NDCG@30", ndcg, t0)


@register_job("user_cf")
def user_cf_job(args) -> None:
    """``train_user_cf`` legacy-trainer parity: user-user dice CF, NDCG@30 on
    a held-out split."""
    from albedo_tpu.recommenders.cf import UserCFRecommender

    t0 = time.time()
    ndcg = _holdout_cf_ndcg(JobContext(args), UserCFRecommender)
    _report("user_cf", "NDCG@30", ndcg, t0)


@register_job("ranking_mf")
def ranking_mf_job(args) -> None:
    """``train_graphlab`` legacy-trainer parity: ranking factorization on the
    binary star matrix (binary_target=True, split by user, top-50 with known
    items excluded — ``train_graphlab.py:23-34``), with repo side features
    (log-stars/forks) as the linear side-data term; NDCG@30 on the held-out
    split plus the canary user's top list."""
    from albedo_tpu.datasets import random_split_by_user
    from albedo_tpu.datasets.ragged import padded_rows
    from albedo_tpu.models.ranking_factorization import RankingFactorization

    t0 = time.time()
    ctx = JobContext(args)
    matrix = ctx.matrix()
    train, test = random_split_by_user(matrix, test_ratio=0.2, seed=42)

    # Side data: per-repo activity features, standardized (the reference's
    # side-data path; its own invocation passes none, so these are additive).
    repo = ctx.tables().repo_info.set_index("repo_id").reindex(matrix.item_ids)
    side = np.stack(
        [
            np.log1p(repo["repo_stargazers_count"].fillna(0).to_numpy(np.float64)),
            np.log1p(repo["repo_forks_count"].fillna(0).to_numpy(np.float64)),
        ],
        axis=1,
    )
    side = (side - side.mean(axis=0)) / np.maximum(side.std(axis=0), 1e-9)

    mf = RankingFactorization(
        rank=16 if ctx.small else 32, epochs=5 if ctx.small else 10,
        batch_size=1024 if ctx.small else 8192,
    )
    model = mf.fit(train, item_side=side.astype(np.float32))

    users_dense = sample_test_users(test, n=250, seed=42)
    indptr, cols_arr, _ = train.csr()
    excl = padded_rows(indptr, cols_arr, users_dense)
    _, idx = model.recommend(users_dense, k=TOP_K, exclude_idx=excl)
    predicted = UserItems(users=users_dense, items=idx.astype(np.int32))
    ndcg = RankingEvaluator(metric_name="ndcg@k", k=TOP_K).evaluate(
        predicted, user_actual_items(test, k=TOP_K)
    )
    _report("ranking_mf", "NDCG@30", ndcg, t0)


@register_job("tfidf_content")
def tfidf_content_job(args) -> None:
    """``train_content_based`` legacy-trainer parity: tf-idf similar-repo
    search. Prints the most-similar repos for the most-starred repo (the
    reference prints a query's top-49, ``train_content_based.py:62-66``) and
    reports indexed-corpus size."""
    from albedo_tpu.recommenders.tfidf import TfidfSimilaritySearch

    t0 = time.time()
    ctx = JobContext(args)
    repo = ctx.tables().repo_info
    search = TfidfSimilaritySearch(min_df=2).fit(repo)
    top_repo = repo.sort_values("repo_stargazers_count", ascending=False).iloc[0]
    for score, name in search.similar(str(top_repo["repo_full_name"]), k=10):
        print(f"[tfidf_content] {score:.4f} {name}")
    _report("tfidf_content", "indexed_repos", float(len(search.doc_ids)), t0)


def _context_bank(ctx, with_user_sim: bool = False, with_als: bool = True):
    """Assemble the default retrieval bank from this context's trained
    artifacts (ALS factors + the Word2Vec content index + the TF-IDF
    projection) — one definition shared by ``build_bank`` and
    ``serve --bank`` (which passes ``with_als=False``: its stage serves
    only the MLT sources, so the factor tables must not be pinned or
    capacity-priced twice)."""
    from albedo_tpu.recommenders import EmbeddingSearchBackend
    from albedo_tpu.recommenders.tfidf import TfidfSimilaritySearch
    from albedo_tpu.retrieval.build import build_default_bank

    tables = ctx.tables()
    backend = EmbeddingSearchBackend(tables.repo_info, ctx.word2vec())
    search = TfidfSimilaritySearch(min_df=2).fit(tables.repo_info)
    bank = build_default_bank(
        ctx.als_model(), ctx.matrix(),
        starring_df=tables.starring,
        content_backend=backend, tfidf_search=search,
        with_user_sim=with_user_sim, with_als=with_als, top_k=TOP_K,
    )
    return bank, backend, search


@register_job("build_bank")
def build_bank_job(args) -> int | None:
    """Build (or inspect) the unified retrieval bank: every embedding-backed
    candidate source — ALS factors, Word2Vec content embeddings, the TF-IDF
    projection, optionally the user-similarity table — sealed into ONE
    stamped, manifest-sealed device-servable artifact
    (``albedo_tpu.retrieval``; see the README retrieval runbook).

    Extra flags: --user-sim (register the user-to-user source),
    --inspect (print the existing artifact's stamp and exit).
    """
    from albedo_tpu.datasets.artifacts import read_meta, artifact_path
    from albedo_tpu.retrieval import bank_artifact_name

    t0 = time.time()
    extra = argparse.ArgumentParser()
    extra.add_argument("--user-sim", action="store_true")
    extra.add_argument("--inspect", action="store_true")
    ns, _ = extra.parse_known_args(getattr(args, "_rest", []))

    ctx = JobContext(args)
    name = bank_artifact_name(ctx.tag)
    if ns.inspect:
        meta = read_meta(artifact_path(name))
        if meta is None:
            print(f"[build_bank] no stamped bank at {name}")
            return EXIT_FAILURE
        import json as _json

        print(_json.dumps(meta.get("bank", meta), indent=2))
        return None
    bank, _, _ = _context_bank(ctx, with_user_sim=ns.user_sim)
    bank.save(name, lineage={
        "als_artifact": ctx.als_artifact_name(),
        "word2vec_artifact": ctx.word2vec_artifact_name(),
        "tag": ctx.tag,
    })
    for sname, info in bank.manifest()["sources"].items():
        print(
            f"[build_bank] {sname}: {info['rows']} rows x {info['dim']} dims "
            f"(calibration scale {info['calibration'].get('scale')})"
        )
    print(f"[build_bank] sealed {name} (version {bank.version})")
    _report("build_bank", "sources", float(len(bank.specs)), t0)


@register_job("serve")
def serve_job(args) -> None:
    """The online inference engine over trained artifacts: micro-batched
    top-k, optional two-stage candidate fan-out + LR re-rank, TTL result
    cache, and the `/metrics` Prometheus plane (``albedo_tpu.serving``).

    Extra flags: --port N (default 8080), --host ADDR (default 127.0.0.1;
    use 0.0.0.0 inside containers), --duration SECONDS (0 = forever),
    --no-batch (direct per-request GEMMs, the seed path), --no-warm (skip
    pre-compiling the batch-shape ladder), --two-stage (register the
    popularity + curation candidate sources and train/load the LR ranker
    for online re-ranking), --cache-ttl SECONDS (default 30; 0 disables),
    --max-batch N (default 64), --window-ms MS (batching window, default 2),
    --reload-watch (poll the artifact store and hot-swap fresh run_pipeline
    outputs through the validation gates), --reload-interval SECONDS (watch
    poll period, default 10). SIGHUP triggers one validated reload
    immediately (watched or not), and POST /admin/reload does the same over
    HTTP — see the README live-ops runbook.
    """
    import signal

    from albedo_tpu.recommenders import CurationRecommender, PopularityRecommender
    from albedo_tpu.serving import HotSwapManager, RecommendationService, serve

    extra = argparse.ArgumentParser()
    extra.add_argument("--port", type=int, default=8080)
    extra.add_argument("--host", default="127.0.0.1")
    extra.add_argument("--duration", type=float, default=0.0)
    extra.add_argument("--no-batch", action="store_true")
    extra.add_argument("--no-warm", action="store_true")
    extra.add_argument("--two-stage", action="store_true")
    extra.add_argument("--cache-ttl", type=float, default=30.0)
    extra.add_argument("--max-batch", type=int, default=64)
    extra.add_argument("--window-ms", type=float, default=2.0)
    extra.add_argument("--reload-watch", action="store_true")
    extra.add_argument("--reload-interval", type=float, default=10.0)
    extra.add_argument("--reload-require-stamp", action="store_true")
    extra.add_argument("--bank", action="store_true")
    ns, _ = extra.parse_known_args(getattr(args, "_rest", []))

    ctx = JobContext(args)
    recommenders = None
    ranker = None
    bank_stage = None
    if ns.bank and not ns.two_stage:
        import sys

        print(
            "[serve] --bank requires --two-stage (the bank is a stage-1 "
            "candidate plane); ignoring --bank", file=sys.stderr,
        )
    if ns.two_stage:
        lo, hi = ctx.star_range()
        recommenders = {
            "popularity": PopularityRecommender(
                popular_repos(ctx.tables().repo_info, lo, hi), top_k=TOP_K
            ),
            "curation": CurationRecommender(
                ctx.tables().starring,
                **({"curator_ids": ctx.curators()} if ctx.curators() else {}),
                top_k=TOP_K,
            ),
        }
        ranker = ctx.ranker_model()
        if ns.bank:
            # The bank-backed candidate stage: content + tfidf answered in
            # one fused device pass (the "als" rows stay on the generation-
            # snapshot batcher source, so hot swaps keep their invariant).
            from albedo_tpu.recommenders import ContentRecommender, TfidfRecommender
            from albedo_tpu.retrieval import BankStage

            bank, content_backend, search = _context_bank(ctx, with_als=False)
            tables = ctx.tables()
            fallbacks = {
                "content": ContentRecommender(
                    content_backend, tables.starring, top_k=TOP_K
                ),
                "tfidf": TfidfRecommender(search, tables.starring, top_k=TOP_K),
            }
            bank_stage = BankStage(
                bank, ctx.matrix(),
                sources=("content", "tfidf"), fallbacks=fallbacks, top_k=TOP_K,
            )
    service = RecommendationService(
        ctx.als_model(), ctx.matrix(),
        repo_info=ctx.tables().repo_info, user_info=ctx.tables().user_info,
        recommenders=recommenders, ranker=ranker,
        batching=not ns.no_batch, warm=not ns.no_batch and not ns.no_warm,
        cache_ttl=ns.cache_ttl, max_batch=ns.max_batch,
        batch_window_ms=ns.window_ms, bank_stage=bank_stage,
    )
    # Live-ops plane: the hot-swap manager always exists (SIGHUP and
    # POST /admin/reload work out of the box); --reload-watch additionally
    # polls the store so the compose ingest->train->serve loop picks up
    # fresh artifacts with no restart and no signal.
    manager = HotSwapManager(
        service,
        artifact_glob=f"{ctx.tag}-alsModel-*.pkl",
        watch_interval_s=ns.reload_interval,
        require_stamp=ns.reload_require_stamp,
    )
    if ns.reload_watch:
        manager.start_watch()
    if hasattr(signal, "SIGHUP"):
        def _sighup(_sig, _frame):
            # Reload on a worker thread: gates + batcher warm are seconds of
            # work and a signal handler must not block the main thread.
            threading.Thread(
                target=manager.request_reload, name="albedo-sighup-reload",
                daemon=True,
            ).start()

        signal.signal(signal.SIGHUP, _sighup)

    server = serve(service, host=ns.host, port=ns.port)
    host, port = server.server_address[:2]
    mode = "two-stage" if ns.two_stage else "als"
    print(f"[serve] listening on http://{host}:{port}/ "
          f"(/recommend/<user_id>, /admin/repos, /admin/users, /metrics, "
          f"/healthz/ready; POST /admin/reload) "
          f"[{mode}, batching={'off' if ns.no_batch else 'on'}, "
          f"cache_ttl={ns.cache_ttl:g}s, "
          f"reload={'watch' if ns.reload_watch else 'on-demand'}]")
    # Signal-interruptible foreground wait: SIGTERM/SIGINT set the stop
    # event instead of tearing the process down mid-batch, and the finally
    # block runs the full drain (reload watcher stopped, batcher drained,
    # pipeline pool shut down, server thread joined) — a scheduler
    # terminating the job gets the same clean shutdown as Ctrl-C.
    stop = threading.Event()

    def _sigstop(_sig, _frame):
        stop.set()
        # First signal starts the clean drain; hand the handlers back to
        # the defaults so a SECOND Ctrl-C/SIGTERM can still kill a wedged
        # shutdown instead of being swallowed by an already-set event.
        for s in (signal.SIGTERM, signal.SIGINT):
            signal.signal(s, signal.SIG_DFL)

    for _sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(_sig, _sigstop)
    try:
        stop.wait(ns.duration if ns.duration > 0 else None)
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()


@register_job("play")
def play_job(args) -> None:
    """``Playground`` parity (``Playground.scala:44-75``): the manual
    scratchpad — load raw tables, fit a quick ALS, save it through the
    artifact store, and print the canary user's top repos."""
    from albedo_tpu.models.als import ALSModel, ImplicitALS

    t0 = time.time()
    ctx = JobContext(args)
    matrix = ctx.matrix()
    arrays = load_or_create_pickle(
        ctx.artifact_name("playgroundALS.pkl"),
        lambda: ImplicitALS(rank=16, max_iter=8).fit(matrix).to_arrays(),
    )
    model = ALSModel.from_arrays(arrays)
    users = ctx.test_user_dense(n=1)
    _, idx = model.recommend(users[:1], k=10)
    for rank, item in enumerate(idx[0], 1):
        print(f"[play] {rank}. repo {matrix.item_ids[int(item)]}")
    _report("play", "rank", float(model.rank), t0)


@register_job("collect_data")
def collect_data_job(args) -> None:
    """``collect_data`` Django command parity: crawl GitHub into a sqlite
    store. Requires network unless a fake transport is injected in tests.

    Extra flags (parsed here): --db PATH, --seed-users a,b,c, --token T[,T2].
    """
    from albedo_tpu.store import EntityStore, GitHubCrawler

    t0 = time.time()
    extra = argparse.ArgumentParser()
    extra.add_argument("--db", default="albedo-crawl.db")
    extra.add_argument("--seed-users", default="vinta")
    extra.add_argument("--token", default="")
    ns, _ = extra.parse_known_args(getattr(args, "_rest", []))
    with EntityStore(ns.db) as store:
        with GitHubCrawler(store, tokens=ns.token.split(",")) as crawler:
            stats = crawler.collect([u for u in ns.seed_users.split(",") if u])
        print(f"[collect_data] {stats}")
    _report("collect_data", "requests", float(stats.requests), t0)


@register_job("drop_data")
def drop_data_job(args) -> None:
    """``drop_data`` Django command parity: truncate the crawl store's tables
    (``drop_data.py:11-13``). Extra flags: --db PATH, --yes (required)."""
    from albedo_tpu.store import EntityStore

    t0 = time.time()
    extra = argparse.ArgumentParser()
    extra.add_argument("--db", default="albedo-crawl.db")
    extra.add_argument("--yes", action="store_true",
                       help="required confirmation; refuses to truncate without it")
    ns, _ = extra.parse_known_args(getattr(args, "_rest", []))
    if not ns.yes:
        import sys

        print("[drop_data] refusing to truncate without --yes", file=sys.stderr)
        return EXIT_REFUSED  # automation must not mistake a refusal for success
    with EntityStore(ns.db) as store:
        before = store.counts()
        store.drop_data()
        print(f"[drop_data] truncated {before}")
    _report("drop_data", "rows_dropped", float(sum(before.values())), t0)


@register_job("sync_index")
def sync_index_job(args) -> None:
    """``sync_data_to_es`` parity: build the content embedding index."""
    from albedo_tpu.store import build_content_index

    t0 = time.time()
    ctx = JobContext(args)
    lo, hi = (10, 290_000) if getattr(args, "tables", None) else (1, 10**9)
    backend = build_content_index(
        ctx.tables().repo_info, ctx.word2vec(), min_stars=lo, max_stars=hi,
        artifact_name=ctx.artifact_name("contentIndex-v2.npz"),
    )
    _report("sync_index", "indexed_repos", float(len(backend.item_ids)), t0)


@register_job("datacheck")
def datacheck_job(args) -> int | None:
    """Standalone run of the ingest data-quality firewall (``make datacheck``):
    evaluates every rule in ``datasets.validate`` against the configured
    dataset (``--tables`` or synthetic), prints per-rule counts, mutates and
    quarantines NOTHING, and exits 1 when violations exist so CI can gate on
    dataset health before a training run spends accelerator time."""
    from albedo_tpu.datasets.validate import validate_starring

    t0 = time.time()
    ctx = JobContext(args)
    tables = ctx.tables()
    s = tables.starring.sort_values("starred_at", kind="stable")
    _, report = validate_starring(
        s,
        user_vocab=tables.user_info["user_id"].to_numpy(np.int64)
        if len(tables.user_info) else None,
        repo_vocab=tables.repo_info["repo_id"].to_numpy(np.int64)
        if len(tables.repo_info) else None,
        now=ctx.now,
        policy="repair",  # evaluate + count every rule; report-only, no sidecar
        quarantine_name=None,
    )
    for rule, count in sorted(report.violations.items()):
        print(f"[datacheck] {rule}: {count}")
    print(f"[datacheck] rows = {report.rows_in} -> {report.rows_out} "
          f"(policy would drop {report.total})")
    _report("datacheck", "violations", float(report.total), t0)
    return EXIT_FAILURE if report.total else None


@register_job("cv_lr")
def cv_lr_job(args) -> None:
    """``LogisticRegressionRankerCV`` — grid over instance-weight columns.

    The featurized set is built ONCE and the five weight-column LR fits run
    as a single vmapped L-BFGS solve (``LogisticRegression.fit_many``), the
    reference CV's materialize-once-then-grid structure
    (``LogisticRegressionRankerCV.scala:275-288,326-332``)."""
    from albedo_tpu.builders.ranker import RankerConfig, train_ranker
    from albedo_tpu.features.weights import WEIGHT_COLUMNS

    t0 = time.time()
    ctx = JobContext(args)
    up, uc, rp, rc = ctx.profiles()
    als = ctx.als_model()
    lo, hi = ctx.star_range()
    config = RankerConfig(
        popular_min_stars=lo, popular_max_stars=hi,
        min_df=3 if ctx.small else 10, lr_max_iter=60 if ctx.small else 300,
    )
    if ctx.small:
        config = config.small()
    r = train_ranker(
        ctx.tables(), up, uc, rp, rc, als, ctx.matrix(), ctx.word2vec(),
        now=ctx.now, config=config, weight_cols=WEIGHT_COLUMNS,
    )
    for weight_col, auc in r.grid:
        print(f"[cv_lr] {weight_col} -> AUC {auc:.6f}")
    best = r.grid[0]
    print(f"[cv_lr] best weight column = {best[0]}")
    _report("cv_lr", "AUC", best[1], t0)
