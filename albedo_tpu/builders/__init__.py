"""Entry-point builders (L4): the per-workload jobs the CLI launches.

Reference parity: the top-level ``object ... { def main }`` classes in
``src/main/scala/ws/vinta/albedo/`` (``PopularityRecommenderBuilder``,
``UserProfileBuilder``, ``RepoProfileBuilder``, ``ALSRecommenderBuilder``,
``Word2VecCorpusBuilder``, ``LogisticRegressionRanker``, the CV variants) and
the Makefile targets that submit them (``Makefile:131-218``).
"""

from albedo_tpu.builders.profiles import (
    FeatureColumns,
    build_repo_profile,
    build_user_profile,
)

__all__ = [
    "FeatureColumns",
    "build_repo_profile",
    "build_user_profile",
]
