"""Entry-point builders (L4): the per-workload jobs the CLI launches.

Reference parity: the top-level ``object ... { def main }`` classes in
``src/main/scala/ws/vinta/albedo/`` (``PopularityRecommenderBuilder``,
``UserProfileBuilder``, ``RepoProfileBuilder``, ``ALSRecommenderBuilder``,
``Word2VecCorpusBuilder``, ``LogisticRegressionRanker``, the CV variants) and
the Makefile targets that submit them (``Makefile:131-218``).
"""

from albedo_tpu.builders.profiles import (
    FeatureColumns,
    build_repo_profile,
    build_user_profile,
)
from albedo_tpu.builders.ranker import (
    ALSScorer,
    RankerConfig,
    RankerModel,
    RankerResult,
    build_feature_pipeline,
    reduce_starring,
    train_ranker,
)

from albedo_tpu.builders import jobs as _jobs  # noqa: F401  (registers CLI jobs)
from albedo_tpu.builders import pipeline as _pipeline  # noqa: F401  (run_pipeline job)
from albedo_tpu.streaming import job as _stream_job  # noqa: F401  (run_stream job)
from albedo_tpu.chaos import soak as _soak_job  # noqa: F401  (chaos soak job)
from albedo_tpu.scoring import job as _score_job  # noqa: F401  (score_all job)

__all__ = [
    "ALSScorer",
    "FeatureColumns",
    "RankerConfig",
    "RankerModel",
    "RankerResult",
    "build_feature_pipeline",
    "build_repo_profile",
    "build_user_profile",
    "reduce_starring",
    "train_ranker",
]
