"""Estimator/Transformer protocol and Pipeline composition.

Reference parity: Spark ML's ``Estimator.fit -> Model`` / ``Transformer.transform``
contract that every albedo stage implements (``recommenders/Recommender.scala:9``
extends ``Transformer``; pipelines assembled at
``LogisticRegressionRanker.scala:227-235``), plus the generic UDF wrapper
``org/apache/spark/ml/feature/FuncTransformer.scala:45-140``.

Tables are pandas DataFrames on the host; fitted state is numpy/python and
picklable, persisted through the artifact store (``load_or_create_model`` =
``ModelUtils.loadOrCreateModel``, ``utils/ModelUtils.scala:7-21``).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence, TypeVar

import pandas as pd

T = TypeVar("T")


class Transformer:
    """A fitted, stateless-or-fitted-state stage: ``transform(df) -> df``."""

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        raise NotImplementedError

    def __call__(self, df: pd.DataFrame) -> pd.DataFrame:
        return self.transform(df)

    def require_cols(self, df: pd.DataFrame, cols: Sequence[str]) -> None:
        """Runtime schema assertion (the reference's ``transformSchema``
        ``require`` checks, e.g. ``Recommender.scala:46-56``)."""
        missing = [c for c in cols if c not in df.columns]
        if missing:
            raise ValueError(f"{type(self).__name__}: missing input columns {missing}")


class Estimator:
    """An unfitted stage: ``fit(df) -> Transformer``."""

    def fit(self, df: pd.DataFrame) -> Transformer:
        raise NotImplementedError


class FuncTransformer(Transformer):
    """Wrap a per-value function as a column transformer
    (``FuncTransformer.scala:45-140``)."""

    def __init__(self, func: Callable[[Any], Any], input_col: str, output_col: str):
        self.func = func
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        self.require_cols(df, [self.input_col])
        out = df.copy()
        out[self.output_col] = [self.func(v) for v in col_values(df[self.input_col])]
        return out


def col_values(values):
    """A pandas column as a plain object ndarray for Python-speed iteration.

    Arrow-backed columns box every element on ``Series.__iter__`` (measured
    ~45 s of a 115 s ranker run at profile scale); one vectorized
    ``to_numpy`` conversion up front makes the downstream per-row loops
    cheap. Non-Series inputs pass through unchanged.
    """
    return values.to_numpy(dtype=object) if isinstance(values, pd.Series) else values


def memo_map(values, func: Callable[[Any], T], key: Callable[[Any], Any] | None = None) -> list[T]:
    """Apply ``func`` once per distinct value and map results back by key.

    The ranker's joined row sets repeat each user/repo document once per
    (user, repo) pair, so per-row tokenize/filter/embed work is ~100x
    redundant; memoizing by document collapses it to once per distinct text.
    Repeated rows share the SAME result object — downstream stages treat
    columns as read-only (Spark DataFrame semantics), so aliasing is safe.

    ``key`` maps unhashable values (token lists) to a hashable key (tuple).
    """
    vals = col_values(values)
    # Identity fast path: repeated rows usually ALIAS the same object (pandas
    # merges copy references; upstream memo_map stages return the same result
    # object per distinct input), so id() resolves most rows without
    # building/hashing a semantic key (tuple() over token lists was ~6 s of a
    # 19 s featurize at bench scale). ONLY safe when the container keeps every
    # element alive for the whole loop (a materialized array): for generator
    # inputs CPython recycles freed ids — zip() literally reuses its result
    # tuple — which would alias different rows to one cache slot.
    use_id = getattr(vals, "dtype", None) == object
    cache: dict = {}
    id_cache: dict = {}
    out = []
    sentinel = object()
    for v in vals:
        got = id_cache.get(id(v), sentinel) if use_id else sentinel
        if got is sentinel:
            k = v if key is None else key(v)
            got = cache.get(k, sentinel)
            if got is sentinel:
                got = func(v)
                cache[k] = got
            if use_id:
                id_cache[id(v)] = got
        out.append(got)
    return out


class IntermediateCacher(Transformer):
    """Pipeline stage that snapshots (and optionally column-prunes) the frame
    flowing through it (``transformers/IntermediateCacher.scala:10-40``).

    Spark's ``.cache()`` materializes a lazy plan so later stages don't
    recompute it; pandas frames are already materialized, so the load-bearing
    parts here are the column pruning (``intermediateColumns``) and the
    retained ``.cached`` snapshot — inspectable mid-pipeline for debugging,
    and a cut point that drops columns downstream stages don't need.
    """

    def __init__(self, columns: Sequence[str] | None = None):
        self.columns = list(columns) if columns else None
        self.cached: pd.DataFrame | None = None

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        if self.columns:
            self.require_cols(df, self.columns)
            df = df[self.columns]
        self.cached = df
        return df


class PipelineModel(Transformer):
    """A fitted pipeline: transformers applied in sequence."""

    def __init__(self, stages: list[Transformer]):
        self.stages = stages

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        for stage in self.stages:
            df = stage.transform(df)
        return df

    def __getitem__(self, i: int) -> Transformer:
        return self.stages[i]


class Pipeline(Estimator):
    """Fit stages in order, each transforming the frame the next one sees —
    Spark ``Pipeline.fit`` semantics."""

    def __init__(self, stages: Sequence[Estimator | Transformer]):
        self.stages = list(stages)

    def fit(self, df: pd.DataFrame) -> PipelineModel:
        fitted: list[Transformer] = []
        for stage in self.stages:
            if isinstance(stage, Estimator):
                model = stage.fit(df)
            elif isinstance(stage, Transformer):
                model = stage
            else:
                raise TypeError(f"pipeline stage {stage!r} is neither Estimator nor Transformer")
            df = model.transform(df)
            fitted.append(model)
        return PipelineModel(fitted)


def load_or_create_model(name: str, create: Callable[[], T]) -> T:
    """``ModelUtils.loadOrCreateModel`` parity: load the artifact if
    materialized today, else train and save (``utils/ModelUtils.scala:7-21``)."""
    from albedo_tpu.datasets.artifacts import load_or_create_pickle

    return load_or_create_pickle(name, create)
