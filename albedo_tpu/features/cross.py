"""User x repo cross features.

Reference parity: ``transformers/UserRepoTransformer.scala:10-50`` +
``closures/UDFs.scala:80-87`` — position and count of the repo's language
within the user's recent-repo-language list.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from albedo_tpu.features.pipeline import Transformer, col_values, memo_map


class UserRepoTransformer(Transformer):
    def __init__(
        self,
        repo_language_col: str = "repo_language",
        user_languages_col: str = "user_recent_repo_languages",
        not_found_offset: int = 50,
    ):
        self.repo_language_col = repo_language_col
        self.user_languages_col = user_languages_col
        # Miss value = len(list) + 50, as repoLanguageIndexInUserRecentRepoLanguagesUDF.
        self.not_found_offset = not_found_offset

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        self.require_cols(df, [self.repo_language_col, self.user_languages_col])

        def compute(pair) -> tuple[int, int]:
            lang, recent = pair
            lang = (lang or "").lower()
            recent = list(recent) if recent is not None else []
            try:
                index = recent.index(lang)
            except ValueError:
                index = len(recent) + self.not_found_offset
            return index, sum(1 for x in recent if x == lang)

        # (language, recent-list) pairs repeat once per (user, repo) row;
        # memoize per distinct pair like the other per-document transforms.
        results = memo_map(
            zip(
                col_values(df[self.repo_language_col]),
                col_values(df[self.user_languages_col]),
            ),
            compute,
            key=lambda p: (p[0], tuple(p[1]) if p[1] is not None else ()),
        )
        out = df.copy()
        out["repo_language_index_in_user_recent_repo_languages"] = np.fromiter(
            (r[0] for r in results), dtype=np.int32, count=len(results)
        )
        out["repo_language_count_in_user_recent_repo_languages"] = np.fromiter(
            (r[1] for r in results), dtype=np.int32, count=len(results)
        )
        return out
