"""User x repo cross features.

Reference parity: ``transformers/UserRepoTransformer.scala:10-50`` +
``closures/UDFs.scala:80-87`` — position and count of the repo's language
within the user's recent-repo-language list.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from albedo_tpu.features.pipeline import Transformer


class UserRepoTransformer(Transformer):
    def __init__(
        self,
        repo_language_col: str = "repo_language",
        user_languages_col: str = "user_recent_repo_languages",
        not_found_offset: int = 50,
    ):
        self.repo_language_col = repo_language_col
        self.user_languages_col = user_languages_col
        # Miss value = len(list) + 50, as repoLanguageIndexInUserRecentRepoLanguagesUDF.
        self.not_found_offset = not_found_offset

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        self.require_cols(df, [self.repo_language_col, self.user_languages_col])
        index_out = np.empty(len(df), dtype=np.int32)
        count_out = np.empty(len(df), dtype=np.int32)
        for r, (lang, recent) in enumerate(
            zip(df[self.repo_language_col], df[self.user_languages_col])
        ):
            lang = (lang or "").lower()
            recent = list(recent) if recent is not None else []
            try:
                index_out[r] = recent.index(lang)
            except ValueError:
                index_out[r] = len(recent) + self.not_found_offset
            count_out[r] = sum(1 for x in recent if x == lang)
        out = df.copy()
        out["repo_language_index_in_user_recent_repo_languages"] = index_out
        out["repo_language_count_in_user_recent_repo_languages"] = count_out
        return out
