"""Negative sampling for the ranker: popular-minus-positives per user.

Reference parity: ``transformers/NegativeBalancer.scala:13-119`` — per user,
take the (popularity-ordered) popular-item set minus the user's positives,
emit the first ``negativePositiveRatio * n_positives`` of them with label
``negativeValue`` and the sentinel timestamp 1999-07-01 (:107), then union with
the positives. The LinkedHashSet preserves popularity order, so negatives are
deterministically the most popular items the user has NOT starred — same here
(SURVEY.md §7 hard part (f)).

The RDD ``aggregateByKey`` over a broadcast set becomes one vectorized numpy
pass on the host.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from albedo_tpu.features.pipeline import Transformer

# 1999-07-01T00:00:00Z, the reference's sentinel (NegativeBalancer.scala:107).
SENTINEL_TIME = 930787200.0


class NegativeBalancer(Transformer):
    def __init__(
        self,
        popular_items: np.ndarray,
        user_col: str = "user_id",
        item_col: str = "repo_id",
        time_col: str = "starred_at",
        label_col: str = "starring",
        negative_value: float = 0.0,
        negative_positive_ratio: float = 1.0,
    ):
        # Popularity-ordered (most popular first), like the broadcast
        # LinkedHashSet built from loadPopularRepoDF (LogisticRegressionRanker.scala:250-255).
        self.popular_items = np.asarray(popular_items, dtype=np.int64)
        self.user_col = user_col
        self.item_col = item_col
        self.time_col = time_col
        self.label_col = label_col
        self.negative_value = negative_value
        self.negative_positive_ratio = negative_positive_ratio

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        self.require_cols(df, [self.user_col, self.item_col, self.time_col, self.label_col])
        pop = self.popular_items
        users = df[self.user_col].to_numpy(np.int64)
        items = df[self.item_col].to_numpy(np.int64)

        neg_users, neg_items = [], []
        order = np.argsort(users, kind="stable")
        bounds = np.nonzero(np.diff(users[order]))[0] + 1
        for chunk in np.split(order, bounds):
            if chunk.size == 0:  # empty input frame
                continue
            u = users[chunk[0]]
            positives = set(items[chunk].tolist())
            need = int(len(positives) * self.negative_positive_ratio)
            if need == 0:
                continue
            # Walk the popularity order, skipping positives.
            out = []
            for it in pop:
                if int(it) in positives:
                    continue
                out.append(it)
                if len(out) >= need:
                    break
            neg_users.extend([u] * len(out))
            neg_items.extend(out)

        negative = pd.DataFrame(
            {
                self.user_col: np.asarray(neg_users, dtype=np.int64),
                self.item_col: np.asarray(neg_items, dtype=np.int64),
                self.time_col: np.full(len(neg_items), SENTINEL_TIME),
                self.label_col: np.full(len(neg_items), self.negative_value),
            }
        )
        out_df = pd.concat(
            [df[[self.user_col, self.item_col, self.time_col, self.label_col]], negative],
            ignore_index=True,
        )
        return out_df
