"""Negative sampling for the ranker: popular-minus-positives per user.

Reference parity: ``transformers/NegativeBalancer.scala:13-119`` — per user,
take the (popularity-ordered) popular-item set minus the user's positives,
emit the first ``negativePositiveRatio * n_positives`` of them with label
``negativeValue`` and the sentinel timestamp 1999-07-01 (:107), then union with
the positives. The LinkedHashSet preserves popularity order, so negatives are
deterministically the most popular items the user has NOT starred — same here
(SURVEY.md §7 hard part (f)).

The RDD ``aggregateByKey`` over a broadcast set becomes one vectorized numpy
pass on the host.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from albedo_tpu.datasets.ragged import segment_positions
from albedo_tpu.features.pipeline import Transformer

# 1999-07-01T00:00:00Z, the reference's sentinel (NegativeBalancer.scala:107).
SENTINEL_TIME = 930787200.0


class NegativeBalancer(Transformer):
    def __init__(
        self,
        popular_items: np.ndarray,
        user_col: str = "user_id",
        item_col: str = "repo_id",
        time_col: str = "starred_at",
        label_col: str = "starring",
        negative_value: float = 0.0,
        negative_positive_ratio: float = 1.0,
    ):
        # Popularity-ordered (most popular first), like the broadcast
        # LinkedHashSet built from loadPopularRepoDF (LogisticRegressionRanker.scala:250-255).
        self.popular_items = np.asarray(popular_items, dtype=np.int64)
        self.user_col = user_col
        self.item_col = item_col
        self.time_col = time_col
        self.label_col = label_col
        self.negative_value = negative_value
        self.negative_positive_ratio = negative_positive_ratio

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        self.require_cols(df, [self.user_col, self.item_col, self.time_col, self.label_col])
        users = df[self.user_col].to_numpy(np.int64)
        items = df[self.item_col].to_numpy(np.int64)
        neg_users, neg_items = self.sample_negatives(users, items)
        negative = pd.DataFrame(
            {
                self.user_col: neg_users,
                self.item_col: neg_items,
                self.time_col: np.full(len(neg_items), SENTINEL_TIME),
                self.label_col: np.full(len(neg_items), self.negative_value),
            }
        )
        out_df = pd.concat(
            [df[[self.user_col, self.item_col, self.time_col, self.label_col]], negative],
            ignore_index=True,
        )
        return out_df

    def sample_negatives(
        self, users: np.ndarray, items: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        """Per user: the first ``ratio * n_positives`` popularity-ordered items
        the user has NOT starred, fully vectorized.

        The round-1 implementation walked the popularity list per user in
        Python (O(users x popular) with per-item casts — VERDICT.md weak #4).
        Here the walk is replaced by the classic "j-th missing index" formula:
        with a user's positive popularity-ranks sorted as p_0 < p_1 < ... and
        g_i = p_i - i, the j-th non-positive index is f(j) = j + |{i: g_i <= j}|,
        computed for all users at once with one composite-key searchsorted.
        """
        pop = self.popular_items
        n_pop = pop.size
        if users.size == 0 or n_pop == 0:
            return np.zeros(0, np.int64), np.zeros(0, np.int64)

        # Distinct (user, item) pairs, user-major (the reference aggregates
        # positives into a per-user set first).
        order = np.lexsort((items, users))
        du, di = users[order], items[order]
        first = np.ones(du.size, dtype=bool)
        first[1:] = (du[1:] != du[:-1]) | (di[1:] != di[:-1])
        du, di = du[first], di[first]

        # Popularity rank of each distinct positive (or -1 if not popular).
        pop_order = np.argsort(pop, kind="stable")
        pop_sorted = pop[pop_order]
        loc = np.searchsorted(pop_sorted, di)
        loc_c = np.minimum(loc, n_pop - 1)
        in_pop = pop_sorted[loc_c] == di
        rank = np.where(in_pop, pop_order[loc_c], -1)

        # Per-user group boundaries over the distinct pairs.
        u_starts = np.nonzero(np.concatenate(([True], du[1:] != du[:-1])))[0]
        n_pos = np.diff(np.concatenate((u_starts, [du.size])))
        uniq_users = du[u_starts]
        n_users = uniq_users.size
        user_idx = np.repeat(np.arange(n_users), n_pos)

        # Sorted positive ranks per user -> g = p_i - i within each group.
        k_per_user = np.bincount(user_idx[in_pop], minlength=n_users)
        g_user = user_idx[in_pop]
        g_order = np.lexsort((rank[in_pop], g_user))
        g_user = g_user[g_order]
        g_rank = rank[in_pop][g_order]
        g = g_rank - segment_positions(k_per_user)  # non-decreasing per user

        need = (n_pos * self.negative_positive_ratio).astype(np.int64)
        take = np.minimum(need, n_pop - k_per_user)
        take = np.maximum(take, 0)

        # Flat (user, j) queries; one searchsorted over composite keys
        # user*K + value resolves the per-user count(g <= j).
        q_user = np.repeat(np.arange(n_users), take)
        j = segment_positions(take)
        K = np.int64(n_pop + 1)
        g_keys = g_user.astype(np.int64) * K + g.astype(np.int64)
        q_keys = q_user.astype(np.int64) * K + j.astype(np.int64)
        k_prefix = np.cumsum(k_per_user) - k_per_user
        count = np.searchsorted(g_keys, q_keys, side="right") - k_prefix[q_user]
        f = j + count
        return uniq_users[q_user], pop[f].astype(np.int64)
