"""Feature-engineering layer (L2): the Estimator/Transformer protocol and the
concrete transformers the profile builders and ranker pipelines compose.

Reference parity: ``src/main/scala/ws/vinta/albedo/transformers/`` and the two
forked Spark classes (``SimpleVectorAssembler``, ``FuncTransformer``). The
assembly target differs by design: instead of one giant sparse vector column
(million-wide one-hots over user_id/repo_id,
``LogisticRegressionRanker.scala:156-157``), features assemble into a
``FeatureMatrix`` of dense blocks + categorical index fields + padded bag
fields that TPU kernels consume as gathers and segment-sums
(SURVEY.md §7 hard part (e)).
"""

from albedo_tpu.features.assembler import FeatureAssembler, FeatureMatrix
from albedo_tpu.features.balancer import NegativeBalancer
from albedo_tpu.features.cross import UserRepoTransformer
from albedo_tpu.features.indexers import FrequencyBinner, StringIndexer, StringIndexerModel
from albedo_tpu.features.pipeline import Estimator, FuncTransformer, Pipeline, PipelineModel, Transformer
from albedo_tpu.features.text import (
    CountVectorizer,
    CountVectorizerModel,
    HanLPTokenizer,
    SnowballStemmer,
    StopWordsRemover,
    Tokenizer,
)
from albedo_tpu.features.weights import InstanceWeigher

__all__ = [
    "CountVectorizer",
    "CountVectorizerModel",
    "Estimator",
    "FeatureAssembler",
    "FeatureMatrix",
    "FrequencyBinner",
    "FuncTransformer",
    "HanLPTokenizer",
    "InstanceWeigher",
    "NegativeBalancer",
    "Pipeline",
    "PipelineModel",
    "SnowballStemmer",
    "StopWordsRemover",
    "StringIndexer",
    "StringIndexerModel",
    "Tokenizer",
    "Transformer",
    "UserRepoTransformer",
]
