"""Text transformers: CJK-aware tokenizer, stop-word removal, count
vectorizer, stemmer.

Reference parity:
- ``transformers/HanLPTokenizer.scala:29-51`` — lowercase, segment, keep
  ``c/r/c++/c#/f#`` as tokens, drop 1-char non-CJK tokens, CJK-aware word
  regex. HanLP's dictionary-driven Chinese segmentation is replaced by CJK
  character unigrams (a pluggable ``segmenter`` hook accepts a real segmenter);
  everything else matches.
- Spark's ``StopWordsRemover`` with the default english list
  (``LogisticRegressionRanker.scala:207-209``).
- ``CountVectorizer().setMinDF(10).setMinTF(1)`` per list column
  (``LogisticRegressionRanker.scala:190-198``), producing bag fields (padded
  index/count arrays) instead of sparse vectors.
- ``transformers/SnowballStemmer.scala:16-28`` — here a self-contained Porter
  stemmer (no external snowball dependency).
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Callable, Sequence

import numpy as np
import pandas as pd

from albedo_tpu.features.assembler import set_vocab_size
from albedo_tpu.features.pipeline import Estimator, Transformer, col_values, memo_map

_LANGUAGE_TOKENS = {"c", "r", "c++", "c#", "f#"}
_RE_CJK_CHAR = re.compile("[぀-ゟ゠-ヿ㄀-ㄯ豈-﫿一-鿿]")
# One left-to-right scan: a `c++`/`c#`/`f#` language token (only where a plain
# word wouldn't swallow it: "libc++" tokenizes as "libc", not a phantom c++)
# or a CJK-aware word. Order of appearance is preserved for w2v windows.
_RE_TOKEN = re.compile(
    "(c\\+\\+|c#|f#)(?![\\w+#])"  # group 1: language tokens with suffix guard
    f"|([{'' }\\w.\\-_぀-ゟ゠-ヿ㄀-ㄯ豈-﫿一-鿿]+)"  # group 2: words
)

# Spark's StopWordsRemover.loadDefaultStopWords("english") list.
ENGLISH_STOP_WORDS = frozenset(
    """i me my myself we our ours ourselves you your yours yourself yourselves he
him his himself she her hers herself it its itself they them their theirs
themselves what which who whom this that these those am is are was were be been
being have has had having do does did doing a an the and but if or because as
until while of at by for with about against between into through during before
after above below to from up down in out on off over under again further then
once here there when where why how all any both each few more most other some
such no nor not only own same so than too very s t can will just don should now
i'll you'll he'll she'll we'll they'll i'd you'd he'd she'd we'd they'd i'm
you're he's she's it's we're they're i've we've you've they've isn't aren't
wasn't weren't haven't hasn't hadn't don't doesn't didn't won't wouldn't
shan't shouldn't mustn't can't couldn't cannot could here's how's let's ought
that's there's what's when's where's who's why's would""".split()
)


def _cjk_unigrams(run: str) -> list[str]:
    """Character-unigram fallback segmenter (the r1-r4 default)."""
    return list(run)


class Tokenizer(Transformer):
    """CJK-aware tokenizer over a string column -> list-of-tokens column.

    CJK runs go through ``segmenter``: by default the built-in
    frequency-dictionary Viterbi segmenter
    (``features/cjk_segmenter.py`` — the HanLP-parity word-level behavior,
    ``transformers/HanLPTokenizer.scala:29-51``); pass ``_cjk_unigrams`` for
    character unigrams or any custom callable."""

    def __init__(
        self,
        input_col: str,
        output_col: str | None = None,
        remove_stop_words: bool = True,
        segmenter: Callable[[str], list[str]] | None = None,
    ):
        if segmenter is None:
            from albedo_tpu.features.cjk_segmenter import default_segmenter

            segmenter = default_segmenter()
        self.input_col = input_col
        self.output_col = output_col or f"{input_col}__words"
        self.remove_stop_words = remove_stop_words
        self.segmenter = segmenter

    def tokenize(self, text: str) -> list[str]:
        text = text.lower()
        out: list[str] = []
        for m in _RE_TOKEN.finditer(text):
            if m.group(1):  # c++ / c# / f# kept whole (HanLPTokenizer:39)
                out.append(m.group(1))
                continue
            word = m.group(2)
            if word in _LANGUAGE_TOKENS:
                out.append(word)  # single-letter languages c / r survive
            elif _RE_CJK_CHAR.search(word):
                # Split mixed runs into CJK segments + latin remainder.
                for run in re.findall(f"{_RE_CJK_CHAR.pattern}+|[^぀-ゟ゠-ヿ㄀-ㄯ豈-﫿一-鿿]+", word):
                    if _RE_CJK_CHAR.search(run):
                        out.extend(self.segmenter(run))
                    elif len(run) > 1:
                        out.append(run)
            elif len(word) > 1:
                out.append(word)  # 1-char non-CJK tokens dropped (HanLPTokenizer:40-47)
        if self.remove_stop_words:
            out = [w for w in out if w not in ENGLISH_STOP_WORDS]
        return out

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        self.require_cols(df, [self.input_col])
        out = df.copy()
        out[self.output_col] = memo_map(
            df[self.input_col], lambda t: self.tokenize(t or "")
        )
        return out


# Alias documenting which reference class this replaces.
HanLPTokenizer = Tokenizer


class StopWordsRemover(Transformer):
    def __init__(
        self,
        input_col: str,
        output_col: str | None = None,
        stop_words: Sequence[str] | frozenset = ENGLISH_STOP_WORDS,
    ):
        self.input_col = input_col
        self.output_col = output_col or f"{input_col}__filtered"
        self.stop_words = frozenset(stop_words)

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        self.require_cols(df, [self.input_col])
        out = df.copy()
        out[self.output_col] = memo_map(
            df[self.input_col],
            lambda words: [w for w in words if w not in self.stop_words],
            key=tuple,
        )
        return out


class CountVectorizerModel(Transformer):
    """Token lists -> bag columns: ``{out}__bag_idx`` / ``{out}__bag_val``
    (variable-length int/float arrays; the assembler pads them)."""

    def __init__(self, input_col: str, output_col: str, vocab: list[str], binary: bool = False):
        self.input_col = input_col
        self.output_col = output_col
        self.vocab = list(vocab)
        self.binary = binary
        self._index = {w: i for i, w in enumerate(self.vocab)}

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    def _bag(self, words) -> tuple[np.ndarray, np.ndarray]:
        counts = Counter(self._index[w] for w in words if w in self._index)
        idx = np.fromiter(counts.keys(), dtype=np.int32, count=len(counts))
        val = np.fromiter(counts.values(), dtype=np.float32, count=len(counts))
        if self.binary:
            val = np.ones_like(val)
        return idx, val

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        self.require_cols(df, [self.input_col])
        bags = memo_map(df[self.input_col], self._bag, key=tuple)
        out = df.copy()
        out[f"{self.output_col}__bag_idx"] = [b[0] for b in bags]
        out[f"{self.output_col}__bag_val"] = [b[1] for b in bags]
        set_vocab_size(out, self.output_col, self.vocab_size)
        return out


class CountVectorizer(Estimator):
    """Vocab = terms appearing in >= ``min_df`` documents, most frequent first,
    capped at ``max_vocab`` (Spark CountVectorizer semantics)."""

    def __init__(
        self,
        input_col: str,
        output_col: str | None = None,
        min_df: int = 10,
        max_vocab: int = 1 << 18,
        binary: bool = False,
    ):
        self.input_col = input_col
        self.output_col = output_col or f"{input_col}__cv"
        self.min_df = min_df
        self.max_vocab = max_vocab
        self.binary = binary

    def fit(self, df: pd.DataFrame) -> CountVectorizerModel:
        # min_df filters on DOCUMENT frequency; vocab order/truncation use
        # total TERM frequency — Spark CountVectorizer semantics. Each ROW is
        # a document (repeats count separately), so repeated docs are counted
        # once with their multiplicity instead of re-walked per row.
        doc_mult: Counter = Counter(
            tuple(words) for words in col_values(df[self.input_col])
        )
        doc_freq: Counter = Counter()
        term_freq: Counter = Counter()
        for doc, m in doc_mult.items():
            for w in set(doc):
                doc_freq[w] += m
            for w in doc:
                term_freq[w] += m
        terms = [
            (w, term_freq[w]) for w, c in doc_freq.items() if c >= self.min_df
        ]
        terms.sort(key=lambda wc: (-wc[1], wc[0]))
        vocab = [w for w, _ in terms[: self.max_vocab]]
        return CountVectorizerModel(self.input_col, self.output_col, vocab, self.binary)


class SnowballStemmer(Transformer):
    """English Porter stemmer over a token-list column
    (``transformers/SnowballStemmer.scala``; defined there but not wired into
    the main pipelines — same here)."""

    def __init__(self, input_col: str, output_col: str | None = None):
        self.input_col = input_col
        self.output_col = output_col or f"{input_col}__stemmed"

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        self.require_cols(df, [self.input_col])
        out = df.copy()
        out[self.output_col] = [
            [porter_stem(w) for w in ws] for ws in col_values(df[self.input_col])
        ]
        return out


# --- Porter stemmer (self-contained) ----------------------------------------

_VOWELS = "aeiou"


def _is_cons(word: str, i: int) -> bool:
    c = word[i]
    if c in _VOWELS:
        return False
    if c == "y":
        return i == 0 or not _is_cons(word, i - 1)
    return True


def _measure(stem: str) -> int:
    """Number of VC sequences."""
    m, prev_vowel = 0, False
    for i in range(len(stem)):
        cons = _is_cons(stem, i)
        if cons and prev_vowel:
            m += 1
        prev_vowel = not cons
    return m


def _has_vowel(stem: str) -> bool:
    return any(not _is_cons(stem, i) for i in range(len(stem)))


def _ends_double_cons(word: str) -> bool:
    return len(word) >= 2 and word[-1] == word[-2] and _is_cons(word, len(word) - 1)


def _cvc(word: str) -> bool:
    if len(word) < 3:
        return False
    return (
        _is_cons(word, len(word) - 3)
        and not _is_cons(word, len(word) - 2)
        and _is_cons(word, len(word) - 1)
        and word[-1] not in "wxy"
    )


def porter_stem(word: str) -> str:
    if len(word) <= 2:
        return word
    w = word.lower()

    # step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif not w.endswith("ss") and w.endswith("s"):
        w = w[:-1]

    # step 1b
    flag = False
    if w.endswith("eed"):
        if _measure(w[:-3]) > 0:
            w = w[:-1]
    elif w.endswith("ed") and _has_vowel(w[:-2]):
        w, flag = w[:-2], True
    elif w.endswith("ing") and _has_vowel(w[:-3]):
        w, flag = w[:-3], True
    if flag:
        if w.endswith(("at", "bl", "iz")):
            w += "e"
        elif _ends_double_cons(w) and not w.endswith(("l", "s", "z")):
            w = w[:-1]
        elif _measure(w) == 1 and _cvc(w):
            w += "e"

    # step 1c
    if w.endswith("y") and _has_vowel(w[:-1]):
        w = w[:-1] + "i"

    # step 2
    for suf, rep in (
        ("ational", "ate"), ("tional", "tion"), ("enci", "ence"), ("anci", "ance"),
        ("izer", "ize"), ("abli", "able"), ("alli", "al"), ("entli", "ent"),
        ("eli", "e"), ("ousli", "ous"), ("ization", "ize"), ("ation", "ate"),
        ("ator", "ate"), ("alism", "al"), ("iveness", "ive"), ("fulness", "ful"),
        ("ousness", "ous"), ("aliti", "al"), ("iviti", "ive"), ("biliti", "ble"),
    ):
        if w.endswith(suf):
            if _measure(w[: -len(suf)]) > 0:
                w = w[: -len(suf)] + rep
            break

    # step 3
    for suf, rep in (
        ("icate", "ic"), ("ative", ""), ("alize", "al"), ("iciti", "ic"),
        ("ical", "ic"), ("ful", ""), ("ness", ""),
    ):
        if w.endswith(suf):
            if _measure(w[: -len(suf)]) > 0:
                w = w[: -len(suf)] + rep
            break

    # step 4
    for suf in (
        "al", "ance", "ence", "er", "ic", "able", "ible", "ant", "ement",
        "ment", "ent", "ou", "ism", "ate", "iti", "ous", "ive", "ize",
    ):
        if w.endswith(suf):
            if _measure(w[: -len(suf)]) > 1:
                w = w[: -len(suf)]
            break
    else:
        if w.endswith("ion") and len(w) > 3 and w[-4] in "st" and _measure(w[:-3]) > 1:
            w = w[:-3]

    # step 5a
    if w.endswith("e"):
        stem = w[:-1]
        if _measure(stem) > 1 or (_measure(stem) == 1 and not _cvc(stem)):
            w = stem
    # step 5b
    if _measure(w) > 1 and _ends_double_cons(w) and w.endswith("l"):
        w = w[:-1]
    return w
