"""Feature assembly into TPU-consumable blocks.

Reference parity: ``org/apache/spark/ml/feature/SimpleVectorAssembler.scala:35-115``
concatenates boolean/continuous/one-hot/count-vector/word2vec columns into one
sparse ``features`` vector per row. A literal port would make million-wide
one-hots over ``user_id``/``repo_id`` (``LogisticRegressionRanker.scala:156-157``)
— hostile to the MXU. Instead assembly produces a ``FeatureMatrix``:

- ``dense``  (N, D) float32 — booleans, continuous scalars, and fixed-dim
  vector columns (word2vec embeddings), MXU-friendly;
- ``cat``    per-field (N,) int32 index arrays — consumed as weight-row
  gathers (mathematically identical to one-hot x weight);
- ``bags``   per-field padded (N, L) index/value arrays — consumed as gather +
  masked segment-sum (the count-vector fields).

Total feature dimensionality (``num_features``) matches what the one-hot
assembler would have produced, and ``to_dense()`` materializes that exact
layout for small-data equivalence tests.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pandas as pd

from albedo_tpu.datasets.ragged import segment_positions
from albedo_tpu.features.pipeline import Estimator, Transformer, col_values

VOCAB_ATTR = "albedo_vocab_size"  # df.attrs[VOCAB_ATTR][col] = size hint


def _dedup_rows(*cols):
    """(repr_index (N,), [distinct values per col]) keyed by object identity.

    The memoized per-document transforms (``memo_map``) alias repeated
    documents to the SAME result objects, so identity-dedup collapses a
    row-set that repeats each user/repo document ~100x down to the distinct
    documents; padding/stacking then runs once per distinct value and rows
    are materialized by one vectorized gather. Non-aliased inputs still work
    — every row is simply its own representative.
    """
    n = len(cols[0])
    slot: dict = {}
    rep = np.empty(n, dtype=np.int64)
    uniq = tuple([] for _ in cols)
    for r in range(n):
        key = tuple(id(c[r]) for c in cols)
        j = slot.get(key)
        if j is None:
            j = len(uniq[0])
            slot[key] = j
            for u, c in zip(uniq, cols):
                u.append(c[r])
        rep[r] = j
    return rep, uniq


def set_vocab_size(df: pd.DataFrame, col: str, size: int) -> None:
    df.attrs.setdefault(VOCAB_ATTR, {})[col] = int(size)


@dataclasses.dataclass
class FeatureMatrix:
    """Assembled features for N rows, in blocks (see module docstring).

    The logical dense block is ``[scalar columns | vector columns]``;
    vector columns (fixed-dim embeddings, e.g. word2vec documents) are
    stored FACTORED as ``vec[f]`` (U_f, D_f) distinct vectors plus
    ``vec_rep[f]`` (N,) representative indices: each user/repo document
    repeats across ~100s of (user, repo) rows, so the expanded copy is
    ~30-50x larger than the distinct set (657 MB vs ~20 MB at r5 ranker
    bench scale — dominating the host->device upload). Device code gathers
    ``vec[rep]`` instead; ``expanded_dense()`` materializes the flat layout
    for compatibility paths."""

    dense: np.ndarray                    # (N, D_scalar) float32
    dense_names: list[str]               # scalar names then vec[f][i] names
    cat: dict[str, np.ndarray]           # field -> (N,) int32
    cat_sizes: dict[str, int]
    bag_idx: dict[str, np.ndarray]       # field -> (U_f|N, L) int32, -1 on padding
    bag_val: dict[str, np.ndarray]       # field -> (U_f|N, L) float32, 0 on padding
    bag_sizes: dict[str, int]
    vec: dict[str, np.ndarray] = dataclasses.field(default_factory=dict)
    vec_rep: dict[str, np.ndarray] = dataclasses.field(default_factory=dict)
    # Optional per-field (N,) rep indices into FACTORED bag rows: bag columns
    # are per-user/per-repo documents repeated across ~50-80 (user, repo)
    # rows, so the distinct-document representation shrinks the flat entry
    # streams (and their per-linesearch-eval TPU gathers) by that factor.
    # A field absent here keeps per-row (N, L) semantics.
    bag_rep: dict[str, np.ndarray] = dataclasses.field(default_factory=dict)

    @property
    def n_rows(self) -> int:
        return int(self.dense.shape[0])

    @property
    def dense_width(self) -> int:
        """Width of the LOGICAL dense block: scalars + factored vec columns."""
        return int(self.dense.shape[1]) + sum(int(v.shape[1]) for v in self.vec.values())

    def vec_fields(self) -> list[str]:
        """Vec field names in the CANONICAL (sorted) order — the order of
        their slices within the logical dense block. Sorted because jax
        reconstructs dict pytrees in sorted-key order inside jit, so offset
        pairing must not depend on insertion order."""
        return sorted(self.vec)

    @property
    def num_features(self) -> int:
        """Width of the equivalent flat one-hot feature vector."""
        return (
            self.dense_width
            + sum(self.cat_sizes.values())
            + sum(self.bag_sizes.values())
        )

    def expanded_dense(self) -> np.ndarray:
        """The (N, dense_width) dense block with vec fields expanded — the
        pre-r5 layout, used by the row-sharded mesh path and to_dense."""
        if not self.vec:
            return self.dense
        return np.concatenate(
            [self.dense] + [self.vec[f][self.vec_rep[f]] for f in self.vec_fields()],
            axis=1,
        )

    def select(self, rows: np.ndarray) -> "FeatureMatrix":
        return FeatureMatrix(
            dense=self.dense[rows],
            dense_names=self.dense_names,
            cat={k: v[rows] for k, v in self.cat.items()},
            cat_sizes=self.cat_sizes,
            bag_idx={
                k: (v if k in self.bag_rep else v[rows])
                for k, v in self.bag_idx.items()
            },
            bag_val={
                k: (v if k in self.bag_rep else v[rows])
                for k, v in self.bag_val.items()
            },
            bag_sizes=self.bag_sizes,
            vec=self.vec,
            vec_rep={k: v[rows] for k, v in self.vec_rep.items()},
            bag_rep={k: v[rows] for k, v in self.bag_rep.items()},
        )

    def expanded_bag(self, f: str) -> tuple[np.ndarray, np.ndarray]:
        """The per-row (N, L) ``(idx, val)`` view of a bag field, whether it
        is stored factored or per-row."""
        idx, val = self.bag_idx[f], self.bag_val[f]
        rep = self.bag_rep.get(f)
        if rep is None:
            return idx, val
        return idx[rep], val[rep]

    def flat_bags(self) -> dict[str, tuple]:
        """Per bag field, the row-major flat entries ``(rows, vocab, vals)``
        of the STORED arrays — distinct-document rows for factored fields
        (``bag_rep``), per-data rows otherwise. Memoized, because both the
        device batch layout and the standardization moments need it (two
        full passes over ~100M-element masks at bench scale otherwise)."""
        cached = self.__dict__.get("_flat_bag_cache")
        if cached is None:
            cached = {}
            for f in self.bag_idx:
                idx, val = self.bag_idx[f], self.bag_val[f]
                ok = idx >= 0
                rows = np.broadcast_to(
                    np.arange(idx.shape[0], dtype=np.int64)[:, None], idx.shape
                )[ok]
                cached[f] = (rows, idx[ok].astype(np.int32), val[ok].astype(np.float32))
            self.__dict__["_flat_bag_cache"] = cached
        return cached

    def to_dense(self) -> np.ndarray:
        """Materialize the flat one-hot layout (tests / small data only):
        [dense | one-hot(cat fields) | multi-hot(bag fields)]."""
        n = self.n_rows
        out = [self.expanded_dense()]
        for name in self.cat:
            block = np.zeros((n, self.cat_sizes[name]), dtype=np.float32)
            idx = self.cat[name]
            ok = (idx >= 0) & (idx < self.cat_sizes[name])
            block[np.nonzero(ok)[0], idx[ok]] = 1.0
            out.append(block)
        for name in self.bag_idx:
            block = np.zeros((n, self.bag_sizes[name]), dtype=np.float32)
            idx, val = self.expanded_bag(name)
            rows = np.repeat(np.arange(n), idx.shape[1]).reshape(idx.shape)
            ok = idx >= 0
            np.add.at(block, (rows[ok], idx[ok]), val[ok])
            out.append(block)
        return np.concatenate(out, axis=1)


from albedo_tpu.utils import pow2_at_least as _pow2_at_least


class FeatureAssemblerModel(Transformer):
    def __init__(
        self,
        dense_cols: list[str],
        vector_cols: list[str],
        cat_sizes: dict[str, int],
        bag_sizes: dict[str, int],
        bag_pad: dict[str, int],
    ):
        self.dense_cols = dense_cols
        self.vector_cols = vector_cols
        self.cat_sizes = cat_sizes
        self.bag_sizes = bag_sizes
        self.bag_pad = bag_pad

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        return df  # assembly happens via assemble(); frame passes through

    def assemble(self, df: pd.DataFrame) -> FeatureMatrix:
        n = len(df)
        blocks, names = [], []
        for c in self.dense_cols:
            self.require_cols(df, [c])
            blocks.append(
                pd.to_numeric(df[c], errors="coerce")
                .fillna(0.0)
                .to_numpy(np.float32)
                .reshape(n, 1)
            )
            names.append(c)
        vec, vec_rep = {}, {}
        # CANONICAL vec-field order is sorted(name): jax flattens dict
        # pytrees in sorted-key order, so everything that pairs per-field
        # slices of the flat dense coefficient vector (block_logits offsets,
        # scales, center, dense_names) must agree on sorted order — insertion
        # order is unrecoverable inside jit.
        for c in sorted(self.vector_cols):
            self.require_cols(df, [c])
            if n:
                rep, (uniq,) = _dedup_rows(col_values(df[c]))
                vec[c] = np.stack([np.asarray(v, dtype=np.float32) for v in uniq])
                vec_rep[c] = rep.astype(np.int32)
            else:
                vec[c] = np.zeros((0, 0), np.float32)
                vec_rep[c] = np.zeros((0,), np.int32)
            # Stored factored (distinct vectors + rep), not expanded — the
            # expanded copy is what made the r4 LR batch 657 MB.
            names.extend(f"{c}[{i}]" for i in range(vec[c].shape[1]))
        dense = (
            np.concatenate(blocks, axis=1)
            if blocks
            else np.zeros((n, 0), dtype=np.float32)
        )

        cat = {}
        for c, size in self.cat_sizes.items():
            self.require_cols(df, [c])
            idx = df[c].to_numpy(np.int64)
            # Unknown slot (= size - 1 under StringIndexer "keep") already
            # encoded; clip runaway values defensively.
            cat[c] = np.clip(idx, 0, size - 1).astype(np.int32)

        bag_idx, bag_val, bag_rep = {}, {}, {}
        for c, size in self.bag_sizes.items():
            ic, vc = f"{c}__bag_idx", f"{c}__bag_val"
            self.require_cols(df, [ic, vc])
            pad = self.bag_pad[c]
            # Pad each DISTINCT bag once (identity dedup over the memoized
            # per-document arrays) and KEEP the factored (distinct, rep)
            # form: the expanded copy repeats each user/repo document across
            # ~50-80 rows, multiplying every downstream host pass and device
            # gather by that factor.
            rep, (u_i, u_v) = _dedup_rows(col_values(df[ic]), col_values(df[vc]))
            u = len(u_i)
            lens = np.fromiter((min(len(a), pad) for a in u_i), np.int64, count=u)
            idx = np.full((u, pad), -1, dtype=np.int32)
            val = np.zeros((u, pad), dtype=np.float32)
            if u and int(lens.sum()):
                pos = segment_positions(lens)
                rows = np.repeat(np.arange(u), lens)
                idx[rows, pos] = np.concatenate(
                    [np.asarray(a[:t], dtype=np.int32) for a, t in zip(u_i, lens)]
                )
                val[rows, pos] = np.concatenate(
                    [np.asarray(a[:t], dtype=np.float32) for a, t in zip(u_v, lens)]
                )
            # -1 rows stay fully masked; real gathers happen on device.
            bag_idx[c] = idx
            bag_val[c] = val
            bag_rep[c] = rep.astype(np.int32)

        return FeatureMatrix(
            dense=dense,
            dense_names=names,
            cat=cat,
            cat_sizes=dict(self.cat_sizes),
            bag_idx=bag_idx,
            bag_val=bag_val,
            bag_sizes=dict(self.bag_sizes),
            vec=vec,
            vec_rep=vec_rep,
            bag_rep=bag_rep,
        )


class FeatureAssembler(Estimator):
    """Resolve block layout from a fitted frame.

    ``cat_cols`` / ``bag_cols`` may map to an explicit vocab size or ``None``
    to resolve from ``df.attrs`` hints (written by StringIndexerModel /
    CountVectorizerModel) or, failing that, ``max+1`` over the fit data.
    Bag pad length = max fit-data bag length rounded up to a power of two
    (bounded shapes for XLA), capped at ``max_bag_pad``.
    """

    def __init__(
        self,
        dense_cols: list[str] | None = None,
        vector_cols: list[str] | None = None,
        cat_cols: dict[str, int | None] | None = None,
        bag_cols: dict[str, int | None] | None = None,
        max_bag_pad: int = 256,
    ):
        self.dense_cols = list(dense_cols or [])
        self.vector_cols = list(vector_cols or [])
        self.cat_cols = dict(cat_cols or {})
        self.bag_cols = dict(bag_cols or {})
        self.max_bag_pad = max_bag_pad

    def fit(self, df: pd.DataFrame) -> FeatureAssemblerModel:
        hints = df.attrs.get(VOCAB_ATTR, {})
        cat_sizes = {}
        for c, size in self.cat_cols.items():
            if size is None:
                size = hints.get(c)
            if size is None:
                size = int(df[c].max()) + 1 if len(df) else 1
            cat_sizes[c] = int(size)
        bag_sizes, bag_pad = {}, {}
        for c, size in self.bag_cols.items():
            if size is None:
                size = hints.get(c)
            if size is None:
                mx = max(
                    (int(np.max(iv)) for iv in col_values(df[f"{c}__bag_idx"]) if len(iv)),
                    default=-1,
                )
                size = mx + 1
            bag_sizes[c] = int(size)
            longest = max((len(iv) for iv in col_values(df[f"{c}__bag_idx"])), default=1)
            bag_pad[c] = min(self.max_bag_pad, _pow2_at_least(max(1, longest)))
        return FeatureAssemblerModel(
            self.dense_cols, self.vector_cols, cat_sizes, bag_sizes, bag_pad
        )
