"""Instance-weight columns for the weighted LR.

Reference parity: the ``SQLTransformer`` weight SQL at
``LogisticRegressionRanker.scala:316-328`` — five variants:

- ``default_weight``                 1.0
- ``positive_weight``                0.9 if starred else 0.1
- ``positive_starred_weight``        0.9 if starred within the last 365 days
- ``positive_created_weight``        0.9 if starred and repo created within 730 days
- ``positive_created_week_weight``   repo-created week number if starred else 1.0

``now`` is injected (the SQL uses ``current_date()``) so tests are
deterministic.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from albedo_tpu.features.pipeline import Transformer

_DAY = 86400.0
_WEEK = 7 * _DAY

WEIGHT_COLUMNS = (
    "default_weight",
    "positive_weight",
    "positive_starred_weight",
    "positive_created_weight",
    "positive_created_week_weight",
)


class InstanceWeigher(Transformer):
    def __init__(
        self,
        now: float,
        label_col: str = "starring",
        time_col: str = "starred_at",
        repo_created_col: str = "repo_created_at",
    ):
        self.now = float(now)
        self.label_col = label_col
        self.time_col = time_col
        self.repo_created_col = repo_created_col

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        self.require_cols(df, [self.label_col, self.time_col, self.repo_created_col])
        pos = df[self.label_col].to_numpy(np.float64) == 1.0
        starred_days = (self.now - df[self.time_col].to_numpy(np.float64)) / _DAY
        created = df[self.repo_created_col].to_numpy(np.float64)
        created_days = (self.now - created) / _DAY

        out = df.copy()
        out["default_weight"] = 1.0
        out["positive_weight"] = np.where(pos, 0.9, 0.1)
        out["positive_starred_weight"] = np.where(pos & (starred_days <= 365), 0.9, 0.1)
        out["positive_created_weight"] = np.where(pos & (created_days <= 730), 0.9, 0.1)
        out["positive_created_week_weight"] = np.where(pos, np.round(created / _WEEK), 1.0)
        return out
