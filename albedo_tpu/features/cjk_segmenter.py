"""Dictionary-driven Chinese word segmentation.

Reference parity: the reference tokenizes text with HanLP, whose standard
tokenizer segments Chinese into dictionary words
(``transformers/HanLPTokenizer.scala:29-51``). Rounds 1-4 here emitted
character unigrams behind the ``Tokenizer(segmenter=...)`` hook; for Chinese
repo descriptions that changes the CountVectorizer/Word2Vec vocabulary
materially (VERDICT r4 missing #2), so this module supplies a real built-in
segmenter and makes it the default.

Algorithm: unigram-frequency Viterbi over the word lattice (the approach of
jieba/HanLP's core): every dictionary word spanning ``text[i:j]`` is a
lattice edge weighted by its smoothed log frequency; single characters are
always edges (OOV fallback, heavily penalized so known multi-char words win);
dynamic programming picks the max-probability path. Equivalent to maximum
matching on this dictionary when frequencies are flat, strictly better when
they are not (classic "北京大学生"-style ambiguities resolve by frequency).

The built-in dictionary is a compact general+software-domain word list with
coarse frequency classes — intentionally small (hundreds of entries, the
long tail of GitHub-description Chinese is domain terms); callers pass
``extra_words`` or a full custom dictionary for broader coverage, or any
other ``Callable[[str], list[str]]`` through the ``segmenter`` hook.
"""

from __future__ import annotations

import math
from typing import Iterable, Mapping

# Coarse frequency classes: (weight, words). Weights are relative unigram
# counts; only their ratios matter to the Viterbi path.
_WORD_CLASSES: list[tuple[int, str]] = [
    # -- very common function words / verbs --
    (500, "的 是 在 和 了 有 与 及 或 等 不 这 那 我们 你们 他们 它 我 你 他 她"),
    (300, "一个 可以 使用 支持 提供 基于 通过 进行 实现 包括 帮助 需要 如何 什么 没有 非常 更多 所有 相关 主要 简单 快速 轻松 免费 中文 英文 自动 手动"),
    # -- software / github domain --
    (200, "代码 程序 项目 工具 框架 系统 应用 软件 开发 学习 数据 文档 教程 示例 例子 插件 模块 组件 功能 接口 服务 平台 环境 版本 配置 管理 测试 部署 安装 运行 构建 编译 调试 优化 性能 安全 网络 前端 后端 全栈 脚本 语言 编程 算法 模型 训练 推理 解析 爬虫 采集 下载 上传 搜索 推荐 分析 统计 可视化 监控 日志 缓存 队列 存储 备份 同步 异步 并发 分布式 集群 容器 镜像 仓库 分支 合并 提交 发布 更新 升级 迁移 扩展 集成 封装 抽象 继承 注解 反射 泛型 协程 线程 进程 内存 磁盘 文件 目录 路径 字符串 数组 列表 字典 函数 方法 类库 源码 开源 社区 贡献 许可 协议"),
    (150, "数据库 服务器 客户端 浏览器 操作系统 命令行 图形界面 用户界面 小程序 公众号 微信 支付宝 淘宝 百度 腾讯 阿里 谷歌 苹果 微软 亚马逊"),
    (150, "机器学习 深度学习 神经网络 人工智能 自然语言 计算机 大数据 云计算 区块链 物联网 图像识别 语音识别 文本分类 知识图谱 强化学习 迁移学习 卷积 循环 注意力 预训练 微调"),
    (100, "一键 一站式 高性能 高可用 跨平台 多平台 轻量级 企业级 工业级 实时 离线 在线 本地 远程 移动端 桌面端 网页版"),
    # -- general nouns common in bios/descriptions --
    (100, "中国 北京 上海 深圳 杭州 广州 大学 学生 工程师 程序员 开发者 设计师 产品 经理 团队 公司 技术 科技 互联网 信息 世界 时间 问题 方案 解决 方式 方法 内容 资源 资料 笔记 博客 网站 论坛 书籍 视频 音乐 电影 游戏 小说 新闻 天气 地图 翻译 词典 日历 邮件 聊天 直播 短信 电话 照片 图片 头像 二维码"),
    (80, "记录 分享 收集 整理 汇总 精选 推荐系统 练习 入门 进阶 高级 初级 中级 基础 核心 原理 实践 实战 指南 手册 总结 计划 目标 任务 清单"),
]


def default_dictionary() -> dict[str, int]:
    """The built-in word -> relative-frequency dictionary (copied fresh)."""
    out: dict[str, int] = {}
    for weight, words in _WORD_CLASSES:
        for w in words.split():
            out[w] = max(out.get(w, 0), weight)
    return out


class DictionarySegmenter:
    """Unigram-Viterbi segmenter over a frequency dictionary.

    ``segmenter("机器学习框架")`` -> ``["机器学习", "框架"]``. Unknown spans
    fall back to single characters, so output tokens always cover the input.
    """

    # Log-prob assigned to an out-of-vocabulary single character: below any
    # dictionary word, so known words absorb their characters, but finite so
    # every input segments.
    _OOV_PENALTY = 2.0

    def __init__(
        self,
        dictionary: Mapping[str, int] | None = None,
        extra_words: Iterable[str] | Mapping[str, int] | None = None,
    ):
        words = dict(default_dictionary() if dictionary is None else dictionary)
        if extra_words is not None:
            if isinstance(extra_words, Mapping):
                words.update(extra_words)
            else:
                for w in extra_words:
                    words.setdefault(w, 100)
        total = sum(words.values()) or 1
        self._logp = {w: math.log(c / total) for w, c in words.items() if w}
        self._max_len = max((len(w) for w in self._logp), default=1)
        self._oov = math.log(1.0 / total) - self._OOV_PENALTY

    def __call__(self, text: str) -> list[str]:
        n = len(text)
        if n == 0:
            return []
        if n == 1:
            return [text]
        # best[i] = (score, backpointer start) for the prefix text[:i].
        neg_inf = float("-inf")
        best = [neg_inf] * (n + 1)
        back = [0] * (n + 1)
        best[0] = 0.0
        logp = self._logp
        for i in range(n):
            si = best[i]
            if si == neg_inf:
                continue
            # Single-char edge always exists (dictionary or OOV fallback).
            hi = min(n, i + self._max_len)
            for j in range(i + 1, hi + 1):
                word = text[i:j]
                p = logp.get(word)
                if p is None:
                    if j > i + 1:
                        continue
                    p = self._oov
                s = si + p
                if s > best[j]:
                    best[j] = s
                    back[j] = i
        out: list[str] = []
        j = n
        while j > 0:
            i = back[j]
            out.append(text[i:j])
            j = i
        out.reverse()
        return out


_DEFAULT: DictionarySegmenter | None = None


def default_segmenter() -> DictionarySegmenter:
    """Shared default instance (the dictionary build is done once)."""
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = DictionarySegmenter()
    return _DEFAULT


def segment(text: str) -> list[str]:
    """Module-level convenience: segment with the shared default dictionary."""
    return default_segmenter()(text)
