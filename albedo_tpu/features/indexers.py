"""Categorical indexing: StringIndexer and frequency binning.

Reference parity: the per-categorical ``StringIndexer().setHandleInvalid("keep")``
+ ``OneHotEncoder`` pairs built for every categorical column INCLUDING
``user_id``/``repo_id`` (``LogisticRegressionRanker.scala:176-188``), and the
frequency-binned company/location categoricals
(``UserProfileBuilder.scala:177-200``). The one-hot step is deliberately
absorbed downstream: an indexed column is consumed by the assembler as an
embedding-style index field, which on TPU is a weight-row gather — numerically
identical to a one-hot dot product without materializing million-wide vectors.
"""

from __future__ import annotations

from collections import Counter

import numpy as np
import pandas as pd

from albedo_tpu.features.assembler import set_vocab_size
from albedo_tpu.features.pipeline import Estimator, Transformer, col_values


class StringIndexerModel(Transformer):
    def __init__(self, input_col: str, output_col: str, labels: list, handle_invalid: str = "keep"):
        self.input_col = input_col
        self.output_col = output_col
        self.labels = list(labels)
        self.handle_invalid = handle_invalid
        self._index = {v: i for i, v in enumerate(self.labels)}

    @property
    def vocab_size(self) -> int:
        """Number of distinct output indices (+1 unknown slot under "keep",
        matching Spark's OneHotEncoder dropLast=false width)."""
        return len(self.labels) + (1 if self.handle_invalid == "keep" else 0)

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        self.require_cols(df, [self.input_col])
        unknown = len(self.labels)
        idx = np.fromiter(
            (self._index.get(v, unknown) for v in col_values(df[self.input_col])),
            dtype=np.int64,
            count=len(df),
        )
        if self.handle_invalid == "error" and (idx == unknown).any():
            bad = df[self.input_col][idx == unknown].iloc[0]
            raise ValueError(f"StringIndexer({self.input_col}): unseen label {bad!r}")
        if self.handle_invalid == "skip":
            out = df[idx != unknown].copy()
            out[self.output_col] = idx[idx != unknown]
        else:
            out = df.copy()
            out[self.output_col] = idx
        set_vocab_size(out, self.output_col, self.vocab_size)
        return out


class StringIndexer(Estimator):
    """Fit labels ordered by frequency desc (ties: value asc), Spark's
    ``frequencyDesc`` default."""

    def __init__(self, input_col: str, output_col: str | None = None, handle_invalid: str = "keep"):
        self.input_col = input_col
        self.output_col = output_col or f"{input_col}__idx"
        self.handle_invalid = handle_invalid

    def fit(self, df: pd.DataFrame) -> StringIndexerModel:
        counts = Counter(df[self.input_col])
        labels = [v for v, _ in sorted(counts.items(), key=lambda kv: (-kv[1], str(kv[0])))]
        return StringIndexerModel(self.input_col, self.output_col, labels, self.handle_invalid)


class FrequencyBinner(Estimator):
    """Replace values seen <= ``threshold`` times with ``__other``
    (``user_binned_company`` / ``user_binned_location``,
    ``UserProfileBuilder.scala:188-195``)."""

    def __init__(self, input_col: str, output_col: str, threshold: int, other: str = "__other"):
        self.input_col = input_col
        self.output_col = output_col
        self.threshold = threshold
        self.other = other

    def fit(self, df: pd.DataFrame) -> "FrequencyBinnerModel":
        counts = Counter(df[self.input_col])
        keep = {v for v, c in counts.items() if c > self.threshold}
        return FrequencyBinnerModel(self.input_col, self.output_col, keep, self.other)


class FrequencyBinnerModel(Transformer):
    def __init__(self, input_col: str, output_col: str, keep: set, other: str):
        self.input_col = input_col
        self.output_col = output_col
        self.keep = keep
        self.other = other

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        self.require_cols(df, [self.input_col])
        out = df.copy()
        out[self.output_col] = [
            v if v in self.keep else self.other
            for v in col_values(df[self.input_col])
        ]
        return out
