"""The ``score_all`` CLI job: the full-catalog batch sweep as one command.

Exit-code contract (the repo-wide table in ARCHITECTURE.md):

- 0   sweep complete, canary passed, manifest sealed
- 1   crash, :class:`MeshLost` (loss budget spent) or capacity refusal
- 4   canary gate refused the publish (prior sealed output untouched)
- 75  preempted — the cursor checkpointed; rerun with ``--resume``
- 137 killed by an armed ``kill`` fault (chaos drills)
"""

from __future__ import annotations

import argparse
import time

from albedo_tpu.cli import EXIT_FAILURE, EXIT_REJECTED, register_job


@register_job("score_all")
def score_all_job(args) -> int | None:
    """Score every user through bank MIPS + the LR re-rank and seal the
    per-shard top-k parquet under a canary-gated manifest.

    Extra flags: --score-shard-users N (users per shard, default 256),
    --score-k N (top-k per user, default 30), --score-max-users N (truncate
    the catalog, 0 = everyone), --canary-floor SCORE (absolute NDCG@30
    minimum), --canary-tolerance FRAC (max regression vs the prior sealed
    output's stamp, default 0.10), --publish-force (seal past a failed
    gate, loudly stamped). Honors the global --resume,
    --checkpoint-every/--keep-last (cursor retention), --mesh-devices
    (row-sharded bank + the elastic remesh ladder), --small, --tables.
    """
    from albedo_tpu.builders.jobs import JobContext, _report
    from albedo_tpu.builders.pipeline import PublishRejected
    from albedo_tpu.parallel.elastic import MeshLost
    from albedo_tpu.scoring.sweep import run_score_all
    from albedo_tpu.utils.capacity import CapacityExceeded

    extra = argparse.ArgumentParser()
    extra.add_argument("--score-shard-users", type=int, default=256)
    extra.add_argument("--score-k", type=int, default=30)
    extra.add_argument("--score-max-users", type=int, default=0)
    extra.add_argument("--canary-floor", type=float, default=0.0)
    extra.add_argument("--canary-tolerance", type=float, default=None)
    extra.add_argument("--publish-force", action="store_true")
    ns, _ = extra.parse_known_args(getattr(args, "_rest", []))

    t0 = time.time()
    ctx = JobContext(args)
    try:
        report = run_score_all(
            ctx,
            shard_users=ns.score_shard_users,
            k=ns.score_k,
            max_users=ns.score_max_users,
            canary_floor=ns.canary_floor,
            canary_tolerance=ns.canary_tolerance,
            publish_force=ns.publish_force,
        )
    except PublishRejected as e:
        print(f"[score_all] PUBLISH REFUSED by the canary gate: {e} "
              f"(prior sealed output untouched; --publish-force overrides)")
        return EXIT_REJECTED
    except MeshLost as e:
        print(f"[score_all] MESH LOST: {e} (cursor retained; rerun with "
              f"--resume on healthy hardware)")
        return EXIT_FAILURE
    except CapacityExceeded as e:
        print(f"[score_all] REFUSED by capacity admission before dispatch: {e}")
        return EXIT_FAILURE
    # Preempted propagates: cli.main maps it to exit 75 (--resume continues).
    print(f"[score_all] generation {report['generation']} sealed: "
          f"{report['n_shards']} shards, {report['rows']} rows, "
          f"canary ndcg@30 = {report['canary']['score']}")
    if report["mesh_events"]["losses"]:
        print(f"[score_all] mesh events: {report['mesh_events']}")
    _report("score_all", "users_scored", float(report["users_scored"]), t0)
    return None
