"""The score_all sweep engine: cursor, elastic shard loop, spill, publish.

Layout (everything under one artifact-store root, ``<tag>-score_all/``)::

    <tag>-score_all/
      gen-000002/                     # one staging dir per sweep generation
        shard_00000.parquet           # per-shard top-k (user_id, repo_id,
        shard_00001.parquet           #   score=LR probability, source)
        ...
      manifest.json                   # sealed LAST: generation + every
                                      #   shard's file/sha256/user range
      manifest.json.sha256            # content manifest of the seal
      manifest.json.meta.json         # canary stamp (publish gate record)

Per-shard spill is tmp + ``os.replace`` with the ``score.spill`` fault site
between write and rename — a kill mid-spill leaves an unsealed tmp the
resume walk ignores and re-scores, never a half-written parquet the publish
trusts. The sweep cursor (which shards are sealed, verified by spill hash
on resume) checkpoints through
:class:`~albedo_tpu.utils.checkpoint.JsonStepCheckpointer` after every
shard, so the cursor is mesh-size independent: a sweep checkpointed at 8
virtual devices resumes on 2 (the elastic ladder's semantics, shared with
``parallel/elastic.py``).

Determinism contract: the final per-user top-k is ordered by
(-probability, repo_id) and candidate scores are exact per item whatever
the mesh layout (row-sharded tables keep each item's dot product on one
device), so an interrupted sweep resumed on a different rung spills the
same rankings an uninterrupted single-device run does — the cross-mesh
parity drill in ``tests/test_score_cli.py`` holds it to 1e-5. The cursor
also pins the generation's featurization instant (``ctx.now``): the LR
re-ranker trains in-process from wall-clock-dated features, so a resume
restores the original ``now`` rather than re-training a drifted ranker
against the shards already sealed.
"""

from __future__ import annotations

import os
import shutil
import time
from pathlib import Path

import numpy as np
import pandas as pd

from albedo_tpu.utils import events, faults
from albedo_tpu.utils.jsonio import atomic_write_json, read_json_or_none

# Fault sites (ARCHITECTURE.md "Fault tolerance" catalog): the shard's
# device work, the spill rename seam, and the publish gate.
SHARD_FAULT = faults.site("score.shard")
SPILL_FAULT = faults.site("score.spill")
PUBLISH_FAULT = faults.site("score.publish")

MANIFEST_NAME = "manifest.json"
CURSOR_KEY = "scoreCursor"
_TMP_MARKER = ".albedo-tmp-"
_MAX_LOSSES = 1  # the elastic loss budget, matching elastic_sharded_fit


def score_output_root(tag: str) -> Path:
    """The sweep's artifact-store root for one dataset tag."""
    from albedo_tpu.datasets.artifacts import artifact_path

    return artifact_path(f"{tag}-score_all")


def _gen_dir(out_root: Path, generation: int) -> Path:
    return out_root / f"gen-{generation:06d}"


def _sweep_tmps(gen_dir: Path) -> int:
    """Remove spill tmps a killed run left in OUR generation's staging dir
    (the cursor owns the generation exclusively, so they are always dead)."""
    if not gen_dir.is_dir():
        return 0
    swept = 0
    for p in gen_dir.iterdir():
        if _TMP_MARKER in p.name:
            p.unlink(missing_ok=True)
            swept += 1
    return swept


def _candidate_frame(bank, raw_ids: np.ndarray, dense: np.ndarray, k: int) -> pd.DataFrame:
    """One batch's fused candidates as the fusion-ready recommender frame
    (user_id, repo_id, score, source) — the batched form of
    ``BankStage.query_frames``: calibrated scores, -1/non-finite slots
    dropped. Seen items stay IN (the reference's consumers filter
    downstream; the NDCG probe protocol scores against recent stars)."""
    out = bank.query(dense, k, raw_user_ids=raw_ids)
    frames = []
    for name, (vals, idx) in out.items():
        spec = bank.specs[name]
        scale = float(bank.calibration.get(name, {}).get("scale", 1.0))
        ok = (idx >= 0) & np.isfinite(vals)
        rows, cols = np.nonzero(ok)
        if rows.size == 0:
            continue
        frames.append(pd.DataFrame({
            "user_id": raw_ids[rows],
            "repo_id": spec.item_ids[idx[rows, cols]],
            "score": vals[rows, cols].astype(np.float64) * scale,
            "source": name,
        }))
    if not frames:
        return pd.DataFrame({
            "user_id": pd.Series(dtype=np.int64),
            "repo_id": pd.Series(dtype=np.int64),
            "score": pd.Series(dtype=np.float64),
            "source": pd.Series(dtype=object),
        })
    return pd.concat(frames, ignore_index=True)


def _score_users(bank, ranker, matrix, dense: np.ndarray, k: int) -> pd.DataFrame:
    """Candidate generation + LR re-rank for one user batch: the sweep's
    unit of device work. Cross-source duplicates keep their best
    probability; the final per-user top-k is ordered by (-probability,
    repo_id) — a TOTAL order, so the spill is bitwise reproducible across
    mesh rungs and resume boundaries."""
    raw = np.asarray(matrix.user_ids)[dense]
    candidates = _candidate_frame(bank, raw, dense, k)
    if not len(candidates):
        return candidates
    scored = ranker.score(candidates)
    scored = scored.sort_values(
        ["user_id", "probability", "repo_id"],
        ascending=[True, False, True], kind="mergesort",
    ).drop_duplicates(["user_id", "repo_id"], keep="first")
    top = scored.groupby("user_id", sort=False).head(k)
    return pd.DataFrame({
        "user_id": top["user_id"].to_numpy(np.int64),
        "repo_id": top["repo_id"].to_numpy(np.int64),
        "score": top["probability"].to_numpy(np.float64),
        "source": top["source"].to_numpy(object),
    })


def _spill_shard(gen_dir: Path, idx: int, frame: pd.DataFrame,
                 start: int, stop: int) -> dict:
    """Seal one shard's top-k parquet: tmp write -> fault seam -> rename.
    A kill at the seam leaves only a tmp (swept on resume); the cursor
    records the sealed file's hash so resume can tell a good spill from a
    torn or corrupted one."""
    from albedo_tpu.datasets.artifacts import file_sha256

    gen_dir.mkdir(parents=True, exist_ok=True)
    name = f"shard_{idx:05d}.parquet"
    path = gen_dir / name
    tmp = gen_dir / f"{name}{_TMP_MARKER}{os.getpid()}"
    frame.to_parquet(tmp, index=False)
    SPILL_FAULT.hit(path=tmp)
    os.replace(tmp, path)
    return {
        "file": name,
        "sha256": file_sha256(path),
        "rows": int(len(frame)),
        "start": int(start),
        "stop": int(stop),
    }


def _bank_specs(ctx):
    """The bank's source specs from this context's trained artifacts — the
    ``_context_bank`` recipe WITHOUT the build, so capacity admission can
    price the tables before a single byte moves to device."""
    from albedo_tpu.recommenders import EmbeddingSearchBackend
    from albedo_tpu.recommenders.tfidf import TfidfSimilaritySearch
    from albedo_tpu.retrieval.build import default_bank_specs

    tables = ctx.tables()
    backend = EmbeddingSearchBackend(tables.repo_info, ctx.word2vec())
    search = TfidfSimilaritySearch(min_df=2).fit(tables.repo_info)
    return default_bank_specs(
        ctx.als_model(), ctx.matrix(), starring_df=tables.starring,
        content_backend=backend, tfidf_search=search, top_k=30,
    )


def _admit_score(table_shapes, shard_users: int, k: int, n_devices: int):
    """The resident -> streamed admission ladder for one sweep config.
    Returns the verdict; a refusal raises
    :class:`~albedo_tpu.utils.capacity.CapacityExceeded` HERE — before the
    bank is built, before any shard is read."""
    from albedo_tpu.utils import capacity

    plans = [
        capacity.plan_score(
            table_shapes, shard_users=shard_users, k=k,
            max_batch=shard_users, n_devices=n_devices,
        ),
        capacity.plan_score(
            table_shapes, shard_users=shard_users, k=k,
            max_batch=64, n_devices=n_devices, streamed=True,
        ),
    ]
    verdict = capacity.admit_ladder(plans)
    if verdict.verdict == "refuse":
        raise capacity.CapacityExceeded(verdict)
    return verdict


def _verify_completed(cursor_doc: dict, gen_dir: Path) -> tuple[dict, list[int]]:
    """Split a restored cursor's completed shards into (still-good, dropped):
    a spill whose file is missing or fails its recorded hash is dropped for
    re-scoring — resume trusts hashes, never mtimes or mere existence."""
    from albedo_tpu.datasets.artifacts import file_sha256

    good: dict = {}
    dropped: list[int] = []
    for key, rec in (cursor_doc.get("completed") or {}).items():
        path = gen_dir / rec["file"]
        try:
            ok = path.is_file() and file_sha256(path) == rec["sha256"]
        except OSError:
            ok = False
        if ok:
            good[key] = rec
        else:
            dropped.append(int(key))
    return good, sorted(dropped)


def check_score_invariants(out_root: Path) -> list[str]:
    """Post-run invariants for the chaos soak's scoring leg: the sealed
    manifest must exist, verify, cover exactly its generation's scored
    shards (contiguous user ranges, no gaps, no extras), and every listed
    spill must match its recorded hash."""
    from albedo_tpu.datasets.artifacts import file_sha256, verify_manifest

    out_root = Path(out_root)
    manifest_path = out_root / MANIFEST_NAME
    doc = read_json_or_none(manifest_path)
    if not isinstance(doc, dict):
        return [f"score: no sealed manifest at {manifest_path}"]
    violations = []
    if verify_manifest(manifest_path) is False:
        violations.append("score: sealed manifest fails its content manifest")
    gen_dir = _gen_dir(out_root, int(doc.get("generation", 0)))
    shards = doc.get("shards") or {}
    n_shards = int(doc.get("n_shards", len(shards)))
    if sorted(int(i) for i in shards) != list(range(n_shards)):
        violations.append(
            f"score: manifest covers shards {sorted(shards)} != 0..{n_shards - 1}"
        )
    expect_start = 0
    for i in range(n_shards):
        rec = shards.get(str(i))
        if rec is None:
            continue
        if int(rec["start"]) != expect_start:
            violations.append(
                f"score: shard {i} starts at {rec['start']}, expected {expect_start}"
            )
        expect_start = int(rec["stop"])
        path = gen_dir / rec["file"]
        try:
            ok = path.is_file() and file_sha256(path) == rec["sha256"]
        except OSError:
            ok = False
        if not ok:
            violations.append(f"score: spill {rec['file']} missing or hash mismatch")
    if n_shards and expect_start != int(doc.get("n_users", expect_start)):
        violations.append(
            f"score: shards cover {expect_start} users, manifest says "
            f"{doc.get('n_users')}"
        )
    return violations


def run_score_all(
    ctx,
    *,
    shard_users: int = 256,
    k: int = 30,
    max_users: int = 0,
    canary_floor: float = 0.0,
    canary_tolerance: float | None = None,
    publish_force: bool = False,
) -> dict:
    """Drive the full sweep: admit -> build bank -> elastic shard loop ->
    canary-gated publish. Returns the run report dict.

    Raises :class:`~albedo_tpu.utils.capacity.CapacityExceeded` (refused
    before any byte moved), :class:`~albedo_tpu.utils.checkpoint.Preempted`
    (cursor checkpointed, exit 75), :class:`~albedo_tpu.parallel.elastic.
    MeshLost` (loss budget spent, journal status ``mesh_lost``), and
    :class:`~albedo_tpu.builders.pipeline.PublishRejected` (canary gate,
    exit 4, prior sealed output untouched).
    """
    from albedo_tpu.builders.pipeline import CANARY_TOLERANCE, PublishRejected
    from albedo_tpu.datasets import artifacts as store
    from albedo_tpu.parallel.elastic import (
        MeshLost,
        collective_deadline_s,
        run_with_deadline,
    )
    from albedo_tpu.parallel.mesh import ITEM_AXIS, make_mesh, next_ladder_rung
    from albedo_tpu.retrieval.bank import RetrievalBank
    from albedo_tpu.settings import get_settings
    from albedo_tpu.utils.checkpoint import (
        JsonStepCheckpointer,
        Preempted,
        PreemptionHandler,
    )
    from albedo_tpu.utils.retry import is_collective_lost

    t0 = time.time()
    matrix = ctx.matrix()
    n_users = int(matrix.n_users)
    if max_users and max_users > 0:
        n_users = min(n_users, int(max_users))
    shard_users = max(1, int(shard_users))
    n_shards = -(-n_users // shard_users)

    # Mesh for the bank's row-sharded layout (the item axis carries the
    # tables; ``parallel/topk.py`` serves per-shard top-k).
    n_req = max(1, int(getattr(ctx.args, "mesh_devices", 0) or 0))
    # Always the SHARDED query path, even on one device: the fused and
    # sharded programs round/tie-break top-k boundaries differently, and
    # cross-mesh resume parity (kill at N devices, resume at N/2) demands
    # one layout-invariant scorer. Item-sharding never splits a single
    # item's dot-product reduction, so scores match bitwise across rungs.
    bank_mesh = make_mesh(n_req, data=1, item=n_req)
    n_dev = int(bank_mesh.shape[ITEM_AXIS])

    # --- admission: price the sweep before any byte moves -----------------
    specs = _bank_specs(ctx)
    table_shapes = [
        shape
        for s in specs
        for shape in (
            [s.vectors.shape]
            + ([s.user_vectors.shape] if s.user_vectors is not None else [])
        )
    ]
    verdict = _admit_score(table_shapes, shard_users, k, n_dev)
    # The chosen rung is REAL, not just priced: the streamed rung bounds the
    # bank's in-flight batch at its own max_batch.
    bank_batch = shard_users if verdict.chosen in ("score", "") else 64
    print(f"[score_all] admission: {verdict.verdict} -> "
          f"{verdict.chosen or verdict.workload} "
          f"({verdict.required_bytes:,} bytes / {verdict.budget_bytes:,} budget)")

    def build_bank(mesh):
        bank = RetrievalBank(max_batch=bank_batch)
        for spec in specs:
            bank.register(spec)
        bank.build(matrix=matrix, mesh=mesh)
        return bank

    bank = build_bank(bank_mesh)

    # --- cursor + staging --------------------------------------------------
    _, resume, keep_last = ctx.checkpoint_opts()
    ckdir = get_settings().checkpoint_dir / ctx.artifact_name(CURSOR_KEY)
    out_root = score_output_root(ctx.tag)
    out_root.mkdir(parents=True, exist_ok=True)
    sealed = read_json_or_none(out_root / MANIFEST_NAME)
    sealed_gen = int(sealed.get("generation", 0)) if isinstance(sealed, dict) else 0

    params = {
        "tag": ctx.tag, "shard_users": shard_users, "k": int(k),
        "n_users": n_users, "n_shards": n_shards,
    }
    cursor = JsonStepCheckpointer(ckdir, keep_last=keep_last)
    completed: dict = {}
    rescore: set[int] = set()
    generation = sealed_gen + 1
    if resume:
        restored = cursor.restore_latest()
        if restored is not None and restored[1].get("params") == params:
            doc = restored[1]
            generation = int(doc.get("generation", generation))
            # Restore the generation's featurization instant: the ranker
            # trains in-process from ``ctx.now``-dated features, so a resume
            # at a later wall clock would re-rank with a slightly different
            # LR than the shards already sealed. The cursor pins ``now`` at
            # generation start; shards scored before and after a kill share
            # one scoring function (the cross-mesh parity contract).
            pinned_now = doc.get("now")
            if pinned_now is not None and float(pinned_now) != float(ctx.now):
                ctx.now = float(pinned_now)
                for cache_key in ("profiles", "ranker", "ranker_auc"):
                    ctx._cache.pop(cache_key, None)
            completed, dropped = _verify_completed(doc, _gen_dir(out_root, generation))
            rescore = set(dropped)
            for _ in completed:
                events.score_shards.inc(outcome="skipped")
            print(f"[score_all] resume: {len(completed)}/{n_shards} shards "
                  f"sealed, {len(dropped)} dropped for re-score "
                  f"(generation {generation})")
        elif restored is not None:
            print("[score_all] resume: cursor params mismatch — starting a "
                  "fresh sweep generation")
    if not completed:
        # Fresh sweep (or nothing resumable): a stale cursor or unsealed
        # staging must not be silently adopted. The SEALED generation and
        # its manifest stay untouched.
        if not resume and ckdir.exists():
            shutil.rmtree(ckdir)
            cursor = JsonStepCheckpointer(ckdir, keep_last=keep_last)
        for p in out_root.glob("gen-*"):
            if p.is_dir() and p != _gen_dir(out_root, sealed_gen):
                shutil.rmtree(p, ignore_errors=True)
    gen_dir = _gen_dir(out_root, generation)
    _sweep_tmps(gen_dir)
    ranker = ctx.ranker_model()  # AFTER the cursor restore pins ctx.now

    deadline = collective_deadline_s()
    mesh_events = {
        "n_shards_start": n_dev, "losses": 0, "resumes": 0, "remeshes": [],
    }
    losses = 0
    resume_pending = False
    users_scored = 0
    cursor.write_journal("running", len(completed), n_shards,
                         extra={"generation": generation})

    def save_cursor() -> None:
        step = (cursor.latest_step() or 0) + 1
        cursor.save(step, {
            "format": "score-cursor-v1",
            "generation": generation,
            "params": params,
            "now": float(ctx.now),
            "completed": completed,
        })

    # --- the elastic shard loop -------------------------------------------
    with PreemptionHandler() as preemption:
        for shard_idx in range(n_shards):
            key = str(shard_idx)
            if key in completed:
                continue
            if preemption.should_stop():
                cursor.write_journal("preempted", len(completed), n_shards,
                                     extra={"generation": generation})
                raise Preempted(len(completed), ckdir)
            start = shard_idx * shard_users
            stop = min(start + shard_users, n_users)
            dense = np.arange(start, stop, dtype=np.int64)

            while True:
                def shard_work(dense=dense, shard_idx=shard_idx):
                    SHARD_FAULT.hit(path=f"shard_{shard_idx:05d}")
                    return _score_users(bank, ranker, matrix, dense, k)

                try:
                    frame = run_with_deadline(
                        shard_work, deadline, detail=f"score shard {shard_idx}"
                    )
                except Exception as e:  # noqa: BLE001 — classify, then decide
                    if not is_collective_lost(e):
                        raise
                    # A shard of the mesh is gone (or the injected drill
                    # says so): count it, spend the loss budget, remesh
                    # down the ladder, re-admit, re-lay the bank, retry.
                    events.mesh_losses.inc()
                    losses += 1
                    mesh_events["losses"] = losses
                    step = len(completed)
                    if losses > _MAX_LOSSES:
                        cursor.write_journal(
                            "mesh_lost", step, n_shards,
                            extra={"generation": generation, "cause": repr(e)},
                        )
                        events.elastic_resumes.inc(outcome="failed")
                        raise MeshLost(step, e, ckdir) from e
                    rung = next_ladder_rung(n_dev)
                    if rung is None:
                        cursor.write_journal(
                            "mesh_lost", step, n_shards,
                            extra={"generation": generation, "cause": repr(e)},
                        )
                        events.elastic_resumes.inc(outcome="failed")
                        raise MeshLost(step, e, ckdir) from e
                    try:
                        _admit_score(table_shapes, shard_users, k, rung)
                        new_mesh = make_mesh(rung, data=1, item=rung)
                        bank = build_bank(new_mesh)
                    except Exception as rebuild_err:  # noqa: BLE001
                        cursor.write_journal(
                            "mesh_lost", step, n_shards,
                            extra={"generation": generation,
                                   "cause": repr(rebuild_err)},
                        )
                        events.elastic_resumes.inc(outcome="failed")
                        raise MeshLost(step, rebuild_err, ckdir) from rebuild_err
                    mesh_events["remeshes"].append({"from": n_dev, "to": rung})
                    n_dev = rung
                    resume_pending = True
                    print(f"[score_all] shard loss at shard {shard_idx}: "
                          f"remeshed to {rung} device(s), resuming")
                    continue
                break

            record = _spill_shard(gen_dir, shard_idx, frame, start, stop)
            completed[key] = record
            save_cursor()
            outcome = "rescored" if shard_idx in rescore else "scored"
            events.score_shards.inc(outcome=outcome)
            events.score_users.inc(stop - start)
            users_scored += stop - start
            if resume_pending:
                events.elastic_resumes.inc(outcome="resumed")
                mesh_events["resumes"] += 1
                resume_pending = False
            cursor.write_journal("running", len(completed), n_shards,
                                 extra={"generation": generation})

    # --- canary-gated publish ---------------------------------------------
    probe_dense = ctx.test_user_dense(150)
    probe_dense = probe_dense[probe_dense < n_users]
    probe = _score_users(bank, ranker, matrix, probe_dense.astype(np.int64), k)
    score = float(ctx.evaluate_topk(probe)) if len(probe) else 0.0
    PUBLISH_FAULT.hit()

    tolerance = CANARY_TOLERANCE if canary_tolerance is None else float(canary_tolerance)
    prior_meta = store.read_meta(out_root / MANIFEST_NAME)
    baseline = None
    if isinstance(prior_meta, dict):
        baseline = (prior_meta.get("canary") or {}).get("score")
    failures = []
    if score < float(canary_floor or 0.0):
        failures.append(
            f"score {score:.5f} below --canary-floor {float(canary_floor):.5f}"
        )
    if baseline is not None and score < float(baseline) * (1.0 - tolerance):
        failures.append(
            f"score {score:.5f} regressed more than {tolerance:.0%} below "
            f"the prior sealed output's {float(baseline):.5f}"
        )
    canary = {
        "metric": "ndcg@30",
        "score": round(score, 6),
        "baseline": None if baseline is None else round(float(baseline), 6),
        "passed": not failures,
        "forced": bool(publish_force and failures),
    }
    if failures:
        if not publish_force:
            # Counted only on an actual refusal; the PRIOR sealed manifest
            # (and its generation dir) is untouched — the new generation's
            # spills stay unsealed staging a rerun may reuse or wipe.
            events.score_publish_rejected.inc(gate="canary")
            cursor.write_journal("complete", len(completed), n_shards,
                                 extra={"generation": generation,
                                        "publish": "rejected"})
            raise PublishRejected("; ".join(failures), score=score,
                                  baseline=baseline)
        print(f"[score_all] !!! CANARY GATE OVERRIDDEN (--publish-force): "
              f"{'; '.join(failures)} — sealing anyway")

    manifest = {
        "format": "score-all-v1",
        "generation": generation,
        "n_users": n_users,
        "n_shards": n_shards,
        "shards": completed,
        **params,
        "rows": int(sum(r["rows"] for r in completed.values())),
        "created_at": time.time(),
    }
    manifest_path = out_root / MANIFEST_NAME
    atomic_write_json(manifest_path, manifest, indent=2)
    store.write_manifest(manifest_path)
    store.write_meta(manifest_path, {
        "canary": canary,
        "params": params,
        "lineage": {
            "als_artifact": ctx.als_artifact_name(),
            "bank_version": bank.version,
            "tag": ctx.tag,
        },
    })
    # The seal supersedes every older generation: prune their staging dirs.
    for p in out_root.glob("gen-*"):
        if p.is_dir() and p != gen_dir:
            shutil.rmtree(p, ignore_errors=True)
    cursor.write_journal("complete", n_shards, n_shards,
                         extra={"generation": generation})
    return {
        "generation": generation,
        "n_users": n_users,
        "n_shards": n_shards,
        "users_scored": users_scored,
        "rows": manifest["rows"],
        "canary": canary,
        "admission": verdict.to_dict(),
        "mesh_events": mesh_events,
        "wall_s": time.time() - t0,
    }
