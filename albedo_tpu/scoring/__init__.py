"""Full-catalog batch scoring: the preemptible, elastic ``score_all`` job.

The reference system's real production workload is offline: the LR ranker
precomputes ranked repos for EVERY user, nightly. This package is that
workload rebuilt as a first-class citizen of the ops machinery — a sweep
over every user shard through the retrieval bank's blocked MIPS plus the
blocked LR re-rank, spilling stamped per-shard top-k parquet, with:

- a **checkpointed sweep cursor** (``utils.checkpoint.JsonStepCheckpointer``)
  so a preempted or killed sweep resumes at the shard boundary;
- **elastic operation** (``parallel/elastic.py`` semantics): collective
  deadline, loss classifier, remesh down the ladder, re-admit, resume;
- a **capacity-admitted** dispatch (``utils.capacity.plan_score`` through
  ``admit_ladder``): resident -> streamed rungs, refusal before any byte
  moves;
- a **canary-gated publish**: probe-slice NDCG@30 against the prior sealed
  output's ``.meta.json`` stamp before the manifest seals (exit 4 on
  refusal, prior sealed output untouched).

See ARCHITECTURE.md "Batch scoring" and the README runbook.
"""

from albedo_tpu.scoring.sweep import (
    MANIFEST_NAME,
    check_score_invariants,
    run_score_all,
    score_output_root,
)

__all__ = [
    "MANIFEST_NAME",
    "check_score_invariants",
    "run_score_all",
    "score_output_root",
]
