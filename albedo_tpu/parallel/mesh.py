"""Device-mesh construction and sharding helpers.

The reference's distributed runtime is Spark (driver + executors, shuffle,
broadcast), configured externally via ``spark-submit`` flags (``Makefile:96-107``)
— albedo itself contains no communication code. The TPU-native replacement is a
``jax.sharding.Mesh`` over the chip slice with named axes:

- ``"data"`` — batch/row parallelism: bucket rows of the ALS normal-equation
  solves, user rows of retrieval, example rows of LR gradient batches. The
  analogue of Spark data-parallel executors.
- ``"item"`` — item-axis (model) parallelism: item-factor shards for retrieval
  scoring and Gramian accumulation (SURVEY.md section 2.5: "sharding the item
  dimension of the Gramian/score matrix across chips").

Collectives ride ICI within a slice (psum for Gramians/gradients, all_gather
for top-k candidate merges), replacing Spark shuffle/broadcast/collect.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
ITEM_AXIS = "item"


def make_mesh(
    n_devices: int | None = None,
    data: int | None = None,
    item: int = 1,
    devices: list | None = None,
) -> Mesh:
    """Build a ``(data, item)`` mesh over the first ``n_devices`` devices.

    By default all devices go on the ``data`` axis — the right layout while
    factor tables fit replicated (rank-50 factors for albedo-scale data are
    ~hundreds of MB). Give ``item > 1`` to shard the item axis as well.
    """
    devs = devices if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    if data is None:
        if n % item != 0:
            raise ValueError(f"{n} devices not divisible by item={item}")
        data = n // item
    if data * item != n:
        raise ValueError(f"mesh {data}x{item} != {n} devices")
    grid = np.asarray(devs).reshape(data, item)
    return Mesh(grid, axis_names=(DATA_AXIS, ITEM_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def row_sharded(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard axis 0 across ``axis``, replicate the rest."""
    return NamedSharding(mesh, P(axis))


def device_put_sharded_rows(x, mesh: Mesh, axis: str = DATA_AXIS):
    return jax.device_put(x, row_sharded(mesh, axis))


def pad_rows_to(x: np.ndarray, multiple: int, fill=0) -> np.ndarray:
    """Pad axis 0 up to a multiple (for even sharding); fill with ``fill``."""
    n = x.shape[0]
    target = -(-n // multiple) * multiple
    if target == n:
        return x
    pad = np.full((target - n, *x.shape[1:]), fill, dtype=x.dtype)
    return np.concatenate([x, pad], axis=0)
