"""Device-mesh construction and sharding helpers.

The reference's distributed runtime is Spark (driver + executors, shuffle,
broadcast), configured externally via ``spark-submit`` flags (``Makefile:96-107``)
— albedo itself contains no communication code. The TPU-native replacement is a
``jax.sharding.Mesh`` over the chip slice with named axes:

- ``"data"`` — batch/row parallelism: bucket rows of the ALS normal-equation
  solves, user rows of retrieval, example rows of LR gradient batches. The
  analogue of Spark data-parallel executors.
- ``"item"`` — item-axis (model) parallelism: item-factor shards for retrieval
  scoring and Gramian accumulation (SURVEY.md section 2.5: "sharding the item
  dimension of the Gramian/score matrix across chips").

Collectives ride ICI within a slice (psum for Gramians/gradients, all_gather
for top-k candidate merges), replacing Spark shuffle/broadcast/collect.
Multi-HOST scaling (several processes, each owning a slice, DCN between them)
goes through ``init_distributed`` + the same global mesh: jax's runtime routes
intra-slice collectives over ICI and inter-slice segments over DCN, so the
sharding code above this module is host-count-agnostic.
"""

from __future__ import annotations

import logging
import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from albedo_tpu.utils import events, faults

log = logging.getLogger(__name__)

DATA_AXIS = "data"
ITEM_AXIS = "item"

# Chaos hook: fires at every mesh construction. A fired fault (any raising
# kind — error/oom) simulates half the slice dropping out: `make_mesh` sees
# fewer devices than exist and must remesh down the ladder instead of
# crashing (the degraded-mesh drill arms this).
MESH_FAULT = faults.site("mesh.devices")


_PROCESS_ID_HINT_ENVS = (
    # Envs jax's cluster auto-detection actually keys off (Slurm, Open MPI,
    # TPU pod metadata) — not every rank-ish variable a launcher might set.
    "SLURM_PROCID", "OMPI_COMM_WORLD_RANK",
    "TPU_WORKER_ID", "CLOUD_TPU_TASK_ID",
)


def _has_process_id_hint() -> bool:
    return any(os.environ.get(e) is not None for e in _PROCESS_ID_HINT_ENVS)


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> int:
    """Join the multi-host world (the NCCL/MPI-backend analogue, SURVEY.md
    section 2.5 'communication backend').

    Single-process runs are a no-op returning 1. Multi-host runs call
    ``jax.distributed.initialize`` — args come from the parameters or the
    standard env (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/
    ``JAX_PROCESS_ID``, as a Dataproc-style launcher would set, mirroring how
    the reference's parallelism is configured by ``spark-submit`` flags rather
    than in code). After this, ``jax.devices()`` spans every host and
    ``make_mesh`` builds the global mesh.
    """
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        env = os.environ.get("JAX_NUM_PROCESSES")
        num_processes = int(env) if env else None
    if process_id is None:
        env = os.environ.get("JAX_PROCESS_ID")
        process_id = int(env) if env else None

    if num_processes is None:
        if coordinator_address:
            # A coordinator with no world size is a misconfigured launcher,
            # not a single-host run.
            raise ValueError(
                "coordinator address set but no process count "
                "(set JAX_NUM_PROCESSES or pass num_processes)"
            )
        return 1
    if num_processes <= 1:
        return 1
    # An explicitly multi-process config with missing pieces must FAIL, not
    # silently run this worker as an independent single-host job while the
    # rest of the world hangs at the barrier.
    if not coordinator_address:
        raise ValueError(
            f"num_processes={num_processes} but no coordinator address "
            "(set JAX_COORDINATOR_ADDRESS or pass coordinator_address)"
        )
    if process_id is None and not _has_process_id_hint():
        # jax.distributed.initialize can auto-detect the process id from
        # cluster envs (Slurm, Open MPI, TPU pod metadata); only refuse when
        # neither an explicit id nor any auto-detect hint exists — otherwise
        # the failure surfaces as an opaque deep-in-JAX RuntimeError.
        raise ValueError(
            f"num_processes={num_processes} but no process id "
            "(set JAX_PROCESS_ID / pass process_id, or run under a launcher "
            "JAX can auto-detect: Slurm, Open MPI, TPU pod)"
        )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return num_processes


def degraded_ladder(requested: int, available: int, item: int = 1) -> int:
    """The largest usable device count when fewer devices are visible than
    requested: halve down the 8 -> 4 -> 2 -> 1 ladder until the rung fits
    ``available`` and (when possible) stays divisible by ``item``. Never
    returns less than 1 — a single device is always a valid (degraded)
    mesh."""
    n = max(1, int(requested))
    while n > available and n > 1:
        n //= 2
    if item > 1:
        m = n
        while m > 1 and m % item:
            m //= 2
        if m % item == 0:
            n = m
    return max(1, n)


def next_ladder_rung(n: int) -> int | None:
    """The rung BELOW ``n`` on the degradation ladder (8 -> 4 -> 2 -> 1), or
    ``None`` when there is nowhere left to go. The elastic remesh-resume
    path (``parallel/elastic.py``) steps down one rung per detected shard
    loss — halving matches :func:`degraded_ladder`'s boot-time semantics,
    and a lost shard's row range is always covered by the surviving half
    because factor tables re-shard from the mesh-portable checkpoint, not
    from surviving device state."""
    n = int(n)
    return n // 2 if n > 1 else None


def make_mesh(
    n_devices: int | None = None,
    data: int | None = None,
    item: int = 1,
    devices: list | None = None,
    allow_degraded: bool = True,
) -> Mesh:
    """Build a ``(data, item)`` mesh over the first ``n_devices`` devices.

    By default all devices go on the ``data`` axis — the right layout while
    factor tables fit replicated (rank-50 factors for albedo-scale data are
    ~hundreds of MB). Give ``item > 1`` to shard the item axis as well.

    **Degraded operation** (``allow_degraded``, default on): when fewer
    devices are visible than requested — a partial slice at startup, or the
    ``mesh.devices`` fault site simulating half the slice dropping out —
    the mesh remeshes to the largest valid ladder rung (8 -> 4 -> 2 -> 1,
    item axis collapsing to 1 if it no longer divides) instead of raising.
    Loud by design: a warning names both counts, and the boot is counted in
    ``albedo_mesh_degraded_total`` so dashboards can page on a fleet booting
    smaller than its slice. An *explicitly inconsistent* shape request
    (``data * item != n_devices`` with every device present) is still a
    configuration error, not a degradation.
    """
    all_devs = devices if devices is not None else jax.devices()
    visible = len(all_devs)
    try:
        MESH_FAULT.hit()
    except Exception as e:  # noqa: BLE001 — any raising kind = device loss
        visible = max(1, visible // 2)
        log.warning("mesh.devices fault fired (%r): %d of %d devices visible",
                    e, visible, len(all_devs))
    requested = int(n_devices) if n_devices is not None else (
        data * item if data is not None else visible
    )
    n = requested
    degraded_item = item
    if requested > visible:
        if not allow_degraded:
            raise ValueError(
                f"need {requested} devices, have {visible} "
                "(degraded remesh disabled)"
            )
        n = degraded_ladder(requested, visible, item=item)
        if item > 1 and n % item:
            degraded_item = 1
        log.warning(
            "DEGRADED MESH: %d device(s) requested, %d visible — remeshed to "
            "%d (item axis %d -> %d). Throughput is proportionally reduced; "
            "results are unchanged.",
            requested, visible, n, item, degraded_item,
        )
        events.mesh_degraded.inc()
        # The requested shape no longer applies; re-derive it below.
        data = None
    devs = all_devs[:n]
    item = degraded_item
    if data is None:
        if n % item != 0:
            raise ValueError(f"{n} devices not divisible by item={item}")
        data = n // item
    if data * item != n:
        raise ValueError(f"mesh {data}x{item} != {n} devices")
    grid = np.asarray(devs).reshape(data, item)
    return Mesh(grid, axis_names=(DATA_AXIS, ITEM_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def row_sharded(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard axis 0 across ``axis``, replicate the rest."""
    return NamedSharding(mesh, P(axis))


def device_put_sharded_rows(x, mesh: Mesh, axis: str = DATA_AXIS):
    return jax.device_put(x, row_sharded(mesh, axis))


def pad_rows_to(x: np.ndarray, multiple: int, fill=0) -> np.ndarray:
    """Pad axis 0 up to a multiple (for even sharding); fill with ``fill``."""
    n = x.shape[0]
    target = -(-n // multiple) * multiple
    if target == n:
        return x
    pad = np.full((target - n, *x.shape[1:]), fill, dtype=x.dtype)
    return np.concatenate([x, pad], axis=0)
