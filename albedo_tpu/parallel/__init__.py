"""Device-mesh parallelism: the TPU-native replacement for the reference's
Spark driver/executor runtime (SURVEY.md sections 2.5, 7).

- ``mesh`` — named-axis mesh construction (``data`` x ``item``) and sharding
  helpers.
- ``als`` — shard_map'd data-parallel ALS bucket solves + psum Gramian for
  sharded factor storage.
- ``topk`` — item-axis-sharded retrieval with k-per-device candidate merge.
- ``lr`` — row-sharded feature batches for data-parallel LR training (psum
  gradient reductions = MLlib's treeAggregate).
- ``elastic`` — the elastic loop around the sharded fit: mesh-portable
  sweep-boundary checkpoints, mid-fit device-loss detection, remesh-resume
  down the degraded ladder (ARCHITECTURE.md "Elastic operation").
"""

from albedo_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    ITEM_AXIS,
    init_distributed,
    make_mesh,
    pad_rows_to,
    replicated,
    row_sharded,
)
from albedo_tpu.parallel.als import (  # noqa: F401
    ShardedALSFit,
    ShardedALSSweep,
    make_sharded_solver,
    make_sharded_update,
    pad_bucket,
    sharded_fit_engine,
    sharded_gramian,
)
from albedo_tpu.parallel.topk import (  # noqa: F401
    make_sharded_topk,
    sharded_topk_scores,
)
from albedo_tpu.parallel.lr import shard_feature_batch  # noqa: F401
from albedo_tpu.parallel.elastic import (  # noqa: F401
    CollectiveTimeout,
    MeshLost,
    elastic_sharded_fit,
)
