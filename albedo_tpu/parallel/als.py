"""Sharded implicit-ALS sweeps over a device mesh.

The reference's ALS scales by Spark MLlib's shuffled in/out factor blocks with
per-block LAPACK solves on executors (``ALSRecommenderBuilder.scala:46-58``
just calls ``als.fit``; the block machinery is inside MLlib). TPU-native
replacement, two composable pieces:

1. **Data-parallel bucket solves** (`make_sharded_solver`): each padded bucket's
   batch dimension is sharded over the mesh's ``data`` axis with ``shard_map``
   — every device runs the same fixed-shape gather → Gramian-correction einsum
   → batched-Cholesky pipeline on its slice of the rows, the direct analogue of
   MLlib's per-executor block solves but with no shuffle: the solved rows are
   re-assembled by XLA (an all-gather over ICI) and scattered into the factor
   table.

2. **psum Gramian** (`sharded_gramian`): when a factor table is stored sharded
   over devices (rows split on ``data``), the shared ``YtY`` term of every
   implicit solve is the sum of per-shard partial Gramians — one ``(k, k)``
   ``psum`` over ICI, the pattern SURVEY.md section 7 step 3 prescribes (ALX).

Factor tables are replicated by default: at albedo scale (≤ millions of rows ×
rank 50, float32) a full table is ≤ a few hundred MB — far below HBM — and
replication makes the per-bucket arbitrary-index gather local.

3. **The fully sharded fit** (`ShardedALSFit`, ALX arXiv:2112.02194) for
   larger-than-HBM factor tables: BOTH tables row-sharded over ``data``,
   per-device bucket blocks solved against all-gathered or ring-passed
   source shards inside shard_map, solved rows landed shard-locally from a
   small all-gathered block, and (optionally) interaction buckets STREAMED
   from the host per half-sweep so the star matrix is never device-resident
   whole. ``models.als.ImplicitALS`` dispatches here when the capacity
   admission ladder says the replicated layout no longer fits
   (ARCHITECTURE.md "Sharded ALS").

The sharded dataflow is PIPELINED end to end by default (ARCHITECTURE.md
"Pipelined sharded dataflow"; ``ALBEDO_PIPELINE=off`` reverts every stage):
a background prefetcher (`_BucketPrefetcher`) uploads bucket i+1 while
bucket i's solve is dispatched (double-buffered — the mesh never waits on a
cold upload after the first bucket), ring phases issue phase p+1's
``ppermute`` ahead of phase p's Gramian-correction compute, and each
bucket's landing scatter is fused into the NEXT bucket's solve dispatch
(`make_pipelined_landsolve` + a final `make_landing_flush`). Same math,
parity-pinned at 1e-5 against the synchronous path.
"""

from __future__ import annotations

import functools
import queue
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
try:  # jax >= 0.6 exports shard_map at top level
    from jax import shard_map
except ImportError:  # 0.4.x spelling
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from albedo_tpu.datasets.ragged import Bucket, device_bucket
from albedo_tpu.ops.als import (
    bucket_cg_body,
    bucket_partial_terms,
    bucket_solve_body,
    scatter_solved,
    solve_corrected,
)
from albedo_tpu.parallel.mesh import DATA_AXIS, pad_rows_to, row_sharded
from albedo_tpu.utils import faults
from albedo_tpu.utils.dataflow import pipeline_enabled

# Chaos hooks for the fully sharded fit: `als.shard.gather` fires once per
# half-sweep ahead of the source-shard assembly (the all-gather / ring pass),
# `als.shard.stream` fires before every streamed bucket upload — so drills
# can fail or kill a sharded fit mid-collective or mid-stream, exactly like
# `als.chunked` does for the single-device degraded path. `als.shard.
# collective` is the ELASTIC surface: it fires at the head of every
# half-sweep's collective phase (the psum Gramian + the per-bucket
# all-gather/ring passes follow it), and its `loss` kind raises the
# device-loss-shaped error a dead shard surfaces as — the elastic driver
# (`parallel/elastic.py`) classifies it and runs the real checkpoint ->
# remesh -> resume machinery instead of crashing the fit.
SHARD_GATHER_FAULT = faults.site("als.shard.gather")
SHARD_STREAM_FAULT = faults.site("als.shard.stream")
SHARD_COLLECTIVE_FAULT = faults.site("als.shard.collective")
# `als.shard.prefetch` fires INSIDE the background prefetch uploader of a
# pipelined streamed fit, before each bucket's device_put — so drills can
# fail, wedge (delay), or kill the prefetch thread specifically. An error
# there is delivered to the consuming sweep and surfaces as a clean failed
# fit; a wedge is bounded by the collective deadline (`PrefetchStalled`),
# never a hang. The site never fires with ALBEDO_PIPELINE=off, on the
# resident sharded path, or on the synchronous streamed path.
SHARD_PREFETCH_FAULT = faults.site("als.shard.prefetch")


class PrefetchStalled(RuntimeError):
    """The pipelined sweep waited longer than the collective deadline for
    the background prefetch uploader to deliver the next bucket — the
    signature of a wedged prefetch thread (stuck disk read, stuck
    device_put). Deliberately NOT shaped like a device loss: remeshing
    cannot revive a host-side reader, so the elastic driver propagates this
    as a plain clean failure instead of burning its loss budget on it."""

    def __init__(self, deadline_s: float):
        super().__init__(
            f"sharded bucket prefetch exceeded the {deadline_s:g}s "
            f"collective deadline waiting for the background uploader"
        )
        self.deadline_s = float(deadline_s)


def pad_bucket(b: Bucket, multiple: int) -> Bucket:
    """Pad a bucket's batch dim to a device-count multiple (padding slots have
    ``row_ids == -1`` and zero weight, so they solve garbage that is dropped on
    scatter)."""
    if b.row_ids.shape[0] % multiple == 0:
        return b
    return Bucket(
        row_ids=pad_rows_to(b.row_ids, multiple, fill=-1),
        idx=pad_rows_to(b.idx, multiple),
        val=pad_rows_to(b.val, multiple),
        mask=pad_rows_to(b.mask, multiple),
    )


def sharded_gramian(mesh: Mesh, axis: str = DATA_AXIS):
    """``F^T F`` for a row-sharded factor table: local partial Gramian + psum."""

    # One (k, k) psum program per mesh, compiled once and memoized via
    # sharded_fit_engine — no per-shape ladder, no cross-process cold cost
    # worth an export; the bucket solves themselves go through utils/aot.
    # albedo: noqa[bare-jit]
    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=P(axis, None),
        out_specs=P(),
    )
    def gramian(local_factors: jax.Array) -> jax.Array:
        return jax.lax.psum(local_factors.T @ local_factors, axis)

    return gramian


def make_sharded_solver(mesh: Mesh, axis: str = DATA_AXIS):
    """Build the jitted sharded bucket solver for this mesh.

    The returned function has the same signature/semantics as
    ``ops.als.solve_bucket`` but runs the per-row solves data-parallel across
    ``axis``. Bucket batch dims must be divisible by the axis size
    (see ``pad_bucket``).
    """
    n_shards = mesh.shape[axis]

    local_solve = shard_map(
        _local_bucket_solve,
        mesh=mesh,
        in_specs=(P(), P(), P(axis), P(axis, None), P(axis, None), P(axis, None), P(), P()),
        out_specs=P(axis),
    )

    # Explicit-collectives REFERENCE implementation (ShardedALSSweep):
    # parity tests pin the fused path against it; it never runs in a fit job.
    # albedo: noqa[bare-jit]
    @functools.partial(jax.jit, donate_argnames=("target",))
    def solve_bucket_sharded(source, yty, target, row_ids, idx, val, mask, reg, alpha):
        if row_ids.shape[0] % n_shards:
            raise ValueError(
                f"bucket batch {row_ids.shape[0]} not divisible by {n_shards} shards"
            )
        solved = local_solve(source, yty, row_ids, idx, val, mask, reg, alpha)
        # Scatter back into the (replicated) target; XLA inserts the all-gather
        # of the row-sharded `solved` over ICI.
        return scatter_solved(target, row_ids, solved)

    return solve_bucket_sharded


def _local_bucket_solve(source, yty, row_ids, idx, val, mask, reg, alpha):
    """Per-device slice of a bucket solve; math shared with the single-device
    path via ``ops.als.bucket_solve_body``."""
    del row_ids  # only needed for the scatter, outside the shard
    return bucket_solve_body(source, yty, idx, val, mask, reg, alpha)


# --- fully sharded fit (ALX layout) -------------------------------------------
#
# Both factor tables stored ROW-SHARDED over the mesh's data axis (1/n of each
# table resident per device), bucket batch dims sharded the same way, and the
# fixed side's factors assembled per bucket inside shard_map:
#
# ``mode="allgather"``  one tiled all-gather materializes the full (padded)
#                       source table transiently per bucket — minimal FLOPs,
#                       transient HBM = one full table.
# ``mode="ring"``       the source shard rotates around the ring (ppermute);
#                       each of the n phases accumulates the Gramian
#                       correction and b-vector for the entries whose rows
#                       live on the visiting shard (``ops.als.
#                       bucket_partial_terms``) — n x the gather/einsum work,
#                       but NO array larger than a 1/n table shard ever
#                       materializes. Cholesky only: the CG matvec would need
#                       the gathered rows at every step.
#
# Solved rows land by all-gathering the (small) solved block + row ids and
# letting every device scatter the rows it owns into its target shard —
# row-sharded in, row-sharded out, no host round trip.


def _assembled_solve(
    source_l, yty, target_l, row_ids_l, idx_l, val_l, mask_l, reg, alpha,
    *, axis, solver, cg_steps, gather_dtype,
):
    """Per-device bucket solve against the all-gathered source table."""
    source = jax.lax.all_gather(source_l, axis, axis=0, tiled=True)
    if solver == "cg":
        # Warm starts read the PRE-SWEEP target rows, which live on whatever
        # shard owns them — assemble the target too (priced by the cost
        # model as the CG mode's extra transient).
        target = jax.lax.all_gather(target_l, axis, axis=0, tiled=True)
        x0 = target[jnp.where(row_ids_l < 0, 0, row_ids_l)]
        return bucket_cg_body(
            source, yty, idx_l, val_l, mask_l, x0, reg, alpha, cg_steps,
            gather_dtype=gather_dtype,
        )
    return bucket_solve_body(
        source, yty, idx_l, val_l, mask_l, reg, alpha, gather_dtype=gather_dtype
    )


def _ring_solve(
    source_l, yty, idx_l, val_l, mask_l, reg, alpha,
    *, axis, n_shards, gather_dtype, overlapped=False,
):
    """Per-device bucket solve with the source shard ring-passed: phase p
    holds the shard born on device ``(self - p) mod n`` and accumulates the
    normal-equation terms for entries whose global index falls in that
    shard's row range; after n phases every entry has been seen exactly
    once, so the accumulated terms equal the full-gather terms.

    ``overlapped`` software-pipelines the loop body: phase p+1's
    ``ppermute`` is ISSUED before phase p's gather/einsum compute, so the
    shard transfer rides the ICI while the MXU chews the current phase —
    same dataflow graph, same math (the permute reads the same ``src`` the
    compute does), only the issue order changes so the async-collective
    scheduler can hide the hop latency. The synchronous order (compute,
    then permute) is kept for ``ALBEDO_PIPELINE=off`` A/B."""
    rows_per = source_l.shape[0]
    k = source_l.shape[1]
    shard = jax.lax.axis_index(axis)
    src0 = (
        source_l if gather_dtype is None
        else source_l.astype(jnp.dtype(gather_dtype))
    )
    c1_full = alpha * val_l                      # (B_l, L); 0 on padding
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    b_l = idx_l.shape[0]
    corr0 = jnp.zeros((b_l, k, k), jnp.float32)
    bvec0 = jnp.zeros((b_l, k), jnp.float32)

    def phase(p, carry):
        src, corr, b_vec = carry
        if overlapped:
            # Phase p+1's shard starts moving before phase p's compute.
            src_next = jax.lax.ppermute(src, axis, perm)
        owner = jax.lax.rem(shard - p + n_shards, n_shards)
        lo = owner * rows_per
        rel = idx_l - lo
        valid = mask_l & (rel >= 0) & (rel < rows_per)
        g = jnp.where(
            valid[..., None],
            src[jnp.clip(rel, 0, rows_per - 1)],
            jnp.zeros((), src.dtype),
        )
        c1 = jnp.where(valid, c1_full, 0.0)
        w = jnp.where(valid, 1.0 + c1_full, 0.0)
        dc, db = bucket_partial_terms(g, c1, w)
        if not overlapped:
            src_next = jax.lax.ppermute(src, axis, perm)
        return src_next, corr + dc, b_vec + db

    _, corr, b_vec = jax.lax.fori_loop(
        0, n_shards, phase, (src0, corr0, bvec0)
    )
    n_b = mask_l.sum(axis=1).astype(jnp.float32)
    return solve_corrected(yty, corr, b_vec, n_b, reg)


def _landing_scatter(target_l, rows_g, solved_g, axis):
    """Owner-shard scatter of an all-gathered solved block: each device
    keeps the rows its target shard owns; padding slots (``row_ids == -1``)
    and foreign rows scatter out of range and drop."""
    shard = jax.lax.axis_index(axis)
    rows_per = target_l.shape[0]
    local = rows_g - shard * rows_per
    local = jnp.where(
        (rows_g >= 0) & (local >= 0) & (local < rows_per), local, rows_per
    )
    return target_l.at[local].set(solved_g, mode="drop")


def _solve_any(
    source_l, yty, target_l, row_ids_l, idx_l, val_l, mask_l, reg, alpha,
    *, axis, n_shards, mode, solver, cg_steps, gather_dtype, overlapped,
):
    if mode == "ring":
        return _ring_solve(
            source_l, yty, idx_l, val_l, mask_l, reg, alpha,
            axis=axis, n_shards=n_shards, gather_dtype=gather_dtype,
            overlapped=overlapped,
        )
    return _assembled_solve(
        source_l, yty, target_l, row_ids_l, idx_l, val_l, mask_l, reg,
        alpha, axis=axis, solver=solver, cg_steps=cg_steps,
        gather_dtype=gather_dtype,
    )


def _sharded_update_body(
    source_l, yty, target_l, row_ids_l, idx_l, val_l, mask_l, reg, alpha,
    *, axis, n_shards, mode, solver, cg_steps, gather_dtype,
):
    solved_l = _solve_any(
        source_l, yty, target_l, row_ids_l, idx_l, val_l, mask_l, reg,
        alpha, axis=axis, n_shards=n_shards, mode=mode, solver=solver,
        cg_steps=cg_steps, gather_dtype=gather_dtype, overlapped=False,
    )
    # Land: the solved block is small (B x k), so all-gather it with its row
    # ids and let each device keep the rows its target shard owns.
    rows_g = jax.lax.all_gather(row_ids_l, axis, axis=0, tiled=True)
    solved_g = jax.lax.all_gather(solved_l, axis, axis=0, tiled=True)
    return _landing_scatter(target_l, rows_g, solved_g, axis)


# --- pipelined dataflow program bodies ----------------------------------------
#
# The pipelined half-sweep splits each bucket's work so every cross-device
# transfer is issued AHEAD of compute it can hide behind (ARCHITECTURE.md
# "Pipelined sharded dataflow"):
#
#   solve      the first bucket: solve only, no landing yet (there is no
#              previous block to land). Ring phases run overlapped.
#   landsolve  every later bucket: the PREVIOUS bucket's solved-block
#              all-gather is issued first, this bucket's solve computes
#              while that (small) block is in flight, then the previous
#              block scatters into the target shard — the landing stops
#              being a separate synchronous tail on every bucket.
#   flush      after the last bucket: land the final pending block.
#
# Parity is exact by construction: each target row appears in exactly ONE
# bucket per half-sweep, so deferring bucket i's landing until bucket i+1's
# dispatch changes no value any solve reads — the CG warm start reads only
# its own bucket's rows (never landed earlier in the sweep), and padding
# rows solve garbage that drops on scatter either way.


def _pipelined_solve_body(
    source_l, yty, target_l, row_ids_l, idx_l, val_l, mask_l, reg, alpha,
    *, axis, n_shards, mode, solver, cg_steps, gather_dtype,
):
    return _solve_any(
        source_l, yty, target_l, row_ids_l, idx_l, val_l, mask_l, reg,
        alpha, axis=axis, n_shards=n_shards, mode=mode, solver=solver,
        cg_steps=cg_steps, gather_dtype=gather_dtype, overlapped=True,
    )


def _pipelined_landsolve_body(
    source_l, yty, target_l, prev_rows_l, prev_solved_l,
    row_ids_l, idx_l, val_l, mask_l, reg, alpha,
    *, axis, n_shards, mode, solver, cg_steps, gather_dtype,
):
    # Previous bucket's landing all-gathers issued FIRST: the (B_prev, k)
    # block transfer overlaps this bucket's gather/einsum/solve compute.
    prev_rows_g = jax.lax.all_gather(prev_rows_l, axis, axis=0, tiled=True)
    prev_solved_g = jax.lax.all_gather(prev_solved_l, axis, axis=0, tiled=True)
    solved_l = _solve_any(
        source_l, yty, target_l, row_ids_l, idx_l, val_l, mask_l, reg,
        alpha, axis=axis, n_shards=n_shards, mode=mode, solver=solver,
        cg_steps=cg_steps, gather_dtype=gather_dtype, overlapped=True,
    )
    target_l = _landing_scatter(target_l, prev_rows_g, prev_solved_g, axis)
    return target_l, solved_l


def _landing_flush_body(target_l, rows_l, solved_l, *, axis):
    rows_g = jax.lax.all_gather(rows_l, axis, axis=0, tiled=True)
    solved_g = jax.lax.all_gather(solved_l, axis, axis=0, tiled=True)
    return _landing_scatter(target_l, rows_g, solved_g, axis)


def make_sharded_update(mesh: Mesh, axis: str = DATA_AXIS, mode: str = "allgather"):
    """Jitted sharded bucket update: row-sharded source/target tables in,
    row-sharded target out. Bucket batch dims and both tables' row counts
    must be device-count multiples (``pad_bucket`` / ``pad_rows_to``)."""
    n_shards = mesh.shape[axis]

    def update(source, yty, target, row_ids, idx, val, mask, reg, alpha,
               solver="cholesky", cg_steps=3, gather_dtype=None):
        body = functools.partial(
            _sharded_update_body, axis=axis, n_shards=n_shards, mode=mode,
            solver=solver, cg_steps=cg_steps, gather_dtype=gather_dtype,
        )
        f = shard_map(
            body, mesh=mesh,
            in_specs=(
                P(axis, None), P(), P(axis, None), P(axis),
                P(axis, None), P(axis, None), P(axis, None), P(), P(),
            ),
            out_specs=P(axis, None),
        )
        return f(source, yty, target, row_ids, idx, val, mask, reg, alpha)

    return jax.jit(
        update, donate_argnums=(2,),
        static_argnames=("solver", "cg_steps", "gather_dtype"),
    )


def make_pipelined_solve(mesh: Mesh, axis: str = DATA_AXIS, mode: str = "allgather"):
    """Solve-only program for the pipelined half-sweep's FIRST bucket:
    row-sharded solved block out, target untouched (read transiently for
    the CG warm start only — NOT donated, the landing comes later)."""
    n_shards = mesh.shape[axis]

    def solve(source, yty, target, row_ids, idx, val, mask, reg, alpha,
              solver="cholesky", cg_steps=3, gather_dtype=None):
        body = functools.partial(
            _pipelined_solve_body, axis=axis, n_shards=n_shards, mode=mode,
            solver=solver, cg_steps=cg_steps, gather_dtype=gather_dtype,
        )
        f = shard_map(
            body, mesh=mesh,
            in_specs=(
                P(axis, None), P(), P(axis, None), P(axis),
                P(axis, None), P(axis, None), P(axis, None), P(), P(),
            ),
            out_specs=P(axis),
        )
        return f(source, yty, target, row_ids, idx, val, mask, reg, alpha)

    return jax.jit(solve, static_argnames=("solver", "cg_steps", "gather_dtype"))


def make_pipelined_landsolve(
    mesh: Mesh, axis: str = DATA_AXIS, mode: str = "allgather"
):
    """The pipelined half-sweep's steady-state program: land the PREVIOUS
    bucket's solved block (its all-gather issued ahead of compute) while
    solving THIS bucket — the fused landing scatter. Returns
    ``(target, solved_l)``; target is donated — the consumed previous
    block and the bucket slabs are NOT (slabs are reused by resident
    sweeps, and a (B, k) block is too small to be worth the
    shape-mismatched-alias donation warnings)."""
    n_shards = mesh.shape[axis]

    def landsolve(source, yty, target, prev_rows, prev_solved,
                  row_ids, idx, val, mask, reg, alpha,
                  solver="cholesky", cg_steps=3, gather_dtype=None):
        body = functools.partial(
            _pipelined_landsolve_body, axis=axis, n_shards=n_shards,
            mode=mode, solver=solver, cg_steps=cg_steps,
            gather_dtype=gather_dtype,
        )
        f = shard_map(
            body, mesh=mesh,
            in_specs=(
                P(axis, None), P(), P(axis, None), P(axis), P(axis, None),
                P(axis), P(axis, None), P(axis, None), P(axis, None),
                P(), P(),
            ),
            out_specs=(P(axis, None), P(axis)),
        )
        return f(source, yty, target, prev_rows, prev_solved,
                 row_ids, idx, val, mask, reg, alpha)

    return jax.jit(
        landsolve, donate_argnums=(2,),
        static_argnames=("solver", "cg_steps", "gather_dtype"),
    )


def make_landing_flush(mesh: Mesh, axis: str = DATA_AXIS):
    """Land one pending solved block (the pipelined half-sweep's tail)."""

    def flush(target, rows, solved):
        f = shard_map(
            functools.partial(_landing_flush_body, axis=axis),
            mesh=mesh,
            in_specs=(P(axis, None), P(axis), P(axis, None)),
            out_specs=P(axis, None),
        )
        return f(target, rows, solved)

    return jax.jit(flush, donate_argnums=(0,))


class _BucketPrefetcher:
    """Double-buffered background bucket uploader for the streamed pipelined
    half-sweep (the ALX host-feeding pattern, arXiv:2112.02194).

    A daemon thread pulls HOST buckets from the provider's iterable — the
    disk read/parse runs off the critical path — pads them and issues the
    async ``device_put`` (``ShardedALSFit.put_bucket``), then parks the
    device bucket in a 1-deep queue. A slot semaphore keeps exactly TWO
    buckets in flight (the one the sweep is solving + the one just
    uploaded): that is the footprint ``utils.capacity.plan_fit_sharded``
    prices for the pipelined-streamed rung, so upload never runs ahead of
    the admission that approved it.

    Failure semantics: an exception in the thread (including the
    ``als.shard.prefetch`` fault site's kinds) is delivered to the
    consuming sweep at its next bucket and re-raised there — a clean failed
    fit. A wedged thread cannot hang the fit: the consumer's queue wait is
    bounded by the collective deadline (:class:`PrefetchStalled`). On ANY
    exit — normal, error, or an exception thrown by the sweep itself (a
    device loss mid-chunk) — the context manager stops the thread and
    drops whatever was in flight, so an elastic remesh-resume never sees a
    half-applied bucket: the chunk re-runs whole from the last boundary.
    """

    def __init__(self, engine: "ShardedALSFit", host_buckets, stats: dict,
                 deadline_s: float):
        self._engine = engine
        self._buckets = host_buckets
        self._stats = stats
        self._deadline = float(deadline_s)
        self._q: queue.Queue = queue.Queue(maxsize=1)
        self._slot = threading.Semaphore(1)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="albedo-shard-prefetch", daemon=True
        )

    def __enter__(self) -> "_BucketPrefetcher":
        self._thread.start()
        return self

    def __exit__(self, *exc) -> bool:
        self._stop.set()
        try:  # unblock a put()-parked thread so it can observe the stop
            self._q.get_nowait()
        except queue.Empty:
            pass
        self._slot.release()
        self._thread.join(timeout=2.0)
        return False

    # ------------------------------------------------- background uploader
    def _run(self) -> None:
        try:
            for b in self._buckets:
                while not self._slot.acquire(timeout=0.1):
                    if self._stop.is_set():
                        return
                if self._stop.is_set():
                    return
                SHARD_STREAM_FAULT.hit()
                SHARD_PREFETCH_FAULT.hit()
                t0 = time.perf_counter()
                dev = self._engine.put_bucket(b)
                # Disjoint stats keys, one writer each: this thread owns
                # upload_s/streamed_buckets, the consumer owns
                # prefetch_wait_s; dict item stores are GIL-atomic.
                self._stats["upload_s"] += time.perf_counter() - t0   # albedo: noqa[shared-state-guard]
                self._stats["streamed_buckets"] += 1
                self._put(("bucket", dev))
        except BaseException as e:  # noqa: BLE001 — re-raised on the consumer
            self._put(("error", e))
            return
        self._put(("done", None))

    def _put(self, item) -> None:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    # ------------------------------------------------------------ consumer
    def __iter__(self) -> "_BucketPrefetcher":
        return self

    def __next__(self):
        t0 = time.perf_counter()
        try:
            kind, payload = self._q.get(
                timeout=self._deadline if self._deadline > 0 else None
            )
        except queue.Empty:
            raise PrefetchStalled(self._deadline) from None
        # Disjoint stats key: the consumer thread is the only writer of
        # prefetch_wait_s (the uploader owns upload_s/streamed_buckets);
        # dict item stores are GIL-atomic.
        self._stats["prefetch_wait_s"] += time.perf_counter() - t0  # albedo: noqa[shared-state-guard]
        if kind == "error":
            raise payload
        if kind == "done":
            raise StopIteration
        self._slot.release()  # free the slot: upload bucket i+2 while i+1 solves
        return payload


def _acquire_executable(
    engine: "ShardedALSFit", fn, kind: str, args, stats: dict, shape_key: tuple
):
    """Per-shape executable through the persistent AOT layer, memoized on
    the engine; ``kind`` names which of the sweep's programs this is
    (update / solve / landsolve / flush) — each gets its own key space and
    its own fingerprint-verified disk export. A module-level conduit
    (forwards ``fn`` into ``persistent_aot_executable``) so graftlint R1
    can prove every pipelined program reaches the AOT layer."""
    from albedo_tpu.utils.aot import persistent_aot_executable

    key = (kind,) + shape_key
    compiled = engine._executables.get(key)
    if compiled is None:
        dev = jax.devices()[0]
        statics = None if kind == "flush" else engine._statics()
        compiled, c_s, tag = persistent_aot_executable(
            fn, args, None, statics,
            key_parts=(
                "als_sharded", kind, jax.__version__,
                jax.default_backend(), getattr(dev, "device_kind", "?"),
                repr(engine.mesh), engine.mode, engine.solver,
                engine.cg_steps, engine.gather_dtype,
            ) + shape_key,
            name=f"als_sharded_{kind}",
        )
        engine._executables[key] = compiled
        stats["compile_s"] += c_s
        stats["compile_sources"].add(tag)
    return compiled


@functools.lru_cache(maxsize=8)
def sharded_fit_engine(
    mesh: Mesh,
    axis: str = DATA_AXIS,
    solver: str = "cholesky",
    cg_steps: int = 3,
    gather_dtype: str | None = None,
    mode: str = "allgather",
) -> "ShardedALSFit":
    """Memoized engine factory: ``Mesh`` is hashable and value-compared, so
    repeated fits on the same layout reuse the engine's jitted update /
    gramian closures and its per-shape executable handles instead of
    retracing per fit."""
    return ShardedALSFit(
        mesh, axis=axis, solver=solver, cg_steps=cg_steps,
        gather_dtype=gather_dtype, mode=mode,
    )


class ShardedALSFit:
    """The fully sharded ALS fit: both tables row-sharded, buckets resident
    (uploaded once, batch-sharded) or STREAMED from the host per half-sweep
    so the star matrix is never device-resident whole.

    Per-bucket-shape executables are acquired through the persistent AOT
    layer (``utils.aot``) — sharded fits run in the same kill-resume chaos
    as every other fit, so their cross-process executable reuse must stay
    fingerprint-verified; ``models.als.ImplicitALS`` drives this engine when
    the capacity admission ladder picks a sharded rung.
    """

    def __init__(
        self,
        mesh: Mesh,
        axis: str = DATA_AXIS,
        solver: str = "cholesky",
        cg_steps: int = 3,
        gather_dtype: str | None = None,
        mode: str = "allgather",
    ):
        if solver not in ("cholesky", "cg"):
            raise ValueError(f"unknown solver {solver!r}")
        if mode not in ("allgather", "ring"):
            raise ValueError(f"unknown shard mode {mode!r}")
        if mode == "ring" and solver == "cg":
            raise ValueError(
                "ring mode supports the cholesky solver only: the CG matvec "
                "re-reads the gathered rows every step, which would re-run "
                "the whole ring per step — use mode='allgather' with cg"
            )
        self.mesh = mesh
        self.axis = axis
        self.solver = solver
        self.cg_steps = int(cg_steps)
        self.gather_dtype = gather_dtype
        self.mode = mode
        self.n_shards = int(mesh.shape[axis])
        self._update = make_sharded_update(mesh, axis, mode)
        self._solve = make_pipelined_solve(mesh, axis, mode)
        self._landsolve = make_pipelined_landsolve(mesh, axis, mode)
        self._flush = make_landing_flush(mesh, axis)
        self._gramian = sharded_gramian(mesh, axis)
        self._rows1d = row_sharded(mesh, axis)
        self._rows2d = NamedSharding(mesh, P(axis, None))
        self._executables: dict[tuple, object] = {}

    # ------------------------------------------------------------- layout
    def shard_table(self, factors) -> jax.Array:
        """Pad rows to a shard-count multiple (pad rows are zeros — no
        bucket references them) and lay the table out row-sharded."""
        f = np.asarray(factors, dtype=np.float32)
        f = pad_rows_to(f, self.n_shards)
        return jax.device_put(f, self._rows2d)

    def put_bucket(self, b: Bucket) -> Bucket:
        """Pad a host bucket's batch dim to the shard count and upload it
        batch-sharded over the mesh."""
        b = pad_bucket(b, self.n_shards)
        return Bucket(
            row_ids=jax.device_put(np.ascontiguousarray(b.row_ids), self._rows1d),
            idx=jax.device_put(b.idx, self._rows2d),
            val=jax.device_put(b.val, self._rows2d),
            mask=jax.device_put(b.mask, self._rows2d),
        )

    # ------------------------------------------------------------ running
    def _statics(self) -> dict:
        return dict(
            solver=self.solver, cg_steps=self.cg_steps,
            gather_dtype=self.gather_dtype,
        )

    def _run_bucket(self, source, yty, target, b: Bucket, reg, alpha, stats: dict):
        args = (source, yty, target, b.row_ids, b.idx, b.val, b.mask, reg, alpha)
        key = (source.shape[0], target.shape[0], tuple(b.idx.shape))
        return _acquire_executable(self, self._update, "update", args, stats, key)(*args)

    def half_sweep(self, source, target, buckets, reg, alpha, stats,
                   streamed=False, pipelined=False):
        """One sharded half-sweep: psum Gramian, then every bucket's gather
        -> solve -> scatter. ``buckets`` yields HOST buckets when
        ``streamed`` (uploaded one at a time, ``als.shard.stream`` firing
        per upload) and device buckets otherwise. ``pipelined`` runs the
        software-pipelined dataflow instead (:meth:`_half_sweep_pipelined`)."""
        SHARD_GATHER_FAULT.hit()
        SHARD_COLLECTIVE_FAULT.hit()
        yty = self._gramian(source)
        if pipelined:
            return self._half_sweep_pipelined(
                source, yty, target, buckets, reg, alpha, stats, streamed
            )
        for b in buckets:
            if streamed:
                SHARD_STREAM_FAULT.hit()
                t0 = time.perf_counter()
                b = self.put_bucket(b)  # async dispatch; overlaps the solves
                stats["upload_s"] += time.perf_counter() - t0
                stats["streamed_buckets"] += 1
            target = self._run_bucket(source, yty, target, b, reg, alpha, stats)
        return target

    def _half_sweep_pipelined(
        self, source, yty, target, buckets, reg, alpha, stats, streamed
    ):
        """The pipelined driver loop (ARCHITECTURE.md "Pipelined sharded
        dataflow"): when ``streamed``, a background prefetcher uploads
        bucket i+1 while bucket i's solve is dispatched; every bucket after
        the first lands the PREVIOUS bucket's solved block inside its own
        solve dispatch (fused landing scatter, overlapped ring phases), and
        a final flush lands the last pending block."""
        pending = None  # (row_ids, solved_l) awaiting landing

        def run(device_buckets):
            nonlocal target, pending
            for b in device_buckets:
                if pending is None:
                    args = (source, yty, target, b.row_ids, b.idx, b.val,
                            b.mask, reg, alpha)
                    key = (source.shape[0], target.shape[0], tuple(b.idx.shape))
                    solved = _acquire_executable(
                        self, self._solve, "solve", args, stats, key
                    )(*args)
                else:
                    prev_rows, prev_solved = pending
                    args = (source, yty, target, prev_rows, prev_solved,
                            b.row_ids, b.idx, b.val, b.mask, reg, alpha)
                    key = (
                        source.shape[0], target.shape[0],
                        tuple(b.idx.shape), int(prev_rows.shape[0]),
                    )
                    target, solved = _acquire_executable(
                        self, self._landsolve, "landsolve", args, stats, key
                    )(*args)
                pending = (b.row_ids, solved)

        if streamed:
            from albedo_tpu.parallel.elastic import collective_deadline_s

            with _BucketPrefetcher(
                self, buckets, stats, collective_deadline_s()
            ) as prefetched:
                run(prefetched)
        else:
            run(buckets)
        if pending is not None:
            rows, solved = pending
            args = (target, rows, solved)
            key = (target.shape[0], int(rows.shape[0]))
            target = _acquire_executable(
                self, self._flush, "flush", args, stats, key
            )(*args)
        return target

    def fit(
        self,
        user_f,
        item_f,
        user_buckets,
        item_buckets,
        reg: float,
        alpha: float,
        n_iter: int,
        streamed: bool = False,
        callback=None,
        pipelined: bool | None = None,
    ) -> tuple[jax.Array, jax.Array, dict]:
        """Run ``n_iter`` full sweeps; returns ``(user_f, item_f, stats)``
        with the factor tables trimmed back to their unpadded row counts.

        ``user_buckets`` / ``item_buckets`` are lists of host buckets, or
        zero-arg callables returning a fresh iterable per half-sweep — the
        disk-backed scale harness streams each half-sweep's buckets from
        spill files through such a provider without ever holding the whole
        side in memory.

        ``pipelined`` (default: the ``ALBEDO_PIPELINE`` switch) runs the
        pipelined dataflow — double-buffered bucket prefetch when
        ``streamed``, overlapped ring phases, fused landing scatter —
        numerically identical to the synchronous path (parity-pinned at
        1e-5 in ``tests/test_sharded_als.py``); ``False`` is the
        synchronous A/B and triage path.
        """
        if pipelined is None:
            pipelined = pipeline_enabled()
        pipelined = bool(pipelined)
        n_users, n_items = int(user_f.shape[0]), int(item_f.shape[0])
        u_provider = user_buckets if callable(user_buckets) else (lambda: user_buckets)
        i_provider = item_buckets if callable(item_buckets) else (lambda: item_buckets)

        stats = {
            "compile_s": 0.0, "compile_sources": set(),
            "streamed_buckets": 0, "upload_s": 0.0,
            "prefetch_wait_s": 0.0, "pipelined": pipelined,
        }
        user_sh = self.shard_table(user_f)
        item_sh = self.shard_table(item_f)
        if not streamed:
            t0 = time.perf_counter()
            user_dev = [self.put_bucket(b) for b in u_provider()]
            item_dev = [self.put_bucket(b) for b in i_provider()]
            stats["upload_s"] = round(time.perf_counter() - t0, 4)
        reg_arr = jnp.float32(reg)
        alpha_arr = jnp.float32(alpha)

        for it in range(int(n_iter)):
            # MLlib order: item factors first (from users), then users.
            item_sh = self.half_sweep(
                user_sh, item_sh,
                i_provider() if streamed else item_dev,
                reg_arr, alpha_arr, stats, streamed=streamed,
                pipelined=pipelined,
            )
            user_sh = self.half_sweep(
                item_sh, user_sh,
                u_provider() if streamed else user_dev,
                reg_arr, alpha_arr, stats, streamed=streamed,
                pipelined=pipelined,
            )
            if callback is not None:
                callback(
                    it,
                    # Checkpoint-callback host copies, by contract (the
                    # chunked refit journals exactly these per boundary).
                    np.asarray(user_sh)[:n_users],   # albedo: noqa[hidden-host-sync]
                    np.asarray(item_sh)[:n_items],   # albedo: noqa[hidden-host-sync]
                )
        stats["upload_s"] = round(stats["upload_s"], 4)
        stats["prefetch_wait_s"] = round(stats["prefetch_wait_s"], 4)
        stats["n_shapes"] = len(self._executables)
        return user_sh[:n_users], item_sh[:n_items], stats


class ShardedALSSweep:
    """Stateful wrapper: pre-pads buckets for a mesh and runs half-sweeps.

    The EXPLICIT shard_map variant of the sharded sweep, kept as the
    spelled-out-collectives reference implementation (and covered by its own
    parity test). ``ImplicitALS.fit`` itself now runs the fused single-dispatch
    path with batch-axis-sharded bucket groups, letting XLA's SPMD partitioner
    insert the equivalent collectives (``models/als.py device_groups``); both
    share the per-bucket math in ``ops.als.bucket_solve_body``.
    """

    def __init__(self, mesh: Mesh, axis: str = DATA_AXIS):
        self.mesh = mesh
        self.axis = axis
        self._solver = make_sharded_solver(mesh, axis)
        self._n = mesh.shape[axis]

    def prepare(self, buckets: list[Bucket]) -> list[Bucket]:
        """Pad to the shard count and upload once, already laid out row-sharded
        over the mesh (no per-iteration transfer or reshard)."""
        rows = row_sharded(self.mesh, self.axis)
        return [device_bucket(pad_bucket(b, self._n), rows) for b in buckets]

    def half_sweep(self, source, target, buckets, reg, alpha):
        yty = source.T @ source
        reg_arr = jnp.float32(reg)
        alpha_arr = jnp.float32(alpha)
        for b in buckets:
            target = self._solver(
                source, yty, target,
                jnp.asarray(b.row_ids), jnp.asarray(b.idx),
                jnp.asarray(b.val), jnp.asarray(b.mask),
                reg_arr, alpha_arr,
            )
        return target
