"""Sharded implicit-ALS sweeps over a device mesh.

The reference's ALS scales by Spark MLlib's shuffled in/out factor blocks with
per-block LAPACK solves on executors (``ALSRecommenderBuilder.scala:46-58``
just calls ``als.fit``; the block machinery is inside MLlib). TPU-native
replacement, two composable pieces:

1. **Data-parallel bucket solves** (`make_sharded_solver`): each padded bucket's
   batch dimension is sharded over the mesh's ``data`` axis with ``shard_map``
   — every device runs the same fixed-shape gather → Gramian-correction einsum
   → batched-Cholesky pipeline on its slice of the rows, the direct analogue of
   MLlib's per-executor block solves but with no shuffle: the solved rows are
   re-assembled by XLA (an all-gather over ICI) and scattered into the factor
   table.

2. **psum Gramian** (`sharded_gramian`): when a factor table is stored sharded
   over devices (rows split on ``data``), the shared ``YtY`` term of every
   implicit solve is the sum of per-shard partial Gramians — one ``(k, k)``
   ``psum`` over ICI, the pattern SURVEY.md section 7 step 3 prescribes (ALX).

Factor tables are replicated by default: at albedo scale (≤ millions of rows ×
rank 50, float32) a full table is ≤ a few hundred MB — far below HBM — and
replication makes the per-bucket arbitrary-index gather local. The sharded
storage path exists for larger-than-HBM factor tables.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
try:  # jax >= 0.6 exports shard_map at top level
    from jax import shard_map
except ImportError:  # 0.4.x spelling
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from albedo_tpu.datasets.ragged import Bucket, device_bucket
from albedo_tpu.ops.als import bucket_solve_body
from albedo_tpu.parallel.mesh import DATA_AXIS, pad_rows_to, row_sharded


def pad_bucket(b: Bucket, multiple: int) -> Bucket:
    """Pad a bucket's batch dim to a device-count multiple (padding slots have
    ``row_ids == -1`` and zero weight, so they solve garbage that is dropped on
    scatter)."""
    if b.row_ids.shape[0] % multiple == 0:
        return b
    return Bucket(
        row_ids=pad_rows_to(b.row_ids, multiple, fill=-1),
        idx=pad_rows_to(b.idx, multiple),
        val=pad_rows_to(b.val, multiple),
        mask=pad_rows_to(b.mask, multiple),
    )


def sharded_gramian(mesh: Mesh, axis: str = DATA_AXIS):
    """``F^T F`` for a row-sharded factor table: local partial Gramian + psum."""

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=P(axis, None),
        out_specs=P(),
    )
    def gramian(local_factors: jax.Array) -> jax.Array:
        return jax.lax.psum(local_factors.T @ local_factors, axis)

    return gramian


def make_sharded_solver(mesh: Mesh, axis: str = DATA_AXIS):
    """Build the jitted sharded bucket solver for this mesh.

    The returned function has the same signature/semantics as
    ``ops.als.solve_bucket`` but runs the per-row solves data-parallel across
    ``axis``. Bucket batch dims must be divisible by the axis size
    (see ``pad_bucket``).
    """
    n_shards = mesh.shape[axis]

    local_solve = shard_map(
        _local_bucket_solve,
        mesh=mesh,
        in_specs=(P(), P(), P(axis), P(axis, None), P(axis, None), P(axis, None), P(), P()),
        out_specs=P(axis),
    )

    @functools.partial(jax.jit, donate_argnames=("target",))
    def solve_bucket_sharded(source, yty, target, row_ids, idx, val, mask, reg, alpha):
        if row_ids.shape[0] % n_shards:
            raise ValueError(
                f"bucket batch {row_ids.shape[0]} not divisible by {n_shards} shards"
            )
        solved = local_solve(source, yty, row_ids, idx, val, mask, reg, alpha)
        # Scatter back into the (replicated) target; XLA inserts the all-gather
        # of the row-sharded `solved` over ICI.
        safe_rows = jnp.where(row_ids < 0, target.shape[0], row_ids)
        return target.at[safe_rows].set(solved, mode="drop")

    return solve_bucket_sharded


def _local_bucket_solve(source, yty, row_ids, idx, val, mask, reg, alpha):
    """Per-device slice of a bucket solve; math shared with the single-device
    path via ``ops.als.bucket_solve_body``."""
    del row_ids  # only needed for the scatter, outside the shard
    return bucket_solve_body(source, yty, idx, val, mask, reg, alpha)


class ShardedALSSweep:
    """Stateful wrapper: pre-pads buckets for a mesh and runs half-sweeps.

    The EXPLICIT shard_map variant of the sharded sweep, kept as the
    spelled-out-collectives reference implementation (and covered by its own
    parity test). ``ImplicitALS.fit`` itself now runs the fused single-dispatch
    path with batch-axis-sharded bucket groups, letting XLA's SPMD partitioner
    insert the equivalent collectives (``models/als.py device_groups``); both
    share the per-bucket math in ``ops.als.bucket_solve_body``.
    """

    def __init__(self, mesh: Mesh, axis: str = DATA_AXIS):
        self.mesh = mesh
        self.axis = axis
        self._solver = make_sharded_solver(mesh, axis)
        self._n = mesh.shape[axis]

    def prepare(self, buckets: list[Bucket]) -> list[Bucket]:
        """Pad to the shard count and upload once, already laid out row-sharded
        over the mesh (no per-iteration transfer or reshard)."""
        rows = row_sharded(self.mesh, self.axis)
        return [device_bucket(pad_bucket(b, self._n), rows) for b in buckets]

    def half_sweep(self, source, target, buckets, reg, alpha):
        yty = source.T @ source
        reg_arr = jnp.float32(reg)
        alpha_arr = jnp.float32(alpha)
        for b in buckets:
            target = self._solver(
                source, yty, target,
                jnp.asarray(b.row_ids), jnp.asarray(b.idx),
                jnp.asarray(b.val), jnp.asarray(b.mask),
                reg_arr, alpha_arr,
            )
        return target
