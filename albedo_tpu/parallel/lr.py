"""Data-parallel logistic-regression training over the device mesh.

Reference parity: MLlib LR's distributed L-BFGS — per-partition gradient sums
``treeAggregate``d to the driver every iteration
(``LogisticRegressionRanker.scala:330-337``, SURVEY.md §2.5). TPU-native
version: the feature batch is laid out row-sharded over the mesh's ``data``
axis and parameters replicated; the SAME jitted loss as the single-device path
then compiles with XLA-inserted psums over ICI for every weighted reduction —
sharding annotations replace hand-written collectives.

Padding rows carry weight 0, so ``sum(w * ce) / sum(w)`` is invariant.
"""

from __future__ import annotations

import jax
import numpy as np

from albedo_tpu.features.assembler import FeatureMatrix
from albedo_tpu.parallel.mesh import DATA_AXIS, pad_rows_to, row_sharded


def shard_feature_batch(
    fm: FeatureMatrix,
    labels: np.ndarray,
    weights: np.ndarray,
    mesh,
    axis: str = DATA_AXIS,
):
    """Pad rows to a shard-count multiple and upload row-sharded.

    Returns ``(batch, labels, weights)`` device arrays shaped like
    ``ops.sparse_linear.feature_batch`` output; padding rows have weight 0 and
    bag indices -1 (fully masked).
    """
    n_shards = mesh.shape[axis]
    sharding = row_sharded(mesh, axis)

    def put(x: np.ndarray, fill=0):
        return jax.device_put(pad_rows_to(np.asarray(x), n_shards, fill=fill), sharding)

    # Expanded dense block: the row-sharded rectangle every device slices
    # evenly (the factored vec layout would replicate the distinct vectors
    # and shard only the rep gather — a later optimization; parity with the
    # single-device fit is what matters here, and params/scales span the
    # same logical width either way).
    batch = {"dense": put(fm.expanded_dense().astype(np.float32))}
    for f, v in fm.cat.items():
        batch[f"cat:{f}"] = put(v)
    for f in fm.bag_idx:
        idx, val = fm.expanded_bag(f)  # per-row view of factored fields
        batch[f"bag_idx:{f}"] = put(idx, fill=-1)
        batch[f"bag_val:{f}"] = put(val)
    y = put(np.asarray(labels, dtype=np.float32))
    w = put(np.asarray(weights, dtype=np.float32))
    return batch, y, w
