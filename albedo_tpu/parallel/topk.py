"""Item-axis-sharded top-k retrieval.

The reference's retrieval blockifies both factor tables and cross-joins blocks
on Spark executors (``recommenders/ALSRecommender.scala:21-61``); the "long"
axis being scaled is the item dimension (SURVEY.md section 2.5). TPU-native:
shard the item-factor table over the mesh's ``item`` axis; each device scores
its shard with one ``(U, r) @ (r, I/D)`` MXU GEMM, keeps a local top-k, then a
k-per-device candidate ``all_gather`` (tiny: ``U x D*k``) merges to the global
top-k. Communication is O(U * D * k), never O(U * I) — the score matrix is
never materialized globally or gathered.

Users stream through in caller-sized blocks (the ``data`` axis of the same
mesh can shard the user rows too, via ``in_specs``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
try:  # jax >= 0.6 exports shard_map at top level
    from jax import shard_map
except ImportError:  # 0.4.x spelling, where check_vma was still check_rep
    from jax.experimental.shard_map import shard_map as _shard_map_04x

    def shard_map(f, /, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_04x(f, **kwargs)
from jax.sharding import Mesh, PartitionSpec as P

from albedo_tpu.parallel.mesh import DATA_AXIS, ITEM_AXIS


@functools.lru_cache(maxsize=64)
def make_sharded_topk(
    mesh: Mesh,
    k: int,
    item_axis: str = ITEM_AXIS,
    data_axis: str | None = DATA_AXIS,
    with_exclude: bool = False,
):
    """Build a jitted sharded top-k scorer for this mesh.

    Returns ``fn(user_factors (U, r), item_factors_padded (I_pad, r)[, exclude
    (U, E)]) -> (scores (U, k), item_idx (U, k))``. ``I_pad`` must be divisible
    by the item-axis size; pad rows must be all-zero AND callers must pass
    ``n_items`` so pads are masked. User rows are sharded over ``data_axis``
    when given (U divisible by that axis size).
    """
    u_spec = P(data_axis) if data_axis else P()

    def local(uf, vf_local, n_items, exclude):
        shard = jax.lax.axis_index(item_axis)
        block = vf_local.shape[0]
        start = shard * block
        global_ids = start + jnp.arange(block, dtype=jnp.int32)
        scores = uf @ vf_local.T                          # (U/D_d, I/D_i) MXU
        neg_inf = jnp.asarray(-jnp.inf, scores.dtype)
        scores = jnp.where(global_ids[None, :] < n_items, scores, neg_inf)
        if exclude is not None:
            local_idx = exclude - start                   # (U/D_d, E)
            oob = (local_idx < 0) | (local_idx >= block) | (exclude < 0)
            local_idx = jnp.where(oob, block, local_idx)
            hit = jnp.zeros(scores.shape, bool)
            rows = jnp.arange(scores.shape[0])[:, None]
            hit = hit.at[rows, local_idx].set(True, mode="drop")
            scores = jnp.where(hit, neg_inf, scores)
        # A shard can hold fewer than k items; the global top-k only needs
        # min(k, block) candidates from each shard.
        k_local = min(k, block)
        vals, idx = jax.lax.top_k(scores, k_local)        # local top-k
        idx = jnp.take(global_ids, idx)
        # Candidate merge: k_local per device -> (U/D_d, D_i*k_local).
        all_vals = jax.lax.all_gather(vals, item_axis, axis=1, tiled=True)
        all_idx = jax.lax.all_gather(idx, item_axis, axis=1, tiled=True)
        if all_vals.shape[1] < k:  # total (padded) catalog smaller than k
            fill = k - all_vals.shape[1]
            all_vals = jnp.pad(all_vals, ((0, 0), (0, fill)), constant_values=-jnp.inf)
            all_idx = jnp.pad(all_idx, ((0, 0), (0, fill)), constant_values=-1)
        out_v, pos = jax.lax.top_k(all_vals, k)
        out_i = jnp.take_along_axis(all_idx, pos, axis=1)
        # Slots that never saw a real item (k > catalog) carry -inf; report
        # index -1 rather than a padded/masked item id.
        out_i = jnp.where(jnp.isneginf(out_v), -1, out_i)
        return out_v, out_i

    # After the candidate all_gather every item shard computes the same merged
    # top-k, so the outputs are replicated over `item_axis`; the varying-axes
    # checker can't infer that, hence check_vma=False.
    if with_exclude:
        fn = shard_map(
            lambda uf, vf, n, ex: local(uf, vf, n, ex),
            mesh=mesh,
            in_specs=(u_spec, P(item_axis, None), P(), u_spec),
            out_specs=(u_spec, u_spec),
            check_vma=False,
        )
    else:
        fn = shard_map(
            lambda uf, vf, n: local(uf, vf, n, None),
            mesh=mesh,
            in_specs=(u_spec, P(item_axis, None), P()),
            out_specs=(u_spec, u_spec),
            check_vma=False,
        )
    # The jitted callable is acquired exclusively through the persistent AOT
    # layer (``sharded_topk_scores`` below — the retrieval bank's sharded
    # query path), so per-shape executables survive process boundaries with
    # the same fingerprint-verified reuse every other serving program gets.
    return jax.jit(fn)


def _padded_device(arr, multiple: int, fill=0):
    """``arr`` padded on axis 0 to a device-count multiple, as a device
    array. An ALREADY-ALIGNED array skips the host round trip entirely —
    that is what lets callers (the retrieval bank's mesh path) pre-pad and
    pin their tables once at build and pass the resident array per query
    instead of paying a full host->device copy of the table per batch."""
    import numpy as np

    from albedo_tpu.parallel.mesh import pad_rows_to

    if arr.shape[0] % multiple == 0:
        return jnp.asarray(arr)  # no-op for device arrays, upload for host
    return jnp.asarray(pad_rows_to(np.asarray(arr), multiple, fill=fill))


def sharded_topk_scores(
    user_factors: jax.Array,
    item_factors: jax.Array,
    k: int,
    mesh: Mesh,
    exclude_idx: jax.Array | None = None,
    n_items: int | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Sharded MIPS top-k through the persistent AOT layer.

    Pads the item table to the item-axis size and the user rows to the
    data-axis size, strips the user padding from the result. ``n_items``
    declares how many leading item rows are REAL when the caller passes a
    pre-padded (device-resident) table — pad rows must be zero and are
    masked out of the top-k. Executables are keyed by (padded shapes, k,
    mesh, backend) and cached through ``utils.aot`` — memory LRU, disk
    export where serializable, fingerprint verification — so a serving
    process re-dispatches without re-tracing.
    """
    from albedo_tpu.utils.aot import persistent_aot_call

    n_items = item_factors.shape[0] if n_items is None else int(n_items)
    n_users = user_factors.shape[0]
    d_item = mesh.shape[ITEM_AXIS]
    d_data = mesh.shape[DATA_AXIS]
    vf = _padded_device(item_factors, d_item)
    uf = _padded_device(user_factors, d_data)
    dev = mesh.devices.flat[0]
    if exclude_idx is not None:
        ex = _padded_device(exclude_idx, d_data, fill=-1)
        fn = make_sharded_topk(mesh, k, with_exclude=True)
        args = (uf, vf, jnp.int32(n_items), ex)
        ex_shape = tuple(ex.shape)
    else:
        fn = make_sharded_topk(mesh, k)
        args = (uf, vf, jnp.int32(n_items))
        ex_shape = ()
    key_parts = (
        "sharded_topk", k, tuple(uf.shape), tuple(vf.shape), ex_shape,
        str(uf.dtype), getattr(dev, "device_kind", "?"), repr(mesh),
        jax.default_backend(),
    )
    (vals, idx), _, _ = persistent_aot_call(
        fn, args, None, None, key_parts, name="sharded_topk"
    )
    return vals[:n_users], idx[:n_users]
