"""Elastic sharded ALS: mesh-portable checkpoints, mid-fit device-loss
detection, and remesh-resume down the degraded ladder.

PR 7 made device loss a handled event at mesh *creation* (the 8 -> 4 -> 2
-> 1 boot ladder) and PR 8 made data bigger than one chip trainable — but
the sharded fit itself stayed all-or-nothing: a shard dying mid-sweep
killed the whole fit and every byte of progress, exactly the failure mode
ALX-scale preemptible fleets (arXiv:2112.02194) and the parallel-ALS
recovery literature (arXiv:1508.03110) treat as routine. This module is
the missing elastic loop around ``ShardedALSFit``:

1. **Sweep-boundary checkpoints** through
   :class:`~albedo_tpu.utils.checkpoint.ShardedStepCheckpointer`:
   row-sharded factor tables written as mesh-size-independent logical
   tables (per-shard files + a sealed layout manifest), so a fit
   checkpointed on 8 devices resumes bit-compatibly on 4, 2, or 1 — the
   resuming engine re-shards the logical table onto ITS mesh.
2. **Loss detection**: a collective watchdog deadline around every chunk's
   dispatch (the all-gather/ring phases plus the fused health read that is
   the completion barrier) classifies a HUNG shard, and
   ``utils.retry.is_collective_lost`` classifies a DEAD one (jaxlib
   ``DEADLINE_EXCEEDED``, distributed-runtime heartbeat failures, the
   ``als.shard.collective`` fault site's ``loss`` kind).
3. **Remesh-resume**: on a detected loss the driver checkpoints surviving
   state where possible (the last sweep boundary's factors), steps one
   rung down the ladder (:func:`~albedo_tpu.parallel.mesh.next_ladder_rung`),
   re-prices the smaller rung through ``capacity.admit_ladder``
   (:meth:`~albedo_tpu.models.als.ImplicitALS.admission_mesh`), re-shards,
   and continues the sweep. ONE remediation attempt per loss budget; when
   the budget is spent or no rung remains, the fit fails CLEANLY with
   :class:`MeshLost` and a journaled cause (journal status ``mesh_lost``)
   — never a hang, never silent data loss.

Losses are counted in ``albedo_mesh_losses_total`` and resume outcomes in
``albedo_elastic_resumes_total{outcome=}``; the fit report gains a
``mesh_events`` record (losses, resumes, remesh trail, checkpoint overhead
per sweep) so elasticity cost is visible in the bench trajectory.

The driver always runs the ROW-SHARDED engine (``sharded="resident"`` or
``"streamed"``, never the replicated GSPMD path): replicated tables cannot
lose a shard without losing the whole model, so elasticity is only
meaningful — and the `als.shard.collective` surface only exists — on the
sharded layout. The admission ladder still re-prices every (re)mesh and
still refuses when even streaming busts the budget.

A note on hung (vs dead) shards: a chunk that exceeds the deadline is
abandoned — its worker thread is left to finish (or wedge) in the
background while the driver remeshes. On a real slice the wedged backend
is unusable anyway and the remesh targets the surviving devices; on the
CPU simulator an injected ``delay`` simply finishes harmlessly after the
remesh has moved on.

Pipelined streamed fits (the default dataflow — ARCHITECTURE.md
"Pipelined sharded dataflow") need no extra machinery here, by design: a
device loss with a PREFETCHED bucket in flight drains cleanly to the last
sweep boundary because (a) the prefetcher is a context manager inside the
chunk's fit — when the loss propagates, it stops its uploader thread and
drops the in-flight device bucket on the way out — and (b) the chunk's
half-applied factor tables are discarded whole: the remesh re-runs the
chunk from the boundary checkpoint, so no half-applied bucket can survive
into the resumed state (parity-pinned in ``tests/test_elastic.py``). A
WEDGED prefetch thread is bounded by the same collective deadline at the
prefetcher's own queue wait (``parallel.als.PrefetchStalled``, a plain
non-loss failure: remeshing cannot revive a host-side reader) with this
driver's chunk deadline as the backstop.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
import threading
from pathlib import Path

import numpy as np

from albedo_tpu.parallel.mesh import DATA_AXIS, make_mesh, next_ladder_rung
from albedo_tpu.utils import capacity as capacity_mod
from albedo_tpu.utils import events
from albedo_tpu.utils.checkpoint import Preempted, ShardedStepCheckpointer
from albedo_tpu.utils.retry import is_collective_lost

log = logging.getLogger(__name__)

_ENV_DEADLINE = "ALBEDO_COLLECTIVE_DEADLINE_S"
_DEFAULT_DEADLINE_S = 300.0


class CollectiveTimeout(RuntimeError):
    """The collective watchdog's deadline tripped: a chunk's dispatch (the
    all-gather/ring phases plus the fused health read that is its
    completion barrier) did not finish in time — the signature of a hung
    shard that will never answer. The message carries DEADLINE_EXCEEDED on
    purpose: ``utils.retry.is_collective_lost`` classifies this exactly
    like jaxlib's own collective timeout, so both land on the same elastic
    path."""

    def __init__(self, deadline_s: float, detail: str = ""):
        super().__init__(
            f"DEADLINE_EXCEEDED: sharded fit chunk exceeded the "
            f"{deadline_s:g}s collective deadline"
            + (f" ({detail})" if detail else "")
        )
        self.deadline_s = float(deadline_s)


class MeshLost(RuntimeError):
    """The elastic fit is out of options: a shard loss was detected and the
    remediation budget is spent (or there is no smaller ladder rung). The
    journal records status ``mesh_lost`` with the cause; the CLI surfaces
    this as a plain failure (exit 1) — the surviving checkpoints remain,
    so a rerun on healthy hardware resumes from the last boundary."""

    def __init__(self, step: int, cause: BaseException, directory: Path | None = None):
        super().__init__(
            f"mesh lost at step {step}: {cause!r}"
            + (f" (checkpoints in {directory})" if directory else "")
        )
        self.step = int(step)
        self.cause = cause
        self.directory = directory


def collective_deadline_s() -> float:
    """The collective watchdog deadline (seconds; <= 0 disables). Env
    ``ALBEDO_COLLECTIVE_DEADLINE_S`` overrides the 300 s default — CPU
    drills shrink it, giant real-slice sweeps may need to grow it."""
    raw = os.environ.get(_ENV_DEADLINE)
    if raw is None:
        return _DEFAULT_DEADLINE_S
    try:
        return float(raw)
    except ValueError:
        return _DEFAULT_DEADLINE_S


def _run_with_deadline(fn, deadline_s: float, detail: str = ""):
    """Run ``fn`` under the collective deadline. A timeout abandons the
    worker (see module docstring) and raises :class:`CollectiveTimeout`.

    The worker is a DAEMON thread on purpose: concurrent.futures threads
    are non-daemon and joined at interpreter exit, so an abandoned wedged
    dispatch would turn the promised clean exit into a process that never
    exits — the exact hang the deadline exists to prevent."""
    if not deadline_s or deadline_s <= 0:
        return fn()
    result: list = []
    error: list = []
    done = threading.Event()

    def worker():
        try:
            result.append(fn())
        except BaseException as e:  # noqa: BLE001 — re-raised on the caller
            error.append(e)
        finally:
            done.set()

    # Never joined BY DESIGN: on CollectiveTimeout the wedged dispatch is
    # abandoned (daemon, so it cannot pin the exit) — joining it would
    # re-create the hang the deadline exists to break.
    t = threading.Thread(target=worker, name="albedo-elastic-chunk", daemon=True)  # albedo: noqa[executor-lifecycle]
    t.start()
    if not done.wait(deadline_s):
        raise CollectiveTimeout(deadline_s, detail)
    if error:
        raise error[0]
    return result[0]


def run_with_deadline(fn, deadline_s: float, detail: str = ""):
    """Public seam on the collective watchdog for other elastic loops (the
    ``score_all`` sweep wraps each shard's device work in it): same daemon
    thread + abandon-on-timeout semantics as the sharded fit's chunks."""
    return _run_with_deadline(fn, deadline_s, detail)


_CHOSEN_TO_MODE = {
    # The elastic driver never runs the replicated GSPMD rung (see module
    # docstring): an ample budget keeps resident sharded tables.
    "als_fit": "resident",
    "als_fit_sharded": "resident",
    "als_fit_sharded_streamed": "streamed",
    # The admission ladder's cheapest rung: streamed with the pipelined
    # double-buffer traded away (one bucket slab in flight).
    "als_fit_sharded_streamed_sync": "streamed_sync",
}


def _resolve_mode(est, matrix, forced) -> tuple[str, dict | None]:
    """One counted ``admit_ladder`` pricing per (re)mesh: the rung's
    per-device shard sizes change with the device count, so every remesh
    re-prices before any byte moves. ``forced`` pins the mode but the
    re-pricing (and its refuse -> ``CapacityExceeded``) still runs."""
    if not capacity_mod.enabled():
        return (forced or "resident"), None
    verdict = est.admission_mesh(matrix)  # raises CapacityExceeded on refuse
    if forced:
        return forced, verdict.to_dict()
    return _CHOSEN_TO_MODE[verdict.chosen], verdict.to_dict()


def elastic_sharded_fit(
    est,
    matrix,
    directory: str | Path,
    every: int = 5,
    keep_last: int | None = None,
    preemption=None,
    watchdog=None,
    max_losses: int = 1,
    deadline_s: float | None = None,
):
    """Resumable, loss-tolerant sharded ALS training (see module doc).

    ``est`` is an :class:`~albedo_tpu.models.als.ImplicitALS` with
    ``est.mesh`` set; ``est.sharded`` of ``"resident"``/``"streamed"``/
    ``True`` pins the shard mode, anything else lets the admission ladder
    choose per mesh. Training runs in chunks of ``every`` sweeps; every
    chunk boundary writes a mesh-portable sharded checkpoint, honors a
    pending :class:`~albedo_tpu.utils.checkpoint.PreemptionHandler` stop
    (journal ``preempted``, :class:`Preempted` -> CLI exit 75), and runs
    the divergence ``watchdog`` (one damped re-run before
    ``TrainingDiverged``) — the same contract as the single-device
    ``checkpointed_als_fit``, extended with the loss state machine.

    Returns the trained :class:`~albedo_tpu.models.als.ALSModel`;
    ``est.last_fit_report`` carries the final chunk's report plus the
    ``mesh_events`` record.
    """
    from albedo_tpu.models.als import ALSModel
    from albedo_tpu.utils.watchdog import TrainingDiverged, damped

    if est.mesh is None:
        raise ValueError("elastic_sharded_fit needs an estimator with a mesh")
    if every < 1:
        raise ValueError(f"checkpoint interval must be >= 1, got {every}")
    deadline = collective_deadline_s() if deadline_s is None else float(deadline_s)
    forced = est.sharded if est.sharded in (
        "resident", "streamed", "streamed_sync"
    ) else ("resident" if est.sharded is True else None)
    orig_est = est

    ckpt = ShardedStepCheckpointer(directory, keep_last=keep_last)
    degraded_before = events.mesh_degraded.total()
    mesh_events: dict = {
        "n_shards_start": int(est.mesh.shape[DATA_AXIS]),
        "losses": 0,
        "resumes": 0,
        "remeshes": [],
        "checkpoint_s": 0.0,
    }

    def _journal_extra(cause: str | None = None) -> dict:
        extra: dict = {"mesh_events": dict(
            mesh_events,
            n_shards=int(est.mesh.shape[DATA_AXIS]),
            degradations=int(events.mesh_degraded.total() - degraded_before),
        )}
        if cause is not None:
            extra["cause"] = cause
        if watchdog is not None and watchdog.trips:
            extra["watchdog"] = watchdog.trips
        return extra

    latest = ckpt.restore_latest()  # sweeps stale shard tmps first
    start, factors = 0, None
    if latest is not None:
        start, arrays = latest
        if int(arrays["rank"]) != est.rank:
            raise ValueError(
                f"checkpoint rank {int(arrays['rank'])} != configured rank "
                f"{est.rank}; refusing to resume into a wrong-rank model"
            )
        expect = ((matrix.n_users, est.rank), (matrix.n_items, est.rank))
        got = (arrays["user_factors"].shape, arrays["item_factors"].shape)
        if tuple(got[0]) != expect[0] or tuple(got[1]) != expect[1]:
            raise ValueError(
                f"checkpoint factor shapes {got} do not match the "
                f"matrix/config {expect}"
            )
        factors = (arrays["user_factors"], arrays["item_factors"])
        if start >= est.max_iter:
            ckpt.write_journal("complete", start, est.max_iter, extra=_journal_extra())
            return ALSModel.from_arrays(arrays)

    # Admission prices THIS mesh's rung — including a resume landing on a
    # smaller (degraded) mesh than the one that checkpointed.
    mode, admission = _resolve_mode(est, matrix, forced)
    ckpt.write_journal("running", start, est.max_iter, extra=_journal_extra())

    report: dict = {}
    model = None
    resume_pending = False
    while start < est.max_iter:
        n = min(every, est.max_iter - start)
        prev = factors
        chunk_est = dataclasses.replace(
            est, max_iter=n, init_factors=prev, sharded=mode
        )
        try:
            model = _run_with_deadline(
                lambda: chunk_est.fit(matrix), deadline,
                detail=f"step {start}+{n} on {est.mesh.shape[DATA_AXIS]} shard(s)",
            )
        except Exception as e:  # noqa: BLE001 — classified below
            if not is_collective_lost(e):
                raise
            # --- the loss state machine ----------------------------------
            mesh_events["losses"] += 1
            events.mesh_losses.inc()
            n_now = int(est.mesh.shape[DATA_AXIS])
            log.error(
                "shard loss detected mid-fit at step %d on %d shard(s): %r",
                start, n_now, e,
            )
            # Surviving state is already durable: every advance of `start`
            # sealed a sweep-boundary checkpoint (and retention never
            # prunes the newest step), so the loss costs at most the
            # in-flight chunk — a loss before the first boundary has
            # nothing to save and the resumed chunk re-seeds
            # deterministically.
            rung = next_ladder_rung(n_now)
            if mesh_events["losses"] > max_losses or rung is None:
                events.elastic_resumes.inc(outcome="failed")
                ckpt.write_journal(
                    "mesh_lost", start, est.max_iter,
                    extra=_journal_extra(cause=repr(e)),
                )
                raise MeshLost(start, e, ckpt.directory) from e
            new_mesh = make_mesh(rung)
            mesh_events["remeshes"].append({
                "step": int(start), "from_shards": n_now,
                "to_shards": int(new_mesh.shape[DATA_AXIS]),
                "cause": repr(e)[-200:],
            })
            est = dataclasses.replace(est, mesh=new_mesh)
            # admit_ladder re-prices the smaller rung before the resume —
            # per-device shard sizes double, so the chosen mode may change.
            # A refuse is as terminal as running out of rungs: journal it
            # (a journal stuck at "running" would read as a live fit) and
            # fail as a clean MeshLost carrying the capacity refusal.
            try:
                mode, admission = _resolve_mode(est, matrix, forced)
            except capacity_mod.CapacityExceeded as ce:
                events.elastic_resumes.inc(outcome="failed")
                ckpt.write_journal(
                    "mesh_lost", start, est.max_iter,
                    extra=_journal_extra(cause=f"{e!r}; resume refused: {ce}"),
                )
                raise MeshLost(start, ce, ckpt.directory) from ce
            mesh_events["remeshes"][-1]["admission"] = admission
            resume_pending = True
            ckpt.write_journal(
                "running", start, est.max_iter, extra=_journal_extra(cause=repr(e))
            )
            continue
        report = chunk_est.last_fit_report
        factors = (model.user_factors, model.item_factors)
        if resume_pending:
            resume_pending = False
            mesh_events["resumes"] += 1
            events.elastic_resumes.inc(outcome="resumed")
        if watchdog is not None and watchdog.check(start + n, *factors):
            # One damped re-run of the tripped chunk from the previous
            # boundary (the single-device remediation contract). A device
            # loss DURING this re-run is terminal but clean: remediating
            # two distinct failure modes at once is not attempted — the
            # loss is counted and journaled (never a journal stuck at
            # "running") and the fit fails as MeshLost; the boundary
            # checkpoints survive for a rerun on healthy hardware.
            chunk_est = dataclasses.replace(
                damped(est), max_iter=n, init_factors=prev, sharded=mode
            )
            try:
                model = _run_with_deadline(
                    lambda: chunk_est.fit(matrix), deadline, detail="damped re-run"
                )
            except Exception as e:  # noqa: BLE001 — classified below
                if not is_collective_lost(e):
                    raise
                mesh_events["losses"] += 1
                events.mesh_losses.inc()
                events.elastic_resumes.inc(outcome="failed")
                ckpt.write_journal(
                    "mesh_lost", start, est.max_iter,
                    extra=_journal_extra(
                        cause=f"loss during damped remediation: {e!r}"
                    ),
                )
                raise MeshLost(start, e, ckpt.directory) from e
            factors = (model.user_factors, model.item_factors)
            if watchdog.check(start + n, *factors):
                ckpt.write_journal(
                    "diverged", start, est.max_iter, extra=_journal_extra()
                )
                raise TrainingDiverged(start + n, watchdog.trips[-1]["kinds"])
            watchdog.mark_remediated()
        start += n
        t0 = time.perf_counter()
        ckpt.save(start, {
            "user_factors": factors[0], "item_factors": factors[1],
            "rank": np.int64(est.rank),
        }, n_shards=int(est.mesh.shape[DATA_AXIS]))
        mesh_events["checkpoint_s"] += time.perf_counter() - t0
        if preemption is not None and preemption.should_stop() and start < est.max_iter:
            ckpt.write_journal("preempted", start, est.max_iter, extra=_journal_extra())
            raise Preempted(start, ckpt.directory)
        ckpt.write_journal("running", start, est.max_iter, extra=_journal_extra())

    mesh_events["checkpoint_s"] = round(mesh_events["checkpoint_s"], 4)
    mesh_events["checkpoint_overhead_per_sweep_s"] = round(
        mesh_events["checkpoint_s"] / max(1, start), 4
    )
    ckpt.write_journal("complete", start, est.max_iter, extra=_journal_extra())
    final = dict(
        mesh_events,
        n_shards=int(est.mesh.shape[DATA_AXIS]),
        degradations=int(events.mesh_degraded.total() - degraded_before),
    )
    orig_est.last_fit_report = dict(report, mesh_events=final, capacity=admission)
    if model is None:  # pragma: no cover — start >= max_iter handled above
        model = ALSModel(user_factors=factors[0], item_factors=factors[1],
                         rank=est.rank)
    return model
