"""Mesh-resident fold-in: the streaming solve sharded over a device mesh.

`streaming.foldin.FoldInEngine` solves touched user rows against a frozen
item table that is fully resident on ONE device — fine at smoke scale,
impossible on any catalog that needs the mesh (ROADMAP item 2: at the
out-of-core 10M x 1M parameterization the item side alone busts a single
device). This module is the mesh citizen of that solve: the frozen item
factors live ROW-SHARDED over the mesh (the ALX posture, arXiv:2112.02194),
their Gramian is the one-psum `sharded_gramian`, and each fold-in batch is
routed so every touched user lands on the device that owns their row shard
and is solved there against ring-passed or all-gathered item shards with
the SAME `bucket_partial_terms`/`solve_corrected` kernels the training
sweep uses (arXiv:1508.03110 composed with PR 8's ring factoring) — no
full item table ever resident on one device in ring mode.

Contracts carried over from the single-device engine, unchanged:

- **pow2 shape ladder through the persistent AOT layer** — the slab is
  ``n_shards * pow2(max per-shard users) x pow2(row length)``, each shape
  compiled once via `persistent_aot_executable` and the handle held;
  regularization and alpha stay traced so the damped watchdog re-solve
  reuses the same executable.
- **The health read is the completion barrier** — each shard reduces its
  solved block to `utils.watchdog.factor_health` partials which are
  psum/pmax'd into ONE replicated (3,) vector inside the same program; its
  single d2h read synchronizes every shard with zero added round-trips
  (bit-identical semantics to `factor_health(solved, solved)` on the
  assembled block).
- **Deadline-guarded collectives** — every dispatch (solve + health read)
  runs under `parallel.elastic.run_with_deadline`, so a dead shard
  surfaces as the same loss-shaped `CollectiveTimeout` the elastic fit
  classifies, never a hang. The streaming cycle (streaming/job.py) drains
  to its last sealed publish, remeshes down the ladder and re-solves.

The `stream.foldin.collective` fault site fires at the head of every
sharded batch dispatch: its `loss` kind raises the device-loss-shaped
error a dead shard surfaces as, which is how the chaos drill kills a
device mid-cycle and pins the 8 -> 4 remesh with fold-in parity.
"""

from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp
import numpy as np
try:  # jax >= 0.6 exports shard_map at top level
    from jax import shard_map
except ImportError:  # 0.4.x spelling
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from albedo_tpu.ops.als import bucket_solve_body
from albedo_tpu.parallel.als import _ring_solve, sharded_gramian
from albedo_tpu.parallel.mesh import DATA_AXIS, pad_rows_to
from albedo_tpu.utils import faults
from albedo_tpu.utils import pow2_at_least as _pow2

log = logging.getLogger(__name__)

# Chaos hook for the mesh-resident fold-in: fires at the head of every
# sharded batch dispatch (the all-gather / ring phases plus the fused
# health psum follow it). The `loss` kind raises the device-loss-shaped
# error a dead shard surfaces as — `utils.retry.is_collective_lost`
# classifies it and the streaming cycle's elastic path (streaming/job.py)
# drains to the last sealed publish, remeshes down the ladder, and
# re-solves the interrupted batch on the smaller rung.
FOLDIN_COLLECTIVE_FAULT = faults.site("stream.foldin.collective")


def _foldin_body(vf_l, yty, idx_l, val_l, mask_l, reg, alpha,
                 *, axis, n_shards, mode):
    """Per-shard fold-in solve + fused health partials.

    ``mode="ring"``: the item shard is ppermute'd around the ring and each
    phase accumulates the normal-equation terms for entries whose global
    item index falls in the visiting shard — `parallel.als._ring_solve`,
    the training sweep's own math, so fold-in/refit parity stays a theorem
    on the mesh too. ``mode="allgather"``: assemble the padded item table
    transient per batch and run `bucket_solve_body` directly (cheaper in
    collectives, priced higher in transient bytes by `plan_foldin`).

    The health tail is `utils.watchdog.factor_health(solved, solved)`
    decomposed into per-shard partials: nonfinite counts and sum-of-squares
    psum, max-abs pmax, finished into the same `[nonfinite, max_abs, rms]`
    layout — replicated, so the caller's single d2h read of the (3,)
    vector is the completion barrier across EVERY shard.
    """
    if mode == "ring":
        solved_l = _ring_solve(
            vf_l, yty, idx_l, val_l, mask_l, reg, alpha,
            axis=axis, n_shards=n_shards, gather_dtype=None, overlapped=True,
        )
    else:
        vf = jax.lax.all_gather(vf_l, axis, axis=0, tiled=True)
        solved_l = bucket_solve_body(vf, yty, idx_l, val_l, mask_l, reg, alpha)
    finite = jnp.isfinite(solved_l)
    safe = jnp.where(finite, solved_l, 0.0)
    nonfinite = jax.lax.psum(
        (solved_l.size - finite.sum()).astype(jnp.float32), axis
    )
    max_abs = jax.lax.pmax(jnp.max(jnp.abs(safe)), axis)
    sumsq = jax.lax.psum(jnp.sum(safe * safe), axis)
    rms = jnp.sqrt(sumsq / float(solved_l.size * n_shards))
    # factor_health(x, x) counts both "tables", hence the doubled count.
    health = jnp.stack([2.0 * nonfinite, max_abs, rms])
    return solved_l, health


def make_sharded_foldin(mesh: Mesh, axis: str = DATA_AXIS, mode: str = "allgather"):
    """Jitted sharded fold-in program: row-sharded item factors +
    replicated Gramian + batch-sharded user slab in, batch-sharded solved
    rows + replicated health vector out. Slab batch dims must be
    shard-count multiples (`ShardedFoldIn.build_slab` guarantees it)."""
    n_shards = mesh.shape[axis]

    def solve(vf, yty, idx, val, mask, reg, alpha):
        body = functools.partial(
            _foldin_body, axis=axis, n_shards=n_shards, mode=mode
        )
        f = shard_map(
            body, mesh=mesh,
            in_specs=(
                P(axis, None), P(), P(axis, None), P(axis, None),
                P(axis, None), P(), P(),
            ),
            out_specs=(P(axis, None), P()),
        )
        return f(vf, yty, idx, val, mask, reg, alpha)

    return jax.jit(solve)


def _acquire_foldin_executable(engine: "ShardedFoldIn", fn, args, shape_key: tuple):
    """Per-shape executable through the persistent AOT layer, memoized on
    the engine. A module-level conduit (forwards ``fn`` into
    ``persistent_aot_executable``) so graftlint R1 can prove the sharded
    fold-in program reaches the AOT layer — same discipline as
    `parallel.als._acquire_executable`."""
    from albedo_tpu.utils.aot import persistent_aot_executable

    compiled = engine._executables.get(shape_key)
    if compiled is None:
        compiled, compile_s, source = persistent_aot_executable(
            fn, args, None, None,
            key_parts=(
                "stream_foldin_sharded", engine.n_shards,
                engine.rank, engine.padded_items, jax.__version__,
                jax.default_backend(), repr(engine.mesh),
            ) + shape_key,
            name="stream_foldin_sharded",
        )
        engine._executables[shape_key] = compiled
        engine.compile_s += compile_s
        if source != "memory":
            log.info(
                "sharded fold-in shape %s ready on %d shards (%s, %.2fs)",
                shape_key, engine.n_shards, source, compile_s,
            )
    return compiled


class ShardedFoldIn:
    """Holds the frozen item side row-sharded over the mesh and solves
    owner-routed fold-in slabs against it.

    The single-device `FoldInEngine` owns the stream-facing contract
    (admission, watchdog remediation, bank publish); this class is its
    mesh substrate: shard layout, routing geometry, the shard_map'd solve,
    and the deadline guard. ``n_users`` (the user table's row count) fixes
    the routing geometry — the same ``ceil(n/n_shards)`` row blocks
    `pad_rows_to` + `P(axis, None)` give every sharded table, so a folded
    row is solved on the device whose user shard (and whose slice of the
    sharded retrieval bank) will hold it.
    """

    def __init__(
        self,
        mesh: Mesh,
        item_factors,
        *,
        axis: str = DATA_AXIS,
        mode: str = "allgather",
        n_users: int = 0,
    ):
        self.mesh = mesh
        self.axis = axis
        self.mode = str(mode)
        self.n_shards = int(mesh.shape[axis])
        f = np.asarray(item_factors, dtype=np.float32)
        self.rank = int(f.shape[1])
        self.n_items = int(f.shape[0])
        f = pad_rows_to(f, self.n_shards)
        self.padded_items = int(f.shape[0])
        # Row-sharded frozen item side: each device holds 1/n of the padded
        # table; the Gramian is the one-psum sharded reduction, replicated.
        self._vf = jax.device_put(f, NamedSharding(mesh, P(axis, None)))
        self._yty = sharded_gramian(mesh, axis)(self._vf)
        # Both assembly programs up front (building the jit closure traces
        # nothing): the admission ladder picks per batch, so an over-budget
        # all-gather transient degrades to ring without rebuilding the
        # engine or re-uploading the item side.
        self._solve_allgather = make_sharded_foldin(mesh, axis, "allgather")
        self._solve_ring = make_sharded_foldin(mesh, axis, "ring")
        self._executables: dict[tuple, object] = {}
        self.n_users = int(n_users)
        self.compile_s = 0.0
        self.dispatches = 0

    # ------------------------------------------------------------- routing

    def owners(self, user_idx) -> np.ndarray:
        """Owner shard per touched user under the row-sharded user-table
        layout (``rows_per = ceil(n_users / n_shards)`` blocks). Without a
        known user-table size (or without addresses at all) routing falls
        back to round-robin — the per-row solves are independent, so
        placement changes no value, only locality."""
        u = np.asarray(user_idx, dtype=np.int64)
        if self.n_users <= 0:
            return u % self.n_shards
        rows_per = -(-self.n_users // self.n_shards)
        return np.minimum(u // rows_per, self.n_shards - 1)

    def build_slab(self, chunk, owners=None):
        """Owner-routed padded slab for one chunk of ``(item_idx,
        confidence)`` rows: user j of owner shard d lands in slice d's rows
        so shard_map's ``P(axis)`` split hands it to its owning device.
        Returns ``(idx, val, mask, pos)`` where ``pos[j]`` is row j's slab
        slot (un-permute the solved block with ``solved[pos]``)."""
        n = self.n_shards
        if owners is None:
            owners = np.arange(len(chunk), dtype=np.int64) % n
        counts = np.bincount(owners, minlength=n)
        b_per = _pow2(max(1, int(counts.max())))
        bucket = n * b_per
        length = _pow2(max(int(ri.size) for ri, _ in chunk))
        idx = np.zeros((bucket, length), dtype=np.int32)
        val = np.zeros((bucket, length), dtype=np.float32)
        mask = np.zeros((bucket, length), dtype=bool)
        pos = np.empty(len(chunk), dtype=np.int64)
        cursor = np.zeros(n, dtype=np.int64)
        for j, (ri, rv) in enumerate(chunk):
            d = int(owners[j])
            r = d * b_per + int(cursor[d])
            cursor[d] += 1
            pos[j] = r
            k = int(ri.size)
            idx[r, :k] = ri
            val[r, :k] = rv
            mask[r, :k] = True
        return idx, val, mask, pos

    # --------------------------------------------------------------- solve

    def warm(self, bucket: int, length: int, mode: str | None = None) -> None:
        args = (
            self._vf, self._yty,
            np.zeros((bucket, length), dtype=np.int32),
            np.zeros((bucket, length), dtype=np.float32),
            np.zeros((bucket, length), dtype=bool),
            jnp.float32(0.1), jnp.float32(1.0),
        )
        mode = self.mode if mode is None else str(mode)
        if mode == "ring":
            _acquire_foldin_executable(
                self, self._solve_ring, args, ("ring", bucket, length)
            )
        else:
            _acquire_foldin_executable(
                self, self._solve_allgather, args, ("allgather", bucket, length)
            )

    def solve(self, idx, val, mask, reg: float, alpha: float,
              mode: str | None = None):
        """Dispatch one padded slab; returns ``(solved, health)`` as host
        arrays. The replicated health vector's d2h read is the completion
        barrier across every shard, and the whole dispatch runs under the
        collective deadline so a dead shard raises loss-shaped instead of
        hanging the stream."""
        from albedo_tpu.parallel.elastic import (
            collective_deadline_s,
            run_with_deadline,
        )

        FOLDIN_COLLECTIVE_FAULT.hit()
        mode = self.mode if mode is None else str(mode)
        bucket, length = int(idx.shape[0]), int(idx.shape[1])
        args = (
            self._vf, self._yty, idx, val, mask,
            jnp.float32(reg), jnp.float32(alpha),
        )
        if mode == "ring":
            compiled = _acquire_foldin_executable(
                self, self._solve_ring, args, ("ring", bucket, length)
            )
        else:
            compiled = _acquire_foldin_executable(
                self, self._solve_allgather, args, ("allgather", bucket, length)
            )

        def dispatch():
            solved_dev, health_dev = compiled(*args)
            # Reading the replicated (3,) health synchronizes every shard;
            # the solved block copy rides the same barrier.
            health = np.asarray(health_dev, dtype=np.float32)
            return np.asarray(solved_dev, dtype=np.float32), health

        solved, health = run_with_deadline(
            dispatch, collective_deadline_s(),
            f"sharded fold-in batch {bucket}x{length} "
            f"({mode}, {self.n_shards} shards)",
        )
        self.dispatches += 1
        return solved, health
