"""Profile builder (L4 ETL) tests.

Parity anchors: ``UserProfileBuilder.scala`` / ``RepoProfileBuilder.scala``
column lists (the printed bucket comments at :204-210 / :158-163).
"""

import numpy as np
import pandas as pd
import pytest

from albedo_tpu.builders import build_repo_profile, build_user_profile
from albedo_tpu.datasets import synthetic_tables

NOW = 1.52e9  # just after the synthetic crawl horizon


@pytest.fixture(scope="module")
def tables():
    return synthetic_tables(n_users=250, n_items=200, mean_stars=15, seed=23)


@pytest.fixture(scope="module")
def user_profile(tables):
    return build_user_profile(tables, now=NOW)


@pytest.fixture(scope="module")
def repo_profile(tables):
    return build_repo_profile(tables, now=NOW, min_stars=1, max_stars=10**9)


def test_user_profile_columns(user_profile):
    profile, cols = user_profile
    # Bucket parity with UserProfileBuilder.scala:204-210.
    assert len(cols.boolean) == 14
    assert len(cols.continuous) == 9
    assert cols.categorical == ["user_account_type", "user_binned_company", "user_binned_location"]
    assert cols.list_ == ["user_recent_repo_languages", "user_recent_repo_topics"]
    assert cols.text == ["user_clean_bio", "user_recent_repo_descriptions"]
    assert set(cols.all()) <= set(profile.columns)
    assert profile["user_id"].is_unique


def test_user_profile_keyword_flags(tables, user_profile):
    profile, _ = user_profile
    merged = profile.merge(tables.user_info[["user_id", "user_bio"]], on="user_id")
    knows_data = merged["user_bio"].str.lower().str.contains("machine learning|deep learning", regex=True)
    assert (merged["user_knows_data"] == (knows_data | merged["user_bio"].str.lower().str.contains("data scien"))).all()


def test_user_profile_recent_lists(tables, user_profile):
    profile, _ = user_profile
    row = profile.iloc[0]
    assert isinstance(row["user_recent_repo_languages"], list)
    assert len(row["user_recent_repo_languages"]) <= 50
    assert all(lang == lang.lower() for lang in row["user_recent_repo_languages"])
    # starred count matches the starring table
    uid = row["user_id"]
    assert row["user_starred_repos_count"] == (tables.starring["user_id"] == uid).sum()


def test_user_profile_ratio_and_days(tables, user_profile):
    profile, _ = user_profile
    merged = profile.merge(
        tables.user_info[["user_id", "user_followers_count", "user_following_count", "user_created_at"]],
        on="user_id",
        suffixes=("", "_raw"),
    )
    expect = np.round(
        merged["user_followers_count_raw"] / (merged["user_following_count_raw"] + 1.0), 3
    )
    np.testing.assert_allclose(merged["user_followers_following_ratio"], expect)
    assert (merged["user_days_between_created_at_today"] >= 0).all()


def test_repo_profile_columns(repo_profile):
    profile, cols = repo_profile
    assert len(cols.boolean) == 9
    assert len(cols.continuous) == 11
    assert cols.categorical == ["repo_owner_type", "repo_language", "repo_binned_language"]
    assert cols.list_ == ["repo_clean_topics"]
    assert cols.text == ["repo_text"]
    assert set(cols.all()) <= set(profile.columns)


def test_repo_profile_filters(tables):
    profile, _ = build_repo_profile(tables, now=NOW, min_stars=1, max_stars=10**9)
    raw = tables.repo_info.set_index("repo_id")
    kept = raw.loc[profile["repo_id"]]
    assert (~kept["repo_is_fork"]).all()
    # description-filtered repos are gone
    assert not profile["repo_id"].isin(
        raw[raw["repo_description"].str.contains("assignment")].index
    ).any()


def test_repo_profile_star_range_filter(tables):
    profile, _ = build_repo_profile(tables, now=NOW, min_stars=100, max_stars=5000)
    raw = tables.repo_info.set_index("repo_id")
    stars = raw.loc[profile["repo_id"], "repo_stargazers_count"]
    assert stars.between(100, 5000).all()


def test_repo_profile_topics_list_and_ratios(tables, repo_profile):
    profile, _ = repo_profile
    row = profile.iloc[0]
    assert isinstance(row["repo_clean_topics"], list)
    raw = tables.repo_info.set_index("repo_id").loc[row["repo_id"]]
    expect = round(raw["repo_forks_count"] / (raw["repo_stargazers_count"] + 1.0), 3)
    assert row["repo_forks_stargazers_ratio"] == pytest.approx(expect)
    assert row["repo_text"] == row["repo_text"].lower()


def test_repo_profile_canary_flag(tables):
    canary = int(tables.starring["user_id"].iloc[0])
    profile, _ = build_repo_profile(
        tables, now=NOW, min_stars=1, max_stars=10**9, canary_user_id=canary
    )
    starred = set(tables.starring[tables.starring["user_id"] == canary]["repo_id"])
    flagged = set(profile[profile["repo_is_vinta_starred"]]["repo_id"])
    assert flagged == starred & set(profile["repo_id"])
