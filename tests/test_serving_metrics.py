"""Metrics plane: Prometheus text exposition and the shared Timer path."""

import pytest

jax = pytest.importorskip("jax")

from albedo_tpu.serving.metrics import (  # noqa: E402
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from albedo_tpu.utils.profiling import Timer  # noqa: E402


def test_counter_labels_and_render():
    c = Counter("t_total", "help text", ("route", "status"))
    c.inc(route="recommend", status="200")
    c.inc(route="recommend", status="200")
    c.inc(route="admin", status="404")
    assert c.value(route="recommend", status="200") == 2
    lines = list(c.render())
    assert 't_total{route="recommend",status="200"} 2' in lines
    assert 't_total{route="admin",status="404"} 1' in lines


def test_unlabelled_counter_renders_zero_sample():
    c = Counter("z_total", "h")
    assert list(c.render()) == ["z_total 0"]


def test_gauge_set():
    g = Gauge("g", "h", ("stage",))
    g.set(1.5, stage="rank")
    g.set(2.5, stage="rank")  # overwrite, not accumulate
    assert list(g.render()) == ['g{stage="rank"} 2.5']


def test_label_escaping():
    c = Counter("e_total", "h", ("reason",))
    c.inc(reason='quo"te\\slash')
    (line,) = c.render()
    assert line == 'e_total{reason="quo\\"te\\\\slash"} 1'


def test_histogram_cumulative_buckets():
    h = Histogram("lat_seconds", "h", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    lines = list(h.render())
    assert 'lat_seconds_bucket{le="0.1"} 1' in lines
    assert 'lat_seconds_bucket{le="1"} 3' in lines
    assert 'lat_seconds_bucket{le="10"} 4' in lines
    assert 'lat_seconds_bucket{le="+Inf"} 5' in lines
    assert "lat_seconds_count 5" in lines
    (sum_line,) = [line for line in lines if line.startswith("lat_seconds_sum ")]
    assert float(sum_line.split()[1]) == pytest.approx(56.05)


def test_histogram_percentile_estimate():
    h = Histogram("p", "h", buckets=(1.0, 2.0, 4.0))
    for v in [0.5] * 50 + [1.5] * 45 + [3.0] * 5:
        h.observe(v)
    assert h.percentile(0.5) == 1.0   # bucket upper bound
    assert h.percentile(0.99) == 4.0
    assert Histogram("q", "h").percentile(0.99) == 0.0  # empty


def test_registry_render_format():
    reg = MetricsRegistry()
    reg.requests.inc(route="recommend", status="200")
    reg.request_latency.observe(0.003)
    reg.degraded.inc(reason="ranker_timeout")
    text = reg.render()
    assert text.endswith("\n")
    assert "# HELP albedo_requests_total" in text
    assert "# TYPE albedo_requests_total counter" in text
    assert "# TYPE albedo_request_latency_seconds histogram" in text
    assert 'albedo_requests_total{route="recommend",status="200"} 1' in text
    assert 'albedo_degraded_total{reason="ranker_timeout"} 1' in text
    # Pre-registered zero-traffic metrics still expose samples.
    assert "albedo_shed_total 0" in text


def test_timer_snapshot_is_report_shaped():
    """Timer.snapshot() is the one exchange format: totals identical to what
    report() prints/returns, counts alongside."""
    t = Timer()
    with t.section("a"):
        pass
    with t.section("a"):
        pass
    with t.section("b"):
        pass
    snap = t.snapshot()
    assert snap["counts"] == {"a": 2, "b": 1}
    assert snap["totals"] == t.report(printer=lambda s: None)
    # Snapshot is a copy, not a live view.
    snap["totals"]["a"] = -1
    assert t.totals["a"] >= 0


def test_observe_timer_exports_stage_gauges():
    reg = MetricsRegistry()
    t = Timer()
    with t.section("stage1_candidates"):
        pass
    reg.observe_timer(t)
    text = reg.render()
    assert 'albedo_stage_seconds{stage="stage1_candidates"}' in text
    assert 'albedo_stage_calls{stage="stage1_candidates"} 1' in text


def test_cache_hit_rate():
    reg = MetricsRegistry()
    assert reg.cache_hit_rate() == 0.0
    reg.cache_hits.inc(3)
    reg.cache_misses.inc()
    assert reg.cache_hit_rate() == 0.75
