"""The out-of-core scale harness (``datasets.synthetic.generate_scale_dataset``):
deterministic bucket-by-bucket generation, user/item side consistency, and a
disk-streamed sharded fit matching the in-memory resident fit. Giant shapes
are env-gated and marked slow — CI exercises the identical code path at toy
sizes."""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from albedo_tpu.datasets.synthetic import (  # noqa: E402
    ScaleDataset,
    generate_scale_dataset,
)
from albedo_tpu.models.als import ImplicitALS  # noqa: E402
from albedo_tpu.parallel import make_mesh  # noqa: E402
from albedo_tpu.parallel.als import ShardedALSFit  # noqa: E402

GEN_KW = dict(
    n_users=200, n_items=96, mean_stars=6, seed=5,
    chunk_users=64, n_partitions=3, batch_size=32,
)


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    root = tmp_path_factory.mktemp("scale-ds")
    return generate_scale_dataset(root, **GEN_KW)


class TestGeneration:
    def test_deterministic_per_seed(self, dataset, tmp_path):
        again = generate_scale_dataset(tmp_path / "again", **GEN_KW)
        a, b = dataset.to_star_matrix(), again.to_star_matrix()
        assert np.array_equal(a.rows, b.rows)
        assert np.array_equal(a.cols, b.cols)
        other = generate_scale_dataset(
            tmp_path / "other", **dict(GEN_KW, seed=6)
        )
        assert other.nnz != dataset.nnz or not np.array_equal(
            other.to_star_matrix().cols, a.cols
        )

    def test_sides_are_consistent(self, dataset):
        # Every interaction appears exactly once on EACH side's buckets.
        m = dataset.to_star_matrix()
        user_nnz = sum(int(b.mask.sum()) for b in dataset.iter_buckets("user"))
        item_nnz = sum(int(b.mask.sum()) for b in dataset.iter_buckets("item"))
        assert user_nnz == item_nnz == dataset.nnz == m.nnz
        # The item side's (row=item, idx=user) entries transpose back to the
        # exact same pair set the user side packed.
        pairs_u, pairs_i = set(), set()
        for b in dataset.iter_buckets("user"):
            for rid, row_idx, row_mask in zip(b.row_ids, b.idx, b.mask):
                if rid >= 0:
                    pairs_u.update((int(rid), int(c)) for c in row_idx[row_mask])
        for b in dataset.iter_buckets("item"):
            for rid, row_idx, row_mask in zip(b.row_ids, b.idx, b.mask):
                if rid >= 0:
                    pairs_i.update((int(u), int(rid)) for u in row_idx[row_mask])
        assert pairs_u == pairs_i

    def test_row_ids_are_global_and_in_range(self, dataset):
        seen_users = set()
        for b in dataset.iter_buckets("user"):
            rid = b.row_ids[b.row_ids >= 0]
            assert rid.max() < dataset.n_users
            assert not (set(rid.tolist()) & seen_users), "user split across chunks"
            seen_users.update(rid.tolist())
        for b in dataset.iter_buckets("item"):
            rid = b.row_ids[b.row_ids >= 0]
            assert rid.max() < dataset.n_items

    def test_power_law_popularity(self, dataset):
        counts = np.sort(dataset.to_star_matrix().item_counts())[::-1]
        top = counts[: max(1, len(counts) // 10)].sum()
        assert top > 0.2 * counts.sum()  # head-heavy, as GitHub stars are

    def test_meta_shapes_match_stored_buckets(self, dataset):
        for side in ("user", "item"):
            stored = {b.shape for b in dataset.iter_buckets(side)}
            assert stored == set(dataset.bucket_shapes(side))

    def test_reopen_from_disk(self, dataset):
        reopened = ScaleDataset(dataset.root)
        assert reopened.nnz == dataset.nnz
        assert sum(1 for _ in reopened.iter_buckets("user")) == sum(
            1 for _ in dataset.iter_buckets("user")
        )

    def test_coalesce_preserves_every_entry_and_cuts_buckets(self, dataset):
        """The per-tier coalescer (the provider's default under the
        pipeline switch) merges chunk-fragmented partial buckets: every
        (row, col, val) entry survives exactly once at its original pad
        width, each row appears in exactly one bucket, and the bucket
        count drops on multi-chunk sides."""

        def entries(buckets):
            out = {}
            for b in buckets:
                for rid, row_idx, row_mask, row_val in zip(
                    b.row_ids, b.idx, b.mask, b.val
                ):
                    if rid >= 0:
                        assert int(rid) not in out, "row split across buckets"
                        out[int(rid)] = {
                            (int(c), float(v))
                            for c, m, v in zip(row_idx, row_mask, row_val) if m
                        }
            return out

        for side in ("user", "item"):
            raw = list(dataset.iter_buckets(side, readahead=False))
            coal = list(
                dataset.iter_buckets(side, readahead=False, coalesce=True)
            )
            assert entries(raw) == entries(coal)
            assert len(coal) <= len(raw)
        # The user side is chunk-fragmented (chunk_users < n_users), so
        # coalescing must actually merge there.
        assert len(list(dataset.iter_buckets("user", coalesce=True))) < len(
            list(dataset.iter_buckets("user"))
        )

    def test_readahead_streams_identical_buckets(self, dataset):
        """The pipelined reader (next file parsed on a background thread)
        yields byte-identical buckets in the identical order as the
        synchronous walk — read-ahead is a latency tool, never a layout
        change."""
        for side in ("user", "item"):
            sync = list(dataset.iter_buckets(side, readahead=False))
            ahead = list(dataset.iter_buckets(side, readahead=True))
            assert len(sync) == len(ahead)
            for a, b in zip(sync, ahead):
                assert np.array_equal(a.row_ids, b.row_ids)
                assert np.array_equal(a.idx, b.idx)
                assert np.array_equal(a.val, b.val)
                assert np.array_equal(a.mask, b.mask)


class TestDiskStreamedFit:
    def test_matches_in_memory_resident_fit(self, dataset):
        m = dataset.to_star_matrix()
        ref = ImplicitALS(
            rank=8, max_iter=2, batch_size=32, seed=1, chunked=False
        ).fit(m)
        key = jax.random.PRNGKey(1)
        uk, ik = jax.random.split(key)
        scale = 1.0 / np.sqrt(8)
        uf = np.asarray(jax.random.normal(uk, (m.n_users, 8))) * scale
        vf = np.asarray(jax.random.normal(ik, (m.n_items, 8))) * scale
        engine = ShardedALSFit(make_mesh(8))
        u2, v2, stats = engine.fit(
            uf.astype(np.float32), vf.astype(np.float32),
            dataset.provider("user"), dataset.provider("item"),
            0.5, 40.0, 2, streamed=True,
        )
        np.testing.assert_allclose(
            np.asarray(u2), ref.user_factors, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(v2), ref.item_factors, atol=1e-5
        )
        assert stats["streamed_buckets"] > 0


@pytest.mark.slow
def test_scale_dataset_large_env_gated(tmp_path):
    """Giant-shape smoke, env-gated so the weak-scaling record's data path
    is testable at real sizes without burdening CI: e.g.
    ``ALBEDO_SCALE_TEST_USERS=1000000 ALBEDO_SCALE_TEST_ITEMS=100000``."""
    n_users = int(os.environ.get("ALBEDO_SCALE_TEST_USERS", "50000"))
    n_items = int(os.environ.get("ALBEDO_SCALE_TEST_ITEMS", "5000"))
    ds = generate_scale_dataset(
        tmp_path / "big", n_users=n_users, n_items=n_items,
        mean_stars=float(os.environ.get("ALBEDO_SCALE_TEST_MEAN_STARS", "12")),
        chunk_users=8192, seed=7,
    )
    assert ds.nnz > n_users  # every user stars at least once
    user_nnz = sum(int(b.mask.sum()) for b in ds.iter_buckets("user"))
    item_nnz = sum(int(b.mask.sum()) for b in ds.iter_buckets("item"))
    assert user_nnz == item_nnz == ds.nnz
