"""Circuit breakers: state machine unit tests, pipeline integration (skip
instead of deadline-wait), and fault-driven trip/half-open recovery over HTTP."""

import json
import time
import urllib.request

import pytest

jax = pytest.importorskip("jax")

from albedo_tpu.datasets import synthetic_tables  # noqa: E402
from albedo_tpu.datasets.tables import popular_repos  # noqa: E402
from albedo_tpu.models.als import ImplicitALS  # noqa: E402
from albedo_tpu.recommenders import PopularityRecommender  # noqa: E402
from albedo_tpu.serving import (  # noqa: E402
    BreakerConfig,
    CircuitBreaker,
    RecommendationService,
    serve,
)
from albedo_tpu.utils import faults  # noqa: E402
from albedo_tpu.utils.retry import RetryPolicy  # noqa: E402


class FakeClock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now


def _config(threshold=2, base=10.0):
    return BreakerConfig(
        failure_threshold=threshold,
        reopen=RetryPolicy(base_s=base, multiplier=2.0, max_delay_s=60.0, jitter=False),
    )


# --- unit: the state machine -------------------------------------------------


def test_breaker_trips_after_consecutive_failures():
    clock = FakeClock()
    br = CircuitBreaker("src", _config(threshold=3), clock=clock)
    assert br.state == "closed"
    br.record_failure()
    br.record_failure()
    assert br.state == "closed" and br.allow()
    # A success in between resets the consecutive count.
    br.record_success()
    br.record_failure()
    br.record_failure()
    assert br.state == "closed"
    br.record_failure()  # third consecutive: trip
    assert br.state == "open"
    assert not br.allow()
    assert br.snapshot()["reopen_in_s"] == pytest.approx(10.0)


def test_breaker_half_open_single_trial_then_close():
    clock = FakeClock()
    br = CircuitBreaker("src", _config(threshold=1), clock=clock)
    br.record_failure()
    assert br.state == "open" and not br.allow()
    clock.now += 10.0  # reopen timer expires
    assert br.allow()  # the ONE half-open trial
    assert br.state == "half_open"
    assert not br.allow()  # concurrent callers are still denied
    br.record_success()
    assert br.state == "closed" and br.allow()


def test_breaker_failed_trial_reopens_with_backoff():
    clock = FakeClock()
    br = CircuitBreaker("src", _config(threshold=1, base=10.0), clock=clock)
    br.record_failure()              # trip 1: reopen after 10s
    clock.now += 10.0
    assert br.allow()
    br.record_failure()              # failed trial -> trip 2: 20s
    assert br.state == "open"
    assert br.snapshot()["reopen_in_s"] == pytest.approx(20.0)
    clock.now += 19.0
    assert not br.allow()
    clock.now += 1.0
    assert br.allow()
    br.record_success()              # recovered: schedule resets
    br.record_failure()
    assert br.snapshot()["reopen_in_s"] == pytest.approx(10.0)


def test_breaker_ignores_late_zombie_results_while_open():
    """A timed-out call finishing in its zombie thread after the trip must
    not flip the breaker state."""
    clock = FakeClock()
    br = CircuitBreaker("src", _config(threshold=1), clock=clock)
    br.record_failure()
    assert br.state == "open"
    br.record_success()   # zombie success
    assert br.state == "open"
    br.record_failure()   # zombie failure: no double-trip either
    assert br.snapshot()["total_trips"] == 1


def test_abandon_trial_releases_the_half_open_slot():
    """An aborted call (hot-swap retirement mid-request) records no outcome;
    abandoning must free the trial slot or every later caller is denied."""
    clock = FakeClock()
    br = CircuitBreaker("src", _config(threshold=1), clock=clock)
    br.record_failure()
    clock.now += 10.0
    assert br.allow()          # trial admitted...
    br.abandon_trial()         # ...but the call was abandoned, not judged
    assert br.state == "half_open"
    assert br.allow()          # next caller gets the trial instead
    br.record_success()
    assert br.state == "closed"


def test_breaker_transition_callback_and_config_validation():
    seen = []
    br = CircuitBreaker(
        "src", _config(threshold=1),
        clock=FakeClock(), on_transition=lambda n, s: seen.append((n, s)),
    )
    br.record_failure()
    assert seen == [("src", "open")]
    with pytest.raises(ValueError):
        BreakerConfig(failure_threshold=0)


def test_breaker_equal_jitter_reopen_bounds():
    cfg = BreakerConfig(
        failure_threshold=1,
        reopen=RetryPolicy(base_s=8.0, multiplier=2.0, max_delay_s=60.0, jitter=True),
    )
    import random

    rng = random.Random(7)
    delays = [cfg.reopen_delay(1, rng) for _ in range(200)]
    assert all(4.0 <= d <= 8.0 for d in delays)  # equal jitter: [cap/2, cap]
    assert min(delays) < 5.0 < max(delays)       # actually jittered
    caps = [cfg.reopen_delay(t, rng) for t in range(1, 12)]
    assert max(caps) <= 60.0                     # schedule honors the cap


# --- integration: pipeline + service -----------------------------------------


@pytest.fixture(scope="module")
def artifacts():
    tables = synthetic_tables(n_users=80, n_items=50, mean_stars=6, seed=11)
    matrix = tables.star_matrix()
    model = ImplicitALS(rank=8, max_iter=2, seed=0).fit(matrix)
    pop = PopularityRecommender(popular_repos(tables.repo_info, 1, 10**9), top_k=20)
    return tables, matrix, model, pop


def _service(artifacts, **kw):
    tables, matrix, model, pop = artifacts
    kw.setdefault("batch_window_ms", 0.0)
    kw.setdefault("breaker_config", _config(threshold=2))
    return RecommendationService(
        model, matrix, repo_info=tables.repo_info,
        recommenders={"popularity": pop}, **kw,
    )


def test_open_breaker_skips_source_instead_of_calling(artifacts):
    _, matrix, _, _ = artifacts
    with _service(artifacts) as svc:
        uid = int(matrix.user_ids[0])
        faults.arm("serving.source.popularity", kind="error", at=1, times=2)
        for i in range(2):
            status, body = svc.handle_recommend(uid, k=5)
            assert status == 200
            assert "candidate_error_popularity" in body["degraded"]
        br = svc.pipeline.breakers["popularity"]
        assert br.state == "open"

        hits_before = faults.FAULTS.hits("serving.source.popularity")
        status, body = svc.handle_recommend(uid, k=5)
        assert status == 200
        assert "breaker_open_popularity" in body["degraded"]
        assert body["items"]  # ALS still answers
        # The source was NOT called: no new hits on its fault site.
        assert faults.FAULTS.hits("serving.source.popularity") == hits_before
        assert svc.metrics.degraded.value(reason="breaker_open_popularity") == 1
        assert svc.metrics.breaker_state.value(source="popularity") == 2


def test_half_open_trial_recovers_the_source(artifacts):
    _, matrix, _, _ = artifacts
    with _service(artifacts) as svc:
        uid = int(matrix.user_ids[1])
        faults.arm("serving.source.popularity", kind="error", at=1, times=2)
        for _ in range(2):
            svc.handle_recommend(uid, k=5)
        br = svc.pipeline.breakers["popularity"]
        assert br.state == "open"

        # Force the reopen timer to expire (deterministic, no sleeping).
        with br._lock:
            br._reopen_at = 0.0
        status, body = svc.handle_recommend(uid, k=5)
        assert status == 200
        # The fault is exhausted (times=2), so the trial call succeeds and
        # the breaker closes; popularity is back in the fusion.
        assert "breaker_open_popularity" not in body["degraded"]
        assert br.state == "closed"
        assert svc.metrics.breaker_transitions.value(source="popularity", to="closed") == 1


def test_failed_trial_reopens(artifacts):
    _, matrix, _, _ = artifacts
    with _service(artifacts) as svc:
        uid = int(matrix.user_ids[2])
        faults.arm("serving.source.popularity", kind="error", at=1, times=0)  # forever
        for _ in range(2):
            svc.handle_recommend(uid, k=5)
        br = svc.pipeline.breakers["popularity"]
        assert br.state == "open"
        with br._lock:
            br._reopen_at = 0.0
        svc.handle_recommend(uid, k=5)  # trial fails (fault still armed)
        assert br.state == "open"
        assert br.snapshot()["total_trips"] == 2


def test_breakers_disabled_keeps_prior_behavior(artifacts):
    _, matrix, _, _ = artifacts
    with _service(artifacts, breakers_enabled=False, breaker_config=None) as svc:
        uid = int(matrix.user_ids[3])
        faults.arm("serving.source.popularity", kind="error", at=1, times=0)
        for _ in range(4):
            status, body = svc.handle_recommend(uid, k=5)
            assert status == 200
            assert "candidate_error_popularity" in body["degraded"]
        assert svc.pipeline.breakers == {}


def test_readiness_reports_breaker_states(artifacts):
    _, matrix, _, _ = artifacts
    with _service(artifacts) as svc:
        uid = int(matrix.user_ids[4])
        svc.handle_recommend(uid, k=5)
        ready, report = svc.readiness()
        assert ready
        assert report["breakers"]["popularity"]["state"] == "closed"
        assert report["breakers"]["als"]["state"] == "closed"


# --- chaos drill over HTTP ---------------------------------------------------


def _get_json(handle, path):
    host, port = handle.server_address[:2]
    with urllib.request.urlopen(f"http://{host}:{port}{path}", timeout=30) as r:
        return json.loads(r.read().decode())


@pytest.mark.chaos
def test_breaker_trip_and_recovery_drill_over_http(artifacts):
    """Acceptance: the serving.breaker.<source> site trips the ALS breaker
    through real HTTP; requests degrade to the surviving sources; the
    half-open trial recovers it; every phase is visible on /metrics."""
    _, matrix, _, _ = artifacts
    with _service(artifacts) as svc:
        with serve(svc, port=0) as handle:
            uid = int(matrix.user_ids[5])
            # Trip the ALS stage at the breaker boundary: 2 failures.
            faults.arm("serving.breaker.als", kind="error", at=1, times=2)
            for _ in range(2):
                body = _get_json(handle, f"/recommend/{uid}?k=5")
                assert "candidate_error_als" in body["degraded"]
                assert body["items"]  # popularity still answers

            body = _get_json(handle, f"/recommend/{uid}?k=5")
            assert "breaker_open_als" in body["degraded"]
            assert all(i["source"] == "popularity" for i in body["items"])

            host, port = handle.server_address[:2]
            with urllib.request.urlopen(f"http://{host}:{port}/metrics", timeout=30) as r:
                text = r.read().decode()
            assert 'albedo_breaker_state{source="als"} 2' in text
            assert 'albedo_breaker_transitions_total{source="als",to="open"} 1' in text
            assert 'albedo_faults_fired_total{site="serving.breaker.als"} 2' in text

            # Recovery: expire the reopen timer; the half-open trial runs
            # against the now-healthy source and closes the breaker.
            br = svc.pipeline.breakers["als"]
            with br._lock:
                br._reopen_at = 0.0
            body = _get_json(handle, f"/recommend/{uid}?k=5")
            assert "breaker_open_als" not in body["degraded"]
            assert any(i["source"] == "als" for i in body["items"])
            assert br.state == "closed"
            ready, report = svc.readiness()
            assert report["breakers"]["als"]["state"] == "closed"
            with urllib.request.urlopen(f"http://{host}:{port}/metrics", timeout=30) as r:
                text = r.read().decode()
            assert 'albedo_breaker_state{source="als"} 0' in text
