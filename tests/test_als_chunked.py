"""The chunked host-streamed ALS fallback: numerics parity with the
device-resident path (both solvers), the admission wiring in ``fit``, the
als.chunked chaos site, and the over-budget-fit-completes acceptance bar."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from albedo_tpu.datasets.synthetic import synthetic_stars  # noqa: E402
from albedo_tpu.models.als import ImplicitALS  # noqa: E402
from albedo_tpu.utils import capacity, faults  # noqa: E402

KW = dict(rank=8, max_iter=3, seed=0, batch_size=16)


def _matrix(seed=1):
    return synthetic_stars(n_users=70, n_items=45, mean_stars=6, seed=seed)


class TestParity:
    @pytest.mark.parametrize("solver", ["cholesky", "cg"])
    def test_chunked_matches_resident(self, solver):
        m = _matrix()
        resident = ImplicitALS(**KW, solver=solver, chunked=False).fit(m)
        chunked = ImplicitALS(**KW, solver=solver, chunked=True).fit(m)
        np.testing.assert_allclose(
            chunked.user_factors, resident.user_factors, atol=1e-4
        )
        np.testing.assert_allclose(
            chunked.item_factors, resident.item_factors, atol=1e-4
        )

    def test_chunked_matches_resident_bf16_gathers(self):
        m = _matrix()
        kw = dict(KW, gather_dtype="bfloat16")
        resident = ImplicitALS(**kw, chunked=False).fit(m)
        chunked = ImplicitALS(**kw, chunked=True).fit(m)
        np.testing.assert_allclose(
            chunked.user_factors, resident.user_factors, atol=1e-2
        )

    def test_chunked_warm_start_matches(self):
        m = _matrix()
        init = (
            np.full((m.n_users, 8), 0.1, np.float32),
            np.full((m.n_items, 8), 0.1, np.float32),
        )
        resident = ImplicitALS(**KW, init_factors=init, chunked=False).fit(m)
        chunked = ImplicitALS(**KW, init_factors=init, chunked=True).fit(m)
        np.testing.assert_allclose(
            chunked.user_factors, resident.user_factors, atol=1e-4
        )

    def test_chunked_callback_sees_every_iteration(self):
        m = _matrix()
        seen = []
        ImplicitALS(**KW, chunked=True).fit(
            m, callback=lambda it, uf, vf: seen.append((it, uf.shape))
        )
        assert [it for it, _ in seen] == [0, 1, 2]
        assert all(shape == (m.n_users, 8) for _, shape in seen)


class TestAdmissionWiring:
    def test_over_budget_fit_completes_via_degrade(self, monkeypatch):
        """The acceptance bar: a fit whose resident plan busts the budget
        must complete through the chunked path — and match the resident
        result trained under a roomy budget."""
        monkeypatch.setenv("ALBEDO_DEVICE_MEM_BYTES", "4g")
        m = _matrix(seed=2)
        resident = ImplicitALS(**KW).fit(m)

        est = ImplicitALS(**KW)
        plan = est.capacity_plan(m)
        chunked_plan = est.capacity_plan(m, chunked=True)
        mid = (plan.required_bytes + chunked_plan.required_bytes) // 2
        monkeypatch.setenv(
            "ALBEDO_DEVICE_MEM_BYTES", str(int(mid / capacity.headroom()))
        )
        m2 = _matrix(seed=2)  # fresh object: cold layout cache
        model = est.fit(m2)
        assert est.last_fit_report["mode"] == "chunked"
        assert est.last_fit_report["capacity"]["verdict"] == "degrade"
        np.testing.assert_allclose(
            model.user_factors, resident.user_factors, atol=1e-4
        )

    def test_warm_groups_cache_stays_resident(self, monkeypatch):
        """Already-uploaded slabs ARE device-resident — re-admitting them
        after the fact would be theater. A warm cache skips admission."""
        monkeypatch.setenv("ALBEDO_DEVICE_MEM_BYTES", "4g")
        m = _matrix(seed=3)
        est = ImplicitALS(**KW)
        est.fit(m)  # warms the per-matrix device-groups cache
        monkeypatch.setenv("ALBEDO_DEVICE_MEM_BYTES", "1000")
        est2 = ImplicitALS(**KW)
        est2.fit(m)
        assert est2.last_fit_report["mode"] == "resident"

    def test_chunked_site_hits_per_half_sweep(self):
        m = _matrix(seed=4)
        before = faults.FAULTS.hits("als.chunked")
        ImplicitALS(**KW, chunked=True).fit(m)
        # Two half-sweeps per iteration, three iterations.
        assert faults.FAULTS.hits("als.chunked") - before == 2 * KW["max_iter"]

    def test_chunked_fault_error_fails_the_fit(self):
        m = _matrix(seed=5)
        faults.arm("als.chunked", kind="error", at=2)
        try:
            with pytest.raises(faults.FaultInjected):
                ImplicitALS(**KW, chunked=True).fit(m)
        finally:
            faults.disarm("als.chunked")

    def test_chunked_report_shape(self):
        m = _matrix(seed=6)
        est = ImplicitALS(**KW, chunked=True)
        est.fit(m)
        report = est.last_fit_report
        assert report["mode"] == "chunked"
        assert report["chunked_shapes"] >= 1
        assert report["health"]["nonfinite"] == 0
        assert report["device_s"] >= 0

    def test_mesh_path_never_reroutes_to_single_device_chunked(self, monkeypatch):
        """Mesh fits run their OWN admission ladder (replicated -> sharded
        -> sharded+streamed, `tests/test_sharded_als.py`) — never the
        single-device chunked reroute. A budget too small for even the
        replicated mesh layout lands on a SHARDED rung, not on
        `mode: chunked`."""
        from albedo_tpu.parallel.mesh import make_mesh

        m = _matrix(seed=7)
        mesh = make_mesh(2)
        est = ImplicitALS(rank=8, max_iter=1, seed=0, batch_size=16, mesh=mesh)
        streamed_bytes = capacity.plan_fit_sharded(
            *est._plan_shapes(m), m.n_users, m.n_items, est.rank, 2,
            streamed=True,
        ).required_bytes
        monkeypatch.setenv("ALBEDO_MEM_HEADROOM", "1.0")
        monkeypatch.setenv("ALBEDO_DEVICE_MEM_BYTES", str(streamed_bytes + 64))
        model = est.fit(m)
        assert np.isfinite(model.user_factors).all()
        assert est.last_fit_report["mode"] in ("sharded", "sharded_streamed")
