"""Overload resilience (PR 20): AIMD admission, CoDel shed, the brownout
ladder's hysteresis, and the over-HTTP forced-overload drill.

The unit half drives the state machines with a fake clock — hysteresis,
monotone degrade, recovery-window reversal, and per-tier shed accounting
are all asserted deterministically. The HTTP half floods a real served
engine with a hair-trigger overload config and asserts the PR-20 contract:
no overload path ever returns a 5xx, every shed carries its tier tag, and
the ladder fully recovers once the flood stops.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from albedo_tpu.datasets import synthetic_tables  # noqa: E402
from albedo_tpu.models.als import ImplicitALS  # noqa: E402
from albedo_tpu.serving import RecommendationService, serve  # noqa: E402
from albedo_tpu.serving.batcher import QueueOverflow  # noqa: E402
from albedo_tpu.serving.metrics import MetricsRegistry  # noqa: E402
from albedo_tpu.serving.overload import (  # noqa: E402
    LEVEL_FULL,
    LEVEL_SHED,
    TIERS,
    AdaptiveLimit,
    BrownoutLadder,
    CoDelShedder,
    OverloadConfig,
    OverloadController,
    tier_name,
)
from albedo_tpu.utils import faults  # noqa: E402


class FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


# --------------------------------------------------------------- AIMD limit


def test_aimd_grows_additively_and_cuts_multiplicatively():
    cfg = OverloadConfig(slo_s=0.1, min_limit=2, max_limit=8)
    lim = AdaptiveLimit(cfg, initial=4)
    assert lim.limit == 4
    assert lim.observe(0.05) == 5          # under SLO: +1
    assert lim.observe(0.05) == 6
    assert lim.observe(0.5) == 3           # breach: x0.5
    assert lim.observe(0.5) == 2           # floor at min_limit
    assert lim.observe(0.5) == 2
    for _ in range(10):
        lim.observe(0.01)
    assert lim.limit == 8                  # ceiling at max_limit
    assert lim.would_admit(7) and not lim.would_admit(8)


def test_aimd_default_limit_is_the_static_bound():
    cfg = OverloadConfig(max_limit=256)
    lim = AdaptiveLimit(cfg)
    assert lim.limit == 256                # unstressed == legacy bounded queue


# ------------------------------------------------------------------- CoDel


def test_codel_requires_a_full_interval_above_target():
    clock = FakeClock()
    codel = CoDelShedder(target_s=0.05, interval_s=1.0, clock=clock)
    assert not codel.offer(0.01)           # under target: nothing
    assert not codel.offer(0.2)            # first above: starts the clock
    clock.advance(0.5)
    assert not codel.offer(0.2)            # interval not yet elapsed
    clock.advance(0.6)
    assert codel.offer(0.2)                # sustained a full interval: shed
    assert not codel.offer(0.2)            # next drop waits its cadence
    clock.advance(1.0)
    assert codel.offer(0.2)                # interval/sqrt(2) elapsed


def test_codel_resets_when_sojourn_recovers():
    clock = FakeClock()
    codel = CoDelShedder(target_s=0.05, interval_s=1.0, clock=clock)
    codel.offer(0.2)
    clock.advance(1.1)
    assert codel.offer(0.2)                # dropping
    assert not codel.offer(0.01)           # back under target: full reset
    assert not codel.offer(0.2)            # must re-earn the interval
    clock.advance(0.5)
    assert not codel.offer(0.2)


# --------------------------------------------------------- brownout ladder


def _ladder(clock, engage_after=3, dwell_s=0.5, recovery_window_s=2.0):
    return BrownoutLadder(
        engage_after=engage_after, dwell_s=dwell_s,
        recovery_window_s=recovery_window_s, clock=clock,
    )


def test_ladder_needs_consecutive_pressure():
    clock = FakeClock()
    ladder = _ladder(clock)
    clock.advance(1.0)                     # dwell since construction
    assert ladder.observe(True) == 0
    assert ladder.observe(True) == 0
    assert ladder.observe(False) == 0      # calm resets the streak
    assert ladder.observe(True) == 0
    assert ladder.observe(True) == 0
    assert ladder.observe(True) == 1       # third CONSECUTIVE signal engages


def test_ladder_monotone_degrade_with_dwell_hysteresis():
    clock = FakeClock()
    ladder = _ladder(clock, engage_after=1, dwell_s=0.5)
    clock.advance(1.0)
    assert ladder.observe(True) == 1
    assert ladder.observe(True) == 1       # dwell not elapsed: held at 1
    clock.advance(0.5)
    assert ladder.observe(True) == 2       # one tier per dwell, never a jump
    clock.advance(0.5)
    assert ladder.observe(True) == 3
    clock.advance(0.5)
    assert ladder.observe(True) == LEVEL_SHED
    clock.advance(0.5)
    assert ladder.observe(True) == LEVEL_SHED  # clamped at shed


def test_ladder_recovers_one_tier_per_window():
    clock = FakeClock()
    ladder = _ladder(clock, engage_after=1, dwell_s=0.0, recovery_window_s=2.0)
    clock.advance(1.0)
    for _ in range(4):
        ladder.observe(True)
    assert ladder.level == LEVEL_SHED
    ladder.observe(False)                  # calm starts the recovery window
    clock.advance(1.9)
    assert ladder.level == LEVEL_SHED      # window not yet held
    clock.advance(0.2)
    assert ladder.level == 3               # one full window: one step down
    clock.advance(2.0)
    assert ladder.level == 2
    clock.advance(50.0)
    assert ladder.level == LEVEL_FULL      # passive decay walks all the way


def test_ladder_pressure_restarts_the_recovery_window():
    clock = FakeClock()
    ladder = _ladder(clock, engage_after=3, dwell_s=0.0, recovery_window_s=2.0)
    clock.advance(1.0)
    for _ in range(3):
        ladder.observe(True)
    assert ladder.level == 1
    ladder.observe(False)
    clock.advance(1.5)
    ladder.observe(True)                   # a blip mid-recovery
    clock.advance(1.9)
    ladder.observe(False)
    assert ladder.level == 1               # window restarted by the blip
    clock.advance(2.1)
    assert ladder.level == LEVEL_FULL


# ------------------------------------------------------------- controller


def test_controller_counts_sheds_per_tier():
    clock = FakeClock()
    metrics = MetricsRegistry()
    cfg = OverloadConfig(
        slo_s=0.1, min_limit=1, max_limit=1,
        engage_after=1, dwell_s=0.0, recovery_window_s=60.0,
    )
    ctl = OverloadController(cfg, metrics=metrics, clock=clock)
    clock.advance(1.0)
    # A limit rejection feeds the ladder as pressure FIRST, so the shed is
    # counted under the tier that rejection itself put in force.
    assert not ctl.admit(outstanding=1)
    assert ctl.brownout_level == 1
    assert metrics.overload_shed.value(tier="skip_rerank") == 1
    # Climb to shed and verify the shed-tier accounting.
    for _ in range(3):
        clock.advance(0.1)
        ctl.ladder.observe(True)
    assert ctl.brownout_level == LEVEL_SHED
    assert not ctl.admit(outstanding=0)
    assert metrics.overload_shed.value(tier="shed") == 1
    assert metrics.brownout_level.value() == LEVEL_SHED
    assert metrics.admission_limit.value() == 1


def test_shed_tier_rejections_do_not_wedge_recovery():
    clock = FakeClock()
    cfg = OverloadConfig(
        min_limit=1, max_limit=8,
        engage_after=1, dwell_s=0.0, recovery_window_s=1.0,
    )
    ctl = OverloadController(cfg, clock=clock)
    clock.advance(1.0)
    for _ in range(4):
        clock.advance(0.1)
        ctl.ladder.observe(True)
    assert ctl.brownout_level == LEVEL_SHED
    ctl.ladder.observe(False)
    # A trickle of rejected requests during recovery must NOT reset the
    # window — only LIMIT rejections are pressure, shed-tier ones are not.
    for _ in range(10):
        clock.advance(0.3)
        ctl.admit(outstanding=0)
    assert ctl.brownout_level < LEVEL_SHED
    clock.advance(10.0)
    assert ctl.brownout_level == LEVEL_FULL
    assert ctl.admit(outstanding=0)


def test_admit_fault_site_forces_the_shed_path():
    ctl = OverloadController(OverloadConfig())
    faults.arm("serving.admit", "error", at=1)
    try:
        assert not ctl.admit(outstanding=0)   # armed fault = shed, no raise
        assert ctl.admit(outstanding=0)       # exhausted: clean again
    finally:
        faults.disarm("serving.admit")


def test_retry_after_prices_limit_and_brownout():
    clock = FakeClock()
    cfg = OverloadConfig(min_limit=1, max_limit=10,
                         engage_after=1, dwell_s=0.0, recovery_window_s=60.0)
    ctl = OverloadController(cfg, clock=clock)
    clock.advance(1.0)
    base = ctl.price_retry_after(1.0, outstanding=0)
    assert base == pytest.approx(1.0)      # level 0, empty queue: unchanged
    ctl.ladder.observe(True)
    ctl.ladder.observe(True)
    level = ctl.brownout_level
    assert level >= 1
    priced = ctl.price_retry_after(1.0, outstanding=0)
    assert priced == pytest.approx(1.0 + level)   # brownout multiplies
    congested = ctl.price_retry_after(1.0, outstanding=29)
    assert congested == pytest.approx((1.0 + level) * 3.0)  # (29+1)/10


def test_tier_names_cover_the_ladder():
    assert TIERS == ("full", "skip_rerank", "bank_only",
                     "cache_popularity", "shed")
    assert tier_name(-3) == "full" and tier_name(99) == "shed"


# --------------------------------------------- the over-HTTP overload drill


@pytest.fixture(scope="module")
def overload_world():
    tables = synthetic_tables(n_users=100, n_items=60, mean_stars=6, seed=13)
    matrix = tables.star_matrix()
    model = ImplicitALS(rank=8, max_iter=2, seed=0).fit(matrix)
    return tables, matrix, model


def _get(handle, path):
    host, port = handle.server_address[:2]
    try:
        with urllib.request.urlopen(f"http://{host}:{port}{path}", timeout=30) as r:
            return r.status, json.loads(r.read() or b"{}"), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


def test_http_forced_overload_drill(overload_world):
    """Flood a served engine configured with a hair-trigger SLO: every
    response is a 200 or a tier-tagged 429 (never a 5xx), the ladder
    engages, and it fully recovers once the flood stops."""
    tables, matrix, model = overload_world
    svc = RecommendationService(
        model, matrix, repo_info=tables.repo_info,
        batching=True, batch_window_ms=5.0,
        overload_config=OverloadConfig(
            slo_s=1e-4,                    # every real batch breaches
            min_limit=1, max_limit=4,
            engage_after=2, dwell_s=0.05, recovery_window_s=0.3,
            codel_target_s=0.01, codel_interval_s=0.05,
        ),
    )
    user_ids = matrix.user_ids
    results: list[tuple[int, dict, dict]] = []
    res_lock = threading.Lock()

    def flood(ci: int) -> None:
        rng = np.random.default_rng(ci)
        local = []
        for _ in range(8):
            uid = int(user_ids[int(rng.integers(0, len(user_ids)))])
            local.append(_get(handle, f"/recommend/{uid}?k=5"))
        with res_lock:
            results.extend(local)

    with serve(svc, port=0) as handle:
        threads = [
            threading.Thread(target=flood, args=(ci,), daemon=True)
            for ci in range(16)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)

        statuses = {s for s, _, _ in results}
        assert statuses <= {200, 429}, f"unexpected statuses: {statuses}"
        n_429 = sum(1 for s, _, _ in results if s == 429)
        tagged = [b for s, b, _ in results if b.get("brownout")]
        assert tagged, "the flood never engaged the brownout ladder"
        # Every degraded/shed response carries a coherent tier tag (a
        # limit shed BEFORE the ladder engages is legitimately level 0).
        for body in tagged:
            assert body["brownout"]["tier"] in TIERS
            assert 0 <= body["brownout"]["level"] <= LEVEL_SHED
            assert body["brownout"]["tier"] == TIERS[body["brownout"]["level"]]
        assert any(b["brownout"]["level"] >= 1 for b in tagged), (
            "the ladder never escalated past full during the flood"
        )
        # Every 429 is priced: Retry-After present and positive.
        for s, body, headers in results:
            if s == 429:
                assert float(headers.get("Retry-After", 0)) > 0
        # Every 429 the clients saw is accounted in the per-tier counter.
        assert svc.metrics.overload_shed.total() == n_429

        # Recovery: no traffic -> idle ticks + passive decay walk the
        # ladder back to full work, and a fresh request is a clean 200.
        deadline = time.monotonic() + 30
        while svc.overload.brownout_level > 0 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert svc.overload.brownout_level == LEVEL_FULL
        status, body, _ = _get(
            handle, f"/recommend/{int(user_ids[0])}?k=5"
        )
        assert status == 200 and not body.get("brownout")


def test_queue_overflow_carries_tier_and_level(overload_world):
    """The QueueOverflow raised at the shed tier carries the tag the HTTP
    layer serializes — drilled directly, without load."""
    tables, matrix, model = overload_world
    svc = RecommendationService(
        model, matrix, batching=True,
        overload_config=OverloadConfig(
            engage_after=1, dwell_s=0.0, recovery_window_s=60.0,
        ),
    )
    try:
        for _ in range(4):
            svc.overload.ladder.observe(True)
            time.sleep(0.01)
        assert svc.overload.brownout_level == LEVEL_SHED
        with pytest.raises(QueueOverflow) as exc:
            svc.handle_recommend(int(matrix.user_ids[0]), k=5)
        assert exc.value.tier == "shed"
        assert exc.value.level == LEVEL_SHED
        assert exc.value.retry_after_s and exc.value.retry_after_s > 0
    finally:
        svc.close()
