"""Shared retry machinery: backoff shape, jitter bounds, deadline budget,
predicates, Retry-After honoring, and the retry counter."""

import random

import pytest

from albedo_tpu.utils import events
from albedo_tpu.utils.retry import (
    RetriesExhausted,
    RetryAfter,
    RetryPolicy,
    retry_call,
)


def test_succeeds_after_transient_failures():
    calls = []

    def fn():
        calls.append(1)
        if len(calls) < 3:
            raise ValueError("transient")
        return "ok"

    sleeps = []
    out = retry_call(
        fn,
        policy=RetryPolicy(max_attempts=5, base_s=0.1, max_delay_s=1.0),
        sleeper=sleeps.append,
        rng=random.Random(0),
    )
    assert out == "ok"
    assert len(calls) == 3
    assert len(sleeps) == 2  # one sleep per retry, none after success


def test_exhaustion_raises_with_cause():
    def fn():
        raise ValueError("always")

    with pytest.raises(RetriesExhausted) as ei:
        retry_call(
            fn,
            policy=RetryPolicy(max_attempts=3, base_s=0.0),
            sleeper=lambda s: None,
        )
    assert ei.value.attempts == 3
    assert isinstance(ei.value.last, ValueError)
    assert isinstance(ei.value.__cause__, ValueError)


def test_non_retryable_propagates_unchanged():
    calls = []

    def fn():
        calls.append(1)
        raise KeyError("fatal")

    with pytest.raises(KeyError):
        retry_call(
            fn,
            retry_on=lambda e: isinstance(e, ValueError),
            sleeper=lambda s: None,
        )
    assert len(calls) == 1  # no second attempt for a non-retryable error


def test_full_jitter_delays_bounded_by_exponential_caps():
    policy = RetryPolicy(max_attempts=6, base_s=1.0, multiplier=2.0, max_delay_s=6.0)
    sleeps = []

    def fn():
        raise ValueError("x")

    with pytest.raises(RetriesExhausted):
        retry_call(fn, policy=policy, sleeper=sleeps.append, rng=random.Random(7))
    caps = [1.0, 2.0, 4.0, 6.0, 6.0]  # base * mult^n clipped at max_delay_s
    assert len(sleeps) == 5
    for delay, cap in zip(sleeps, caps):
        assert 0.0 <= delay <= cap


def test_no_jitter_uses_deterministic_caps():
    policy = RetryPolicy(max_attempts=4, base_s=0.5, multiplier=2.0,
                         max_delay_s=10.0, jitter=False)
    sleeps = []

    def fn():
        raise ValueError("x")

    with pytest.raises(RetriesExhausted):
        retry_call(fn, policy=policy, sleeper=sleeps.append)
    assert sleeps == [0.5, 1.0, 2.0]


def test_retry_after_overrides_backoff():
    calls = []

    def fn():
        calls.append(1)
        if len(calls) == 1:
            raise RetryAfter(123.0, "server says wait")
        return "ok"

    sleeps = []
    assert retry_call(fn, sleeper=sleeps.append) == "ok"
    assert sleeps == [123.0]


def test_deadline_stops_retrying():
    clock = {"t": 0.0}

    def fake_clock():
        return clock["t"]

    def fake_sleep(s):
        clock["t"] += s

    def fn():
        raise ValueError("x")

    policy = RetryPolicy(max_attempts=100, base_s=1.0, multiplier=1.0,
                         max_delay_s=1.0, deadline_s=3.5, jitter=False)
    with pytest.raises(RetriesExhausted) as ei:
        retry_call(fn, policy=policy, sleeper=fake_sleep, clock=fake_clock)
    # 1s sleeps until the 3.5s budget is gone: far fewer than 100 attempts.
    assert ei.value.attempts <= 6
    assert clock["t"] <= 3.6


def test_retry_counter_increments_by_site():
    before = events.retry_attempts.value(site="test.site")
    calls = []

    def fn():
        calls.append(1)
        if len(calls) < 3:
            raise ValueError("x")
        return 1

    retry_call(fn, site="test.site", policy=RetryPolicy(max_attempts=5, base_s=0.0),
               sleeper=lambda s: None)
    assert events.retry_attempts.value(site="test.site") == before + 2
