"""Recommender layer (L3) tests.

Parity anchors: ``recommenders/*.scala`` — source tagging, top-k limits,
popularity/curation score formulas, ALS retrieval via the model's factors,
content MLT behind the embedding backend.
"""

import numpy as np
import pandas as pd
import pytest

from albedo_tpu.datasets import synthetic_tables
from albedo_tpu.models.als import ImplicitALS
from albedo_tpu.models.word2vec import Word2Vec
from albedo_tpu.recommenders import (
    ALSRecommender,
    ContentRecommender,
    CurationRecommender,
    EmbeddingSearchBackend,
    PopularityRecommender,
    fuse_candidates,
)
from albedo_tpu.recommenders.popularity import popularity_score
from albedo_tpu.datasets.tables import popular_repos


@pytest.fixture(scope="module")
def world():
    tables = synthetic_tables(n_users=200, n_items=150, mean_stars=15, seed=11)
    matrix = tables.star_matrix()
    model = ImplicitALS(rank=8, max_iter=4, reg_param=0.1).fit(matrix)
    return tables, matrix, model


def test_als_recommender_topk_and_source(world):
    tables, matrix, model = world
    rec = ALSRecommender(model, matrix, top_k=10)
    users = matrix.user_ids[:5]
    out = rec.recommend_for_users(users)
    assert set(out.columns) == {"user_id", "repo_id", "score", "source"}
    assert (out["source"] == "als").all()
    assert out.groupby("user_id").size().max() <= 10
    assert set(out["user_id"]) == set(users.tolist())
    # items are raw ids from the catalog
    assert set(out["repo_id"]).issubset(set(matrix.item_ids.tolist()))


def test_als_recommender_unknown_user_dropped(world):
    _, matrix, model = world
    rec = ALSRecommender(model, matrix, top_k=5)
    out = rec.recommend_for_users(np.array([999999999]))
    assert len(out) == 0


def test_als_recommender_exclude_seen(world):
    _, matrix, model = world
    rec = ALSRecommender(model, matrix, top_k=10, exclude_seen=True)
    users = matrix.user_ids[:8]
    out = rec.recommend_for_users(users)
    indptr, cols, _ = matrix.csr()
    for u_raw, grp in out.groupby("user_id"):
        u = int(matrix.users_of(np.array([u_raw]))[0])
        seen = set(matrix.item_ids[cols[indptr[u] : indptr[u + 1]]].tolist())
        assert not seen & set(grp["repo_id"].tolist())


def test_als_recommender_transform_protocol(world):
    _, matrix, model = world
    rec = ALSRecommender(model, matrix, top_k=3)
    out = rec.transform(pd.DataFrame({"user_id": matrix.user_ids[:2]}))
    assert len(out) <= 6


def test_popularity_recommender_formula(world):
    tables, matrix, _ = world
    pop = popular_repos(tables.repo_info, min_stars=1, max_stars=10**9)
    rec = PopularityRecommender(pop, top_k=7)
    users = np.array([1, 2, 3])
    out = rec.recommend_for_users(users)
    assert len(out) == 3 * 7
    assert (out["source"] == "popularity").all()
    top = pop.head(7)
    expected = popularity_score(
        top["repo_stargazers_count"].to_numpy(np.float64),
        top["repo_created_at"].to_numpy(np.float64),
    )
    got = out[out["user_id"] == 1]["score"].to_numpy()
    np.testing.assert_allclose(got, expected)
    # log10 term: 1000 stars ~ 3.0 plus time decay
    s = popularity_score(np.array([1000.0]), np.array([0.0]))
    assert s[0] == pytest.approx(3.0)


def test_curation_recommender(world):
    tables, _, _ = world
    star = tables.starring
    curators = tuple(star["user_id"].iloc[:2].tolist())
    rec = CurationRecommender(star, curator_ids=curators, top_k=5)
    out = rec.recommend_for_users(np.array([42]))
    assert (out["source"] == "curation").all()
    assert len(out) <= 5
    # scores are starred_at epochs, newest first
    assert (np.diff(out["score"].to_numpy()) <= 0).all()
    curated = star[star["user_id"].isin(curators)]
    assert set(out["repo_id"]).issubset(set(curated["repo_id"].tolist()))


def test_content_recommender_embedding_backend(world):
    tables, matrix, _ = world
    corpus = [
        (d + " " + t.replace(",", " ")).split()
        for d, t in zip(tables.repo_info["repo_description"], tables.repo_info["repo_topics"])
    ]
    w2v = Word2Vec(dim=16, min_count=2, max_iter=3, subsample=0.0, batch_size=256).fit_corpus(corpus)
    backend = EmbeddingSearchBackend(tables.repo_info, w2v)
    rec = ContentRecommender(backend, tables.starring, top_k=5)
    users = tables.starring["user_id"].unique()[:4]
    out = rec.recommend_for_users(users)
    assert (out["source"] == "content").all()
    assert out.groupby("user_id").size().max() <= 5
    # no query repo may appear in its own result set
    for u, grp in out.groupby("user_id"):
        recent = set(rec._user_recent_repos(int(u)).tolist())
        assert not recent & set(grp["repo_id"].tolist())


def test_content_eval_mode_offsets_queries(world):
    tables, _, _ = world
    user = int(tables.starring["user_id"].iloc[0])
    rec_a = ContentRecommender(SearchStub(), tables.starring, top_k=3)
    rec_b = ContentRecommender(SearchStub(), tables.starring, top_k=3, enable_evaluation_mode=True)
    qa = rec_a._user_recent_repos(user)
    qb = rec_b._user_recent_repos(user)
    s = tables.starring[tables.starring["user_id"] == user].sort_values(
        "starred_at", ascending=False
    )["repo_id"].to_numpy()
    np.testing.assert_array_equal(qa, s[:3])
    np.testing.assert_array_equal(qb, s[3:6])


class SearchStub:
    def more_like_this(self, queries, k):
        return [(np.array([7], dtype=np.int64), np.array([1.0])) for _ in queries]


def test_fuse_candidates_dedup(world):
    a = pd.DataFrame({"user_id": [1, 1], "repo_id": [10, 11], "score": [0.9, 0.8], "source": "als"})
    b = pd.DataFrame({"user_id": [1, 2], "repo_id": [10, 12], "score": [5.0, 4.0], "source": "popularity"})
    fused = fuse_candidates([a, b])
    assert len(fused) == 3
    row = fused[(fused["user_id"] == 1) & (fused["repo_id"] == 10)]
    assert row["source"].iloc[0] == "als"  # first source wins
