"""Streaming delta ingest: the synthetic generator, the delta rule set
(catalog reuse + fold-out routing + tombstones), and the StarOverlay's
merge/decay semantics."""

import numpy as np
import pandas as pd
import pytest

from albedo_tpu.datasets.star_matrix import StarMatrix
from albedo_tpu.datasets.synthetic import synthetic_stars
from albedo_tpu.datasets.synthetic_tables import synthetic_delta_stream
from albedo_tpu.datasets.validate import DataValidationError, validate_starring
from albedo_tpu.streaming.deltas import StarOverlay, validate_deltas
from albedo_tpu.utils import events

NOW = 1.6e9


@pytest.fixture(scope="module")
def base():
    return synthetic_stars(n_users=200, n_items=120, rank=8, mean_stars=12, seed=5)


def _deltas(rows):
    return pd.DataFrame(
        rows, columns=["user_id", "repo_id", "starred_at", "starring", "op"]
    )


# --- the synthetic generator --------------------------------------------------


def test_generator_is_deterministic_and_schema_complete(base):
    a = synthetic_delta_stream(base, n_batches=3, batch_size=100, seed=9)
    b = synthetic_delta_stream(base, n_batches=3, batch_size=100, seed=9)
    assert len(a) == 3
    for fa, fb in zip(a, b):
        pd.testing.assert_frame_equal(fa, fb)
        assert list(fa.columns) == ["user_id", "repo_id", "starred_at", "starring", "op"]
        assert set(fa["op"]) <= {"star", "unstar"}
        assert len(fa) == 100


def test_generator_emits_every_delta_class(base):
    (batch,) = synthetic_delta_stream(
        base, n_batches=1, batch_size=200, seed=3,
        frac_unstar=0.1, frac_new_user=0.05, frac_new_repo=0.05,
    )
    du = base.users_of(batch["user_id"].to_numpy(np.int64))
    di = base.items_of(batch["repo_id"].to_numpy(np.int64))
    star = (batch["op"] == "star").to_numpy()
    assert (~star).sum() == 20  # un-stars
    assert ((du < 0) & star).sum() == 10  # new users
    assert ((di < 0) & star).sum() == 10  # new repos
    # Un-stars tombstone pairs that actually exist in the base matrix.
    keys = base.rows.astype(np.int64) * base.n_items + base.cols
    un = ~star
    un_keys = du[un].astype(np.int64) * base.n_items + di[un]
    assert np.isin(un_keys, keys).all()


def test_generator_timestamps_are_monotone_across_batches(base):
    batches = synthetic_delta_stream(base, n_batches=3, batch_size=50, seed=1)
    maxima = [float(b["starred_at"].max()) for b in batches]
    minima = [float(b["starred_at"].min()) for b in batches]
    assert maxima[0] < minima[1] < maxima[1] < minima[2]
    for b in batches:
        assert b["starred_at"].is_monotonic_increasing


def test_generator_new_stars_follow_popularity(base):
    """Power-law shape: the top-popularity third of the catalog should soak
    up well over its uniform share of fresh stars."""
    (batch,) = synthetic_delta_stream(
        base, n_batches=1, batch_size=600, seed=11,
        frac_unstar=0.0, frac_new_user=0.0, frac_new_repo=0.0,
    )
    di = base.items_of(batch["repo_id"].to_numpy(np.int64))
    counts = base.item_counts()
    top_third = set(np.argsort(-counts)[: base.n_items // 3].tolist())
    frac = np.mean([int(i) in top_third for i in di])
    assert frac > 0.55  # uniform would be ~0.33


# --- validate_deltas ----------------------------------------------------------


def test_unknown_entities_route_to_fold_out_not_violations(base):
    deltas = _deltas([
        (int(base.user_ids[0]), int(base.item_ids[1]), NOW, 1.0, "star"),
        (99_999_999, int(base.item_ids[0]), NOW, 1.0, "star"),  # new user
        (int(base.user_ids[0]), 88_888_888, NOW, 1.0, "star"),  # new repo
    ])
    batch = validate_deltas(deltas, base, now=NOW, policy="repair")
    assert batch.n_rows == 1
    assert batch.n_fold_out == 2
    assert batch.report.violations == {}
    assert events.stream_deltas.value(kind="folded_out") == 2


def test_dangling_tombstone_is_a_violation(base):
    deltas = _deltas([
        (99_999_999, int(base.item_ids[0]), NOW, 1.0, "unstar"),
    ])
    batch = validate_deltas(deltas, base, now=NOW, policy="repair")
    assert batch.n_rows == 0
    assert batch.n_fold_out == 0
    assert batch.report.violations == {"dangling_tombstone": 1}
    with pytest.raises(DataValidationError):
        validate_deltas(deltas, base, now=NOW, policy="strict")


def test_catalog_rules_apply_to_delta_rows(base):
    u, r = int(base.user_ids[0]), int(base.item_ids[0])
    deltas = _deltas([
        (u, r, NOW, -1.0, "star"),            # nonpositive confidence
        (u, int(base.item_ids[1]), NOW * 9, 1.0, "star"),  # far future
        (u, int(base.item_ids[2]), NOW, 1.0, "star"),      # clean
    ])
    batch = validate_deltas(deltas, base, now=NOW, policy="repair")
    assert batch.report.violations.get("nonpositive_confidence") == 1
    assert batch.report.violations.get("timestamp_range") == 1
    assert batch.n_rows == 1


def test_cross_op_keep_last_resolves_star_then_unstar(base):
    """A pair starred then un-starred inside one batch must leave only the
    tombstone (the catalog's duplicate keep-last runs across ops)."""
    u, r = int(base.user_ids[3]), int(base.item_ids[3])
    deltas = _deltas([
        (u, r, NOW + 1, 1.0, "star"),
        (u, r, NOW + 2, 1.0, "unstar"),
    ])
    batch = validate_deltas(deltas, base, now=NOW + 10, policy="repair")
    assert batch.n_rows == 1
    assert batch.frame.iloc[0]["op"] == "unstar"
    # Resolution is the stream's normal mechanics, not corruption: strict
    # must NOT die on superseded rows (they count, but don't raise).
    strict = validate_deltas(deltas, base, now=NOW + 10, policy="strict")
    assert strict.n_rows == 1
    assert strict.frame.iloc[0]["op"] == "unstar"
    assert strict.report.violations.get("duplicate_pair") == 1


def test_unparseable_ids_are_invalid_not_fold_out(base):
    """The conformer's -1 sentinel is not an identity: corrupt-id rows must
    be dropped as `invalid_id`, never queued for a refit to train a phantom
    id -1 user on."""
    import pandas as pd

    deltas = pd.DataFrame({
        "user_id": ["not-a-number", str(int(base.user_ids[0]))],
        "repo_id": [str(int(base.item_ids[0])), str(int(base.item_ids[1]))],
        "starred_at": [NOW, NOW],
        "starring": [1.0, 1.0],
        "op": ["star", "star"],
    })
    batch = validate_deltas(deltas, base, now=NOW, policy="repair")
    assert batch.n_rows == 1
    assert batch.n_fold_out == 0
    assert batch.report.violations.get("invalid_id") == 1
    with pytest.raises(DataValidationError):
        validate_deltas(deltas, base, now=NOW, policy="strict")


def test_fold_out_rows_still_face_the_non_vocab_rules(base):
    """A violating row must fail at the ingest that saw it, not cycles later
    inside a refit's strict ingest: fold-out routing skips only the vocab
    rules, never confidence/timestamp."""
    deltas = _deltas([
        (99_999_999, int(base.item_ids[0]), NOW, -1.0, "star"),  # unknown user, bad conf
        (77_777_777, int(base.item_ids[1]), NOW, 1.0, "star"),   # unknown user, clean
    ])
    batch = validate_deltas(deltas, base, now=NOW, policy="repair")
    assert batch.n_fold_out == 1  # only the clean row queues
    assert batch.report.violations.get("nonpositive_confidence") == 1
    with pytest.raises(DataValidationError):
        validate_deltas(deltas, base, now=NOW, policy="strict")


def test_off_policy_still_routes_fold_out(base):
    deltas = _deltas([
        (99_999_999, int(base.item_ids[0]), NOW, 1.0, "star"),
        (int(base.user_ids[0]), int(base.item_ids[0]), NOW * 9, 1.0, "star"),
    ])
    batch = validate_deltas(deltas, base, now=NOW, policy="off")
    # Fold-out is physics (frozen vocabularies), not policy; the catalog
    # rules are policy and stay off.
    assert batch.n_fold_out == 1
    assert batch.n_rows == 1
    assert batch.report.violations == {}


def test_tombstone_starring_value_never_trips_confidence_rule(base):
    u = int(base.user_ids[0])
    r = int(base.item_ids[base.cols[base.rows == 0][0]])
    deltas = _deltas([(u, r, NOW, 0.0, "unstar")])
    batch = validate_deltas(deltas, base, now=NOW, policy="repair")
    assert batch.n_rows == 1
    assert "nonpositive_confidence" not in batch.report.violations


# --- the timestamp_range `now` satellite --------------------------------------


def test_validate_starring_without_now_uses_wall_clock():
    """The future-skew rule must fire even when the caller forgot `now` —
    it used to be silently skipped, so year-3000 rows validated clean."""
    frame = pd.DataFrame({
        "user_id": [1, 2],
        "repo_id": [10, 20],
        "starred_at": [1.5e9, 32_503_680_000.0],  # ~year 3000
        "starring": [1.0, 1.0],
    })
    clean, report = validate_starring(frame, policy="repair")
    assert report.violations.get("timestamp_range") == 1
    assert len(clean) == 1


def test_validate_starring_explicit_now_is_deterministic():
    frame = pd.DataFrame({
        "user_id": [1], "repo_id": [10],
        "starred_at": [NOW + 3 * 86_400.0], "starring": [1.0],
    })
    # Replayed "in the past": the row is future-skewed relative to NOW...
    _, report = validate_starring(frame, policy="repair", now=NOW)
    assert report.violations.get("timestamp_range") == 1
    # ...and clean relative to a later replay clock. Same frame, same
    # verdicts for the same `now` — never wall-clock-dependent.
    _, report2 = validate_starring(frame, policy="repair", now=NOW + 4 * 86_400.0)
    assert report2.violations == {}


# --- StarOverlay --------------------------------------------------------------


def _apply(base, rows, now=NOW, **overlay_kw):
    overlay = StarOverlay(base, **overlay_kw)
    batch = validate_deltas(_deltas(rows), base, now=now, policy="repair")
    report = overlay.apply(batch)
    return overlay, report


def test_overlay_apply_star_and_tombstone(base):
    u_new = int(base.user_ids[7])
    # An item this user has NOT starred:
    seen = set(base.cols[base.rows == 7].tolist())
    i_new = next(i for i in range(base.n_items) if i not in seen)
    # An existing pair to tombstone:
    u_t, i_t = int(base.rows[0]), int(base.cols[0])
    overlay, report = _apply(base, [
        (u_new, int(base.item_ids[i_new]), NOW, 1.0, "star"),
        (int(base.user_ids[u_t]), int(base.item_ids[i_t]), NOW, 1.0, "unstar"),
    ])
    assert report["applied"] == 1 and report["tombstoned"] == 1
    assert overlay.has_pair(7, i_new)
    assert not overlay.has_pair(u_t, i_t)
    mat = overlay.materialize(NOW)
    assert mat.nnz == base.nnz  # one added, one removed
    dense = mat.dense()
    assert dense[7, i_new] > 1.0  # fresh star carries the recency boost
    assert dense[u_t, i_t] == 0.0


def test_overlay_unstar_of_overlay_star_restores_absence(base):
    u = int(base.user_ids[2])
    seen = set(base.cols[base.rows == 2].tolist())
    i = next(i for i in range(base.n_items) if i not in seen)
    r = int(base.item_ids[i])
    overlay, _ = _apply(base, [(u, r, NOW, 1.0, "star")])
    batch = validate_deltas(
        _deltas([(u, r, NOW + 1, 1.0, "unstar")]), base, now=NOW + 1, policy="repair"
    )
    report = overlay.apply(batch)
    assert report["tombstoned"] == 1
    assert not overlay.has_pair(2, i)
    assert overlay.materialize(NOW + 1).nnz == base.nnz
    # A second tombstone of the now-absent pair is dangling.
    batch2 = validate_deltas(
        _deltas([(u, r, NOW + 2, 1.0, "unstar")]), base, now=NOW + 2, policy="repair"
    )
    report2 = overlay.apply(batch2)
    assert report2["dangling_tombstones"] == 1


def test_overlay_confidence_decays_toward_base_weight(base):
    overlay = StarOverlay(base, half_life_s=86_400.0, recency_boost=1.0)
    fresh = overlay.confidence(NOW, NOW)
    day_old = overlay.confidence(NOW - 86_400.0, NOW)
    month_old = overlay.confidence(NOW - 30 * 86_400.0, NOW)
    assert fresh == pytest.approx(2.0)
    assert day_old == pytest.approx(1.5)
    assert 1.0 < month_old < 1.01
    assert fresh > day_old > month_old


def test_overlay_user_row_matches_materialized_row(base):
    """The fold-in parity anchor: user_row and materialize share one merge."""
    batches = synthetic_delta_stream(base, n_batches=2, batch_size=150, seed=2)
    overlay = StarOverlay(base)
    now = NOW
    touched: set[int] = set()
    for frame in batches:
        now = float(frame["starred_at"].max())
        batch = validate_deltas(frame, base, now=now, policy="repair")
        touched.update(overlay.apply(batch)["touched_users"])
    mat = overlay.materialize(now)
    indptr, cols, vals = mat.csr()
    assert touched
    for du in sorted(touched):
        idx, val = overlay.user_row(du, now)
        mc = cols[indptr[du]:indptr[du + 1]]
        mv = vals[indptr[du]:indptr[du + 1]]
        o_row, o_mat = np.argsort(idx), np.argsort(mc)
        assert np.array_equal(idx[o_row], mc[o_mat])
        np.testing.assert_allclose(val[o_row], mv[o_mat], rtol=1e-6)


def test_overlay_materialize_keeps_vocabularies(base):
    overlay, _ = _apply(base, [
        (int(base.user_ids[0]), int(base.item_ids[1]), NOW, 1.0, "star"),
    ])
    mat = overlay.materialize(NOW)
    assert np.array_equal(mat.user_ids, base.user_ids)
    assert np.array_equal(mat.item_ids, base.item_ids)
    assert isinstance(mat, StarMatrix)


def test_overlay_updated_starring_for_refit(base):
    star_frame = pd.DataFrame({
        "user_id": base.user_ids[base.rows].astype(np.int64),
        "repo_id": base.item_ids[base.cols].astype(np.int64),
        "starred_at": np.full(base.nnz, 1.5e9),
        "starring": np.ones(base.nnz),
    })
    u_t, i_t = int(base.rows[0]), int(base.cols[0])
    u_new = int(base.user_ids[9])
    seen = set(base.cols[base.rows == 9].tolist())
    i_new = next(i for i in range(base.n_items) if i not in seen)
    overlay, _ = _apply(base, [
        (u_new, int(base.item_ids[i_new]), NOW, 1.0, "star"),
        (int(base.user_ids[u_t]), int(base.item_ids[i_t]), NOW, 1.0, "unstar"),
    ])
    fold_out = _deltas([(424242, 525252, NOW, 1.0, "star")])[
        ["user_id", "repo_id", "starred_at", "starring", "op"]
    ]
    updated = overlay.updated_starring(star_frame, fold_out=fold_out)
    # One tombstoned row gone, one overlay star added, one fold-out row added.
    assert len(updated) == base.nnz + 1
    keys = set(zip(updated["user_id"], updated["repo_id"]))
    assert (int(base.user_ids[u_t]), int(base.item_ids[i_t])) not in keys
    assert (u_new, int(base.item_ids[i_new])) in keys
    assert (424242, 525252) in keys


def test_stream_ingest_fault_site_fires(base):
    from albedo_tpu.utils import faults
    from albedo_tpu.utils.faults import FaultInjected

    faults.site("stream.ingest").arm(kind="error")
    with pytest.raises(FaultInjected):
        validate_deltas(_deltas([]), base, now=NOW, policy="repair")
