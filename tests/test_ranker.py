"""End-to-end LogisticRegressionRanker pipeline test.

Parity anchor: ``LogisticRegressionRanker.scala:21-447`` — the full chain
reduce -> profiles -> feature pipeline -> negative balance -> weighted LR ->
AUC -> fuse -> re-rank -> NDCG@30, on synthetic tables. The committed AUC
(0.9425) is the shape gate: a working ranker separates starred from
popular-unstarred pairs far better than chance.
"""

import numpy as np
import pandas as pd
import pytest

from albedo_tpu.builders import (
    ALSScorer,
    RankerConfig,
    build_repo_profile,
    build_user_profile,
    reduce_starring,
    train_ranker,
)
from albedo_tpu.datasets import synthetic_tables
from albedo_tpu.datasets.tables import popular_repos
from albedo_tpu.models.als import ImplicitALS
from albedo_tpu.models.word2vec import Word2Vec
from albedo_tpu.recommenders import ALSRecommender, CurationRecommender, PopularityRecommender

NOW = 1.52e9


@pytest.fixture(scope="module")
def ranker_world():
    tables = synthetic_tables(n_users=300, n_items=220, mean_stars=18, seed=31)
    matrix = tables.star_matrix()
    user_profile, user_cols = build_user_profile(tables, now=NOW)
    repo_profile, repo_cols = build_repo_profile(
        tables, now=NOW, min_stars=1, max_stars=10**9, language_bin_threshold=3
    )
    als_model = ImplicitALS(rank=8, max_iter=5, reg_param=0.1).fit(matrix)
    corpus = [
        t.split() for t in repo_profile["repo_text"]
    ] + [t.split() for t in user_profile["user_recent_repo_descriptions"]]
    w2v = Word2Vec(dim=8, min_count=3, max_iter=2, subsample=0.0, batch_size=512).fit_corpus(corpus)
    return tables, matrix, user_profile, user_cols, repo_profile, repo_cols, als_model, w2v


@pytest.fixture(scope="module")
def trained(ranker_world):
    tables, matrix, up, uc, rp, rc, als_model, w2v = ranker_world
    config = RankerConfig(
        lr_max_iter=60,
        popular_min_stars=1,
        popular_max_stars=10**9,
        min_df=3,
        test_ratio=0.2,
        n_test_users=60,
    )
    recs = [
        ALSRecommender(als_model, matrix, top_k=20),
        CurationRecommender(
            tables.starring,
            curator_ids=tuple(tables.starring["user_id"].iloc[:3].tolist()),
            top_k=10,
        ),
        PopularityRecommender(
            popular_repos(tables.repo_info, 1, 10**9), top_k=10
        ),
    ]
    return train_ranker(
        tables, up, uc, rp, rc, als_model, matrix, w2v,
        now=NOW, config=config, recommenders=recs,
    )


def test_ranker_auc_beats_chance(trained):
    # Reference gate: areaUnderROC 0.9425 (LogisticRegressionRanker.scala:364).
    # Synthetic data is smaller/noisier; demand strong separation.
    assert trained.auc > 0.75, trained.auc


def test_ranker_ndcg_positive(trained):
    assert trained.ndcg is not None
    assert 0.0 < trained.ndcg <= 1.0


def test_ranker_scores_candidates(trained):
    model = trained.model
    users = model.user_profile["user_id"].iloc[:3].to_numpy(np.int64)
    repos = model.repo_profile["repo_id"].iloc[:4].to_numpy(np.int64)
    cand = pd.DataFrame(
        {
            "user_id": np.repeat(users, len(repos)),
            "repo_id": np.tile(repos, len(users)),
        }
    )
    scored = model.score(cand)
    assert "probability" in scored.columns
    assert ((scored["probability"] >= 0) & (scored["probability"] <= 1)).all()
    assert len(scored) <= len(cand)  # cold pairs dropped


def test_reduce_starring_caps_hyperactive_users():
    df = pd.DataFrame(
        {
            "user_id": [1] * 5 + [2] * 2,
            "repo_id": list(range(5)) + [10, 11],
            "starred_at": np.arange(7.0),
            "starring": np.ones(7),
        }
    )
    out = reduce_starring(df, max_count=3)
    assert set(out["user_id"]) == {2}


def test_als_scorer_cold_start_drop(ranker_world):
    tables, matrix, *_ , als_model, _w2v = ranker_world
    scorer = ALSScorer(als_model, matrix)
    df = pd.DataFrame(
        {
            "user_id": [int(matrix.user_ids[0]), 999999999],
            "repo_id": [int(matrix.item_ids[0]), int(matrix.item_ids[0])],
        }
    )
    out = scorer.transform(df)
    assert len(out) == 1  # unknown user dropped
    dense_u = matrix.users_of(np.array([matrix.user_ids[0]]))
    dense_i = matrix.items_of(np.array([matrix.item_ids[0]]))
    expect = als_model.predict(dense_u, dense_i)[0]
    assert out["als_score"].iloc[0] == pytest.approx(float(expect), rel=1e-5)


def test_als_scorer_keep_mode(ranker_world):
    _, matrix, *_, als_model, _w2v = ranker_world
    scorer = ALSScorer(als_model, matrix, cold_start="keep")
    df = pd.DataFrame({"user_id": [999999999], "repo_id": [int(matrix.item_ids[0])]})
    out = scorer.transform(df)
    assert len(out) == 1 and out["als_score"].iloc[0] == 0.0
