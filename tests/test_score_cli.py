"""The ``score_all`` exit-code contract and the cross-mesh kill/resume
parity acceptance, through the real CLI in subprocesses.

Exit codes drilled end-to-end: 0 (sealed), 4 (canary refusal — a verdict,
prior seal untouched), 75 (SIGTERM preemption — cursor checkpointed,
``--resume`` continues), 137 (hard kill mid-spill). The parity drill is the
PR's acceptance bound: a sweep killed mid-spill on an 8-way mesh and resumed
on a 2-way mesh must spill the SAME per-user top-k (exact candidate sets,
scores within 1e-5) as an uninterrupted single-device sweep.

Marked ``chaos`` + ``slow`` (each subprocess pays the jax import + in-process
ranker training): tier-1 covers the same lifecycle in-process in
``tests/test_scoring.py``. Every arm pins the SAME ``XLA_FLAGS`` 8-virtual-
device environment — the ranker trains in-process and its LR batching varies
with the VISIBLE device count, so only ``--mesh-devices`` (the bank's mesh
rung) may differ between arms.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pandas as pd
import pytest

pytestmark = [pytest.mark.chaos, pytest.mark.slow]

MANIFEST = "manifest.json"


def _env(data_dir: Path, **extra: str) -> dict:
    env = dict(os.environ)
    env.pop("ALBEDO_FAULTS", None)  # never inherit the harness's own arming
    env.update(
        ALBEDO_DATA_DIR=str(data_dir),
        ALBEDO_CHECKPOINT_DIR=str(data_dir / "checkpoints"),
        ALBEDO_TODAY="20260803",
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        **extra,
    )
    return env


def _score_all(env: dict, *extra_args: str) -> subprocess.CompletedProcess:
    cmd = [
        sys.executable, "-m", "albedo_tpu.cli", "score_all", "--small",
        "--score-shard-users", "120", "--score-k", "10", *extra_args,
    ]
    return subprocess.run(cmd, capture_output=True, text=True, env=env,
                          timeout=580)


def _out_root(data_dir: Path) -> Path:
    roots = list(data_dir.rglob(f"*-score_all/{MANIFEST}"))
    assert roots, f"no sealed score_all manifest under {data_dir}"
    return roots[0].parent


def _topk_frame(out_root: Path) -> pd.DataFrame:
    doc = json.loads((out_root / MANIFEST).read_text())
    gen_dir = out_root / f"gen-{int(doc['generation']):06d}"
    parts = [
        pd.read_parquet(gen_dir / rec["file"])
        for _, rec in sorted(doc["shards"].items(), key=lambda kv: int(kv[0]))
    ]
    frame = pd.concat(parts, ignore_index=True)
    return frame.sort_values(["user_id", "repo_id"]).reset_index(drop=True)


def test_exit_code_contract(tmp_path):
    env = _env(tmp_path / "data")

    # 0: clean sweep seals the manifest.
    proc = _score_all(env)
    assert proc.returncode == 0, (proc.returncode, proc.stdout, proc.stderr)
    assert "sealed" in proc.stdout
    out_root = _out_root(tmp_path / "data")
    sealed_bytes = (out_root / MANIFEST).read_bytes()

    # 4: an unreachable canary floor REFUSES the publish — a verdict, not a
    # crash — and the prior seal is byte-identical after the refusal.
    refused = _score_all(env, "--canary-floor", "1.1")
    assert refused.returncode == 4, (refused.returncode, refused.stderr)
    assert "PUBLISH REFUSED" in refused.stdout
    assert (out_root / MANIFEST).read_bytes() == sealed_bytes

    # 75: SIGTERM mid-sweep checkpoints the cursor and exits EX_TEMPFAIL.
    preempted = _score_all({**env, "ALBEDO_FAULTS": "score.shard:term@2"})
    assert preempted.returncode == 75, (preempted.returncode, preempted.stderr)
    journals = [
        p for p in (tmp_path / "data/checkpoints").rglob("journal.json")
        if "scoreCursor" in str(p)
    ]
    assert journals and json.loads(journals[0].read_text())["status"] == "preempted"

    # ...and --resume finishes the generation from the cursor.
    resumed = _score_all(env, "--resume")
    assert resumed.returncode == 0, (resumed.returncode, resumed.stderr)
    assert "resume:" in resumed.stdout
    assert json.loads(journals[0].read_text())["status"] == "complete"

    # 137: a hard kill at the spill seam is a real SIGKILL-style death.
    killed = _score_all({**env, "ALBEDO_FAULTS": "score.spill:kill@1"})
    assert killed.returncode == 137, (killed.returncode, killed.stderr)


def test_cross_mesh_kill_resume_parity(tmp_path):
    """The acceptance drill: kill mid-spill on the 8-way mesh, resume on a
    2-way mesh, and the sealed per-user top-k matches an uninterrupted
    single-device sweep — exact candidate sets, scores within 1e-5.

    All three runs share ONE artifact store: the drill holds the SWEEP to
    parity across mesh rungs, so its inputs (ALS factors, w2v, the ranker's
    training environment) must be the same bytes in every arm — retraining
    per arm would vary the factors with the training mesh's shard count
    (sharded-fit reduction order) and measure the trainer, not the sweep.
    The ref and kill arms also pin the same ``--now`` (the ranker's
    featurization instant — user/repo ages move with the wall clock); the
    RESUME arm deliberately does not: the sweep cursor pins ``now`` at
    generation start and the resume must restore it, or the shards sealed
    after the kill re-rank with a different LR than the shards before it."""
    env = _env(tmp_path / "data")

    # Reference arm: uninterrupted, one device. Snapshot its frame now —
    # the chaos arm's seal supersedes this generation.
    ref = _score_all(env, "--mesh-devices", "1", "--now", "1700000000")
    assert ref.returncode == 0, (ref.returncode, ref.stdout, ref.stderr)
    ref_frame = _topk_frame(_out_root(tmp_path / "data"))

    # Chaos arm: a fresh sweep generation (trained artifacts reloaded from
    # the store), killed at the 2nd shard's spill seam on the full mesh...
    killed = _score_all(
        {**env, "ALBEDO_FAULTS": "score.spill:kill@2"},
        "--mesh-devices", "8", "--now", "1700000000",
    )
    assert killed.returncode == 137, (killed.returncode, killed.stderr)

    # ...and resumed on a mesh HALF the size (device loss between runs) —
    # with NO --now: the cursor carries the generation's instant.
    resumed = _score_all(env, "--mesh-devices", "2", "--resume")
    assert resumed.returncode == 0, (resumed.returncode, resumed.stderr)
    assert "resume:" in resumed.stdout
    chaos_frame = _topk_frame(_out_root(tmp_path / "data"))

    # Exact per-user candidate sets...
    assert len(chaos_frame) == len(ref_frame)
    assert (chaos_frame["user_id"].to_numpy()
            == ref_frame["user_id"].to_numpy()).all()
    assert (chaos_frame["repo_id"].to_numpy()
            == ref_frame["repo_id"].to_numpy()).all()
    # ...and probability parity to 1e-5 (observed bitwise on this stack).
    np.testing.assert_allclose(
        chaos_frame["score"].to_numpy(), ref_frame["score"].to_numpy(),
        atol=1e-5, rtol=0,
    )
