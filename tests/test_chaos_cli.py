"""End-to-end kill/resume chaos through the real CLI, in subprocesses.

These are the acceptance-grade preemption drills: a ``train_als`` run is
actually killed (SIGKILL via the fault harness's ``kill`` action — exit 137,
no cleanup) or preempted (SIGTERM via ``term`` — checkpoint + exit 75), then
rerun with ``--resume``; the resumed run must finish from the surviving
checkpoints and match the uninterrupted run's NDCG@30 within 1e-3.

Marked ``chaos`` (the ``make chaos`` suite) and ``slow`` (three CLI
subprocesses each pay the jax import + compile): tier-1 covers the same
parity logic in-process in ``test_checkpoint.py::test_kill_resume_ndcg_parity``.
"""

import os
import re
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = [pytest.mark.chaos, pytest.mark.slow]

_NDCG_RE = re.compile(r"\[train_als\] NDCG@30 = ([0-9.eE+-]+)")


def _env(data_dir: Path, **extra: str) -> dict:
    env = dict(os.environ)
    env.pop("ALBEDO_FAULTS", None)  # never inherit the harness's own arming
    env.update(
        ALBEDO_DATA_DIR=str(data_dir),
        ALBEDO_CHECKPOINT_DIR=str(data_dir / "checkpoints"),
        ALBEDO_TODAY="20260803",
        JAX_PLATFORMS="cpu",
        **extra,
    )
    return env


def _train_als(env: dict, *extra_args: str) -> subprocess.CompletedProcess:
    # Compilation caches are ON (no --no-compilation-cache pin): the PR 3
    # drills had to pin it off because serialized-executable reuse on this
    # jaxlib/CPU combination drifted numerics between processes; the AOT
    # output-fingerprint self-check (utils/aot.py) now discards any cached
    # executable that cannot reproduce the exporting process's probe output,
    # so resumed runs are parity-exact with the caches engaged.
    cmd = [
        sys.executable, "-m", "albedo_tpu.cli", "train_als", "--small",
        "--checkpoint-every", "2", *extra_args,
    ]
    return subprocess.run(cmd, capture_output=True, text=True, env=env, timeout=580)

def _ndcg(proc: subprocess.CompletedProcess) -> float:
    m = _NDCG_RE.search(proc.stdout)
    assert m, f"no NDCG in output:\n{proc.stdout}\n{proc.stderr}"
    return float(m.group(1))


def test_sigkill_then_resume_matches_uninterrupted_ndcg(tmp_path):
    # Reference: uninterrupted checkpointed run in its own data dir.
    ref = _train_als(_env(tmp_path / "ref"))
    assert ref.returncode == 0, ref.stderr
    ndcg_ref = _ndcg(ref)

    # Chaos run: hard-killed (os._exit(137)) right after the 2nd checkpoint.
    env = _env(tmp_path / "data")
    killed = _train_als({**env, "ALBEDO_FAULTS": "checkpoint.save:kill@2"})
    assert killed.returncode == 137, (killed.returncode, killed.stderr)
    steps = sorted((tmp_path / "data/checkpoints").rglob("step_*"))
    assert steps, "the killed run left no checkpoints"

    # Resume from the survivors; quality parity with the uninterrupted run.
    resumed = _train_als(env, "--resume")
    assert resumed.returncode == 0, resumed.stderr
    assert abs(_ndcg(resumed) - ndcg_ref) <= 1e-3


def test_sigterm_preempts_cleanly_and_resumes(tmp_path):
    env = _env(tmp_path / "data")
    # SIGTERM at the 1st checkpoint boundary: the preemption handler flags,
    # the fit checkpoints, the CLI exits 75 (EX_TEMPFAIL) with a journal.
    preempted = _train_als({**env, "ALBEDO_FAULTS": "checkpoint.save:term@1"})
    assert preempted.returncode == 75, (preempted.returncode, preempted.stderr)
    journals = list((tmp_path / "data/checkpoints").rglob("journal.json"))
    assert journals and '"status": "preempted"' in journals[0].read_text()

    resumed = _train_als(env, "--resume")
    assert resumed.returncode == 0, resumed.stderr
    assert _ndcg(resumed) > 0
    assert '"status": "complete"' in journals[0].read_text()


# --- the poisoned-pipeline drill (PR 5) ---------------------------------------


def _write_poisoned_dataset(dest: Path) -> None:
    """A CSV dataset seeding EVERY ingest violation class on top of coherent
    synthetic tables: dangling user/repo ids, a duplicate (user, repo) star,
    non-positive and NaN confidences, NaN/negative/future timestamps, and a
    poison user starring most of the catalog."""
    import numpy as np
    import pandas as pd

    from albedo_tpu.datasets import synthetic_tables

    tables = synthetic_tables(n_users=120, n_items=80, mean_stars=10, seed=11)
    s = tables.starring
    now = 1_700_000_000.0
    dense_uid = int(tables.user_info["user_id"].iloc[0])
    dense_repos = tables.repo_info["repo_id"].to_numpy(np.int64)[:70]
    first = s.iloc[0]
    bad = pd.DataFrame({
        "user_id": [-1, int(first["user_id"]), int(first["user_id"]),
                    int(first["user_id"]), int(first["user_id"])],
        "repo_id": [int(first["repo_id"]), -2, int(first["repo_id"]),
                    int(tables.repo_info["repo_id"].iloc[1]),
                    int(tables.repo_info["repo_id"].iloc[2])],
        "starred_at": [now, now, now - 1.0,            # dup keeps the later
                       np.nan, now + 30 * 86_400.0],   # NaN / future clock
        "starring": [1.0, 1.0, 1.0, -3.0, np.nan],
    })
    poison = pd.DataFrame({
        "user_id": np.full(len(dense_repos), dense_uid, np.int64),
        "repo_id": dense_repos,
        "starred_at": np.full(len(dense_repos), now - 86_400.0),
        "starring": np.ones(len(dense_repos)),
    })
    dest.mkdir(parents=True, exist_ok=True)
    tables.user_info.to_csv(dest / "user_info.csv", index=False)
    tables.repo_info.to_csv(dest / "repo_info.csv", index=False)
    tables.relation.to_csv(dest / "relation.csv", index=False)
    pd.concat([s, bad, poison], ignore_index=True).to_csv(
        dest / "starring.csv", index=False
    )


def _run_pipeline(env: dict, tables: Path, *extra: str) -> subprocess.CompletedProcess:
    cmd = [
        sys.executable, "-m", "albedo_tpu.cli", "run_pipeline", "--small",
        "--tables", str(tables), "--data-policy", "repair",
        "--checkpoint-every", "2", *extra,
    ]
    return subprocess.run(cmd, capture_output=True, text=True, env=env, timeout=580)


def test_poisoned_pipeline_drill(tmp_path):
    """Acceptance: a dataset seeded with every violation class plus an
    injected mid-fit NaN runs the real CLI to completion under
    ``--data-policy repair`` — violations quarantined + journaled, the
    watchdog remediation journaled into the publish stamp — and a second
    run whose canary gate fails exits 4 (a verdict, not a crash) with the
    journal recording the refusal."""
    import json

    tables_dir = tmp_path / "tables"
    _write_poisoned_dataset(tables_dir)
    env = _env(tmp_path / "data")

    # Run 1: poisoned ingest + a NaN scribbled into the first watchdog check.
    proc = _run_pipeline(
        {**env, "ALBEDO_FAULTS": "train.watchdog:error@1"}, tables_dir
    )
    assert proc.returncode == 0, (proc.returncode, proc.stdout, proc.stderr)

    art_dir = tmp_path / "data"
    journal_path = next(art_dir.rglob("*pipeline-journal.json"))
    journal = json.loads(journal_path.read_text())
    assert journal["status"] == "complete"
    ingest = journal["stages"]["ingest"]["result"]
    for rule in ("dangling_user", "dangling_repo", "duplicate_pair",
                 "nonpositive_confidence", "timestamp_range", "dense_user"):
        assert ingest["violations"].get(rule, 0) >= 1, rule
    assert ingest["rows_out"] < ingest["rows_in"]
    # The dropped rows are quarantined, reviewable, rule-tagged.
    sidecar = next(art_dir.rglob("*.quarantine-*.csv"))
    assert sidecar.name == ingest["quarantined_to"]
    assert "rule" in sidecar.read_text().splitlines()[0]
    # The published stamp records lineage, the canary verdict, AND the
    # remediated mid-fit divergence.
    meta = json.loads(next(art_dir.rglob("*alsModel*.pkl.meta.json")).read_text())
    assert meta["canary"]["passed"] is True
    assert meta["lineage"]["quarantined"] == ingest["violations"]
    trips = meta["watchdog"]["trips"]
    assert trips and trips[0]["kinds"] == ["nonfinite"]
    assert trips[0]["remediated"] is True

    # Run 2: an unreachable canary floor — the gate REFUSES to publish.
    # Exit 4 is a verdict (retrain/investigate), distinct from 1 (crash)
    # and 75 (preempted).
    refused = _run_pipeline(env, tables_dir, "--canary-floor", "1.1")
    assert refused.returncode == 4, (refused.returncode, refused.stderr)
    assert "PUBLISH REFUSED" in refused.stdout
    journal = json.loads(journal_path.read_text())
    assert journal["status"] == "rejected"
    assert journal["stages"]["canary"]["status"] == "rejected"

    # Run 3: --publish-force overrides the same gate, loudly.
    forced = _run_pipeline(env, tables_dir, "--canary-floor", "1.1",
                           "--publish-force")
    assert forced.returncode == 0, (forced.returncode, forced.stderr)
    assert "CANARY GATE OVERRIDDEN" in forced.stdout
    meta = json.loads(next(art_dir.rglob("*alsModel*.pkl.meta.json")).read_text())
    assert meta["canary"]["forced"] is True
