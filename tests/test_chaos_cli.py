"""End-to-end kill/resume chaos through the real CLI, in subprocesses.

These are the acceptance-grade preemption drills: a ``train_als`` run is
actually killed (SIGKILL via the fault harness's ``kill`` action — exit 137,
no cleanup) or preempted (SIGTERM via ``term`` — checkpoint + exit 75), then
rerun with ``--resume``; the resumed run must finish from the surviving
checkpoints and match the uninterrupted run's NDCG@30 within 1e-3.

Marked ``chaos`` (the ``make chaos`` suite) and ``slow`` (three CLI
subprocesses each pay the jax import + compile): tier-1 covers the same
parity logic in-process in ``test_checkpoint.py::test_kill_resume_ndcg_parity``.
"""

import os
import re
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = [pytest.mark.chaos, pytest.mark.slow]

_NDCG_RE = re.compile(r"\[train_als\] NDCG@30 = ([0-9.eE+-]+)")


def _env(data_dir: Path, **extra: str) -> dict:
    env = dict(os.environ)
    env.pop("ALBEDO_FAULTS", None)  # never inherit the harness's own arming
    env.update(
        ALBEDO_DATA_DIR=str(data_dir),
        ALBEDO_CHECKPOINT_DIR=str(data_dir / "checkpoints"),
        ALBEDO_TODAY="20260803",
        JAX_PLATFORMS="cpu",
        **extra,
    )
    return env


def _train_als(env: dict, *extra_args: str) -> subprocess.CompletedProcess:
    # Compilation caches are ON (no --no-compilation-cache pin): the PR 3
    # drills had to pin it off because serialized-executable reuse on this
    # jaxlib/CPU combination drifted numerics between processes; the AOT
    # output-fingerprint self-check (utils/aot.py) now discards any cached
    # executable that cannot reproduce the exporting process's probe output,
    # so resumed runs are parity-exact with the caches engaged.
    cmd = [
        sys.executable, "-m", "albedo_tpu.cli", "train_als", "--small",
        "--checkpoint-every", "2", *extra_args,
    ]
    return subprocess.run(cmd, capture_output=True, text=True, env=env, timeout=580)

def _ndcg(proc: subprocess.CompletedProcess) -> float:
    m = _NDCG_RE.search(proc.stdout)
    assert m, f"no NDCG in output:\n{proc.stdout}\n{proc.stderr}"
    return float(m.group(1))


def test_sigkill_then_resume_matches_uninterrupted_ndcg(tmp_path):
    # Reference: uninterrupted checkpointed run in its own data dir.
    ref = _train_als(_env(tmp_path / "ref"))
    assert ref.returncode == 0, ref.stderr
    ndcg_ref = _ndcg(ref)

    # Chaos run: hard-killed (os._exit(137)) right after the 2nd checkpoint.
    env = _env(tmp_path / "data")
    killed = _train_als({**env, "ALBEDO_FAULTS": "checkpoint.save:kill@2"})
    assert killed.returncode == 137, (killed.returncode, killed.stderr)
    steps = sorted((tmp_path / "data/checkpoints").rglob("step_*"))
    assert steps, "the killed run left no checkpoints"

    # Resume from the survivors; quality parity with the uninterrupted run.
    resumed = _train_als(env, "--resume")
    assert resumed.returncode == 0, resumed.stderr
    assert abs(_ndcg(resumed) - ndcg_ref) <= 1e-3


def test_sigterm_preempts_cleanly_and_resumes(tmp_path):
    env = _env(tmp_path / "data")
    # SIGTERM at the 1st checkpoint boundary: the preemption handler flags,
    # the fit checkpoints, the CLI exits 75 (EX_TEMPFAIL) with a journal.
    preempted = _train_als({**env, "ALBEDO_FAULTS": "checkpoint.save:term@1"})
    assert preempted.returncode == 75, (preempted.returncode, preempted.stderr)
    journals = list((tmp_path / "data/checkpoints").rglob("journal.json"))
    assert journals and '"status": "preempted"' in journals[0].read_text()

    resumed = _train_als(env, "--resume")
    assert resumed.returncode == 0, resumed.stderr
    assert _ndcg(resumed) > 0
    assert '"status": "complete"' in journals[0].read_text()
