"""Validated zero-downtime hot-swap: gates, promote, rollback, watch, and the
corrupt-artifact-mid-serve chaos drill through real HTTP."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from albedo_tpu.datasets import synthetic_tables  # noqa: E402
from albedo_tpu.datasets.artifacts import (  # noqa: E402
    artifact_path,
    save_pickle,
    write_manifest,
)
from albedo_tpu.datasets.tables import popular_repos  # noqa: E402
from albedo_tpu.models.als import ALSModel, ImplicitALS  # noqa: E402
from albedo_tpu.recommenders import PopularityRecommender  # noqa: E402
from albedo_tpu.serving import (  # noqa: E402
    HotSwapManager,
    RecommendationService,
    serve,
)
from albedo_tpu.utils import faults  # noqa: E402

K = 8


@pytest.fixture(scope="module")
def artifacts():
    tables = synthetic_tables(n_users=80, n_items=50, mean_stars=6, seed=21)
    matrix = tables.star_matrix()
    model_a = ImplicitALS(rank=8, max_iter=2, seed=0).fit(matrix)
    model_b = ImplicitALS(rank=8, max_iter=4, seed=3).fit(matrix)
    return tables, matrix, model_a, model_b


def _write_model(name: str, model: ALSModel, manifest: bool = True):
    """Materialize a model artifact the way run_pipeline's store does."""
    path = artifact_path(name)
    save_pickle(path, model.to_arrays())
    if manifest:
        write_manifest(path)
    return path


def _service(artifacts, **kw):
    tables, matrix, model_a, _ = artifacts
    kw.setdefault("batch_window_ms", 0.0)
    return RecommendationService(
        model_a, matrix, repo_info=tables.repo_info, **kw
    )


def _expected(model: ALSModel, matrix, uid: int, k: int):
    dense = matrix.users_of(np.array([uid], dtype=np.int64))
    vals, idx = model.recommend(dense, k=k)
    ok = (idx[0] >= 0) & np.isfinite(vals[0])
    return [
        (int(matrix.item_ids[i]), float(v))
        for i, v in zip(idx[0][ok], vals[0][ok])
    ]


def test_promote_good_artifact_swaps_generation(artifacts):
    tables, matrix, model_a, model_b = artifacts
    with _service(artifacts) as svc:
        mgr = HotSwapManager(svc, probe_users=4, probe_k=K)
        path = _write_model("candidate-alsModel.pkl", model_b)
        assert svc.generation.number == 1

        report = mgr.request_reload(path)
        assert report["outcome"] == "promoted", report
        assert report["generation"] == 2
        assert report["gates"]["manifest"] == "ok"
        assert report["gates"]["invariants"] == "ok"
        assert report["gates"]["post_swap_parity"] == "ok"
        assert svc.generation.number == 2
        assert svc.metrics.reloads.value(outcome="promoted") == 1
        assert svc.metrics.model_generation.value() == 2

        # Requests now serve model B's numbers, tagged generation 2.
        uid = int(matrix.user_ids[0])
        status, body = svc.handle_recommend(uid, k=K, exclude_seen=False)
        assert status == 200 and body["generation"] == 2
        got = [(i["repo_id"], i["score"]) for i in body["items"]]
        assert got == _expected(model_b, matrix, uid, K)

        # The displaced generation's batcher was retired — no zombies.
        assert svc._zombie_batchers == []

        ready, rep = svc.readiness()
        assert ready and rep["generation"] == 2 and rep["origin"].endswith(".pkl")


def test_corrupt_candidate_rejected_and_quarantined(artifacts):
    _, matrix, _, model_b = artifacts
    with _service(artifacts) as svc:
        mgr = HotSwapManager(svc, probe_users=4, probe_k=K)
        path = _write_model("candidate-alsModel.pkl", model_b)
        # Flip one byte AFTER the manifest was written: checksum mismatch.
        data = bytearray(path.read_bytes())
        data[len(data) // 2] ^= 0xFF
        path.write_bytes(bytes(data))

        report = mgr.request_reload(path)
        assert report["outcome"] == "rejected" and report["gate"] == "manifest"
        assert "quarantined_to" in report
        assert not path.exists()  # moved aside as evidence
        assert path.with_name(report["quarantined_to"]).exists()
        # Incumbent untouched and still serving.
        assert svc.generation.number == 1
        status, body = svc.handle_recommend(int(matrix.user_ids[0]), k=K)
        assert status == 200 and body["generation"] == 1
        assert svc.metrics.reload_rejected.value(gate="manifest") == 1
        assert svc.metrics.reloads.value(outcome="rejected") == 1


def test_invariant_gate_rejects_wrong_shapes_and_nonfinite(artifacts):
    _, matrix, _, model_b = artifacts
    with _service(artifacts) as svc:
        mgr = HotSwapManager(svc, probe_users=4, probe_k=K)
        # Wrong user count: a different dataset's model must not swap in.
        wrong = ALSModel(
            np.ones((matrix.n_users + 5, 8), np.float32),
            np.ones((matrix.n_items, 8), np.float32), 8,
        )
        report = mgr.request_reload(_write_model("wrong-alsModel.pkl", wrong))
        assert report["outcome"] == "rejected" and report["gate"] == "invariants"
        assert "matrix" in report["detail"]

        # NaN factors: loadable, checksum-clean, and still not servable.
        uf = model_b.user_factors.copy()
        uf[3, 2] = np.nan
        bad = ALSModel(uf, model_b.item_factors.copy(), model_b.rank)
        report = mgr.request_reload(_write_model("nan-alsModel.pkl", bad))
        assert report["outcome"] == "rejected" and report["gate"] == "invariants"
        assert "finite" in report["detail"]
        assert svc.generation.number == 1


def test_missing_manifest_is_recorded_not_fatal(artifacts):
    _, _, _, model_b = artifacts
    with _service(artifacts) as svc:
        mgr = HotSwapManager(svc, probe_users=4, probe_k=K)
        path = _write_model("bare-alsModel.pkl", model_b, manifest=False)
        report = mgr.request_reload(path)
        assert report["outcome"] == "promoted"
        assert "unverified" in report["gates"]["manifest"]


def test_rollback_on_post_swap_parity_failure(artifacts):
    _, matrix, _, model_b = artifacts
    with _service(artifacts) as svc:
        mgr = HotSwapManager(svc, probe_users=4, probe_k=K)
        mgr._post_swap_parity = lambda *a, **k: (False, "forced mismatch")
        path = _write_model("parity-alsModel.pkl", model_b)

        report = mgr.request_reload(path)
        assert report["outcome"] == "rolled_back"
        assert svc.generation.number == 1  # incumbent re-promoted
        assert svc.metrics.reloads.value(outcome="rolled_back") == 1
        assert not path.exists()  # bad artifact quarantined
        # The incumbent still answers (its batcher was never stopped).
        status, body = svc.handle_recommend(int(matrix.user_ids[1]), k=K)
        assert status == 200 and body["generation"] == 1
        assert svc._zombie_batchers == []


def test_transient_overload_during_parity_probe_keeps_promotion(artifacts):
    """A full queue / busy worker during the post-swap probe is NOT a parity
    verdict: the promotion stands (gates already validated the model
    directly) and the artifact is NOT quarantined — a loaded fleet must not
    destroy every fresh artifact by rename."""
    from albedo_tpu.serving import QueueOverflow

    _, _, _, model_b = artifacts
    with _service(artifacts) as svc:
        mgr = HotSwapManager(svc, probe_users=4, probe_k=K)

        def overloaded(*a, **kw):
            raise QueueOverflow("serving queue full (256 waiting)")

        mgr._probe_via_batcher = overloaded
        path = _write_model("busy-alsModel.pkl", model_b)
        report = mgr.request_reload(path)
        assert report["outcome"] == "promoted"
        assert "inconclusive" in report["gates"]["post_swap_parity"]
        assert svc.generation.number == 2
        assert path.exists()  # not quarantined


def test_reload_rejects_traversal_names(artifacts):
    with _service(artifacts) as svc:
        mgr = HotSwapManager(svc, probe_users=4, probe_k=K)
        report = mgr.request_reload("../../etc/passwd")
        assert report["outcome"] == "rejected"
        assert "escapes the store" in report["detail"]
        assert svc.generation.number == 1


def test_generation_numbers_never_reused_after_rollback(artifacts):
    """Candidate numbers come from a monotonic counter, not the current
    generation + 1 (regression): after a rollback 2 -> 1, the next promotion
    must be 3 — a slow request still holding the first gen-2 snapshot could
    otherwise write its model's body under the second gen-2's cache key."""
    _, _, _, model_b = artifacts
    with _service(artifacts) as svc:
        mgr = HotSwapManager(svc, probe_users=4, probe_k=K)
        mgr._post_swap_parity = lambda *a, **k: (False, "forced mismatch")
        report = mgr.request_reload(_write_model("re1-alsModel.pkl", model_b))
        assert report["outcome"] == "rolled_back" and svc.generation.number == 1

        mgr._post_swap_parity = lambda *a, **k: (True, "ok")
        report = mgr.request_reload(_write_model("re2-alsModel.pkl", model_b))
        assert report["outcome"] == "promoted"
        assert report["generation"] == 3  # "2" already served traffic once
        assert svc.generation.number == 3


def test_watcher_falls_back_to_older_candidate_when_newest_rejected(artifacts):
    """Two candidates land between polls and the newest fails its gates: the
    SAME sweep must attempt the older valid one (regression: it was marked
    seen and silently dropped forever, pinning the service to a stale
    model while a validated artifact sat in the store)."""
    _, _, _, model_b = artifacts
    with _service(artifacts) as svc:
        # Tiny interval: _watch_once's post-promotion watchdog pause must
        # not stall the test for the production default.
        mgr = HotSwapManager(svc, probe_users=4, probe_k=K,
                             watch_interval_s=0.05)
        good = _write_model("w1-alsModel.pkl", model_b)
        bad = _write_model("w2-alsModel.pkl", model_b)
        data = bytearray(bad.read_bytes())
        data[len(data) // 2] ^= 0xFF
        bad.write_bytes(bytes(data))  # newest: checksum mismatch

        mgr._watch_once()
        assert svc.generation.number == 2
        assert svc.generation.origin == str(good)
        assert svc.metrics.reload_rejected.value(gate="manifest") == 1
        # Both outcomes marked seen: the next sweep attempts nothing new.
        before = svc.metrics.reloads.total()
        mgr._watch_once()
        assert svc.metrics.reloads.total() == before


def test_error_rate_watchdog_rolls_back(artifacts):
    _, matrix, _, model_b = artifacts
    with _service(artifacts) as svc:
        mgr = HotSwapManager(
            svc, probe_users=4, probe_k=K,
            error_rate_threshold=0.5, error_rate_min_requests=10,
        )
        report = mgr.request_reload(_write_model("err-alsModel.pkl", model_b))
        assert report["outcome"] == "promoted" and svc.generation.number == 2

        # Simulate a post-swap 5xx storm on the request counter.
        for _ in range(12):
            svc.metrics.requests.inc(route="recommend", status="500")
        verdict = mgr.check_error_rate()
        assert verdict["verdict"] == "regressed"
        assert verdict["rolled_back_to"] == 1
        assert svc.generation.number == 1
        assert svc.metrics.reloads.value(outcome="rolled_back") == 1
        # And the engine still serves on the rolled-back generation.
        status, body = svc.handle_recommend(int(matrix.user_ids[2]), k=K)
        assert status == 200 and body["generation"] == 1


def test_parity_rollback_clears_watchdog_state(artifacts):
    """A parity-failure rollback must clear the error-rate watchdog's
    baseline (regression): a later 5xx spike unrelated to any swap would
    otherwise 'roll back' the restored incumbent onto itself and
    quarantine-rename the healthy artifact behind the live model."""
    _, matrix, _, model_b = artifacts
    with _service(artifacts) as svc:
        mgr = HotSwapManager(svc, probe_users=4, probe_k=K,
                             error_rate_min_requests=10)
        mgr._post_swap_parity = lambda *a, **k: (False, "forced mismatch")
        report = mgr.request_reload(_write_model("stale-alsModel.pkl", model_b))
        assert report["outcome"] == "rolled_back"
        assert svc.generation.number == 1

        for _ in range(12):
            svc.metrics.requests.inc(route="recommend", status="500")
        verdict = mgr.check_error_rate()
        assert verdict == {"checked": False}
        assert svc.generation.number == 1
        # Only the parity rollback counted; the 5xx spike triggered nothing.
        assert svc.metrics.reloads.value(outcome="rolled_back") == 1


def test_error_rate_watchdog_healthy_traffic_keeps_generation(artifacts):
    _, _, _, model_b = artifacts
    with _service(artifacts) as svc:
        mgr = HotSwapManager(svc, probe_users=4, probe_k=K,
                             error_rate_min_requests=5)
        mgr.request_reload(_write_model("ok-alsModel.pkl", model_b))
        for _ in range(20):
            svc.metrics.requests.inc(route="recommend", status="200")
        verdict = mgr.check_error_rate()
        assert verdict["verdict"] == "healthy"
        assert svc.generation.number == 2


def test_swap_under_load_parity(artifacts):
    """Concurrent /recommend traffic across a hot-swap sees only generation
    1 or 2 responses, each bit-exact for its generation's model — no torn
    reads, no mixed state."""
    tables, matrix, model_a, model_b = artifacts
    uids = [int(u) for u in matrix.user_ids[:6]]
    expected = {
        1: {uid: _expected(model_a, matrix, uid, K) for uid in uids},
        2: {uid: _expected(model_b, matrix, uid, K) for uid in uids},
    }
    with _service(artifacts, cache_ttl=0.0) as svc:
        mgr = HotSwapManager(svc, probe_users=4, probe_k=K)
        path = _write_model("load-alsModel.pkl", model_b)

        stop = threading.Event()
        results: list[tuple[int, int, list]] = []
        errors: list[BaseException] = []

        def hammer():
            i = 0
            while not stop.is_set():
                uid = uids[i % len(uids)]
                i += 1
                try:
                    status, body = svc.handle_recommend(
                        uid, k=K, exclude_seen=False
                    )
                    assert status == 200, body
                    results.append((
                        body["generation"], uid,
                        [(it["repo_id"], it["score"]) for it in body["items"]],
                    ))
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)
                    return

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.2)  # traffic flowing on generation 1
        report = mgr.request_reload(path)  # swap under load
        time.sleep(0.2)  # traffic flowing on generation 2
        stop.set()
        for t in threads:
            t.join(timeout=30)

        assert not errors, errors[0]
        assert report["outcome"] == "promoted"
        gens = {g for g, _, _ in results}
        assert gens == {1, 2}, f"expected traffic on both generations, saw {gens}"
        for gen, uid, items in results:
            assert items == expected[gen][uid], (
                f"generation {gen} response for user {uid} does not match "
                f"that generation's model"
            )


def test_watcher_promotes_fresh_artifact(artifacts):
    _, _, _, model_b = artifacts
    with _service(artifacts) as svc:
        mgr = HotSwapManager(svc, artifact_glob="watched-*.pkl",
                             watch_interval_s=0.05, probe_users=4, probe_k=K)
        mgr.start_watch()
        try:
            assert svc.generation.number == 1
            _write_model("watched-alsModel.pkl", model_b)
            deadline = time.monotonic() + 20
            while svc.generation.number != 2 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert svc.generation.number == 2
            assert svc.generation.origin.endswith("watched-alsModel.pkl")
        finally:
            mgr.stop()


# --- the acceptance chaos drill, through real HTTP ---------------------------


def _get(handle, path):
    host, port = handle.server_address[:2]
    try:
        with urllib.request.urlopen(f"http://{host}:{port}{path}", timeout=30) as r:
            return r.status, json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _post(handle, path):
    host, port = handle.server_address[:2]
    req = urllib.request.Request(
        f"http://{host}:{port}{path}", data=b"", method="POST"
    )
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


@pytest.mark.chaos
def test_corrupt_candidate_mid_serve_drill_over_http(artifacts):
    """Acceptance: inject a corrupt candidate via the fault harness during a
    reload — the incumbent keeps serving, the corrupt generation is
    quarantined and counted on /metrics, a subsequent good artifact
    promotes, and probe parity holds across the swap."""
    tables, matrix, model_a, model_b = artifacts
    pop = PopularityRecommender(popular_repos(tables.repo_info, 1, 10**9), top_k=20)
    with _service(artifacts, recommenders={"popularity": pop}) as svc:
        mgr = HotSwapManager(svc, probe_users=4, probe_k=K)
        with serve(svc, port=0) as handle:
            uid = int(matrix.user_ids[0])
            status, before = _get(handle, f"/recommend/{uid}?k={K}&exclude_seen=0")
            assert status == 200 and before["generation"] == 1

            # Candidate lands; the fault harness corrupts it as the reload
            # touches it (reload.load fires before the manifest check).
            path = _write_model("drill-alsModel.pkl", model_b)
            faults.arm("reload.load", kind="corrupt", at=1)
            status, report = _post(handle, "/admin/reload?artifact=" + path.name)
            assert status == 409
            assert report["outcome"] == "rejected" and report["gate"] == "manifest"

            # Incumbent survived, same generation, same answers.
            status, after = _get(handle, f"/recommend/{uid}?k={K}&exclude_seen=0")
            assert status == 200 and after["generation"] == 1
            assert after["items"] == before["items"]

            # The quarantine, the rejection, and the fault firing are all
            # visible on /metrics.
            host, port = handle.server_address[:2]
            with urllib.request.urlopen(f"http://{host}:{port}/metrics", timeout=30) as r:
                text = r.read().decode()
            assert 'albedo_reload_rejected_total{gate="manifest"} 1' in text
            assert 'albedo_faults_fired_total{site="reload.load"} 1' in text
            assert 'albedo_artifact_corruptions_total{artifact="drill-alsModel.pkl"} 1' in text
            assert "albedo_model_generation 1" in text

            # A subsequent good artifact promotes...
            good = _write_model("drill2-alsModel.pkl", model_b)
            status, report = _post(handle, "/admin/reload?artifact=" + good.name)
            assert status == 200 and report["outcome"] == "promoted", report

            # ...and probe parity holds across the swap: the served top-K
            # for the probe user now matches model B bit-for-bit.
            status, swapped = _get(handle, f"/recommend/{uid}?k={K}&exclude_seen=0")
            assert status == 200 and swapped["generation"] == 2
            got = [(i["repo_id"], i["score"]) for i in swapped["items"]]
            assert got == _expected(model_b, matrix, uid, K)
            status, ready = _get(handle, "/healthz/ready")
            assert status == 200 and ready["generation"] == 2


# --- the publish-quality stamp gate (PR 5) ------------------------------------


def _stamp(path, score=0.5, passed=True, forced=False):
    from albedo_tpu.datasets.artifacts import write_meta

    return write_meta(path, {
        "canary": {"metric": "ndcg@30", "score": score, "passed": passed,
                   "forced": forced},
    })


def test_unstamped_artifact_rejected_under_require_stamp(artifacts):
    from albedo_tpu.utils import events

    _, matrix, _, model_b = artifacts
    with _service(artifacts) as svc:
        mgr = HotSwapManager(svc, probe_users=4, probe_k=K, require_stamp=True)
        path = _write_model("candidate-alsModel.pkl", model_b)
        report = mgr.request_reload(path)
        assert report["outcome"] == "rejected" and report["gate"] == "stamp"
        assert "unstamped" in report["detail"]
        assert svc.generation.number == 1
        assert events.publish_rejected.value(gate="stamp") == 1
        # Rejected candidate quarantined under the shared convention.
        assert not path.exists()


def test_unstamped_artifact_admitted_by_default(artifacts):
    _, _, _, model_b = artifacts
    with _service(artifacts) as svc:
        mgr = HotSwapManager(svc, probe_users=4, probe_k=K)
        path = _write_model("candidate-alsModel.pkl", model_b)
        report = mgr.request_reload(path)
        assert report["outcome"] == "promoted"
        assert report["gates"]["stamp"] == "missing (unverified)"


def test_stamped_artifact_promotes_and_records_score(artifacts):
    _, _, _, model_b = artifacts
    with _service(artifacts) as svc:
        mgr = HotSwapManager(svc, probe_users=4, probe_k=K, require_stamp=True)
        path = _write_model("candidate-alsModel.pkl", model_b)
        _stamp(path, score=0.42)
        report = mgr.request_reload(path)
        assert report["outcome"] == "promoted"
        assert report["gates"]["stamp"] == {"canary_score": 0.42}


def test_stamp_recording_failed_canary_rejects(artifacts):
    _, _, _, model_b = artifacts
    with _service(artifacts) as svc:
        mgr = HotSwapManager(svc, probe_users=4, probe_k=K)
        path = _write_model("candidate-alsModel.pkl", model_b)
        _stamp(path, score=0.1, passed=False)
        report = mgr.request_reload(path)
        assert report["outcome"] == "rejected" and report["gate"] == "stamp"
        assert "failed canary" in report["detail"]


def test_forced_stamp_admitted_but_visible(artifacts):
    _, _, _, model_b = artifacts
    with _service(artifacts) as svc:
        mgr = HotSwapManager(svc, probe_users=4, probe_k=K, require_stamp=True)
        path = _write_model("candidate-alsModel.pkl", model_b)
        _stamp(path, score=0.1, passed=False, forced=True)
        report = mgr.request_reload(path)
        assert report["outcome"] == "promoted"
        assert report["gates"]["stamp"] == {"canary_score": 0.1, "forced": True}


def test_stamp_for_different_bytes_rejects(artifacts):
    """A stamp issued against other bytes must not vouch for this artifact —
    even when the .sha256 manifest itself is valid."""
    _, _, model_a, model_b = artifacts
    with _service(artifacts) as svc:
        mgr = HotSwapManager(svc, probe_users=4, probe_k=K)
        path = _write_model("candidate-alsModel.pkl", model_b)
        _stamp(path, score=0.9)  # stamp binds to model_b's bytes
        # The artifact is then replaced (re-manifested, so gate 1 passes).
        _write_model("candidate-alsModel.pkl", model_a)
        report = mgr.request_reload(path)
        assert report["outcome"] == "rejected" and report["gate"] == "stamp"
        assert "different artifact bytes" in report["detail"]


def test_stamp_regression_vs_promoted_generation_rejects(artifacts):
    from albedo_tpu.utils import events

    _, _, model_a, model_b = artifacts
    with _service(artifacts) as svc:
        mgr = HotSwapManager(svc, probe_users=4, probe_k=K, canary_tolerance=0.10)
        good = _write_model("good-alsModel.pkl", model_b)
        _stamp(good, score=0.50)
        assert mgr.request_reload(good)["outcome"] == "promoted"

        # A later candidate scoring >10% below the PROMOTED generation's
        # stamp is refused before the unpickle.
        worse = _write_model("worse-alsModel.pkl", model_a)
        _stamp(worse, score=0.40)
        report = mgr.request_reload(worse)
        assert report["outcome"] == "rejected" and report["gate"] == "stamp"
        assert "regressed" in report["detail"]
        assert svc.generation.number == 2  # the good generation still serves
        assert events.publish_rejected.value(gate="stamp") == 1

        # Within tolerance promotes and advances the baseline.
        ok = _write_model("ok-alsModel.pkl", model_a)
        _stamp(ok, score=0.47)
        assert mgr.request_reload(ok)["outcome"] == "promoted"
        assert mgr._promoted_canary_score == 0.47


def test_rollback_restores_incumbent_stamp_baseline(artifacts):
    """An error-rate rollback must also roll the stamp gate's regression
    baseline back to the re-promoted incumbent's own score — otherwise the
    rolled-back candidate's (higher) score keeps gating out candidates
    better than what is actually serving, blocking recovery."""
    _, _, model_a, model_b = artifacts
    with _service(artifacts) as svc:
        mgr = HotSwapManager(
            svc, probe_users=4, probe_k=K, canary_tolerance=0.10,
            error_rate_threshold=0.5, error_rate_min_requests=10,
        )
        good = _write_model("g-alsModel.pkl", model_b)
        _stamp(good, score=0.50)
        assert mgr.request_reload(good)["outcome"] == "promoted"

        better = _write_model("b-alsModel.pkl", model_a)
        _stamp(better, score=0.60)
        assert mgr.request_reload(better)["outcome"] == "promoted"

        # Post-swap 5xx storm rolls back to the 0.50 generation.
        for _ in range(12):
            svc.metrics.requests.inc(route="recommend", status="500")
        assert mgr.check_error_rate()["verdict"] == "regressed"
        assert mgr._promoted_canary_score == 0.50

        # A candidate better than what is SERVING (0.52 > 0.50) promotes —
        # under the rolled-back 0.60 baseline it would have been refused.
        recovery = _write_model("r-alsModel.pkl", model_b)
        _stamp(recovery, score=0.52)
        assert mgr.request_reload(recovery)["outcome"] == "promoted"
        assert mgr._promoted_canary_score == 0.52


def test_stamp_binding_survives_missing_manifest(artifacts):
    """Losing the .sha256 sidecar must not let a stamp vouch for different
    bytes — the gate falls back to hashing the artifact itself."""
    from albedo_tpu.datasets.artifacts import manifest_path

    _, _, model_a, model_b = artifacts
    with _service(artifacts) as svc:
        mgr = HotSwapManager(svc, probe_users=4, probe_k=K)
        path = _write_model("candidate-alsModel.pkl", model_b)
        _stamp(path, score=0.9)  # binds to model_b's bytes
        # Replace the bytes and strip the manifest: gate 1 admits it as
        # "missing (unverified)", so only the stamp's own hash can catch it.
        _write_model("candidate-alsModel.pkl", model_a)
        manifest_path(path).unlink()
        report = mgr.request_reload(path)
        assert report["outcome"] == "rejected" and report["gate"] == "stamp"
        assert "different artifact bytes" in report["detail"]


@pytest.mark.chaos
def test_stamp_gate_drill_over_http(artifacts):
    """Acceptance (PR 5): a live server keeps serving the last-known-good
    generation while the reload stamp gate rejects an UNSTAMPED candidate
    (require_stamp) and then a REGRESSED-stamp candidate — both visible on
    /metrics as albedo_publish_rejected_total{gate="stamp"}."""
    tables, matrix, model_a, model_b = artifacts
    with _service(artifacts) as svc:
        mgr = HotSwapManager(svc, probe_users=4, probe_k=K, require_stamp=True)
        with serve(svc, port=0) as handle:
            # Promote the stamped last-known-good.
            good = _write_model("lkg-alsModel.pkl", model_b)
            _stamp(good, score=0.50)
            status, report = _post(handle, "/admin/reload?artifact=" + good.name)
            assert status == 200 and report["outcome"] == "promoted", report
            uid = int(matrix.user_ids[0])
            status, before = _get(handle, f"/recommend/{uid}?k={K}&exclude_seen=0")
            assert status == 200 and before["generation"] == 2

            # An unstamped candidate never reaches the swap path.
            unstamped = _write_model("sneaky-alsModel.pkl", model_a)
            status, report = _post(handle, "/admin/reload?artifact=" + unstamped.name)
            assert status == 409
            assert report["outcome"] == "rejected" and report["gate"] == "stamp"

            # Neither does a stamped-but-regressed one.
            worse = _write_model("regressed-alsModel.pkl", model_a)
            _stamp(worse, score=0.30)
            status, report = _post(handle, "/admin/reload?artifact=" + worse.name)
            assert status == 409
            assert report["outcome"] == "rejected" and report["gate"] == "stamp"

            # The incumbent generation served identically throughout.
            status, after = _get(handle, f"/recommend/{uid}?k={K}&exclude_seen=0")
            assert status == 200 and after["generation"] == 2
            assert after["items"] == before["items"]

            host, port = handle.server_address[:2]
            with urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=30
            ) as r:
                text = r.read().decode()
            assert 'albedo_publish_rejected_total{gate="stamp"} 2' in text
            assert 'albedo_reload_rejected_total{gate="stamp"} 2' in text
            assert "albedo_model_generation 2" in text
