"""Every module in the package must import cleanly — catches import-time
breakage in modules no other test happens to touch (the reference has no
equivalent; its JVM build at least enforced compilation)."""

import importlib
import pkgutil

import albedo_tpu


def test_all_modules_import():
    failures = []
    for mod in pkgutil.walk_packages(albedo_tpu.__path__, prefix="albedo_tpu."):
        try:
            importlib.import_module(mod.name)
        except Exception as e:  # noqa: BLE001
            failures.append((mod.name, repr(e)))
    assert not failures, failures
