"""Raw-table layer: schemas, ingest, popular view, and the string cleaners.

Reference parity anchors: ``schemas/package.scala``, ``utils/DatasetUtils.scala``
(loaders + popular query), ``closures/UDFs.scala:32-78`` (cleaners).
"""

import numpy as np
import pandas as pd
import pytest

from albedo_tpu.datasets import (
    load_or_create_raw_tables,
    load_raw_tables,
    popular_repos,
    synthetic_tables,
)
from albedo_tpu.datasets.tables import (
    REPO_INFO_SCHEMA,
    STARRING_SCHEMA,
    USER_INFO_SCHEMA,
    conform,
)
from albedo_tpu.text import (
    clean_company,
    clean_location,
    extract_email_domain,
    extract_words_include_cjk,
)


@pytest.fixture(scope="module")
def tables():
    return synthetic_tables(n_users=150, n_items=120, mean_stars=12, seed=3)


def test_schemas_complete(tables):
    # Column parity with schemas/package.scala (15 user cols, 24 repo cols).
    assert len(USER_INFO_SCHEMA) == 15
    assert len(REPO_INFO_SCHEMA) == 24
    assert list(tables.user_info.columns) == list(USER_INFO_SCHEMA)
    assert list(tables.repo_info.columns) == list(REPO_INFO_SCHEMA)
    assert list(tables.starring.columns) == list(STARRING_SCHEMA)


def test_star_matrix_roundtrip(tables):
    m = tables.star_matrix()
    assert m.n_users == tables.starring["user_id"].nunique()
    assert m.n_items == tables.starring["repo_id"].nunique()
    assert m.nnz == len(tables.starring.drop_duplicates(["user_id", "repo_id"]))
    # starring column is the implicit 1.0 rating
    assert (tables.starring["starring"] == 1.0).all()


def test_starred_at_monotonic_per_user(tables):
    s = tables.starring.sort_values(["user_id", "starred_at"])
    g = s.groupby("user_id")["starred_at"]
    assert (g.diff().dropna() >= 0).all()


def test_popular_repos_range(tables):
    pop = popular_repos(tables.repo_info, min_stars=100, max_stars=49_000)
    assert (pop["repo_stargazers_count"].between(100, 49_000)).all()
    assert (pop["repo_stargazers_count"].diff().dropna() <= 0).all()


def test_conform_fills_missing():
    df = pd.DataFrame({"user_id": [1, 2], "user_login": ["a", None]})
    out = conform(df, USER_INFO_SCHEMA)
    assert out["user_login"].tolist() == ["a", ""]
    assert (out["user_followers_count"] == 0).all()
    assert out["user_created_at"].dtype == np.float64


def test_ingest_csv_dir_django_names(tables, tmp_path):
    # Django table-name aliases, like the JDBC reads in DatasetUtils.
    tables.user_info.rename(
        columns={
            "user_id": "id", "user_login": "login", "user_account_type": "account_type",
            "user_name": "name", "user_company": "company", "user_blog": "blog",
            "user_location": "location", "user_email": "email", "user_bio": "bio",
            "user_public_repos_count": "public_repos",
            "user_public_gists_count": "public_gists",
            "user_followers_count": "followers", "user_following_count": "following",
            "user_created_at": "created_at", "user_updated_at": "updated_at",
        }
    ).to_csv(tmp_path / "app_userinfo.csv", index=False)
    tables.starring.to_csv(tmp_path / "app_repostarring.csv", index=False)
    got = load_raw_tables(tmp_path)
    assert got.user_info["user_login"].tolist() == tables.user_info["user_login"].tolist()
    assert len(got.starring) == len(tables.starring)
    assert len(got.repo_info) == 0  # missing file -> empty conformed frame


def test_ingest_sqlite(tables, tmp_path):
    import sqlite3

    db = tmp_path / "albedo.db"
    with sqlite3.connect(db) as conn:
        tables.starring.to_sql("app_repostarring", conn, index=False)
        tables.repo_info.to_sql("repo_info", conn, index=False)
    got = load_raw_tables(db)
    assert len(got.starring) == len(tables.starring)
    assert got.repo_info["repo_id"].tolist() == tables.repo_info["repo_id"].tolist()


def test_load_or_create_raw_tables_cache_hit(tables):
    calls = []

    def create():
        calls.append(1)
        return tables

    first = load_or_create_raw_tables(create)
    second = load_or_create_raw_tables(lambda: (_ for _ in ()).throw(AssertionError))
    assert len(calls) == 1  # one conformed build serves all four table artifacts
    assert first.starring["user_id"].tolist() == second.starring["user_id"].tolist()


# --- string cleaners ---------------------------------------------------------


def test_clean_company_examples():
    assert clean_company("@BigCorp Inc.") == "bigcorp"
    assert clean_company("tinystartup.io") == "tinystartup"
    assert clean_company("Formerly @MegaSoft") == "megasoft"
    assert clean_company("ACME Co Ltd") == "acme"
    assert clean_company("") == "__empty"
    assert clean_company("!!!") == "__empty"


def test_clean_location_takes_city():
    assert clean_location("Taipei, Taiwan") == "taipei"
    assert clean_location("New York City") == "new york"
    assert clean_location("") == "__empty"
    # Scala's extractor needs a FULL match: multi-comma locations raise
    # MatchError in the reference and keep the whole (cleaned) string.
    assert clean_location("San Francisco, CA, USA") == "san francisco ca usa"


def test_cjk_words_kept():
    words = extract_words_include_cjk("機械学習 rocks deep-learning")
    assert "機械学習" in words and "rocks" in words and "deep-learning" in words
    assert clean_location("東京") == "東京"


def test_email_domain():
    assert extract_email_domain("someone@example.com") == "example.com"
    assert extract_email_domain("no-at-sign") == "no-at-sign"


def test_synthetic_tables_deterministic():
    a = synthetic_tables(n_users=40, n_items=30, seed=9)
    b = synthetic_tables(n_users=40, n_items=30, seed=9)
    pd.testing.assert_frame_equal(a.repo_info, b.repo_info)
    pd.testing.assert_frame_equal(a.starring, b.starring)
