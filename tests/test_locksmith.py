"""locksmith (analysis/locksmith.py): the ALBEDO_LOCKCHECK sanitizer.

Three layers:

1. **Mechanics** — named_lock passthrough when disabled, tracked wrapper
   when armed, balanced with/acquire/release, reentrant RLocks.
2. **Detection** — the seeded ABBA inversion (the acceptance drill: a
   deliberate lock-order cycle IS detected), self-deadlock raises,
   consistent ordering stays silent, unguarded-shared-access on
   note_access'd objects, violations counted in
   albedo_lockcheck_violations_total{kind=}.
3. **Integration** — the micro-batcher runs a real concurrent load with
   the sanitizer armed and stays violation-free, and every observed edge
   between catalogued locks matches the ARCHITECTURE.md lock-order
   catalog's direction (the static<->runtime round-trip).
"""

import threading

import pytest

from albedo_tpu.analysis import locksmith
from albedo_tpu.analysis.locksmith import (
    LOCKCHECK_KIND_ORDER,
    LOCKCHECK_KIND_SELF,
    LOCKCHECK_KIND_UNGUARDED,
    LockOrderViolation,
    _TrackedLock,
    named_lock,
)


@pytest.fixture
def armed(monkeypatch):
    monkeypatch.setenv("ALBEDO_LOCKCHECK", "1")
    locksmith.reset()
    yield
    locksmith.reset()


# --- 1. mechanics -------------------------------------------------------------


def test_named_lock_is_plain_when_disabled(monkeypatch):
    monkeypatch.delenv("ALBEDO_LOCKCHECK", raising=False)
    lock = named_lock("test.plain")
    assert type(lock) is type(threading.Lock())
    rlock = named_lock("test.plain.r", reentrant=True)
    assert type(rlock) is type(threading.RLock())


def test_named_lock_is_tracked_when_armed(armed):
    lock = named_lock("test.tracked")
    assert isinstance(lock, _TrackedLock)
    with lock:
        assert lock.locked()
    assert not lock.locked()
    assert lock.acquire(timeout=1.0)
    lock.release()
    assert locksmith.violations() == []


def test_reentrant_tracked_lock(armed):
    lock = named_lock("test.reentrant", reentrant=True)
    with lock:
        with lock:  # no self-deadlock report for an RLock
            pass
    assert locksmith.violations() == []


# --- 2. detection -------------------------------------------------------------


def test_consistent_order_is_silent(armed):
    a, b = named_lock("test.a"), named_lock("test.b")

    def use():
        for _ in range(5):
            with a:
                with b:
                    pass

    threads = [threading.Thread(target=use, daemon=True) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert locksmith.violations() == []
    assert ("test.a", "test.b") in locksmith.order_edges()


def test_seeded_abba_inversion_is_detected(armed):
    """The acceptance drill: a deliberate lock-order inversion must be
    caught. Thread 1 takes a->b, thread 2 takes b->a; the second ordering
    to land records an `order` violation (no actual deadlock needed — the
    graph check fires on the edge, not on the block)."""
    a, b = named_lock("test.inv.a"), named_lock("test.inv.b")
    gate = threading.Barrier(2, timeout=10.0)

    def ab():
        with a:
            with b:
                pass
        gate.wait()  # both orders recorded before the threads exit
        return None

    def ba():
        gate.wait()  # a->b lands first, deterministically
        with b:
            with a:
                pass

    t1 = threading.Thread(target=ab, daemon=True)
    t2 = threading.Thread(target=ba, daemon=True)
    t1.start(); t2.start()
    t1.join(10.0); t2.join(10.0)
    kinds = [v["kind"] for v in locksmith.violations()]
    assert LOCKCHECK_KIND_ORDER in kinds, locksmith.violations()
    v = next(v for v in locksmith.violations() if v["kind"] == LOCKCHECK_KIND_ORDER)
    assert {v["acquiring"], v["holding"]} == {"test.inv.a", "test.inv.b"}


def test_self_deadlock_raises_instead_of_hanging(armed):
    lock = named_lock("test.self")
    with lock:
        with pytest.raises(LockOrderViolation):
            lock.acquire()
    assert [v["kind"] for v in locksmith.violations()] == [LOCKCHECK_KIND_SELF]


def test_unguarded_shared_access_detected(armed):
    """note_access: two threads, at least one write, no common lock."""
    done = threading.Event()

    def writer():
        locksmith.note_access("test.shared.obj", write=True)
        done.set()

    t = threading.Thread(target=writer, daemon=True)
    t.start(); t.join(10.0)
    assert done.wait(10.0)
    locksmith.note_access("test.shared.obj", write=False)  # main thread
    kinds = [v["kind"] for v in locksmith.violations()]
    assert kinds == [LOCKCHECK_KIND_UNGUARDED]


def test_owner_scoped_records_are_per_instance(armed):
    """Two instances each writing under their OWN lock instance share no
    lock by construction (a live batcher + a reload candidate's) — with
    ``owner=`` scoping that must NOT read as an unguarded violation,
    while two threads on the SAME owner with no common lock still must."""

    class Box:
        def __init__(self, tag):
            self.lock = named_lock("test.owner.stats")

        def touch(self):
            with self.lock:
                locksmith.note_access("test.owner.state", write=True, owner=self)

    b1, b2 = Box("a"), Box("b")
    t1 = threading.Thread(target=b1.touch, daemon=True)
    t2 = threading.Thread(target=b2.touch, daemon=True)
    t1.start(); t2.start(); t1.join(10.0); t2.join(10.0)
    assert locksmith.violations() == []

    # Same owner, no common lock: still caught.
    t3 = threading.Thread(
        target=lambda: locksmith.note_access(
            "test.owner.state", write=True, owner=b1
        ),
        daemon=True,
    )
    t3.start(); t3.join(10.0)
    assert [v["kind"] for v in locksmith.violations()] == [
        LOCKCHECK_KIND_UNGUARDED
    ]


def test_thread_records_keyed_by_object_not_ident(armed):
    """CPython reuses thread idents after exit; records must not merge a
    dead worker's lockset into an unrelated new thread (which would hide a
    real race behind ``len(threads) < 2``). Keying by the Thread object
    keeps every worker distinct however idents recycle."""
    lock = named_lock("test.ident.lock")

    def guarded_writer():
        with lock:
            locksmith.note_access("test.ident.obj", write=True)

    def unguarded_writer():
        locksmith.note_access("test.ident.obj", write=True)

    # Run sequentially so CPython is FREE to hand the second thread the
    # first one's ident — with get_ident keying these could merge into one
    # record and the empty intersection would go unreported.
    t1 = threading.Thread(target=guarded_writer, daemon=True)
    t1.start(); t1.join(10.0)
    t2 = threading.Thread(target=unguarded_writer, daemon=True)
    t2.start(); t2.join(10.0)
    with locksmith._STATE.guard:
        rec = locksmith._STATE.shared["test.ident.obj"]
        assert len(rec["threads"]) == 2, "threads merged — ident-keyed records"
    assert [v["kind"] for v in locksmith.violations()] == [
        LOCKCHECK_KIND_UNGUARDED
    ]


def test_guarded_shared_access_is_silent(armed):
    lock = named_lock("test.shared.guard")

    def writer():
        with lock:
            locksmith.note_access("test.shared.ok", write=True)

    t = threading.Thread(target=writer, daemon=True)
    t.start(); t.join(10.0)
    with lock:
        locksmith.note_access("test.shared.ok", write=True)
    assert locksmith.violations() == []


def test_reentrant_locked_mirrors_untracked_rlock(armed):
    """The wrapper promises API parity with what named_lock would return
    untracked: RLock has no .locked() before Python 3.12, so the tracked
    flavor must raise AttributeError there, not crash mid-check."""
    r = named_lock("test.re.locked", reentrant=True)
    if hasattr(threading.RLock(), "locked"):
        assert r.locked() is False
    else:
        with pytest.raises(AttributeError):
            r.locked()


def test_soak_invariant_reports_each_violation_once(armed, tmp_path):
    """locksmith.violations() is cumulative; the soak invariant sweep must
    attribute a violation to the cycle that observed it, not re-report it
    in every later cycle."""
    from albedo_tpu.chaos.soak import check_invariants

    check_invariants._lockcheck_seen = 0
    a, b = named_lock("test.soak.a"), named_lock("test.soak.b")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    first = [v for v in check_invariants(tmp_path) if "locksmith" in v]
    second = [v for v in check_invariants(tmp_path) if "locksmith" in v]
    assert len(first) == 1 and second == [], (first, second)
    # A reset between cycles (fresh sanitizer epoch) starts the cursor over.
    locksmith.reset()
    with b:
        with a:
            pass
    with a:
        with b:
            pass
    third = [v for v in check_invariants(tmp_path) if "locksmith" in v]
    assert len(third) == 1, third


def test_violations_counted_in_metric(armed):
    from albedo_tpu.utils import events

    counter = events.global_counter(
        events.LOCKCHECK_VIOLATIONS_TOTAL, "", ("kind",)
    )
    before = counter.value(kind=LOCKCHECK_KIND_ORDER)
    a, b = named_lock("test.m.a"), named_lock("test.m.b")
    with a:
        with b:
            pass
    with b:
        with a:  # same-thread inversion: still an ABBA shape
            pass
    assert any(v["kind"] == LOCKCHECK_KIND_ORDER for v in locksmith.violations())
    assert counter.value(kind=LOCKCHECK_KIND_ORDER) == before + 1


def test_reset_clears_everything(armed):
    a, b = named_lock("test.r.a"), named_lock("test.r.b")
    with a:
        with b:
            pass
    assert locksmith.order_edges()
    locksmith.reset()
    assert locksmith.order_edges() == set()
    assert locksmith.violations() == []


# --- 3. integration -----------------------------------------------------------


@pytest.fixture(scope="module")
def als_artifacts():
    jax = pytest.importorskip("jax")  # noqa: F841
    from albedo_tpu.datasets import synthetic_tables
    from albedo_tpu.models.als import ImplicitALS

    tables = synthetic_tables(n_users=60, n_items=40, mean_stars=6, seed=7)
    matrix = tables.star_matrix()
    model = ImplicitALS(rank=8, max_iter=2, seed=0).fit(matrix)
    return matrix, model


def test_batcher_under_locksmith_is_violation_free(armed, als_artifacts):
    """The real micro-batcher (its locks created through named_lock AFTER
    the env is set) under a concurrent submit load: no inversions, no
    self-deadlocks — the tier-1 copy of the `make sanitize` invariant."""
    import numpy as np

    from albedo_tpu.serving.batcher import MicroBatcher

    matrix, model = als_artifacts
    batcher = MicroBatcher(model, window_ms=2.0)
    assert isinstance(batcher._exec_lock, _TrackedLock)
    try:
        def load(seed):
            rng = np.random.default_rng(seed)
            futs = [
                batcher.submit(int(rng.integers(0, matrix.n_users)), 5)
                for _ in range(10)
            ]
            for f in futs:
                f.result(timeout=30)
            batcher.retry_after_s()
            _ = batcher.mean_batch_size

        threads = [
            threading.Thread(target=load, args=(i,), daemon=True)
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)
    finally:
        batcher.stop()
    assert locksmith.violations() == []


def test_observed_edges_match_the_catalog(armed, als_artifacts):
    """The static<->runtime round-trip: any acquisition edge the sanitizer
    observed between two locks that BOTH appear in the ARCHITECTURE.md
    lock-order catalog must match a catalogued row's direction. Edges
    touching uncatalogued locks are out of scope (the catalog only
    declares orders for pairs that nest)."""
    import numpy as np

    from albedo_tpu.analysis.core import default_tree
    from albedo_tpu.analysis.rules_concurrency import lock_order_catalog
    from albedo_tpu.serving.batcher import MicroBatcher

    matrix, model = als_artifacts
    batcher = MicroBatcher(model, window_ms=1.0)
    try:
        futs = [batcher.submit(u, 5) for u in range(8)]
        for f in futs:
            f.result(timeout=30)
    finally:
        batcher.stop()

    catalog = lock_order_catalog(default_tree())
    assert catalog, "the ARCHITECTURE.md lock-order catalog is missing"
    names_in_catalog = {n for pair in catalog for n in pair}
    for outer, inner in locksmith.order_edges():
        if outer in names_in_catalog and inner in names_in_catalog:
            assert (outer, inner) in catalog, (
                f"observed acquisition order {outer} -> {inner} is not a "
                f"catalogued direction — either catalog it or it inverts "
                f"a declared row"
            )
