"""Bench harness units: the analytic ALS FLOP model and failure-path helpers.

The bench contract (VERDICT round 1): probe the backend before touching the
device, emit ONE structured JSON line on success or failure, and report MFU
from an analytic FLOP model rather than claims in commit messages.
"""

import json
import subprocess
import sys

import numpy as np
import pytest

import bench
from albedo_tpu.datasets.synthetic import synthetic_stars


def test_als_fit_flops_scaling():
    m = synthetic_stars(n_users=300, n_items=200, mean_stars=10, seed=1)
    one = bench.als_fit_flops(m, rank=8, iters=1, batch_size=64, max_entries=1 << 16)
    ten = bench.als_fit_flops(m, rank=8, iters=10, batch_size=64, max_entries=1 << 16)
    assert one["flops"] > 0
    assert ten["flops"] == 10 * one["flops"]
    assert ten["per_iter"] == one["per_iter"]
    # Padding can only add entries; each nnz is bucketed twice per iteration
    # (CSR user-solve + CSC item-solve), hence logical_entries = 2*nnz.
    assert one["logical_entries"] == 2 * one["logical_nnz"]
    assert one["padded_entries"] >= one["logical_entries"]
    # The Gramian term dominates and scales ~k^2: rank 16 >= ~3x rank 8.
    big = bench.als_fit_flops(m, rank=16, iters=1, batch_size=64, max_entries=1 << 16)
    assert big["flops"] > 3 * one["flops"]


def test_peak_flops_lookup():
    peak, src = bench.peak_flops_for("TPU v4", measured=1.0)
    assert peak == 275e12 and "v4" in src
    peak, src = bench.peak_flops_for("weird accelerator", measured=123.0)
    assert peak == 123.0 and "measured" in src


def test_stray_pid_scan_runs():
    pids = bench.stray_accelerator_pids()
    assert isinstance(pids, list)


def test_bench_error_record_is_json(tmp_path):
    """A broken backend must yield rc!=0 and ONE parseable JSON error line
    (round-1 failure mode: bare stack trace, nothing parseable)."""
    proc = subprocess.run(
        [sys.executable, str(bench.__file__)],
        capture_output=True, text=True, timeout=120,
        env={
            "PATH": "/usr/bin:/bin",
            # Force the probe subprocess to die instantly.
            "ALBEDO_BENCH_PLATFORM": "definitely_not_a_platform",
            "ALBEDO_BENCH_PROBE_TIMEOUT": "30",
        },
    )
    assert proc.returncode != 0
    line = proc.stdout.strip().splitlines()[-1]
    record = json.loads(line)
    assert record["stage"] == "backend_probe"
    assert record["value"] is None and record["error"]


def test_watchdog_preserves_flagship_record():
    """If the watchdog fires AFTER the ALS headline is computed (a wedged or
    crawling ranker stage), the bench must exit 0 with the GOOD flagship
    record as its last line — the driver parses the last line only."""
    import os

    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "ALBEDO_BENCH_PLATFORM": "cpu",
        "ALBEDO_BENCH_USERS": "300", "ALBEDO_BENCH_ITEMS": "200",
        "ALBEDO_BENCH_ITERS": "1", "ALBEDO_BENCH_MEAN_STARS": "6",
        "ALBEDO_BENCH_GEMM_N": "256", "ALBEDO_BENCH_GEMM_CHAIN": "2",
        "ALBEDO_BENCH_HBM_FLOATS": str(1 << 20),
        "ALBEDO_BENCH_BREAKDOWN": "0",
        "ALBEDO_BENCH_RANKER": "1",
        # Deterministic fault injection: stall the ranker past the watchdog.
        "ALBEDO_BENCH_FAULT_SLEEP": "3600",
        "ALBEDO_BENCH_TIMEOUT": "35",
    })
    proc = subprocess.run(
        [sys.executable, str(bench.__file__)],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert proc.returncode == 0, proc.stdout[-500:] + proc.stderr[-500:]
    record = json.loads(proc.stdout.strip().splitlines()[-1])
    assert record["metric"] == "als_train_wallclock_rank50_iter26"
    assert record["value"] is not None and record["value"] > 0
    assert "watchdog" in (record["ranker_error"] or "")
    assert record["status"] == "partial"  # the documented partial contract


def test_w2v_refscale_record_shape(monkeypatch):
    """Tiny-scale run of the reference-scale W2V bench: the record must state
    corpus volume and throughput so the multiplier is priced per token."""
    monkeypatch.setenv("ALBEDO_BENCH_W2V_TOKENS", "20000")
    monkeypatch.setenv("ALBEDO_BENCH_W2V_VOCAB", "500")
    rec = bench.w2v_refscale_bench()
    assert rec["metric"] == "w2v_train_wallclock_refscale"
    assert rec["corpus_tokens"] == 20000
    assert rec["value"] > 0 and rec["epoch_tokens_per_s"] > 0
    assert rec["vocab_size"] > 0
    assert "scale_note" in rec and "unpublished" in rec["scale_note"]
    assert rec["backend"] and rec["device_kind"]
    assert rec["virtual_devices"] >= 0


def test_watchdog_partial_status_field():
    """The watchdog re-emit carries status=partial (ADVICE r4 #1 contract)."""
    record = bench.error_record("x", "y")
    assert "status" not in record  # hard failures carry stage/error instead
    # The error-record shape is pinned by the failure contract: hardware
    # provenance is a success-record stamp only.
    for key in ("backend", "virtual_devices"):
        assert key not in record


def test_hardware_fields_shape(monkeypatch):
    """Every scenario record carries hardware provenance: backend,
    device_kind, and the forced-virtual device count (0 on real chips)."""
    fields = bench.hardware_fields()
    assert set(fields) == {"backend", "device_kind", "virtual_devices"}
    assert fields["backend"] and fields["device_kind"]
    # Under the test harness CPU is forced to 8 virtual devices; either way
    # the field is a non-negative int, and 0 whenever nothing is forced.
    assert isinstance(fields["virtual_devices"], int)
    assert fields["virtual_devices"] >= 0
    monkeypatch.setenv("XLA_FLAGS", "")
    assert bench.hardware_fields()["virtual_devices"] == 0


@pytest.mark.slow
def test_retrieval_scenario_record_shape(monkeypatch):
    """Micro-size run of the `retrieval` scenario: the parity gate must
    actually run, and the record must carry both arms' latencies, the
    speedup, and the bytes-scanned GB/s model (the RETRIEVAL_r01 shape)."""
    monkeypatch.setenv("ALBEDO_RETRIEVAL_USERS", "300")
    monkeypatch.setenv("ALBEDO_RETRIEVAL_ITEMS", "200")
    monkeypatch.setenv("ALBEDO_RETRIEVAL_CONCURRENCY", "8")
    monkeypatch.setenv("ALBEDO_RETRIEVAL_DURATION", "0.5")
    monkeypatch.setenv("ALBEDO_RETRIEVAL_TRIALS", "1")
    rec = bench.retrieval_bench()
    assert rec["metric"] == "retrieval_candidates_rps"
    assert rec["parity_checked"] > 0
    assert set(rec["sources"]) == {"als", "content", "tfidf"}
    for arm in ("bank", "fanout"):
        assert rec[arm]["rps"] > 0 and rec[arm]["p99_ms"] >= rec[arm]["p50_ms"]
    assert rec["speedup_vs_fanout"] > 0
    assert rec["bytes_scanned_per_query"] == sum(
        s["rows"] * s["dim"] * 4 for s in rec["sources"].values()
    )
    assert rec["backend"] and rec["device_kind"]
    assert rec["virtual_devices"] >= 0


@pytest.mark.slow
def test_scale_scenario_record_shape(monkeypatch, tmp_path):
    """Micro-size run of the `scale` weak-scaling scenario: the record must
    carry the full curve (per-sweep wall-clock, GB/s per chip vs roofline,
    efficiency), the per-stage overlap accounting (explicit warm + separate
    compile reporting, upload-hidden fraction, interleaved sync trials, the
    ring-phase probe), the largest-fittable estimates for both assembly
    modes, and land in MULTICHIP_r07.json."""
    out = tmp_path / "MULTICHIP_r07.json"
    monkeypatch.setenv("ALBEDO_SCALE_USERS_PER_CHIP", "200")
    monkeypatch.setenv("ALBEDO_SCALE_ITEMS", "100")
    monkeypatch.setenv("ALBEDO_SCALE_MEAN_STARS", "5")
    monkeypatch.setenv("ALBEDO_SCALE_SWEEPS", "1")
    monkeypatch.setenv("ALBEDO_SCALE_DEVICES", "1,2")
    monkeypatch.setenv("ALBEDO_SCALE_OUT", str(out))
    rec = bench.scale_bench()
    assert rec["metric"] == "sharded_als_weak_scaling"
    assert [row["n_devices"] for row in rec["weak_scaling"]] == [1, 2]
    for row in rec["weak_scaling"]:
        assert row["per_sweep_s"] > 0
        assert row["achieved_gbps_per_chip"] > 0
        assert 0 <= row["roofline_frac"] <= 1
        assert row["streamed_buckets_per_sweep"] > 0
        assert row["n_users"] == 200 * row["n_devices"]  # fixed work per chip
        # Compile is warmed out of the trials and reported separately —
        # a trial median can never land on a compile-bearing sweep.
        assert row["compile"]["warm_sweeps"] >= 2
        assert row["compile"]["warmup_compile_s"] >= 0
        ov = row["overlap"]
        assert ov["sync_per_sweep_s"] > 0
        assert ov["upload_s_per_sweep"] >= 0
        assert ov["prefetch_wait_s_per_sweep"] >= 0
        if ov["upload_hidden_frac"] is not None:
            assert 0 <= ov["upload_hidden_frac"] <= 1
        # Elasticity cost is visible, not silent: per-rung mesh events +
        # the measured sweep-boundary checkpoint overhead.
        me = row["mesh_events"]
        assert me["losses"] == 0 and me["resumes"] == 0
        assert me["checkpoint_s"] > 0
        assert me["checkpoint_overhead_frac_per_sweep"] >= 0
    assert rec["weak_scaling"][0]["efficiency_vs_1chip"] == 1.0
    assert rec["roofline_gbps_per_chip"] == 285.0
    assert rec["pipeline"] == "on"
    probe = rec["ring_overlap_probe"]
    assert "error" in probe or (
        probe["overlapped_per_sweep_s"] > 0 and probe["sync_per_sweep_s"] > 0
    )
    for mode in ("allgather", "ring"):
        assert rec["largest_fittable"][mode]["max_users"] > 0
    assert json.loads(out.read_text())["metric"] == "sharded_als_weak_scaling"
    assert rec["backend"] and rec["device_kind"]
    assert rec["virtual_devices"] >= 0


@pytest.mark.slow
def test_scoring_scenario_record_shape(monkeypatch, tmp_path):
    """Micro-size run of the `scoring` scenario: the record must carry
    users/s per chip, chip-seconds per million users, the canary score the
    publish was gated on, and the analytic 10M x 1M out-of-core admission
    (both rungs' bytes + the ladder verdict), and land in SCORING_r01.json."""
    out = tmp_path / "SCORING_r01.json"
    monkeypatch.setenv("ALBEDO_SCORING_USERS", "150")
    monkeypatch.setenv("ALBEDO_SCORING_ITEMS", "100")
    monkeypatch.setenv("ALBEDO_SCORING_SHARD_USERS", "64")
    monkeypatch.setenv("ALBEDO_SCORING_K", "10")
    monkeypatch.setenv("ALBEDO_SCORING_OUT", str(out))
    rec = bench.scoring_bench()
    assert rec["metric"] == "score_all_users_per_s_per_chip"
    assert rec["value"] > 0
    assert rec["chip_seconds_per_million_users"] > 0
    assert rec["users_scored"] > 0 and rec["rows_spilled"] > 0
    assert rec["n_shards"] >= 2  # shard_users=64 over >=100 matrix users
    assert 0.0 <= rec["canary_ndcg30"] <= 1.0
    assert rec["admission"]["workload"].startswith("score")
    ooc = rec["out_of_core_10m_x_1m"]
    assert ooc["n_users"] == 10_000_000 and ooc["n_items"] == 1_000_000
    # The streamed rung trades transient query memory for resident tables:
    # its footprint must be strictly cheaper than the resident rung's.
    assert 0 < ooc["streamed_bytes"] < ooc["resident_bytes"]
    assert ooc["verdict"]["workload"] == "score"
    assert ooc["est_chip_hours"] > 0
    assert rec["backend"] and rec["device_kind"]
    assert rec["virtual_devices"] >= 0
    assert json.loads(out.read_text())["metric"] == "score_all_users_per_s_per_chip"
