"""The ingest data-quality firewall: rule catalog, policy matrix, quarantine
sidecars, matrix invariants, and the lineage fingerprint
(``datasets/validate.py``; ARCHITECTURE.md "Data quality")."""

import numpy as np
import pandas as pd
import pytest

from albedo_tpu.datasets import synthetic_tables
from albedo_tpu.datasets.artifacts import artifact_path
from albedo_tpu.datasets.star_matrix import StarMatrix
from albedo_tpu.datasets.validate import (
    DataValidationError,
    dense_user_threshold,
    matrix_fingerprint,
    validate_matrix,
    validate_starring,
)
from albedo_tpu.utils import events, faults

NOW = 1_700_000_000.0


def clean_frame(n=6) -> pd.DataFrame:
    return pd.DataFrame({
        "user_id": np.arange(n, dtype=np.int64) % 3 + 100,
        "repo_id": np.arange(n, dtype=np.int64) + 500,
        "starred_at": NOW - np.arange(n, dtype=np.float64) * 1e4,
        "starring": np.ones(n),
    })


def poisoned_frame() -> tuple[pd.DataFrame, dict[str, int]]:
    """One frame seeding every violation class, plus the expected counts."""
    s = clean_frame(6)
    bad = pd.DataFrame({
        # dangling ids (vocabulary = the clean frame's own ids)
        "user_id": [999, 100, 100, 101, 102, 101, 102],
        "repo_id": [500, 9999, 501, 502, 503, 504, 505],
        "starred_at": [NOW, NOW, NOW, np.nan, -5.0, NOW + 10 * 86_400, NOW],
        "starring": [1.0, 1.0, 0.0, -2.0, np.nan, 1.0, 1.0],
    })
    # (102, 505) duplicates a clean-frame pair with a newer VALID row — the
    # earlier clean row is the flagged duplicate. (101, 504)'s newer
    # duplicate is corrupt (future timestamp): it falls under its own rule
    # and must NOT cost the pair its valid clean row.
    frame = pd.concat([s, bad], ignore_index=True)
    expected = {
        "dangling_user": 1,
        "dangling_repo": 1,
        "duplicate_pair": 1,
        "nonpositive_confidence": 3,
        "timestamp_range": 3,
    }
    return frame, expected


def _vocab(frame):
    return dict(
        user_vocab=np.array([100, 101, 102], np.int64),
        repo_vocab=np.arange(500, 520, dtype=np.int64),
        now=NOW,
    )


def test_clean_frame_passes_all_rules():
    s = clean_frame()
    out, report = validate_starring(s, policy="repair", **_vocab(s))
    assert report.violations == {}
    assert report.rows_in == report.rows_out == len(s)
    pd.testing.assert_frame_equal(out, s)


def test_every_rule_fires_and_counts():
    frame, expected = poisoned_frame()
    out, report = validate_starring(frame, policy="repair", **_vocab(frame))
    for rule, count in expected.items():
        assert report.violations[rule] == count, rule
        assert events.data_violations.value(rule=rule) == count
    # Survivors: no flagged row, and the duplicate kept the LAST occurrence.
    assert len(out) == report.rows_out < report.rows_in
    assert not (out["starring"] <= 0).any()
    kept_505 = out[(out["user_id"] == 102) & (out["repo_id"] == 505)]
    assert kept_505["starred_at"].tolist() == [NOW]  # the newer valid dup won
    # The corrupt newer duplicate of (101, 504) was dropped under its own
    # rule; the valid clean row for the pair survived.
    assert len(out[(out["user_id"] == 101) & (out["repo_id"] == 504)]) == 1


def test_dense_user_poison_flagged(monkeypatch):
    monkeypatch.setenv("ALBEDO_DENSE_USER_MIN", "5")
    monkeypatch.setenv("ALBEDO_DENSE_USER_FRAC", "0.8")
    # Poison user 7 stars 9 of the 10 distinct repos (threshold = 8); user 8
    # stars 2 and stays clean.
    s = pd.DataFrame({
        "user_id": [7] * 9 + [8, 8],
        "repo_id": list(range(500, 509)) + [509, 500],
        "starred_at": [NOW] * 11,
        "starring": [1.0] * 11,
    })
    out, report = validate_starring(s, policy="repair", now=NOW)
    assert report.violations == {"dense_user": 9}
    assert out["user_id"].tolist() == [8, 8]


def test_dense_user_counts_distinct_repos_not_raw_rows(monkeypatch):
    monkeypatch.setenv("ALBEDO_DENSE_USER_MIN", "5")
    monkeypatch.setenv("ALBEDO_DENSE_USER_FRAC", "0.8")
    # User 7's crawl logged each of 4 distinct stars three times: 12 raw rows
    # exceed the threshold (8 of the 10-repo catalog) but only 4 distinct
    # repos do not — duplicated rows must not make a legitimate user poison.
    s = pd.DataFrame({
        "user_id": [7] * 12 + [8] * 5 + [9] * 5,
        "repo_id": [500, 501, 502, 503] * 3 + list(range(500, 505))
        + list(range(505, 510)),
        "starred_at": NOW - np.arange(22, dtype=np.float64),
        "starring": [1.0] * 22,
    })
    out, report = validate_starring(s, policy="repair", now=NOW)
    assert "dense_user" not in report.violations
    assert report.violations == {"duplicate_pair": 8}
    assert sorted(out[out["user_id"] == 7]["repo_id"]) == [500, 501, 502, 503]
    frame, expected = poisoned_frame()
    with pytest.raises(DataValidationError) as ei:
        validate_starring(frame, policy="strict", **_vocab(frame))
    # ALL rules evaluated before raising — the report is complete, not
    # first-failure-only.
    for rule in expected:
        assert rule in ei.value.report.violations, rule


def test_off_policy_is_passthrough():
    frame, _ = poisoned_frame()
    out, report = validate_starring(frame, policy="off", **_vocab(frame))
    assert out is frame
    assert report.violations == {}
    assert events.data_violations.value(rule="dangling_user") == 0


def test_unknown_policy_rejected():
    with pytest.raises(ValueError, match="unknown data policy"):
        validate_starring(clean_frame(), policy="paranoid")


def test_duplicate_pair_keeps_most_recent():
    s = pd.DataFrame({
        "user_id": [1, 1, 1],
        "repo_id": [7, 7, 7],
        "starred_at": [100.0, 300.0, 200.0],
        "starring": [1.0, 1.0, 1.0],
    }).sort_values("starred_at", kind="stable")
    out, report = validate_starring(s, policy="repair", now=NOW)
    assert report.violations["duplicate_pair"] == 2
    assert out["starred_at"].tolist() == [300.0]


def test_repair_writes_rule_tagged_quarantine_sidecar():
    frame, _ = poisoned_frame()
    _, report = validate_starring(
        frame, policy="repair", quarantine_name="t-starring", **_vocab(frame)
    )
    assert report.quarantined_to == "t-starring.quarantine-1.csv"
    side = pd.read_csv(artifact_path(report.quarantined_to))
    assert len(side) == report.rows_in - report.rows_out
    assert "rule" in side.columns and (side["rule"] != "").all()
    # A row tripping several rules carries them comma-joined.
    multi = side[side["rule"].str.contains(",")]
    assert len(multi) >= 1
    # A second pass numbers the next sidecar, never overwrites evidence.
    _, r2 = validate_starring(
        frame, policy="repair", quarantine_name="t-starring", **_vocab(frame)
    )
    assert r2.quarantined_to == "t-starring.quarantine-2.csv"


def test_dense_user_threshold_floor_and_frac(monkeypatch):
    monkeypatch.delenv("ALBEDO_DENSE_USER_FRAC", raising=False)
    monkeypatch.delenv("ALBEDO_DENSE_USER_MIN", raising=False)
    # Tiny catalogs stay under the floor: an enthusiast is not poison.
    assert dense_user_threshold(10) == 20
    # Large catalogs scale by fraction.
    assert dense_user_threshold(1000) == 800
    assert dense_user_threshold(1000, frac=0.5, floor=3) == 500


def test_fault_site_fires_in_validation_pass():
    faults.arm("data.validate", kind="error", at=1)
    with pytest.raises(faults.FaultInjected):
        validate_starring(clean_frame(), policy="repair", now=NOW)
    # Policy off never reaches the site (the firewall is bypassed).
    faults.arm("data.validate", kind="error", at=1)
    validate_starring(clean_frame(), policy="off", now=NOW)


def test_synthetic_tables_are_clean_through_validated_matrix():
    tables = synthetic_tables(n_users=60, n_items=40, mean_stars=6, seed=3)
    matrix, report = tables.validated_star_matrix(policy="repair", now=NOW)
    assert report.violations == {}
    # Byte-identical to the unvalidated build on clean data.
    ref = tables.star_matrix()
    np.testing.assert_array_equal(matrix.rows, ref.rows)
    np.testing.assert_array_equal(matrix.cols, ref.cols)
    np.testing.assert_array_equal(matrix.vals, ref.vals)


def test_validated_matrix_drops_dangling_rows():
    tables = synthetic_tables(n_users=60, n_items=40, mean_stars=6, seed=3)
    dirty = tables.starring.copy()
    dirty.loc[dirty.index[0], "user_id"] = -1  # not in user_info
    tables = type(tables)(
        user_info=tables.user_info, repo_info=tables.repo_info,
        starring=dirty, relation=tables.relation,
    )
    matrix, report = tables.validated_star_matrix(policy="repair", now=NOW)
    assert report.violations == {"dangling_user": 1}
    assert -1 not in matrix.user_ids
    with pytest.raises(DataValidationError):
        tables.validated_star_matrix(policy="strict", now=NOW)


def test_repair_matrix_matches_reference_build_on_dirty_data():
    """The from_codes fast path must be byte-identical to from_interactions
    over the surviving rows, even when repair dropped rows from several
    rules (codes are a strict subset of the factorization's range)."""
    tables = synthetic_tables(n_users=60, n_items=40, mean_stars=6, seed=7)
    dirty = tables.starring.copy()
    dirty.loc[dirty.index[0], "user_id"] = -1          # dangling_user
    dirty.loc[dirty.index[1], "repo_id"] = -2          # dangling_repo
    dirty.loc[dirty.index[2], "starring"] = 0.0        # nonpositive_confidence
    dirty.loc[dirty.index[3], "starred_at"] = NOW * 9  # timestamp_range
    dup = dirty.iloc[[4]].copy()
    dup["starred_at"] = NOW  # duplicate_pair: valid and newer than any synthetic row
    dirty = pd.concat([dirty, dup], ignore_index=True)
    tables = type(tables)(
        user_info=tables.user_info, repo_info=tables.repo_info,
        starring=dirty, relation=tables.relation,
    )
    matrix, report = tables.validated_star_matrix(policy="repair", now=NOW)
    for rule in ("dangling_user", "dangling_repo", "nonpositive_confidence",
                 "timestamp_range", "duplicate_pair"):
        assert report.violations[rule] >= 1, rule

    from albedo_tpu.datasets.validate import validate_starring as _vs

    s = dirty.sort_values("starred_at", kind="stable")
    clean, _ = _vs(
        s,
        user_vocab=tables.user_info["user_id"].to_numpy(np.int64),
        repo_vocab=tables.repo_info["repo_id"].to_numpy(np.int64),
        now=NOW, policy="repair",
    )
    ref = StarMatrix.from_interactions(
        raw_users=clean["user_id"].to_numpy(np.int64),
        raw_items=clean["repo_id"].to_numpy(np.int64),
    )
    np.testing.assert_array_equal(matrix.user_ids, ref.user_ids)
    np.testing.assert_array_equal(matrix.item_ids, ref.item_ids)
    np.testing.assert_array_equal(matrix.rows, ref.rows)
    np.testing.assert_array_equal(matrix.cols, ref.cols)
    np.testing.assert_array_equal(matrix.vals, ref.vals)


# --- matrix-level invariants --------------------------------------------------


def _matrix(rows, cols, vals, n_users=4, n_items=3) -> StarMatrix:
    return StarMatrix(
        user_ids=np.arange(n_users, dtype=np.int64),
        item_ids=np.arange(n_items, dtype=np.int64),
        rows=np.asarray(rows, np.int32),
        cols=np.asarray(cols, np.int32),
        vals=np.asarray(vals, np.float32),
    )


def test_matrix_invariants_clean():
    report = validate_matrix(_matrix([0, 1], [0, 1], [1.0, 2.0]), policy="strict")
    assert report.violations == {}


def test_matrix_invariants_flag_oob_and_degenerate():
    m = _matrix([0, 1, 5], [0, 1, 0], [1.0, 0.0, 1.0])
    report = validate_matrix(m, policy="repair")
    assert report.violations["index_out_of_range"] == 1
    assert report.violations["nonpositive_confidence"] == 1
    # user 1's only entry is zero-confidence: a degenerate all-zero row.
    assert report.violations["all_zero_row"] == 1
    assert events.data_violations.value(rule="all_zero_row") == 1
    with pytest.raises(DataValidationError):
        validate_matrix(m, policy="strict")


def test_matrix_fingerprint_tracks_content():
    a = _matrix([0, 1], [0, 1], [1.0, 2.0])
    b = _matrix([0, 1], [0, 1], [1.0, 2.0])
    c = _matrix([0, 1], [0, 1], [1.0, 3.0])
    assert matrix_fingerprint(a) == matrix_fingerprint(b)
    assert matrix_fingerprint(a) != matrix_fingerprint(c)
