"""graftlint concurrency tier (R6-R8) + thread-root discovery + parse cache.

Mirrors tests/test_graftlint.py's layers for the new tier:

1. **Fixture proofs** — each rule fires on its committed ``*_bad`` shapes
   and stays silent on the near-identical ``*_ok``/pragma'd ones.
2. **Discovery** — thread spawn sites on the real tree (the inventory's
   ground truth), instantiation-edge reachability, and the derived hot
   roots that replaced the hand-listed `DEFAULT_HOT_ROOTS` thread entries.
3. **Anchors** — the real tree's lock inventory, lock-order catalog, and
   thread inventory round-trip against ARCHITECTURE.md.
4. **Parse cache** — warm loads reuse unchanged files, mtime/size changes
   invalidate, corrupt caches are ignored.
"""

import os
import time
from pathlib import Path

from albedo_tpu.analysis import ProjectTree, collect_findings, default_tree
from albedo_tpu.analysis.callgraph import derived_thread_roots
from albedo_tpu.analysis.core import CACHE_NAME
from albedo_tpu.analysis.rules_concurrency import (
    lock_inventory,
    lock_order_catalog,
    thread_inventory_doc,
)
from albedo_tpu.analysis.rules_device import DEFAULT_HOT_ROOTS, hot_roots

FIXTURES = Path(__file__).resolve().parent.parent / (
    "albedo_tpu/analysis/fixtures"
)


def run_rule(name: str, rule_id: str):
    return collect_findings(ProjectTree.load(FIXTURES / name), rule_ids=[rule_id])


# --- 1. fixture proofs --------------------------------------------------------


def test_shared_state_guard_fires_on_fixture():
    findings = run_rule("shared_state", "shared-state-guard")
    msgs = [f.message for f in findings]
    assert any("self.processed" in m for m in msgs), msgs
    assert any("`_COUNT`" in m and "bump_unguarded" in m for m in msgs), msgs
    # A locked intra-class caller of the thread target must not launder
    # the bare thread entry away (Restarter.restart holds the lock, the
    # spawned thread holds nothing).
    assert any("self.ticks" in m and "Restarter" in m for m in msgs), msgs
    # Guarded writes (lexical + the *_locked caller-intersection pattern),
    # primitives (queue/Event), publish-once __init__ state, the guarded
    # global, and the pragma'd counter all stay silent.
    joined = "\n".join(msgs)
    for silent in ("latency", "_results", "config", "_TOTAL", "debug_marks", "_q"):
        assert silent not in joined, (silent, msgs)
    assert len(findings) == 3, [f.render() for f in findings]


def test_lock_discipline_fires_on_fixture():
    findings = run_rule("lock_discipline", "lock-discipline")
    msgs = [f.message for f in findings]

    def has(*subs):
        return any(all(s in m for s in subs) for m in msgs)

    assert has("`_bare`", "named_lock")
    assert has("`fix.inner` -> `fix.outer`", "INVERTS")
    assert has("`fix.outer` -> `fix.stray`", "not in the ARCHITECTURE.md")
    assert has("bare `.acquire()`", "`fix.outer`")
    assert has("bare `.release()`", "`fix.outer`")
    assert has("`fix.ghost`", "stale catalog row")
    # The declared direction — lexical AND through the one-hop call — and
    # the named_lock creations stay silent. So does the joined non-daemon
    # worker: the daemon obligation lives in R8, conditioned on the spawn
    # lacking a join path — R7 must not second-guess a joined thread.
    assert not has("`fix.outer` -> `fix.inner`")
    assert not has("daemon")
    assert len(findings) == 6, [f.render() for f in findings]


def test_executor_lifecycle_fires_on_fixture():
    findings = run_rule("executor_lifecycle", "executor-lifecycle")
    msgs = [f.message for f in findings]

    def has(*subs):
        return any(all(s in m for s in subs) for m in msgs)

    assert has("executor constructed without a binding")
    assert has("executor bound to `_pool`", "no reachable `.shutdown()`")
    assert has("thread bound to `_thread` is never joined")
    assert has("fire-and-forget non-daemon")
    assert has("`fix-forgotten`", "missing from")
    assert has("`fix-phantom`", "stale row")
    # OwnedPool (close() shuts down), the with-managed pool, Looper's
    # joined thread, and serve_ok's handed-off+joined server stay silent.
    assert not has("`fix-server`")
    assert not has("`fix-looper`")
    assert len(findings) == 6, [f.render() for f in findings]


# --- 2. thread-root discovery on the real tree --------------------------------


def test_discovery_sees_every_known_spawn_site():
    tree = default_tree()
    spawns = tree.thread_spawns()
    threads = {s.name for s in spawns if s.kind == "thread"}
    assert threads == {
        "albedo-micro-batcher", "albedo-http", "albedo-reload-watch",
        "albedo-sighup-reload", "albedo-shard-prefetch",
        "albedo-elastic-chunk", "albedo-loadgen-pacer",
        "albedo-loadgen-worker",
    }
    # Every Thread spawn in the tree is daemonized (the PR 12 invariant).
    assert all(s.daemon for s in spawns if s.kind == "thread")
    # Executor constructions: the pipeline pools, the crawler pool, and the
    # with-managed host-side pools.
    ex_modules = {s.module for s in spawns if s.kind == "executor"}
    assert "albedo_tpu/serving/pipeline.py" in ex_modules
    assert "albedo_tpu/store/crawler.py" in ex_modules
    assert "albedo_tpu/datasets/ragged.py" in ex_modules


def test_prefetcher_run_is_a_derived_root_not_hand_listed():
    """The satellite: PR 13's hand-patched thread entries are now derived.
    `_BucketPrefetcher._run` must NOT be in the static tuple, and MUST be
    found by discovery through fit -> _half_sweep_pipelined ->
    _BucketPrefetcher() -> Thread(target=self._run)."""
    assert ("albedo_tpu/parallel/als.py", "_BucketPrefetcher._run") \
        not in DEFAULT_HOT_ROOTS
    assert ("albedo_tpu/parallel/als.py", "ShardedALSFit._half_sweep_pipelined") \
        not in DEFAULT_HOT_ROOTS
    tree = default_tree()
    derived = derived_thread_roots(tree, list(DEFAULT_HOT_ROOTS), tree.callgraph())
    assert ("albedo_tpu/parallel/als.py", "_BucketPrefetcher._run") in derived
    roots = hot_roots(tree)
    assert ("albedo_tpu/parallel/als.py", "_BucketPrefetcher._run") in roots
    # And the driver loop stays covered through plain reachability.
    reached = {
        (f.module, f.qualname)
        for f in tree.callgraph().reachable(roots)
    }
    assert ("albedo_tpu/parallel/als.py", "ShardedALSFit._half_sweep_pipelined") \
        in reached


def test_instantiation_edges_reach_init():
    """`Foo(...)` resolves to `Foo.__init__` — without this edge the
    prefetcher's spawn site (inside its __init__) would be invisible."""
    tree = default_tree()
    graph = tree.callgraph()
    reached = {
        (f.module, f.qualname)
        for f in graph.reachable([("albedo_tpu/parallel/als.py", "ShardedALSFit.fit")])
    }
    assert ("albedo_tpu/parallel/als.py", "_BucketPrefetcher.__init__") in reached


def test_fixture_thread_roots_follow_into_spawned_code(tmp_path):
    """R2 through a spawned thread: a hidden sync inside a thread target
    spawned from a hot root is flagged without hand-listing the target."""
    root = tmp_path / "repo"
    (root / "albedo_tpu/models").mkdir(parents=True)
    (root / "albedo_tpu/models/hot.py").write_text(
        "import threading\n"
        "\n"
        "\n"
        "class Fit:\n"
        "    def fit(self, xs):\n"
        "        t = threading.Thread(target=self._feed, args=(xs,),\n"
        "                             name='fix-feed', daemon=True)\n"
        "        t.start()\n"
        "        t.join()\n"
        "\n"
        "    def _feed(self, xs):\n"
        "        for x in xs:\n"
        "            x.tolist()\n"
    )
    from albedo_tpu.analysis.rules_device import HiddenHostSync

    tree = ProjectTree.load(root)
    rule = HiddenHostSync(
        roots=(("albedo_tpu/models/hot.py", "Fit.fit"),), allow_modules=()
    )
    findings = collect_findings(tree, rules=[rule])
    assert len(findings) == 1 and ".tolist()" in findings[0].message
    # Discovery off -> the thread body is invisible (the pre-tier blind spot).
    blind = HiddenHostSync(
        roots=(("albedo_tpu/models/hot.py", "Fit.fit"),), allow_modules=(),
        discover_threads=False,
    )
    assert collect_findings(tree, rules=[blind]) == []


# --- 3. anchors against the real tree -----------------------------------------


def test_real_lock_inventory_is_locksmith_named():
    inv = lock_inventory(default_tree())
    names = {l.name for l in inv.values()}
    for expected in (
        "serving.batcher.exec", "serving.batcher.submit",
        "serving.batcher.stats", "serving.service.gen",
        "serving.reload.reload", "serving.breaker.state",
        "retrieval.bank.exec", "retrieval.stage.swap",
        "utils.aot.memcache", "utils.aot.bypass", "utils.devcache.entries",
        "utils.faults.registry", "store.crawler.stats",
    ):
        assert expected in names, f"{expected} missing from the lock inventory"
    assert len(names) >= 18


def test_real_lock_catalog_round_trips():
    tree = default_tree()
    catalog = lock_order_catalog(tree)
    assert catalog, "ARCHITECTURE.md lock-order catalog missing/empty"
    names = {l.name for l in lock_inventory(tree).values()}
    for a, b in catalog:
        assert a in names, f"catalog names unknown lock {a}"
        assert b in names, f"catalog names unknown lock {b}"
    assert ("serving.reload.reload", "serving.service.gen") in catalog


def test_real_thread_inventory_round_trips():
    tree = default_tree()
    doc = thread_inventory_doc(tree)
    spawned = {s.name for s in tree.thread_spawns() if s.kind == "thread"}
    assert set(doc) == spawned


# --- 4. the parse cache -------------------------------------------------------


def _mini_repo(tmp_path) -> Path:
    root = tmp_path / "repo"
    (root / "albedo_tpu").mkdir(parents=True)
    (root / "albedo_tpu/a.py").write_text("X = 1\n")
    (root / "albedo_tpu/b.py").write_text("Y = 2\n")
    return root


def test_parse_cache_hits_and_invalidates(tmp_path, monkeypatch):
    import ast as ast_module

    root = _mini_repo(tmp_path)
    ProjectTree.load(root, cache=True)
    assert (root / CACHE_NAME).exists()

    real_parse = ast_module.parse
    parses: list = []

    def counting_parse(src, *a, **k):
        parses.append(k.get("filename"))
        return real_parse(src, *a, **k)

    monkeypatch.setattr(ast_module, "parse", counting_parse)

    t2 = ProjectTree.load(root, cache=True)
    assert parses == [], "warm load must not re-parse unchanged files"
    assert t2.modules["albedo_tpu/a.py"].source == "X = 1\n"

    # A content change (mtime+size key) re-parses just that file.
    time.sleep(0.01)
    (root / "albedo_tpu/a.py").write_text("X = 111\n")
    t3 = ProjectTree.load(root, cache=True)
    assert len(parses) == 1
    assert t3.modules["albedo_tpu/a.py"].source == "X = 111\n"
    assert t3.modules["albedo_tpu/b.py"].source == "Y = 2\n"

    # An mtime bump alone (touch) also invalidates — conservative key.
    time.sleep(0.01)
    os.utime(root / "albedo_tpu/b.py")
    ProjectTree.load(root, cache=True)
    assert len(parses) == 2


def test_parse_cache_reuses_modules_across_processes_shape(tmp_path):
    """The cache payload round-trips Module objects (ast + pragmas) — the
    thing a warm `make lint` skips re-building."""
    root = _mini_repo(tmp_path)
    (root / "albedo_tpu/a.py").write_text(
        "import threading\n"
        "L = threading.Lock()  # albedo: noqa[lock-discipline]\n"
    )
    ProjectTree.load(root, cache=True)
    warm = ProjectTree.load(root, cache=True)
    mod = warm.modules["albedo_tpu/a.py"]
    assert mod.suppressed("lock-discipline", 2)
    assert mod.tree.body  # the AST came back usable


def test_parse_cache_ignores_corruption_and_library_default_off(tmp_path):
    root = _mini_repo(tmp_path)
    (root / CACHE_NAME).write_bytes(b"not a pickle")
    tree = ProjectTree.load(root, cache=True)  # corrupt cache -> full parse
    assert set(tree.modules) == {"albedo_tpu/a.py", "albedo_tpu/b.py"}

    clean = tmp_path / "clean"
    (clean / "albedo_tpu").mkdir(parents=True)
    (clean / "albedo_tpu/c.py").write_text("Z = 3\n")
    ProjectTree.load(clean)  # default: library loads never write caches
    assert not (clean / CACHE_NAME).exists()
