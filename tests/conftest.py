"""Test harness: force JAX onto 8 virtual CPU devices.

This is the JAX analogue of the reference's commented-out
``local-cluster[1, 3, 12288]`` Spark master (e.g. ``ALSRecommenderBuilder.scala:18``)
— multi-device semantics without hardware, so pjit/shard_map/psum paths are
exercised in CI (SURVEY.md section 4 implication).

Must run before any ``import jax`` anywhere in the test session.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# The environment may pre-import jax (e.g. a sitecustomize on PYTHONPATH) with
# a hardware platform pinned; env vars alone are then too late. The config
# update works post-import as long as no backend has been initialized yet.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu"

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)


@pytest.fixture(autouse=True)
def _reset_fault_registry():
    """No armed fault, hit counter, or global event count leaks between
    tests."""
    from albedo_tpu.utils import events, faults

    faults.reset()
    events.reset_global_metrics()
    yield
    faults.reset()
    events.reset_global_metrics()


@pytest.fixture(autouse=True)
def _isolated_artifact_dir(tmp_path, monkeypatch):
    """Point the artifact store at a per-test temp dir."""
    monkeypatch.setenv("ALBEDO_DATA_DIR", str(tmp_path / "albedo-data"))
    monkeypatch.setenv("ALBEDO_CHECKPOINT_DIR", str(tmp_path / "albedo-data/checkpoints"))
    from albedo_tpu import settings

    settings.reset_settings()
    yield
    settings.reset_settings()
