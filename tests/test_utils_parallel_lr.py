"""Sharded LR training, profiling harness, and schema assertions."""

import numpy as np
import pandas as pd
import pytest

from albedo_tpu.features.assembler import FeatureMatrix
from albedo_tpu.models.logistic_regression import LogisticRegression
from albedo_tpu.parallel import make_mesh
from albedo_tpu.utils import Timer, assert_columns, equals_ignore_nullability, timed, timing


def make_fm(rng, n=700):
    dense = rng.normal(size=(n, 4)).astype(np.float32)
    cat = rng.integers(0, 6, size=n).astype(np.int32)
    bag_idx = rng.integers(0, 9, size=(n, 3)).astype(np.int32)
    bag_idx[rng.random((n, 3)) < 0.3] = -1
    bag_val = np.where(bag_idx >= 0, 1.0, 0.0).astype(np.float32)
    return FeatureMatrix(
        dense=dense, dense_names=list("abcd"),
        cat={"c": cat}, cat_sizes={"c": 6},
        bag_idx={"b": bag_idx}, bag_val={"b": bag_val}, bag_sizes={"b": 9},
    )


def test_sharded_lr_matches_single_device(rng):
    """Row-sharded batch + replicated params == single-device fit: the
    XLA-inserted psum reduction is MLlib's treeAggregate (SURVEY.md §2.5)."""
    fm = make_fm(rng, n=701)  # deliberately not divisible by 8 (padding path)
    w_true = rng.normal(size=fm.num_features)
    y = (rng.random(701) < 1 / (1 + np.exp(-(fm.to_dense() @ w_true)))).astype(np.float32)
    weights = rng.uniform(0.5, 1.5, size=701).astype(np.float32)

    mesh = make_mesh(8)
    base = LogisticRegression(max_iter=80, reg_param=0.05).fit(fm, y, sample_weight=weights)
    shard = LogisticRegression(max_iter=80, reg_param=0.05, mesh=mesh).fit(
        fm, y, sample_weight=weights
    )
    assert shard.train_loss == pytest.approx(base.train_loss, rel=1e-4)
    np.testing.assert_allclose(
        shard.predict_proba(fm), base.predict_proba(fm), rtol=5e-3, atol=5e-3
    )


def test_timer_sections(capsys):
    t = Timer()
    with t.section("a"):
        pass
    with t.section("a"):
        pass
    with t.section("b"):
        pass
    totals = t.report()
    assert t.counts["a"] == 2 and t.counts["b"] == 1
    assert set(totals) == {"a", "b"}
    assert "a:" in capsys.readouterr().out


def test_timed_and_timing_sync_jax(capsys):
    import jax.numpy as jnp

    with timed("block", sync=jnp.ones(4)):
        out = jnp.arange(8).sum()

    @timing
    def work():
        return jnp.ones(3) * 2

    work()
    printed = capsys.readouterr().out
    assert "[block]" in printed and "[work]" in printed


def test_schema_helpers():
    a = pd.DataFrame({"x": [1], "y": [1.0]})
    b = pd.DataFrame({"x": pd.array([2], dtype="Int64"), "y": [2.5]})
    assert equals_ignore_nullability(a, b)
    assert not equals_ignore_nullability(a, a.rename(columns={"x": "z"}))
    assert_columns(a, {"x": "i", "y": "f"})
    with pytest.raises(ValueError, match="missing column"):
        assert_columns(a, {"zzz": "i"})
    with pytest.raises(ValueError, match="dtype kind"):
        assert_columns(a, {"x": "f"})


def test_fit_many_grid_matches_sequential(rng):
    """The vmapped weight-column grid (CV parity) must match per-column fits,
    with and without grid sharding over the 8-device mesh."""
    fm = make_fm(rng, n=500)
    w_true = rng.normal(size=fm.num_features)
    y = (rng.random(500) < 1 / (1 + np.exp(-(fm.to_dense() @ w_true)))).astype(np.float32)
    grid = np.stack(
        [np.ones(500), rng.uniform(0.5, 2.0, 500), rng.uniform(0.1, 1.0, 500)]
    ).astype(np.float32)

    lr = LogisticRegression(max_iter=60, reg_param=0.05)
    seq = [lr.fit(fm, y, sample_weight=w) for w in grid]
    for mesh in (None, make_mesh(8)):
        many = lr.fit_many(fm, y, grid, grid_mesh=mesh)
        assert len(many) == 3
        for m, s in zip(many, seq):
            np.testing.assert_allclose(
                m.coefficients["dense"], s.coefficients["dense"], rtol=2e-2, atol=2e-3
            )
            assert m.train_loss == pytest.approx(s.train_loss, rel=1e-3)
