"""Micro-batcher: parity with the single-request path, coalescing, overflow,
and drain-on-shutdown semantics."""

import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from albedo_tpu.datasets import synthetic_tables  # noqa: E402
from albedo_tpu.models.als import ImplicitALS  # noqa: E402
from albedo_tpu.serving import MicroBatcher, QueueOverflow, RecommendationService  # noqa: E402


@pytest.fixture(scope="module")
def artifacts():
    tables = synthetic_tables(n_users=120, n_items=80, mean_stars=8, seed=5)
    matrix = tables.star_matrix()
    model = ImplicitALS(rank=8, max_iter=3, seed=0).fit(matrix)
    return tables, matrix, model


def test_batched_parity_byte_identical(artifacts):
    """The acceptance gate: batched results are byte-identical to the seed's
    single-request path for random concurrent request mixes (mixed users,
    ks, exclusion flags)."""
    tables, matrix, model = artifacts
    with RecommendationService(model, matrix, batching=False) as single, \
         RecommendationService(model, matrix, batching=True) as batched:
        rng = np.random.default_rng(0)
        mixes = [
            (int(rng.choice(matrix.user_ids)), int(rng.choice([3, 7, 30])),
             bool(rng.integers(0, 2)))
            for _ in range(40)
        ]
        # Baselines computed serially on the unbatched engine.
        baselines = [
            single.recommend(uid, k=k, exclude_seen=ex) for uid, k, ex in mixes
        ]
        # The same mix fired CONCURRENTLY at the batched engine.
        results: list = [None] * len(mixes)

        def worker(lo: int, hi: int) -> None:
            for i in range(lo, hi):
                uid, k, ex = mixes[i]
                _, results[i] = batched.handle_recommend(uid, k=k, exclude_seen=ex)

        threads = [
            threading.Thread(target=worker, args=(i * 10, (i + 1) * 10))
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        for base, got in zip(baselines, results):
            assert [(i["repo_id"], i["score"]) for i in base["items"]] == [
                (i["repo_id"], i["score"]) for i in got["items"]
            ]


def test_batcher_future_parity_bitexact(artifacts):
    """Raw scores/indices from the batcher match ALSModel.recommend exactly
    (np.testing.assert_array_equal — not allclose)."""
    _, matrix, model = artifacts
    batcher = MicroBatcher(model, window_ms=5.0)
    try:
        users = np.arange(16, dtype=np.int64)
        base_vals, base_idx = model.recommend(users, k=10)
        futs = [batcher.submit(int(u), 10) for u in users]
        got = [f.result(timeout=30) for f in futs]
        np.testing.assert_array_equal(np.stack([v for v, _ in got]), base_vals)
        np.testing.assert_array_equal(np.stack([i for _, i in got]), base_idx)
    finally:
        batcher.stop()


def test_concurrent_requests_coalesce(artifacts):
    """Simultaneous submissions actually share device batches."""
    _, matrix, model = artifacts
    batcher = MicroBatcher(model, window_ms=50.0)
    try:
        batcher.warm(ks=(10,), with_exclusion=False)
        start = threading.Barrier(12)
        futs: list = [None] * 12

        def submit(i: int) -> None:
            start.wait()
            futs[i] = batcher.submit(i, 10)

        threads = [threading.Thread(target=submit, args=(i,)) for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for f in futs:
            f.result(timeout=30)
        assert batcher.requests_served == 12
        assert batcher.mean_batch_size > 1.5, (
            f"no coalescing: mean batch {batcher.mean_batch_size}"
        )
    finally:
        batcher.stop()


def test_queue_overflow_raises(artifacts):
    _, matrix, model = artifacts
    batcher = MicroBatcher(model, max_queue=2, window_ms=0.0)
    try:
        # Wedge the worker so the queue backs up deterministically.
        release = threading.Event()
        entered = threading.Event()

        def slow_execute(k, mode, reqs):
            entered.set()
            release.wait(timeout=30)
            for r in reqs:
                if not r.future.done():
                    r.future.set_result(
                        (np.zeros(k, np.float32), np.full(k, -1, np.int32))
                    )

        batcher._execute = slow_execute
        batcher.submit(0, 5)
        assert entered.wait(timeout=10)
        batcher.submit(1, 5)
        batcher.submit(2, 5)
        with pytest.raises(QueueOverflow):
            batcher.submit(3, 5)
        release.set()
    finally:
        release.set()
        batcher.stop()


def test_stop_drains_pending_work(artifacts):
    _, matrix, model = artifacts
    batcher = MicroBatcher(model, window_ms=0.5)
    futs = [batcher.submit(i, 5) for i in range(20)]
    batcher.stop(drain=True)
    for f in futs:
        vals, idx = f.result(timeout=1)  # already resolved: drained
        assert vals.shape == (5,) and idx.shape == (5,)
    with pytest.raises(RuntimeError):
        batcher.submit(0, 5)


def test_deadline_expired_request_is_shed_not_computed(artifacts):
    """Admission control at the batcher: a request whose deadline already
    passed when the worker reaches it fails with DeadlineExceeded; one with
    headroom is served normally from the same queue."""
    from albedo_tpu.serving import DeadlineExceeded

    _, matrix, model = artifacts
    batcher = MicroBatcher(model, window_ms=0.0)
    try:
        dead = batcher.submit(0, 5, deadline=time.monotonic() - 0.01)
        live = batcher.submit(1, 5, deadline=time.monotonic() + 30.0)
        with pytest.raises(DeadlineExceeded) as ei:
            dead.result(timeout=10)
        assert isinstance(ei.value, QueueOverflow)  # same 429 contract
        assert 1.0 <= ei.value.retry_after_s <= 30.0
        vals, idx = live.result(timeout=10)
        assert vals.shape == (5,) and idx.shape == (5,)
        assert 1.0 <= batcher.retry_after_s() <= 30.0
    finally:
        batcher.stop()


def test_warm_precompiles_ladder(artifacts):
    _, matrix, model = artifacts
    batcher = MicroBatcher(model, max_batch=4, window_ms=0.0)
    try:
        sources = batcher.warm(ks=(5,), with_exclusion=False)
        # k quantizes up to the pow2 ladder (5 -> 8).
        assert set(sources) == {(1, 8, "none"), (2, 8, "none"), (4, 8, "none")}
        # Second warm: everything already in the handle cache.
        again = batcher.warm(ks=(5,), with_exclusion=False)
        assert all(src == "memory" for src in again.values())
    finally:
        batcher.stop()


def test_host_mode_exclusion_width_contract(artifacts):
    """Over-wide host-mode exclude rows are rejected at submit (silent
    truncation would serve already-seen items and break parity; the
    original code crashed the whole batch with a broadcast error).
    In-width rows serve exactly like the single-request path."""
    _, matrix, model = artifacts
    batcher = MicroBatcher(model, excl_width=4, window_ms=0.0)
    try:
        with pytest.raises(ValueError, match="wider than excl_width"):
            batcher.submit(0, 5, np.arange(20, dtype=np.int32))
        row = np.arange(3, dtype=np.int32)
        vals, idx = batcher.submit(0, 5, row).result(timeout=30)
        base_v, base_i = model.recommend(np.array([0]), k=5, exclude_idx=row[None, :])
        np.testing.assert_array_equal(vals, base_v[0])
        np.testing.assert_array_equal(idx, base_i[0])
    finally:
        batcher.stop()


def test_out_of_range_user_rejected(artifacts):
    _, matrix, model = artifacts
    batcher = MicroBatcher(model)
    try:
        with pytest.raises(IndexError):
            batcher.submit(10**9, 5)
        with pytest.raises(IndexError):
            batcher.submit(-1, 5)
        with pytest.raises(ValueError):
            batcher.submit(0, 5, exclude=True)  # no exclusion table configured
    finally:
        batcher.stop()


@pytest.mark.slow
def test_sustained_concurrent_load(artifacts):
    """Load test: 16 closed-loop clients for a few seconds; every response
    well-formed, batches actually form, nothing hangs or leaks."""
    tables, matrix, model = artifacts
    with RecommendationService(model, matrix, batching=True, warm=True) as svc:
        stop = threading.Event()
        errors: list = []
        counts = [0] * 16

        def client(ci: int) -> None:
            rng = np.random.default_rng(ci)
            while not stop.is_set():
                uid = int(matrix.user_ids[int(rng.integers(0, matrix.n_users))])
                try:
                    status, body = svc.handle_recommend(uid, k=10)
                    assert status == 200 and len(body["items"]) == 10
                    counts[ci] += 1
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                    return

        threads = [threading.Thread(target=client, args=(i,)) for i in range(16)]
        for t in threads:
            t.start()
        time.sleep(3.0)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors[:3]
        # Correctness-under-load is the point; the count floor only proves
        # the engine made real progress (CI boxes share cores, so no rps bar).
        assert sum(counts) >= 32
        assert svc.batcher.mean_batch_size > 1.0
