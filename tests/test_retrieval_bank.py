"""The retrieval bank: build/calibration/versioning, blocked-MIPS parity
with every host-side score path (single-device AND mesh-sharded), seen-item
exclusion through the shared table, the streaming overlay hook, capacity
admission, and generation promotion gates."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from albedo_tpu.datasets import synthetic_tables  # noqa: E402
from albedo_tpu.datasets.ragged import padded_rows  # noqa: E402
from albedo_tpu.models.als import ImplicitALS  # noqa: E402
from albedo_tpu.recommenders import (  # noqa: E402
    ALSRecommender,
    ContentRecommender,
    EmbeddingSearchBackend,
    TfidfRecommender,
    TfidfSimilaritySearch,
)
from albedo_tpu.retrieval import (  # noqa: E402
    BankSourceSpec,
    BankStage,
    RetrievalBank,
    candidate_parity,
)
from albedo_tpu.retrieval.parity import frame_to_pairs  # noqa: E402
from albedo_tpu.utils import capacity, events, faults  # noqa: E402

K = 12


class _W2VStub:
    """Deterministic word2vec stand-in: hash words to fixed unit vectors —
    the content backend only needs ``document_vector``."""

    dim = 12

    def document_vector(self, words):
        if not words:
            return np.zeros(self.dim, dtype=np.float32)
        rows = []
        for w in words:
            rng = np.random.default_rng(abs(hash(w)) % (2**32))
            rows.append(rng.normal(size=self.dim))
        v = np.mean(rows, axis=0)
        return (v / max(np.linalg.norm(v), 1e-9)).astype(np.float32)


@pytest.fixture(scope="module")
def world():
    tables = synthetic_tables(n_users=150, n_items=110, mean_stars=8, seed=3)
    matrix = tables.star_matrix()
    model = ImplicitALS(rank=8, max_iter=3, seed=0).fit(matrix)
    als = ALSRecommender(model, matrix, exclude_seen=True, top_k=K)
    backend = EmbeddingSearchBackend(tables.repo_info, _W2VStub())
    content = ContentRecommender(backend, tables.starring, top_k=K)
    search = TfidfSimilaritySearch(min_df=1).fit(tables.repo_info)
    tfidf = TfidfRecommender(search, tables.starring, top_k=K)
    indptr, cols, _ = matrix.csr()
    excl = padded_rows(indptr, cols, np.arange(matrix.n_users))
    return tables, matrix, model, als, content, tfidf, search, excl


def _built_bank(world, mesh=None):
    _tables, matrix, _model, als, content, tfidf, _search, excl = world
    bank = RetrievalBank()
    bank.register(als.bank_registration())
    bank.register(content.bank_registration())
    bank.register(tfidf.bank_registration())
    bank.build(matrix=matrix, exclude_table=excl, mesh=mesh)
    return bank


@pytest.fixture(scope="module")
def bank(world):
    return _built_bank(world)


def _bank_pairs(bank, name, vals, idx, row):
    ok = (idx[row] >= 0) & np.isfinite(vals[row])
    return (
        bank.specs[name].item_ids[idx[row][ok]],
        vals[row][ok].astype(np.float64),
    )


# --- parity against every host-side score path --------------------------------


def test_bank_matches_host_paths_per_source(world, bank):
    _tables, matrix, _model, als, content, tfidf, _search, _excl = world
    users = np.arange(12, dtype=np.int64)
    raw = matrix.user_ids[users]
    out = bank.query(users, K, raw_user_ids=raw, exclude_seen=True)
    hosts = {
        "als": als.recommend_for_users(raw),
        "content": content.recommend_for_users(raw),
        "tfidf": tfidf.recommend_for_users(raw),
    }
    for name, frame in hosts.items():
        vals, idx = out[name]
        for row, uid in enumerate(raw):
            report = candidate_parity(
                frame_to_pairs(frame, int(uid)),
                _bank_pairs(bank, name, vals, idx, row),
            )
            assert report["ok"], (name, int(uid), report)


def test_exclusion_actually_excludes_seen_items(world, bank):
    _tables, matrix, _model, *_ = world
    indptr, cols, _ = matrix.csr()
    users = np.arange(8, dtype=np.int64)
    vals, idx = bank.query(users, K, exclude_seen=True, sources=("als",))["als"]
    for row, du in enumerate(users):
        seen = set(cols[indptr[du]:indptr[du + 1]].tolist())
        got = set(idx[row][idx[row] >= 0].tolist())
        assert not (seen & got), f"user {du} was served already-seen items"


def test_exclude_seen_without_table_refuses(world):
    _tables, matrix, _model, als, *_ = world
    bank = RetrievalBank()
    bank.register(als.bank_registration())
    bank.build(matrix=matrix)  # no exclude_table
    with pytest.raises(ValueError, match="exclude_table"):
        bank.query(np.arange(2), 5, exclude_seen=True)


def test_unknown_users_get_no_user_row_candidates(world, bank):
    _tables, matrix, *_ = world
    vals, idx = bank.query(np.array([-1, 0]), 5, sources=("als",))["als"]
    assert np.all(idx[0] == -1) and not np.any(np.isfinite(vals[0]))
    assert np.any(idx[1] >= 0)


def test_item_mean_query_without_raw_ids_refuses(world, bank):
    with pytest.raises(ValueError, match="raw_user_ids"):
        bank.query(np.array([0]), 5, sources=("content",))


def test_sharded_bank_matches_single_device(world, bank):
    from albedo_tpu.parallel.mesh import make_mesh

    sharded = _built_bank(world, mesh=make_mesh())
    users = np.arange(10, dtype=np.int64)
    raw = world[1].user_ids[users]
    for kwargs in ({"exclude_seen": True}, {"exclude_seen": False}):
        a = bank.query(users, K, raw_user_ids=raw, **kwargs)
        b = sharded.query(users, K, raw_user_ids=raw, **kwargs)
        for name in bank.source_names:
            va, _ia = a[name]
            vb, _ib = b[name]
            mask = np.isfinite(va) & np.isfinite(vb)
            assert np.allclose(va[mask], vb[mask], atol=1e-5), (name, kwargs)
            assert np.array_equal(np.isfinite(va), np.isfinite(vb))


# --- build semantics ----------------------------------------------------------


def test_calibration_recorded_per_source(bank):
    for name in bank.source_names:
        cal = bank.calibration[name]
        assert cal["scale"] > 0
        assert cal["row_norm_max"] >= cal["row_norm_mean"] >= 0
    # Cosine sources' top-1 sits at ~1.0 already: scale ~1.
    assert bank.calibration["content"]["scale"] == pytest.approx(1.0, abs=0.2)


def test_build_fires_fault_site_and_counts_admission(world):
    _tables, matrix, _model, als, *_ = world
    faults.arm("retrieval.build", "error", at=1)
    bank = RetrievalBank()
    bank.register(als.bank_registration())
    with pytest.raises(faults.FaultInjected):
        bank.build(matrix=matrix)
    faults.reset()
    bank.build(matrix=matrix)
    assert bank.admission is not None and bank.admission.verdict == "fit"
    assert events.capacity_verdicts.value(verdict="fit", workload="retrieval") >= 1


def test_capacity_refusal_before_any_upload(world, monkeypatch):
    _tables, matrix, _model, als, *_ = world
    bank = RetrievalBank()
    bank.register(als.bank_registration())
    with pytest.raises(capacity.CapacityExceeded):
        bank.build(matrix=matrix, budget=1024)
    assert not bank._built


def test_plan_retrieval_prices_generations_and_tables():
    one = capacity.plan_retrieval([(1000, 64), (500, 64)], generations=1)
    two = capacity.plan_retrieval([(1000, 64), (500, 64)], generations=2)
    assert one.items["embedding_tables"] == 1500 * 64 * 4
    assert two.items["embedding_tables"] == 2 * one.items["embedding_tables"]
    assert capacity.plan_retrieval([(10, 4)], excl_entries=100).items[
        "exclusion_table"
    ] == 400


def test_version_roundtrip_and_sealed_artifact(world, bank):
    from albedo_tpu.datasets import artifacts as store

    _tables, matrix, *_ = world
    path = bank.save("test-retrievalBank-v1.pkl", lineage={"tag": "t"})
    assert store.verify_manifest(path) is True
    meta = store.read_meta(path)
    assert meta["bank"]["version"] == bank.version
    assert set(meta["bank"]["sources"]) == set(bank.source_names)
    loaded = RetrievalBank.load("test-retrievalBank-v1.pkl")
    loaded.build(matrix=matrix)
    assert loaded.version == bank.version


# --- scenario diversity -------------------------------------------------------


def test_similar_repos_by_example(world, bank):
    _tables, _matrix, _model, _als, _content, _tfidf, search, _excl = world
    query_repo = int(search.doc_ids[0])
    (ids, scores), = bank.query_similar("tfidf", [np.array([query_repo])], 5)
    assert query_repo not in ids  # MLT never returns the query itself
    assert np.all(np.diff(scores) <= 1e-12)  # score-descending
    # Cross-check against the host path.
    (h_ids, h_scores), = search.similar_to_repos([np.array([query_repo])], 5)
    report = candidate_parity((h_ids, h_scores), (ids, scores))
    assert report["ok"], report


def test_user_to_user_similarity_source(world):
    _tables, matrix, model, *_ = world
    uf = np.asarray(model.user_factors, np.float32)
    bank = RetrievalBank()
    bank.register(BankSourceSpec(
        name="user_sim", kind="user_rows", vectors=uf,
        item_ids=matrix.user_ids, user_vectors=uf,
    ))
    bank.build(matrix=matrix)
    vals, idx = bank.query(np.array([5]), 3)["user_sim"]
    assert idx[0][0] == 5  # a user's nearest neighbor is themself


# --- the streaming overlay hook ----------------------------------------------


def test_publish_user_rows_lands_in_next_query(world):
    _tables, matrix, model, als, *_ = world
    bank = RetrievalBank()
    bank.register(als.bank_registration())
    bank.build(matrix=matrix)
    rng = np.random.default_rng(0)
    fresh = rng.normal(size=(2, model.rank)).astype(np.float32)
    gen = bank.publish_user_rows("als", np.array([0, 1]), fresh)
    assert gen == 1
    vals, idx = bank.query(np.array([0]), 5)["als"]
    expected = fresh[0] @ np.asarray(model.item_factors, np.float32).T
    top = np.sort(expected)[::-1][:5]
    assert np.allclose(np.sort(vals[0])[::-1], top, atol=1e-5)


def test_overlay_never_mutates_the_registered_model(world):
    """bank_registration registers a no-copy view of the model's factors;
    the first publish must copy — overlay rows must never rewrite the
    trained artifact under the model's other holders."""
    _tables, matrix, model, als, *_ = world
    before = np.array(model.user_factors, dtype=np.float32, copy=True)
    bank = RetrievalBank()
    bank.register(als.bank_registration())
    bank.build(matrix=matrix)
    bank.publish_user_rows(
        "als", np.array([0]),
        np.full((1, model.rank), 123.0, dtype=np.float32),
    )
    assert np.array_equal(np.asarray(model.user_factors, np.float32), before)
    assert bank.specs["als"].user_vectors[0, 0] == 123.0


def test_foldin_engine_publishes_into_attached_bank(world):
    from albedo_tpu.streaming.foldin import FoldInEngine

    _tables, matrix, model, als, *_ = world
    bank = RetrievalBank()
    bank.register(als.bank_registration())
    bank.build(matrix=matrix)
    engine = FoldInEngine(model)
    engine.attach_bank(bank, source="als")
    indptr, cols, vals_ = matrix.csr()
    du = 3
    row_idx = cols[indptr[du]:indptr[du + 1]].astype(np.int32)
    row_val = vals_[indptr[du]:indptr[du + 1]].astype(np.float32)
    solved = engine.fold_in(
        [(row_idx, row_val)], user_idx=np.array([du], dtype=np.int64)
    )
    assert bank.overlay_generation == 1
    # The bank's user table now carries the freshly solved row.
    assert np.allclose(bank.specs["als"].user_vectors[du], solved[0], atol=1e-6)


def test_diverged_foldin_publishes_nothing(world):
    from albedo_tpu.streaming.foldin import FoldInDiverged, FoldInEngine

    _tables, matrix, model, als, *_ = world
    bank = RetrievalBank()
    bank.register(als.bank_registration())
    bank.build(matrix=matrix)
    engine = FoldInEngine(model, max_rms=1e-30)  # every solve "diverges"
    engine.attach_bank(bank, source="als")
    indptr, cols, vals_ = matrix.csr()
    row_idx = cols[indptr[0]:indptr[1]].astype(np.int32)
    row_val = vals_[indptr[0]:indptr[1]].astype(np.float32)
    with pytest.raises(FoldInDiverged):
        engine.fold_in([(row_idx, row_val)], user_idx=np.array([0]))
    assert bank.overlay_generation == 0  # nothing landed


# --- generation promotion -----------------------------------------------------


def test_stage_reload_gates(world, bank, monkeypatch):
    _tables, matrix, _model, als, content, tfidf, _search, _excl = world
    stage = BankStage(
        _built_bank(world), matrix,
        fallbacks={"als": als, "content": content, "tfidf": tfidf}, top_k=K,
    )
    bank.save("test-bankgen.pkl")
    report = stage.reload("test-bankgen.pkl")
    assert report["outcome"] == "promoted" and stage.generation == 2
    assert events.retrieval_promotions.value(outcome="promoted") == 1
    # Promoted candidate must keep answering item_mean sources (providers
    # are inherited from the incumbent).
    frames = stage.query_frames(int(matrix.user_ids[0]), k=5)
    assert set(frames) == set(stage.source_names)

    # Missing manifest -> manifest gate.
    report = stage.reload("no-such-bank.pkl")
    assert report == {
        "outcome": "rejected", "gate": "manifest", "why": report["why"],
    }

    # A candidate that drops a source is a restart, not a swap.
    small = RetrievalBank()
    small.register(als.bank_registration())
    small.build(matrix=matrix)
    small.save("test-bankgen-small.pkl")
    report = stage.reload("test-bankgen-small.pkl")
    assert report["outcome"] == "rejected" and report["gate"] == "invariants"

    # Capacity refusal is a recorded rejection, not a crash.
    monkeypatch.setenv("ALBEDO_DEVICE_MEM_BYTES", "4096")
    report = stage.reload("test-bankgen.pkl")
    monkeypatch.delenv("ALBEDO_DEVICE_MEM_BYTES")
    assert report["outcome"] == "rejected" and report["gate"] == "capacity"
    assert events.retrieval_promotions.value(outcome="rejected") == 3


def test_unstamped_bank_rejected_when_stamp_required(world, bank, tmp_path):
    from albedo_tpu.datasets import artifacts as store

    _tables, matrix, *_ = world
    stage = BankStage(_built_bank(world), matrix, top_k=K)
    path = bank.save("test-bank-nostamp.pkl")
    store.meta_path(path).unlink()  # strip the stamp, keep the manifest
    report = stage.reload("test-bank-nostamp.pkl", require_stamp=True)
    assert report["outcome"] == "rejected" and report["gate"] == "stamp"


# --- the shared device-residency cache ---------------------------------------


def test_device_projection_cached_per_identity(world):
    from albedo_tpu.utils.devcache import device_put_cached

    _tables, _matrix, _model, _als, _content, _tfidf, search, _excl = world
    a = search._device_matrix()
    b = search._device_matrix()
    assert a is b  # one upload per model identity
    # The bank's build reuses the same device copy (owner + array shared).
    c = device_put_cached(search, search.matrix)
    assert c is a
