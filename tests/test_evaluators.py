"""Evaluator parity tests: vectorized metrics vs a slow literal transcription
of Spark MLlib's RankingMetrics semantics (the reference's metric engine)."""

import numpy as np
import pytest

from albedo_tpu.datasets.star_matrix import StarMatrix
from albedo_tpu.evaluators import (
    RankingEvaluator,
    UserItems,
    area_under_roc,
    mean_average_precision,
    ndcg_at_k,
    precision_at_k,
    user_actual_items,
    user_items_from_pairs,
)


def _mllib_ndcg(pred, lab, k):
    lab_set = set(lab)
    if not lab_set:
        return 0.0
    n = min(max(len(pred), len(lab_set)), k)
    dcg = max_dcg = 0.0
    for i in range(n):
        gain = 1.0 / np.log(i + 2)
        if i < len(pred) and pred[i] in lab_set:
            dcg += gain
        if i < len(lab_set):
            max_dcg += gain
    return dcg / max_dcg


def _mllib_precision(pred, lab, k):
    lab_set = set(lab)
    n = min(len(pred), k)
    cnt = sum(1 for i in range(n) if pred[i] in lab_set)
    return cnt / k


def _mllib_map(pred, lab):
    lab_set = set(lab)
    if not lab_set:
        return 0.0
    cnt = 0
    prec_sum = 0.0
    for i, p in enumerate(pred):
        if p in lab_set:
            cnt += 1
            prec_sum += cnt / (i + 1)
    return prec_sum / len(lab_set)


def _random_lists(rng, n_queries, max_pred, max_lab, n_items=50):
    preds, labs = [], []
    for _ in range(n_queries):
        np_ = rng.integers(0, max_pred + 1)
        nl = rng.integers(0, max_lab + 1)
        preds.append(rng.choice(n_items, size=np_, replace=False))
        labs.append(rng.choice(n_items, size=nl, replace=False))
    return preds, labs


def _pad(lists, width):
    out = np.full((len(lists), width), -1, dtype=np.int32)
    for i, x in enumerate(lists):
        out[i, : len(x)] = x
    return out


@pytest.mark.parametrize("k", [1, 5, 30])
def test_metrics_match_mllib_semantics(rng, k):
    preds, labs = _random_lists(rng, 40, max_pred=k + 4, max_lab=k + 4)
    # Reference slices both sides to k before RankingMetrics (RankingEvaluator.scala:96-97).
    preds_k = [p[:k] for p in preds]
    labs_k = [l[:k] for l in labs]
    pred_arr, lab_arr = _pad(preds_k, k), _pad(labs_k, k)

    want_ndcg = np.mean([_mllib_ndcg(p, l, k) for p, l in zip(preds_k, labs_k)])
    want_prec = np.mean([_mllib_precision(p, l, k) for p, l in zip(preds_k, labs_k)])
    want_map = np.mean([_mllib_map(p, l) for p, l in zip(preds_k, labs_k)])

    assert ndcg_at_k(pred_arr, lab_arr, k) == pytest.approx(want_ndcg, abs=1e-6)
    assert precision_at_k(pred_arr, lab_arr, k) == pytest.approx(want_prec, abs=1e-6)
    assert mean_average_precision(pred_arr, lab_arr, k) == pytest.approx(want_map, abs=1e-6)


def test_ndcg_hand_computed():
    # One user, perfect first hit then a miss then a hit; 2 relevant items.
    pred = np.array([[7, 3, 9]], dtype=np.int32)
    actual = np.array([[7, 9, -1]], dtype=np.int32)
    g = lambda i: 1.0 / np.log(i + 2)  # noqa: E731
    want = (g(0) + g(2)) / (g(0) + g(1))
    assert ndcg_at_k(pred, actual, 3) == pytest.approx(want, abs=1e-6)
    # Perfect ranking -> 1.0
    assert ndcg_at_k(np.array([[7, 9]]), np.array([[9, 7]]), 2) == pytest.approx(1.0)


def test_evaluator_inner_join_and_k():
    # Users 1 and 2 in both; user 3 only predicted; user 4 only actual.
    predicted = UserItems(
        users=np.array([1, 2, 3], dtype=np.int32),
        items=np.array([[10, 11], [20, 21], [30, 31]], dtype=np.int32),
    )
    actual = UserItems(
        users=np.array([1, 2, 4], dtype=np.int32),
        items=np.array([[10, -1], [99, -1], [40, -1]], dtype=np.int32),
    )
    ev = RankingEvaluator(metric_name="precision@k", k=2)
    # user1: 1 hit / k=2 -> 0.5; user2: 0 hits -> 0. Mean = 0.25.
    assert ev.evaluate(predicted, actual) == pytest.approx(0.25)


def test_user_items_from_pairs_orders_and_truncates():
    users = np.array([5, 5, 5, 8])
    items = np.array([100, 101, 102, 200])
    score = np.array([0.1, 0.9, 0.5, 1.0])
    ui = user_items_from_pairs(users, items, order_key=score, k=2)
    assert ui.users.tolist() == [5, 8]
    assert ui.items[0].tolist() == [101, 102]  # by score desc, truncated to 2
    assert ui.items[1].tolist() == [200, -1]


def test_user_actual_items_recency():
    m = StarMatrix.from_interactions(
        raw_users=np.array([1, 1, 1, 2]),
        raw_items=np.array([10, 20, 30, 10]),
    )
    # Insertion order is the recency proxy: latest first.
    ui = user_actual_items(m, k=2)
    it = {10: 0, 20: 1, 30: 2}  # dense item indices (sorted raw ids)
    assert ui.items[0].tolist() == [it[30], it[20]]


def test_auc_pairwise_reference(rng):
    scores = rng.normal(size=200)
    labels = (rng.random(200) < 0.3).astype(np.float64)
    scores[labels > 0] += 0.8
    # O(n^2) pairwise definition with half-credit ties.
    pos, neg = scores[labels > 0], scores[labels <= 0]
    cmp = (pos[:, None] > neg[None, :]).sum() + 0.5 * (pos[:, None] == neg[None, :]).sum()
    want = cmp / (len(pos) * len(neg))
    assert area_under_roc(labels, scores) == pytest.approx(want, abs=1e-9)


def test_auc_weighted_ties(rng):
    scores = np.round(rng.normal(size=300), 1)  # force ties
    labels = (rng.random(300) < 0.4).astype(np.float64)
    weights = rng.integers(1, 4, size=300).astype(np.float64)
    # Weighted pairwise reference.
    pos, neg = labels > 0.5, labels <= 0.5
    sp, wp = scores[pos], weights[pos]
    sn, wn = scores[neg], weights[neg]
    num = (wp[:, None] * wn[None, :] * (sp[:, None] > sn[None, :])).sum()
    num += 0.5 * (wp[:, None] * wn[None, :] * (sp[:, None] == sn[None, :])).sum()
    want = num / (wp.sum() * wn.sum())
    assert area_under_roc(labels, scores, weights) == pytest.approx(want, abs=1e-9)


# --- edge cases the canary publish gate makes load-bearing (PR 5) -------------


def test_ndcg_empty_ground_truth_rows_score_zero():
    """A user with no held-out positives scores 0 and still counts toward the
    mean (MLlib semantics) — all-empty actuals give exactly 0.0, not NaN."""
    pred = np.array([[0, 1, 2], [3, 4, 5]], dtype=np.int32)
    empty = np.full((2, 3), -1, dtype=np.int32)
    assert ndcg_at_k(pred, empty, k=3) == 0.0
    assert mean_average_precision(pred, empty, k=3) == 0.0
    # Mixed: one empty row halves the mean of the other.
    actual = np.array([[0, 1, 2], [-1, -1, -1]], dtype=np.int32)
    full = ndcg_at_k(pred[:1], actual[:1], k=3)
    assert ndcg_at_k(pred, actual, k=3) == pytest.approx(full / 2.0, abs=1e-7)


def test_ndcg_k_larger_than_candidate_list():
    """k beyond both list widths must match the hand-computed reference, not
    index out of range or dilute the ideal DCG."""
    pred = np.array([[7, 3]], dtype=np.int32)
    actual = np.array([[3]], dtype=np.int32)
    got = ndcg_at_k(pred, actual, k=30)
    want = _mllib_ndcg([7, 3], [3], 30)
    assert got == pytest.approx(want, abs=1e-6)
    # f32 accumulation inside the evaluator: compare at f32 resolution.
    assert precision_at_k(pred, actual, k=30) == pytest.approx(1 / 30, abs=1e-6)


def test_evaluator_no_common_users_raises():
    p = UserItems(np.array([1], np.int32), np.array([[0]], np.int32))
    a = UserItems(np.array([2], np.int32), np.array([[0]], np.int32))
    with pytest.raises(ValueError, match="no users in common"):
        RankingEvaluator(k=5).evaluate(p, a)


def test_tied_scores_deterministic_stable():
    """Ties break by input order (stable sort), identically across runs."""
    users = np.array([1] * 4)
    items = np.array([10, 11, 12, 13], dtype=np.int32)
    score = np.array([0.5, 0.9, 0.5, 0.5])
    runs = [
        user_items_from_pairs(users, items, order_key=score, k=4).items.tolist()
        for _ in range(3)
    ]
    assert runs[0] == runs[1] == runs[2]
    # Best score first, then the tied block in input order.
    assert runs[0][0] == [11, 10, 12, 13]


def test_nan_scores_rank_last_deterministically():
    """A diverged model's NaN scores must depress the ranking, not shuffle it:
    NaN-keyed items land after every real score, stably."""
    users = np.array([1] * 4)
    items = np.array([10, 11, 12, 13], dtype=np.int32)
    score = np.array([np.nan, 0.2, np.nan, 0.7])
    ui = user_items_from_pairs(users, items, order_key=score, k=4)
    assert ui.items[0].tolist() == [13, 11, 10, 12]
