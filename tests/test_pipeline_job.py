"""run_pipeline: the one-command offline chain — journal records, resume
skipping, per-stage retry, failure journaling, and corrupt-artifact
self-healing."""

import argparse

import pytest

jax = pytest.importorskip("jax")

from albedo_tpu.builders.jobs import JobContext  # noqa: E402
from albedo_tpu.builders.pipeline import (  # noqa: E402
    JOURNAL_NAME,
    STAGES,
    PipelineStageFailed,
    load_journal,
    run_pipeline,
)
from albedo_tpu.datasets import synthetic_tables  # noqa: E402
from albedo_tpu.datasets.artifacts import artifact_path  # noqa: E402
from albedo_tpu.utils import events, faults  # noqa: E402

_NOSLEEP = dict(sleeper=lambda s: None, verbose=False)


def make_ctx():
    ns = argparse.Namespace(
        small=True, tables=None, now=1700000000.0, no_compilation_cache=True
    )
    tables = synthetic_tables(n_users=120, n_items=80, mean_stars=10, seed=11)
    return JobContext(ns, tables=tables, tag="pipetest")


def journal_on_disk(ctx) -> dict:
    return load_journal(artifact_path(ctx.artifact_name(JOURNAL_NAME)))


def test_job_is_registered():
    import albedo_tpu.builders  # noqa: F401  (registers)
    from albedo_tpu.cli import _JOBS

    assert "run_pipeline" in _JOBS


def test_full_chain_completes_and_journals():
    ctx = make_ctx()
    journal = run_pipeline(ctx, **_NOSLEEP)
    assert journal["status"] == "complete"
    assert set(journal["stages"]) == {n for n, _ in STAGES}
    for name, record in journal["stages"].items():
        assert record["status"] == "done", name
        assert record["attempts"] == 1
        assert record["finished_at"] >= record["started_at"]
    # Stage results carry the chain's vitals.
    assert journal["stages"]["popularity"]["result"]["rows"] > 0
    assert journal["stages"]["train_als"]["result"]["rank"] == 16  # small config
    assert journal["stages"]["train_lr"]["result"]["auc"] > 0.5
    # Journal is persisted, and listed artifacts exist with manifests.
    disk = journal_on_disk(ctx)
    assert disk["status"] == "complete"
    for record in disk["stages"].values():
        for name in record["artifacts"]:
            path = artifact_path(name)
            assert path.exists()
            # Sidecars (the .meta.json quality stamp, row-quarantine CSVs)
            # are evidence/metadata, not store artifacts — no manifest.
            if not (name.endswith(".meta.json") or ".quarantine-" in name):
                assert path.with_name(path.name + ".sha256").exists()
    # The canary stage stamped the flagship artifact.
    assert disk["stages"]["canary"]["result"]["passed"] is True
    assert artifact_path(ctx.als_artifact_name() + ".meta.json").exists()


def test_resume_skips_completed_stages():
    ctx = make_ctx()
    stages = ["popularity", "user_profile"]
    first = run_pipeline(ctx, stages=stages, **_NOSLEEP)
    # Fresh context (new process analogue): resume must skip, not re-run.
    second = run_pipeline(make_ctx(), resume=True, stages=stages, **_NOSLEEP)
    for name in stages:
        assert second["stages"][name]["status"] == "done"
        # started_at unchanged == the stage body never re-ran.
        assert second["stages"][name]["started_at"] == first["stages"][name]["started_at"]


def test_stage_retries_through_transient_fault():
    faults.arm("pipeline.stage.popularity", kind="error", at=1)  # fails once
    journal = run_pipeline(make_ctx(), stages=["popularity"], **_NOSLEEP)
    record = journal["stages"]["popularity"]
    assert record["status"] == "done"
    assert record["attempts"] == 2
    assert events.retry_attempts.value(site="pipeline.popularity") >= 1


def test_stage_failure_journals_and_resume_retries():
    ctx = make_ctx()
    faults.arm("pipeline.stage.repo_profile", kind="error", times=0)  # permanent
    with pytest.raises(PipelineStageFailed) as ei:
        run_pipeline(ctx, stages=["popularity", "repo_profile"],
                     max_stage_attempts=2, **_NOSLEEP)
    assert ei.value.stage == "repo_profile"
    disk = journal_on_disk(ctx)
    assert disk["status"] == "failed"
    assert disk["stages"]["popularity"]["status"] == "done"
    failed = disk["stages"]["repo_profile"]
    assert failed["status"] == "failed"
    assert failed["attempts"] == 2
    assert "FaultInjected" in failed["error"]

    # The outage ends; --resume completes the chain from where it stopped.
    faults.disarm("pipeline.stage.repo_profile")
    healed = run_pipeline(make_ctx(), resume=True,
                          stages=["popularity", "repo_profile"], **_NOSLEEP)
    assert healed["status"] == "partial"  # clean subset run, not the full chain
    assert healed["stages"]["popularity"]["started_at"] == disk["stages"]["popularity"]["started_at"]
    assert healed["stages"]["repo_profile"]["status"] == "done"


def test_corrupted_artifact_heals_without_intervention():
    """Acceptance: a bit-flipped artifact (fault site) is quarantined and
    regenerated; the pipeline completes and the corruption is counted."""
    ctx = make_ctx()
    run_pipeline(ctx, stages=["popularity"], **_NOSLEEP)
    name = ctx.artifact_name("popularRepoDF.parquet")

    faults.arm("artifact.load", kind="corrupt", at=1)
    before = events.artifact_corruptions.value(artifact=name)
    journal = run_pipeline(make_ctx(), stages=["popularity"], **_NOSLEEP)
    assert journal["status"] == "partial"  # clean, but a subset of the chain
    assert journal["stages"]["popularity"]["status"] == "done"
    assert events.artifact_corruptions.value(artifact=name) == before + 1
    path = artifact_path(name)
    assert path.exists()  # regenerated in place
    assert path.with_name(name + ".corrupt-1").exists()  # evidence kept


def test_stage_retry_resumes_from_own_checkpoints():
    """A transient checkpoint-write failure mid-ALS must NOT make the stage
    retry wipe the steps this very run saved and restart from iteration 0:
    the retry resumes. Observable via checkpoint.save hit counts: --small
    trains 8 iters every 2 (4 saves). The fault site fires AFTER the Orbax
    write, so step 4's data survives the injected IOError and the retry
    resumes from step 4 — 2 more saves, 4 hits total. A from-scratch restart
    (the bug: rmtree on every attempt) would re-save all 4 steps: 6 hits."""
    ctx = make_ctx()
    ctx.args.checkpoint_every = 2
    faults.arm("checkpoint.save", kind="ioerror", at=2)
    journal = run_pipeline(ctx, stages=["train_als"], **_NOSLEEP)
    assert journal["stages"]["train_als"]["status"] == "done"
    assert journal["stages"]["train_als"]["attempts"] == 2
    assert faults.FAULTS.hits("checkpoint.save") == 4


def test_preempted_stage_propagates_without_retry(monkeypatch):
    """A Preempted raised mid-stage is a scheduler notice, not a transient
    failure: no retry (which would restart training under a dying pod), the
    journal records 'preempted', and the exception reaches the CLI's
    exit-75 mapping."""
    from albedo_tpu.utils.checkpoint import Preempted

    ctx = make_ctx()
    calls = []

    def fake_als_model():
        calls.append(1)
        raise Preempted(4)

    monkeypatch.setattr(ctx, "als_model", fake_als_model)
    with pytest.raises(Preempted):
        run_pipeline(ctx, stages=["popularity", "train_als"], **_NOSLEEP)
    assert len(calls) == 1  # exactly one attempt
    disk = journal_on_disk(ctx)
    assert disk["status"] == "preempted"
    assert disk["stages"]["train_als"]["status"] == "preempted"
    assert disk["stages"]["popularity"]["status"] == "done"


def test_unknown_stage_rejected():
    with pytest.raises(ValueError):
        run_pipeline(make_ctx(), stages=["nope"], **_NOSLEEP)


# --- the data-quality firewall stages (PR 5) ----------------------------------


def make_poisoned_ctx():
    """A context whose starring frame seeds dangling/duplicate/nonpositive/
    future-timestamp violations on top of the clean synthetic tables."""
    import numpy as np
    import pandas as pd

    ns = argparse.Namespace(
        small=True, tables=None, now=1700000000.0, no_compilation_cache=True
    )
    tables = synthetic_tables(n_users=120, n_items=80, mean_stars=10, seed=11)
    bad = pd.DataFrame({
        "user_id": [-1, int(tables.starring["user_id"].iloc[0]),
                    int(tables.starring["user_id"].iloc[0])],
        "repo_id": [int(tables.starring["repo_id"].iloc[0]), -1,
                    int(tables.starring["repo_id"].iloc[0])],
        "starred_at": [1.0e9, np.nan, 2.0e9],
        "starring": [1.0, 1.0, -3.0],
    })
    dirty = type(tables)(
        user_info=tables.user_info, repo_info=tables.repo_info,
        starring=pd.concat([tables.starring, bad], ignore_index=True),
        relation=tables.relation,
    )
    ns.data_policy = "repair"
    return JobContext(ns, tables=dirty, tag="pipetest")


def test_ingest_stage_quarantines_and_journals_violations():
    ctx = make_poisoned_ctx()
    journal = run_pipeline(ctx, stages=["ingest"], **_NOSLEEP)
    record = journal["stages"]["ingest"]
    assert record["status"] == "done"
    result = record["result"]
    assert result["policy"] == "repair"
    assert result["violations"]["dangling_user"] == 1
    assert result["violations"]["dangling_repo"] == 1
    assert result["violations"]["nonpositive_confidence"] == 1
    assert result["rows_out"] < result["rows_in"]
    # The rule-tagged sidecar is journaled as stage evidence and exists.
    assert result["quarantined_to"] in record["artifacts"]
    assert artifact_path(result["quarantined_to"]).exists()
    assert events.data_violations.value(rule="dangling_user") == 1


def test_ingest_stage_strict_fails_before_training():
    ctx = make_poisoned_ctx()
    ctx.args.data_policy = "strict"
    with pytest.raises(PipelineStageFailed) as ei:
        run_pipeline(ctx, stages=["ingest"], max_stage_attempts=1, **_NOSLEEP)
    assert ei.value.stage == "ingest"
    assert "DataValidationError" in journal_on_disk(ctx)["stages"]["ingest"]["error"]


def _canary_stages():
    return ["ingest", "train_als", "canary"]


def test_canary_gate_stamps_passing_artifact():
    from albedo_tpu.datasets.artifacts import read_meta

    ctx = make_ctx()
    journal = run_pipeline(ctx, stages=_canary_stages(), **_NOSLEEP)
    result = journal["stages"]["canary"]["result"]
    assert result["passed"] is True and result["metric"] == "ndcg@30"
    assert result["score"] > 0
    meta = read_meta(artifact_path(ctx.als_artifact_name()))
    assert meta["canary"]["passed"] is True
    assert meta["lineage"]["data_hash"]
    assert meta["lineage"]["rows"]["nnz"] == ctx.matrix().nnz
    assert meta["artifact"] == ctx.als_artifact_name()
    assert meta["sha256"]  # bound to the artifact bytes


def test_canary_gate_rejects_regression_vs_last_known_good():
    from albedo_tpu.builders.pipeline import PublishRejected, last_known_good
    from albedo_tpu.datasets.artifacts import save_pickle, write_meta

    # First run measures what this config actually scores (and stamps it).
    first = run_pipeline(make_ctx(), stages=_canary_stages(), **_NOSLEEP)
    score = first["stages"]["canary"]["result"]["score"]

    # Plant a NEWER last-known-good stamp the candidate regresses against
    # (>10% above the score this deterministic config reproduces). The stamp
    # must carry the SAME hyperparameter key — the gate is keyed so a
    # --small rank-16 run is never judged against a rank-50 baseline.
    ctx = make_ctx()
    planted = round(score * 1.5, 6)
    # Re-stamp the trained artifact in place (bytes + manifest untouched) —
    # the next run loads the same model and compares against this score.
    lkg = artifact_path(ctx.als_artifact_name())
    write_meta(lkg, {"canary": {"score": planted, "passed": True}})
    # A stamp under a DIFFERENT config key is invisible to this gate, no
    # matter how new or high-scoring.
    other = artifact_path(ctx.artifact_name("alsModel-50-0.5-40.0-26.pkl"))
    save_pickle(other, {"x": 2})
    write_meta(other, {"canary": {"score": planted * 2, "passed": True}})
    assert last_known_good(ctx) == (ctx.als_artifact_name(), planted)

    with pytest.raises(PublishRejected) as ei:
        run_pipeline(ctx, stages=_canary_stages(), **_NOSLEEP)
    assert ei.value.baseline == planted
    assert journal_on_disk(ctx)["status"] == "rejected"
    assert journal_on_disk(ctx)["stages"]["canary"]["status"] == "rejected"
    assert events.publish_rejected.value(gate="canary") == 1
    # The verdict is final: no retry attempts were spent on it.
    assert journal_on_disk(ctx)["stages"]["canary"]["attempts"] == 1


def test_canary_floor_rejects_and_force_publishes():
    from albedo_tpu.builders.pipeline import PublishRejected
    from albedo_tpu.datasets.artifacts import read_meta

    ctx = make_ctx()
    ctx.args.canary_floor = 1.1  # NDCG can never reach it
    with pytest.raises(PublishRejected):
        run_pipeline(ctx, stages=_canary_stages(), **_NOSLEEP)

    # --publish-force: same gate failure publishes anyway, loudly recorded.
    ctx2 = make_ctx()
    ctx2.args.canary_floor = 1.1
    ctx2.args.publish_force = True
    journal = run_pipeline(ctx2, stages=_canary_stages(), **_NOSLEEP)
    result = journal["stages"]["canary"]["result"]
    assert result["passed"] is False and result["forced"] is True
    meta = read_meta(artifact_path(ctx2.als_artifact_name()))
    assert meta["canary"]["forced"] is True
    # Only the actual refusal counts — the forced run DID publish (visible
    # via forced: true), so it must not inflate the refusal counter.
    assert events.publish_rejected.value(gate="canary") == 1


def test_canary_fault_site_retries_as_transient():
    faults.arm("pipeline.canary", kind="error", at=1)
    journal = run_pipeline(make_ctx(), stages=_canary_stages(), **_NOSLEEP)
    record = journal["stages"]["canary"]
    assert record["status"] == "done"
    assert record["attempts"] == 2
