"""CLI job-context tests (L4 glue).

The reference has no tests here; these pin the corpus-construction contract
the advisor flagged: the Word2Vec training corpus must be tokenized with the
SAME stages the ranker's inference pipeline uses (Tokenizer ->
StopWordsRemover), over the reference's full user+repo text concat
(``Word2VecCorpusBuilder.scala:47-69``).
"""

import argparse

from albedo_tpu.builders.jobs import JobContext
from albedo_tpu.features.text import ENGLISH_STOP_WORDS, Tokenizer


def make_ctx(**over):
    ns = argparse.Namespace(small=True, tables=None, now=1700000000.0)
    for k, v in over.items():
        setattr(ns, k, v)
    return JobContext(ns)


def test_word2vec_corpus_matches_inference_tokenization():
    ctx = make_ctx()
    corpus = ctx.word2vec_corpus()
    tables = ctx.tables()
    # One sentence per user plus one per repo.
    assert len(corpus) == len(tables.user_info) + len(tables.repo_info)
    tok = Tokenizer("x")
    flat = [w for s in corpus for w in s]
    assert flat, "corpus should not be empty"
    for w in flat[:200]:
        # Every corpus token must round-trip through the inference tokenizer
        # unchanged (no punctuation-adjacent OOV) and not be a stop word.
        assert tok.tokenize(w) == [w] or len(w) == 1  # CJK unigrams pass len-1
        assert w not in ENGLISH_STOP_WORDS


def test_word2vec_corpus_includes_user_and_repo_fields():
    ctx = make_ctx()
    corpus = {w for s in ctx.word2vec_corpus() for w in s}
    tables = ctx.tables()
    tok = Tokenizer("x")
    # A user login and a repo language must surface in the vocab source.
    login_tokens = [t for t in tok.tokenize(str(tables.user_info["user_login"].iloc[0])) if t]
    lang_tokens = [t for t in tok.tokenize(str(tables.repo_info["repo_language"].iloc[0])) if t]
    assert any(t in corpus for t in login_tokens)
    assert any(t in corpus for t in lang_tokens)


def test_drop_data_job_requires_confirmation(tmp_path):
    """The drop_data job refuses without --yes and truncates with it
    (drop_data.py:11-13 parity, plus a guard the reference lacks)."""
    from albedo_tpu.cli import main
    from albedo_tpu.store import EntityStore

    db = tmp_path / "crawl.db"
    with EntityStore(db) as store:
        store.upsert_user({"id": 1, "login": "a"})
        store.add_starring(1, 2)
        store.commit()

    assert main(["drop_data", "--db", str(db)]) == 3  # refused, nonzero exit
    with EntityStore(db) as store:
        assert store.counts()["app_repostarring"] == 1  # refused: intact

    assert main(["drop_data", "--db", str(db), "--yes"]) == 0
    with EntityStore(db) as store:
        assert sum(store.counts().values()) == 0


def test_cli_platform_flag(tmp_path, monkeypatch):
    """--platform cpu pins the backend before any job code touches devices.

    conftest already runs tests on CPU, so assert the MECHANISM: the flag must
    route through jax.config.update BEFORE the job body executes."""
    import jax

    from albedo_tpu.cli import main

    calls = []
    real_update = jax.config.update
    monkeypatch.setattr(
        jax.config, "update", lambda k, v: (calls.append((k, v)), real_update(k, v))
    )
    monkeypatch.setenv("ALBEDO_DATA_DIR", str(tmp_path))
    assert main(["popularity", "--small", "--platform", "cpu"]) == 0
    assert ("jax_platforms", "cpu") in calls
    # Without the flag, the CLI must not touch the platform config.
    calls.clear()
    assert main(["popularity", "--small"]) == 0
    assert ("jax_platforms", "cpu") not in calls


def test_solver_flag_reaches_als(monkeypatch):
    """--solver cg must flow from the CLI namespace into ImplicitALS and tag
    the artifact key so cg/cholesky models never collide in the cache."""
    seen = {}

    from albedo_tpu.models import als as als_mod

    class SpyALS(als_mod.ImplicitALS):
        def fit(self, matrix, callback=None):
            seen["solver"] = self.solver
            seen["cg_steps"] = self.cg_steps
            return super().fit(matrix, callback)

    monkeypatch.setattr(als_mod, "ImplicitALS", SpyALS)
    ctx = make_ctx(solver="cg", cg_steps=2)
    ctx.als_model()
    assert seen == {"solver": "cg", "cg_steps": 2}
    # The cg-tagged artifact must actually exist on disk (cache-collision
    # guard: cg and cholesky models write different keys).
    from albedo_tpu.datasets.artifacts import artifact_path

    tagged = artifact_path(ctx.artifact_name("alsModel-16-0.5-40.0-8-cg2.pkl"))
    assert tagged.exists(), tagged


def test_word2vec_explain_params_dump(capsys):
    """train_word2vec prints the estimator's hyperparameters before fitting
    (Word2VecCorpusBuilder.scala:85 explainParams parity)."""
    from albedo_tpu.builders.jobs import train_word2vec_job

    train_word2vec_job(make_ctx().args)
    out = capsys.readouterr().out
    assert "[train_word2vec] Word2Vec(" in out
    assert "dim=16" in out and "max_iter=3" in out
