"""Golden-metric end-to-end gate: the reference's regression anchor pattern.

The reference commits expected NDCG@30 values at every builder's call site
(``ALSRecommenderBuilder.scala:105``: ALS 0.05209 vs popularity 0.00202 —
a ~25x gap). The dataset isn't distributable, so the gate here is the *shape*
of that result on the synthetic star matrix: ALS NDCG@30 must beat the
popularity baseline by a wide factor, deterministically under seed 42.
"""

import numpy as np
import pytest

from albedo_tpu.datasets import random_split_by_user, sample_test_users, synthetic_stars
from albedo_tpu.evaluators import RankingEvaluator, UserItems, user_actual_items
from albedo_tpu.models.als import ImplicitALS


@pytest.fixture(scope="module")
def als_eval():
    matrix = synthetic_stars(n_users=600, n_items=400, rank=8, mean_stars=25, seed=7)
    train, test = random_split_by_user(matrix, test_ratio=0.2, seed=42)
    users = sample_test_users(train, n=200, seed=42)
    model = ImplicitALS(rank=16, reg_param=0.1, alpha=40.0, max_iter=10).fit(train)

    # Exclude training positives from retrieval, like the PySpark track's
    # recommend_items exclusion path.
    indptr, cols, _ = train.csr()
    width = int(np.diff(indptr)[users].max())
    excl = np.full((len(users), width), -1, dtype=np.int32)
    for r, u in enumerate(users):
        lo, hi = indptr[u], indptr[u + 1]
        excl[r, : hi - lo] = cols[lo:hi]

    _, idx = model.recommend(users, k=30, exclude_idx=excl)
    predicted = UserItems(users=users, items=idx.astype(np.int32))
    actual = user_actual_items(test, k=30)
    return train, test, users, predicted, actual


def test_als_beats_popularity_by_wide_margin(als_eval):
    train, test, users, predicted, actual = als_eval
    ev = RankingEvaluator(metric_name="ndcg@k", k=30)
    als_ndcg = ev.evaluate(predicted, actual)

    pop_order = np.argsort(-train.item_counts(), kind="stable")[:30].astype(np.int32)
    pop_pred = UserItems(users=users, items=np.tile(pop_order, (len(users), 1)))
    pop_ndcg = ev.evaluate(pop_pred, actual)

    assert als_ndcg > 2 * pop_ndcg, (als_ndcg, pop_ndcg)
    assert als_ndcg > 0.05


def test_all_metrics_positive(als_eval):
    _, _, _, predicted, actual = als_eval
    for name in ("ndcg@k", "precision@k", "map"):
        v = RankingEvaluator(metric_name=name, k=30).evaluate(predicted, actual)
        assert 0.0 < v <= 1.0, (name, v)
