"""Streaming chaos through the real CLI: a run_stream process hard-killed
mid-fold-in must leave NOTHING a serving watcher could promote — the served
generation is never a half-applied delta.

The publish protocol makes this structural: the pickle is written first,
the ``.meta.json`` stamp second, and the ``.sha256`` manifest LAST — the
reload watcher only attempts candidates whose manifest exists, so a death
anywhere before the final rename leaves an unsealed (or absent) file no
watcher will touch. This drill kills the process one step earlier still —
inside the first device fold-in batch — and checks the store.

Marked ``chaos`` + ``slow`` (two CLI subprocesses, each paying the jax
import + small ALS fit); tier-1 covers the in-process fold-in/publish
invariants in ``test_streaming_stream.py``.
"""

import os
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = [pytest.mark.chaos, pytest.mark.slow]


def _env(data_dir: Path, **extra: str) -> dict:
    env = dict(os.environ)
    env.pop("ALBEDO_FAULTS", None)
    env.update(
        ALBEDO_DATA_DIR=str(data_dir),
        ALBEDO_CHECKPOINT_DIR=str(data_dir / "checkpoints"),
        ALBEDO_TODAY="20260803",
        JAX_PLATFORMS="cpu",
        **extra,
    )
    return env


def _run_stream(env: dict, *extra_args: str) -> subprocess.CompletedProcess:
    cmd = [
        sys.executable, "-m", "albedo_tpu.cli", "run_stream", "--small",
        "--cycles", "1", "--delta-batch", "60", "--probe-users", "40",
        *extra_args,
    ]
    return subprocess.run(cmd, capture_output=True, text=True, env=env, timeout=580)


def test_device_loss_mid_foldin_remeshes_and_completes(tmp_path):
    """Elastic drill: a device lost mid-fold-in at 8 virtual devices must not
    kill the stream — the cycle drains, remeshes to 4, re-solves the batch on
    the smaller rung, and the folded factors match an uninterrupted
    single-device stream to 1e-5."""
    import json
    import pickle

    import numpy as np

    # Lossy run: 8 virtual CPU devices, injected collective loss on the first
    # sharded fold-in dispatch.
    lossy = tmp_path / "lossy"
    env8 = _env(
        lossy,
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        ALBEDO_FAULTS="stream.foldin.collective:loss@1",
    )
    res = _run_stream(env8, "--mesh-devices", "8")
    assert res.returncode == 0, (res.returncode, res.stderr[-2000:])

    journal = json.loads(
        next(lossy.rglob("*stream-journal.json")).read_text()
    )
    me = journal["mesh_events"]
    assert me["n_shards_start"] == 8
    assert me["losses"] >= 1 and me["resumes"] >= 1, me
    assert me["remeshes"] and me["remeshes"][0]["from_shards"] == 8
    assert me["remeshes"][0]["to_shards"] == 4
    assert me["remeshes"][0]["admission"]["n_devices"] == 4
    assert me["n_shards"] == 4

    # Clean single-device reference stream on a separate store, same seeds.
    clean = tmp_path / "clean"
    ref = _run_stream(_env(clean))
    assert ref.returncode == 0, ref.stderr[-2000:]

    def factors(root: Path) -> np.ndarray:
        with open(next(root.rglob("*stream-g1.pkl")), "rb") as fh:
            return np.asarray(pickle.load(fh)["user_factors"])

    got, want = factors(lossy), factors(clean)
    assert got.shape == want.shape
    assert np.allclose(got, want, atol=1e-5), float(np.max(np.abs(got - want)))


def test_kill_mid_foldin_never_publishes_half_applied_delta(tmp_path):
    data = tmp_path / "data"
    env = _env(data)

    # Hard-kill (os._exit(137), no cleanup) inside the first fold-in batch:
    # after the base model trained and deltas were ingested, before any
    # stream generation could publish.
    killed = _run_stream({**env, "ALBEDO_FAULTS": "stream.foldin:kill@1"})
    assert killed.returncode == 137, (killed.returncode, killed.stderr)

    # The base artifact survived intact; NO stream generation exists in any
    # state — sealed, unsealed, or stamped — so a reload watcher has nothing
    # half-applied to even consider.
    base = list(data.rglob("*alsModel*.pkl"))
    assert base, "the killed run should have left its trained base artifact"
    assert not list(data.rglob("*stream-g*")), (
        "a killed fold-in must not leave any stream-generation file behind"
    )

    # Same store, clean rerun: the stream recovers from the intact base and
    # publishes a SEALED generation (manifest present = watcher-visible).
    ok = _run_stream(env)
    assert ok.returncode == 0, ok.stderr
    sealed = list(data.rglob("*stream-g1.pkl"))
    assert sealed, ok.stdout
    assert (sealed[0].parent / (sealed[0].name + ".sha256")).exists()
    assert (sealed[0].parent / (sealed[0].name + ".meta.json")).exists()
