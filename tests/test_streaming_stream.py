"""The streaming loop end to end: drift monitor verdicts, the run_stream
journal/publish/lineage contract, hot-swap promotion of incremental
generations through the real reload gates, the forced-drift exactly-one-
refit drill, and the fold-in-vs-refit quality bound."""

import argparse

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from albedo_tpu.builders.jobs import JobContext  # noqa: E402
from albedo_tpu.datasets import artifacts as store  # noqa: E402
from albedo_tpu.datasets import synthetic_tables  # noqa: E402
from albedo_tpu.datasets.split import sample_test_users  # noqa: E402
from albedo_tpu.streaming.drift import DriftMonitor, probe_score  # noqa: E402
from albedo_tpu.streaming.job import JOURNAL_NAME, run_stream  # noqa: E402
from albedo_tpu.utils import events, faults  # noqa: E402

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


def make_ctx(tag="streamtest", **args_over):
    ns = argparse.Namespace(
        small=True, tables=None, now=1700000000.0, no_compilation_cache=True,
        data_policy=None, solver="cholesky", cg_steps=3, checkpoint_every=0,
        resume=False, keep_last=3, _rest=[],
        **args_over,
    )
    tables = synthetic_tables(n_users=120, n_items=80, mean_stars=10, seed=11)
    return JobContext(ns, tables=tables, tag=tag), ns


def _opts(**over):
    base = dict(
        cycles=2, delta_batch=80, stream_seed=7, deltas="",
        drift_tolerance=0.05, drift_floor=0.0, drift_every=1,
        half_life_days=7.0, recency_boost=1.0, foldout_limit=0,
        max_foldin_batch=16, probe_users=40, no_publish=False,
        keep_stream=3, refit_checkpoint_every=2,
    )
    base.update(over)
    return argparse.Namespace(**base)


# --- drift monitor ------------------------------------------------------------


class TestDriftMonitor:
    @pytest.fixture(scope="class")
    def fitted(self):
        from albedo_tpu.datasets.synthetic import synthetic_stars
        from albedo_tpu.models.als import ImplicitALS

        matrix = synthetic_stars(n_users=150, n_items=100, rank=8, mean_stars=10, seed=6)
        model = ImplicitALS(rank=8, max_iter=4).fit(matrix)
        probe = sample_test_users(matrix, n=40)
        return matrix, model, probe

    def test_healthy_model_does_not_drift(self, fitted):
        matrix, model, probe = fitted
        score = probe_score(model, matrix, probe)
        monitor = DriftMonitor(baseline=score, tolerance=0.05)
        verdict = monitor.check(model, matrix, probe)
        assert not verdict["drifted"]
        assert verdict["score"] == pytest.approx(score, abs=1e-6)

    def test_decay_past_tolerance_drifts(self, fitted):
        matrix, model, probe = fitted
        score = probe_score(model, matrix, probe)
        monitor = DriftMonitor(baseline=score * 2.0, tolerance=0.05)
        verdict = monitor.check(model, matrix, probe)
        assert verdict["drifted"]
        assert "decayed" in verdict["reasons"][0]

    def test_floor_drifts_and_rebase_resets(self, fitted):
        matrix, model, probe = fitted
        monitor = DriftMonitor(baseline=None, tolerance=0.05, floor=2.0)
        verdict = monitor.check(model, matrix, probe)
        assert verdict["drifted"]
        monitor.rebase(0.9)
        assert monitor.baseline == 0.9
        assert monitor.refits == 1
        assert monitor.baseline_source == "refit"

    def test_drift_fault_site_fires(self, fitted):
        from albedo_tpu.utils.faults import FaultInjected

        matrix, model, probe = fitted
        monitor = DriftMonitor(baseline=None)
        faults.site("stream.drift").arm(kind="error")
        with pytest.raises(FaultInjected):
            monitor.check(model, matrix, probe)


# --- the end-to-end loop ------------------------------------------------------


def test_run_stream_end_to_end_publishes_hot_swappable_generations():
    """The acceptance drill's fast half: synthetic deltas -> validated
    ingest -> fold-in -> stamped publish, then a live HotSwapManager
    promotes the newest stream generation through the real gates, and the
    served factors ARE the folded factors."""
    from albedo_tpu.serving.reload import HotSwapManager
    from albedo_tpu.serving.service import RecommendationService

    ctx, ns = make_ctx()
    opts = _opts(cycles=2)
    journal = run_stream(ctx, ns, opts)

    assert journal["status"] == "complete"
    s = journal["summary"]
    assert s["cycles"] == 2 and s["publishes"] == 2 and s["refits"] == 0
    assert s["deltas_applied"] > 0
    for cycle in journal["cycles"]:
        assert cycle["status"] == "done"
        assert cycle["cycle_s"] < 60.0  # the acceptance bound, on tiny data
        assert not cycle["drift"]["drifted"]

    # Journal is on disk; the published generations are sealed + stamped.
    disk = store.artifact_path(ctx.artifact_name(JOURNAL_NAME))
    assert disk.exists()
    g2 = store.artifact_path(
        ctx.artifact_name(f"{ctx.als_key()}-stream-g2.pkl")
    )
    assert g2.exists() and store.verify_manifest(g2) is True
    meta = store.read_meta(g2)
    assert meta["canary"]["passed"] is True
    assert meta["canary"]["source"] == "drift_check"  # measured this cycle
    lineage = meta["lineage"]
    assert lineage["stream_generation"] == 2
    assert lineage["delta_count"] == s["deltas_applied"]
    assert lineage["base_artifact"] == ctx.als_artifact_name()
    base_sha = store.read_manifest_sha(store.artifact_path(ctx.als_artifact_name()))
    assert lineage["base_sha256"] == base_sha

    # Hot-swap through the REAL reload gates: manifest, stamp, load,
    # invariants (shapes frozen by design), probe, post-swap parity.
    with RecommendationService(ctx.als_model(), ctx.matrix()) as service:
        manager = HotSwapManager(
            service, artifact_glob=f"{ctx.tag}-alsModel-*stream-g*.pkl"
        )
        report = manager.request_reload()
        assert report["outcome"] == "promoted", report
        served = service.generation.model.user_factors
        published = np.asarray(
            store.load_pickle(g2)["user_factors"], dtype=np.float32
        )
        assert np.array_equal(served, published)
        # Folded rows actually differ from the base model (the swap moved
        # the served state forward, not sideways).
        assert not np.array_equal(served, ctx.als_model().user_factors)


def test_forced_drift_triggers_exactly_one_checkpointed_refit():
    """The acceptance drill's slow half: a drift verdict past tolerance
    schedules ONE full checkpointed refit (journaled, counted), the stream
    rebases on it, and the fold-out queue is absorbed."""
    from albedo_tpu.settings import get_settings

    ctx, ns = make_ctx(tag="streamrefit")
    opts = _opts(cycles=2, drift_floor=1.0, drift_every=2, delta_batch=60)
    journal = run_stream(ctx, ns, opts)

    assert journal["summary"]["refits"] == 1
    assert events.drift_refits.total() == 1
    refit = journal["cycles"][-1]["refit"]
    assert refit["journal_status"] == "partial"  # ingest/train_als/canary subset
    assert refit["canary_score"] > 0
    assert "below the absolute floor" in refit["reasons"][0]
    # The refit absorbed the fold-out queue: vocabulary grew past the base.
    assert refit["n_users"] >= ctx.matrix().n_users
    assert journal["summary"]["fold_out_rows"] == 0
    # It really checkpointed (preemption-safe machinery engaged).
    steps = list(get_settings().checkpoint_dir.rglob("step_*"))
    assert steps, "refit left no checkpoint steps"
    # The refit's own pipeline journal + canary stamp exist.
    refit_meta = store.read_meta(store.artifact_path(refit["artifact"]))
    assert refit_meta is not None
    assert refit_meta["canary"]["score"] == pytest.approx(refit["canary_score"])
    # Publishes after the rebase stamp the refit artifact as lineage base,
    # with delta_count RESET — everything folded so far is inside the refit.
    last_pub = journal["cycles"][-1]["publish"]
    pub_meta = store.read_meta(store.artifact_path(last_pub["artifact"]))
    assert pub_meta["lineage"]["base_artifact"] == refit["artifact"]
    assert pub_meta["lineage"]["delta_count"] == 0
    # ...while the run-total summary still counts every applied delta.
    assert journal["summary"]["deltas_applied"] > 0


def test_foldin_quality_within_five_percent_of_full_refit():
    """Acceptance bound: fold-in NDCG@30 on the probe slice within 5% of a
    full refit trained on the SAME materialized data."""
    from albedo_tpu.models.als import ALSModel, ImplicitALS
    from albedo_tpu.streaming.deltas import StarOverlay, validate_deltas

    ctx, _ = make_ctx(tag="streamparity")
    matrix = ctx.matrix()
    model = ctx.als_model()
    from albedo_tpu.datasets.synthetic_tables import synthetic_delta_stream

    overlay = StarOverlay(matrix)
    batches = synthetic_delta_stream(
        matrix, n_batches=2, batch_size=60, seed=13,
        frac_new_user=0.0, frac_new_repo=0.0,
    )
    now = 0.0
    uf = np.array(model.user_factors, copy=True)
    from albedo_tpu.streaming.foldin import FoldInEngine

    engine = FoldInEngine(model, reg_param=0.5, alpha=40.0)
    for frame in batches:
        now = float(frame["starred_at"].max())
        touched = overlay.apply(
            validate_deltas(frame, matrix, now=now, policy="repair")
        )["touched_users"]
        rows = [(du, *overlay.user_row(du, now)) for du in touched]
        rows = [(du, i, v) for du, i, v in rows if i.size]
        if rows:
            solved = engine.fold_in([(i, v) for _, i, v in rows])
            uf[np.asarray([du for du, _, _ in rows])] = solved

    current = overlay.materialize(now)
    probe = ctx.test_user_dense(40)
    folded = ALSModel(uf, model.item_factors, rank=model.rank)
    fold_score = probe_score(folded, current, probe)
    refit = ImplicitALS(rank=16, max_iter=8).fit(current)
    refit_score = probe_score(refit, current, probe)
    assert fold_score >= refit_score * 0.95, (fold_score, refit_score)


def test_run_stream_counts_metrics_and_quarantines():
    ctx, ns = make_ctx(tag="streammetrics")
    journal = run_stream(ctx, ns, _opts(cycles=1))
    assert events.stream_publishes.value(outcome="published") == 1
    assert events.foldin_users.total() > 0
    applied = events.stream_deltas.value(kind="applied")
    assert applied == journal["cycles"][0]["ingest"]["applied"]
    assert events.stream_deltas.value(kind="folded_out") == (
        journal["cycles"][0]["ingest"]["fold_out"]
    )


def test_run_stream_retention_prunes_old_generations():
    ctx, ns = make_ctx(tag="streamkeep")
    run_stream(ctx, ns, _opts(cycles=3, keep_stream=2, drift_every=99))
    names = sorted(
        p.name for p in store.get_settings().artifact_dir.glob(
            f"{ctx.tag}-*stream-g*.pkl"
        )
    )
    assert names == [
        ctx.artifact_name(f"{ctx.als_key()}-stream-g2.pkl"),
        ctx.artifact_name(f"{ctx.als_key()}-stream-g3.pkl"),
    ]
    # No drift check ran inside the --drift-every window: the stamp must say
    # the score is inherited, not measured on these folded factors.
    meta = store.read_meta(store.artifact_path(names[-1]))
    assert meta["canary"]["source"] == "inherited"


def test_delta_files_every_file_is_a_cycle_and_clock_survives_junk(tmp_path):
    """--deltas processes EVERY file (no silent --cycles truncation), in
    CHRONOLOGICAL order (batch max timestamp, not file name — lexicographic
    replay would let an old star overwrite a newer tombstone), and a file
    missing starred_at neither crashes the stream clock nor poisons it with
    NaN — those rows just fail timestamp_range in repair."""
    from albedo_tpu.datasets.synthetic_tables import synthetic_delta_stream

    ctx, ns = make_ctx(tag="streamfiles")
    frames = synthetic_delta_stream(
        ctx.matrix(), n_batches=3, batch_size=40, seed=5,
        start_at=ctx.tables().starring["starred_at"].max() + 60.0,
    )
    sizes = []
    # Chronologically-FIRST batch gets the lexicographically-LAST name.
    for name, frame in zip(("zz-first.csv", "batch-001.csv", "batch-002.csv"), frames):
        frame.iloc[: 10 + 10 * len(sizes)].to_csv(tmp_path / name, index=False)
        sizes.append(10 + 10 * len(sizes))
    # A fourth, degenerate file: no starred_at column at all (sorts last).
    frames[0].drop(columns=["starred_at"]).to_csv(
        tmp_path / "aaa-no-ts.csv", index=False
    )
    journal = run_stream(
        ctx, ns,
        _opts(cycles=1, deltas=str(tmp_path), drift_every=99, no_publish=True),
    )
    assert journal["status"] == "complete"
    assert journal["summary"]["cycles"] == 4  # every file, not --cycles
    # Chronological replay: distinct per-batch sizes identify the order.
    assert [c["ingest"]["rows_in"] for c in journal["cycles"][:3]] == sizes
    last = journal["cycles"][-1]["ingest"]
    assert last["applied"] == 0  # all rows failed timestamp_range under repair
    assert last["violations"].get("timestamp_range", 0) > 0


def test_failed_cycle_lands_in_the_journal(monkeypatch):
    """Exit-code triage needs journal evidence: a cycle that dies (here a
    fold-in divergence) must be journaled as failed with the error, and the
    on-disk journal status must not be left 'running'."""
    import json

    from albedo_tpu.streaming.foldin import FoldInDiverged, FoldInEngine

    def boom(self, rows, user_idx=None):
        raise FoldInDiverged(len(rows), {"nonfinite": 1, "max_abs": 0.0, "rms": 0.0})

    monkeypatch.setattr(FoldInEngine, "fold_in", boom)
    ctx, ns = make_ctx(tag="streamfail")
    with pytest.raises(FoldInDiverged):
        run_stream(ctx, ns, _opts(cycles=2))
    on_disk = json.loads(
        store.artifact_path(ctx.artifact_name(JOURNAL_NAME)).read_text()
    )
    assert on_disk["status"] == "failed"
    assert on_disk["cycles"][0]["status"] == "failed"
    assert "FoldInDiverged" in on_disk["cycles"][0]["error"]
    assert len(on_disk["cycles"]) == 1  # died in cycle 1, cycle 2 never ran


def test_run_stream_job_is_registered():
    import albedo_tpu.builders  # noqa: F401

    from albedo_tpu.cli import _JOBS

    assert "run_stream" in _JOBS
