"""Two-stage online pipeline: fan-out/fusion, per-stage deadlines, and the
degradation matrix (ranker timeout, cold artifacts, broken sources)."""

import time

import numpy as np
import pandas as pd
import pytest

jax = pytest.importorskip("jax")

from albedo_tpu.datasets import synthetic_tables  # noqa: E402
from albedo_tpu.datasets.tables import popular_repos  # noqa: E402
from albedo_tpu.models.als import ImplicitALS  # noqa: E402
from albedo_tpu.recommenders import PopularityRecommender  # noqa: E402
from albedo_tpu.recommenders.base import Recommender  # noqa: E402
from albedo_tpu.serving import RecommendationService, StageDeadlines  # noqa: E402


@pytest.fixture(scope="module")
def artifacts():
    tables = synthetic_tables(n_users=100, n_items=60, mean_stars=8, seed=7)
    matrix = tables.star_matrix()
    model = ImplicitALS(rank=8, max_iter=3, seed=0).fit(matrix)
    pop = PopularityRecommender(
        popular_repos(tables.repo_info, 1, 10**9), top_k=20
    )
    return tables, matrix, model, pop


class StubRanker:
    """RankerModel stand-in: deterministic probability = item-id rank."""

    def __init__(self, delay_s: float = 0.0, fail: bool = False, empty: bool = False):
        self.delay_s = delay_s
        self.fail = fail
        self.empty = empty
        self.calls = 0

    def score(self, candidates: pd.DataFrame) -> pd.DataFrame:
        self.calls += 1
        if self.delay_s:
            time.sleep(self.delay_s)
        if self.fail:
            raise RuntimeError("ranker exploded")
        out = candidates.copy()
        out["probability"] = 1.0 / (1.0 + out["repo_id"].astype(float))
        if self.empty:
            out = out.iloc[0:0]
        return out


def _service(artifacts, ranker=None, deadlines=None, model="als", **kw):
    tables, matrix, als, pop = artifacts
    return RecommendationService(
        als if model == "als" else None,
        matrix,
        repo_info=tables.repo_info,
        recommenders={"popularity": pop},
        ranker=ranker,
        deadlines=deadlines,
        **kw,
    )


def test_two_stage_ranked_path(artifacts):
    ranker = StubRanker()
    with _service(artifacts, ranker=ranker) as svc:
        _, matrix, _, _ = artifacts
        uid = int(matrix.user_ids[0])
        status, body = svc.handle_recommend(uid, k=10)
        assert status == 200
        assert body["stage"] == "two_stage"
        assert body["degraded"] == []
        assert ranker.calls == 1
        assert len(body["items"]) == 10
        # Ranked by probability descending.
        probs = [i["score"] for i in body["items"]]
        assert probs == sorted(probs, reverse=True)
        # Fusion provenance survives re-ranking.
        assert {i["source"] for i in body["items"]} <= {"als", "popularity"}
        # ALS candidates exclude seen items on the two-stage path.
        indptr, cols, _ = matrix.csr()
        dense = matrix.users_of(np.array([uid]))[0]
        seen = set(matrix.item_ids[cols[indptr[dense]:indptr[dense + 1]]].tolist())
        als_items = {i["repo_id"] for i in body["items"] if i["source"] == "als"}
        assert not (seen & als_items)


def test_ranker_timeout_degrades_to_raw_als(artifacts):
    slow = StubRanker(delay_s=2.0)
    with _service(
        artifacts, ranker=slow,
        deadlines=StageDeadlines(candidates_s=10.0, ranker_s=0.05),
    ) as svc:
        _, matrix, _, _ = artifacts
        uid = int(matrix.user_ids[1])
        status, body = svc.handle_recommend(uid, k=5)
        assert status == 200
        assert "ranker_timeout" in body["degraded"]
        assert body["stage"] == "stage1_als"  # raw ALS scores took over
        assert body["items"] and all(i["source"] == "als" for i in body["items"])
        assert svc.metrics.degraded.value(reason="ranker_timeout") == 1


def test_ranker_error_degrades(artifacts):
    with _service(artifacts, ranker=StubRanker(fail=True)) as svc:
        _, matrix, _, _ = artifacts
        status, body = svc.handle_recommend(int(matrix.user_ids[2]), k=5)
        assert status == 200
        assert "ranker_error" in body["degraded"]
        assert body["items"]
        assert svc.metrics.degraded.value(reason="ranker_error") == 1


def test_ranker_cold_drop_all_degrades(artifacts):
    with _service(artifacts, ranker=StubRanker(empty=True)) as svc:
        _, matrix, _, _ = artifacts
        status, body = svc.handle_recommend(int(matrix.user_ids[3]), k=5)
        assert status == 200
        assert "ranker_empty" in body["degraded"]
        assert body["items"]


def test_cold_artifacts_fall_back_to_popularity(artifacts):
    """model=None (ALS artifacts missing): popularity keeps answering."""
    with _service(artifacts, model=None) as svc:
        _, matrix, _, _ = artifacts
        status, body = svc.handle_recommend(int(matrix.user_ids[0]), k=5)
        assert status == 200
        assert "cold_artifacts" in body["degraded"]
        assert body["items"] and all(i["source"] == "popularity" for i in body["items"])
        assert svc.metrics.degraded.value(reason="cold_artifacts") == 1


def test_cold_artifacts_without_any_fallback_is_503(artifacts):
    _, matrix, _, _ = artifacts
    with RecommendationService(None, matrix) as svc:
        status, body = svc.handle_recommend(int(matrix.user_ids[0]), k=5)
        assert status == 503
        assert body["error"] and body["items"] == []


def test_broken_candidate_source_degrades_not_500s(artifacts):
    class Broken(Recommender):
        source = "content"

        def recommend_for_users(self, user_ids):
            raise RuntimeError("index offline")

    tables, matrix, als, pop = artifacts
    with RecommendationService(
        als, matrix,
        recommenders={"popularity": pop, "content": Broken()},
    ) as svc:
        status, body = svc.handle_recommend(int(matrix.user_ids[0]), k=5)
        assert status == 200
        assert "candidate_error_content" in body["degraded"]
        assert body["items"]


def test_slow_candidate_source_times_out(artifacts):
    class Slow(Recommender):
        source = "content"

        def recommend_for_users(self, user_ids):
            time.sleep(5.0)
            return pd.DataFrame()

    tables, matrix, als, pop = artifacts
    with RecommendationService(
        als, matrix,
        recommenders={"popularity": pop, "content": Slow()},
        deadlines=StageDeadlines(candidates_s=0.2, ranker_s=0.5),
    ) as svc:
        t0 = time.monotonic()
        status, body = svc.handle_recommend(int(matrix.user_ids[0]), k=5)
        assert status == 200
        assert time.monotonic() - t0 < 4.0  # deadline, not the source's 5s
        assert "candidate_timeout_content" in body["degraded"]
        assert body["items"]


def test_two_stage_honors_exclude_seen_flag(artifacts):
    """?exclude_seen=0 must reach the pipeline's ALS source (regression:
    the flag was parsed, cache-keyed, then silently ignored)."""
    with _service(artifacts, ranker=None) as svc:
        _, matrix, _, _ = artifacts
        indptr, cols, _ = matrix.csr()
        lens = indptr[1:] - indptr[:-1]
        dense = int(np.argmax(lens))  # user with the most history
        uid = int(matrix.user_ids[dense])
        seen = set(matrix.item_ids[cols[indptr[dense]:indptr[dense + 1]]].tolist())

        _, body_ex = svc.handle_recommend(uid, k=20, exclude_seen=True)
        als_ex = {i["repo_id"] for i in body_ex["items"] if i["source"] == "als"}
        assert not (seen & als_ex)

        _, body_in = svc.handle_recommend(uid, k=20, exclude_seen=False)
        als_in = {i["repo_id"] for i in body_in["items"] if i["source"] == "als"}
        # With history included, the strongest scores ARE the seen items.
        assert seen & als_in


def test_als_source_survives_topk_wider_than_catalog(artifacts):
    """top_k > n_items: -inf pad entries carry indices >= n_items; the
    source must mask before gathering item_ids (regression: IndexError,
    silently degrading every request to candidate_error_als)."""
    from albedo_tpu.serving import BatchedALSSource, MicroBatcher

    _, matrix, model, _ = artifacts
    batcher = MicroBatcher(model, window_ms=0.0)
    try:
        src = BatchedALSSource(batcher, matrix, top_k=matrix.n_items + 40)
        frame = src.recommend_for_users(matrix.user_ids[:2])
        assert len(frame)  # real items only, no crash
        assert set(frame["repo_id"]).issubset(set(matrix.item_ids.tolist()))
    finally:
        batcher.stop()


# --- fault-injected chaos over HTTP ------------------------------------------
# The degradation matrix driven by the REAL fault sites through the REAL
# server — no hand-stubbed errors anywhere in the request path.


def _get_json(host, port, path):
    import json as _json
    import urllib.request

    with urllib.request.urlopen(f"http://{host}:{port}{path}", timeout=15) as r:
        return _json.loads(r.read().decode())


def test_fault_injected_ranker_error_degrades_over_http(artifacts):
    from albedo_tpu.serving import serve
    from albedo_tpu.utils import faults

    with _service(artifacts, ranker=StubRanker()) as svc:
        handle = serve(svc, port=0)
        try:
            host, port = handle.server_address[:2]
            _, matrix, _, _ = artifacts
            uid = int(matrix.user_ids[4])
            faults.arm("serving.rank", kind="error", at=1)
            body = _get_json(host, port, f"/recommend/{uid}")
            assert "ranker_error" in body["degraded"]
            assert body["stage"] == "stage1_als"
            assert body["items"]
            # The next request is healthy again (times=1): full two-stage.
            body2 = _get_json(host, port, f"/recommend/{uid}?k=5")
            assert body2["stage"] == "two_stage"
            # Both the degradation counter and the fault firing are on /metrics.
            import urllib.request

            with urllib.request.urlopen(f"http://{host}:{port}/metrics", timeout=15) as r:
                text = r.read().decode()
            assert 'albedo_degraded_total{reason="ranker_error"} 1' in text
            assert 'albedo_faults_fired_total{site="serving.rank"}' in text
        finally:
            handle.shutdown()


def test_fault_injected_source_error_degrades_over_http(artifacts):
    from albedo_tpu.serving import serve
    from albedo_tpu.utils import faults

    with _service(artifacts, ranker=StubRanker()) as svc:
        handle = serve(svc, port=0)
        try:
            host, port = handle.server_address[:2]
            _, matrix, _, _ = artifacts
            faults.arm("serving.source.popularity", kind="ioerror", at=1)
            body = _get_json(host, port, f"/recommend/{int(matrix.user_ids[5])}")
            assert "candidate_error_popularity" in body["degraded"]
            # ALS candidates survived, so the request still re-ranked.
            assert body["stage"] == "two_stage"
            assert body["items"]
        finally:
            handle.shutdown()


def test_fault_injected_source_delay_times_out_over_http(artifacts):
    from albedo_tpu.serving import serve
    from albedo_tpu.utils import faults

    with _service(
        artifacts, ranker=None,
        deadlines=StageDeadlines(candidates_s=0.15, ranker_s=0.5),
    ) as svc:
        handle = serve(svc, port=0)
        try:
            host, port = handle.server_address[:2]
            _, matrix, _, _ = artifacts
            faults.arm("serving.source.popularity", kind="delay", param=1.5, at=1)
            t0 = time.monotonic()
            body = _get_json(host, port, f"/recommend/{int(matrix.user_ids[6])}")
            assert time.monotonic() - t0 < 1.4  # deadline, not the fault's 1.5s
            assert "candidate_timeout_popularity" in body["degraded"]
            assert body["items"] and all(i["source"] == "als" for i in body["items"])
        finally:
            handle.shutdown()


def test_stage_timings_reach_metrics(artifacts):
    with _service(artifacts, ranker=StubRanker()) as svc:
        _, matrix, _, _ = artifacts
        svc.handle_recommend(int(matrix.user_ids[0]), k=5)
        snap = svc.pipeline.timer.snapshot()
        assert snap["counts"].get("stage1_candidates") == 1
        assert snap["counts"].get("stage2_rank") == 1
        # The /metrics handler refreshes the gauges from the timer at scrape
        # time; emulate the scrape.
        svc.metrics.observe_timer(svc.pipeline.timer)
        text = svc.metrics.render()
        assert 'albedo_stage_seconds{stage="stage1_candidates"}' in text


def test_client_deadline_sheds_two_stage_before_compute(artifacts):
    """Admission control must bite in pipeline mode too (regression: the
    deadline was silently dropped on every path except pure batched ALS):
    an already-lapsed deadline is shed with the 429-shaped DeadlineExceeded
    before any stage spends work."""
    from albedo_tpu.serving.batcher import DeadlineExceeded

    ranker = StubRanker()
    with _service(artifacts, ranker=ranker) as svc:
        _, matrix, _, _ = artifacts
        with pytest.raises(DeadlineExceeded):
            svc.handle_recommend(
                int(matrix.user_ids[0]), k=5,
                deadline=time.monotonic() - 0.01,
            )
        assert ranker.calls == 0  # shed before compute, not computed-then-late
        assert svc.metrics.deadline_shed.value() == 1


def test_client_deadline_caps_ranker_budget(artifacts):
    """A live-but-tight client deadline bounds the whole response: the
    ranker's generous stage budget is cut to the client's remaining time,
    so the request degrades to stage-1 scores inside the deadline instead
    of arriving late."""
    slow = StubRanker(delay_s=3.0)
    with _service(
        artifacts, ranker=slow,
        deadlines=StageDeadlines(candidates_s=10.0, ranker_s=8.0),
    ) as svc:
        _, matrix, _, _ = artifacts
        t0 = time.monotonic()
        status, body = svc.handle_recommend(
            int(matrix.user_ids[1]), k=5, deadline=t0 + 0.4,
        )
        assert status == 200
        assert time.monotonic() - t0 < 2.5  # client budget, not ranker_s=8
        assert "ranker_timeout" in body["degraded"]
        assert body["items"]


def test_client_deadline_timeout_does_not_penalize_breaker(artifacts):
    """A source cut short by the CLIENT's deadline (its own stage budget
    untouched) degrades but records no breaker outcome — a run of
    tight-deadline requests must not trip a perfectly healthy source."""

    class Slow(Recommender):
        source = "content"

        def recommend_for_users(self, user_ids):
            time.sleep(1.0)
            return pd.DataFrame()

    tables, matrix, als, pop = artifacts
    with RecommendationService(
        als, matrix,
        recommenders={"popularity": pop, "content": Slow()},
        deadlines=StageDeadlines(candidates_s=30.0, ranker_s=0.5),
    ) as svc:
        for _ in range(3):
            status, body = svc.handle_recommend(
                int(matrix.user_ids[0]), k=5,
                deadline=time.monotonic() + 0.15,
            )
            assert status == 200
            assert "candidate_timeout_content" in body["degraded"]
        br = svc.pipeline.breakers["content"]
        assert br.state == "closed"
        assert br.snapshot()["consecutive_failures"] == 0
