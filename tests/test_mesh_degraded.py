"""Degraded-mesh operation: the 8 -> 4 -> 2 -> 1 remesh ladder when fewer
devices are visible than requested (startup shortfall or an injected
``mesh.devices`` device-loss fault), and the factors-are-identical contract
for fits on a degraded mesh."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from albedo_tpu.datasets.synthetic import synthetic_stars  # noqa: E402
from albedo_tpu.models.als import ImplicitALS  # noqa: E402
from albedo_tpu.parallel.mesh import (  # noqa: E402
    DATA_AXIS,
    ITEM_AXIS,
    degraded_ladder,
    make_mesh,
)
from albedo_tpu.utils import events, faults  # noqa: E402


class TestLadder:
    @pytest.mark.parametrize(
        "requested,available,item,expect",
        [
            (8, 8, 1, 8),
            (16, 8, 1, 8),
            (8, 4, 1, 4),
            (8, 3, 1, 2),
            (8, 1, 1, 1),
            (8, 4, 2, 4),
            (8, 3, 2, 2),
            (1, 1, 1, 1),
        ],
    )
    def test_ladder(self, requested, available, item, expect):
        assert degraded_ladder(requested, available, item=item) == expect

    def test_never_below_one(self):
        assert degraded_ladder(64, 0, item=4) == 1


class TestMakeMesh:
    def test_full_request_unchanged(self):
        mesh = make_mesh(8)
        assert mesh.shape[DATA_AXIS] == 8 and mesh.shape[ITEM_AXIS] == 1

    def test_oversized_request_degrades_loudly(self):
        before = events.mesh_degraded.total()
        mesh = make_mesh(16)  # the CI box forces 8 virtual devices
        assert mesh.shape[DATA_AXIS] * mesh.shape[ITEM_AXIS] == 8
        assert events.mesh_degraded.total() == before + 1

    def test_degraded_remesh_disabled_raises(self):
        with pytest.raises(ValueError, match="degraded remesh disabled"):
            make_mesh(16, allow_degraded=False)

    def test_device_loss_fault_halves_the_mesh(self):
        faults.arm("mesh.devices", kind="error", at=1)
        before = events.mesh_degraded.total()
        mesh = make_mesh(8, data=4, item=2)
        assert mesh.shape[DATA_AXIS] * mesh.shape[ITEM_AXIS] == 4
        assert mesh.shape[ITEM_AXIS] == 2  # item axis survives when it divides
        assert events.mesh_degraded.total() == before + 1
        assert faults.FAULTS.fired("mesh.devices") == 1

    def test_item_axis_collapses_when_it_no_longer_divides(self):
        # 8 requested with item=8, only 4 visible: 4 % 8 != 0 -> item -> 1.
        faults.arm("mesh.devices", kind="error", at=1)
        mesh = make_mesh(8, data=1, item=8)
        assert mesh.shape[ITEM_AXIS] == 1
        assert mesh.shape[DATA_AXIS] * mesh.shape[ITEM_AXIS] == 4

    def test_oom_kind_also_reads_as_device_loss(self):
        faults.arm("mesh.devices", kind="oom", at=1)
        mesh = make_mesh(8)
        assert mesh.shape[DATA_AXIS] * mesh.shape[ITEM_AXIS] == 4

    def test_explicit_shape_mismatch_still_errors(self):
        with pytest.raises(ValueError, match="!="):
            make_mesh(8, data=3, item=2)


class TestDegradedFitParity:
    def test_degraded_mesh_reaches_the_same_factors(self):
        """The multichip drill's contract, in-suite: half the slice drops
        out, the remeshed fit is slower but lands the SAME factors."""
        matrix = synthetic_stars(n_users=64, n_items=48, mean_stars=6, seed=3)
        kw = dict(rank=8, max_iter=2, batch_size=32, seed=0)
        full = ImplicitALS(**kw, mesh=make_mesh(8)).fit(matrix)

        faults.arm("mesh.devices", kind="error", at=1)
        degraded_mesh = make_mesh(8)
        assert degraded_mesh.shape[DATA_AXIS] == 4
        matrix2 = synthetic_stars(n_users=64, n_items=48, mean_stars=6, seed=3)
        degraded = ImplicitALS(**kw, mesh=degraded_mesh).fit(matrix2)

        np.testing.assert_allclose(
            degraded.user_factors, full.user_factors, atol=1e-5
        )
        np.testing.assert_allclose(
            degraded.item_factors, full.item_factors, atol=1e-5
        )

    def test_sharded_fit_parity_down_the_ladder(self):
        """The ALX-layout fit under degradation: the SAME matrix trained
        with row-sharded tables + streamed buckets on 8, 4 (fault-degraded
        from 8), and 2 devices must land the same factors — fewer shards
        means slower and bigger table shards, never different numbers."""
        matrix = synthetic_stars(n_users=64, n_items=48, mean_stars=6, seed=3)
        kw = dict(
            rank=8, max_iter=2, batch_size=32, seed=0, sharded="streamed"
        )
        full = ImplicitALS(**kw, mesh=make_mesh(8)).fit(matrix)

        faults.arm("mesh.devices", kind="error", at=1)
        mesh4 = make_mesh(8)  # half the slice drops out -> 4 devices
        assert mesh4.shape[DATA_AXIS] == 4
        ladder = [mesh4, make_mesh(2)]
        for mesh in ladder:
            est = ImplicitALS(**kw, mesh=mesh)
            got = est.fit(matrix)
            assert est.last_fit_report["mode"] == "sharded_streamed"
            np.testing.assert_allclose(
                got.user_factors, full.user_factors, atol=1e-5
            )
            np.testing.assert_allclose(
                got.item_factors, full.item_factors, atol=1e-5
            )
