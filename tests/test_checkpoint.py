"""Orbax checkpointing: pytree roundtrip, step management, and resumable ALS
training (kill mid-train, resume from latest, reach the same quality) — plus
the fault-tolerance layer: garbage step dirs, corrupt-step fallback,
retention pruning, preemption handling, and kill-resume NDCG parity."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
pytest.importorskip("orbax.checkpoint")

from albedo_tpu.datasets import synthetic_stars  # noqa: E402
from albedo_tpu.evaluators import (  # noqa: E402
    RankingEvaluator,
    UserItems,
    user_actual_items,
)
from albedo_tpu.models.als import ImplicitALS  # noqa: E402
from albedo_tpu.utils import events, faults  # noqa: E402
from albedo_tpu.utils.checkpoint import (  # noqa: E402
    Preempted,
    PreemptionHandler,
    StepCheckpointer,
    checkpointed_als_fit,
    restore_pytree,
    save_pytree,
)


def test_pytree_roundtrip(tmp_path):
    tree = {"a": np.arange(12, dtype=np.float32).reshape(3, 4), "b": np.int64(7)}
    save_pytree(tmp_path / "ckpt", tree)
    back = restore_pytree(tmp_path / "ckpt")
    np.testing.assert_array_equal(back["a"], tree["a"])
    assert int(back["b"]) == 7


def test_step_checkpointer_latest(tmp_path):
    ckpt = StepCheckpointer(tmp_path / "steps")
    assert ckpt.restore_latest() is None
    ckpt.save(2, {"x": np.ones(3)})
    ckpt.save(10, {"x": np.full(3, 10.0)})
    assert ckpt.steps() == [2, 10]
    step, tree = ckpt.restore_latest()
    assert step == 10
    np.testing.assert_array_equal(tree["x"], np.full(3, 10.0))


def test_checkpointed_als_resume(tmp_path):
    m = synthetic_stars(n_users=150, n_items=90, mean_stars=10, seed=6)
    als = ImplicitALS(rank=8, reg_param=0.3, alpha=10.0, max_iter=6, seed=4)

    # Uninterrupted run with checkpoints every 2 iterations.
    full = checkpointed_als_fit(als, m, tmp_path / "full", every=2)
    assert StepCheckpointer(tmp_path / "full").steps() == [2, 4, 6]

    # Simulate a kill after iteration 4: copy the first two checkpoints, then
    # resume — the resumed run must continue from step 4 (2 more iterations).
    partial_dir = tmp_path / "partial"
    src = StepCheckpointer(tmp_path / "full")
    dst = StepCheckpointer(partial_dir)
    for step in (2, 4):
        dst.save(step, src.restore(step))
    resumed = checkpointed_als_fit(als, m, partial_dir, every=2)
    assert StepCheckpointer(partial_dir).latest_step() == 6

    # Resumed factors land at the same solution the uninterrupted run reached
    # (ALS re-solves rows exactly from the checkpointed state).
    np.testing.assert_allclose(
        resumed.user_factors, full.user_factors, rtol=5e-3, atol=5e-4
    )

    # A fit already at max_iter restores without retraining.
    again = checkpointed_als_fit(als, m, partial_dir, every=2)
    np.testing.assert_allclose(again.user_factors, resumed.user_factors, rtol=1e-6)


# --- fault tolerance ---------------------------------------------------------


def test_steps_skips_garbage_dirs(tmp_path):
    """Leftover Orbax temp dirs, stray files, and half-created (empty) step
    dirs must be invisible — not crash steps()/restore_latest()."""
    ckpt = StepCheckpointer(tmp_path / "steps")
    ckpt.save(2, {"x": np.ones(3)})
    # Plant the garbage a preempted writer leaves behind.
    (tmp_path / "steps" / "step_00000004.orbax-checkpoint-tmp-99").mkdir()
    (tmp_path / "steps" / "step_00000004.orbax-checkpoint-tmp-99" / "d").write_bytes(b"x")
    (tmp_path / "steps" / "step_00000006").mkdir()  # mkdir happened, write didn't
    (tmp_path / "steps" / "step_garbage").mkdir()
    (tmp_path / "steps" / "step_00000008x").mkdir()
    (tmp_path / "steps" / "not_a_step.txt").write_text("hi")
    assert ckpt.steps() == [2]
    step, tree = ckpt.restore_latest()
    assert step == 2
    np.testing.assert_array_equal(tree["x"], np.ones(3))


def test_restore_latest_falls_back_to_newest_readable(tmp_path):
    ckpt = StepCheckpointer(tmp_path / "steps")
    ckpt.save(2, {"x": np.full(3, 2.0)})
    ckpt.save(4, {"x": np.full(3, 4.0)})
    # Corrupt the newest step's payload: checksum verification catches it.
    target = sorted(
        p for p in (tmp_path / "steps" / "step_00000004").rglob("*") if p.is_file()
    )[0]
    data = bytearray(target.read_bytes())
    data[len(data) // 2] ^= 0xFF
    target.write_bytes(bytes(data))

    before = events.checkpoint_fallbacks.total()
    step, tree = ckpt.restore_latest()
    assert step == 2
    np.testing.assert_array_equal(tree["x"], np.full(3, 2.0))
    assert events.checkpoint_fallbacks.total() == before + 1


def test_restore_latest_survives_unreadable_step_without_manifest(tmp_path):
    """A step dir whose manifest is gone AND whose contents are trash (the
    pre-manifest seed bug: restore_latest crashed) falls back."""
    ckpt = StepCheckpointer(tmp_path / "steps")
    ckpt.save(2, {"x": np.ones(2)})
    bad = tmp_path / "steps" / "step_00000009"
    bad.mkdir()
    (bad / "checkpoint").write_bytes(b"not an orbax checkpoint")
    step, _ = ckpt.restore_latest()
    assert step == 2


def test_restore_latest_all_unreadable_returns_none(tmp_path):
    ckpt = StepCheckpointer(tmp_path / "steps")
    bad = tmp_path / "steps" / "step_00000003"
    bad.mkdir()
    (bad / "checkpoint").write_bytes(b"junk")
    assert ckpt.restore_latest() is None


def test_retention_pruning(tmp_path):
    ckpt = StepCheckpointer(tmp_path / "steps", keep_last=2)
    for step in (2, 4, 6, 8):
        ckpt.save(step, {"x": np.full(2, float(step))})
    assert ckpt.steps() == [6, 8]
    # Manifests pruned alongside their steps.
    leftovers = sorted(p.name for p in (tmp_path / "steps").glob("step_*.sha256"))
    assert leftovers == ["step_00000006.sha256", "step_00000008.sha256"]
    step, tree = ckpt.restore_latest()
    assert step == 8


def test_corrupt_fault_site_on_save_is_caught_on_restore(tmp_path):
    faults.arm("checkpoint.save", kind="corrupt", at=2)
    ckpt = StepCheckpointer(tmp_path / "steps")
    ckpt.save(2, {"x": np.ones(2)})
    ckpt.save(4, {"x": np.full(2, 4.0)})  # corrupted before its manifest
    # The manifest hashed the corrupted bytes, so verify passes — but orbax
    # restore fails on the flipped payload and the walk falls back to step 2.
    step, _ = ckpt.restore_latest()
    assert step in (2, 4)  # depending on which file the flip hit
    if step == 4:
        # If orbax tolerated the flip (metadata file), the restore is still
        # self-consistent; nothing to assert beyond not crashing.
        return
    np.testing.assert_array_equal(ckpt.restore(2)["x"], np.ones(2))


def test_checkpoint_interval_must_be_positive(tmp_path):
    m = synthetic_stars(n_users=40, n_items=30, mean_stars=5, seed=2)
    als = ImplicitALS(rank=4, max_iter=4, seed=1)
    with pytest.raises(ValueError, match="interval"):
        checkpointed_als_fit(als, m, tmp_path / "bad", every=0)


def test_preemption_checkpoint_and_resume(tmp_path):
    m = synthetic_stars(n_users=120, n_items=70, mean_stars=8, seed=3)
    als = ImplicitALS(rank=8, reg_param=0.3, alpha=10.0, max_iter=6, seed=1)
    handler = PreemptionHandler()
    handler.request_stop()  # as if SIGTERM arrived during the first chunk
    with pytest.raises(Preempted) as ei:
        checkpointed_als_fit(als, m, tmp_path / "pre", every=2, preemption=handler)
    assert ei.value.step == 2
    ckpt = StepCheckpointer(tmp_path / "pre")
    assert ckpt.steps() == [2]
    assert ckpt.read_journal()["status"] == "preempted"

    # Resume (no preemption this time) finishes and journals completion.
    model = checkpointed_als_fit(als, m, tmp_path / "pre", every=2)
    assert ckpt.latest_step() == 6
    assert ckpt.read_journal()["status"] == "complete"
    assert model.user_factors.shape == (m.n_users, 8)


def test_preemption_handler_installs_and_restores_signal(tmp_path):
    import signal as _signal

    prev = _signal.getsignal(_signal.SIGTERM)
    with PreemptionHandler() as h:
        assert not h.should_stop()
        _signal.raise_signal(_signal.SIGTERM)
        assert h.should_stop()
    assert _signal.getsignal(_signal.SIGTERM) is prev


def _ndcg30(model, matrix) -> float:
    users = np.arange(min(100, matrix.n_users), dtype=np.int64)
    _, idx = model.recommend(users, k=30)
    predicted = UserItems(users=users, items=idx.astype(np.int32))
    return RankingEvaluator(metric_name="ndcg@k", k=30).evaluate(
        predicted, user_actual_items(matrix, k=30)
    )


def test_kill_resume_ndcg_parity(tmp_path):
    """Acceptance: a fit killed mid-train (fault harness, at a checkpoint
    boundary) and rerun with resume matches the uninterrupted run's NDCG@30
    within 1e-3."""
    m = synthetic_stars(n_users=150, n_items=90, mean_stars=10, seed=6)
    als = ImplicitALS(rank=8, reg_param=0.3, alpha=10.0, max_iter=6, seed=4)

    full = checkpointed_als_fit(als, m, tmp_path / "full", every=2)
    ndcg_full = _ndcg30(full, m)

    # Kill the run via the fault harness right after the 2nd checkpoint.
    faults.arm("checkpoint.save", kind="error", at=2)
    with pytest.raises(faults.FaultInjected):
        checkpointed_als_fit(als, m, tmp_path / "killed", every=2)
    faults.disarm("checkpoint.save")
    assert StepCheckpointer(tmp_path / "killed").steps() == [2, 4]

    resumed = checkpointed_als_fit(als, m, tmp_path / "killed", every=2)
    ndcg_resumed = _ndcg30(resumed, m)
    assert abs(ndcg_resumed - ndcg_full) <= 1e-3
    assert ndcg_full > 0  # the metric is non-degenerate
