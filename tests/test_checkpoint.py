"""Orbax checkpointing: pytree roundtrip, step management, and resumable ALS
training (kill mid-train, resume from latest, reach the same quality)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
pytest.importorskip("orbax.checkpoint")

from albedo_tpu.datasets import synthetic_stars  # noqa: E402
from albedo_tpu.models.als import ImplicitALS  # noqa: E402
from albedo_tpu.utils.checkpoint import (  # noqa: E402
    StepCheckpointer,
    checkpointed_als_fit,
    restore_pytree,
    save_pytree,
)


def test_pytree_roundtrip(tmp_path):
    tree = {"a": np.arange(12, dtype=np.float32).reshape(3, 4), "b": np.int64(7)}
    save_pytree(tmp_path / "ckpt", tree)
    back = restore_pytree(tmp_path / "ckpt")
    np.testing.assert_array_equal(back["a"], tree["a"])
    assert int(back["b"]) == 7


def test_step_checkpointer_latest(tmp_path):
    ckpt = StepCheckpointer(tmp_path / "steps")
    assert ckpt.restore_latest() is None
    ckpt.save(2, {"x": np.ones(3)})
    ckpt.save(10, {"x": np.full(3, 10.0)})
    assert ckpt.steps() == [2, 10]
    step, tree = ckpt.restore_latest()
    assert step == 10
    np.testing.assert_array_equal(tree["x"], np.full(3, 10.0))


def test_checkpointed_als_resume(tmp_path):
    m = synthetic_stars(n_users=150, n_items=90, mean_stars=10, seed=6)
    als = ImplicitALS(rank=8, reg_param=0.3, alpha=10.0, max_iter=6, seed=4)

    # Uninterrupted run with checkpoints every 2 iterations.
    full = checkpointed_als_fit(als, m, tmp_path / "full", every=2)
    assert StepCheckpointer(tmp_path / "full").steps() == [2, 4, 6]

    # Simulate a kill after iteration 4: copy the first two checkpoints, then
    # resume — the resumed run must continue from step 4 (2 more iterations).
    partial_dir = tmp_path / "partial"
    src = StepCheckpointer(tmp_path / "full")
    dst = StepCheckpointer(partial_dir)
    for step in (2, 4):
        dst.save(step, src.restore(step))
    resumed = checkpointed_als_fit(als, m, partial_dir, every=2)
    assert StepCheckpointer(partial_dir).latest_step() == 6

    # Resumed factors land at the same solution the uninterrupted run reached
    # (ALS re-solves rows exactly from the checkpointed state).
    np.testing.assert_allclose(
        resumed.user_factors, full.user_factors, rtol=5e-3, atol=5e-4
    )

    # A fit already at max_iter restores without retraining.
    again = checkpointed_als_fit(als, m, partial_dir, every=2)
    np.testing.assert_allclose(again.user_factors, resumed.user_factors, rtol=1e-6)
