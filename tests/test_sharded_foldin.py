"""Mesh-resident fold-in: per-device admission pricing, owner routing
geometry, 1-device mesh parity against the single-device engine, the
elastic streaming loss contract (clean ``MeshLost`` when no rung remains),
the rung-stamped lineage + reload-gate tolerance pin, and the retrieval
bank's recompile-free mesh publish surviving a mid-stream reshard.

Multi-shard behavior (the 8 -> 4 remesh with fold-in parity) needs virtual
host devices a warmed-up test process cannot add; that lives in the CLI
chaos drill (``tests/test_chaos_stream.py``, slow)."""

import argparse

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from albedo_tpu.builders.jobs import JobContext  # noqa: E402
from albedo_tpu.datasets import artifacts as store  # noqa: E402
from albedo_tpu.datasets import synthetic_tables  # noqa: E402
from albedo_tpu.datasets.synthetic import synthetic_stars  # noqa: E402
from albedo_tpu.models.als import ImplicitALS  # noqa: E402
from albedo_tpu.parallel.elastic import MeshLost  # noqa: E402
from albedo_tpu.parallel.foldin import ShardedFoldIn  # noqa: E402
from albedo_tpu.parallel.mesh import make_mesh  # noqa: E402
from albedo_tpu.streaming.foldin import FoldInEngine  # noqa: E402
from albedo_tpu.streaming.job import run_stream  # noqa: E402
from albedo_tpu.utils import capacity, events, faults  # noqa: E402

REG, ALPHA = 0.5, 40.0


@pytest.fixture(scope="module")
def trained():
    matrix = synthetic_stars(n_users=150, n_items=100, rank=8, mean_stars=10, seed=4)
    model = ImplicitALS(rank=8, reg_param=REG, alpha=ALPHA, max_iter=4).fit(matrix)
    return matrix, model


def _random_rows(n_items, n_rows, seed):
    """Synthetic ``(item_idx, confidence)`` fold-in rows with ragged
    lengths — what ``StarOverlay.user_row`` hands the engine."""
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n_rows):
        k = int(rng.integers(1, 12))
        idx = rng.choice(n_items, size=k, replace=False).astype(np.int64)
        val = rng.uniform(0.5, 4.0, size=k).astype(np.float32)
        rows.append((idx, val))
    return rows


# --- per-device pricing -------------------------------------------------------


class TestPlanFoldin:
    def test_single_device_price_is_the_legacy_plan(self):
        old = capacity.plan_foldin(64, 32, 8, 100)
        new = capacity.plan_foldin(64, 32, 8, 100, n_devices=1, mode="ring")
        assert old.workload == new.workload == "foldin"
        assert old.items == new.items
        assert "transient_assembly" not in new.items

    def test_mesh_rungs_scale_per_device(self):
        p1 = capacity.plan_foldin(64, 32, 8, 1000)
        p4 = capacity.plan_foldin(64, 32, 8, 1000, n_devices=4)
        assert p4.workload == "foldin_sharded"
        # Each device holds 1/4 of the item table and 1/4 of the slab.
        assert p4.items["frozen_item_side"] < p1.items["frozen_item_side"]
        assert p4.items["rung_slab"] == p1.items["rung_slab"] // 4
        # The all-gather transient is the whole padded item table.
        i_pad = p4.items["transient_assembly"] // (8 * 4)
        assert i_pad >= 1000 and i_pad % 4 == 0

    def test_ring_transient_undercuts_allgather(self):
        ag = capacity.plan_foldin(64, 32, 8, 1000, n_devices=4, mode="allgather")
        ring = capacity.plan_foldin(64, 32, 8, 1000, n_devices=4, mode="ring")
        assert ring.workload == "foldin_sharded_ring"
        # Ring holds two 1/n shards in flight vs the full gathered table —
        # 2/n of the all-gather transient, what the admission ladder trades on.
        assert (
            ring.items["transient_assembly"] * 4
            == ag.items["transient_assembly"] * 2
        )
        assert ring.required_bytes < ag.required_bytes


# --- owner routing geometry ---------------------------------------------------


def _geometry(n_shards: int, n_users: int) -> ShardedFoldIn:
    """Routing geometry only (pure numpy) — no mesh or device required, so
    shard counts a 1-CPU test box cannot boot are still coverable."""
    sf = ShardedFoldIn.__new__(ShardedFoldIn)
    sf.n_shards = n_shards
    sf.n_users = n_users
    return sf


class TestRouting:
    def test_owners_follow_user_table_shard_blocks(self):
        sf = _geometry(4, 100)  # rows_per = ceil(100/4) = 25
        got = sf.owners([0, 24, 25, 50, 74, 75, 99])
        assert got.tolist() == [0, 0, 1, 2, 2, 3, 3]

    def test_pad_tail_users_clamp_to_the_last_shard(self):
        sf = _geometry(4, 10)  # rows_per = 3: users 9.. belong to shard 3
        assert sf.owners([9]).tolist() == [3]

    def test_round_robin_without_a_user_table(self):
        sf = _geometry(4, 0)
        assert sf.owners([0, 1, 5, 11]).tolist() == [0, 1, 1, 3]

    def test_build_slab_routes_and_unpermutes(self):
        sf = _geometry(2, 8)  # rows_per = 4: users 0-3 -> shard 0
        rows = _random_rows(50, 5, seed=3)
        owners = np.array([0, 1, 1, 0, 1])
        idx, val, mask, pos = sf.build_slab(rows, owners)
        # 3 rows on the busiest shard -> pow2 block of 4 per shard.
        assert idx.shape[0] == 2 * 4 and idx.shape == val.shape == mask.shape
        assert (idx.shape[1] & (idx.shape[1] - 1)) == 0  # pow2 length
        for j, (ri, rv) in enumerate(rows):
            r = pos[j]
            # Row j landed inside its owner's block...
            assert owners[j] * 4 <= r < (owners[j] + 1) * 4
            # ...carrying exactly its entries.
            assert np.array_equal(idx[r, : ri.size], ri)
            assert np.allclose(val[r, : ri.size], rv)
            assert mask[r].sum() == ri.size
        assert len(set(pos.tolist())) == len(rows)


# --- 1-device mesh parity -----------------------------------------------------

# Everything below compiles shard_map programs (engine construction alone
# pays the sharded-Gramian trace); the tier-1 budget on a CPU box cannot
# absorb them, so they ride the slow lane with the chaos drills. The pure
# host-side pricing/routing tests above stay tier-1.


@pytest.mark.slow
class TestMeshParity:
    @pytest.mark.parametrize("mode", ["allgather", "ring"])
    def test_mesh_engine_matches_single_device(self, trained, mode):
        matrix, model = trained
        rows = _random_rows(matrix.n_items, 23, seed=9)
        single = FoldInEngine(model, reg_param=REG, alpha=ALPHA, max_batch=16)
        mesh = FoldInEngine(
            model, reg_param=REG, alpha=ALPHA, max_batch=16,
            mesh=make_mesh(1), shard_mode=mode,
        )
        want = single.fold_in(rows)
        got = mesh.fold_in(rows)
        assert np.allclose(got, want, atol=1e-5), np.abs(got - want).max()
        assert mesh.last_admission is not None
        assert mesh.last_admission["n_devices"] == 1
        # A 1-device mesh prices as the plain fold-in rung.
        assert mesh.last_admission["chosen"] == "foldin"

    def test_warm_registers_sharded_executables(self, trained):
        _, model = trained
        engine = FoldInEngine(
            model, reg_param=REG, alpha=ALPHA, max_batch=16, mesh=make_mesh(1),
        )
        assert engine.warm((8,)) >= 1

    def test_injected_oom_degrades_never_refuses(self, trained, monkeypatch):
        """The never-refuse contract on the mesh: an injected admission oom
        forces the preferred rung over budget; the batch must still fold
        (degraded), with the verdict on the admission record."""
        monkeypatch.setenv("ALBEDO_DEVICE_MEM_BYTES", str(64 << 30))
        matrix, model = trained
        rows = _random_rows(matrix.n_items, 8, seed=2)
        engine = FoldInEngine(
            model, reg_param=REG, alpha=ALPHA, max_batch=16, mesh=make_mesh(1),
        )
        reference = FoldInEngine(
            model, reg_param=REG, alpha=ALPHA, max_batch=16,
        ).fold_in(rows)
        faults.arm("capacity.admit", kind="oom", at=1)
        try:
            solved = engine.fold_in(rows)
        finally:
            faults.disarm("capacity.admit")
        assert np.allclose(solved, reference, atol=1e-5)
        assert engine.last_admission["verdict"] in ("degrade", "refuse", "fit")
        assert engine.last_admission["chosen"] != ""


# --- the elastic streaming cycle ----------------------------------------------


def make_ctx(tag, **args_over):
    ns = argparse.Namespace(
        small=True, tables=None, now=1700000000.0, no_compilation_cache=True,
        data_policy=None, solver="cholesky", cg_steps=3, checkpoint_every=0,
        resume=False, keep_last=3, _rest=[],
        **args_over,
    )
    tables = synthetic_tables(n_users=120, n_items=80, mean_stars=10, seed=11)
    return JobContext(ns, tables=tables, tag=tag), ns


def _opts(**over):
    base = dict(
        cycles=1, delta_batch=60, stream_seed=7, deltas="",
        drift_tolerance=0.05, drift_floor=0.0, drift_every=1,
        half_life_days=7.0, recency_boost=1.0, foldout_limit=0,
        max_foldin_batch=16, probe_users=40, no_publish=False,
        keep_stream=3, refit_checkpoint_every=2,
    )
    base.update(over)
    return argparse.Namespace(**base)


@pytest.mark.slow
class TestElasticStream:
    def test_mesh_stream_journals_rung_and_stamps_lineage(self):
        """A clean mesh stream: mesh_events on the journal, the rung on the
        cycle record and the lineage stamp — and the reload gate PROMOTES
        the mesh-published generation into a single-device service (the
        stamp gate reads named lineage keys, so a rung change between
        publisher and reloader is tolerated by construction)."""
        from albedo_tpu.serving.reload import HotSwapManager
        from albedo_tpu.serving.service import RecommendationService

        ctx, ns = make_ctx("streammesh", mesh_devices=1)
        journal = run_stream(ctx, ns, _opts())
        me = journal["mesh_events"]
        assert me["n_shards_start"] == 1 and me["n_shards"] == 1
        assert me["losses"] == 0 and me["remeshes"] == []
        rec = journal["cycles"][0]["foldin"]
        assert rec["n_devices"] == 1
        assert rec["admission"]["chosen"] == "foldin"
        g1 = store.artifact_path(
            ctx.artifact_name(f"{ctx.als_key()}-stream-g1.pkl")
        )
        assert store.verify_manifest(g1) is True
        assert store.read_meta(g1)["lineage"]["n_devices"] == 1
        with RecommendationService(ctx.als_model(), ctx.matrix()) as service:
            manager = HotSwapManager(
                service, artifact_glob=f"{ctx.tag}-alsModel-*stream-g*.pkl"
            )
            assert manager.request_reload()["outcome"] == "promoted"

    def test_loss_with_no_rung_below_fails_clean_with_nothing_published(self):
        """The 1-device loss contract: a collective loss with no smaller
        rung raises MeshLost (counted, resume outcome ``failed``) and the
        drained cycle publishes NOTHING — no half-applied generation."""
        ctx, ns = make_ctx("streammeshloss", mesh_devices=1)
        losses = events.mesh_losses.total()
        failed = events.elastic_resumes.value(outcome="failed")
        faults.arm("stream.foldin.collective", kind="loss", at=1)
        try:
            with pytest.raises(MeshLost):
                run_stream(ctx, ns, _opts())
        finally:
            faults.disarm("stream.foldin.collective")
        assert events.mesh_losses.total() == losses + 1
        assert events.elastic_resumes.value(outcome="failed") == failed + 1
        g1 = store.artifact_path(
            ctx.artifact_name(f"{ctx.als_key()}-stream-g1.pkl")
        )
        assert not g1.exists()


# --- bank publish on the mesh -------------------------------------------------


@pytest.mark.slow
class TestBankMeshPublish:
    def test_mesh_foldin_publishes_and_survives_reshard(self, trained):
        """The streaming overlay on the mesh: folded rows land in the
        serving bank with no recompile, and a mid-stream ``reshard`` keeps
        SUBSEQUENT fold-ins landing on the new layout."""
        from albedo_tpu.retrieval.bank import RetrievalBank

        matrix, model = trained
        bank = RetrievalBank(max_batch=8)
        bank.register_source(
            "als", kind="user_rows", vectors=model.item_factors,
            item_ids=np.asarray(matrix.item_ids),
            user_vectors=model.user_factors,
        )
        bank.build(matrix=matrix)
        engine = FoldInEngine(
            model, reg_param=REG, alpha=ALPHA, max_batch=16, mesh=make_mesh(1),
        )
        engine.attach_bank(bank, "als")

        uidx1 = np.array([3, 7, 11], dtype=np.int64)
        solved1 = engine.fold_in(
            _random_rows(matrix.n_items, len(uidx1), seed=5), user_idx=uidx1
        )
        gen1 = bank.overlay_generation
        assert gen1 >= 1
        assert np.array_equal(bank.specs["als"].user_vectors[uidx1], solved1)

        bank.reshard(make_mesh(1))

        uidx2 = np.array([2, 19], dtype=np.int64)
        solved2 = engine.fold_in(
            _random_rows(matrix.n_items, len(uidx2), seed=6), user_idx=uidx2
        )
        assert bank.overlay_generation > gen1
        assert np.array_equal(bank.specs["als"].user_vectors[uidx2], solved2)
        # Earlier overlay rows survived the reshard, and queries answer.
        assert np.array_equal(bank.specs["als"].user_vectors[uidx1], solved1)
        vals, _ = bank.query(uidx1, k=5, sources=("als",))["als"]
        assert np.isfinite(np.asarray(vals)).all()
