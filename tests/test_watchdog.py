"""The training divergence watchdog: on-device health stats, tripwires,
damped remediation, the ``train.watchdog`` chaos site, and the shared
quarantine helper (``utils/watchdog.py``, ``utils/quarantine.py``)."""

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from albedo_tpu.datasets.synthetic import synthetic_stars  # noqa: E402
from albedo_tpu.models.als import ImplicitALS  # noqa: E402
from albedo_tpu.utils import events, faults  # noqa: E402
from albedo_tpu.utils.checkpoint import StepCheckpointer, checkpointed_als_fit  # noqa: E402
from albedo_tpu.utils.quarantine import next_marked_path, quarantine_rename  # noqa: E402
from albedo_tpu.utils.watchdog import (  # noqa: E402
    DivergenceWatchdog,
    TrainingDiverged,
    check_lr_loss,
    damped,
    factor_health,
    guarded_fit,
    health_dict,
)


def test_factor_health_device_stats():
    uf = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    vf = np.array([[0.5, np.nan]], np.float32)
    h = health_dict(factor_health(uf, vf))
    assert h["nonfinite"] == 1
    assert h["max_abs"] == pytest.approx(4.0)
    # RMS = max over tables, NaN treated as 0 in the finite view.
    assert h["rms"] == pytest.approx(float(np.sqrt(np.mean(np.square(uf)))))


def test_watchdog_trips_nonfinite_norm_trajectory():
    wd = DivergenceWatchdog(max_rms=10.0, max_growth=5.0)
    ok = np.ones((4, 4), np.float32)
    assert wd.check(1, ok, ok) == []

    bad = ok.copy(); bad[0, 0] = np.inf
    assert wd.check(2, bad, ok) == ["nonfinite"]
    assert events.watchdog_trips.value(kind="nonfinite") == 1

    assert "norm" in wd.check(3, np.full((4, 4), 100.0, np.float32), ok)
    # 1.0 -> 8.0 is an >5x jump vs the last HEALTHY baseline (step 1).
    assert wd.check(4, np.full((4, 4), 8.0, np.float32), ok) == ["trajectory"]
    assert len(wd.trips) == 3 and not any(t["remediated"] for t in wd.trips)


def test_trajectory_baseline_only_advances_on_healthy_checks():
    wd = DivergenceWatchdog(max_rms=1e6, max_growth=3.0)
    one = np.ones((2, 2), np.float32)
    assert wd.check(1, one, one) == []
    # A 4x explosion trips; a SECOND check at the same level must still trip
    # (the tripped check must not have ratcheted the baseline up to 4.0).
    assert wd.check(2, 4 * one, one) == ["trajectory"]
    assert wd.check(3, 4 * one, one) == ["trajectory"]


def test_fault_site_scribbles_nan_into_check():
    wd = DivergenceWatchdog()
    ok = np.ones((3, 3), np.float32)
    faults.arm("train.watchdog", kind="error", at=1)
    assert wd.check(1, ok, ok) == ["nonfinite"]
    assert wd.trips[-1]["nonfinite"] == 1
    # The caller's array is untouched — the scribble happens on a copy.
    assert np.isfinite(ok).all()
    assert wd.check(2, ok, ok) == []  # fault exhausted; healthy again


def test_damped_estimator_stabilizers():
    als = ImplicitALS(rank=4, reg_param=0.5, gather_dtype="bfloat16")
    d = damped(als)
    assert d.gather_dtype is None
    assert d.reg_param == pytest.approx(5.0)
    assert d.rank == als.rank


@dataclasses.dataclass
class _FakeALS:
    """Estimator double for guarded_fit: diverges for the first ``sick``
    fits, then recovers (remediation replaces the instance via
    ``dataclasses.replace``, so call counting lives in a shared list)."""

    reg_param: float = 0.5
    gather_dtype: str | None = "bfloat16"
    max_iter: int = 4
    sick: int = 1
    calls: list = dataclasses.field(default_factory=list)

    def fit(self, matrix):
        self.calls.append(self.reg_param)
        f = np.ones((3, 2), np.float32)
        if len(self.calls) <= self.sick:
            f = f * np.nan
        return dataclasses.replace(_Model(), user_factors=f, item_factors=f)


@dataclasses.dataclass
class _Model:
    user_factors: np.ndarray = None
    item_factors: np.ndarray = None


def test_guarded_fit_remediates_once():
    calls = []
    als = _FakeALS(sick=1, calls=calls)
    model, trips = guarded_fit(als, matrix=None)
    assert np.isfinite(model.user_factors).all()
    # Second call came from the damped estimator: 10x regularization.
    assert calls == [0.5, 5.0]
    assert len(trips) == 1 and trips[0]["remediated"] is True
    assert trips[0]["kinds"] == ["nonfinite"]


def test_guarded_fit_raises_when_remediation_fails():
    als = _FakeALS(sick=2, calls=[])
    with pytest.raises(TrainingDiverged):
        guarded_fit(als, matrix=None)
    assert events.watchdog_trips.value(kind="nonfinite") == 2


def test_checkpointed_fit_remediates_tripped_chunk(tmp_path):
    """The mid-fit NaN drill, in process: a chunk-boundary check trips (the
    fault site scribbles NaN), the chunk re-runs damped from the previous
    checkpoint, the fit completes, and the journal records the remediated
    trip."""
    m = synthetic_stars(n_users=120, n_items=70, mean_stars=8, seed=6)
    als = ImplicitALS(rank=8, max_iter=4, seed=4)
    wd = DivergenceWatchdog()
    faults.arm("train.watchdog", kind="error", at=2)  # trips the 2nd check
    model = checkpointed_als_fit(
        als, m, tmp_path / "wd", every=2, watchdog=wd
    )
    assert np.isfinite(model.user_factors).all()
    assert len(wd.trips) == 1 and wd.trips[0]["remediated"] is True
    journal = StepCheckpointer(tmp_path / "wd").read_journal()
    assert journal["status"] == "complete"
    assert journal["watchdog"][0]["kinds"] == ["nonfinite"]
    assert journal["watchdog"][0]["remediated"] is True
    assert events.watchdog_trips.value(kind="nonfinite") == 1


def test_checkpointed_fit_gives_up_after_failed_remediation(tmp_path):
    m = synthetic_stars(n_users=80, n_items=50, mean_stars=6, seed=6)
    als = ImplicitALS(rank=8, max_iter=2, seed=4)
    wd = DivergenceWatchdog()
    faults.arm("train.watchdog", kind="error", at=1, times=2)  # both checks
    with pytest.raises(TrainingDiverged):
        checkpointed_als_fit(als, m, tmp_path / "div", every=2, watchdog=wd)
    journal = StepCheckpointer(tmp_path / "div").read_journal()
    assert journal["status"] == "diverged"
    assert any(not t["remediated"] for t in journal["watchdog"])


def test_check_lr_loss():
    assert check_lr_loss(0.31)
    assert not check_lr_loss(float("nan"))
    assert not check_lr_loss(float("inf"))
    assert events.watchdog_trips.value(kind="lr") == 2


# --- the shared quarantine convention -----------------------------------------


def test_next_marked_path_numbers_from_one(tmp_path):
    p = tmp_path / "model.pkl"
    assert next_marked_path(p).name == "model.pkl.corrupt-1"
    (tmp_path / "model.pkl.corrupt-1").touch()
    assert next_marked_path(p).name == "model.pkl.corrupt-2"
    assert next_marked_path(p, ".quarantine-", ".csv").name == "model.pkl.quarantine-1.csv"


def test_quarantine_rename_moves_sidecars_along(tmp_path):
    p = tmp_path / "model.pkl"
    p.write_bytes(b"data")
    (tmp_path / "model.pkl.sha256").write_text("{}")
    (tmp_path / "model.pkl.meta.json").write_text("{}")
    dest = quarantine_rename(p, reason="test")
    assert dest.name == "model.pkl.corrupt-1"
    assert not p.exists()
    # No stale sidecar may vouch for the slot's next occupant.
    assert not (tmp_path / "model.pkl.sha256").exists()
    assert (tmp_path / "model.pkl.corrupt-1.sha256").exists()
    assert (tmp_path / "model.pkl.corrupt-1.meta.json").exists()
