"""graftlint (albedo_tpu/analysis): fixtures fire, mechanics hold, tree is clean.

Four layers:

1. **Fixture proofs** — every rule R1-R5 must flag its committed ``*_bad``
   snippet and must NOT flag the near-identical ``*_ok`` one (the acceptance
   criterion: "each rule is demonstrated to fire on a committed fixture").
2. **Mechanics** — ``# albedo: noqa[rule]`` pragmas, the baseline multiset
   matching (grandfather / fresh / stale), and the CLI surface.
3. **Anchors** — the extractors must see the real tree's known surface
   (registries, AOT-fed names, hot-loop reachability), guarding against a
   refactor that silently blinds a rule.
4. **Self-lint** — zero non-baselined findings on this repo, which is what
   ``make lint`` enforces; this is the tier-1 copy of that gate.
"""

import json
from pathlib import Path

import pytest

from albedo_tpu.analysis import (
    Finding,
    ProjectTree,
    all_rules,
    apply_baseline,
    collect_findings,
    default_tree,
    load_baseline,
    write_baseline,
)
from albedo_tpu.analysis.callgraph import CallGraph
from albedo_tpu.analysis.cli import main as lint_main
from albedo_tpu.analysis.rules_contract import (
    exit_code_registry,
    metric_registry,
)
from albedo_tpu.analysis.rules_device import (
    DEFAULT_HOT_ROOTS,
    HiddenHostSync,
    _fed_names,
)

FIXTURES = Path(__file__).resolve().parent.parent / (
    "albedo_tpu/analysis/fixtures"
)


def fixture_tree(name: str) -> ProjectTree:
    return ProjectTree.load(FIXTURES / name)


def run_rule(name: str, rule_id: str, rule=None) -> list[Finding]:
    tree = fixture_tree(name)
    rules = [rule] if rule is not None else None
    return collect_findings(tree, rules=rules, rule_ids=None if rule else [rule_id])


# --- 1. fixture proofs --------------------------------------------------------


def test_bare_jit_fires_on_fixture():
    findings = run_rule("bare_jit", "bare-jit")
    flagged = {(f.line, f.message.split("`")[1]) for f in findings}
    names = {n for _, n in flagged}
    assert "bad_decorated" in names
    assert "bad_partial" in names
    assert "jitted" in names          # the bad_call_site assignment
    # Sanctioned and pragma'd sites must NOT appear.
    assert "ok_decorated" not in names
    assert "fn" not in names          # assignment-chain sanctioning
    assert len(findings) == 3, [f.render() for f in findings]


def test_hidden_host_sync_fires_on_fixture():
    rule = HiddenHostSync(
        roots=(("albedo_tpu/models/als.py", "Trainer.fit"),),
        allow_modules=(),
    )
    findings = run_rule("host_sync", "hidden-host-sync", rule=rule)
    msgs = [f.message for f in findings]
    assert any("float()" in m and "helper" in m for m in msgs), msgs
    assert any(".item()" in m for m in msgs), msgs
    assert any("np.asarray" in m and "Trainer.fit" in m for m in msgs), msgs
    # Unreachable code, out-of-loop conversions, and the pragma'd line stay
    # silent: exactly one asarray finding (the un-pragma'd loop).
    assert len(findings) == 3, [f.render() for f in findings]


def test_dtype_discipline_fires_on_fixture():
    findings = run_rule("dtype", "dtype-discipline")
    assert len(findings) == 1, [f.render() for f in findings]
    assert "bad_kernel" in findings[0].message
    assert "preferred_element_type" in findings[0].message


def test_retrace_hazard_fires_on_fixture():
    findings = run_rule("retrace", "retrace-hazard")
    msgs = [f.message for f in findings]
    assert any("bad_branch" in m and "threshold" in m for m in msgs), msgs
    assert any("bad_unhashable_static" in m and "opts" in m for m in msgs), msgs
    # Static branches, shape/identity tests, host helpers, pragmas: silent.
    assert len(findings) == 2, [f.render() for f in findings]


def test_contract_drift_fires_on_fixture():
    findings = run_rule("contract", "contract-drift")
    msgs = [f.message for f in findings]

    def has(*subs):
        return any(all(s in m for s in subs) for m in msgs)

    assert has("undocumented.site", "not in the ARCHITECTURE.md site catalog")
    assert has("ghost.site", "no code declares")
    assert has("albedo_good_total", "inline metric name")
    assert has("albedo_ghost_total", "not registered")
    assert has("albedo_phantom_total", "does not register")
    assert has("albedo_undocumented_total", "missing from the ARCHITECTURE.md")
    assert has("bare exit code 75")
    assert has("exit code 9 is outside the contract")
    assert has("documents exit code 99")
    assert has("75", "missing", "exit-code table")
    # The pragma'd `return 1` must not be among them.
    assert len(findings) == 10, [f.render() for f in findings]


# --- 2. mechanics -------------------------------------------------------------


def test_pragma_star_suppresses_all_rules(tmp_path):
    root = tmp_path / "repo"
    (root / "albedo_tpu/models").mkdir(parents=True)
    (root / "albedo_tpu/models/m.py").write_text(
        "import jax\n"
        "\n"
        "# albedo: noqa[*]\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x\n"
    )
    tree = ProjectTree.load(root)
    assert collect_findings(tree, rule_ids=["bare-jit"]) == []


def test_baseline_grandfathers_and_goes_stale(tmp_path):
    findings = run_rule("dtype", "dtype-discipline")
    assert findings
    path = tmp_path / "baseline.json"
    write_baseline(path, findings)
    baseline = load_baseline(path)

    fresh, grandfathered, stale = apply_baseline(findings, baseline)
    assert fresh == [] and len(grandfathered) == len(findings) and stale == []

    # A new finding (not in the baseline) surfaces as fresh.
    extra = Finding("dtype-discipline", "albedo_tpu/ops/new.py", 3, 0,
                    "msg", "jnp.einsum('ij,jk->ik', a, b)")
    fresh, _, stale = apply_baseline(findings + [extra], baseline)
    assert fresh == [extra] and stale == []

    # A fixed finding leaves its entry stale.
    fresh, _, stale = apply_baseline([], baseline)
    assert fresh == [] and len(stale) == len(findings)


def test_baseline_matches_as_multiset():
    f = Finding("r", "p.py", 10, 0, "m", "dup_line()")
    g = Finding("r", "p.py", 20, 0, "m", "dup_line()")
    assert f.fingerprint() == g.fingerprint()
    # One entry absorbs exactly one of the two identical-line findings.
    baseline = [f.to_dict()]
    fresh, grandfathered, stale = apply_baseline([f, g], baseline)
    assert len(fresh) == 1 and len(grandfathered) == 1 and stale == []


def test_cli_json_and_exit_codes(tmp_path, capsys):
    assert lint_main(["--list-rules"]) == 0
    capsys.readouterr()

    rc = lint_main(["--root", str(FIXTURES / "dtype"), "--no-baseline", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert len(out["findings"]) == 1
    assert out["findings"][0]["rule"] == "dtype-discipline"

    # Baselining the fixture findings turns the same run green.
    rc = lint_main([
        "--root", str(FIXTURES / "dtype"),
        "--baseline", str(tmp_path / "b.json"), "--write-baseline",
    ])
    capsys.readouterr()
    assert rc == 0
    rc = lint_main([
        "--root", str(FIXTURES / "dtype"), "--baseline", str(tmp_path / "b.json"),
    ])
    capsys.readouterr()
    assert rc == 0

    assert lint_main(["--rules", "no-such-rule"]) == 2
    capsys.readouterr()

    # A partial-rule baseline rewrite would delete other rules' entries.
    assert lint_main(["--rules", "bare-jit", "--write-baseline"]) == 2
    capsys.readouterr()


# --- 3. anchors against the real tree ----------------------------------------


def test_rule_registry_is_complete():
    assert set(all_rules()) == {
        "bare-jit", "hidden-host-sync", "contract-drift",
        "dtype-discipline", "retrace-hazard",
        # The concurrency tier (tests/test_concurrency_lint.py).
        "shared-state-guard", "lock-discipline", "executor-lifecycle",
    }


def test_metric_registry_matches_events_module():
    from albedo_tpu.utils import events

    registry = metric_registry(default_tree())
    assert set(registry) == set(events.METRIC_NAMES)
    assert "albedo_requests_total" in registry
    assert "albedo_mesh_degraded_total" in registry
    assert len(registry) >= 30


def test_exit_code_registry_matches_cli():
    from albedo_tpu import cli

    registry = exit_code_registry(default_tree())
    assert set(registry) == {0, 1, 2, 3, 4, 75, 137}
    assert registry[75][0] == "EXIT_PREEMPTED"
    assert cli.EXIT_PREEMPTED == 75 and cli.EXIT_KILLED == 137


def test_aot_fed_names_see_the_real_surface():
    fed = _fed_names(default_tree())
    # Direct feeds, conduit feeds, and assignment-chain propagation.
    for name in (
        "als_fit_fused", "als_init_fit_fused", "chunked_bucket_update",
        "_gather_topk", "_gather_topk_device_excl", "_foldin_solve",
        "make_sharded_update", "_lbfgs_fit_jit", "_lbfgs_fit_many_jit",
        "_block_logits_jit", "epoch_jit", "run_jit",
        # The pipelined sharded dataflow's programs flow through the
        # _acquire_executable conduit into the AOT layer.
        "make_pipelined_solve", "make_pipelined_landsolve",
        "make_landing_flush",
    ):
        assert name in fed, f"{name} not recognized as AOT-fed"


def test_hot_loop_reachability_sees_the_real_surface():
    from albedo_tpu.analysis.rules_device import hot_roots

    tree = default_tree()
    graph = CallGraph(tree)
    reached = {
        (f.module, f.qualname)
        for f in graph.reachable(hot_roots(tree, graph))
    }
    assert ("albedo_tpu/models/als.py", "ImplicitALS.fit") in reached
    assert ("albedo_tpu/serving/batcher.py", "MicroBatcher._execute") in reached
    assert ("albedo_tpu/streaming/foldin.py", "FoldInEngine._solve_chunk") in reached
    # The pipelined driver loop is reachable from ShardedALSFit.fit, and
    # the background prefetch uploader — which the call graph cannot
    # follow onto (Thread(target=...)) — is a DERIVED hot root from the
    # thread-root discovery, no longer hand-listed (PR 13's entries).
    assert (
        "albedo_tpu/parallel/als.py", "ShardedALSFit._half_sweep_pipelined"
    ) in reached
    assert ("albedo_tpu/parallel/als.py", "_BucketPrefetcher._run") in reached
    assert ("albedo_tpu/parallel/als.py", "ShardedALSFit.put_bucket") in reached
    # Cross-module edge through a function-local import.
    assert ("albedo_tpu/ops/als.py", "gramian") in reached


# --- 4. the self-lint gate ----------------------------------------------------


def test_repo_lints_clean_with_zero_nonbaselined_findings():
    tree = default_tree()
    findings = collect_findings(tree)
    baseline = load_baseline(tree.root / ".graftlint-baseline.json")
    fresh, _grandfathered, stale = apply_baseline(findings, baseline)
    assert fresh == [], "new graftlint findings:\n" + "\n".join(
        f.render() for f in fresh
    )
    assert stale == [], (
        "stale baseline entries (finding fixed? regenerate with "
        "`make lint-baseline` and commit the shrink): "
        + json.dumps(stale, indent=2)
    )


def test_known_intentional_sites_carry_pragmas_not_baseline():
    """The tree's intentional exceptions are pragma'd in place (reviewable
    reasons), so the checked-in baseline stays empty."""
    baseline = load_baseline(default_tree().root / ".graftlint-baseline.json")
    assert baseline == []


@pytest.mark.parametrize("rule_id", sorted(all_rules()))
def test_each_rule_runs_standalone_on_the_tree(rule_id):
    """Every rule executes over the real tree without raising (pragmas may
    silence them; this is the no-crash guarantee per rule)."""
    collect_findings(default_tree(), rule_ids=[rule_id])
