"""Memory-budget admission (utils.capacity): detection, pricing, verdicts,
the oom fault conversion, the compiler cross-check, and the OOM-permanent
retry classification (the fail-fast-to-degrade contract)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from albedo_tpu.utils import capacity, events, faults  # noqa: E402
from albedo_tpu.utils.faults import InjectedResourceExhausted  # noqa: E402
from albedo_tpu.utils.retry import (  # noqa: E402
    RetriesExhausted,
    RetryPolicy,
    default_retry_predicate,
    is_resource_exhausted,
    retry_call,
)


# --- detection ----------------------------------------------------------------


class TestDetection:
    def test_env_override_wins(self, monkeypatch):
        monkeypatch.setenv("ALBEDO_DEVICE_MEM_BYTES", "123456")
        assert capacity.device_memory_bytes() == 123456

    def test_env_override_suffixes(self, monkeypatch):
        monkeypatch.setenv("ALBEDO_DEVICE_MEM_BYTES", "2g")
        assert capacity.device_memory_bytes() == 2 << 30
        monkeypatch.setenv("ALBEDO_DEVICE_MEM_BYTES", "512m")
        assert capacity.device_memory_bytes() == 512 << 20

    def test_detection_without_env_is_positive(self, monkeypatch):
        monkeypatch.delenv("ALBEDO_DEVICE_MEM_BYTES", raising=False)
        # CPU CI: memory_stats is absent -> /proc/meminfo or the fallback.
        assert capacity.device_memory_bytes() > 1 << 20

    def test_budget_applies_headroom(self, monkeypatch):
        monkeypatch.setenv("ALBEDO_DEVICE_MEM_BYTES", "1000000")
        monkeypatch.setenv("ALBEDO_MEM_HEADROOM", "0.5")
        assert capacity.budget_bytes() == 500000

    def test_capacity_off_switch(self, monkeypatch):
        monkeypatch.setenv("ALBEDO_DEVICE_MEM_BYTES", "10")
        monkeypatch.setenv("ALBEDO_CAPACITY", "off")
        plan = capacity.CapacityPlan("x", {"stuff": 10**12})
        assert capacity.admit(plan).verdict == "fit"


# --- pricing ------------------------------------------------------------------


class TestPlans:
    def test_plan_fit_items_and_monotonicity(self):
        small = capacity.plan_fit([(8, 16)], [(8, 16)], 100, 50, 8)
        big = capacity.plan_fit([(64, 128)], [(64, 128)], 100, 50, 8)
        assert set(small.items) == {
            "factor_tables", "bucket_slabs", "landing_pools", "transient_gather",
        }
        assert 0 < small.required_bytes < big.required_bytes
        # bf16 gathers stream fewer bytes.
        bf16 = capacity.plan_fit([(64, 128)], [(64, 128)], 100, 50, 8, "bfloat16")
        assert bf16.required_bytes < big.required_bytes

    def test_chunked_plan_is_cheaper_than_resident(self):
        shapes = [(64, 64), (32, 128), (128, 16)]
        resident = capacity.plan_fit(shapes, shapes, 500, 300, 16)
        chunked = capacity.plan_fit_chunked(shapes, shapes, 500, 300, 16)
        assert chunked.required_bytes < resident.required_bytes

    def test_plan_serve_scales_with_generations(self):
        one = capacity.plan_serve(1000, 500, 16, excl_entries=100, generations=1)
        two = capacity.plan_serve(1000, 500, 16, excl_entries=100, generations=2)
        assert two.items["factor_tables"] == 2 * one.items["factor_tables"]

    def test_max_foldin_entries_monotone_in_budget(self):
        lo = capacity.max_foldin_entries(16, 1000, budget=100_000)
        hi = capacity.max_foldin_entries(16, 1000, budget=1_000_000)
        assert 1 <= lo < hi

    def test_max_foldin_entries_floor_is_one(self):
        assert capacity.max_foldin_entries(16, 10**6, budget=10) == 1

    def test_max_foldin_entries_longer_rungs_amortize_the_gramian(self):
        # The per-slot (B, rank, rank) correction amortizes over length: a
        # longer rung gets a larger entry budget, and the default length=1
        # is the conservative floor (never under-prices 1-star rows).
        short = capacity.max_foldin_entries(50, 1000, budget=10_000_000)
        long_ = capacity.max_foldin_entries(50, 1000, budget=10_000_000, length=64)
        assert short < long_

    def test_bucket_plan_shapes_match_planner(self):
        from albedo_tpu.datasets.ragged import plan_buckets
        from albedo_tpu.datasets.synthetic import synthetic_stars

        m = synthetic_stars(n_users=80, n_items=40, mean_stars=6, seed=0)
        indptr = m.csr()[0]
        shapes = capacity.bucket_plan_shapes(indptr, batch_size=16)
        assert shapes == [p.shape for p in plan_buckets(indptr, batch_size=16)]
        assert all(b >= 1 and ln >= 1 for b, ln in shapes)


class TestShardedPlans:
    SHAPES = [(64, 64), (32, 128), (128, 16)]

    def test_per_device_bytes_shrink_with_devices(self):
        p1 = capacity.plan_fit_sharded(self.SHAPES, self.SHAPES, 4000, 2000, 16, 1)
        p8 = capacity.plan_fit_sharded(self.SHAPES, self.SHAPES, 4000, 2000, 16, 8)
        assert p8.required_bytes < p1.required_bytes

    def test_streamed_sync_keeps_one_slab_in_flight(self):
        resident = capacity.plan_fit_sharded(
            self.SHAPES, self.SHAPES, 4000, 2000, 16, 8, streamed=False
        )
        streamed = capacity.plan_fit_sharded(
            self.SHAPES, self.SHAPES, 4000, 2000, 16, 8, streamed=True,
            pipelined=False,
        )
        assert streamed.workload == "als_fit_sharded_streamed_sync"
        assert streamed.required_bytes < resident.required_bytes
        assert "streamed_slab_in_flight" in streamed.items
        assert "bucket_slab_shards" in resident.items
        assert (
            streamed.items["streamed_slab_in_flight"]
            < resident.items["bucket_slab_shards"]
        )

    def test_pipelined_streamed_prices_two_slabs_in_flight(self):
        """The double-buffered prefetch holds the bucket being solved AND
        the one the background uploader just landed: the pipelined-streamed
        rung prices the two LARGEST slab shards, strictly more than the
        synchronous single slab and strictly less than two copies of the
        worst (the two in-flight buckets are distinct buckets)."""
        sync = capacity.plan_fit_sharded(
            self.SHAPES, self.SHAPES, 4000, 2000, 16, 8, streamed=True,
            pipelined=False,
        )
        piped = capacity.plan_fit_sharded(
            self.SHAPES, self.SHAPES, 4000, 2000, 16, 8, streamed=True,
            pipelined=True,
        )
        assert piped.workload == "als_fit_sharded_streamed"
        assert "streamed_slabs_in_flight" in piped.items
        worst = sync.items["streamed_slab_in_flight"]
        assert worst < piped.items["streamed_slabs_in_flight"] <= 2 * worst
        # Everything else prices identically: the pipeline costs exactly
        # one extra in-flight slab, nothing hidden.
        assert piped.items["factor_table_shards"] == sync.items["factor_table_shards"]
        assert piped.items["transient_assembly"] == sync.items["transient_assembly"]

    def test_ladder_ordering_pipelined_above_sync(self):
        """The admission ladder's degradation order holds: resident >
        pipelined-streamed > synchronous-streamed, so a budget squeezed
        between the last two picks unpipelined-streamed as the cheaper
        rung instead of refusing."""
        resident = capacity.plan_fit_sharded(
            self.SHAPES, self.SHAPES, 4000, 2000, 16, 8, streamed=False
        )
        piped = capacity.plan_fit_sharded(
            self.SHAPES, self.SHAPES, 4000, 2000, 16, 8, streamed=True
        )
        sync = capacity.plan_fit_sharded(
            self.SHAPES, self.SHAPES, 4000, 2000, 16, 8, streamed=True,
            pipelined=False,
        )
        assert sync.required_bytes < piped.required_bytes < resident.required_bytes
        verdict = capacity.admit_ladder(
            [resident, piped, sync], budget=sync.required_bytes + 1
        )
        assert verdict.verdict == "degrade"
        assert verdict.chosen == "als_fit_sharded_streamed_sync"

    def test_ring_transient_below_allgather(self):
        # Ring never materializes a full table: at large table sizes its
        # per-device transient is a fraction of the all-gather mode's.
        ag = capacity.plan_fit_sharded(
            self.SHAPES, self.SHAPES, 10**6, 10**5, 32, 8, mode="allgather"
        )
        ring = capacity.plan_fit_sharded(
            self.SHAPES, self.SHAPES, 10**6, 10**5, 32, 8, mode="ring"
        )
        assert ring.items["transient_assembly"] < ag.items["transient_assembly"]

    def test_cg_prices_the_target_assembly_too(self):
        chol = capacity.plan_fit_sharded(
            self.SHAPES, self.SHAPES, 10**5, 10**5, 32, 8, solver="cholesky"
        )
        cg = capacity.plan_fit_sharded(
            self.SHAPES, self.SHAPES, 10**5, 10**5, 32, 8, solver="cg"
        )
        assert cg.items["transient_assembly"] > chol.items["transient_assembly"]

    def test_mesh_resident_divides_slabs_not_tables(self):
        one = capacity.plan_fit(self.SHAPES, self.SHAPES, 4000, 2000, 16)
        eight = capacity.plan_fit(
            self.SHAPES, self.SHAPES, 4000, 2000, 16, n_devices=8
        )
        assert eight.items["factor_tables"] == one.items["factor_tables"]
        assert eight.items["bucket_slabs"] < one.items["bucket_slabs"]

    def test_sharded_tables_scale_down_with_devices(self):
        p2 = capacity.plan_fit_sharded(self.SHAPES, self.SHAPES, 4000, 2000, 16, 2)
        p8 = capacity.plan_fit_sharded(self.SHAPES, self.SHAPES, 4000, 2000, 16, 8)
        assert p8.items["factor_table_shards"] < p2.items["factor_table_shards"]


class TestAdmitLadder:
    def _ladder(self):
        return [
            capacity.CapacityPlan("a", {"x": 1000}),
            capacity.CapacityPlan("b", {"x": 500}),
            capacity.CapacityPlan("c", {"x": 100}),
        ]

    def test_first_rung_fits(self):
        v = capacity.admit_ladder(self._ladder(), budget=2000)
        assert v.verdict == "fit" and v.chosen == "a"

    def test_degrade_picks_first_fitting_rung(self):
        v = capacity.admit_ladder(self._ladder(), budget=600)
        assert v.verdict == "degrade" and v.chosen == "b"
        v = capacity.admit_ladder(self._ladder(), budget=200)
        assert v.verdict == "degrade" and v.chosen == "c"

    def test_refuse_when_no_rung_fits(self):
        v = capacity.admit_ladder(self._ladder(), budget=50)
        assert v.verdict == "refuse" and v.chosen == ""
        assert "every rung" in v.detail

    def test_one_counted_verdict_per_call(self):
        before = events.capacity_verdicts.value(verdict="degrade", workload="a")
        capacity.admit_ladder(self._ladder(), budget=600)
        assert events.capacity_verdicts.value(
            verdict="degrade", workload="a"
        ) == before + 1

    def test_injected_oom_lands_on_the_second_rung(self):
        faults.arm("capacity.admit", kind="oom", at=1)
        v = capacity.admit_ladder(self._ladder(), budget=10**9)
        assert v.verdict == "degrade" and v.chosen == "b"
        assert "injected" in v.detail

    def test_empty_ladder_rejected(self):
        with pytest.raises(ValueError, match="at least one"):
            capacity.admit_ladder([], budget=100)

    def test_verdict_to_dict_carries_chosen(self):
        v = capacity.admit_ladder(self._ladder(), budget=600)
        assert v.to_dict()["chosen"] == "b"


# --- admission ----------------------------------------------------------------


class TestAdmit:
    def test_fit_within_budget(self):
        v = capacity.admit(capacity.CapacityPlan("w", {"a": 10}), budget=100)
        assert v.verdict == "fit" and v.fits

    def test_degrade_when_degradable(self):
        v = capacity.admit(
            capacity.CapacityPlan("w", {"a": 1000}), budget=100, degradable=True
        )
        assert v.verdict == "degrade"

    def test_refuse_when_not_degradable(self):
        v = capacity.admit(capacity.CapacityPlan("w", {"a": 1000}), budget=100)
        assert v.verdict == "refuse"

    def test_verdicts_counted(self):
        before = events.capacity_verdicts.value(verdict="refuse", workload="w")
        capacity.admit(capacity.CapacityPlan("w", {"a": 1000}), budget=100)
        assert events.capacity_verdicts.value(
            verdict="refuse", workload="w"
        ) == before + 1

    def test_armed_oom_forces_over_budget_not_crash(self):
        faults.arm("capacity.admit", kind="oom", at=1)
        v = capacity.admit(
            capacity.CapacityPlan("w", {"a": 1}), budget=10**9, degradable=True
        )
        assert v.verdict == "degrade"
        assert "injected" in v.detail

    def test_armed_error_kind_still_propagates(self):
        # Only OOM converts to a verdict; other kinds are real failures.
        faults.arm("capacity.admit", kind="error", at=1)
        with pytest.raises(faults.FaultInjected):
            capacity.admit(capacity.CapacityPlan("w", {"a": 1}), budget=10**9)

    def test_capacity_exceeded_message_carries_pricing(self):
        v = capacity.admit(capacity.CapacityPlan("w", {"a": 1000}), budget=100)
        err = capacity.CapacityExceeded(v)
        assert "refused: capacity" in str(err)
        assert err.verdict.required_bytes == 1000

    def test_capacity_exceeded_is_retry_permanent(self):
        # A deterministic refusal must fail FAST through the pipeline's
        # stage retries — same contract as a real device OOM.
        v = capacity.admit(capacity.CapacityPlan("w", {"a": 1000}), budget=100)
        assert is_resource_exhausted(capacity.CapacityExceeded(v))
        assert not default_retry_predicate(capacity.CapacityExceeded(v))


# --- compiler cross-check -----------------------------------------------------


class TestCrossCheck:
    def test_cross_check_on_real_executable(self):
        import jax.numpy as jnp

        compiled = jax.jit(lambda x: x @ x.T).lower(
            jnp.zeros((64, 32), jnp.float32)
        ).compile()
        analysis = capacity.compiled_memory_bytes(compiled)
        if analysis is None:
            pytest.skip("backend exposes no memory_analysis")
        assert analysis["total"] >= 0
        record = capacity.cross_check(
            capacity.CapacityPlan("x", {"a": max(1, analysis["total"])}), compiled
        )
        assert record is None or record["ratio"] <= 2.0

    def test_cross_check_tolerates_garbage_handle(self):
        assert capacity.compiled_memory_bytes(object()) is None
        assert capacity.cross_check(capacity.CapacityPlan("x", {"a": 1}), object()) is None


# --- the OOM retry classification (satellite) ---------------------------------


class TestResourceExhaustedClassification:
    def test_injected_oom_is_resource_exhausted(self):
        exc = InjectedResourceExhausted("RESOURCE_EXHAUSTED: injected")
        assert is_resource_exhausted(exc)
        assert not default_retry_predicate(exc)

    def test_memoryerror_is_permanent(self):
        assert is_resource_exhausted(MemoryError("oom"))

    def test_xla_shaped_error_by_name_and_message(self):
        XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})
        assert is_resource_exhausted(
            XlaRuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating 1g")
        )
        assert not is_resource_exhausted(XlaRuntimeError("INVALID_ARGUMENT"))

    def test_ordinary_errors_stay_retryable(self):
        assert default_retry_predicate(OSError("flaky disk"))
        assert default_retry_predicate(RuntimeError("transient"))

    def test_retry_call_fails_fast_on_oom_by_default(self):
        calls = []

        def attempt():
            calls.append(1)
            raise InjectedResourceExhausted("RESOURCE_EXHAUSTED: boom")

        with pytest.raises(InjectedResourceExhausted):
            retry_call(
                attempt, policy=RetryPolicy(max_attempts=5, jitter=False),
                sleeper=lambda s: None, site="t",
            )
        assert len(calls) == 1  # no backoff budget burned re-OOMing

    def test_retry_call_still_retries_transients(self):
        calls = []

        def attempt():
            calls.append(1)
            raise OSError("flaky")

        with pytest.raises(RetriesExhausted):
            retry_call(
                attempt, policy=RetryPolicy(max_attempts=3, jitter=False),
                sleeper=lambda s: None, site="t",
            )
        assert len(calls) == 3

    def test_oom_fault_kind_fires_and_counts(self):
        faults.arm("x.site", kind="oom", at=1)
        with pytest.raises(InjectedResourceExhausted) as ei:
            faults.hit("x.site")
        assert "RESOURCE_EXHAUSTED" in str(ei.value)
        assert faults.FAULTS.fired("x.site") == 1

    def test_oom_kind_parses_from_env(self):
        reg = faults.FaultRegistry(env="a.b:oom@2")
        reg.hit("a.b")
        with pytest.raises(InjectedResourceExhausted):
            reg.hit("a.b")


# --- end to end: admission drives the estimator -------------------------------


class TestEstimatorAdmission:
    def test_admission_fit_verdict_on_roomy_budget(self, monkeypatch):
        from albedo_tpu.datasets.synthetic import synthetic_stars
        from albedo_tpu.models.als import ImplicitALS

        monkeypatch.setenv("ALBEDO_DEVICE_MEM_BYTES", "4g")
        m = synthetic_stars(n_users=60, n_items=40, mean_stars=5, seed=0)
        assert ImplicitALS(rank=8, batch_size=16).admission(m).verdict == "fit"

    def test_admission_refuses_when_even_chunked_is_over(self, monkeypatch):
        from albedo_tpu.datasets.synthetic import synthetic_stars
        from albedo_tpu.models.als import ImplicitALS

        m = synthetic_stars(n_users=60, n_items=40, mean_stars=5, seed=0)
        est = ImplicitALS(rank=8, batch_size=16)
        chunked = est.capacity_plan(m, chunked=True)
        monkeypatch.setenv(
            "ALBEDO_DEVICE_MEM_BYTES", str(chunked.required_bytes // 2)
        )
        with pytest.raises(capacity.CapacityExceeded):
            est.admission(m)

    def test_fit_report_records_verdict(self, monkeypatch):
        from albedo_tpu.datasets.synthetic import synthetic_stars
        from albedo_tpu.models.als import ImplicitALS

        monkeypatch.setenv("ALBEDO_DEVICE_MEM_BYTES", "4g")
        m = synthetic_stars(n_users=60, n_items=40, mean_stars=5, seed=0)
        est = ImplicitALS(rank=8, max_iter=1, batch_size=16)
        est.fit(m)
        assert est.last_fit_report["mode"] == "resident"
        assert est.last_fit_report["capacity"]["verdict"] == "fit"
        assert np.isfinite(est.last_fit_report["health"]["rms"])
        # The compiler cross-check rode along (None only when the backend
        # exposes no memory_analysis).
        cross = est.last_fit_report["capacity_cross_check"]
        assert cross is None or cross["compiled_bytes"] > 0
