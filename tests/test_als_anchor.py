"""External-anchor validation of the implicit-ALS trainer.

The `implicit` package is not installed in this image and cannot be: an
install was attempted and recorded in r5 — ``pip install implicit`` fails
with ``NameResolutionError: Failed to resolve 'pypi.org'`` (the environment
has zero network egress), and no wheel/sdist is vendored in the image to
build from. The anchor is therefore the
EXACT dense-solve reference: an independent numpy implementation of the
Hu-Koren-Volinsky normal equations with Spark MLlib's conventions
(c = 1 + alpha*r, regParam scaled by the row's rating count, item-then-user
sweep order — ``ALSRecommenderBuilder.scala:46-58``). The production trainer
must track it iteration-for-iteration at mid scale, and its retrieval quality
must follow a pinned recall-vs-iterations curve. Either assertion fails if
factor quality drifts (optimizer bugs, precision regressions, bucketing bugs).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from albedo_tpu.datasets import random_split_by_user, synthetic_stars  # noqa: E402
from albedo_tpu.models.als import ImplicitALS  # noqa: E402


def dense_implicit_als(matrix, rank, reg, alpha, iters, seed):
    """Independent dense reference: full normal-equation solves per row, no
    bucketing, no jax — numpy only. Matches ImplicitALS's init + sweep order."""
    import jax.numpy as jnp  # init must match the trainer's PRNG exactly

    key = jax.random.PRNGKey(seed)
    ukey, ikey = jax.random.split(key)
    scale = 1.0 / np.sqrt(rank)
    uf = np.asarray(jax.random.normal(ukey, (matrix.n_users, rank), jnp.float32)) * scale
    vf = np.asarray(jax.random.normal(ikey, (matrix.n_items, rank), jnp.float32)) * scale

    def half(source, target, indptr, indices, vals):
        yty = source.T @ source
        out = target.copy()
        for r in range(indptr.shape[0] - 1):
            lo, hi = int(indptr[r]), int(indptr[r + 1])
            if hi == lo:
                continue
            y = source[indices[lo:hi]]
            c1 = alpha * vals[lo:hi]
            a_mat = yty + (y * c1[:, None]).T @ y + reg * (hi - lo) * np.eye(rank)
            b_vec = ((1.0 + c1)[:, None] * y).sum(axis=0)
            out[r] = np.linalg.solve(a_mat, b_vec)
        return out

    csr = matrix.csr()
    csc = matrix.csc()
    for _ in range(iters):
        vf = half(uf, vf, *csc)   # items first (MLlib order)
        uf = half(vf, uf, *csr)
    return uf, vf


@pytest.fixture(scope="module")
def mid_matrix():
    return synthetic_stars(n_users=800, n_items=500, rank=12, mean_stars=25, seed=13)


def test_fit_tracks_dense_reference_at_mid_scale(mid_matrix):
    """The fused bucketed trainer and the dense numpy reference must agree on
    the final factors after multiple alternating sweeps."""
    rank, reg, alpha, iters, seed = 16, 0.4, 20.0, 5, 3
    ref_uf, ref_vf = dense_implicit_als(mid_matrix, rank, reg, alpha, iters, seed)
    got = ImplicitALS(
        rank=rank, reg_param=reg, alpha=alpha, max_iter=iters, seed=seed
    ).fit(mid_matrix)
    # Iterated Cholesky vs np.linalg.solve accumulate slightly differently;
    # the factors must still agree to ~0.1%.
    np.testing.assert_allclose(got.user_factors, ref_uf, rtol=5e-3, atol=5e-4)
    np.testing.assert_allclose(got.item_factors, ref_vf, rtol=5e-3, atol=5e-4)


def recall_at_k(model, train, test, k=30, n_users=300):
    """Fraction of held-out positives recovered in the top-k (seen excluded)."""
    from albedo_tpu.datasets.ragged import padded_rows

    test_csr = test.csr()
    counts = np.diff(test_csr[0])
    users = np.nonzero(counts > 0)[0][:n_users]
    indptr, cols, _ = train.csr()
    excl = padded_rows(indptr, cols, users)
    _, idx = model.recommend(users, k=k, exclude_idx=excl)
    hits = total = 0
    for row, u in enumerate(users):
        lo, hi = test_csr[0][u], test_csr[0][u + 1]
        actual = set(test_csr[1][lo:hi].tolist())
        hits += len(actual & set(idx[row].tolist()))
        total += len(actual)
    return hits / max(1, total)


def test_recall_vs_iterations_curve(mid_matrix):
    """Retrieval quality must improve with sweeps and end above a pinned
    floor — the drift gate for anything that degrades factor quality without
    breaking exact parity (e.g. a precision regression)."""
    train, test = random_split_by_user(mid_matrix, test_ratio=0.2, seed=5)
    als = ImplicitALS(rank=16, reg_param=0.1, alpha=40.0, max_iter=12, seed=0)

    checkpoints = {1, 3, 12}
    curve = {}

    def track(it, uf, vf):
        if it + 1 in checkpoints:
            from albedo_tpu.models.als import ALSModel

            curve[it + 1] = recall_at_k(
                ALSModel(user_factors=uf, item_factors=vf, rank=als.rank), train, test
            )

    als.fit(train, callback=track)
    # Monotone-ish improvement: later checkpoints never fall below earlier
    # ones by more than noise, and the curve spans a real gain.
    assert curve[3] >= curve[1] - 0.02, curve
    assert curve[12] >= curve[3] - 0.02, curve
    assert curve[12] >= curve[1] + 0.05, curve
    # Pinned floor: planted rank-12 structure at this scale recovers well over
    # a third of held-out stars in the top-30 (observed ~baseline, see commit).
    assert curve[12] > 0.35, curve


def test_cg_solver_holds_recall_floor(mid_matrix):
    """The fast warm-started-CG path (the bench's solver) must match the exact
    solver's held-out recall within noise at the same anchor scale — the drift
    gate for CG-specific regressions (preconditioner, warm starts, step count)."""
    train, test = random_split_by_user(mid_matrix, test_ratio=0.2, seed=5)
    kw = dict(rank=16, reg_param=0.1, alpha=40.0, max_iter=12, seed=0)
    exact = ImplicitALS(**kw).fit(train)
    fast = ImplicitALS(**kw, solver="cg").fit(train)
    r_exact = recall_at_k(exact, train, test)
    r_fast = recall_at_k(fast, train, test)
    assert r_fast >= r_exact - 0.03, (r_fast, r_exact)
    assert r_fast > 0.35, r_fast
