"""Memory-based CF recommenders: parity vs dense numpy formulas, exclusion,
and the no-materialization scale gate (albedo-size matrices must not OOM)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from albedo_tpu.datasets import StarMatrix, synthetic_stars  # noqa: E402
from albedo_tpu.recommenders.cf import ItemCFRecommender, UserCFRecommender  # noqa: E402


def dense_item_cf_scores(r):
    """train_item_cf.py:38 reference: cosine item-item sims, R @ S / |S|.sum."""
    counts = r.sum(axis=0)
    rhat = np.divide(r, np.sqrt(counts)[None, :], out=np.zeros_like(r), where=counts > 0)
    s = rhat.T @ rhat                       # (I, I) cosine similarities
    return (r @ s) / np.maximum(np.abs(s).sum(axis=1), 1e-12)


def dense_user_cf_scores(r):
    """train_user_cf.py:37 reference: dice user-user sims, S @ R / |S|.sum."""
    inter = r @ r.T
    n = r.sum(axis=1)
    s = 2.0 * inter / np.maximum(n[:, None] + n[None, :], 1e-12)
    return (s @ r) / np.maximum(np.abs(s).sum(axis=1, keepdims=True), 1e-12)


@pytest.fixture(scope="module")
def world():
    m = synthetic_stars(n_users=150, n_items=90, mean_stars=10, seed=17)
    return m, m.dense() > 0


def _scores_from_frame(df, matrix, n_users, n_items):
    out = np.full((n_users, n_items), -np.inf)
    rows = matrix.users_of(df["user_id"].to_numpy(np.int64))
    cols = matrix.items_of(df["repo_id"].to_numpy(np.int64))
    out[rows, cols] = df["score"].to_numpy()
    return out


@pytest.mark.parametrize(
    "cls,dense_fn",
    [(ItemCFRecommender, dense_item_cf_scores), (UserCFRecommender, dense_user_cf_scores)],
)
def test_cf_matches_dense_reference(world, cls, dense_fn):
    m, r01 = world
    r = r01.astype(np.float64)
    expected = dense_fn(r)
    expected[r01] = -np.inf                      # reference drops starred items

    k = 12
    rec = cls(m, top_k=k)
    df = rec.recommend_for_users(m.user_ids)
    got = _scores_from_frame(df, m, m.n_users, m.n_items)

    for u in range(m.n_users):
        top = np.argsort(-expected[u])[:k]
        top = top[np.isfinite(expected[u][top])]
        ret = np.nonzero(np.isfinite(got[u]))[0]
        # The returned set is exactly the reference's top-k (score ties can
        # permute order; compare score values instead of index order).
        np.testing.assert_allclose(
            np.sort(got[u][ret])[::-1],
            np.sort(expected[u][top])[::-1],
            rtol=2e-4, atol=2e-5,
        )
        assert not (set(ret) & set(np.nonzero(r01[u])[0])), "starred item leaked"


def test_cf_source_and_unknown_users(world):
    m, _ = world
    rec = ItemCFRecommender(m, top_k=5)
    df = rec.recommend_for_users(np.array([m.user_ids[0], 10**9]))
    assert set(df["source"]) == {"item_cf"}
    assert set(df["user_id"]) == {m.user_ids[0]}


def test_cf_scales_without_materialization():
    """100k x 100k must run in bounded memory: anything that materializes a
    dense U x I (or I x I) matrix would need tens of GB and die here."""
    rng = np.random.default_rng(0)
    n_users = n_items = 100_000
    nnz = 400_000
    rows = rng.integers(0, n_users, nnz).astype(np.int32)
    cols = rng.integers(0, n_items, nnz).astype(np.int32)
    keys = np.unique(rows.astype(np.int64) * n_items + cols)
    rows = (keys // n_items).astype(np.int32)
    cols = (keys % n_items).astype(np.int32)
    m = StarMatrix(
        user_ids=np.arange(n_users, dtype=np.int64),
        item_ids=np.arange(n_items, dtype=np.int64),
        rows=rows, cols=cols,
        vals=np.ones(len(rows), dtype=np.float32),
    )
    users = m.user_ids[np.unique(rows[:500])][:64]
    for cls in (ItemCFRecommender, UserCFRecommender):
        df = cls(m, top_k=10, user_block=64).recommend_for_users(users)
        assert len(df) > 0
        assert np.isfinite(df["score"]).all()
