"""The bank-backed candidate stage inside the serving plane: fused stage-1
answers, the bank-failure -> host-fallback edge of the degradation matrix
(tags + counters over real HTTP), snapshot precedence, and readiness."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from albedo_tpu.datasets import synthetic_tables  # noqa: E402
from albedo_tpu.datasets.ragged import padded_rows  # noqa: E402
from albedo_tpu.datasets.tables import popular_repos  # noqa: E402
from albedo_tpu.models.als import ImplicitALS  # noqa: E402
from albedo_tpu.recommenders import (  # noqa: E402
    ALSRecommender,
    PopularityRecommender,
    TfidfRecommender,
    TfidfSimilaritySearch,
)
from albedo_tpu.retrieval import BankStage, RetrievalBank  # noqa: E402
from albedo_tpu.serving import RecommendationService, serve  # noqa: E402
from albedo_tpu.serving.pipeline import StageDeadlines, TwoStagePipeline  # noqa: E402
from albedo_tpu.utils import events, faults  # noqa: E402

K = 10


@pytest.fixture(scope="module")
def world():
    tables = synthetic_tables(n_users=120, n_items=90, mean_stars=8, seed=5)
    matrix = tables.star_matrix()
    model = ImplicitALS(rank=8, max_iter=3, seed=0).fit(matrix)
    als = ALSRecommender(model, matrix, exclude_seen=True, top_k=K)
    search = TfidfSimilaritySearch(min_df=1).fit(tables.repo_info)
    tfidf = TfidfRecommender(search, tables.starring, top_k=K)
    pop = PopularityRecommender(
        popular_repos(tables.repo_info, 1, 10**9), top_k=K
    )
    return tables, matrix, model, als, tfidf, pop


def _stage(world):
    tables, matrix, model, als, tfidf, _pop = world
    indptr, cols, _ = matrix.csr()
    excl = padded_rows(indptr, cols, np.arange(matrix.n_users))
    bank = RetrievalBank()
    bank.register(als.bank_registration())
    bank.register(tfidf.bank_registration())
    bank.build(matrix=matrix, exclude_table=excl)
    return BankStage(
        bank, matrix, fallbacks={"als": als, "tfidf": tfidf}, top_k=K
    )


def test_bank_serves_its_sources_threaded_sources_stay(world):
    _tables, matrix, _model, als, tfidf, pop = world
    pipe = TwoStagePipeline(
        {"als": als, "tfidf": tfidf, "popularity": pop}, bank_stage=_stage(world)
    )
    try:
        out = pipe.recommend(int(matrix.user_ids[0]), 30)
        assert out["degraded"] == []
        sources = {i["source"] for i in out["items"]}
        assert {"als", "popularity"} <= sources
        # No breaker exists for bank-served sources — they never ran on the
        # threaded path; popularity (threaded) gets one on first use.
        assert "als" not in pipe.breakers and "tfidf" not in pipe.breakers
        assert "popularity" in pipe.breakers
    finally:
        pipe.close()


def test_bank_error_falls_back_to_host_per_source_path(world):
    _tables, matrix, _model, als, tfidf, pop = world
    pipe = TwoStagePipeline(
        {"als": als, "tfidf": tfidf, "popularity": pop}, bank_stage=_stage(world)
    )
    try:
        uid = int(matrix.user_ids[0])
        baseline = pipe.recommend(uid, 30)
        faults.arm("retrieval.query", "error", at=1)
        out = pipe.recommend(uid, 30)
        assert "bank_error" in out["degraded"]
        assert events.retrieval_fallbacks.value(reason="bank_error") == 1
        # The fallback really ran the host path: same sources still answer.
        assert {i["source"] for i in out["items"]} == {
            i["source"] for i in baseline["items"]
        }
        # The next request (fault exhausted) is clean again.
        after = pipe.recommend(uid, 30)
        assert after["degraded"] == []
    finally:
        pipe.close()


def test_bank_timeout_tagged_and_host_path_answers(world):
    _tables, matrix, _model, als, tfidf, pop = world
    pipe = TwoStagePipeline(
        {"als": als, "tfidf": tfidf, "popularity": pop},
        bank_stage=_stage(world),
        deadlines=StageDeadlines(candidates_s=2.0),
    )
    try:
        uid = int(matrix.user_ids[0])
        baseline = pipe.recommend(uid, 30)  # warm every executable first
        faults.arm("retrieval.query", "delay", at=1, param=3.0)
        out = pipe.recommend(uid, 30)
        assert "bank_timeout" in out["degraded"]
        assert events.retrieval_fallbacks.value(reason="bank_timeout") == 1
        # Not a 500 — and the HOST fallback really answered the covered
        # sources (the bank's wait is capped at half the stage budget, so
        # the fallback had real time, not a zero-budget collect).
        assert {i["source"] for i in out["items"]} == {
            i["source"] for i in baseline["items"]
        }
        assert not any(d.startswith("candidate_timeout") for d in out["degraded"])
    finally:
        pipe.close()


def test_generation_snapshot_als_wins_over_bank_als(world):
    import pandas as pd

    _tables, matrix, _model, als, tfidf, pop = world
    stage = _stage(world)
    pipe = TwoStagePipeline({"popularity": pop}, bank_stage=stage)

    calls = {"n": 0}
    marker_repo = int(matrix.item_ids[0])

    class SnapshotALS(ALSRecommender):
        """Returns a DISTINCTIVE frame — if the bank's als rows clobbered
        the snapshot's, the marker would vanish from the response."""

        def recommend_for_users(self, user_ids, **kw):
            calls["n"] += 1
            return pd.DataFrame({
                "user_id": np.asarray(user_ids, np.int64),
                "repo_id": np.full(len(user_ids), marker_repo, np.int64),
                "score": np.full(len(user_ids), 999.0),
                "source": "als",
            })

    snap = SnapshotALS(als.model, matrix, exclude_seen=True, top_k=K)
    try:
        out = pipe.recommend(
            int(matrix.user_ids[0]), 30, extra_sources={"als": snap}
        )
        assert calls["n"] == 1  # the snapshot source answered, not the bank
        assert out["degraded"] == []
        als_items = [i for i in out["items"] if i["source"] == "als"]
        assert als_items and als_items[0]["repo_id"] == marker_repo, (
            "the bank's als frame clobbered the generation snapshot's"
        )
    finally:
        pipe.close()


def test_stage_forwards_overlay_to_promoted_bank(world):
    """Fold-in subscribers attach the STAGE: publishes after a promotion
    must land in the newly promoted bank, not the retired one."""
    _tables, matrix, model, als, _tfidf, _pop = world
    stage = _stage(world)
    old_bank = stage.bank
    old_bank.save("test-stage-forward.pkl")
    assert stage.reload("test-stage-forward.pkl")["outcome"] == "promoted"
    new_bank = stage.bank
    assert new_bank is not old_bank
    fresh = np.random.default_rng(1).normal(size=(1, model.rank)).astype(np.float32)
    stage.publish_user_rows("als", np.array([0]), fresh)
    assert new_bank.overlay_generation == 1
    assert old_bank.overlay_generation == 0


def test_end_to_end_ndcg_unchanged_by_bank(world):
    """The acceptance bound: candidate NDCG@30 through the full pipeline is
    the same whether stage 1 fans out host threads or queries the bank —
    candidate parity per source implies end-to-end quality parity, and this
    pins it on the actual recommend() path."""
    from albedo_tpu.evaluators import (
        RankingEvaluator,
        user_actual_items,
        user_items_from_pairs,
    )

    _tables, matrix, _model, als, tfidf, pop = world
    sources = {"als": als, "tfidf": tfidf, "popularity": pop}
    fanout = TwoStagePipeline(dict(sources))
    banked = TwoStagePipeline(dict(sources), bank_stage=_stage(world))
    try:
        probe = np.arange(0, matrix.n_users, 4, dtype=np.int64)[:40]
        scores = {}
        for tag, pipe in (("fanout", fanout), ("bank", banked)):
            users, items, vals = [], [], []
            for du in probe:
                uid = int(matrix.user_ids[int(du)])
                out = pipe.recommend(uid, 30)
                assert out["degraded"] == [], (tag, out["degraded"])
                for rank, item in enumerate(out["items"]):
                    users.append(uid)
                    items.append(item["repo_id"])
                    vals.append(-rank)  # served order IS the ranking
            predicted = user_items_from_pairs(
                matrix.users_of(np.asarray(users, np.int64)),
                matrix.items_of(np.asarray(items, np.int64)),
                order_key=np.asarray(vals, np.float64),
                k=30,
            )
            scores[tag] = RankingEvaluator(metric_name="ndcg@k", k=30).evaluate(
                predicted, user_actual_items(matrix, k=30)
            )
        assert scores["bank"] == pytest.approx(scores["fanout"], abs=1e-6), scores
    finally:
        fanout.close()
        banked.close()


# --- over real HTTP -----------------------------------------------------------


def _get(handle, path):
    host, port = handle.server_address[:2]
    try:
        with urllib.request.urlopen(f"http://{host}:{port}{path}", timeout=30) as r:
            return r.status, json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


@pytest.fixture()
def server(world):
    tables, matrix, model, als, tfidf, pop = world
    svc = RecommendationService(
        model, matrix,
        repo_info=tables.repo_info, user_info=tables.user_info,
        recommenders={"popularity": pop},
        bank_stage=_stage(world),
    )
    with serve(svc, port=0) as handle:
        yield handle, matrix


def test_bank_failure_over_http_degrades_not_500(server):
    handle, matrix = server
    uid = int(matrix.user_ids[1])
    status, body = _get(handle, f"/recommend/{uid}")
    assert status == 200 and body["degraded"] == []
    faults.arm("retrieval.query", "error", at=1)
    status, body = _get(handle, f"/recommend/{uid}?k=7")
    assert status == 200, body
    assert "bank_error" in body["degraded"]
    assert body["items"], "fallback must still answer"
    # Tags AND counters: the metrics page shows both planes.
    status, _ = _get(handle, f"/recommend/{uid}")
    host, port = handle.server_address[:2]
    with urllib.request.urlopen(f"http://{host}:{port}/metrics", timeout=30) as r:
        page = r.read().decode()
    assert 'albedo_retrieval_fallbacks_total{reason="bank_error"} 1' in page
    assert 'albedo_degraded_total{reason="bank_error"} 1' in page
    assert "albedo_retrieval_queries_total" in page


def test_readiness_reports_bank_snapshot(server):
    handle, _matrix = server
    status, body = _get(handle, "/healthz/ready")
    assert status == 200
    snap = body["retrieval_bank"]
    assert snap["sources"] == ["als", "tfidf"]
    assert snap["generation"] == 1 and snap["version"]
