"""Data-acquisition layer (L0): store, crawler (offline fake transport),
content-index sync.

Parity anchors: ``app/models.py`` unique constraints + swallowed
IntegrityError, ``collect_data.py`` BFS/token/rate-limit behavior,
``sync_data_to_es.py`` eligibility filter.
"""

import numpy as np
import pytest

from albedo_tpu.datasets import load_raw_tables, synthetic_tables
from albedo_tpu.models.word2vec import Word2Vec
from albedo_tpu.store import EntityStore, GitHubCrawler, build_content_index, load_content_index


# --- fake GitHub API ---------------------------------------------------------


class FakeGitHub:
    """Deterministic in-memory GitHub REST surface for crawler tests."""

    def __init__(self):
        self.users = {}       # login -> user json
        self.following = {}   # login -> [user json]
        self.followers = {}
        self.starred = {}     # login -> [{starred_at, repo}]
        self.repos = {}       # id -> repo json
        self.calls = []
        self.fail_403_first = set()  # paths that 403 once

    def add_user(self, uid, login, **kw):
        self.users[login] = {"id": uid, "login": login, "type": "User", **kw}
        self.following.setdefault(login, [])
        self.followers.setdefault(login, [])
        self.starred.setdefault(login, [])
        return self.users[login]

    def add_repo(self, rid, full_name, **kw):
        owner_login = full_name.split("/")[0]
        owner = self.users.get(owner_login, {"id": 0, "login": owner_login})
        self.repos[rid] = {
            "id": rid, "full_name": full_name, "name": full_name.split("/")[1],
            "owner": owner, "stargazers_count": 5, **kw,
        }
        return self.repos[rid]

    def star(self, login, rid, at="2017-01-01T00:00:00Z"):
        self.starred[login].append({"starred_at": at, "repo": self.repos[rid]})

    def transport(self, path, params, token):
        self.calls.append((path, dict(params), token))
        if path in self.fail_403_first:
            self.fail_403_first.discard(path)
            return 403, None
        page = int(params.get("page", 1))

        def paged(items):
            per = int(params.get("per_page", 100))
            return 200, items[(page - 1) * per : page * per]

        parts = path.strip("/").split("/")
        if parts[0] == "users" and len(parts) == 2:
            u = self.users.get(parts[1])
            return (200, u) if u else (404, None)
        if parts[0] == "users" and parts[2] == "following":
            return paged(self.following.get(parts[1], []))
        if parts[0] == "users" and parts[2] == "followers":
            return paged(self.followers.get(parts[1], []))
        if parts[0] == "users" and parts[2] == "starred":
            return paged(self.starred.get(parts[1], []))
        if parts[0] == "repositories":
            r = self.repos.get(int(parts[1]))
            return (200, r) if r else (404, None)
        return 404, None


@pytest.fixture()
def world():
    gh = FakeGitHub()
    alice = gh.add_user(1, "alice", bio="deep learning", company="ACME")
    bob = gh.add_user(2, "bob")
    carol = gh.add_user(3, "carol")
    gh.add_repo(100, "alice/nn-lib", language="Python", description="neural nets")
    gh.add_repo(101, "bob/webkit", language="C++", description="web engine")
    gh.add_repo(102, "carol/tool", language="Go", description="cli tool")
    gh.following["alice"] = [bob]
    gh.followers["alice"] = [carol]
    gh.star("alice", 100)
    gh.star("alice", 101)
    gh.star("bob", 101)
    gh.star("carol", 102, at="2017-06-01T00:00:00Z")
    return gh


def test_crawler_bfs_discovers_everything(world):
    store = EntityStore()
    crawler = GitHubCrawler(store, transport=world.transport, sleeper=lambda s: None)
    stats = crawler.collect(["alice"])
    counts = store.counts()
    # alice seeded; bob + carol discovered via follow edges; all stars pulled.
    assert counts["app_userinfo"] == 3
    assert counts["app_repostarring"] == 4
    assert counts["app_userrelation"] == 2
    assert counts["app_repoinfo"] == 3
    assert stats.users == 3 and stats.starrings == 4


def test_crawler_idempotent_rerun(world):
    store = EntityStore()
    kw = dict(transport=world.transport, sleeper=lambda s: None)
    GitHubCrawler(store, **kw).collect(["alice"])
    first = store.counts()
    GitHubCrawler(store, **kw).collect(["alice"])  # unique constraints dedup
    assert store.counts() == first


def test_crawler_rate_limit_sleeps_and_retries(world):
    sleeps = []
    world.fail_403_first.add("/users/alice")
    store = EntityStore()
    crawler = GitHubCrawler(store, transport=world.transport, sleeper=sleeps.append)
    crawler.collect(["alice"])
    assert crawler.stats.rate_limit_sleeps == 1
    assert sleeps[0] == 30 * 60  # collect_data.py:60-66
    assert store.counts()["app_userinfo"] == 3  # retried and succeeded


def test_crawler_token_rotation(world):
    store = EntityStore()
    crawler = GitHubCrawler(
        store, tokens=["t1", "t2", "t3"], transport=world.transport, sleeper=lambda s: None
    )
    crawler.collect(["alice"])
    used = {t for _, _, t in world.calls}
    assert used <= {"t1", "t2", "t3"} and len(used) > 1


def test_crawler_pagination(world):
    # 250 followers -> 3 pages of 100.
    world.followers["alice"] = [
        {"id": 1000 + i, "login": f"f{i}", "type": "User"} for i in range(250)
    ]
    store = EntityStore()
    crawler = GitHubCrawler(store, transport=world.transport, sleeper=lambda s: None, max_pages=10)
    u = crawler.fetch_user_info("alice")
    found = crawler.fetch_follower_users("alice", int(u["id"]))
    assert len(found) == 250
    pages = sorted(
        p["page"] for path, p, _ in world.calls if path.endswith("/followers")
    )
    assert pages[0] == 1 and max(pages) >= 3


def test_store_file_roundtrip_into_datasets(world, tmp_path):
    db = tmp_path / "crawl.db"
    with EntityStore(db) as store:
        GitHubCrawler(store, transport=world.transport, sleeper=lambda s: None).collect(["alice"])
    tables = load_raw_tables(db)
    assert len(tables.user_info) == 3
    assert len(tables.starring) == 4
    m = tables.star_matrix()
    assert m.n_users == 3 and m.n_items == 3
    # bio survived into the schema-conformed frame
    assert (tables.user_info["user_bio"] == "deep learning").any()


def test_store_drop_data(world):
    store = EntityStore()
    GitHubCrawler(store, transport=world.transport, sleeper=lambda s: None).collect(["alice"])
    store.drop_data(["app_repostarring"])
    c = store.counts()
    assert c["app_repostarring"] == 0 and c["app_userinfo"] == 3
    store.drop_data()
    assert all(v == 0 for v in store.counts().values())


# --- retry/backoff + chaos ---------------------------------------------------


def test_crawler_honors_retry_after_header(world):
    """A 403 carrying Retry-After sleeps the server's number, not 30 min."""
    state = {"first": True}

    def transport(path, params, token):
        if path == "/users/alice" and state.pop("first", False):
            return 403, None, {"Retry-After": "7"}
        return world.transport(path, params, token)

    sleeps = []
    store = EntityStore()
    crawler = GitHubCrawler(store, transport=transport, sleeper=sleeps.append)
    crawler.collect(["alice"])
    assert sleeps[0] == 7.0
    assert crawler.stats.rate_limit_sleeps == 1
    assert store.counts()["app_userinfo"] == 3


def test_rate_limit_delay_header_precedence():
    from albedo_tpu.store.crawler import RATE_LIMIT_SLEEP_S, rate_limit_delay

    # Retry-After wins over X-RateLimit-Reset; header names case-insensitive.
    assert rate_limit_delay({"retry-after": "5", "X-RateLimit-Reset": "999999"}) == 5.0
    # Reset is epoch seconds: wait the remaining window.
    assert rate_limit_delay({"X-RateLimit-Reset": "1000"}, now=lambda: 900.0) == 100.0
    # A reset in the past clamps to zero, not a negative sleep.
    assert rate_limit_delay({"X-RateLimit-Reset": "800"}, now=lambda: 900.0) == 0.0
    # No headers (every legacy 2-tuple transport): the reference's 30 minutes.
    assert rate_limit_delay({}) == RATE_LIMIT_SLEEP_S
    assert rate_limit_delay(None) == RATE_LIMIT_SLEEP_S
    # Garbage header values fall through, never raise.
    assert rate_limit_delay({"Retry-After": "soon"}) == RATE_LIMIT_SLEEP_S
    # Bogus huge values (or ms-resolution resets) clamp to the 30-min ceiling
    # instead of parking a crawler thread for days.
    assert rate_limit_delay({"Retry-After": "10000000"}) == RATE_LIMIT_SLEEP_S
    assert rate_limit_delay(
        {"X-RateLimit-Reset": "1776000000000"}, now=lambda: 1776000000.0
    ) == RATE_LIMIT_SLEEP_S


def test_crawler_5xx_uses_jittered_backoff(world):
    """Transient 5xx retries back off exponentially (bounded by the policy
    caps) instead of the seed's fixed sleep(1.0), and don't count as
    rate-limit sleeps."""
    failures = {"n": 2}

    def transport(path, params, token):
        if path == "/users/alice" and failures["n"] > 0:
            failures["n"] -= 1
            return 502, None
        return world.transport(path, params, token)

    sleeps = []
    store = EntityStore()
    crawler = GitHubCrawler(store, transport=transport, sleeper=sleeps.append)
    crawler.collect(["alice"])
    assert len(sleeps) == 2
    assert 0.0 <= sleeps[0] <= 0.5  # full jitter within base_s cap
    assert 0.0 <= sleeps[1] <= 1.0  # second retry: doubled cap
    assert crawler.stats.rate_limit_sleeps == 0
    assert store.counts()["app_userinfo"] == 3


def test_crawler_gives_up_after_persistent_5xx(world):
    from albedo_tpu.store.crawler import RateLimited

    def transport(path, params, token):
        return 500, None

    crawler = GitHubCrawler(EntityStore(), transport=transport, sleeper=lambda s: None)
    with pytest.raises(RateLimited):
        crawler._request("/users/alice")
    assert crawler.stats.requests == 5  # MAX_RETRIES attempts, then give up


def test_rate_limit_sleep_counter_matches_performed_sleeps():
    """A 403 on the FINAL attempt gives up without sleeping — the counter
    must not count a sleep that never happened."""
    from albedo_tpu.store.crawler import MAX_RETRIES, RateLimited

    def transport(path, params, token):
        return 403, None, {"Retry-After": "1"}

    sleeps = []
    crawler = GitHubCrawler(EntityStore(), transport=transport, sleeper=sleeps.append)
    with pytest.raises(RateLimited):
        crawler._request("/users/alice")
    assert len(sleeps) == MAX_RETRIES - 1
    assert crawler.stats.rate_limit_sleeps == len(sleeps)


def test_crawler_transport_fault_site_is_retried(world):
    """An injected IOError at the transport fault site behaves like a flaky
    network: retried with backoff, then the crawl succeeds."""
    from albedo_tpu.utils import faults

    faults.arm("crawler.transport", kind="ioerror", at=1)
    sleeps = []
    store = EntityStore()
    crawler = GitHubCrawler(store, transport=world.transport, sleeper=sleeps.append)
    crawler.collect(["alice"])
    assert faults.FAULTS.fired("crawler.transport") == 1
    assert len(sleeps) >= 1
    assert store.counts()["app_userinfo"] == 3


# --- content index -----------------------------------------------------------


def test_content_index_filter_and_roundtrip(tmp_path, monkeypatch):
    tables = synthetic_tables(n_users=80, n_items=60, mean_stars=8, seed=5)
    corpus = [d.split() for d in tables.repo_info["repo_description"]]
    w2v = Word2Vec(dim=8, min_count=1, max_iter=1, subsample=0.0, batch_size=128).fit_corpus(corpus)

    lo, hi = 3, int(tables.repo_info["repo_stargazers_count"].max())
    backend = build_content_index(
        tables.repo_info, w2v, min_stars=lo, max_stars=hi,
        artifact_name="contentIndex.npz",
    )
    eligible = tables.repo_info[
        tables.repo_info["repo_stargazers_count"].between(lo, hi)
        & ~tables.repo_info["repo_is_fork"]
    ]
    assert set(backend.item_ids.tolist()) == set(eligible["repo_id"].tolist())
    norms = np.linalg.norm(backend.vectors, axis=1)
    assert ((norms < 1.01) & ((norms > 0.99) | (norms == 0))).all()

    # Cache hit: loading must not re-embed (word2vec_model unused).
    again = load_content_index("contentIndex.npz")
    np.testing.assert_array_equal(again.item_ids, backend.item_ids)
    np.testing.assert_allclose(again.vectors, backend.vectors)
    out = again.more_like_this([backend.item_ids[:2]], k=3)
    assert len(out) == 1 and len(out[0][0]) <= 3
