"""The ALX-scale sharded ALS fit (``parallel.als.ShardedALSFit`` behind
``ImplicitALS.fit``): both factor tables row-sharded over the 8-virtual-CPU
mesh, parity with the single-device resident fit pinned at atol 1e-5 across
solvers/modes, the streamed-bucket path, the ``als.shard.*`` chaos surface,
and the capacity admission ladder (forced-low-budget acceptance drill
included)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from albedo_tpu.datasets.synthetic import synthetic_stars  # noqa: E402
from albedo_tpu.models.als import ImplicitALS  # noqa: E402
from albedo_tpu.parallel import make_mesh  # noqa: E402
from albedo_tpu.parallel.als import ShardedALSFit  # noqa: E402
from albedo_tpu.utils import capacity, faults  # noqa: E402

ATOL = 1e-5
KW = dict(rank=8, max_iter=2, batch_size=32, seed=1)


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8
    return make_mesh(8)


@pytest.fixture(scope="module")
def matrix():
    return synthetic_stars(n_users=64, n_items=48, mean_stars=6, seed=3)


@pytest.fixture(scope="module")
def reference(matrix):
    """Single-device RESIDENT fit (admission bypassed) — the parity anchor."""
    return ImplicitALS(**KW, chunked=False).fit(matrix)


def _parity(model, reference):
    np.testing.assert_allclose(
        model.user_factors, reference.user_factors, atol=ATOL
    )
    np.testing.assert_allclose(
        model.item_factors, reference.item_factors, atol=ATOL
    )


class TestParity:
    def test_sharded_resident_matches_single_device(self, mesh8, matrix, reference):
        est = ImplicitALS(**KW, mesh=mesh8, sharded=True)
        model = est.fit(matrix)
        _parity(model, reference)
        rep = est.last_fit_report
        assert rep["mode"] == "sharded"
        assert rep["n_shards"] == 8
        assert rep["streamed_buckets"] == 0

    def test_sharded_streamed_matches_single_device(self, mesh8, matrix, reference):
        est = ImplicitALS(**KW, mesh=mesh8, sharded="streamed")
        model = est.fit(matrix)
        _parity(model, reference)
        rep = est.last_fit_report
        assert rep["mode"] == "sharded_streamed"
        # Every bucket of every half-sweep re-uploaded: the star matrix was
        # never device-resident whole.
        assert rep["streamed_buckets"] > 0

    def test_ring_mode_matches_single_device(self, mesh8, matrix, reference):
        est = ImplicitALS(**KW, mesh=mesh8, sharded=True, shard_mode="ring")
        model = est.fit(matrix)
        _parity(model, reference)
        assert est.last_fit_report["shard_mode"] == "ring"

    def test_cg_with_warm_start_matches_single_device(self, mesh8, matrix):
        rng = np.random.default_rng(0)
        init = (
            rng.normal(0, 0.1, (matrix.n_users, KW["rank"])).astype(np.float32),
            rng.normal(0, 0.1, (matrix.n_items, KW["rank"])).astype(np.float32),
        )
        kw = dict(KW, solver="cg", init_factors=init)
        ref = ImplicitALS(**kw, chunked=False).fit(matrix)
        model = ImplicitALS(**kw, mesh=mesh8, sharded=True).fit(matrix)
        _parity(model, ref)

    def test_ring_with_cg_rejected(self, mesh8):
        with pytest.raises(ValueError, match="ring mode"):
            ShardedALSFit(mesh8, solver="cg", mode="ring")


class TestPipelinedDataflow:
    """The pipelined dataflow (double-buffered prefetch, overlapped ring
    phases, fused landing scatter) is numerically IDENTICAL to the
    synchronous PR 8 dataflow — the parity matrix pins streamed-pipelined
    vs streamed-synchronous vs resident against the single-device fit."""

    def _engine_fit(self, mesh8, matrix, mode="allgather", solver="cholesky",
                    streamed=True, pipelined=True, init=None):
        est = ImplicitALS(**KW, solver=solver, shard_mode=mode, mesh=mesh8)
        eng = ShardedALSFit(mesh8, solver=solver, mode=mode)
        if init is None:
            import jax as _jax
            import jax.numpy as _jnp
            ukey, ikey = _jax.random.split(_jax.random.PRNGKey(KW["seed"]))
            scale = 1.0 / np.sqrt(KW["rank"])
            init = (
                np.asarray(_jax.random.normal(
                    ukey, (matrix.n_users, KW["rank"]), _jnp.float32) * scale),
                np.asarray(_jax.random.normal(
                    ikey, (matrix.n_items, KW["rank"]), _jnp.float32) * scale),
            )
        ub, ib = est._host_buckets(matrix)
        u, v, stats = eng.fit(
            init[0], init[1], ub, ib, est.reg_param, est.alpha, KW["max_iter"],
            streamed=streamed, pipelined=pipelined,
        )
        return np.asarray(u), np.asarray(v), stats

    @pytest.mark.parametrize("mode", ["allgather", "ring"])
    def test_streamed_pipelined_matches_sync_and_resident(
        self, mesh8, matrix, reference, mode
    ):
        for streamed, pipelined in ((True, True), (True, False), (False, True)):
            u, v, stats = self._engine_fit(
                mesh8, matrix, mode=mode, streamed=streamed, pipelined=pipelined
            )
            np.testing.assert_allclose(u, reference.user_factors, atol=ATOL)
            np.testing.assert_allclose(v, reference.item_factors, atol=ATOL)
            assert stats["pipelined"] is pipelined

    def test_cg_pipelined_matches_single_device(self, mesh8, matrix):
        rng = np.random.default_rng(0)
        init = (
            rng.normal(0, 0.1, (matrix.n_users, KW["rank"])).astype(np.float32),
            rng.normal(0, 0.1, (matrix.n_items, KW["rank"])).astype(np.float32),
        )
        ref = ImplicitALS(**KW, solver="cg", init_factors=init, chunked=False).fit(matrix)
        u, v, _ = self._engine_fit(
            mesh8, matrix, solver="cg", streamed=True, pipelined=True, init=init
        )
        np.testing.assert_allclose(u, ref.user_factors, atol=ATOL)
        np.testing.assert_allclose(v, ref.item_factors, atol=ATOL)

    def test_streamed_default_is_pipelined_with_prefetch(self, mesh8, matrix, reference):
        est = ImplicitALS(**KW, mesh=mesh8, sharded="streamed")
        model = est.fit(matrix)
        rep = est.last_fit_report
        assert rep["pipelined"] is True
        assert rep["streamed_buckets"] > 0
        # Uploads happened in the background thread; the sweep's stall time
        # is recorded separately from the (hidden) upload time.
        assert rep["prefetch_wait_s"] >= 0
        assert faults.FAULTS.hits("als.shard.prefetch") > 0
        _parity(model, reference)

    def test_streamed_sync_mode_reachable_for_triage(self, mesh8, matrix, reference):
        before = faults.FAULTS.hits("als.shard.prefetch")
        est = ImplicitALS(**KW, mesh=mesh8, sharded="streamed_sync")
        model = est.fit(matrix)
        rep = est.last_fit_report
        assert rep["mode"] == "sharded_streamed"
        assert rep["pipelined"] is False
        # The synchronous path never touches the prefetch surface.
        assert faults.FAULTS.hits("als.shard.prefetch") == before
        _parity(model, reference)

    def test_env_off_switch_reverts_to_sync(self, mesh8, matrix, reference, monkeypatch):
        monkeypatch.setenv("ALBEDO_PIPELINE", "off")
        before = faults.FAULTS.hits("als.shard.prefetch")
        est = ImplicitALS(**KW, mesh=mesh8, sharded="streamed")
        model = est.fit(matrix)
        assert est.last_fit_report["pipelined"] is False
        assert faults.FAULTS.hits("als.shard.prefetch") == before
        _parity(model, reference)


class TestPrefetchFaultSite:
    def test_prefetch_error_surfaces_as_clean_failed_fit(self, mesh8, matrix):
        # at=2: the first bucket prefetches fine, the SECOND dies in the
        # background uploader — the error must be delivered to the
        # consuming sweep and fail the fit cleanly, never hang it.
        faults.arm("als.shard.prefetch", kind="error", at=2)
        est = ImplicitALS(**KW, mesh=mesh8, sharded="streamed")
        with pytest.raises(faults.FaultInjected):
            est.fit(matrix)
        assert faults.FAULTS.fired("als.shard.prefetch") == 1

    def test_prefetch_silent_on_resident_path(self, mesh8, matrix, reference):
        faults.arm("als.shard.prefetch", kind="error", at=1)
        model = ImplicitALS(**KW, mesh=mesh8, sharded=True).fit(matrix)
        assert faults.FAULTS.fired("als.shard.prefetch") == 0
        _parity(model, reference)

    def test_wedged_prefetch_bounded_by_collective_deadline(
        self, mesh8, matrix, monkeypatch
    ):
        """A prefetch thread stuck longer than the collective deadline must
        surface as PrefetchStalled — a clean failed fit, never a hang. The
        injected delay out-sleeps a shrunk deadline, exactly the
        wedged-uploader shape."""
        from albedo_tpu.parallel.als import PrefetchStalled

        monkeypatch.setenv("ALBEDO_COLLECTIVE_DEADLINE_S", "0.2")
        faults.arm("als.shard.prefetch", kind="delay", at=1, param=2.0)
        est = ImplicitALS(**KW, mesh=mesh8, sharded="streamed")
        with pytest.raises(PrefetchStalled, match="collective deadline"):
            est.fit(matrix)


class TestFaultSites:
    def test_gather_fault_fails_the_fit(self, mesh8, matrix):
        faults.arm("als.shard.gather", kind="error", at=1)
        est = ImplicitALS(**KW, mesh=mesh8, sharded=True)
        with pytest.raises(faults.FaultInjected):
            est.fit(matrix)
        assert faults.FAULTS.fired("als.shard.gather") == 1

    def test_stream_fault_fails_mid_stream(self, mesh8, matrix):
        # at=2: the first bucket uploads fine, the SECOND dies — a genuinely
        # mid-stream failure, not a failed first dispatch.
        faults.arm("als.shard.stream", kind="error", at=2)
        est = ImplicitALS(**KW, mesh=mesh8, sharded="streamed")
        with pytest.raises(faults.FaultInjected):
            est.fit(matrix)
        assert faults.FAULTS.fired("als.shard.stream") == 1

    def test_stream_site_silent_when_resident(self, mesh8, matrix, reference):
        # The resident sharded path never streams, so an armed stream fault
        # must never fire there.
        faults.arm("als.shard.stream", kind="error", at=1)
        model = ImplicitALS(**KW, mesh=mesh8, sharded=True).fit(matrix)
        assert faults.FAULTS.fired("als.shard.stream") == 0
        _parity(model, reference)


class TestAdmissionLadder:
    def _plans(self, matrix, est):
        shapes_u, shapes_i = est._plan_shapes(matrix)
        args = (shapes_u, shapes_i, matrix.n_users, matrix.n_items, est.rank)
        return (
            capacity.plan_fit(*args, n_devices=8),
            capacity.plan_fit_sharded(*args, 8, streamed=False),
            capacity.plan_fit_sharded(*args, 8, streamed=True),
        )

    def test_acceptance_drill_over_budget_trains_sharded(
        self, mesh8, matrix, reference, monkeypatch
    ):
        """The ISSUE acceptance criterion: a matrix whose replicated factor
        tables + interactions exceed one device's (forced-low) budget trains
        to completion on the 8-device mesh through the sharded path, factors
        matching the single-device resident fit within atol 1e-5."""
        est = ImplicitALS(**KW, mesh=mesh8)
        replicated, sharded, _ = self._plans(matrix, est)
        # Budget between the replicated per-device plan and the sharded one.
        monkeypatch.setenv("ALBEDO_MEM_HEADROOM", "1.0")
        monkeypatch.setenv(
            "ALBEDO_DEVICE_MEM_BYTES", str(sharded.required_bytes + 64)
        )
        assert sharded.required_bytes + 64 < replicated.required_bytes
        model = est.fit(matrix)
        rep = est.last_fit_report
        assert rep["mode"] == "sharded"
        assert rep["capacity"]["verdict"] == "degrade"
        assert rep["capacity"]["chosen"] == "als_fit_sharded"
        _parity(model, reference)

    def test_tighter_budget_degrades_to_streamed(
        self, mesh8, matrix, reference, monkeypatch
    ):
        est = ImplicitALS(**KW, mesh=mesh8)
        _, sharded, streamed = self._plans(matrix, est)
        monkeypatch.setenv("ALBEDO_MEM_HEADROOM", "1.0")
        monkeypatch.setenv(
            "ALBEDO_DEVICE_MEM_BYTES", str(streamed.required_bytes + 64)
        )
        assert streamed.required_bytes + 64 < sharded.required_bytes
        model = est.fit(matrix)
        rep = est.last_fit_report
        assert rep["mode"] == "sharded_streamed"
        assert rep["capacity"]["chosen"] == "als_fit_sharded_streamed"
        _parity(model, reference)

    def test_refuses_when_even_streamed_busts(self, mesh8, matrix, monkeypatch):
        monkeypatch.setenv("ALBEDO_DEVICE_MEM_BYTES", "1k")
        est = ImplicitALS(**KW, mesh=mesh8)
        with pytest.raises(capacity.CapacityExceeded, match="refused: capacity"):
            est.fit(matrix)

    def test_ample_budget_keeps_the_replicated_path(self, mesh8, matrix, monkeypatch):
        # Admission-only (running the fused GSPMD fit here would just re-pay
        # its compile): an ample budget verdicts `fit` on the first rung, so
        # `fit()` falls through to the existing replicated path.
        monkeypatch.setenv("ALBEDO_DEVICE_MEM_BYTES", "64g")
        est = ImplicitALS(**KW, mesh=mesh8)
        v = est.admission_mesh(matrix)
        assert v.verdict == "fit" and v.chosen == "als_fit"

    def test_injected_oom_reroutes_to_sharded(self, mesh8, matrix, reference):
        faults.arm("capacity.admit", kind="oom", at=1)
        est = ImplicitALS(**KW, mesh=mesh8)
        model = est.fit(matrix)
        rep = est.last_fit_report
        assert rep["mode"] == "sharded"
        assert "injected" in rep["capacity"]["detail"]
        _parity(model, reference)
