"""Multi-device tests on the 8-virtual-CPU-device mesh (conftest).

The JAX analogue of the reference's ``local-cluster[1, 3, 12288]`` pseudo-
distributed Spark mode (SURVEY.md section 4): same math as the single-device
paths, executed through shard_map/psum/all_gather, asserted equal.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from albedo_tpu.datasets.synthetic import synthetic_stars
from albedo_tpu.models.als import ImplicitALS
from albedo_tpu.ops.topk import topk_scores
from albedo_tpu.parallel import (
    make_mesh,
    pad_bucket,
    sharded_gramian,
    sharded_topk_scores,
)
from albedo_tpu.datasets.ragged import Bucket


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return make_mesh(8)


@pytest.fixture(scope="module")
def mesh_2d():
    return make_mesh(8, data=2, item=4)


def test_mesh_axes(mesh8, mesh_2d):
    assert mesh8.shape == {"data": 8, "item": 1}
    assert mesh_2d.shape == {"data": 2, "item": 4}


def test_sharded_gramian_matches_dense(mesh8, rng):
    f = rng.normal(size=(64, 10)).astype(np.float32)
    out = sharded_gramian(mesh8)(jnp.asarray(f))
    np.testing.assert_allclose(np.asarray(out), f.T @ f, rtol=1e-4, atol=1e-4)


def test_pad_bucket_divisible():
    b = Bucket(
        row_ids=np.array([3, 5, 7], np.int32),
        idx=np.zeros((3, 4), np.int32),
        val=np.ones((3, 4), np.float32),
        mask=np.ones((3, 4), bool),
    )
    p = pad_bucket(b, 8)
    assert p.row_ids.shape == (8,)
    assert (p.row_ids[3:] == -1).all()
    assert (p.val[3:] == 0).all()


def test_sharded_als_matches_single_device(mesh8):
    m = synthetic_stars(n_users=120, n_items=80, mean_stars=10, seed=7)
    base = ImplicitALS(rank=8, max_iter=3, batch_size=32, seed=1)
    sharded = ImplicitALS(rank=8, max_iter=3, batch_size=32, seed=1, mesh=mesh8)
    m_base = base.fit(m)
    m_shard = sharded.fit(m)
    # Same math, different device layout: factors must agree to float32 tolerance.
    np.testing.assert_allclose(
        m_shard.user_factors, m_base.user_factors, rtol=2e-3, atol=2e-3
    )
    np.testing.assert_allclose(
        m_shard.item_factors, m_base.item_factors, rtol=2e-3, atol=2e-3
    )


def test_explicit_shard_map_sweep_matches_single_device(mesh8):
    """ShardedALSSweep — the explicit shard_map reference variant (the fit
    itself uses the GSPMD fused path) — must match the per-bucket sweep."""
    from albedo_tpu.datasets import bucket_rows
    from albedo_tpu.ops.als import als_half_sweep
    from albedo_tpu.parallel.als import ShardedALSSweep

    m = synthetic_stars(n_users=96, n_items=64, mean_stars=8, seed=2)
    rng = np.random.default_rng(0)
    user_f = rng.normal(0, 0.1, (m.n_users, 8)).astype(np.float32)
    item_f = rng.normal(0, 0.1, (m.n_items, 8)).astype(np.float32)
    buckets = bucket_rows(*m.csr(), batch_size=32)

    expected = als_half_sweep(
        jnp.asarray(item_f), jnp.asarray(user_f), buckets, 0.3, 10.0
    )
    sweep = ShardedALSSweep(mesh8)
    got = sweep.half_sweep(
        jnp.asarray(item_f), jnp.asarray(user_f), sweep.prepare(buckets), 0.3, 10.0
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), rtol=2e-3, atol=2e-4)


@pytest.mark.parametrize("mesh_name", ["mesh8_item", "mesh_2d"])
def test_sharded_topk_matches_single_device(mesh_name, rng, request):
    if mesh_name == "mesh8_item":
        mesh = make_mesh(8, data=1, item=8)
    else:
        mesh = request.getfixturevalue("mesh_2d")
    uf = rng.normal(size=(24, 6)).astype(np.float32)
    vf = rng.normal(size=(50, 6)).astype(np.float32)
    ref_v, ref_i = topk_scores(jnp.asarray(uf), jnp.asarray(vf), k=5)
    got_v, got_i = sharded_topk_scores(uf, vf, k=5, mesh=mesh)
    np.testing.assert_allclose(np.asarray(got_v), np.asarray(ref_v), rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(got_i), np.asarray(ref_i))


def test_sharded_topk_small_catalog(rng):
    # k larger than the per-shard block (and than the whole catalog): result
    # is padded with -inf/-1 instead of crashing.
    mesh = make_mesh(8, data=1, item=8)
    uf = rng.normal(size=(4, 3)).astype(np.float32)
    vf = rng.normal(size=(5, 3)).astype(np.float32)
    vals, idx = sharded_topk_scores(uf, vf, k=7, mesh=mesh)
    vals, idx = np.asarray(vals), np.asarray(idx)
    assert vals.shape == (4, 7)
    ref_v, ref_i = topk_scores(jnp.asarray(uf), jnp.asarray(vf), k=5)
    np.testing.assert_allclose(vals[:, :5], np.asarray(ref_v), rtol=1e-5)
    np.testing.assert_array_equal(idx[:, :5], np.asarray(ref_i))
    assert (idx[:, 5:] == -1).all() and np.isneginf(vals[:, 5:]).all()


def test_sharded_topk_exclusion(rng):
    mesh = make_mesh(8, data=2, item=4)
    uf = rng.normal(size=(10, 4)).astype(np.float32)
    vf = rng.normal(size=(33, 4)).astype(np.float32)
    # Exclude each user's unexcluded top-1 and check it disappears.
    _, base_i = sharded_topk_scores(uf, vf, k=3, mesh=mesh)
    excl = np.full((10, 2), -1, np.int32)
    excl[:, 0] = np.asarray(base_i)[:, 0]
    _, got_i = sharded_topk_scores(uf, vf, k=3, mesh=mesh, exclude_idx=excl)
    got = np.asarray(got_i)
    for u in range(10):
        assert excl[u, 0] not in got[u]


def test_sharded_word2vec_matches_single_device(mesh8):
    """Mesh-path W2V (pairs row-sharded, tables replicated, XLA-inserted
    psums) must reproduce the single-device fit: same computation graph, only
    the layout differs (VERDICT round 1 next-step #4)."""
    from albedo_tpu.models.word2vec import Word2Vec

    rng = np.random.default_rng(4)
    words = [f"w{i}" for i in range(30)]
    sentences = [
        [words[j] for j in rng.integers(0, 30, size=rng.integers(3, 9))]
        for _ in range(300)
    ]
    kw = dict(dim=8, window=3, min_count=1, max_iter=4, batch_size=64,
              subsample=0.0, seed=9)
    single = Word2Vec(**kw).fit_corpus(sentences)
    sharded = Word2Vec(**kw, mesh=mesh8).fit_corpus(sentences)
    assert single.vocab == sharded.vocab
    # Identical math modulo reduction order: tight-but-not-bitwise tolerance.
    np.testing.assert_allclose(sharded.vectors, single.vectors, rtol=5e-3, atol=5e-4)
    # And the embeddings must be non-trivial (training actually happened).
    assert np.linalg.norm(single.vectors, axis=1).mean() > 0.01


def test_init_distributed_single_process_noop(monkeypatch):
    """Without a coordinator the helper is a no-op world of 1 (this process);
    env-provided settings are read the way a multi-host launcher would set them."""
    from albedo_tpu.parallel.mesh import init_distributed

    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    monkeypatch.delenv("JAX_PROCESS_ID", raising=False)
    assert init_distributed() == 1
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "host:1234")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "1")
    assert init_distributed() == 1  # single process: still a no-op
    # Misconfigured multi-process worlds must fail loudly, not run this
    # worker as an independent single-host job.
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    with pytest.raises(ValueError, match="process id"):
        init_distributed()
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS")
    with pytest.raises(ValueError, match="coordinator address"):
        init_distributed()
    monkeypatch.delenv("JAX_NUM_PROCESSES")
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "host:1234")
    with pytest.raises(ValueError, match="process count"):
        init_distributed()
